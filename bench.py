"""Benchmark: MLM training-step throughput, printed as ONE JSON line.

Measures tokens/sec/chip for the reference train_mlm-equivalent hot loop
(IMDB config: 512-token sequences, 256 latents, 3 encoder layers × 6
self-attention layers per block, batch 64 — SURVEY.md §3.1 / BASELINE.md) on
whatever accelerator jax selects (the driver runs this on the real TPU chip).

The reference publishes no throughput numbers (BASELINE.md), so
``vs_baseline`` is the ratio against the value recorded in BASELINE.json's
``published`` map when present, else 1.0.

Env knobs: PIT_BENCH_CPU=1 forces CPU; PIT_BENCH_STEPS / PIT_BENCH_BATCH
override defaults; PIT_BENCH_ATTN selects the attention impl
('xla' | 'pallas' | 'packed', default 'xla' — measured fastest at these
skinny head dims, see PERF.md);
PIT_BENCH_GATHER sets the masked-decode capacity (-1 auto — measured ~35%
faster than full decode: the (B, 512, 10003) logits and their CE dominate HBM
traffic; 0 = reference-shaped full decode).

Timing note: the loop is synced by fetching the loss scalar to host, NOT by
``jax.block_until_ready`` — on tunneled/remote PJRT backends (axon)
block_until_ready can return before the device work completes, inflating
throughput ~10x. A one-step run is timed first and subtracted so the fetch
round-trip doesn't count against the steady-state rate.
"""

from __future__ import annotations

import json
import os


def main() -> None:
    if os.environ.get("PIT_BENCH_CPU") == "1":
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )

    vocab, seq_len = 10003, 512
    num_latents, channels = 256, 64
    batch_size = int(os.environ.get("PIT_BENCH_BATCH", "64"))
    steps = int(os.environ.get("PIT_BENCH_STEPS", "20"))
    compute_dtype = jnp.bfloat16
    attn_impl = os.environ.get("PIT_BENCH_ATTN", "xla")
    if attn_impl not in ("xla", "pallas", "packed"):
        raise SystemExit(
            f"PIT_BENCH_ATTN must be 'xla', 'pallas' or 'packed', got {attn_impl!r}")
    gather = int(os.environ.get("PIT_BENCH_GATHER", "-1"))
    if gather < 0:
        gather = mlm_gather_capacity(seq_len)

    from perceiver_io_tpu.models.presets import flagship_mlm

    model = flagship_mlm(
        vocab_size=vocab, max_seq_len=seq_len, num_latents=num_latents,
        num_channels=channels, dtype=compute_dtype, attn_impl=attn_impl,
    )

    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(
            rng.integers(3, vocab, (batch_size, seq_len)).astype(np.int32)
        ),
        "pad_mask": jnp.zeros((batch_size, seq_len), dtype=bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    train_step, _, _ = make_mlm_steps(model, schedule, loss_gather_capacity=gather or None)

    from perceiver_io_tpu.utils.benchmarking import time_train_step

    seconds_per_step, _ = time_train_step(
        train_step, state, batch, steps, windows=3
    )

    # the jitted step runs on exactly one device (no sharding here), so
    # per-chip throughput is the total regardless of how many chips the
    # host exposes
    tokens_per_sec_per_chip = batch_size * seq_len / seconds_per_step

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get("mlm_tokens_per_sec_per_chip")
    except Exception:
        pass
    vs_baseline = tokens_per_sec_per_chip / baseline if baseline else 1.0

    print(json.dumps({
        "metric": "mlm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
