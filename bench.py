"""Benchmark: MLM training-step throughput, printed as ONE JSON line.

Measures tokens/sec/chip for the reference train_mlm-equivalent hot loop
(IMDB config: 512-token sequences, 256 latents, 3 encoder layers × 6
self-attention layers per block, batch 64 — SURVEY.md §3.1 / BASELINE.md) on
whatever accelerator jax selects (the driver runs this on the real TPU chip).

The reference publishes no throughput numbers (BASELINE.md), so
``vs_baseline`` is the ratio against the value recorded in BASELINE.json's
``published`` map when present, else 1.0.

Measurement (PERF.md discipline): the HEADLINE value comes from a
``jax.profiler`` device trace — the TPU's own per-step durations — which is
immune to the tunneled-backend distortions that made host-clock numbers swing
±20% with infrastructure noise (VERDICT r2: the r02 headline regressed with
the tunnel, not the chip). A host-clock chained-window measurement (loss-
scalar sync, 1-iter run subtracted, median of 3 windows) is taken too and
reported alongside; it becomes the headline only when the device trace is
unavailable (non-TPU backends). The JSON line carries both numbers plus
``method`` so the record says which clock produced it.

Env knobs: PIT_BENCH_CPU=1 forces CPU; PIT_BENCH_STEPS / PIT_BENCH_BATCH
override defaults; PIT_BENCH_ATTN selects the attention impl
('xla' | 'pallas' | 'packed', default 'xla' — measured fastest at these
skinny head dims, see PERF.md);
PIT_BENCH_GATHER sets the masked-decode capacity (-1 auto — measured ~35%
faster than full decode: the (B, 512, 10003) logits and their CE dominate HBM
traffic; 0 = reference-shaped full decode). PIT_BENCH_HEAD selects the vocab
head ('pallas' default on TPU — the fused flash-CE kernel, device-measured
10.42 → 9.82 ms/step; 'none' = unfused; 'xla' = chunked-scan variant).
PIT_BENCH_HOST_ONLY=1 skips the device trace (host clock becomes the
headline). PIT_COMPILE_CACHE=DIR persists XLA compiles across sessions
(opt-in cold-start amortization; compile time never enters the device-trace
headline — PERF.md §Cold start). PIT_BENCH_BACKEND_DEADLINE_S (default 120)
bounds the first
backend probe: when the tunnel is dark the probe times out and the script
prints a single ``{"error": "tpu_unavailable", ...}`` JSON record and exits
nonzero instead of hanging or dumping a raw traceback (BENCH_r05).
"""

from __future__ import annotations

import json
import os
import sys

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import (  # noqa: E402 — after the
    BackendProbeTimeout,  # stdout-contract imports; probes stay deadlined
    probe_backend,
)


def _probe_backend() -> str:
    """Resolve the jax backend under a wall-clock deadline.

    The first backend touch is where a dark axon tunnel bites: the PJRT
    plugin hangs (or raises) inside ``jax.default_backend()``, which used to
    escape as a raw traceback on stdout — violating the one-JSON-line
    contract exactly when the driver most needs a parseable record. The
    shared ``utils.platform.probe_backend`` helper runs the probe on an
    abandonable daemon thread; on timeout or error ONE JSON error line is
    printed and the process exits nonzero via ``os._exit`` (a wedged PJRT
    thread cannot be joined). PIT_BENCH_BACKEND_DEADLINE_S overrides the
    120 s default.
    """
    try:
        return probe_backend(deadline_s=120.0).backend
    except BackendProbeTimeout as e:
        _exit_backend_unavailable(str(e))
    except Exception as e:  # backend init raised (plugin error, no devices)
        _exit_backend_unavailable(f"{type(e).__name__}: {str(e)[:300]}")


def _exit_backend_unavailable(reason: str) -> None:
    """Emit the single JSON error record and exit nonzero."""
    emit_json_line({
        "error": "tpu_unavailable",
        "metric": "mlm_tokens_per_sec_per_chip",
        "value": None,
        "reason": reason,
    })
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(2)


def main() -> None:
    if os.environ.get("PIT_BENCH_CPU") == "1":
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()

    import jax
    import jax.numpy as jnp
    import numpy as np

    backend = _probe_backend()

    # opt-in compile persistence (PIT_COMPILE_CACHE=DIR): repeat sessions
    # skip the remote recompiles. Compile time never enters the headline —
    # the device-trace lower-quartile step time is measured after warmup —
    # so the cache cannot perturb the metric (PERF.md §Cold start).
    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()

    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )

    vocab, seq_len = 10003, 512
    num_latents, channels = 256, 64
    batch_size = int(os.environ.get("PIT_BENCH_BATCH", "64"))
    steps = int(os.environ.get("PIT_BENCH_STEPS", "20"))
    compute_dtype = jnp.bfloat16
    attn_impl = os.environ.get("PIT_BENCH_ATTN", "xla")
    if attn_impl not in ("xla", "pallas", "packed"):
        raise SystemExit(
            f"PIT_BENCH_ATTN must be 'xla', 'pallas' or 'packed', got {attn_impl!r}")
    gather = int(os.environ.get("PIT_BENCH_GATHER", "-1"))
    if gather < 0:
        gather = mlm_gather_capacity(seq_len)
    head = os.environ.get("PIT_BENCH_HEAD")
    if head is None:
        # the fused flash-CE head is a TPU kernel; off-TPU it would run in
        # interpreter mode (orders of magnitude slower)
        head = "pallas" if backend == "tpu" else "none"
    fused_head = {"pallas": "pallas", "xla": True, "none": False}.get(head)
    if fused_head is None:
        raise SystemExit(
            f"PIT_BENCH_HEAD must be 'pallas', 'xla' or 'none', got {head!r}")

    from perceiver_io_tpu.models.presets import flagship_mlm

    model = flagship_mlm(
        vocab_size=vocab, max_seq_len=seq_len, num_latents=num_latents,
        num_channels=channels, dtype=compute_dtype, attn_impl=attn_impl,
    )

    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(
            rng.integers(3, vocab, (batch_size, seq_len)).astype(np.int32)
        ),
        "pad_mask": jnp.zeros((batch_size, seq_len), dtype=bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    train_step, _, _ = make_mlm_steps(
        model, schedule, loss_gather_capacity=gather or None,
        fused_head=fused_head,
    )

    from perceiver_io_tpu.utils.benchmarking import (
        time_train_step,
        time_train_step_device,
    )

    jitted = jax.jit(train_step, donate_argnums=(0,))

    # the jitted step donates its state argument, so each measurement gets
    # its own copy — a device-trace attempt that fails AFTER its first step
    # has already consumed the state it was handed
    fresh_state = lambda: jax.tree.map(jnp.copy, state)

    device_s = None
    if (backend == "tpu"
            and os.environ.get("PIT_BENCH_HOST_ONLY") != "1"):
        try:
            device_s, _, _ = time_train_step_device(
                train_step, fresh_state(), batch, steps, jitted=jitted
            )
        except Exception:
            device_s = None  # fall back to the host clock below

    host_s, _ = time_train_step(
        train_step, fresh_state(), batch, steps, windows=3, jitted=jitted
    )

    # the jitted step runs on exactly one device (no sharding here), so
    # per-chip throughput is the total regardless of how many chips the
    # host exposes
    seconds_per_step = device_s if device_s is not None else host_s
    tokens_per_sec_per_chip = batch_size * seq_len / seconds_per_step

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get("mlm_tokens_per_sec_per_chip")
    except Exception:
        pass
    vs_baseline = tokens_per_sec_per_chip / baseline if baseline else 1.0

    emit_json_line({
        "metric": "mlm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "method": "device_trace" if device_s is not None else "host_clock",
        "device_ms_per_step": (
            round(device_s * 1e3, 3) if device_s is not None else None
        ),
        "host_ms_per_step": round(host_s * 1e3, 3),
    })

    _maybe_kernel_smoke(backend)


def _maybe_kernel_smoke(backend: str) -> None:
    """Refresh KERNELSMOKE.json after the headline (VERDICT r3 item 5).

    Runs ``tools/kernel_smoke.py`` in a SUBPROCESS (own timeout, stdout
    discarded — this file's contract is exactly ONE JSON line on stdout) so
    every bench run re-validates the measured VMEM-guard tiers in
    ``ops/pallas_attention.py`` / ``ops/pallas_ce.py`` against the current
    compiler on the real chip. TPU-only; failures land in the artifact's
    ``failures`` map, never in the bench output. PIT_SKIP_KERNEL_SMOKE=1
    skips (e.g. when iterating on bench timing alone).
    """
    import subprocess

    if backend != "tpu" or os.environ.get("PIT_SKIP_KERNEL_SMOKE") == "1":
        return
    root = os.path.dirname(os.path.abspath(__file__))
    # A wedged/crashed smoke run must be DISTINGUISHABLE from a passing one:
    # otherwise last round's KERNELSMOKE.json sits there looking fresh. The
    # artifact is best-effort (the headline already printed, and stdout's
    # one-JSON-line contract holds), but failures get a stderr note and a
    # stale artifact gets stamped so its age is self-evident.
    out_path = os.path.join(root, "KERNELSMOKE.json")
    try:
        mtime_before = os.path.getmtime(out_path)
    except OSError:
        mtime_before = None
    crashed = False
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "kernel_smoke.py"),
             "--out", out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=900, check=False,
        )
        failure = f"exit code {proc.returncode}" if proc.returncode else None
    except Exception as e:  # timeout, spawn failure
        failure = repr(e)
        crashed = True
    try:
        refreshed = os.path.getmtime(out_path) != mtime_before
    except OSError:
        refreshed = False
    if failure is None:
        return
    if crashed:
        # a timeout/spawn failure means the run did NOT complete — even if
        # the kill landed after a partial artifact write (mtime changed), its
        # contents cannot be trusted as this run's verdict: stamp it stale
        print(f"bench: kernel smoke did not complete ({failure}) — stamping "
              f"{out_path} stale", file=sys.stderr)
        _stamp_stale_kernel_smoke(out_path, failure)
    elif refreshed:
        # a CLEAN non-zero exit with a rewritten artifact means the smoke RAN
        # and recorded regressions in its failures map — that is the signal
        # the artifact exists to carry, not staleness
        print(f"bench: kernel smoke reported failures ({failure}) — see the "
              f"failures map in {out_path}", file=sys.stderr)
    else:
        print(f"bench: kernel smoke did NOT refresh {out_path} ({failure}) — "
              "the artifact on disk is from an earlier run", file=sys.stderr)
        _stamp_stale_kernel_smoke(out_path, failure)


def _stamp_stale_kernel_smoke(out_path: str, failure: str) -> None:
    """Mark the existing artifact as NOT refreshed by this bench run."""
    try:
        with open(out_path) as f:
            data = json.load(f)
        data["stale"] = True
        data["stale_reason"] = f"kernel_smoke failed under bench.py: {failure}"
        with open(out_path, "w") as f:
            json.dump(data, f)
            f.write("\n")
    except Exception:
        pass  # no artifact to stamp, or unwritable — the stderr note stands


if __name__ == "__main__":
    main()
