"""pitlint (perceiver_io_tpu/analysis): per-rule fixtures, baseline
round-trip, the tier-1 repo-wide static pass, the sharding cross-check, the
``tools/lint.py`` one-JSON-line contract, and the runtime sanitizers.

The repo-wide pass IS the enforcement: it runs the same rules
``tools/lint.py`` runs over ``perceiver_io_tpu/``, ``tools/``, and
``bench.py`` and fails on any non-baselined finding — a new stray
``.item()`` on the dispatch path or a renamed fault site breaks tier-1, not
a reviewer's memory."""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from perceiver_io_tpu.analysis import (
    Baseline,
    FileContext,
    Finding,
    LockOrderViolation,
    RecompileDetected,
    no_implicit_transfers,
    no_recompile,
    record_lock_order,
    scan_paths,
)
from perceiver_io_tpu.analysis.core import all_rules
from perceiver_io_tpu.analysis.rules_clock import DurationClockRule
from perceiver_io_tpu.analysis.rules_contract import ToolContractRule
from perceiver_io_tpu.analysis.rules_faults import FaultSiteRule
from perceiver_io_tpu.analysis.rules_locks import LockDisciplineRule
from perceiver_io_tpu.analysis.rules_purity import JitPurityRule

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check(rule, src, relpath="pkg/mod.py"):
    ctx = FileContext(relpath, relpath, textwrap.dedent(src))
    return [f for f in rule.check(ctx) if not ctx.suppressed(f.rule, f.line)]


# -- PIT-JIT ------------------------------------------------------------------


def test_jit_purity_flags_clock_rng_io_and_fetches_in_jitted_code():
    src = """
    import time
    import jax
    import numpy as np

    def helper(x):
        return x.mean().item()

    def traced(x):
        t = time.time()
        noise = np.random.normal()
        print("tracing")
        loss = float(metrics["loss"])
        return helper(x) * t * noise * loss

    step = jax.jit(traced)
    """
    found = _check(JitPurityRule(), src)
    msgs = " | ".join(f.message for f in found)
    assert any(f.scope == "traced" and "time.time" in f.message
               for f in found)
    assert "np.random" in msgs
    assert "print()" in msgs
    assert "float() scalar fetch" in msgs
    # reachability: helper is only reachable THROUGH the jitted root
    assert any(f.scope == "helper" and ".item()" in f.message for f in found)


def test_jit_purity_ignores_host_code_and_decorated_roots_work():
    host_only = """
    import time

    def host_loop(x):
        t0 = time.monotonic()
        print("serving", x)
        return time.monotonic() - t0
    """
    assert _check(JitPurityRule(), host_only) == []

    decorated = """
    import time
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def step(x):
        time.sleep(1.0)
        return x
    """
    found = _check(JitPurityRule(), decorated)
    assert len(found) == 1 and "time.sleep" in found[0].message


def test_jit_purity_treats_ops_models_modules_as_traced():
    src = """
    import time

    def anything(x):
        return time.monotonic()
    """
    assert _check(JitPurityRule(), src, "perceiver_io_tpu/other/m.py") == []
    found = _check(JitPurityRule(), src, "perceiver_io_tpu/ops/m.py")
    assert len(found) == 1 and found[0].rule == "PIT-JIT"


# -- PIT-CONTRACT -------------------------------------------------------------


def test_contract_flags_stdout_and_bare_probes_in_tools_only():
    src = """
    import sys
    import jax

    def main():
        backend = jax.default_backend()
        print("human table row")
        print("sneaky", file=sys.stdout)
        print("log line", file=sys.stderr)
    """
    found = _check(ToolContractRule(), src, "tools/somebench.py")
    assert sum("bare jax.default_backend" in f.message for f in found) == 1
    assert sum("print() to stdout" in f.message for f in found) == 2
    assert len(found) == 3  # the stderr print passes
    # identical code outside tools/ is not this rule's business
    assert _check(ToolContractRule(), src, "perceiver_io_tpu/x.py") == []


def test_contract_sanctions_emit_json_line_and_deadline_helpers():
    src = """
    import jax
    from perceiver_io_tpu.utils.jsonline import emit_json_line

    def probe_backend():
        return jax.devices()  # the sanctioned helper's own implementation

    def main():
        emit_json_line({"metric": "x", "value": 1})
    """
    assert _check(ToolContractRule(), src, "tools/somebench.py") == []


# -- PIT-FAULT ----------------------------------------------------------------


def test_fault_rule_validates_sites_specs_and_fstring_prefixes():
    src = """
    from perceiver_io_tpu.resilience import FaultSpec, faults

    def instrumented(name, env, monkeypatch):
        faults.inject("engine.dispatch")            # registered
        faults.inject(f"engine.dispatch.{name}")    # suffixed site
        faults.fire("deploy.publish", None)         # registered
        faults.inject("engine.dispach")             # typo'd
        faults.inject(f"engine.warmup.{name}")      # unregistered prefix
        FaultSpec(site="trainer.metrics", kind="nan", at=(1,))
        FaultSpec(site="trainer.metricz", kind="nan", at=(1,))
        env["PIT_FAULTS"] = "deploy.publish:nan@2"
        env["PIT_FAULTS"] = "deploy.publsh:nan@2"
        monkeypatch.setenv("PIT_FAULTS", "engine.dispatch:transient@1")
        monkeypatch.setenv("PIT_FAULTS", "engine.dispatch:transientt@1")
    """
    found = _check(FaultSiteRule(), src)
    assert len(found) == 5, [f.message for f in found]
    assert sum("engine.dispach" in f.message for f in found) == 1
    assert sum("prefix" in f.message for f in found) == 1
    assert sum("trainer.metricz" in f.message for f in found) == 1
    assert sum("deploy.publsh" in f.message for f in found) == 1
    assert sum("transientt" in f.message for f in found) == 1


def test_fault_rule_checks_doc_examples():
    rule = FaultSiteRule()
    good = 'drill with PIT_FAULTS="engine.dispatch:transient@2,5" set'
    bad = 'drill with PIT_FAULTS="engine.dispatch:sometimes@2" set'
    meta = 'the grammar is PIT_FAULTS="site:kind@WHEN" per clause'
    assert rule.check_text("DOC.md", good) == []
    assert rule.check_text("DOC.md", meta) == []  # meta-variables: not a drill
    found = rule.check_text("DOC.md", bad)
    assert len(found) == 1 and found[0].line == 1


# -- PIT-LOCK -----------------------------------------------------------------


def test_lock_rule_enforces_guarded_by_declarations():
    src = """
    import threading

    class Engine:
        _guarded_by = {"_stats": "_stats_lock", "_backlog": "_stats_lock"}
        _assumes_locked = ("caller_holds",)

        def __init__(self):
            self._stats_lock = threading.Lock()
            self._stats = {}
            self._backlog = 0  # __init__ is exempt (not shared yet)

        def good(self):
            with self._stats_lock:
                self._stats["x"] = self._backlog

        def bad(self):
            return self._stats["x"]

        def caller_holds(self):
            self._backlog += 1

        def _drain_locked(self):
            self._backlog -= 1

        def fast_path(self):
            return self._backlog  # pitlint: ignore[PIT-LOCK] racy diagnostic
    """
    found = _check(LockDisciplineRule(), src)
    assert len(found) == 1
    assert found[0].scope == "Engine.bad" and "_stats" in found[0].message


def test_lock_rule_with_items_evaluate_outside_the_lock():
    src = """
    class C:
        _guarded_by = {"_table": "_lock"}

        def swap(self):
            with self._locks[self._table]:  # _table read BEFORE acquisition
                pass
    """
    found = _check(LockDisciplineRule(), src)
    assert len(found) == 1 and found[0].scope == "C.swap"


# -- PIT-CLOCK ----------------------------------------------------------------


def test_clock_rule_flags_wall_clock_durations_only():
    src = """
    import time

    def bad_duration():
        t0 = time.time()
        work()
        return time.time() - t0

    def good_duration():
        t0 = time.monotonic()
        work()
        return time.monotonic() - t0

    def good_timestamp():
        return {"published_unix_s": time.time()}

    class T:
        def __init__(self):
            self._t0 = time.time()

        def age(self):
            return now() - self._t0
    """
    found = _check(DurationClockRule(), src)
    scopes = sorted(f.scope for f in found)
    assert scopes == ["T.age", "bad_duration"], found


def test_pragma_suppresses_a_rule_on_its_line():
    src = """
    import time

    def epoch_from_boot(uptime_s):
        return time.time() - uptime_s  # pitlint: ignore[PIT-CLOCK] epoch math
    """
    assert _check(DurationClockRule(), src) == []


# -- PIT-SPAN -----------------------------------------------------------------


def test_span_rule_validates_literal_names_against_the_registry():
    """The PIT-FAULT pattern for tracing: a record_span site naming an
    unregistered span cannot reach HEAD — a typo'd hop would silently
    decouple from the assembler."""
    from perceiver_io_tpu.analysis.rules_spans import SpanNameRule

    src = """
    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.obs.reqtrace import record_span

    def good(ctx, t0):
        obs.record_span("router_request", ctx, t0, 0.1)
        record_span("replica_serve", ctx, t0, 0.1, replica="r0")

    def bad(ctx, t0):
        obs.record_span("router_requests_typo", ctx, t0, 0.1)

    def dynamic(ctx, t0, name):
        record_span(name, ctx, t0, 0.1)  # non-literal: runtime's problem
    """
    found = _check(SpanNameRule(), src)
    assert len(found) == 1
    assert found[0].scope == "bad"
    assert "router_requests_typo" in found[0].message
    assert "SPAN_NAMES" in found[0].message

    # the registry module itself and the lint fixtures are excluded
    assert SpanNameRule().check(
        FileContext("x", "perceiver_io_tpu/obs/reqtrace.py",
                    'record_span("not_a_span", None, 0, 0)')) == ()


# -- PIT-METRIC ---------------------------------------------------------------


def test_metric_rule_resolves_literals_against_registered_instruments():
    """The PIT-SPAN pattern for the alerting layer: an AlertRule(metric=)
    or series_key() literal naming an instrument nothing registers would
    build a rule that silently never fires — it must fail lint instead.
    The known set derives from the package's .counter/.gauge/.histogram
    registration literals."""
    from perceiver_io_tpu.analysis.rules_metrics import (
        MetricNameRule,
        known_metric_names,
        strip_series_key,
    )

    known = known_metric_names()
    # spot-check the scan found real registrations across layers
    for name in ("serving_queue_depth", "slo_error_budget_burn_rate",
                 "fleet_replica_slo_burn", "fleet_scrape_age_s",
                 "eventlog_dropped_total", "alert_state",
                 "router_latency_seconds"):
        assert name in known, f"{name} missing from the known-metric scan"
    assert strip_series_key(
        'serving_phase_seconds{engine="e",phase="queue"}:p99') \
        == "serving_phase_seconds"
    assert strip_series_key("reqs_total:count") == "reqs_total"
    assert strip_series_key("ns:custom") == "ns:custom"  # not a field

    src = """
    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.obs import AlertRule

    def good(store):
        obs.AlertRule(name="q", metric="serving_queue_depth", threshold=1)
        AlertRule("burn", "slo_error_budget_burn_rate:p99")
        store.last(obs.series_key("router_latency_seconds",
                                  {"router": "r"}, field="p99"))

    def bad():
        obs.AlertRule(name="q", metric="serving_queue_depht", threshold=1)
        obs.series_key("router_latency_secondz")

    def dynamic(name):
        obs.AlertRule(name="d", metric=name)  # runtime's problem
    """
    found = _check(MetricNameRule(), src)
    assert len(found) == 2
    assert all(f.scope == "bad" for f in found)
    assert "serving_queue_depht" in found[0].message
    assert "router_latency_secondz" in found[1].message

    # the lint suite's own fixtures are excluded
    assert MetricNameRule().check(
        FileContext("x", "tests/test_lint.py",
                    'series_key("not_a_metric")')) == ()


# -- baseline -----------------------------------------------------------------


def test_baseline_round_trip_split_and_stale_detection(tmp_path):
    f1 = Finding("PIT-CLOCK", "a.py", 10, "f", "msg one")
    f2 = Finding("PIT-JIT", "b.py", 20, "g", "msg two")
    bl = Baseline()
    bl.keys[f1.key()] = "justified: epoch math"
    bl.keys["PIT-LOCK|gone.py|h|paid down"] = "old debt"
    path = str(tmp_path / "baseline.txt")
    bl.save(path)
    loaded = Baseline.load(path)
    assert loaded.keys == bl.keys  # justifications survive the round trip

    new, old = loaded.split([f1, f2])
    assert old == [f1] and new == [f2]
    # line numbers are NOT part of the key: the entry survives edits above it
    assert Finding("PIT-CLOCK", "a.py", 999, "f", "msg one") in loaded
    assert loaded.stale_keys([f1, f2]) == ["PIT-LOCK|gone.py|h|paid down"]


# -- the tier-1 repo-wide pass ------------------------------------------------


def test_repo_static_pass_is_clean_and_fast():
    """THE enforcement test: the full rule set over the shared lint scope
    (core.DEFAULT_TARGETS — perceiver_io_tpu/, tools/, bench.py; tests/
    under the fault-site rule only; PIT_FAULTS examples in the markdown
    docs) yields zero non-baselined findings, inside the budget (<20 s on
    this container; measured ~2 s). ONE scope definition with
    tools/lint.py, so the fast local loop and CI cannot disagree."""
    from perceiver_io_tpu.analysis.core import (
        DEFAULT_BASELINE,
        DEFAULT_TARGETS,
        DOC_TARGETS,
        TEST_FAULT_TARGETS,
    )

    t0 = time.monotonic()
    findings = scan_paths(
        [os.path.join(ROOT, t) for t in DEFAULT_TARGETS], root=ROOT)
    rule = FaultSiteRule()
    findings.extend(scan_paths(
        [os.path.join(ROOT, t) for t in TEST_FAULT_TARGETS],
        rules=[rule], root=ROOT))
    # doc halves of the fault rule (PIT_FAULTS examples in markdown)
    for doc in DOC_TARGETS:
        p = os.path.join(ROOT, doc)
        if os.path.exists(p):
            with open(p, encoding="utf-8") as fh:
                findings.extend(rule.check_text(doc, fh.read()))
    elapsed = time.monotonic() - t0
    baseline = Baseline.load(DEFAULT_BASELINE)
    new, _ = baseline.split(findings)
    assert new == [], "NEW pitlint findings:\n" + "\n".join(
        f.render() for f in new)
    stale = baseline.stale_keys(findings)
    assert stale == [], f"stale baseline entries (prune them): {stale}"
    assert elapsed < 20.0, f"static pass took {elapsed:.1f}s (budget 20s)"


def test_sharding_rules_cover_every_preset():
    """Satellite: every parallel/sharding.py path-regex matches >=1 param
    path in EACH models/presets.py preset tree (CPU-only shape tracing) —
    a torch-parity param rename cannot silently strand a sharding rule."""
    from perceiver_io_tpu.analysis.crosscheck import audit_sharding_rules

    assert audit_sharding_rules() == []


def test_sharding_crosscheck_catches_a_stranded_rule(monkeypatch):
    from jax.sharding import PartitionSpec as P

    import perceiver_io_tpu.parallel.sharding as sharding
    from perceiver_io_tpu.analysis.crosscheck import audit_sharding_rules

    from perceiver_io_tpu.analysis.crosscheck import _preset_builders

    monkeypatch.setattr(
        sharding, "PARAM_RULES",
        tuple(sharding.PARAM_RULES) + ((r"renamed_proj/kernel$", P()),))
    found = audit_sharding_rules()
    # one finding per audited preset (the MLM family + the r18 AR presets)
    assert len(found) == len(_preset_builders()) >= 5
    assert all("renamed_proj" in f.message for f in found)


# -- tools/lint.py contract ---------------------------------------------------


def _run_lint(*args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lint.py"),
         "--no-crosscheck", *args],
        capture_output=True, text=True, timeout=300,
    )


def test_lint_cli_clean_at_head_one_json_line():
    proc = _run_lint()
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["tool"] == "pitlint" and record["ok"] is True
    assert record["new"] == 0 and record["stale_baseline"] == 0


def test_lint_cli_nonzero_exit_and_one_json_line_on_violation(tmp_path):
    bad = tmp_path / "bad_tool.py"
    bad.write_text(textwrap.dedent("""
        import time

        def measure():
            t0 = time.time()
            work()
            return time.time() - t0
    """))
    proc = _run_lint(str(bad))
    assert proc.returncode == 1, (proc.stdout, proc.stderr[-1000:])
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["ok"] is False and record["new"] >= 1
    assert record["by_rule"].get("PIT-CLOCK", 0) >= 1
    assert "PIT-CLOCK" in proc.stderr  # detail rides stderr


# -- runtime sanitizers -------------------------------------------------------


def test_no_recompile_passes_warm_and_trips_cold():
    f = jax.jit(lambda x: x * 2.0 + 1.0)
    f(jnp.ones(5))  # compile OUTSIDE the guard
    with no_recompile():
        f(jnp.ones(5))  # cache hit: silent
    with pytest.raises(RecompileDetected, match="compilation"):
        with no_recompile():
            jax.jit(lambda x: x * 3.0 - 7.0)(jnp.ones(6))


def test_transfer_guard_is_really_armed():
    """CPU cannot exhibit a device->host transfer (arrays are host-resident)
    so the d2h default is structural here and bites on device backends; the
    'all' direction proves the arming mechanism works in-process."""
    f = jax.jit(lambda x: x + 1)
    f(np.ones(3))  # warm (and an implicit transfer OUTSIDE the guard: fine)
    with no_implicit_transfers():
        jax.device_get(f(jnp.ones(3)))  # explicit fetch stays legal
    with pytest.raises(Exception, match="[Dd]isallowed"):
        with no_implicit_transfers(direction="all"):
            f(np.ones(3))  # numpy arg -> implicit host-to-device
    with pytest.raises(ValueError, match="unknown direction"):
        with no_implicit_transfers(direction="d2h"):  # typo must not
            pass                                      # silently mis-arm


def test_lock_order_recorder_benign_and_cycle():
    with record_lock_order() as rec:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with a:  # same order again: consistent
            with b:
                pass
    assert rec.acquisitions == 4 and rec.find_cycle() is None

    with pytest.raises(LockOrderViolation, match="cycle"):
        with record_lock_order():
            a = threading.Lock()
            b = threading.Lock()
            a.site, b.site = "siteA", "siteB"  # stable node names
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass


def test_lock_order_recorder_body_error_wins_over_check():
    with pytest.raises(ValueError, match="body"):
        with record_lock_order():
            a = threading.Lock()
            b = threading.Lock()
            a.site, b.site = "sA", "sB"
            with a:
                with b:
                    pass
            with b:
                with a:
                    raise ValueError("body")


# -- repo hygiene: orphan bytecode (PIT-BYTECODE, r22) ------------------------


def test_orphan_bytecode_scan_flags_residue(tmp_path):
    """Deleted modules must be GONE: a legacy-layout pyc is importable in
    place of (or alongside) its source, and an orphan __pycache__ pyc is
    residue from a deleted module. Live cache entries are not findings."""
    from perceiver_io_tpu.analysis.core import scan_orphan_bytecode

    pkg = tmp_path / "pkg"
    (pkg / "__pycache__").mkdir(parents=True)
    (pkg / "live.py").write_text("x = 1\n")
    (pkg / "__pycache__" / "live.cpython-311.pyc").write_bytes(b"\x00")
    (pkg / "__pycache__" / "deleted.cpython-311.pyc").write_bytes(b"\x00")
    (pkg / "ghost.pyc").write_bytes(b"\x00")
    (pkg / "live.pyc").write_bytes(b"\x00")

    findings = scan_orphan_bytecode(str(tmp_path), targets=("pkg",))
    assert all(f.rule == "PIT-BYTECODE" for f in findings)
    by_path = {f.path: f.message for f in findings}
    assert "in place of deleted" in by_path["pkg/ghost.pyc"]
    assert "alongside" in by_path["pkg/live.pyc"]
    assert "residue" in by_path["pkg/__pycache__/deleted.cpython-311.pyc"]
    assert "pkg/__pycache__/live.cpython-311.pyc" not in by_path  # live


def test_repo_has_no_orphan_bytecode():
    """The r22 satellite pin: the stale serving/__pycache__/transport pycs
    are deleted and nothing like them comes back (lint runs this scan on
    every invocation — same scope as tools/lint.py)."""
    from perceiver_io_tpu.analysis.core import (
        DEFAULT_TARGETS,
        TEST_FAULT_TARGETS,
        scan_orphan_bytecode,
    )

    findings = scan_orphan_bytecode(
        ROOT, targets=(*DEFAULT_TARGETS, *TEST_FAULT_TARGETS))
    # legacy-layout pycs are always findings; __pycache__ pycs only when
    # their source is gone — a live dev tree's caches stay clean either way
    assert findings == [], "\n".join(f.render() for f in findings)
