"""Checkpoint/resume tests: round trip, best-k retention, hparams embedding,
encoder-subtree transfer (SURVEY.md §4 item (e))."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import perceiver_io_tpu as pit
from perceiver_io_tpu.ops.masking import TextMasking
from perceiver_io_tpu.training import (
    CheckpointManager,
    OptimizerConfig,
    TrainState,
    load_hparams,
    make_mlm_steps,
    make_optimizer,
    restore_encoder_params,
    restore_params,
    restore_train_state,
)

VOCAB, SEQ, CH, LATENTS = 32, 8, 16, 4


def tiny_mlm(vocab=VOCAB, seq=SEQ, ch=CH, latents=LATENTS):
    latent_shape = (latents, ch)
    return pit.PerceiverMLM(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=vocab, max_seq_len=seq, num_channels=ch
            ),
            latent_shape=latent_shape,
            num_layers=2,
            num_cross_attention_heads=2,
            num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=vocab, max_seq_len=seq, num_output_channels=ch
            ),
            latent_shape=latent_shape,
            num_cross_attention_heads=2,
        ),
        masking=TextMasking(
            vocab_size=vocab, unk_token_id=1, mask_token_id=2, num_special_tokens=3
        ),
    )


@pytest.fixture
def state_and_batch(rng):
    model = tiny_mlm()
    batch = {
        "token_ids": jnp.asarray(rng.integers(3, VOCAB, (2, SEQ)).astype(np.int32)),
        "pad_mask": jnp.zeros((2, SEQ), dtype=bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    return model, state, batch, schedule


def _trees_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.allclose(x, y) for x, y in zip(leaves_a, leaves_b))


def test_save_restore_round_trip(tmp_path, state_and_batch):
    model, state, batch, schedule = state_and_batch
    train_step, _, _ = make_mlm_steps(model, schedule)
    step_fn = jax.jit(train_step)

    for _ in range(3):
        state, metrics = step_fn(state, batch)

    with CheckpointManager(str(tmp_path / "ckpt"), async_save=False) as mngr:
        mngr.save(int(state.step), state, {"val_loss": float(metrics["loss"])})
        like = TrainState.create(
            jax.tree.map(jnp.zeros_like, state.params), state.tx, jax.random.key(0)
        )
        restored = mngr.restore_state(like)

    assert int(restored.step) == int(state.step)
    assert _trees_equal(restored.params, state.params)
    assert _trees_equal(restored.opt_state, state.opt_state)
    # restored rng must continue the same stream
    assert np.array_equal(
        jax.random.key_data(restored.rng), jax.random.key_data(state.rng)
    )

    # training continues identically from the restored state
    s1, m1 = step_fn(state, batch)
    s2, m2 = step_fn(restored, batch)
    assert np.allclose(float(m1["loss"]), float(m2["loss"]))


def test_best_k_retention(tmp_path, state_and_batch):
    _, state, _, _ = state_and_batch
    losses = {1: 5.0, 2: 3.0, 3: 4.0, 4: 2.0}  # best two: steps 4, 2
    with CheckpointManager(
        str(tmp_path / "ckpt"), max_to_keep=2, async_save=False
    ) as mngr:
        for step, loss in losses.items():
            mngr.save(step, state.replace(step=jnp.asarray(step)), {"val_loss": loss})
        assert mngr.best_step == 4
        assert sorted(mngr.all_steps) == [2, 4]
        assert mngr.restore_metrics()["val_loss"] == 2.0


def test_hparams_embedding(tmp_path, state_and_batch):
    _, state, _, _ = state_and_batch
    hparams = {"num_latents": LATENTS, "optimizer": OptimizerConfig(one_cycle_lr=True)}
    with CheckpointManager(
        str(tmp_path / "ckpt"), hparams=hparams, async_save=False
    ) as mngr:
        mngr.save(1, state, {"val_loss": 1.0})
    loaded = load_hparams(str(tmp_path / "ckpt"))
    assert loaded["num_latents"] == LATENTS
    assert loaded["optimizer"]["one_cycle_lr"] is True


def test_restore_params_and_module_level_restore(tmp_path, state_and_batch):
    _, state, _, _ = state_and_batch
    path = str(tmp_path / "ckpt")
    with CheckpointManager(path, async_save=False) as mngr:
        mngr.save(7, state, {"val_loss": 1.0})

    params = restore_params(path, jax.tree.map(jnp.zeros_like, state.params))
    assert _trees_equal(params, state.params)

    like = TrainState.create(
        jax.tree.map(jnp.zeros_like, state.params), state.tx, jax.random.key(9)
    )
    restored = restore_train_state(path, like)
    assert int(restored.step) == int(state.step)


def test_module_level_restore_prefers_best_step(tmp_path, state_and_batch):
    """restore_* helpers must load the best-by-val_loss step, not the latest."""
    _, state, _, _ = state_and_batch
    path = str(tmp_path / "ckpt")
    with CheckpointManager(path, max_to_keep=3, async_save=False) as mngr:
        best = state.replace(
            step=jnp.asarray(1),
            params=jax.tree.map(lambda a: a + 1.0, state.params),
        )
        mngr.save(1, best, {"val_loss": 0.4})
        mngr.save(2, state.replace(step=jnp.asarray(2)), {"val_loss": 0.7})

    like = TrainState.create(
        jax.tree.map(jnp.zeros_like, state.params), state.tx, jax.random.key(0)
    )
    restored = restore_train_state(path, like)
    assert int(restored.step) == 1
    params = restore_params(path, jax.tree.map(jnp.zeros_like, state.params))
    assert _trees_equal(params, best.params)


def test_encoder_transfer(tmp_path, state_and_batch, rng):
    """Pretrained-MLM-encoder → text-classifier graft
    (reference train_seq_clf.py:18-24 semantics as a pytree swap)."""
    _, state, _, _ = state_and_batch
    path = str(tmp_path / "ckpt")
    with CheckpointManager(path, async_save=False) as mngr:
        mngr.save(1, state, {"val_loss": 1.0})

    # fresh classifier sharing the encoder architecture
    latent_shape = (LATENTS, CH)
    clf = pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=SEQ, num_channels=CH
            ),
            latent_shape=latent_shape,
            num_layers=2,
            num_cross_attention_heads=2,
            num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=2, num_output_channels=CH
            ),
            latent_shape=latent_shape,
            num_cross_attention_heads=2,
        ),
    )
    token_ids = jnp.asarray(rng.integers(3, VOCAB, (2, SEQ)).astype(np.int32))
    pad_mask = jnp.zeros((2, SEQ), dtype=bool)
    clf_params = clf.init({"params": jax.random.key(3)}, token_ids, pad_mask)["params"]

    encoder_params = restore_encoder_params(
        path, jax.tree.map(jnp.zeros_like, clf_params["encoder"])
    )
    assert _trees_equal(encoder_params, state.params["encoder"])

    grafted = dict(clf_params)
    grafted["encoder"] = encoder_params
    logits = clf.apply({"params": grafted}, token_ids, pad_mask)
    assert logits.shape == (2, 2)
    assert np.isfinite(np.asarray(logits)).all()


def test_save_last_and_prefer_latest(tmp_path, state_and_batch):
    """Preemption flow: best slot holds an old champion, last/ holds the newer
    state; prefer_latest resumes from last/, default restore from best."""
    model, state, batch, schedule = state_and_batch
    train_step, _, _ = make_mlm_steps(model, schedule)
    step_fn = jax.jit(train_step)

    directory = str(tmp_path / "ckpt")
    with CheckpointManager(directory, async_save=False) as mngr:
        state, _ = step_fn(state, batch)
        mngr.save(int(state.step), state, {"val_loss": 1.0})  # champion @ 1
        champion = state
        for _ in range(3):
            state, _ = step_fn(state, batch)
        # a worse metric would be GC'd by the ranked slot; last/ keeps it
        mngr.save_last(int(state.step), state)

    like = TrainState.create(
        jax.tree.map(jnp.zeros_like, state.params), state.tx, jax.random.key(0)
    )
    latest = restore_train_state(directory, like, prefer_latest=True)
    assert int(latest.step) == int(state.step)
    assert _trees_equal(latest.params, state.params)

    best = restore_train_state(directory, like)
    assert int(best.step) == int(champion.step)


@pytest.mark.slow  # tier-1 budget (r10): prefer_latest semantics stay
# tier-1 in test_save_last_and_prefer_latest; the corrupted-newest fallback
# in tests/test_resilience.py
def test_prefer_latest_without_last_slot(tmp_path, state_and_batch):
    """prefer_latest with no last/ dir falls back to the ranked slot."""
    model, state, batch, schedule = state_and_batch
    train_step, _, _ = make_mlm_steps(model, schedule)
    state, _ = jax.jit(train_step)(state, batch)
    directory = str(tmp_path / "ckpt")
    with CheckpointManager(directory, async_save=False) as mngr:
        mngr.save(int(state.step), state, {"val_loss": 1.0})
    like = TrainState.create(
        jax.tree.map(jnp.zeros_like, state.params), state.tx, jax.random.key(0)
    )
    restored = restore_train_state(directory, like, prefer_latest=True)
    assert int(restored.step) == int(state.step)


def test_prefer_latest_falls_back_past_corrupt_newest_step(tmp_path,
                                                           state_and_batch):
    """A run killed MID-SAVE leaves a truncated newest step dir; the
    crash-resume path (prefer_latest) must warn and restore the previous
    good step instead of crashing exactly when recovery is needed."""
    import glob
    import warnings as _warnings

    _, state, _, _ = state_and_batch
    directory = str(tmp_path / "ckpt")
    with CheckpointManager(directory, max_to_keep=3, async_save=False) as mngr:
        for step in (1, 2):
            mngr.save(step, state.replace(step=jnp.asarray(step)),
                      {"val_loss": float(step)})
    # truncate every file of the newest step (the killed-mid-save signature)
    for path in glob.glob(os.path.join(directory, "2", "**"), recursive=True):
        if os.path.isfile(path):
            open(path, "wb").close()

    like = TrainState.create(
        jax.tree.map(jnp.zeros_like, state.params), state.tx, jax.random.key(0)
    )
    with pytest.warns(UserWarning, match="failed to restore"):
        restored = restore_train_state(directory, like, prefer_latest=True)
    assert int(restored.step) == 1
    assert _trees_equal(restored.params, state.params)

    # every candidate corrupt → the restore error propagates (no silent junk)
    for path in glob.glob(os.path.join(directory, "1", "**"), recursive=True):
        if os.path.isfile(path):
            open(path, "wb").close()
    with pytest.raises(Exception):
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            restore_train_state(directory, like, prefer_latest=True)


@pytest.mark.slow  # tier-1 budget (r21): single-process checkpoint round-
# trip stays tier-1 in test_save_restore_round_trip; zero3 sharding-rule
# correctness stays in tests/test_sharding.py::test_zero3_param_sharding
def test_zero3_sharded_state_round_trip(tmp_path, state_and_batch):
    """A ZeRO-3-sharded TrainState (params AND opt-state over the data axis)
    checkpoints and restores: saved values equal the sharded originals, and
    a fresh replicated-like restore continues training identically — so
    --zero3 runs keep the same preemption/resume guarantees as replicated
    ones."""
    from perceiver_io_tpu.parallel import make_mesh, make_sharded_train_step

    model, state, batch, schedule = state_and_batch
    train_step, _, _ = make_mlm_steps(model, schedule)

    # the fixture batch has 2 rows — too few to shard over dp=4
    rng = np.random.default_rng(7)
    batch = {
        "token_ids": jnp.asarray(
            rng.integers(3, VOCAB, (8, SEQ)).astype(np.int32)),
        "pad_mask": jnp.zeros((8, SEQ), dtype=bool),
    }
    mesh = make_mesh(dp=4, tp=2, sp=1)
    step, sstate, bshard = make_sharded_train_step(
        train_step, mesh, state, batch, zero_opt="params",
        donate_state=False,
    )
    gbatch = jax.device_put(batch, bshard)
    for _ in range(2):
        sstate, metrics = step(sstate, gbatch)

    with CheckpointManager(str(tmp_path / "ckpt"), async_save=False) as mngr:
        mngr.save(int(sstate.step), sstate, {"val_loss": float(metrics["loss"])})
        like = TrainState.create(
            jax.tree.map(jnp.zeros_like, state.params), state.tx,
            jax.random.key(0),
        )
        restored = mngr.restore_state(like)

    assert int(restored.step) == int(sstate.step)
    assert _trees_equal(restored.params, jax.device_get(sstate.params))
    assert _trees_equal(restored.opt_state, jax.device_get(sstate.opt_state))

    # training continues identically: restored (replicated) vs live sharded.
    # The partitionable PRNG guarantees identical masks either way; the
    # remaining slack is reduction order — the tp=2 vocab projection + CE
    # reduce in a different association than the replicated step (measured
    # ~2e-4 relative on this compiler), not a state-restore defect.
    cont_sharded, m1 = step(sstate, gbatch)
    _, m2 = jax.jit(train_step)(restored, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-3)
