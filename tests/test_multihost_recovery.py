"""Tier-1 units for the multi-host training fault-tolerance layer (r19).

In-process, single-controller coverage of every policy the 2-process drills
exercise end to end (tests/test_multihost.py keeps the real-cluster and
kill/SIGTERM chaos versions behind slow marks):

- the device-side collective-consistent bad-step guard
  (``training/steps.py make_guarded_step``) and its scanned-window reduce;
- the coordination-flags agreement channel
  (``parallel/sharding.py coord_flags_sharding``) and the trainer's
  SIGTERM-preemption plumbing over it (``force_coordination``);
- bounded-exit detection (``resilience/multihost.py``): the per-step
  deadline against a wedged dispatch (the wedged-peer fixture) and the
  KV-store peer-liveness monitor;
- the restart-the-world supervisor (``cli/common.py WorldSupervisor``)
  against fake children: restart + resume wiring, attempt budget, backoff,
  crash-loop detach, and the ``spawn.child_exit`` chaos site.
"""

import json
import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import faults
from perceiver_io_tpu.resilience.multihost import (
    InMemoryKV,
    PeerLivenessMonitor,
    StepDeadline,
)
from perceiver_io_tpu.training import TrainState
from perceiver_io_tpu.training.steps import (
    make_guarded_step,
    make_scanned_step,
)


@pytest.fixture(autouse=True)
def _clean_injector():
    previous = faults.install(None)
    yield
    faults.install(previous)


def _toy_step():
    def train_step(state, batch):
        def loss_fn(params):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), {"loss": loss}

    return train_step


def _toy_state():
    return TrainState.create(
        {"w": jnp.zeros((3, 1))}, optax.sgd(0.1), jax.random.key(0))


def _toy_batch(n=4, bad=False):
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (n, 3)).astype(np.float32)
    if bad:
        x = np.full_like(x, np.nan)
    return {"x": x, "y": (x @ np.asarray([[1.0], [-2.0], [0.5]], np.float32))}


# -- the guarded step: device-side skip ---------------------------------------


def test_guarded_step_skips_nonfinite_on_device():
    """A NaN loss keeps EVERY pre-step leaf (params, opt_state, step, rng)
    via the on-device select and raises the int32 bad_step flag; a finite
    loss advances normally with the flag down."""
    step = jax.jit(make_guarded_step(_toy_step()))
    state = _toy_state()

    good, metrics = step(state, _toy_batch())
    assert int(metrics["bad_step"]) == 0
    assert int(jax.device_get(good.step)) == 1

    kept, metrics = step(good, _toy_batch(bad=True))
    assert int(metrics["bad_step"]) == 1
    assert not np.isfinite(float(metrics["loss"]))
    assert int(jax.device_get(kept.step)) == 1  # not advanced
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(kept.params["w"])),
        np.asarray(jax.device_get(good.params["w"])),
    )
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(kept.rng)),
        np.asarray(jax.random.key_data(good.rng)),
    )


def test_guarded_step_under_scan_applies_good_substeps_only():
    """Guarded-inside-scanned: a bad mid-window sub-step is skipped while
    its neighbors apply, and the integer window-MAX reduce keeps the flag
    visible (a float mean or last-value reduce would mask it)."""
    step = jax.jit(make_scanned_step(make_guarded_step(_toy_step())))
    state = _toy_state()
    g, b = _toy_batch(), _toy_batch(bad=True)
    stacked = {k: np.stack([g[k], b[k], g[k]]) for k in g}
    out, metrics = step(state, stacked)
    assert int(metrics["bad_step"]) == 1
    assert int(jax.device_get(out.step)) == 2  # 2 of 3 sub-steps applied


# -- the coordination channel -------------------------------------------------


def test_coord_flags_agreement_rides_the_step():
    """make_sharded_train_step(coord_flags=True): the per-device flag vector
    reduces to the fleet-wide OR inside the dispatch and comes back
    replicated — one raised element anywhere flips the agreed scalar."""
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.parallel.sharding import make_sharded_train_step

    mesh = make_mesh()
    n = mesh.size
    batch = _toy_batch(n=2 * n)
    step, sstate, _ = make_sharded_train_step(
        _toy_step(), mesh, _toy_state(), batch,
        donate_state=False, coord_flags=True,
    )
    sh = step.coord_flags_sharding
    assert sh is not None

    def flags(vec):
        return jax.make_array_from_process_local_data(
            sh, np.asarray(vec, np.int32), (n,))

    _, metrics = step(sstate, batch, flags([0] * n))
    assert int(jax.device_get(metrics["coord_flags"])) == 0
    one_hot = [0] * n
    one_hot[n // 2] = 1
    _, metrics = step(sstate, batch, flags(one_hot))
    assert int(jax.device_get(metrics["coord_flags"])) == 1
    # a real bitwise OR, not a max: DIFFERENT bits from different hosts
    # must both survive (a max would return 2 here and drop bit 0)
    mixed = [0] * n
    mixed[0], mixed[-1] = 1, 2
    _, metrics = step(sstate, batch, flags(mixed))
    assert int(jax.device_get(metrics["coord_flags"])) == 3


def test_trainer_coordinated_sigterm_preempt_save(tmp_path):
    """SIGTERM plumbing through the agreement channel (force_coordination:
    the single-controller harness for the multi-host path): the local flag
    rides the next dispatch, the AGREED verdict is acted on at a step
    boundary — save_last + preempt counter + agreed gauge — and the run
    stops cleanly well before max_steps."""
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    rng = np.random.default_rng(1)
    w_true = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
    batches = []
    for _ in range(16):
        x = rng.normal(0, 1, (8, 3)).astype(np.float32)
        batches.append({"x": x, "y": x @ w_true})

    class SigtermAt(list):
        def __iter__(self):
            for i, b in enumerate(list.__iter__(self)):
                if i == 3:
                    os.kill(os.getpid(), signal.SIGTERM)
                yield b

    saves0 = obs.get_registry().counter("trainer_preempt_saves_total").value
    cfg = TrainerConfig(
        max_steps=16, log_every_n_steps=100, logdir=str(tmp_path),
        experiment="coord", use_tensorboard=False, compute_mfu=False,
        async_checkpoint=False, force_coordination=True,
    )
    trainer = Trainer(_toy_step(), None, _toy_state(), cfg,
                      example_batch=batches[0], mesh=make_mesh())
    with trainer:
        state = trainer.fit(SigtermAt(batches))
    final = int(jax.device_get(state.step))
    # signal lands before dispatch 4; the flag rides dispatch 4 and the
    # agreement is read after dispatch 5 — stop at the boundary after that
    assert 4 <= final <= 6
    assert final < 16
    reg = obs.get_registry()
    assert reg.counter("trainer_preempt_saves_total").value == saves0 + 1
    assert reg.gauge("multihost_last_step_agreed").value >= final - 1
    last = os.path.join(trainer.run_dir, "checkpoints", "last", str(final))
    assert os.path.isdir(last)
    # the default disposition came back after fit()
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL


def test_trainer_multiprocess_gates(monkeypatch, tmp_path):
    """dispatch retries / fit attempts stay single-process-only; meshless
    skip_nonfinite_steps under multiple processes is refused (no collective
    to agree over)."""
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    base = dict(max_steps=4, logdir=str(tmp_path), use_tensorboard=False)
    with pytest.raises(ValueError, match="single-process only"):
        Trainer(_toy_step(), None, _toy_state(),
                TrainerConfig(dispatch_error_retries=2, **base),
                example_batch=_toy_batch())
    with pytest.raises(ValueError, match="single-process only"):
        Trainer(_toy_step(), None, _toy_state(),
                TrainerConfig(fit_attempts=2, **base),
                example_batch=_toy_batch())
    with pytest.raises(ValueError, match="needs a mesh"):
        Trainer(_toy_step(), None, _toy_state(),
                TrainerConfig(skip_nonfinite_steps=True, **base),
                example_batch=_toy_batch())


# -- bounded-exit detection ---------------------------------------------------


def test_step_deadline_fires_within_bounded_window():
    """The wedged-peer fixture: a dispatch that never completes expires the
    per-step deadline within the configured window — once — and a beat
    before the deadline keeps it quiet."""
    fired = []
    guard = StepDeadline("t_wedge", 0.3, on_expire=lambda: fired.append(
        time.monotonic()))
    try:
        armed_at = time.monotonic()
        guard.arm()
        deadline = time.monotonic() + 3.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fired, "deadline never fired on a wedged dispatch"
        waited = fired[0] - armed_at
        assert 0.3 <= waited < 1.5  # bounded: deadline + monitor cadence
        time.sleep(0.5)
        assert len(fired) == 1  # once per wedge, not per poll
    finally:
        guard.close()

    quiet = StepDeadline("t_live", 0.4, on_expire=lambda: fired.append(None))
    try:
        quiet.arm()
        for _ in range(6):
            time.sleep(0.1)
            quiet.beat()
        assert len(fired) == 1  # no new firings while beating
    finally:
        quiet.close()


def test_peer_liveness_monitor_detects_dead_peer():
    """Two monitors over one shared KV: while both beat, no peer is down;
    when one stops beating, the survivor declares it dead within the
    deadline and bumps multihost_peer_down_total."""
    kv = InMemoryKV()
    down = []
    down0 = obs.get_registry().counter("multihost_peer_down_total").value
    a = PeerLivenessMonitor(
        process_id=0, num_processes=2, kv=kv, interval_s=0.05,
        deadline_s=0.4, on_peer_down=down.append).start()
    b = PeerLivenessMonitor(
        process_id=1, num_processes=2, kv=kv, interval_s=0.05,
        deadline_s=0.4, on_peer_down=down.append).start()
    try:
        time.sleep(0.4)
        assert not down and a.peers_down() == () and b.peers_down() == ()
        b.close()  # peer 1 dies silently
        deadline = time.monotonic() + 3.0
        while not down and time.monotonic() < deadline:
            time.sleep(0.02)
        assert down == [1]
        assert a.peers_down() == (1,)
        assert (obs.get_registry().counter("multihost_peer_down_total").value
                == down0 + 1)
    finally:
        a.close()
        b.close()


def test_peer_liveness_heartbeat_fault_site():
    """PIT_FAULTS-driven liveness drill: a hang injected at
    multihost.heartbeat freezes one monitor's publisher, so its PEER marks
    it down — no process killed."""
    kv = InMemoryKV()
    down = []
    a = PeerLivenessMonitor(
        process_id=0, num_processes=2, kv=kv, interval_s=0.05,
        deadline_s=0.4, on_peer_down=down.append).start()
    release = threading.Event()
    # b's publisher wedges on its 3rd beat round (site counters are
    # process-global: rounds 1-2 are a's startup beats)
    injector = faults.FaultInjector([faults.FaultSpec(
        site="multihost.heartbeat", kind="hang", every=1, release=release)])
    b = PeerLivenessMonitor(
        process_id=1, num_processes=2, kv=kv, interval_s=0.05,
        deadline_s=0.4, on_peer_down=down.append)
    faults.install(injector)
    b.start()
    try:
        deadline = time.monotonic() + 3.0
        while 1 not in down and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in down  # a declared the frozen b dead
    finally:
        release.set()
        faults.install(None)
        a.close()
        b.close()


def test_peer_liveness_kv_failure_escalates():
    """Transient KV errors are tolerated and counted; past the consecutive
    limit the coordinator itself is presumed gone — peer -1 down."""

    class FlakyKV(InMemoryKV):
        def __init__(self):
            super().__init__()
            self.fail = False

        def key_value_set(self, key, value, allow_overwrite=False):
            if self.fail:
                raise ConnectionResetError("coordinator gone")
            super().key_value_set(key, value, allow_overwrite)

    kv = FlakyKV()
    down = []
    m = PeerLivenessMonitor(
        process_id=0, num_processes=1, kv=kv, interval_s=0.02,
        deadline_s=5.0, kv_failure_limit=3, on_peer_down=down.append)
    m._beat_once()
    assert m.kv_failures() == 0
    kv.fail = True
    m._beat_once()
    m._beat_once()
    assert m.kv_failures() == 2 and not down
    m._beat_once()
    assert down == [-1]


def test_fault_sites_registered():
    for site in ("trainer.collective", "multihost.heartbeat",
                 "spawn.child_exit", "multihost.resize",
                 "multihost.buddy_send", "multihost.join"):
        assert faults.validate_site(site) == site
    # and the grammar accepts drill specs against them
    inj = faults.parse_spec(
        "trainer.collective:nan@3;spawn.child_exit:transient@1")
    assert inj is not None
    assert faults.parse_spec(
        "multihost.resize:fatal@1;multihost.buddy_send:nan@every:1;"
        "multihost.join:transient@1") is not None


# -- the restart-the-world supervisor -----------------------------------------


class FakeChild:
    """A scripted child: exits with ``rc`` after ``after_polls`` polls
    (None = runs forever until terminated)."""

    def __init__(self, rc=0, after_polls=0):
        self.rc = rc
        self.after = after_polls
        self.polls = 0
        self.terminated = False
        self.killed = False

    def poll(self):
        if self.terminated or self.killed:
            return self.rc if self.rc is not None else -15
        self.polls += 1
        if self.after is not None and self.polls > self.after:
            return self.rc
        return None

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True

    def wait(self, timeout=None):
        return self.poll()


def _supervisor(worlds, **kw):
    """A WorldSupervisor over a script of fake worlds: each entry is a list
    of FakeChild. Returns (supervisor, launches[], sleeps[])."""
    from perceiver_io_tpu.cli.common import WorldSupervisor

    launches, sleeps = [], []
    script = iter(worlds)

    def launch(resume_dir):
        launches.append(resume_dir)
        return next(script), [None, None]

    kw.setdefault("poll_s", 0.0)
    sup = WorldSupervisor(
        launch=launch, n=2, sleep=sleeps.append, **kw)
    return sup, launches, sleeps


def test_supervisor_success_needs_no_restart():
    sup, launches, sleeps = _supervisor(
        [[FakeChild(0), FakeChild(0)]], attempts=3)
    sup.run()
    assert launches == [None] and sleeps == []


def test_supervisor_restarts_world_with_resume_and_backoff(tmp_path):
    """First world dies (one child rc=-9) → the survivors are reaped, the
    counter and backoff actuate, and the relaunch carries the newest
    resumable run dir; second world completes."""
    restarts0 = obs.get_registry().counter("spawn_world_restarts_total").value
    survivor = FakeChild(0, after_polls=None)
    worlds = [[FakeChild(-9, after_polls=2), survivor],
              [FakeChild(0), FakeChild(0)]]
    sup, launches, sleeps = _supervisor(
        worlds, attempts=3, find_resume=lambda: str(tmp_path / "version_1"))
    # defeat the crash-loop detector: fakes fail instantly by construction
    import perceiver_io_tpu.cli.common as common

    orig = common._CRASHLOOP_WINDOW_S
    common._CRASHLOOP_WINDOW_S = -1.0
    try:
        sup.run()
    finally:
        common._CRASHLOOP_WINDOW_S = orig
    assert launches == [None, str(tmp_path / "version_1")]
    assert survivor.terminated  # the world is killed as a unit
    assert len(sleeps) == 1 and sleeps[0] > 0
    assert (obs.get_registry().counter("spawn_world_restarts_total").value
            == restarts0 + 1)


def test_supervisor_attempt_budget_exhausted_raises():
    import perceiver_io_tpu.cli.common as common

    worlds = [[FakeChild(3), FakeChild(0)] for _ in range(2)]
    sup, launches, _ = _supervisor(worlds, attempts=2)
    orig = common._CRASHLOOP_WINDOW_S
    common._CRASHLOOP_WINDOW_S = -1.0
    try:
        with pytest.raises(SystemExit) as exc:
            sup.run()
    finally:
        common._CRASHLOOP_WINDOW_S = orig
    assert exc.value.code == 3
    assert len(launches) == 2


def test_supervisor_crash_loop_detaches_early():
    """Consecutive instant failures detach after _CRASHLOOP_LIMIT worlds
    even with attempts left — a deterministic failure must not burn the
    budget at backoff cadence."""
    import perceiver_io_tpu.cli.common as common

    worlds = [[FakeChild(7), FakeChild(0)] for _ in range(10)]
    sup, launches, _ = _supervisor(worlds, attempts=10)
    with pytest.raises(SystemExit) as exc:
        sup.run()
    assert exc.value.code == 7
    assert len(launches) == common._CRASHLOOP_LIMIT


def test_supervisor_child_exit_fault_site_restarts():
    """PIT_FAULTS drill: an injected raise at spawn.child_exit is treated as
    an observed child death — the world restarts without any real kill."""
    import perceiver_io_tpu.cli.common as common

    faults.install(faults.parse_spec("spawn.child_exit:transient@1"))
    first_world = [FakeChild(0, after_polls=None),
                   FakeChild(0, after_polls=None)]
    worlds = [first_world, [FakeChild(0), FakeChild(0)]]
    sup, launches, _ = _supervisor(worlds, attempts=3)
    orig = common._CRASHLOOP_WINDOW_S
    common._CRASHLOOP_WINDOW_S = -1.0
    try:
        sup.run()
    finally:
        common._CRASHLOOP_WINDOW_S = orig
    assert len(launches) == 2
    assert all(c.terminated for c in first_world)


def test_newest_resumable_run_scans_committed_checkpoints(tmp_path):
    from perceiver_io_tpu.cli.common import _newest_resumable_run

    assert _newest_resumable_run(str(tmp_path), "exp") is None
    base = tmp_path / "exp"
    # version_0: committed step; version_2: hparams but no committed step;
    # version_1: last/-slot commit only
    v0 = base / "version_0" / "checkpoints"
    (v0 / "4").mkdir(parents=True)
    (v0 / "hparams.json").write_text("{}")
    (v0 / "4" / "_CHECKPOINT_METADATA").write_text("{}")
    assert _newest_resumable_run(str(tmp_path), "exp") == str(base / "version_0")
    v1 = base / "version_1" / "checkpoints"
    (v1 / "last" / "7").mkdir(parents=True)
    (v1 / "hparams.json").write_text("{}")
    (v1 / "last" / "7" / "_CHECKPOINT_METADATA").write_text("{}")
    assert _newest_resumable_run(str(tmp_path), "exp") == str(base / "version_1")
    v2 = base / "version_2" / "checkpoints"
    v2.mkdir(parents=True)
    (v2 / "hparams.json").write_text("{}")
    # newest dir is not resumable — fall back to the newest one that is
    assert _newest_resumable_run(str(tmp_path), "exp") == str(base / "version_1")


# -- elastic resize (r23): descriptor, progress, buddy mirrors, supervisor ----


def test_world_descriptor_shrink_grow_buddy_ring():
    from perceiver_io_tpu.parallel.mesh import WorldDescriptor

    w = WorldDescriptor(0, (0, 1, 2, 3), node_id=1)
    assert (w.process_id, w.num_processes, w.leader) == (1, 4, 0)
    assert [w.buddy_of(r) for r in w.ranks] == [1, 2, 3, 0]
    s = w.shrink(3)
    assert s.generation == 1 and s.ranks == (0, 1, 2)
    assert s.buddy_of(2) == 0  # the ring re-closes over the survivors
    g = s.grow(4)
    assert g.generation == 2 and g.ranks == (0, 1, 2, 4)
    assert g.process_id == 1  # node ids are stable; jax ids are dense
    assert g.buddy_of(4) == 0
    with pytest.raises(ValueError):
        WorldDescriptor(0, (0, 2), node_id=1)  # not a member


def test_elastic_progress_file_roundtrip(tmp_path):
    from perceiver_io_tpu.resilience.elastic import (
        note_progress, progress_path, read_progress)

    path = progress_path(str(tmp_path))
    assert read_progress(path) is None  # missing file: no progress yet
    note_progress(path, generation=1, step=7, world_size=3)
    rec = read_progress(path)
    assert (rec["generation"], rec["step"], rec["world_size"]) == (1, 7, 3)
    assert rec["wall"] > 0
    note_progress(path, generation=2, step=9, world_size=4)
    assert read_progress(path)["step"] == 9  # atomic replace, last wins


def test_elastic_config_validation():
    from perceiver_io_tpu.resilience.elastic import ElasticConfig

    cfg = ElasticConfig(node_id=2, n_max=5,
                        coordinator_address="localhost:12345")
    assert cfg.coordinator_port == 12345
    with pytest.raises(ValueError):
        ElasticConfig(node_id=5, n_max=5,
                      coordinator_address="localhost:12345")


def _np_snapshot():
    return {"params": {"w": np.arange(6, dtype=np.float32).reshape(3, 2)},
            "step": np.asarray(4, np.int64)}


def test_buddy_mirror_roundtrip_is_digest_identical(tmp_path):
    from perceiver_io_tpu.resilience.elastic import BuddyMirror, BuddyStore
    from perceiver_io_tpu.training.checkpoint import snapshot_digest

    store = BuddyStore(0, root=str(tmp_path)).start()
    try:
        mirror = BuddyMirror(1, root=str(tmp_path))
        snap = _np_snapshot()
        mirror.mirror_to(0, snap, generation=1, step=4)
        meta = store.mirror_meta(1)
        assert meta["digest"] == snapshot_digest(snap)
        assert (meta["owner"], meta["gen"], meta["step"]) == (1, 1, 4)
        got = mirror.fetch_from(0, 1, _np_snapshot())
        assert got is not None
        restored, rmeta = got
        assert snapshot_digest(restored) == meta["digest"]
        np.testing.assert_array_equal(restored["params"]["w"],
                                      snap["params"]["w"])
        # a shard nobody mirrored is a clean miss, not an error
        assert mirror.fetch_from(0, 9, _np_snapshot()) is None
    finally:
        store.close()


def test_buddy_send_corruption_is_digest_rejected(tmp_path):
    """The multihost.buddy_send chaos site: a NaN-poisoned mirror payload
    must be REJECTED at fetch time (digest mismatch), never restored."""
    from perceiver_io_tpu.resilience.elastic import BuddyMirror, BuddyStore

    store = BuddyStore(0, root=str(tmp_path)).start()
    try:
        mirror = BuddyMirror(1, root=str(tmp_path))
        faults.install(faults.parse_spec("multihost.buddy_send:nan@1"))
        mirror.mirror_to(0, _np_snapshot(), generation=1, step=4)
        faults.install(None)
        assert store.mirror_meta(1) is not None  # the PUT itself landed
        assert mirror.fetch_from(0, 1, _np_snapshot()) is None
    finally:
        store.close()


def test_reresolve_shardings_reports_degradation():
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.parallel.sharding import reresolve_shardings

    devs = jax.devices()
    old = make_mesh(dp=2, tp=2, devices=devs[:4])
    new = make_mesh(dp=2, tp=4, devices=devs[:8])
    tree = {"q_proj": {"kernel": np.zeros((4, 6), np.float32)},
            "norm": {"scale": np.zeros((4,), np.float32)}}
    shardings, degraded = reresolve_shardings(tree, old, new)
    # 6 % tp=2 fit on the old mesh but 6 % tp=4 falls back to replication
    assert degraded == ["q_proj/kernel"]
    assert set(jax.tree.leaves(shardings, is_leaf=lambda x: hasattr(
        x, "mesh"))) != set()
    # same tp: nothing degrades
    _, none_degraded = reresolve_shardings(tree, old, old)
    assert none_degraded == []


def test_dataloader_reshard_preserves_global_batches():
    from perceiver_io_tpu.data.pipeline import DataLoader

    examples = list(range(24))

    def collate(batch):
        return np.asarray(batch)

    def epoch_of(loader, epoch):
        loader.epoch = epoch
        return [b.tolist() for b in loader]

    whole = DataLoader(examples, batch_size=12, collate=collate,
                       shuffle=True, seed=3, drop_last=True)
    shard0 = DataLoader(examples, batch_size=12, collate=collate,
                        shuffle=True, seed=3, drop_last=True,
                        shard_id=0, num_shards=4)
    full = epoch_of(whole, 5)
    quarter = epoch_of(shard0, 5)
    # elastic handoff: re-shard 4 -> 3 mid-run; the GLOBAL batch at each
    # step is unchanged, only its slicing moves
    shard0.reshard(0, 3)
    third = epoch_of(shard0, 5)
    for full_b, q_b, t_b in zip(full, quarter, third):
        assert full_b[:3] == q_b
        assert full_b[:4] == t_b


class _ScriptedProgress:
    """A progress probe whose step advances on every call — the signature
    of an elastic world that keeps training through deaths."""

    def __init__(self, advancing=True):
        self.calls = 0
        self.advancing = advancing

    def __call__(self):
        if self.advancing:
            self.calls += 1
        return {"generation": 1, "step": self.calls, "wall": float(self.calls)}


def _elastic_supervisor(worlds, n, **kw):
    from perceiver_io_tpu.cli.common import WorldSupervisor

    launches, sleeps = [], []
    script = iter(worlds)

    def launch(resume_dir):
        launches.append(resume_dir)
        return next(script), [None] * n

    kw.setdefault("poll_s", 0.0)
    sup = WorldSupervisor(launch=launch, n=n, sleep=sleeps.append, **kw)
    return sup, launches, sleeps


def test_supervisor_elastic_absorbs_death_when_progress_advances():
    """--elastic: a child death with elastic progress still advancing is
    ABSORBED — no reap, no relaunch; the survivors finish the job."""
    absorbed0 = obs.get_registry().counter(
        "spawn_elastic_absorbed_total").value
    world = [FakeChild(0, after_polls=8), FakeChild(0, after_polls=8),
             FakeChild(1, after_polls=2)]
    sup, launches, _ = _elastic_supervisor(
        [world], n=3, attempts=2, elastic=True, quorum=1,
        progress_probe=_ScriptedProgress())
    sup.run()
    assert launches == [None]  # one world, zero restarts
    assert not world[0].terminated and not world[1].terminated
    assert (obs.get_registry().counter("spawn_elastic_absorbed_total").value
            == absorbed0 + 1)


def test_supervisor_elastic_quorum_floor_restarts_world():
    """--elastic below the quorum floor degrades to restart-the-world."""
    import perceiver_io_tpu.cli.common as common

    worlds = [[FakeChild(0, after_polls=None), FakeChild(1, after_polls=2)],
              [FakeChild(0), FakeChild(0)]]
    sup, launches, _ = _elastic_supervisor(
        worlds, n=2, attempts=3, elastic=True, quorum=2,
        progress_probe=_ScriptedProgress())
    orig = common._CRASHLOOP_WINDOW_S
    common._CRASHLOOP_WINDOW_S = -1.0
    try:
        sup.run()
    finally:
        common._CRASHLOOP_WINDOW_S = orig
    assert len(launches) == 2  # the restart actuated
    assert worlds[0][0].terminated  # survivors reaped with the world


def test_supervisor_elastic_stalled_progress_restarts_world():
    """--elastic with quorum met but NO elastic progress inside the grace
    window falls back to restart-the-world (the resize wedged/failed)."""
    import perceiver_io_tpu.cli.common as common

    worlds = [[FakeChild(0, after_polls=None), FakeChild(0, after_polls=None),
               FakeChild(1, after_polls=2)],
              [FakeChild(0), FakeChild(0), FakeChild(0)]]
    sup, launches, _ = _elastic_supervisor(
        worlds, n=3, attempts=3, elastic=True, quorum=1,
        progress_probe=_ScriptedProgress(advancing=False),
        elastic_grace_s=0.05)
    orig = common._CRASHLOOP_WINDOW_S
    common._CRASHLOOP_WINDOW_S = -1.0
    try:
        sup.run()
    finally:
        common._CRASHLOOP_WINDOW_S = orig
    assert len(launches) == 2


def test_supervisor_progress_resets_attempt_budget():
    """The satellite fix: a world that made step progress earns back the
    FULL --spawn_attempts budget — rejoins reaching a clean boundary (or
    plain productive training) must not inherit old failures' attempts."""
    import perceiver_io_tpu.cli.common as common

    worlds = [[FakeChild(5), FakeChild(0)],
              [FakeChild(5), FakeChild(0)],
              [FakeChild(0), FakeChild(0)]]
    sup, launches, _ = _elastic_supervisor(
        worlds, n=2, attempts=2, progress_probe=_ScriptedProgress())
    orig = common._CRASHLOOP_WINDOW_S
    common._CRASHLOOP_WINDOW_S = -1.0
    try:
        sup.run()  # would raise SystemExit after 2 launches without the fix
    finally:
        common._CRASHLOOP_WINDOW_S = orig
    assert len(launches) == 3


def test_multihost_drill_dry_declares_elastic_keys(capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "multihost_drill", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "multihost_drill.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--elastic", "--dry"]) == 0
    record = json.loads(capsys.readouterr().out.strip())
    assert record["dry"] is True and record["mode"] == "elastic"
    for key in ("resize_wall_s", "grow_wall_s", "join_wall_s",
                "buddy_restore_bytes", "steps_lost", "parity", "speedup"):
        assert key in record


# -- the elastic chaos drills (slow): 4 -> 3 -> 4 on the real CPU cluster -----

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ELASTIC_WORKER = os.path.join(_REPO, "tests", "elastic_worker.py")


def _spawn_elastic(workdir, *, steps=12, pool=5, die_rank=3, die_at=4,
                   quorum=3, rank_env=None, extra=()):
    """Run the elastic pool to completion; returns (rcs, per-rank reports).

    ``rank_env`` maps rank -> extra env (per-rank PIT_FAULTS drills)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = _REPO + os.pathsep + base_env.get(
        "PYTHONPATH", "")
    procs = []
    for rank in range(pool):
        env = dict(base_env)
        env.update((rank_env or {}).get(rank, {}))
        log = open(os.path.join(str(workdir), f"r{rank}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, _ELASTIC_WORKER, "--rank", str(rank),
             "--pool", str(pool), "--port", str(port),
             "--workdir", str(workdir), "--steps", str(steps),
             "--die_rank", str(die_rank), "--die_at", str(die_at),
             "--quorum", str(quorum), *extra],
            env=env, stdout=log, stderr=log))
    rcs = [p.wait(timeout=240) for p in procs]
    reports = {}
    for rank in range(pool):
        path = os.path.join(str(workdir), f"rank{rank}_elastic.json")
        if os.path.exists(path):
            with open(path) as f:
                reports[rank] = json.load(f)
    return rcs, reports


def _control_losses(steps=12):
    """The unkilled single-process control: the same deterministic global
    batches (seed/epoch-pure DataLoader order), the same SGD math — what
    every elastic world must reproduce step for step."""
    from perceiver_io_tpu.data.pipeline import DataLoader

    rng = np.random.default_rng(0)
    w_true = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
    x = rng.normal(0, 1, (96, 3)).astype(np.float32)
    examples = list(zip(x, x @ w_true))

    def collate(batch):
        return {"x": np.stack([e[0] for e in batch]),
                "y": np.stack([e[1] for e in batch])}

    loader = DataLoader(examples, batch_size=24, collate=collate,
                        shuffle=True, seed=0, drop_last=True)
    w = np.zeros((3, 1), np.float32)
    losses = []
    while len(losses) < steps:
        for batch in loader:
            pred = batch["x"] @ w
            err = pred - batch["y"]
            losses.append(float(np.mean(err ** 2)))
            w = w - 0.1 * (2.0 / len(err)) * (batch["x"].T @ err)
            if len(losses) >= steps:
                break
    return losses


def _merged_losses(reports, ranks):
    merged = {}
    for r in ranks:
        for k, v in reports[r]["losses"].items():
            if int(k) in merged:
                assert merged[int(k)] == v, f"step {k} diverged across ranks"
            merged[int(k)] = v
    return merged


@pytest.mark.slow  # tier-1 budget (r23): 5-process 4->3->4 chaos drill ~60s
def test_elastic_chaos_drill_4_3_4(tmp_path):
    """The acceptance drill: kill rank 3 mid-epoch -> survivors resize to 3
    IN-PROCESS and replay from the buddy-mirrored boundary (zero steps
    lost, loss-parity with the unkilled control), then the hot spare joins
    back to 4 through the same resize path and the whole world converges
    to one state digest."""
    rcs, reports = _spawn_elastic(tmp_path)
    assert rcs[3] == 1, "the killed rank must exit nonzero"
    assert [rcs[r] for r in (0, 1, 2, 4)] == [0, 0, 0, 0], (
        f"survivor rcs {rcs}")

    # ISSUE bound: <=1 step loss divergence vs the control; measured zero
    merged = _merged_losses(reports, (0, 1, 2))
    lost = sorted(set(range(12)) - set(merged))
    assert not lost, f"steps lost: {lost}"
    control = _control_losses(12)
    for s in range(12):
        assert abs(merged[s] - control[s]) <= 1e-4 * (abs(control[s]) + 1e-8)

    # peer-redundant restore: the restored shard is digest-identical to
    # the buddy mirror (replicated state: also to the survivor's own)
    restored = [e for e in reports[0]["events"]
                if e["kind"] == "mirror_restored"]
    assert restored and restored[0]["owner"] == 3
    assert restored[0]["digest"] == restored[0]["own_digest"]
    assert restored[0]["bytes"] > 0

    # generation history 4 -> 3 -> 4 on every survivor, dense jax view
    for r in (0, 1, 2):
        gens = [(g["gen"], tuple(g["ranks"]))
                for g in reports[r]["generations"]]
        assert gens == [(0, (0, 1, 2, 3)), (1, (0, 1, 2)),
                        (2, (0, 1, 2, 4))]

    # the spare joined from its buddy's self-copy and caught up
    kinds4 = [e["kind"] for e in reports[4]["events"]]
    assert "joined" in kinds4
    assert reports[4]["final_step"] == 12

    # one agreed final state across the post-resize world
    digests = {reports[r]["final_digest"] for r in (0, 1, 2, 4)}
    assert len(digests) == 1 and None not in digests

    # recovery wall: decision -> resume, bounded well under the ~10-11s
    # restart-the-world baseline (PERF.md SElastic training)
    walls = [reports[r]["walls"]["decision_to_resume_s"] for r in (0, 1, 2)]
    assert max(walls) < 20.0, f"resize walls {walls}"
    assert all("grow_s" in reports[r]["walls"] for r in (0, 1, 2))
    assert "join_s" in reports[4]["walls"]


@pytest.mark.slow  # tier-1 budget (r23): fault-site chaos variants ~60s
def test_elastic_fault_drill_corrupt_mirror_and_flaky_join(tmp_path):
    """Two drilled fault sites in one world: the dead rank's buddy mirrors
    were NaN-poisoned in flight (multihost.buddy_send) -> digest-REJECTED
    at restore, training continues from the survivor's own replicated
    state; the spare's first join attempt is injected transient
    (multihost.join) -> it retries the same invite and lands."""
    rank_env = {3: {"PIT_FAULTS": "multihost.buddy_send:nan@every:1"},
                4: {"PIT_FAULTS": "multihost.join:transient@1"}}
    rcs, reports = _spawn_elastic(tmp_path, rank_env=rank_env)
    assert [rcs[r] for r in (0, 1, 2, 4)] == [0, 0, 0, 0], (
        f"survivor rcs {rcs}")

    rejected = [e for e in reports[0]["events"]
                if e["kind"] == "mirror_rejected"]
    assert rejected and rejected[0]["owner"] == 3
    assert not any(e["kind"] == "mirror_restored"
                   for e in reports[0]["events"])

    kinds4 = [e["kind"] for e in reports[4]["events"]]
    assert "join_retry" in kinds4 and "joined" in kinds4

    merged = _merged_losses(reports, (0, 1, 2))
    assert sorted(merged) == list(range(12))  # still zero steps lost
    digests = {reports[r]["final_digest"] for r in (0, 1, 2, 4)}
    assert len(digests) == 1 and None not in digests


@pytest.mark.slow  # tier-1 budget (r23): double-death mid-resize drill ~90s
def test_elastic_fault_drill_death_mid_resize(tmp_path):
    """A second rank dies INSIDE the resize (multihost.resize:fatal, the
    kill -9 drill): the first rebuild attempt times out on the dead
    rank's rendezvous key, shrink_until_stable retires it and lands the
    remaining two; the spare still joins at the agreed boundary."""
    rank_env = {2: {"PIT_FAULTS": "multihost.resize:fatal@1"}}
    rcs, reports = _spawn_elastic(
        tmp_path, quorum=2, rank_env=rank_env,
        extra=("--sync_timeout_ms", "8000"))
    assert rcs[0] == 0 and rcs[1] == 0, f"survivor rcs {rcs}"
    assert rcs[2] == 1 and rcs[3] == 1

    assert any(e["kind"] == "die_in_resize" for e in reports[2]["events"])
    for r in (0, 1):
        gens = [tuple(g["ranks"]) for g in reports[r]["generations"]]
        assert (0, 1) in gens and gens[-1] == (0, 1, 4)
        assert reports[r]["final_step"] == 12

    merged = _merged_losses(reports, (0, 1))
    assert sorted(merged) == list(range(12))
    assert reports[4]["final_step"] == 12
    digests = {reports[r]["final_digest"] for r in (0, 1, 4)}
    assert len(digests) == 1 and None not in digests
