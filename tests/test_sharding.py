"""SPMD tests on the 8-virtual-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8 — SURVEY.md §4's strategy)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import perceiver_io_tpu as pit
from perceiver_io_tpu.ops.masking import TextMasking
from perceiver_io_tpu.parallel import (
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    batch_pspecs,
    make_mesh,
    make_sharded_train_step,
    sharding_for_tree,
)
from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    make_classifier_steps,
    make_mlm_steps,
    make_optimizer,
)

VOCAB, L, C, NLAT = 50, 32, 64, 16


def build_mlm():
    enc = pit.PerceiverEncoder(
        input_adapter=pit.TextInputAdapter(vocab_size=VOCAB, max_seq_len=L, num_channels=C),
        latent_shape=(NLAT, C),
        num_layers=2,
    )
    dec = pit.PerceiverDecoder(
        output_adapter=pit.TextOutputAdapter(vocab_size=VOCAB, max_seq_len=L,
                                             num_output_channels=C),
        latent_shape=(NLAT, C),
    )
    return pit.PerceiverMLM(
        encoder=enc, decoder=dec, masking=TextMasking(VOCAB, 1, 2, 3)
    )


@pytest.fixture(scope="module")
def mlm_parts():
    model = build_mlm()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(3, VOCAB, (16, L)).astype(np.int32))
    pad = jnp.zeros((16, L), dtype=bool)
    batch = {"token_ids": ids, "pad_mask": pad}
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)}, ids, pad
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    train_step, eval_step, _ = make_mlm_steps(model, sched)
    return model, variables["params"], tx, batch, train_step


@pytest.fixture
def mlm_setup(mlm_parts):
    """Fresh TrainState per test: sharded steps donate their state, and a
    donated state can alias the source buffers it was device_put from."""
    model, params, tx, batch, train_step = mlm_parts
    state = TrainState.create(jax.tree.map(jnp.copy, params), tx, jax.random.key(2))
    return model, state, batch, train_step


def _run(step, state, batch, n=3):
    losses = []
    for _ in range(n):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return state, losses


def test_mesh_shapes():
    mesh = make_mesh(dp=2, tp=2, sp=2)
    assert mesh.shape == {"data": 2, "model": 2, "seq": 2}
    mesh = make_mesh()  # all devices on data
    assert mesh.shape["data"] == 8


def test_mesh_validates():
    with pytest.raises(ValueError, match="divisible"):
        make_mesh(tp=3)
    with pytest.raises(ValueError, match="!="):
        make_mesh(dp=3, tp=2, sp=2)


def test_hybrid_dcn_mesh_layout():
    """``dcn_dp`` lays the data axis out DCN-major while tp stays inside one
    granule. On a single-process CPU backend granules fall back to contiguous
    chunks, so devices 0-3 must fill data rows 0-1 and devices 4-7 rows 2-3."""
    devices = jax.devices()
    mesh = make_mesh(tp=2, dcn_dp=2)  # dp = 4 total, 2 inner per granule
    assert mesh.shape == {"data": 4, "model": 2, "seq": 1}
    grid = np.asarray(mesh.devices)
    assert {d.id for d in grid[:2].flat} == {d.id for d in devices[:4]}
    assert {d.id for d in grid[2:].flat} == {d.id for d in devices[4:]}
    # every tp pair sits inside one granule (its collectives never cross DCN)
    for row in grid.reshape(4, 2):
        ids = sorted(d.id for d in row)
        assert all(i < 4 for i in ids) or all(i >= 4 for i in ids)


def test_hybrid_dcn_mesh_validates():
    with pytest.raises(ValueError, match="must divide"):
        make_mesh(tp=2, dcn_dp=3)  # dp = 4; 3 does not divide it
    with pytest.raises(ValueError, match=">= 1"):
        make_mesh(dcn_dp=0)


@pytest.mark.slow  # the hybrid-DCN mesh is also exercised end to end by
# tests/test_cli.py::test_train_mlm_hybrid_dcn_mesh (tier-1)
def test_hybrid_dcn_mesh_matches_single_device(mlm_setup):
    """The hybrid layout changes device placement only — the logical mesh and
    therefore the training numerics must be identical."""
    model, state, batch, train_step = mlm_setup
    _, ref = _run(jax.jit(train_step), state, batch)
    mesh = make_mesh(tp=2, dcn_dp=2)
    step, sstate, bshard = make_sharded_train_step(train_step, mesh, state, batch)
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


def test_dp_tp_sp_matches_single_device(mlm_setup):
    """Full 3D sharding (data × model × seq) must reproduce the single-device
    loss trajectory — collectives inserted by XLA, not by us."""
    model, state, batch, train_step = mlm_setup
    _, ref = _run(jax.jit(train_step), state, batch)

    mesh = make_mesh(dp=2, tp=2, sp=2)
    step, sstate, bshard = make_sharded_train_step(
        train_step, mesh, state, batch, shard_seq=True
    )
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


@pytest.mark.slow  # tier-1 budget (r10): pure-dp semantics are a subset of
# the composite test_dp_tp_sp_matches_single_device parity gate (tier-1)
def test_pure_dp_matches_single_device(mlm_setup):
    model, state, batch, train_step = mlm_setup
    _, ref = _run(jax.jit(train_step), state, batch)
    mesh = make_mesh()  # 8-way data parallel
    step, sstate, bshard = make_sharded_train_step(train_step, mesh, state, batch)
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


def test_tp_leaves_actually_sharded(mlm_setup):
    model, state, batch, train_step = mlm_setup
    mesh = make_mesh(dp=4, tp=2, sp=1)
    _, sstate, _ = make_sharded_train_step(train_step, mesh, state, batch)
    kernel = sstate.params["encoder"]["layer_1"]["cross_attention_layer"][
        "cross_attention"]["attention"]["q_proj"]["kernel"]
    assert kernel.sharding.spec == P(None, AXIS_MODEL)
    # local shard is half the columns
    shard = kernel.addressable_shards[0]
    assert shard.data.shape == (kernel.shape[0], kernel.shape[1] // 2)
    # optimizer state (adam mu) picks up the same rule through path matching
    mu = None
    for leaf_state in jax.tree.leaves(
        sstate.opt_state, is_leaf=lambda x: hasattr(x, "mu")
    ):
        if hasattr(leaf_state, "mu"):
            mu = leaf_state.mu
            break
    assert mu is not None
    mu_kernel = mu["encoder"]["layer_1"]["cross_attention_layer"][
        "cross_attention"]["attention"]["q_proj"]["kernel"]
    assert mu_kernel.sharding.spec == P(None, AXIS_MODEL)


def test_uneven_dims_stay_replicated(mlm_setup):
    """vocab=50 output projection doesn't divide tp=4 ⇒ falls back to
    replication instead of padded shards."""
    model, state, batch, train_step = mlm_setup
    mesh = make_mesh(dp=2, tp=4, sp=1)
    shardings = sharding_for_tree(state.params, mesh)
    spec = shardings["decoder"]["output_adapter"]["linear"]["kernel"].spec
    assert spec == P()  # 50 % 4 != 0
    # while divisible leaves are sharded
    q = shardings["encoder"]["layer_1"]["cross_attention_layer"][
        "cross_attention"]["attention"]["q_proj"]["kernel"].spec
    assert q == P(None, AXIS_MODEL)


def test_batch_pspecs():
    mesh = make_mesh(dp=2, tp=2, sp=2)
    batch = {
        "token_ids": np.zeros((8, 16), np.int32),
        "pad_mask": np.zeros((8, 16), bool),
        "label": np.zeros((8,), np.int32),
        "image": np.zeros((8, 28, 28, 1), np.float32),
    }
    specs = batch_pspecs(batch, mesh, shard_seq=True)
    assert specs["token_ids"] == P(AXIS_DATA, "seq")
    assert specs["pad_mask"] == P(AXIS_DATA, "seq")
    assert specs["label"] == P(AXIS_DATA)
    # image/frames: first spatial axis (contiguous prefix of flattened M)
    assert specs["image"] == P(AXIS_DATA, "seq", None, None)
    frames = {"frames": np.zeros((8, 2, 16, 16, 3), np.float32)}
    assert batch_pspecs(frames, mesh, shard_seq=True)["frames"] == P(
        AXIS_DATA, None, "seq", None, None
    )
    specs = batch_pspecs(batch, mesh, shard_seq=False)
    assert specs["token_ids"] == P(AXIS_DATA, None)
    assert specs["image"] == P(AXIS_DATA, None, None, None)


@pytest.mark.slow  # sharding-rule parity stays tier-1 on the MLM family
# (dp_tp_sp/zero/tp-vocab); the image model rides the mesh'd CLI in
# tests/test_cli.py::test_train_img_clf
def test_image_classifier_sharded(rng):
    enc = pit.PerceiverEncoder(
        input_adapter=pit.ImageInputAdapter(image_shape=(8, 8, 1), num_frequency_bands=6),
        latent_shape=(8, 32),
        num_layers=2,
    )
    dec = pit.PerceiverDecoder(
        output_adapter=pit.ClassificationOutputAdapter(num_classes=4, num_output_channels=32),
        latent_shape=(8, 32),
    )
    model = pit.PerceiverIO(encoder=enc, decoder=dec)
    images = jnp.asarray(rng.standard_normal((16, 8, 8, 1)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 4, 16))
    batch = {"image": images, "label": labels}
    variables = model.init(jax.random.key(0), images)
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(1))
    train_step, _ = make_classifier_steps(model, input_kind="image")

    # Sharded steps donate their state and device_put can alias the source
    # buffers, so give each sharded run its own copy.
    fresh = lambda: jax.tree.map(jnp.copy, state)

    _, ref = _run(jax.jit(train_step), fresh(), batch)
    mesh = make_mesh(dp=4, tp=2, sp=1)
    step, sstate, bshard = make_sharded_train_step(train_step, mesh, fresh(), batch)
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)

    # sequence-parallel over the image's first spatial axis (KV stream)
    mesh = make_mesh(dp=2, tp=2, sp=2)
    step, sstate, bshard = make_sharded_train_step(
        train_step, mesh, fresh(), batch, shard_seq=True
    )
    assert bshard["image"].spec == P(AXIS_DATA, "seq", None, None)
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


def test_padded_vocab_projection_shards_under_tp(rng):
    """pad_classes_to makes the vocab projection divisible by tp, so the
    framework's biggest matmul tensor-shards instead of falling back to
    replication (SURVEY.md §7 'vocab-sharded output projection')."""
    vocab = 51  # divides nothing
    enc = pit.PerceiverEncoder(
        input_adapter=pit.TextInputAdapter(vocab_size=vocab, max_seq_len=L, num_channels=C),
        latent_shape=(NLAT, C),
        num_layers=2,
    )
    dec = pit.PerceiverDecoder(
        output_adapter=pit.TextOutputAdapter(
            vocab_size=vocab, max_seq_len=L, num_output_channels=C,
            pad_classes_to=8,  # 51 -> 56 = 4 tp * 14
        ),
        latent_shape=(NLAT, C),
    )
    model = pit.PerceiverMLM(
        encoder=enc, decoder=dec, masking=TextMasking(vocab, 1, 2, 3)
    )
    rng_np = np.random.default_rng(0)
    ids = jnp.asarray(rng_np.integers(3, vocab, (16, L)).astype(np.int32))
    pad = jnp.zeros((16, L), dtype=bool)
    batch = {"token_ids": ids, "pad_mask": pad}
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)}, ids, pad
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    train_step, _, _ = make_mlm_steps(model, sched)
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    fresh = lambda: jax.tree.map(jnp.copy, state)

    _, ref = _run(jax.jit(train_step), fresh(), batch)

    mesh = make_mesh(dp=2, tp=4, sp=1)
    spec = sharding_for_tree(state.params, mesh)[
        "decoder"]["output_adapter"]["linear"]["kernel"].spec
    assert spec == P(None, AXIS_MODEL)  # 56 % 4 == 0: sharded, not replicated

    step, sstate, bshard = make_sharded_train_step(train_step, mesh, fresh(), batch)
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


def test_multimodal_autoencoder_sharded(rng):
    from perceiver_io_tpu.models.multimodal import build_multimodal_autoencoder
    from perceiver_io_tpu.training import make_multimodal_steps

    model = build_multimodal_autoencoder(
        video_shape=(2, 8, 8, 1),
        num_audio_samples=64,
        samples_per_patch=8,
        num_classes=3,
        latent_shape=(8, 32),
        video_patch_shape=(1, 4, 4),
        num_self_attention_layers_per_block=1,
        num_self_attention_heads=2,
        num_modality_channels=4,
        video_frequency_bands=2,
        audio_frequency_bands=2,
    )
    batch = {
        "video": jnp.asarray(rng.normal(0, 1, (8, 2, 8, 8, 1)).astype(np.float32)),
        "audio": jnp.asarray(rng.normal(0, 1, (8, 64, 1)).astype(np.float32)),
        "label": jnp.asarray(rng.integers(0, 3, 8).astype(np.int32)),
    }
    variables = model.init(
        {"params": jax.random.key(0)},
        {"video": batch["video"], "audio": batch["audio"]},
    )
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(1))
    train_step, _ = make_multimodal_steps(model)
    fresh = lambda: jax.tree.map(jnp.copy, state)

    _, ref = _run(jax.jit(train_step), fresh(), batch)

    # dict-input batches shard on the data axis; params/optimizer follow the
    # standard tp rules (attention/MLP widths)
    mesh = make_mesh(dp=4, tp=2, sp=1)
    step, sstate, bshard = make_sharded_train_step(train_step, mesh, fresh(), batch)
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


def test_zero_opt_state_sharding(mlm_setup):
    """ZeRO optimizer-state sharding (SURVEY §2.3): mu/nu leaves shard over
    the data axis, params stay replicated, and the training math is
    unchanged vs the fully-replicated run."""
    from perceiver_io_tpu.parallel import zero_state_shardings

    model, state, batch, train_step = mlm_setup
    fresh = lambda: jax.tree.map(jnp.copy, state)

    _, ref = _run(jax.jit(train_step), fresh(), batch)

    mesh = make_mesh(dp=4, tp=2, sp=1)
    step, sstate, bshard = make_sharded_train_step(
        train_step, mesh, fresh(), batch, zero_opt=True
    )
    # params replicated; mu sharded over data on its first divisible dim
    shardings = zero_state_shardings(state, mesh)
    p_spec = shardings.params["encoder"]["latent"].spec
    assert p_spec == P()
    flat = jax.tree_util.tree_flatten_with_path(shardings.opt_state)[0]
    mu_specs = [s.spec for path, s in flat
                if "mu" in jax.tree_util.keystr(path) and len(s.spec) > 0]
    assert mu_specs and any(AXIS_DATA in spec for spec in mu_specs)
    # the live state is actually placed that way (not just planned)
    live = jax.tree_util.tree_flatten_with_path(sstate.opt_state)[0]
    live_mu = [l.sharding.spec for path, l in live
               if "mu" in jax.tree_util.keystr(path)
               and getattr(l, "ndim", 0) > 0]
    assert any(AXIS_DATA in spec for spec in live_mu)

    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


def test_zero3_param_sharding(mlm_setup):
    """ZeRO-3/FSDP flavor (``zero_opt='params'`` / CLI ``--zero3``): params
    AND opt-state shard over the data axis, GSPMD inserts the
    all-gather-on-use, and the training math is unchanged vs the
    fully-replicated run."""
    from perceiver_io_tpu.parallel import zero_state_shardings

    model, state, batch, train_step = mlm_setup
    fresh = lambda: jax.tree.map(jnp.copy, state)

    _, ref = _run(jax.jit(train_step), fresh(), batch)

    mesh = make_mesh(dp=4, tp=2, sp=1)
    step, sstate, bshard = make_sharded_train_step(
        train_step, mesh, fresh(), batch, zero_opt="params"
    )
    # the PLAN shards params over data (on top of any model-axis rule)...
    shardings = zero_state_shardings(state, mesh, params_too=True)
    flat = jax.tree_util.tree_flatten_with_path(shardings.params)[0]
    p_specs = [s.spec for _, s in flat if len(s.spec) > 0]
    assert p_specs and any(AXIS_DATA in spec for spec in p_specs)
    # ...and the LIVE placed params actually carry it
    live = jax.tree_util.tree_flatten_with_path(sstate.params)[0]
    live_specs = [l.sharding.spec for _, l in live if getattr(l, "ndim", 0) > 0]
    assert any(AXIS_DATA in spec for spec in live_specs)

    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=1e-5)


# -- Pallas kernel × SPMD composition ----------------------------------------
# The long-context design sells blockwise-KV Pallas attention together with
# seq/model sharding (SURVEY.md §5); these tests run the kernel (interpret
# mode off-TPU) under jit with sharded inputs on the 8-device mesh so the
# composition — GSPMD partitioning around pallas_call — is exercised, not
# assumed.


def build_mlm_pallas():
    enc = pit.PerceiverEncoder(
        input_adapter=pit.TextInputAdapter(vocab_size=VOCAB, max_seq_len=L, num_channels=C),
        latent_shape=(NLAT, C),
        num_layers=2,
        attn_impl="pallas",
    )
    dec = pit.PerceiverDecoder(
        output_adapter=pit.TextOutputAdapter(vocab_size=VOCAB, max_seq_len=L,
                                             num_output_channels=C),
        latent_shape=(NLAT, C),
        attn_impl="pallas",
    )
    return pit.PerceiverMLM(
        encoder=enc, decoder=dec, masking=TextMasking(VOCAB, 1, 2, 3)
    )


@pytest.mark.slow  # pallas-under-mesh parity also held by
# test_pallas_sp_step_matches_xla_and_shards_kv (tier-1)
def test_pallas_step_sharded_matches_xla_single_device(mlm_parts):
    """Full MLM train step on the Pallas kernel path, sharded dp×tp×sp —
    must reproduce the single-device XLA-path loss trajectory (same param
    tree: attn_impl changes the kernel, not the parameters)."""
    _, params, tx, batch, xla_step = mlm_parts
    fresh = lambda: TrainState.create(
        jax.tree.map(jnp.copy, params), tx, jax.random.key(2)
    )
    _, ref = _run(jax.jit(xla_step), fresh(), batch)

    model = build_mlm_pallas()
    tx2, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    pallas_step, _, _ = make_mlm_steps(model, sched)
    mesh = make_mesh(dp=2, tp=2, sp=2)
    step, sstate, bshard = make_sharded_train_step(
        pallas_step, mesh, fresh(), batch, shard_seq=True
    )
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=2e-5)


def _kernel_ref(q, k, v, pad_mask):
    """Plain softmax attention with the kernel's 1/sqrt(D) scaling."""
    logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(q.shape[-1])
    if pad_mask is not None:
        logits = jnp.where(pad_mask[:, None, None, :], -1e30, logits)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", probs.astype(q.dtype), v)


@pytest.mark.parametrize("case", ["seq", "model", "seq+model"])
def test_fused_attention_with_sharded_inputs(case, rng):
    """fused_attention under jit with seq-sharded KV and/or model-sharded
    heads: GSPMD must produce the same numbers as the unsharded call."""
    from perceiver_io_tpu.ops.pallas_attention import fused_attention

    B, T, S, H, D = 4, 8, 64, 4, 16
    q = jnp.asarray(rng.normal(0, 1, (B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
    pad = jnp.zeros((B, S), dtype=bool).at[:, -7:].set(True)

    ref = fused_attention(q, k, v, pad_mask=pad)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(_kernel_ref(q, k, v, pad)), atol=1e-5
    )

    mesh = make_mesh(dp=2, tp=2, sp=2)
    seq = AXIS_SEQ if "seq" in case else None
    mdl = AXIS_MODEL if "model" in case else None
    shard = lambda spec: NamedSharding(mesh, spec)
    jitted = jax.jit(
        lambda q, k, v, m: fused_attention(q, k, v, pad_mask=m),
        in_shardings=(
            shard(P(AXIS_DATA, None, mdl, None)),
            shard(P(AXIS_DATA, seq, mdl, None)),
            shard(P(AXIS_DATA, seq, mdl, None)),
            shard(P(AXIS_DATA, seq)),
        ),
    )
    out = jitted(q, k, v, pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fused_attention_grads_with_sharded_inputs(rng):
    """The custom-VJP flash backward must also compose with sharded inputs."""
    from perceiver_io_tpu.ops.pallas_attention import fused_attention

    B, T, S, H, D = 4, 8, 64, 2, 8
    q = jnp.asarray(rng.normal(0, 1, (B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
    pad = jnp.zeros((B, S), dtype=bool).at[:, -5:].set(True)

    def loss(q, k, v):
        return jnp.sum(fused_attention(q, k, v, pad_mask=pad) ** 2)

    ref_grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    mesh = make_mesh(dp=2, tp=1, sp=4)
    shard = lambda spec: NamedSharding(mesh, spec)
    jitted = jax.jit(
        jax.grad(loss, argnums=(0, 1, 2)),
        in_shardings=(
            shard(P(AXIS_DATA, None, None, None)),
            shard(P(AXIS_DATA, AXIS_SEQ, None, None)),
            shard(P(AXIS_DATA, AXIS_SEQ, None, None)),
        ),
    )
    out_grads = jitted(q, k, v)
    for got, want in zip(out_grads, ref_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


@pytest.mark.slow  # knob variant of
# test_dryrun_multichip_covers_kernel_paths_by_default (tier-1)
def test_dryrun_multichip_pallas_knob(monkeypatch):
    """The driver's dry run exercises the kernel path when PIT_DRYRUN_ATTN
    is set (VERDICT r1: Pallas × SPMD was never run together)."""
    import __graft_entry__ as graft

    monkeypatch.setenv("PIT_DRYRUN_ATTN", "pallas")
    graft.dryrun_multichip(8)


@pytest.mark.slow  # the driver runs dryrun_multichip(8) itself as a
# separate check (CLAUDE.md); the default-coverage assertion stays for
# manual runs
def test_dryrun_multichip_covers_kernel_paths_by_default(monkeypatch):
    """Without any env, the dry run must run the XLA, Pallas AND
    sequence-parallel paths (VERDICT r2: the recorded multi-chip artifact
    had only ever certified the XLA path)."""
    import __graft_entry__ as graft

    monkeypatch.delenv("PIT_DRYRUN_ATTN", raising=False)
    graft.dryrun_multichip(8)


# -- sequence-parallel routing through the MODEL path -------------------------
# VERDICT r2 item 1: seq_parallel_fused_attention must be reachable from the
# model/trainer dispatch, not just as an exported op. These tests run the full
# MLM train step with attn_impl='pallas_sp' under shard_seq=True and verify
# (a) the loss trajectory matches the single-device XLA path, and (b) the
# shard_map-local kernel really sees S/n keys per device — the O(S/n) memory
# property, asserted at trace time rather than assumed.


def build_mlm_sp():
    enc = pit.PerceiverEncoder(
        input_adapter=pit.TextInputAdapter(vocab_size=VOCAB, max_seq_len=L, num_channels=C),
        latent_shape=(NLAT, C),
        num_layers=2,
        attn_impl="pallas_sp",
    )
    dec = pit.PerceiverDecoder(
        output_adapter=pit.TextOutputAdapter(vocab_size=VOCAB, max_seq_len=L,
                                             num_output_channels=C),
        latent_shape=(NLAT, C),
        attn_impl="pallas_sp",
    )
    return pit.PerceiverMLM(
        encoder=enc, decoder=dec, masking=TextMasking(VOCAB, 1, 2, 3)
    )


@pytest.mark.slow  # tier-1 budget (r22 box drift): sp-kernel-on-mesh
# parity retained tier-1 by test_pallas_sp_indivisible_batch_falls_back
# (mesh dispatch), TestSpGradientCanary (sp backward gate), and
# test_fused_attention_*_with_sharded_inputs (kernel numerics under
# shardings); the driver runs dryrun_multichip(8) over the kernel paths.
def test_pallas_sp_step_matches_xla_and_shards_kv(mlm_parts, monkeypatch):
    import perceiver_io_tpu.ops.pallas_attention as pa

    _, params, tx, batch, xla_step = mlm_parts
    fresh = lambda: TrainState.create(
        jax.tree.map(jnp.copy, params), tx, jax.random.key(2)
    )
    _, ref = _run(jax.jit(xla_step), fresh(), batch)

    calls = {"global": [], "local": []}
    orig_sp = pa.seq_parallel_fused_attention

    def recording_sp(q, k, v, **kw):
        calls["global"].append((k.shape, kw["axis"]))
        return orig_sp(q, k, v, **kw)

    orig_local = pa._sp_fused

    def recording_local(q, k, v, bias, *rest):
        calls["local"].append(k.shape)  # heads-major (B_loc, H, S_loc, D)
        return orig_local(q, k, v, bias, *rest)

    monkeypatch.setattr(pa, "seq_parallel_fused_attention", recording_sp)
    monkeypatch.setattr(pa, "_sp_fused", recording_local)

    model = build_mlm_sp()
    tx2, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    sp_step, _, _ = make_mlm_steps(model, sched)
    mesh = make_mesh(dp=2, tp=2, sp=2)
    step, sstate, bshard = make_sharded_train_step(
        sp_step, mesh, fresh(), batch, shard_seq=True
    )
    _, sharded = _run(step, sstate, jax.device_put(batch, bshard))
    np.testing.assert_allclose(sharded, ref, atol=2e-5)

    # the encoder's cross-attention (and ONLY it — the self-attention and
    # decoder have latent-sized KV) routed through the sp op, with the full
    # token axis as global KV
    assert calls["global"], "seq_parallel_fused_attention never dispatched"
    assert all(shape[1] == L for shape, _ in calls["global"])
    assert all(ax == AXIS_SEQ for _, ax in calls["global"])
    # ... and each device's kernel streamed only its S/sp shard of keys
    assert calls["local"], "_sp_fused never traced"
    assert all(shape[2] == L // mesh.shape[AXIS_SEQ] for shape in calls["local"])


def test_pallas_sp_indivisible_batch_falls_back(mlm_parts):
    """An eval batch that doesn't divide the data axis (drop_last=False
    tail) must NOT be routed into shard_map — it falls back to the plain
    kernel/XLA path instead of crashing mid-validation."""
    from perceiver_io_tpu.parallel import sequence_parallel_context

    _, params, tx, batch, _ = mlm_parts
    odd = {k: v[:5] for k, v in batch.items()}  # 5 % dp(2) != 0

    model = build_mlm_sp()
    tx2, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    _, eval_step, _ = make_mlm_steps(model, sched)
    state = TrainState.create(
        jax.tree.map(jnp.copy, params), tx, jax.random.key(2)
    )
    ref = float(eval_step(state, odd, jax.random.key(7))["loss"])

    mesh = make_mesh(dp=2, tp=2, sp=2)

    def wrapped(s, b, k):
        with sequence_parallel_context(mesh):
            return jax.jit(eval_step)(s, b, k)

    got = float(wrapped(state, odd, jax.random.key(7))["loss"])
    np.testing.assert_allclose(got, ref, atol=2e-5)


@pytest.mark.slow  # tier-1 budget (r10): the sp-kernel parity gate stays
# tier-1 in test_pallas_sp_step_matches_xla_and_shards_kv; the fallback
# routing in test_pallas_sp_indivisible_batch_falls_back
def test_pallas_sp_without_mesh_degrades_to_pallas(mlm_parts):
    """attn_impl='pallas_sp' on a single device (no active regime) must be
    exactly the plain kernel path — same trajectory, no mesh required."""
    _, params, tx, batch, xla_step = mlm_parts
    fresh = lambda: TrainState.create(
        jax.tree.map(jnp.copy, params), tx, jax.random.key(2)
    )
    _, ref = _run(jax.jit(xla_step), fresh(), batch)

    model = build_mlm_sp()
    tx2, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    sp_step, _, _ = make_mlm_steps(model, sched)
    _, got = _run(jax.jit(sp_step), fresh(), batch)
    np.testing.assert_allclose(got, ref, atol=2e-5)


class TestSpGradientCanary:
    """The shard_seq setup-time probe that turns a silent shard_map
    transpose-convention change (a JAX-upgrade hazard _sp_bwd documents)
    into a loud startup failure."""

    def test_passes_on_healthy_mesh(self):
        import perceiver_io_tpu.parallel.sharding as sh
        from perceiver_io_tpu.parallel import make_mesh

        sh._SP_CANARY_OK.clear()  # force real probes despite earlier tests
        sh.sp_gradient_canary(make_mesh(dp=2, tp=1, sp=4))  # must not raise
        sh.sp_gradient_canary(make_mesh(dp=1, tp=1, sp=8))

    def test_detects_a_rescaled_backward(self, monkeypatch):
        """Simulate the failure mode the canary exists for: gradients off by
        an integer factor with the forward exact (what a changed check_rep
        transpose convention would produce)."""
        import perceiver_io_tpu.ops.pallas_attention as pa
        from perceiver_io_tpu.parallel import make_mesh
        from perceiver_io_tpu.parallel.sharding import sp_gradient_canary

        orig = pa.seq_parallel_fused_attention

        @jax.custom_vjp
        def rescaled(q, k, v):
            return orig(q, k, v, mesh=mesh, axis="seq")

        def fwd(q, k, v):
            out, vjp = jax.vjp(
                lambda q, k, v: orig(q, k, v, mesh=mesh, axis="seq"),
                q, k, v,
            )
            return out, vjp

        def bwd(vjp, g):
            dq, dk, dv = vjp(g)
            return 4.0 * dq, 4.0 * dk, 4.0 * dv  # the silent 4x rescale

        rescaled.defvjp(fwd, bwd)
        mesh = make_mesh(dp=2, tp=1, sp=4)
        monkeypatch.setattr(
            pa, "seq_parallel_fused_attention",
            lambda q, k, v, **kw: rescaled(q, k, v),
        )
        import perceiver_io_tpu.parallel.sharding as sh

        sh._SP_CANARY_OK.clear()  # the per-topology pass cache would skip us
        try:
            with pytest.raises(RuntimeError, match="canary FAILED"):
                sp_gradient_canary(mesh)
        finally:
            # a FAILED probe must not have been cached as ok, and later
            # tests should re-probe the healthy implementation themselves
            sh._SP_CANARY_OK.clear()
