"""Distributed request tracing core (obs/reqtrace + the r15 EventLog and
registry extensions): context propagation, dual-clock span records,
cross-process assembly with clock alignment, tail-based sampling, histogram
exemplars, and the trace buffer.

The fleet-level end-to-end (router → RPC → replica → engine, reconciliation
against the latency histograms, the chaos reroute span) lives in
``tests/test_fabric.py`` — this file pins the building blocks in isolation.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.obs.reqtrace import (
    SPAN_NAMES,
    TraceBuffer,
    TraceContext,
    assemble_traces,
    maybe_trace,
    record_span,
    tail_sample,
)


# -- TraceContext -------------------------------------------------------------


def test_trace_context_mint_child_and_header_roundtrip():
    root = TraceContext.mint()
    assert len(root.trace_id) == 16 and len(root.span_id) == 8
    assert root.parent_id is None and root.sampled

    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id

    # wire roundtrip: the receiver reconstructs the CALLER's context, and
    # its child() parents under the caller's span — the cross-process link
    headers = child.to_headers()
    assert set(headers) == {"X-Trace-Id", "X-Parent-Span", "X-Sampled"}
    remote = TraceContext.from_headers(headers)
    assert remote.trace_id == root.trace_id
    assert remote.span_id == child.span_id
    remote_child = remote.child()
    assert remote_child.parent_id == child.span_id

    # unsampled decisions survive the hop
    cold = TraceContext.mint(sampled=False)
    assert not TraceContext.from_headers(cold.to_headers()).sampled
    # untraced request: no headers -> no context
    assert TraceContext.from_headers({}) is None


def test_maybe_trace_requires_event_log_and_honors_sampling(tmp_path):
    obs.configure_event_log(None)
    assert maybe_trace() is None  # free when nothing would record
    obs.configure_event_log(str(tmp_path / "ev.jsonl"))
    try:
        assert maybe_trace(1.0) is not None
        assert maybe_trace(0.0) is None
        got = sum(maybe_trace(0.5) is not None for _ in range(400))
        assert 100 < got < 300  # the coin is real on both sides
    finally:
        obs.configure_event_log(None)


# -- EventLog dual stamps (the schema the assembler's alignment needs) --------


def test_event_log_dual_stamp_schema_roundtrip(tmp_path):
    """Every record carries wall (``t``), monotonic (``mono``), and ``pid``
    stamps — durations come from mono (PIT-CLOCK), alignment anchors mono
    onto wall, pid keys the per-process offset."""
    path = str(tmp_path / "events.jsonl")
    obs.configure_event_log(path)
    try:
        obs.event("first", k=1)
        obs.event("second", k=2)
        record_span("deploy_swap", None, time.monotonic(), 0.25, step=7)
    finally:
        obs.configure_event_log(None)
    rows = [json.loads(l) for l in open(path)]
    assert len(rows) == 3
    for r in rows:
        assert {"t", "mono", "pid"} <= set(r)
        assert r["pid"] == os.getpid()
        assert abs(r["t"] - time.time()) < 60  # wall epoch, not monotonic
    assert rows[0]["mono"] <= rows[1]["mono"] <= rows[2]["mono"]
    span = rows[2]
    assert span["event"] == "span" and span["name"] == "deploy_swap"
    assert span["trace"] is None and span["dur_s"] == 0.25
    assert span["step"] == 7
    # sampled-out contexts record nothing
    obs.configure_event_log(path)
    try:
        record_span("deploy_swap",
                    TraceContext.mint(sampled=False), 0.0, 0.1)
    finally:
        obs.configure_event_log(None)
    assert len(open(path).readlines()) == 3


# -- histogram exemplars ------------------------------------------------------


def test_histogram_exemplars_ride_snapshot_and_stay_bounded():
    reg = obs.MetricsRegistry()
    h = reg.histogram("lat_seconds", "x", {"engine": "e"})
    h.observe(0.5)  # exemplar-less observations stay exemplar-less
    assert h.exemplars() == []
    for i in range(20):
        h.observe(float(i), exemplar=f"trace{i}")
    ex = h.exemplars()
    assert len(ex) == 8  # bounded ring
    assert ex[0] == {"value": 19.0, "trace": "trace19"}  # slowest first
    snap = reg.snapshot()
    entry = snap["histograms"]['lat_seconds{engine="e"}']
    assert entry["exemplars"][0]["trace"] == "trace19"
    # a histogram with no exemplars doesn't grow the snapshot key
    reg.histogram("plain_seconds", "y").observe(1.0)
    assert "exemplars" not in reg.snapshot()["histograms"]["plain_seconds"]
    # the sticky slot: the slowest exemplar'd observation survives any
    # amount of faster traffic scrolling the recency ring
    h2 = reg.histogram("tail_seconds", "z")
    h2.observe(9.0, exemplar="the_slow_one")
    for i in range(100):
        h2.observe(0.001, exemplar=f"fast{i}")
    ex2 = h2.exemplars()
    assert len(ex2) == 9  # ring of 8 + the sticky slowest
    assert ex2[0] == {"value": 9.0, "trace": "the_slow_one"}


# -- TraceBuffer --------------------------------------------------------------


def test_trace_buffer_bounded_and_thread_safe():
    buf = TraceBuffer(capacity=8)
    threads = [
        threading.Thread(target=lambda b: [
            buf.add(f"t{b}_{i}", i / 100.0, ok=True) for i in range(50)
        ], args=(t,))
        for t in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(buf) == 8  # capacity, not 200
    slow = buf.slowest(3)
    assert len(slow) == 3
    assert slow[0]["total_s"] >= slow[1]["total_s"] >= slow[2]["total_s"]
    assert buf.recent(2) == buf.recent()[-2:]
    with pytest.raises(ValueError):
        TraceBuffer(capacity=0)


# -- assembly + clock alignment ----------------------------------------------


def _rec(pid, wall, mono, **fields):
    """A raw event record as a process's EventLog would write it."""
    return {"t": wall, "mono": mono, "pid": pid, **fields}


def _span_rec(pid, wall, mono, name, trace, span, parent, start, dur,
              **fields):
    return _rec(pid, wall, mono, event="span", name=name, trace=trace,
                span=span, parent=parent, mono_start=start, dur_s=dur,
                **fields)


def test_assemble_aligns_clocks_across_processes():
    """Two processes with WILDLY different monotonic bases (boot times): the
    wall anchors recover a consistent timeline — the replica's span lands
    inside the router's attempt window."""
    wall = 1_700_000_000.0
    # router process: mono base 1000; replica process: mono base 500000
    router_pid, replica_pid = 11, 22
    records = [
        # each process writes a few ordinary events (the alignment anchors)
        _rec(router_pid, wall + 0.0, 1000.0, event="x"),
        _rec(router_pid, wall + 1.0, 1001.0, event="x"),
        _rec(replica_pid, wall + 0.5, 500000.5, event="x"),
        _rec(replica_pid, wall + 1.5, 500001.5, event="x"),
        # the trace: root (router) -> attempt (router) -> serve (replica)
        _span_rec(router_pid, wall + 2.0, 1002.0, "router_request",
                  "T1", "R", None, start=1001.0, dur=1.0, ok=True),
        _span_rec(router_pid, wall + 1.9, 1001.9, "router_attempt",
                  "T1", "A", "R", start=1001.1, dur=0.8, replica="r0"),
        _span_rec(replica_pid, wall + 1.8, 500001.8, "replica_serve",
                  "T1", "S", "A", start=500001.2, dur=0.6),
    ]
    traces, context = assemble_traces(records)
    assert context == []
    t = traces["T1"]
    assert t["root"]["name"] == "router_request"
    assert t["processes"] == ["11", "22"]
    by_name = {s["name"]: s for s in t["spans"]}
    root, attempt, serve = (by_name["router_request"],
                            by_name["router_attempt"],
                            by_name["replica_serve"])
    assert attempt["span"] in root["children"]
    assert serve["span"] in attempt["children"]
    # the alignment claim: despite a ~499000s monotonic skew, the replica
    # span sits INSIDE the router attempt's absolute window
    assert (attempt["abs_start"] - 0.01 <= serve["abs_start"]
            <= attempt["abs_start"] + attempt["dur_s"])
    # exclusive self-times telescope back to the root duration
    assert t["total_s"] == 1.0
    assert abs(t["span_sum_s"] - 1.0) < 1e-6


def test_assemble_expands_request_phases_into_engine_child_spans():
    from perceiver_io_tpu.inference.engine import PHASES

    wall, pid = 1_700_000_000.0, 7
    phases = {"admission": 0.01, "queue": 0.02, "assembly": 0.005,
              "dispatch": 0.015, "device": 0.04, "complete": 0.01}
    records = [
        _rec(pid, wall, 100.0, event="request_phases", engine="e",
             bucket=2, rows=1, trace="T2", span="E", parent="S",
             mono_start=99.0, total_s=0.1, **phases),
        # an UNTRACED request_phases record must not assemble
        _rec(pid, wall, 101.0, event="request_phases", engine="e",
             bucket=2, rows=1, total_s=0.1, **phases),
    ]
    traces, _ = assemble_traces(records)
    assert list(traces) == ["T2"]
    spans = traces["T2"]["spans"]
    engine = next(s for s in spans if s["name"] == "engine")
    assert engine["dur_s"] == pytest.approx(sum(phases.values()))
    kids = [s for s in spans if s["parent"] == "E"]
    assert [s["name"] for s in kids] == [f"phase:{p}" for p in PHASES]
    # phase children tile the engine span contiguously
    t = engine["mono_start"]
    for s in kids:
        assert s["mono_start"] == pytest.approx(t, abs=1e-6)
        t += s["dur_s"]


def test_assemble_expands_batch_records_per_part():
    """The engine's compact spooled span record (";"-joined packed
    integer-µs rows — the serialization-amortized form full tracing
    actually emits) expands into one engine span + six phase children PER
    PART."""
    from perceiver_io_tpu.inference.engine import PHASES

    wall, pid = 1_700_000_000.0, 9
    part = lambda i: (f"T{i},S{i},P{i},{99_000_000 + i},1,"
                      f"100,200,50,150,400,100,4")
    records = [
        _rec(pid, wall, 100.0, event="request_phases_batch", engine="e",
             parts=";".join([part(0), part(1)])),
    ]
    traces, _ = assemble_traces(records)
    assert sorted(traces) == ["T0", "T1"]
    for i in (0, 1):
        spans = traces[f"T{i}"]["spans"]
        engine = next(s for s in spans if s["name"] == "engine")
        assert engine["span"] == f"S{i}" and engine["parent"] == f"P{i}"
        assert engine["dur_s"] == pytest.approx(1e-3)  # 1000 µs summed
        assert engine["mono_start"] == pytest.approx(99.0 + i * 1e-6)
        assert engine["bucket"] == 4 and engine["rows"] == 1
        kids = [s for s in spans if s["parent"] == f"S{i}"]
        assert [s["name"] for s in kids] == [f"phase:{p}" for p in PHASES]
        assert kids[4]["dur_s"] == pytest.approx(400e-6)  # device


def test_assemble_orphan_falls_back_to_earliest_span():
    """An engine-minted root (single-process serving) has no recorded parent
    span: the earliest orphan becomes the root instead of the trace being
    dropped."""
    records = [
        _span_rec(1, 100.0, 10.0, "replica_serve", "T3", "S", "GHOST",
                  start=9.0, dur=0.5),
    ]
    traces, _ = assemble_traces(records)
    assert traces["T3"]["root"]["name"] == "replica_serve"
    assert traces["T3"]["total_s"] == 0.5


def test_tail_sample_keeps_flags_and_slow_tail_deterministically():
    def trace(i, total, **flags):
        return {"trace": f"t{i:03d}", "total_s": total,
                "flags": {"error": False, "reroute": False, "spill": False,
                          **flags}}

    traces = {f"t{i:03d}": trace(i, 0.01 + i * 1e-4) for i in range(100)}
    traces["t000"]["flags"]["reroute"] = True  # fastest, but flagged
    traces["t001"]["flags"]["error"] = True

    kept = tail_sample(traces, slow_pct=0.95, sample=0.0)
    reasons = {k: v["kept_for"] for k, v in kept.items()}
    assert reasons["t000"] == "flag" and reasons["t001"] == "flag"
    slow = [k for k, r in reasons.items() if r == "slow"]
    assert len(slow) >= 5  # the top 5%
    assert all(k >= "t095" for k in slow), slow
    # sample=0 keeps nothing else; determinism across calls
    assert tail_sample(traces, slow_pct=0.95, sample=0.3, seed=1) \
        == tail_sample(traces, slow_pct=0.95, sample=0.3, seed=1)
    assert tail_sample({}) == {}


# -- the span-name registry ---------------------------------------------------


def test_span_names_registry_covers_recorded_sites():
    """Every name the runtime records is registered (the PIT-SPAN rule
    enforces the converse statically at every literal site)."""
    assert {"router_request", "router_attempt", "router_reroute",
            "router_affinity_spill", "replica_serve",
            "deploy_swap"} <= set(SPAN_NAMES)
