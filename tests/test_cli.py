"""End-to-end CLI tests on tiny synthetic configs (reference train/ entry
points, SURVEY.md §4d integration tier)."""

import json
import os

import numpy as np
import pytest

from perceiver_io_tpu.cli import train_img_clf, train_mlm, train_seq_clf
from perceiver_io_tpu.training import read_metrics

TINY_MODEL = [
    "--num_latents", "8", "--num_latent_channels", "16",
    "--num_encoder_layers", "2", "--num_self_attention_layers_per_block", "1",
    "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
    "--dtype", "float32",
]


def _common(tmp_path, name):
    return [
        "--synthetic", "--logdir", str(tmp_path / "logs" / name),
        "--root", str(tmp_path / "cache"),
    ]


@pytest.mark.slow  # tier-1 budget (r10): the image-classifier CLI e2e stays
# tier-1 via test_train_imagenet (imagefolder task); MNIST data/adapters in
# tests/test_data.py and tests/test_adapters.py
def test_train_img_clf(tmp_path):
    run_dir = train_img_clf.main(
        _common(tmp_path, "img") + TINY_MODEL + [
            "--synthetic_size", "128", "--batch_size", "16",
            "--max_epochs", "1", "--log_every_n_steps", "2",
        ]
    )
    rows = read_metrics(run_dir)
    assert any("train_loss" in r for r in rows)
    assert any("val_loss" in r for r in rows)
    assert os.path.isdir(os.path.join(run_dir, "checkpoints"))


@pytest.mark.slow  # tier-1 budget (r19): hybrid ICI×DCN coverage stays
# tier-1 in test_sharding.py (layout, validation, and
# test_hybrid_dcn_mesh_matches_single_device numeric parity) and in the
# 2-real-process granule check of test_multihost.py — this is the 20s
# end-to-end CLI variant
def test_train_mlm_hybrid_dcn_mesh(tmp_path):
    """--dcn_dp 2 --tp 2 trains end to end on the 8-device CPU mesh (the
    hybrid ICI×DCN layout is placement-only — the run must behave exactly
    like the flat mesh)."""
    run_dir = train_mlm.main(
        _common(tmp_path, "mlmdcn") + TINY_MODEL + [
            "--synthetic_size", "64", "--batch_size", "16",
            "--max_seq_len", "32", "--vocab_size", "90",
            "--max_steps", "3", "--log_every_n_steps", "1",
            "--tp", "2", "--dcn_dp", "2",
        ]
    )
    rows = read_metrics(run_dir)
    losses = [r["train_loss"] for r in rows if "train_loss" in r]
    assert losses and np.isfinite(losses).all()


@pytest.mark.slow  # tier-1 budget (r10): fused-head numerics stay tier-1 in
# tests/test_train_steps.py::test_mlm_step_fused_head_matches_unfused; flag
# parsing in test_all_parsers_build_and_render_help
def test_train_mlm_fused_head_flag(tmp_path):
    """--fused_head pallas trains end to end (interpret mode off-TPU) and
    --fused_head pallas under --tp vocab sharding is rejected with the
    single-device-head explanation."""
    args = _common(tmp_path, "mlmfh") + TINY_MODEL + [
        "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", "32", "--vocab_size", "90",
        "--max_steps", "2", "--log_every_n_steps", "1",
        "--fused_head", "pallas",
    ]
    run_dir = train_mlm.main(args)
    rows = read_metrics(run_dir)
    assert any("train_loss" in r for r in rows)

    with pytest.raises(SystemExit, match="single-device head"):
        train_mlm.main(args + ["--tp", "2"])


@pytest.mark.slow  # encoder-transfer restore semantics stay tier-1 in
# tests/test_checkpoint.py::test_encoder_transfer; this is the CLI ride
def test_train_mlm_then_transfer(tmp_path):
    mlm_args = _common(tmp_path, "mlm") + TINY_MODEL + [
        "--synthetic_size", "96", "--batch_size", "16",
        "--max_seq_len", "64", "--vocab_size", "150",
        "--max_steps", "4", "--log_every_n_steps", "2",
        "--num_predictions", "3",
    ]
    run_dir = train_mlm.main(mlm_args)
    rows = read_metrics(run_dir)
    assert any("train_loss" in r for r in rows)
    # masked-sample predictions were logged as text
    assert any(r.get("tag") == "predictions" for r in rows)
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    with open(os.path.join(ckpt_dir, "hparams.json")) as f:
        hparams = json.load(f)
    assert hparams["num_latents"] == 8

    # transfer: bigger model args on the CLI must be overridden by the
    # checkpoint's hparams so the restored encoder fits
    clf_run = train_seq_clf.main(
        _common(tmp_path, "clf") + [
            "--num_latents", "32",  # overridden from hparams
            "--dtype", "float32",
            "--synthetic_size", "96", "--batch_size", "16",
            "--max_seq_len", "64", "--vocab_size", "150",
            "--max_steps", "3", "--log_every_n_steps", "1",
            "--mlm_checkpoint", ckpt_dir, "--freeze_encoder",
        ]
    )
    rows = read_metrics(clf_run)
    assert any("val_acc" in r for r in rows)

    # resume path
    resumed = train_seq_clf.main(
        _common(tmp_path, "clf") + [
            "--dtype", "float32",
            "--synthetic_size", "96", "--batch_size", "16",
            "--max_seq_len", "64", "--vocab_size", "150",
            "--max_steps", "5", "--log_every_n_steps", "1",
            "--clf_checkpoint", os.path.join(clf_run, "checkpoints"),
        ]
    )
    rows = read_metrics(resumed)
    # resumed at step 3, trained to 5
    assert max(r["step"] for r in rows) == 5


@pytest.mark.slow  # tier-1 budget (r21): the serve CLI pipeline stays
# tier-1 via test_serve_metrics_sidecar_end_to_end (same train+serve path
# plus the sidecar); engine fused==cached parity stays in
# tests/test_engine.py::test_mlm_server_latent_cache_decode_many
def test_serve_cli_end_to_end(tmp_path):
    """Train a tiny MLM, then serve it through the micro-batching engine CLI:
    fused, latent-cache, and bf16 paths all answer, fused == cached, and the
    JSON-line results carry per-[MASK] top-k token lists."""
    import glob

    from perceiver_io_tpu.cli import serve

    run_dir = train_mlm.main(
        _common(tmp_path, "servemlm") + [
            "--num_latents", "4", "--num_latent_channels", "16",
            "--num_encoder_layers", "1",
            "--num_self_attention_layers_per_block", "1",
            "--num_cross_attention_heads", "2",
            "--num_self_attention_heads", "2", "--dtype", "float32",
            "--synthetic_size", "64", "--batch_size", "16",
            "--max_seq_len", "32", "--vocab_size", "120",
            "--max_steps", "2", "--log_every_n_steps", "1",
            "--num_predictions", "2",
        ]
    )
    ckpt = os.path.join(run_dir, "checkpoints")
    tok = glob.glob(str(tmp_path / "cache" / "*tokenizer*.json"))[0]
    base = ["--checkpoint", ckpt, "--tokenizer", tok, "--max_batch", "4",
            "--k", "3"]

    # the resilience AND SLO flags ride the happy path too: generous
    # deadline/queue bound, an armed breaker, and a declared SLO must not
    # perturb results
    fused = serve.main(
        base + ["--bucket_widths", "16",
                "--request_deadline_s", "60", "--queue_limit", "256",
                "--breaker_failures", "3", "--breaker_cooldown_s", "1",
                "--slo_p99_ms", "60000", "--slo_availability", "0.99",
                "--texts", "a [MASK] b", "no mask here"]
    )
    assert len(fused) == 2
    assert len(fused[0]["fills"]) == 1 and len(fused[0]["fills"][0]) == 3
    assert fused[1]["fills"] == []

    cached = serve.main(
        base + ["--cached", "--no_warmup", "--texts", "a [MASK] b"]
    )
    assert cached[0]["fills"] == fused[0]["fills"]

    bf16 = serve.main(
        base + ["--dtype", "bfloat16", "--no_warmup",
                "--texts", "a [MASK] b"]
    )
    assert len(bf16[0]["fills"][0]) == 3  # bf16 rounds: presence, not parity

    # weight-only int8 at f32 compute: on this tiny model the top-k picks
    # match the f32 path (quantization error ≪ the logit gaps)
    int8w = serve.main(
        base + ["--quantize", "int8", "--no_warmup",
                "--texts", "a [MASK] b"]
    )
    assert int8w[0]["fills"] == fused[0]["fills"]

    # zero-recompile cold start: --compile_cache serves identical fills and
    # persists the on-demand programs as .pitx entries (the zero-compile
    # warm-family assertion lives in test_engine.py / test_aot_cache.py;
    # --no_warmup keeps this run inside the tier-1 budget)
    cache_dir = tmp_path / "ccache"
    cached_serve = serve.main(
        base + ["--compile_cache", str(cache_dir), "--no_warmup",
                "--texts", "a [MASK] b"]
    )
    assert cached_serve[0]["fills"] == fused[0]["fills"]
    assert any(f.endswith(".pitx") for f in os.listdir(cache_dir))

    # fail-soft (satellite): a cache path that cannot exist (nested under a
    # regular file) must WARN and serve uncached — never refuse traffic
    blocker = tmp_path / "a_file"
    blocker.write_text("x")
    with pytest.warns(UserWarning, match="unusable"):
        soft = serve.main(
            base + ["--compile_cache", str(blocker / "cache"), "--no_warmup",
                    "--texts", "a [MASK] b"]
        )
    assert soft[0]["fills"] == fused[0]["fills"]

    with pytest.raises(SystemExit, match="nothing to serve"):
        serve.main(base)


@pytest.mark.slow  # tier-1 budget (r21): the one-JSON-line bench-CLI
# contract stays tier-1 via test_coldstart_bench_cpu_emits_one_json_line
# and the load_bench --dry/--cpu contract tests; the engine A/B itself is
# a tools-only path with no serving-side coverage gap
def test_inference_bench_engine_cpu_emits_one_json_line(tmp_path):
    """tools/inference_bench.py --engine --cpu runs the full serving-engine
    A/B offline and emits EXACTLY one JSON line on stdout (the driver's
    inference-trajectory contract)."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "inference_bench.py"),
         "--engine", "--cpu", "--preset", "tiny",
         "--requests", "8", "--rounds", "1"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["mode"] == "engine" and result["backend"] == "cpu"
    for key in ("naive_requests_per_s", "engine_requests_per_s", "speedup",
                "engine_tokens_per_s"):
        assert key in result, result
    assert any(k.startswith("bucket") and k.endswith("p50_ms")
               for k in result), result


@pytest.mark.slow  # tier-1 budget (r22 box drift): the serve CLI
# contract stays tier-1 in test_serve_cli_end_to_end; the metrics
# registry/exporters are unit-covered in tests/test_obs.py. This drill
# adds only the sidecar-process layer.
def test_serve_metrics_sidecar_end_to_end(tmp_path):
    """The observability acceptance drill: a live serve.py process with
    --metrics_port answers /metrics with valid Prometheus text carrying
    nonzero engine counters after one request, /healthz 200, /statz JSON —
    while stdout stays exactly one JSON line per text."""
    import glob
    import re
    import subprocess
    import sys
    import time
    import urllib.request

    run_dir = train_mlm.main(
        _common(tmp_path, "obsmlm") + [
            "--num_latents", "4", "--num_latent_channels", "16",
            "--num_encoder_layers", "1",
            "--num_self_attention_layers_per_block", "1",
            "--num_cross_attention_heads", "2",
            "--num_self_attention_heads", "2", "--dtype", "float32",
            "--synthetic_size", "64", "--batch_size", "16",
            "--max_seq_len", "32", "--vocab_size", "120",
            "--max_steps", "2", "--log_every_n_steps", "1",
            "--num_predictions", "2",
        ]
    )
    ckpt = os.path.join(run_dir, "checkpoints")
    tok = glob.glob(str(tmp_path / "cache" / "*tokenizer*.json"))[0]
    events = str(tmp_path / "events.jsonl")
    series = str(tmp_path / "series.jsonl")
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps([
        # never fires (healthz must stay ok); its state gauge still exports
        {"name": "queue_hot", "metric": "serving_queue_depth",
         "threshold": 1e6, "window_s": 60, "severity": "page"},
    ]))

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "perceiver_io_tpu.cli.serve", "--cpu",
         "--checkpoint", ckpt, "--tokenizer", tok, "--stdin",
         "--max_batch", "4", "--bucket_widths", "16", "--no_warmup",
         "--metrics_port", "0", "--heartbeat_deadline_s", "60",
         "--events_jsonl", events, "--k", "2",
         "--series_interval_s", "0.1", "--series_jsonl", series,
         "--alert_rules", str(rules)],
        cwd=root, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        # the sidecar address is printed to stderr before the model loads
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stderr.readline()
            m = re.search(r"metrics on http://127\.0\.0\.1:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
            assert line or proc.poll() is None, proc.poll()
        assert port, "serve never announced its metrics port"
        base = f"http://127.0.0.1:{port}"

        proc.stdin.write("a [MASK] b\n")
        proc.stdin.flush()

        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.status, r.read().decode()

        # poll until the request flowed through the engine (batches counts
        # at dispatch, after the submit-side requests counter)
        text = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            _, text = get("/metrics")
            m = re.search(
                r'^serving_batches_total\{engine="mlm"\} (\d+)$',
                text, re.M)
            if m and int(m.group(1)) >= 1:
                break
            time.sleep(0.25)
        else:
            raise AssertionError(f"no nonzero engine counters:\n{text}")
        assert "# TYPE serving_requests_total counter" in text
        assert re.search(
            r'^serving_requests_total\{engine="mlm"\} [1-9]', text, re.M)
        assert re.search(
            r'^serving_rows_total\{engine="mlm"\} [1-9]', text, re.M)

        code, body = get("/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, body = get("/statz")
        statz = json.loads(body)
        assert code == 200
        assert statz["counters"]['serving_requests_total{engine="mlm"}'] >= 1
        assert statz["health"]["status"] == "ok"
        # the never-firing page rule still exports its state gauge, and the
        # alerting healthz source reports it without degrading the probe
        assert statz["gauges"]['alert_state{rule="queue_hot"}'] == 0.0
        assert statz["health"]["sources"]["alerts:serve"]["paging"] == []
        # /seriesz serves the sampled history live: the engine's request
        # counter has accumulated windowed samples by now
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            code, body = get("/seriesz")
            entry = json.loads(body)["series"].get(
                'serving_requests_total{engine="mlm"}')
            if entry and entry["n"] >= 2 and entry["last"] >= 1:
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"/seriesz never showed the history: {body}")

        # communicate() flushes and closes stdin → serve drains and exits
        out, err = proc.communicate(timeout=120)
        assert proc.returncode == 0, err[-2000:]
        lines = [l for l in out.splitlines() if l.strip()]
        assert len(lines) == 1, out  # one JSON line per text, nothing else
        row = json.loads(lines[0])
        assert row["text"] == "a [MASK] b"
        assert len(row["fills"]) == 1 and len(row["fills"][0]) == 2
        # the event log captured the compile events (all off-stdout)
        rows = [json.loads(l) for l in open(events)]
        assert any(r.get("event") == "serving_compile" for r in rows)
        # the series JSONL drained on close: every persisted sweep parses
        # and carries the sampled engine counter
        srows = [json.loads(l) for l in open(series)]
        assert len(srows) >= 2
        assert all(r["event"] == "series_sample" for r in srows)
        assert srows[-1]["series"][
            'serving_requests_total{engine="mlm"}'] >= 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def test_json_emitters_keep_one_line_stdout_contract(tmp_path):
    """CI guard (satellite): the tools/ JSON emitters must keep exactly one
    JSON line on stdout with the telemetry subsystem wired in — all logs ride
    stderr. kernel_smoke --dry covers the report shape without touching any
    device; inference_bench --engine --cpu has its own full test above."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "kernel_smoke.py"),
         "--dry", "--out", str(tmp_path / "ks.json")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    report = json.loads(lines[0])
    assert report["metric"] == "kernel_smoke" and report["dry"] is True
    assert report["total"] > 0 and report["skipped"]
    # the weight-only int8 path is registered in the per-round smoke
    assert "quant-int8w-dequant" in report["skipped"]
    # the generative causal decode geometries are registered too (the
    # in-kernel causal flag at guard boundaries + the q_len=1 step shape)
    assert "attn-causal-prefill-d128" in report["skipped"]
    assert "attn-q1-decode-32k" in report["skipped"]
    # the continuous-batching arena shapes: batched q1 step + batched
    # causal prefill (batch = arena slots) at VMEM-guard boundaries
    assert "attn-arena8-q1-32k" in report["skipped"]
    assert "attn-arena16-prefill-d64" in report["skipped"]
    # the fused dequant-matmul kernel geometries (r24): flagship vocab
    # head, grouped int4, and the all-axes-unaligned pad/slice path
    assert "qmm-int8-vocab-head" in report["skipped"]
    assert "qmm-int4-grouped-mlp" in report["skipped"]
    assert "qmm-int8-awkward-f32" in report["skipped"]
    with open(tmp_path / "ks.json") as f:
        assert json.loads(f.read()) == report


@pytest.mark.slow  # tier-1 budget (r10): the int8w parity bounds stay
# tier-1 in tests/test_quant.py (engine parity vs the f32 oracle) and the
# serve --quantize int8 e2e; the one-JSON-line stdout contract shape is
# asserted tier-1 by the inference_bench/coldstart_bench contract tests
def test_quant_bench_cpu_emits_one_json_line(tmp_path):
    """tools/quant_bench.py --cpu runs the interleaved bf16-vs-int8w engine
    A/B offline and emits EXACTLY one JSON line on stdout (the driver's
    quant-trajectory contract): throughput both arms, parity error vs the
    f32 oracle within the documented tiny-preset bound, and the predicted
    bytes-streamed accounting."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "quant_bench.py"),
         "--cpu", "--preset", "tiny", "--requests", "8", "--rounds", "1"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["mode"] == "quant" and result["backend"] == "cpu"
    for key in ("bf16_requests_per_s", "int8w_requests_per_s",
                "int4w_requests_per_s", "speedup_int8w_vs_bf16",
                "speedup_int4w_vs_bf16", "parity_bf16_rel_err",
                "parity_int8w_rel_err", "parity_int4w_rel_err",
                "param_bytes_int8w", "param_bytes_int4w",
                "predicted_weight_stream_ratio",
                "predicted_weight_stream_ratio_int4w",
                "qmm_pallas_ms", "qmm_xla_ms", "qmm_kernel_rel_err",
                "speedup_qmm_pallas_vs_xla"):
        assert key in result, result
    # the documented tiny-preset parity bounds (PERF.md §Quantization)
    assert result["parity_int8w_rel_err"] <= 0.05, result
    assert result["parity_int4w_rel_err"] <= 0.35, result
    # the kernel A/B consumes identical quantized operands — any gap is
    # purely kernel-vs-XLA, and in bf16 compute it measures exactly 0
    assert result["qmm_kernel_rel_err"] <= 2e-5, result
    assert 0 < result["predicted_weight_stream_ratio"] < 1, result
    assert (result["predicted_weight_stream_ratio_int4w"]
            < result["predicted_weight_stream_ratio"]), result


def test_quant_bench_dry_declares_record_keys(tmp_path):
    """tools/quant_bench.py --dry: one JSON line declaring the record's key
    contract without touching any device — what bench_compare and the
    driver key their floor classes on (tier-1: no model build, <5 s)."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "quant_bench.py"),
         "--dry"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["mode"] == "quant" and result["dry"] is True
    keys = set(result["keys"])
    for key in ("bf16_requests_per_s", "int8w_requests_per_s",
                "int4w_requests_per_s", "parity_int4w_rel_err",
                "param_bytes_int4w", "qmm_pallas_ms",
                "speedup_qmm_pallas_vs_xla"):
        assert key in keys, result
    assert "achieved_hbm_ratio_int8w_vs_bf16" in result["tpu_only_keys"]


@pytest.mark.slow  # tier-1 budget (r19): the executable-cache tier keeps
# its full tier-1 suite (test_aot_cache.py: warm-start bit-identity,
# corruption fallback, fail-soft open); this 20s subprocess variant covers
# the jax persistent-cache tier behind --compile_cache, whose enable path
# is fail-soft config plumbing
def test_train_cli_compile_cache_persists_step_compiles(tmp_path):
    """--compile_cache on a train CLI (tier 2: jax's persistent compilation
    cache) populates the directory with the step's compiled entries and the
    run stays green. Subprocess on purpose: the recorded negative result
    (PERF.md §Cold start) forbids flipping the process-global cache config
    inside the tier-1 process, where later tests serialize AOT executables."""
    import subprocess
    import sys

    cache = tmp_path / "tcache"
    proc = subprocess.run(
        [sys.executable, "-m", "perceiver_io_tpu.cli.train_mlm",
         "--synthetic", "--synthetic_size", "32", "--batch_size", "16",
         "--max_seq_len", "32", "--vocab_size", "90",
         "--num_latents", "4", "--num_latent_channels", "16",
         "--num_encoder_layers", "1",
         "--num_self_attention_layers_per_block", "1",
         "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
         "--dtype", "float32", "--max_steps", "1", "--log_every_n_steps", "1",
         "--logdir", str(tmp_path / "logs"), "--root", str(tmp_path / "cache"),
         "--compile_cache", str(cache)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"persistent compilation cache: {cache}" in proc.stderr
    assert any(n.endswith("-cache") for n in os.listdir(cache)), (
        "no compiled entries persisted")


@pytest.mark.slow  # tier-1 budget (r22 box drift): compile-cache
# mechanics stay tier-1 in tests/test_aot_cache.py; the cache
# subprocess drill was slow-marked in r20. This is the bench CLI shell.
def test_coldstart_bench_cpu_emits_one_json_line(tmp_path):
    """tools/coldstart_bench.py --cpu runs the same-process cold-vs-warm
    warmup A/B over the AOT executable cache and emits EXACTLY one JSON line
    on stdout. The acceptance bars ride the record: the warm pass performs
    ZERO XLA compiles and is >= 5x faster than the cold pass, and the
    background arm answers its first request before the family is warm."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "coldstart_bench.py"),
         "--cpu", "--max_batch", "4", "--widths", "32",
         "--cache_dir", str(tmp_path / "cache")],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["metric"] == "coldstart_warmup_speedup"
    assert result["backend"] == "cpu"
    assert result["compiles_warm"] == 0, result
    assert result["compiles_cold"] == result["programs"] > 0, result
    assert result["speedup"] >= 5, result
    assert result["bg_first_result_s"] <= result["bg_family_warm_s"], result


def test_load_bench_dry_emits_schema_json_line():
    """tools/load_bench.py --dry emits EXACTLY one JSON line describing the
    record shape (point + phase keys) without touching any backend."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "load_bench.py"),
         "--dry"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["metric"] == "load_bench" and record["dry"] is True
    assert record["sweep"] == [] and record["capacity"] is None
    for key in ("offered_rps", "achieved_rps", "p99_ms", "shed_rate",
                "phase_p50_ms", "breaker"):
        assert key in record["point_keys"], record
    assert record["phase_keys"] == [
        "admission", "queue", "assembly", "dispatch", "device", "complete"]
    # the continuous-deployment ride-along (--publish_every_s) declares its
    # block's keys; the block itself is null when the ride-along is off
    assert record["deploy"] is None
    for key in ("publishes", "swaps", "rejects", "rollbacks",
                "p99_steady_ms", "p99_swap_ms", "per_swap_p99_ms"):
        assert key in record["deploy_keys"], record
    # the elastic-autoscaling (--schedule/--autoscale) and admission
    # (--noisy_neighbor) blocks declare their keys the same way
    assert record["autoscale"] is None and record["admission"] is None
    assert record["schedule"] is None
    for key in ("schedule", "peak_replicas", "scale_ups", "scale_downs",
                "spawn_failures", "replica_seconds",
                "static_replica_seconds", "replica_seconds_saved_pct",
                "p99_within_slo", "lost_accepted"):
        assert key in record["autoscale_keys"], record
    for key in ("classes", "abuser_quota_rps", "victim_p99_delta_pct",
                "abuser_shed_drill", "victim_p99_unprotected_ms",
                "sheds_by_reason", "null"):
        assert key in record["admission_keys"], record
    # the generative traffic class (--generate_rps) declares its block's
    # keys the same way — the second, stateful class the r17 policies see
    assert record["generate"] is None
    for key in ("offered_streams", "completed", "failed", "tokens_total",
                "steps_per_s", "stream_p99_ms", "followups", "resumed",
                "reroutes", "spills", "stream"):
        assert key in record["generate_keys"], record
    # the token-level streaming sub-block (r21) declares its keys: caller-
    # clock TTFT/ITL, engine-side goodput, flight-recorder idle attribution
    for key in ("ttft_p50_ms", "ttft_p95_ms", "itl_p50_ms", "itl_p95_ms",
                "streams_timed", "tokens_generated", "tokens_delivered",
                "tokens_wasted", "goodput", "idle_slot_rounds",
                "idle_attributed", "idle_attribution_frac", "idle_causes"):
        assert key in record["stream_keys"], record
    # the generate-class trace A/B rides the trace block
    assert "generate_ab" in record["trace_keys"], record


@pytest.mark.slow  # tier-1 budget (r22 box drift): the load_bench
# record schema stays tier-1 in test_load_bench_dry_fleet_schema and
# the full --cpu contract run is the r21 slow-marked drill; the
# saturation/SLO logic is unit-covered in tests/test_obs.py (slo).
def test_load_bench_cpu_sweep_shows_saturation_signature(tmp_path):
    """The SLO-observability acceptance drill: tools/load_bench.py --cpu
    emits ONE JSON line whose open-loop sweep shows the saturation
    signature — achieved throughput plateaus below the top offered rate,
    p99 inflects away from its floor, shed rate becomes nonzero past the
    knee — plus a fitted capacity estimate and per-phase attribution whose
    sum reconciles with the end-to-end latency."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "load_bench.py"),
         "--cpu", "--duration_s", "1.5", "--calibration_waves", "2",
         "--calibration_wave_size", "16",
         "--rate_factors", "0.3,0.8,1.5,3.0"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["metric"] == "load_bench" and record["backend"] == "cpu"
    assert record["preset"] == "tiny" and record["dry"] is False
    sweep = record["sweep"]
    assert len(sweep) == 4

    # the saturation signature: shedding appears past the knee ...
    assert sweep[-1]["shed_rate"] > 0, sweep
    # ... p99 inflects away from its light-load floor ...
    p99s = [p["p99_ms"] for p in sweep]
    assert max(p99s) > 1.5 * min(p99s), p99s
    # ... and achieved throughput plateaus below the top offered rate
    assert sweep[-1]["achieved_rps"] < 0.9 * sweep[-1]["offered_rps"], sweep
    # saturation is QUEUEING, attributed: the queue phase grows from the
    # first point to the last far more than the device phase does
    q_growth = (sweep[-1]["phase_p50_ms"]["queue"]
                - sweep[0]["phase_p50_ms"]["queue"])
    d_growth = (sweep[-1]["phase_p50_ms"]["device"]
                - sweep[0]["phase_p50_ms"]["device"])
    assert q_growth > d_growth, (q_growth, d_growth)

    # the fitted capacity model rides the record
    cap = record["capacity"]
    assert cap["capacity_rps"] > 0
    assert cap["service_floor_ms"] > 0
    assert "knee_rps" in cap and "slo_sustainable_rps" in cap
    assert cap["slo"]["availability_target"] == 0.999

    # per-phase attribution present on every point, and the phase sum
    # self-check reconciles with end-to-end latency
    for point in sweep:
        assert set(point["phase_p50_ms"]) == {
            "admission", "queue", "assembly", "dispatch", "device",
            "complete"}
    assert 0.9 <= record["phase_sum_ratio"] <= 1.1, record["phase_sum_ratio"]


@pytest.mark.slow  # tier-1 budget (r21): the TTFT/ITL/goodput/attribution
# semantics this run exercises stay tier-1 at the engine level in
# tests/test_stream_obs.py (reconciliation + flight kill drill) and the
# schema contract stays tier-1 in test_load_bench_dry_emits_schema_json_line;
# this is the full-stack subprocess run (router -> batched replica ->
# flight recorder -> record assembly), ~65 s of warmup-dominated wall
def test_load_bench_cpu_generate_stream_block_populates_finite():
    """A --generate_rps --decode_batching --trace_ab run populates every
    stream key with a FINITE value: caller-clock TTFT/ITL percentiles,
    engine-side goodput accounting, the flight recorder's idle attribution
    (>= 0.95 — the acceptance bar), and the generate-class traced-vs-
    untraced A/B block."""
    import math
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "load_bench.py"),
         "--cpu", "--duration_s", "1.5", "--calibration_waves", "1",
         "--calibration_wave_size", "8", "--rate_factors", "0.8",
         "--replicas", "1", "--generate_rps", "8", "--decode_batching",
         "--trace_ab", "--trace_ab_waves", "2"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    stream = record["generate"]["stream"]
    for key in ("ttft_p50_ms", "ttft_p95_ms", "itl_p50_ms", "itl_p95_ms"):
        assert isinstance(stream[key], float) and stream[key] > 0, stream
        assert math.isfinite(stream[key]), stream
    assert stream["ttft_p95_ms"] >= stream["ttft_p50_ms"]
    assert stream["streams_timed"] > 0
    # goodput ledger: generated >= delivered, wasted accounts the gap
    assert stream["tokens_generated"] >= stream["tokens_delivered"] > 0
    assert stream["tokens_wasted"] == (stream["tokens_generated"]
                                       - stream["tokens_delivered"])
    assert 0.0 < stream["goodput"] <= 1.0
    # the flight recorder attributed the idleness (acceptance: >= 95%)
    assert stream["idle_slot_rounds"] >= 0
    assert stream["idle_attribution_frac"] >= 0.95, stream
    assert set(stream["idle_causes"]) == {
        "no_pending", "width_mismatch", "arena_full", "draining"}
    assert (sum(stream["idle_causes"].values())
            == stream["idle_attributed"])
    # the generate-class A/B populated alongside the request-class one
    gen_ab = record["trace"]["generate_ab"]
    assert gen_ab["untraced_tokens_per_s"] > 0
    assert gen_ab["traced_tokens_per_s"] > 0
    assert gen_ab["decode_events_recorded"] > 0
    assert math.isfinite(gen_ab["overhead_pct"])
    # the built-in null control: same paired waves, log hooked in NEITHER
    # arm — readers judge overhead_pct against this floor, not against 0
    assert math.isfinite(gen_ab["null_overhead_pct"])


def test_decode_flight_dry_emits_schema_json_line():
    """tools/decode_flight.py --dry emits EXACTLY one JSON line declaring
    the attribution-record keys without touching any backend."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "decode_flight.py"),
         "--dry"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["metric"] == "decode_flight" and record["dry"] is True
    for key in ("rounds", "slot_rounds", "idle_slot_rounds", "attributed",
                "attribution_frac", "causes", "evicts", "grows",
                "pending_max", "dumps", "dump_reasons", "drill"):
        assert key in record["record_keys"], record


def test_deploy_bench_dry_emits_schema_json_line():
    """tools/deploy_bench.py --dry emits EXACTLY one JSON line declaring the
    record + per-swap keys without touching any backend."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "deploy_bench.py"),
         "--dry"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["metric"] == "deploy_bench" and record["dry"] is True
    for key in ("swaps", "rejects", "rollbacks", "lost_accepted",
                "swap_cadence_s", "p99_steady_ms", "p99_swap_ms",
                "blip_ratio", "per_swap"):
        assert key in record["record_keys"], record
    assert record["per_swap_keys"] == [
        "step", "action", "gate_ms", "swap_ms", "p99_ms", "n_window"]


@pytest.mark.slow  # tier-1 budget (r21): gated-rollout + zero-lost-
# accepted semantics stay tier-1 in tests/test_deploy.py::
# test_fleet_deploy_chaos_e2e (real fleet, chaos injection); this is the
# bench-CLI wrapper over the same loop
def test_deploy_bench_cpu_gated_swaps_zero_loss(tmp_path):
    """The deployment-loop acceptance contract: tools/deploy_bench.py --cpu
    pushes N publications through gate + hot-swap under open-loop traffic
    and emits ONE JSON line with every swap completed, ZERO lost accepted
    requests, and the per-swap latency attribution populated."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "deploy_bench.py"),
         "--cpu", "--swaps", "3", "--publish_every_s", "0.5",
         "--calibration_waves", "1", "--rate_factor", "0.3"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["metric"] == "deploy_bench" and record["backend"] == "cpu"
    assert record["preset"] == "tiny" and record["mode"] == "engine"
    # every publication passed the gate and swapped; none were lost to it
    assert record["publishes"] == record["swaps"] == 3, record
    assert record["rejects"] == 0 and record["rollbacks"] == 0, record
    assert record["lost_accepted"] == 0 and record["failed"] == 0, record
    assert record["completed"] > 0 and record["shed"] == 0, record
    # attribution populated: a steady p99 plus a window around every swap
    assert record["p99_steady_ms"] is not None, record
    assert len(record["per_swap"]) == 3, record
    for s in record["per_swap"]:
        assert s["action"] == "swapped" and s["swap_ms"] > 0, s
        assert s["n_window"] > 0, s


def test_bench_backend_probe_emits_json_error_record():
    """BENCH_r05 regression: with the backend probe unable to answer inside
    its deadline (deadline 0 simulates the dark-tunnel hang), bench.py must
    emit ONE JSON error record on stdout — not a raw traceback — and exit
    nonzero."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        env={**os.environ, "PIT_BENCH_CPU": "1",
             "PIT_BENCH_BACKEND_DEADLINE_S": "0"},
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode != 0
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["error"] == "tpu_unavailable"
    assert record["value"] is None
    assert "reason" in record


def test_encode_masked_samples(tmp_path):
    from perceiver_io_tpu.data.imdb import IMDBDataModule

    data = IMDBDataModule(
        root=str(tmp_path / "cache"), max_seq_len=16, vocab_size=120,
        synthetic=True, synthetic_size=64,
    )
    data.prepare_data()
    data.setup()
    mask_id = data.tokenizer.token_to_id("[MASK]")
    ids, pad = train_mlm.encode_masked_samples(
        data.collator, ["movie was [MASK] and [MASK] acting"]
    )
    assert ids.shape == (1, 16)
    assert (ids[0] == mask_id).sum() == 2
    assert pad.dtype == bool


@pytest.mark.slow  # tier-1 budget (r22 box drift): the shared train
# loop/CLI machinery stays tier-1 via the train_mlm variants above;
# the image model forward/adapters are unit-covered in test_model.py.
def test_train_imagenet(tmp_path):
    from perceiver_io_tpu.cli import train_imagenet

    run_dir = train_imagenet.main(
        _common(tmp_path, "imagenet") + TINY_MODEL + [
            "--synthetic_size", "64", "--synthetic_classes", "4",
            "--image_size", "16", "--batch_size", "8", "--num_workers", "2",
            "--num_frequency_bands", "4",
            "--max_epochs", "1", "--log_every_n_steps", "2",
        ]
    )
    rows = read_metrics(run_dir)
    assert any("train_loss" in r for r in rows)
    assert any("val_loss" in r for r in rows)
    assert os.path.isdir(os.path.join(run_dir, "checkpoints"))


@pytest.mark.slow  # tier-1 budget (r11): multimodal adapter/model/loss
# numerics stay tier-1 in tests/test_multimodal.py (incl. the
# make_multimodal_steps train step), the sharded end-to-end in
# tests/test_sharding.py::test_multimodal_autoencoder_sharded, flag parsing
# in test_all_parsers_build_and_render_help, and the Trainer-CLI plumbing
# via the train_mlm e2es in this file
def test_train_multimodal(tmp_path):
    from perceiver_io_tpu.cli import train_multimodal

    run_dir = train_multimodal.main(
        _common(tmp_path, "multimodal") + TINY_MODEL + [
            "--synthetic_size", "32", "--batch_size", "8",
            "--video_frames", "2", "--video_size", "8", "--video_channels", "1",
            "--video_patch", "1", "4", "4",
            "--audio_samples", "64", "--samples_per_patch", "8",
            "--num_classes", "3", "--num_modality_channels", "4",
            "--video_frequency_bands", "2", "--audio_frequency_bands", "2",
            "--max_epochs", "1", "--log_every_n_steps", "1",
        ]
    )
    rows = read_metrics(run_dir)
    assert any("train_loss" in r for r in rows)
    assert any("val_loss" in r for r in rows)
    assert any("val_acc" in r for r in rows)
    assert os.path.isdir(os.path.join(run_dir, "checkpoints"))


@pytest.mark.slow  # tier-1 budget (r10): near-duplicate of the flow CLI e2e
# in tests/test_flow_data.py::test_train_flow_cli (tier-1)
def test_train_flow(tmp_path):
    from perceiver_io_tpu.cli import train_flow

    run_dir = train_flow.main(
        _common(tmp_path, "flow") + TINY_MODEL + [
            "--synthetic_size", "32", "--batch_size", "8",
            "--image_height", "12", "--image_width", "16",
            "--num_frequency_bands", "4",
            "--max_epochs", "1", "--log_every_n_steps", "1",
        ]
    )
    rows = read_metrics(run_dir)
    assert any("train_loss" in r for r in rows)
    assert any("val_loss" in r for r in rows)
    assert os.path.isdir(os.path.join(run_dir, "checkpoints"))


def test_all_parsers_build_and_render_help():
    """Every entry point's composed parser builds without argparse conflicts
    and renders help (cheap guard for flag collisions across the shared
    argument groups)."""
    from perceiver_io_tpu.cli import (
        train_flow,
        train_imagenet,
        train_img_clf,
        train_mlm,
        train_multimodal,
        train_seq_clf,
    )

    for mod in (train_mlm, train_seq_clf, train_img_clf,
                train_imagenet, train_flow, train_multimodal):
        parser = mod.build_parser()
        help_text = parser.format_help()
        for flag in ("--dp", "--tp", "--sp", "--zero", "--multihost",
                     "--resume", "--attn_impl", "--dtype",
                     "--selfprofile_every_n_steps",
                     "--skip_nonfinite_steps", "--rollback_after_bad_steps",
                     "--dispatch_error_retries", "--fit_attempts"):
            assert flag in help_text, f"{mod.__name__} missing {flag}"

    from perceiver_io_tpu.cli import serve

    help_text = serve.build_parser().format_help()
    for flag in ("--checkpoint", "--tokenizer", "--bucket_widths", "--dtype",
                 "--quantize", "--cached", "--max_delay_ms", "--metrics_port",
                 "--heartbeat_deadline_s", "--selfprofile_every",
                 "--events_jsonl", "--events_max_mb", "--cpu",
                 "--request_deadline_s", "--queue_limit",
                 "--dispatch_retries", "--breaker_failures",
                 "--breaker_cooldown_s", "--slo_p99_ms",
                 "--slo_availability", "--slo_burn_alert", "--span_every"):
        assert flag in help_text, f"serve missing {flag}"


def test_mlm_preset_flagship_tpu_defaults():
    """--preset flagship_tpu moves the width/compute DEFAULTS (256 latents x
    512 channels, attn_impl xla — models/presets.py flagship_tpu_mlm) while
    explicit flags still override the preset. Resolution is post-parse
    (apply_preset over None sentinels), so it composes with resume's
    hparams-as-defaults layering and never reads global sys.argv."""
    from perceiver_io_tpu.cli import train_mlm

    def parse(argv):
        return train_mlm.apply_preset(
            train_mlm.build_parser().parse_args(argv))

    ref = parse([])
    assert (ref.num_latents, ref.num_latent_channels) == (64, 64)
    assert ref.attn_impl == "auto"

    args = parse(["--preset", "flagship_tpu"])
    assert (args.num_latents, args.num_latent_channels) == (256, 512)
    assert args.attn_impl == "xla"
    # the recipe shape is untouched: reference batch/seq/layer defaults
    assert (args.batch_size, args.max_seq_len) == (64, 512)
    assert (args.num_encoder_layers,
            args.num_self_attention_layers_per_block) == (3, 6)

    args = parse(["--preset", "flagship_tpu", "--num_latent_channels", "128",
                  "--attn_impl", "auto"])
    assert (args.num_latents, args.num_latent_channels) == (256, 128)
    assert args.attn_impl == "auto"


@pytest.mark.slow  # tier-1 budget (r10): zero3 rule correctness stays
# tier-1 in tests/test_sharding.py::test_zero3_param_sharding and the
# checkpoint path in test_zero3_sharded_state_round_trip
def test_train_mlm_zero3(tmp_path):
    """--zero3 (ZeRO-3/FSDP flavor: params AND opt-state over the data
    axis, GSPMD all-gather-on-use) trains end to end on the 8-device mesh
    with finite losses."""
    run_dir = train_mlm.main(
        _common(tmp_path, "mlmz3") + TINY_MODEL + [
            "--synthetic_size", "64", "--batch_size", "16",
            "--max_seq_len", "32", "--vocab_size", "90",
            "--max_steps", "3", "--log_every_n_steps", "1",
            "--dp", "8", "--zero3",
        ]
    )
    rows = read_metrics(run_dir)
    losses = [r["train_loss"] for r in rows if "train_loss" in r]
    assert losses and np.isfinite(losses).all()


@pytest.mark.slow  # tier-1 budget (r19): resume determinism stays tier-1 in
# test_trainer.py::test_resume_fast_forwards_data_stream +
# test_cli_resume_continues_run, and the bucket×K grouped-emission
# contract in test_data.py's group_widths/group_size units — this is the
# 30s full-CLI composition of both
def test_bucketed_stacked_resume_is_bit_for_bit(tmp_path):
    """Deterministic resume survives the r4 composition: with width buckets
    AND steps_per_dispatch=2 active, a run STOPPED at step 4 (end-of-run
    checkpoint; the SIGTERM last/ path has its own drill) and resumed
    to step 8 reproduces the uninterrupted run's logged losses EXACTLY
    (float-equal) — the loader's grouped emission order is a deterministic
    (seed, epoch) function consumed strictly as a prefix, so the resume
    arithmetic lands on the very same batches."""
    base = [
        "--synthetic", "--synthetic_size", "128", "--batch_size", "8",
        "--max_seq_len", "256", "--vocab_size", "120",
        "--bucket_widths", "128", "--length_sort_window", "2",
        "--steps_per_dispatch", "2",
        "--num_latents", "8", "--num_latent_channels", "16",
        "--num_encoder_layers", "1",
        "--num_self_attention_layers_per_block", "1",
        "--dtype", "float32", "--log_every_n_steps", "1",
        "--root", str(tmp_path / "cache"),
    ]

    def losses(run_dir):
        rows = read_metrics(run_dir)
        return {r["step"]: r["train_loss"] for r in rows if "train_loss" in r}

    full = losses(train_mlm.main(
        base + ["--max_steps", "8",
                "--logdir", str(tmp_path / "full"), "--experiment", "f"]))
    part = train_mlm.main(
        base + ["--max_steps", "4",
                "--logdir", str(tmp_path / "part"), "--experiment", "p"])
    resumed = losses(train_mlm.main(base + ["--max_steps", "8", "--resume", part]))

    tail_full = {k: v for k, v in full.items() if k > 4}
    tail_res = {k: v for k, v in resumed.items() if k > 4}
    assert tail_full and tail_full.keys() == tail_res.keys()
    for k in tail_full:
        assert tail_full[k] == tail_res[k], (k, tail_full[k], tail_res[k])


def test_resume_nothing_to_resume_fails_clearly(tmp_path):
    """--resume on a dir with no usable checkpoint must fail with the clear
    nothing-to-resume message (not a raw traceback) in all three shapes: no
    checkpoints/ at all, a regular file as the path, and the
    killed-after-construction window where hparams.json exists but zero
    checkpoint steps were saved."""
    tiny = _common(tmp_path, "rz") + TINY_MODEL + [
        "--synthetic_size", "32", "--max_seq_len", "32", "--vocab_size", "90",
        "--batch_size", "8", "--max_steps", "1", "--log_every_n_steps", "1",
    ]

    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit, match="no usable checkpoint"):
        train_mlm.main(tiny + ["--resume", str(empty)])

    not_a_dir = tmp_path / "file.txt"
    not_a_dir.write_text("x")
    with pytest.raises(SystemExit, match="no usable checkpoint"):
        train_mlm.main(tiny + ["--resume", str(not_a_dir)])

    constructed = tmp_path / "constructed"
    (constructed / "checkpoints").mkdir(parents=True)
    (constructed / "checkpoints" / "hparams.json").write_text(
        json.dumps({"num_latents": 8}))
    with pytest.raises(SystemExit, match="no usable checkpoint"):
        train_mlm.main(tiny + ["--resume", str(constructed)])


def test_spawn_retry_gate_reads_coordination_errors(tmp_path):
    """The spawn_hosts port-race retry fires only on distributed-bring-up
    evidence in a child log — a deterministic fast failure (bad flag,
    import error) must NOT look like a race (cli/common.py)."""
    from perceiver_io_tpu.cli.common import _logs_show_coordination_failure

    logs = iter(range(10))

    def fake_log(text):
        f = (tmp_path / f"rank{next(logs)}.log").open("w+")
        f.write(text)
        f.flush()
        return f

    race = fake_log("jaxlib ... UNAVAILABLE: failed to connect to coordinator")
    bind = fake_log("RuntimeError: [Errno 98] Address already in use")
    plain = fake_log("error: unrecognized arguments: --definitely-not-a-flag")
    assert _logs_show_coordination_failure([None, race])
    assert _logs_show_coordination_failure([None, bind])
    assert not _logs_show_coordination_failure([None, plain])
    assert not _logs_show_coordination_failure([None])  # rank 0 only
