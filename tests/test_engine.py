"""Serving engine: continuous micro-batching, AOT bucket warmup, the
encode/decode latent-cache split, and width-bucketed text serving."""

import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import perceiver_io_tpu as pit
from perceiver_io_tpu.data.tokenizer import (
    MASK_TOKEN,
    PAD_TOKEN,
    UNK_TOKEN,
    WordPieceTokenizer,
)
from perceiver_io_tpu.inference import (
    EngineClosed,
    MLMPredictor,
    MLMServer,
    ServingEngine,
    encode_masked_texts,
)
from perceiver_io_tpu.ops.masking import TextMasking


def _word_tokenizer():
    words = ["movie", "great", "terrible", "watch", "the", "was", "plot",
             "ending", "felt", "slow", "a", "b"]
    vocab = {PAD_TOKEN: 0, UNK_TOKEN: 1, MASK_TOKEN: 2}
    for w in words:
        vocab[w] = len(vocab)
    return WordPieceTokenizer(vocab=vocab)


def _tiny_mlm(vocab_size, max_seq_len=16, c=16):
    return pit.PerceiverMLM(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len, num_channels=c
            ),
            latent_shape=(4, c),
            num_layers=2,
            num_self_attention_layers_per_block=1,
            num_cross_attention_heads=2,
            num_self_attention_heads=2,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len,
                num_output_channels=c,
            ),
            latent_shape=(4, c),
            num_cross_attention_heads=2,
        ),
        masking=TextMasking(vocab_size, 1, 2, 3),
    )


def _init_mlm(model, max_seq_len=16):
    ids = np.zeros((1, max_seq_len), np.int32)
    return model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        jnp.asarray(ids), jnp.asarray(ids == 1),
    )["params"]


# -- encode/decode split (model core) ----------------------------------------


def test_encode_decode_split_parity():
    """decode(encode(x)) must equal the fused forward at f32/2e-5 — full
    decode AND the positions= gathered decode (the latent-cache serving
    path is exactly the fused computation, split)."""
    tok = _word_tokenizer()
    model = _tiny_mlm(tok.get_vocab_size())
    ids, pad = encode_masked_texts(
        tok, ["the movie was [MASK]", "a [MASK] plot and a [MASK] ending"], 16
    )
    params = _init_mlm(model)

    fused, _ = model.apply(
        {"params": params}, ids, pad, masking=False, deterministic=True
    )
    latents = model.apply({"params": params}, ids, pad, method="encode")
    split = model.apply({"params": params}, latents, method="decode")
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(split)[:, : ids.shape[1], :], atol=2e-5
    )

    positions = np.asarray([[3, 0], [1, 7]], np.int32)
    fused_pos, _ = model.apply(
        {"params": params}, ids, pad, masking=False, deterministic=True,
        positions=positions,
    )
    split_pos = model.apply(
        {"params": params}, latents, positions=positions, method="decode"
    )
    np.testing.assert_allclose(
        np.asarray(fused_pos), np.asarray(split_pos), atol=2e-5
    )


def test_perceiver_io_encode_decode_split(rng):
    """The generic PerceiverIO core exposes the same split."""
    enc = pit.PerceiverEncoder(
        input_adapter=pit.ImageInputAdapter(
            image_shape=(6, 6, 1), num_frequency_bands=3
        ),
        latent_shape=(4, 16), num_layers=1,
        num_self_attention_layers_per_block=1,
        num_cross_attention_heads=2, num_self_attention_heads=2,
    )
    dec = pit.PerceiverDecoder(
        output_adapter=pit.ClassificationOutputAdapter(
            num_classes=3, num_output_channels=16
        ),
        latent_shape=(4, 16), num_cross_attention_heads=2,
    )
    model = pit.PerceiverIO(encoder=enc, decoder=dec)
    x = jnp.asarray(rng.normal(0, 1, (3, 6, 6, 1)), jnp.float32)
    params = model.init({"params": jax.random.key(0)}, x)["params"]
    fused = model.apply({"params": params}, x)
    latents = model.apply({"params": params}, x, method="encode")
    split = model.apply({"params": params}, latents, method="decode")
    np.testing.assert_allclose(np.asarray(fused), np.asarray(split), atol=2e-5)


# -- ServingEngine core ------------------------------------------------------


def test_engine_bucket_warmup_compiles_once():
    """warmup() compiles one program per power-of-two bucket, and the serving
    stream then NEVER compiles: the traced-call counter (jax traces exactly
    once per compilation) stays at the warmup count across mixed batch
    sizes, padded buckets, and an oversized chunked request.

    The steady phase runs under BOTH runtime sanitizers (analysis/): the
    XLA-level ``no_recompile()`` (the trace counter alone cannot see a
    constant-folding recompile of an unchanged trace) and the armed
    device→host transfer guard (a silent host fetch on the dispatch or
    completion path is a per-batch ~100 ms tunnel round trip in
    production)."""
    from perceiver_io_tpu.analysis import no_implicit_transfers, no_recompile

    traces = [0]

    def apply_fn(p, x):
        traces[0] += 1
        return x * p + 1.0

    with ServingEngine(
        apply_fn, jnp.float32(2.0), max_batch=8, name="warm"
    ) as eng:
        warmed = eng.warmup(np.zeros((1, 3), np.float32))
        assert warmed == [1, 2, 4, 8]
        assert traces[0] == 4
        assert eng.num_programs == 4

        sizes = (1, 2, 3, 5, 8, 19)  # 19 chunks into 8+8+4(padded)
        with no_recompile(), no_implicit_transfers():
            futures = [
                eng.submit(np.full((n, 3), float(n), np.float32))
                for n in sizes
            ]
            for n, fut in zip(sizes, futures):
                out = fut.result(timeout=60)
                assert out.shape == (n, 3)
                np.testing.assert_allclose(out, n * 2.0 + 1.0)
        assert traces[0] == 4, "steady-state serving must not compile"


def test_engine_queue_drain_mixed_sizes_and_signatures():
    """Mixed batch sizes, two input signatures (widths), an oversized
    request, and an empty request all drain correctly under one engine —
    every request's rows come back exactly (row i carries value i)."""

    def apply_fn(p, x):
        return x + p

    with ServingEngine(apply_fn, jnp.float32(0.5), max_batch=4) as eng:
        cases = []
        for i, (n, width) in enumerate(
            [(1, 3), (4, 5), (2, 3), (11, 5), (3, 3), (0, 3)]
        ):
            x = np.full((n, width), float(i), np.float32)
            x += np.arange(n, dtype=np.float32)[:, None] if n else 0
            cases.append((x, eng.submit(x)))
        for x, fut in cases:
            out = fut.result(timeout=60)
            assert out.shape == x.shape
            np.testing.assert_allclose(out, x + 0.5)
        stats = eng.stats()
        assert stats["requests"] == len(cases) - 1  # empty skips the queue
        assert stats["rows"] == sum(len(x) for x, _ in cases)


def test_engine_concurrent_submitters():
    """Requests submitted from many threads (the serving situation) coalesce
    into micro-batches and every caller gets its own rows back."""

    def apply_fn(p, x):
        return x * p

    results = {}
    with ServingEngine(apply_fn, jnp.float32(3.0), max_batch=16) as eng:
        eng.warmup(np.zeros((1, 2), np.float32))

        def client(i):
            x = np.full((1 + i % 3, 2), float(i), np.float32)
            results[i] = (x, eng.submit(x).result(timeout=60))

        threads = [threading.Thread(target=client, args=(i,)) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i, (x, out) in results.items():
        np.testing.assert_allclose(out, x * 3.0, err_msg=str(i))


def test_engine_error_propagates_and_engine_survives():
    """A request whose shapes break the program fails ITS future; the engine
    keeps serving later requests."""

    def apply_fn(p, x):
        return x @ p  # (n, 3) @ (3,) — a (n, 2) input cannot trace

    with ServingEngine(
        apply_fn, jnp.arange(3, dtype=jnp.float32), max_batch=4
    ) as eng:
        bad = eng.submit(np.ones((2, 2), np.float32))
        with pytest.raises(Exception):
            bad.result(timeout=60)
        good = eng.submit(np.ones((2, 3), np.float32))
        np.testing.assert_allclose(good.result(timeout=60), [3.0, 3.0])

    with pytest.raises(EngineClosed):
        eng.submit(np.ones((1, 3), np.float32))


def test_engine_update_params_requantize_queues_not_races():
    """Hot-swapping params on a QUANTIZED engine while submitters hammer it:
    requests that arrive mid-(re)quantization queue and are served with a
    COMPLETE tree — every result is consistent with exactly one installed
    param set (k * row-sum), never a torn mix of old int8 values with new
    scales. (The quantize-at-load error-isolation satellite.)

    Weights are k * ones(3, 3): per-channel symmetric int8 represents them
    EXACTLY (w/scale = ±127 on the grid), so any tearing shows up as a
    result outside the integer-k set, not as quantization noise."""

    def apply_fn(p, x):
        return x @ p["lin"]["kernel"]

    def params_for(k):
        return {"lin": {"kernel": np.full((3, 3), float(k), np.float32)}}

    ks = (1, 2, 3, 4, 5)
    stop = threading.Event()
    errors = []
    completed = [0] * 4  # per-client served-request counters (int writes
    #                      under the GIL; read by the pacing loop below)

    with ServingEngine(
        apply_fn, params_for(ks[0]), max_batch=8, quantize="int8"
    ) as eng:
        eng.warmup(np.zeros((1, 3), np.float32))

        def client(i):
            rng = np.random.default_rng(i)
            while not stop.is_set():
                x = rng.normal(0, 1, (2, 3)).astype(np.float32)
                out = np.asarray(eng.submit(x).result(timeout=60))
                completed[i] += 1
                row_sum = x.sum(axis=1)
                # out[r, c] must equal k * row_sum[r] for ONE k across the
                # whole result (a torn tree would mix ratios). Rows with a
                # small |row_sum| are excluded generously: the division
                # amplifies f32 summation-order noise, and a torn tree is a
                # WHOLE-COLUMN integer-ratio flip, not a 1e-3 wiggle.
                ratios = out / np.where(
                    np.abs(row_sum[:, None]) < 1e-1, np.nan, row_sum[:, None]
                )
                ratios = ratios[np.isfinite(ratios)]
                if ratios.size == 0:
                    continue
                k = np.round(np.median(ratios))
                if k not in ks or not np.allclose(
                    ratios, k, rtol=1e-3, atol=1e-3
                ):
                    errors.append((k, ratios.min(), ratios.max()))

        threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()

        def wait_served(min_total, deadline_s=30.0):
            # pace the drill so dispatches GENUINELY overlap the staging/
            # install window — an instantaneous update burst would barely
            # exercise the queue-not-race property
            deadline = time.monotonic() + deadline_s
            while sum(completed) < min_total and time.monotonic() < deadline:
                time.sleep(0.005)

        wait_served(4)  # every client is in its serving loop
        # re-quantize repeatedly while the submitters run: preparation on
        # this (caller) thread, atomic install on the worker thread, with
        # requests flowing between consecutive swaps
        served = sum(completed)
        for _ in range(3):
            for k in ks:
                eng.update_params(params_for(k))
                served += 2
                wait_served(served)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:5]
        assert sum(completed) >= served, "drill ended before overlap happened"

        # the LAST staged tree wins once the queue drains
        x = np.ones((1, 3), np.float32)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            out = np.asarray(eng.submit(x).result(timeout=60))
            if np.allclose(out, 3.0 * ks[-1]):
                break
            time.sleep(0.01)
        np.testing.assert_allclose(out, 3.0 * ks[-1], rtol=1e-5)

    with pytest.raises(EngineClosed):
        eng.update_params(params_for(1))


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_engine_worker_crash_closes_engine_with_cause():
    """A worker crash must leave the engine CLOSED, not half-dead: post-crash
    submits raise EngineClosed immediately (never enqueue into a dead queue
    and hang toward a timeout), with the crash cause chained as __cause__."""

    def apply_fn(p, x):
        return x + p

    eng = ServingEngine(apply_fn, jnp.float32(1.0), max_batch=4, name="crash_t")
    try:
        boom = RuntimeError("worker exploded")

        def bad_next_batch(timeout):
            raise boom

        eng._next_batch = bad_next_batch  # crash OUTSIDE the per-batch guard
        eng._thread.join(timeout=30)
        assert not eng._thread.is_alive()

        t0 = time.monotonic()
        with pytest.raises(EngineClosed, match="crashed") as excinfo:
            eng.submit(np.ones((1, 3), np.float32))
        assert time.monotonic() - t0 < 5, "must fast-fail, not hang"
        assert excinfo.value.__cause__ is boom

        with pytest.raises(EngineClosed, match="crashed"):
            eng.update_params(jnp.float32(2.0))
    finally:
        eng.close()


def test_engine_bf16_compute_dtype():
    """compute_dtype='bfloat16' casts floating params/inputs once (the bf16
    serving path); results track f32 at bf16 tolerance."""

    def apply_fn(p, x):
        return x @ p

    p32 = jnp.asarray(np.linspace(0, 1, 12, dtype=np.float32).reshape(3, 4))
    x = np.linspace(-1, 1, 6, dtype=np.float32).reshape(2, 3)
    want = x @ np.asarray(p32)
    with ServingEngine(
        apply_fn, p32, max_batch=4, compute_dtype="bfloat16"
    ) as eng:
        assert eng.params.dtype == jnp.bfloat16
        out = eng.predict(x, timeout=60)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), want, rtol=2e-2, atol=2e-2
        )


# -- MLMServer: width buckets + latent cache ---------------------------------


@pytest.fixture(scope="module")
def mlm_setup():
    tok = _word_tokenizer()
    model = _tiny_mlm(tok.get_vocab_size())
    params = _init_mlm(model)
    return tok, model, params


TEXTS = [
    "the movie was [MASK]",                                   # short
    "a [MASK] plot and a [MASK] ending",                      # two masks
    "no mask here",                                           # no mask
    "the movie was great the plot felt slow the [MASK] was",  # long
]


def test_mlm_server_width_bucketed_roundtrip(mlm_setup):
    """Variable-length texts round-trip through the tokenizer into width
    buckets, and fill-mask results exactly match the (max-width)
    MLMPredictor path — width bucketing changes the shapes, not the math."""
    tok, model, params = mlm_setup
    want = MLMPredictor(
        model, params, tok, max_seq_len=16, max_batch=4
    ).fill_masks(TEXTS, k=3)

    with MLMServer(
        model, params, tok, max_seq_len=16, bucket_widths=[8], max_batch=4
    ) as server:
        # a constrained family (tier-1 budget, r10): full-default-family
        # warmup cost is exercised by test_engine_bucket_warmup_compiles_once
        # and the r10 warm-cache tests below; here warmup only needs to exist
        # so the steady-state no-new-programs assertion has a baseline
        warmed = server.warmup(batch_buckets=[1], query_buckets=(1, 2))
        assert warmed > 0
        got = server.fill_masks(TEXTS, k=3)
        assert got == want
        # the short texts really were served at the 8-wide bucket: the fused
        # engine saw an 8-wide program signature
        widths_seen = {
            key[0][0][0] for key, _ in server.engine._programs
        }
        assert 8 in widths_seen, widths_seen

        # steady state after warmup: repeat requests add no programs
        programs = server.engine.num_programs
        assert server.fill_masks(TEXTS, k=3) == want
        assert server.engine.num_programs == programs


def test_mlm_server_latent_cache_decode_many(mlm_setup):
    """Encode once, decode many: fill_masks_cached matches the fused path,
    and explicit-position decode matches the model's gathered decode — with
    ZERO additional encoder work after encode()."""
    tok, model, params = mlm_setup
    with MLMServer(
        model, params, tok, max_seq_len=16, bucket_widths=[8], max_batch=4
    ) as server:
        want = server.fill_masks(TEXTS, k=3)
        cached = server.encode(TEXTS)
        assert cached.latents.shape[0] == len(TEXTS)
        encoder_batches = server.encoder.stats()["batches"]

        assert server.fill_masks_cached(cached, k=3) == want
        # decode-many against the same latents: 3 more decode rounds
        positions = np.tile(np.arange(4, dtype=np.int32), (len(TEXTS), 1))
        logits = server.decode(cached, positions)
        assert logits.shape[:2] == (len(TEXTS), 4)
        for shift in (1, 2):
            more = server.decode(cached, (positions + shift) % 8)
            assert more.shape == logits.shape
        assert server.encoder.stats()["batches"] == encoder_batches, (
            "decode-many must not re-run the encoder"
        )

        # the decoded logits are the fused forward's rows (full parity chain:
        # fused == encode+decode at these positions)
        row = 1
        width = len(cached.token_ids[row])
        ids = cached.token_ids[row][None]
        fused, _ = model.apply(
            {"params": params}, ids, ids == tok.token_to_id(PAD_TOKEN),
            masking=False, deterministic=True,
            positions=positions[row: row + 1],
        )
        np.testing.assert_allclose(
            logits[row], np.asarray(fused)[0], atol=2e-5
        )


def test_engine_stats_snapshot_is_locked_and_deep():
    """stats() is a consistent deep copy: mutating the snapshot (or its
    latency lists) never touches live engine state, and concurrent submitters
    hammering the counters while snapshots are taken leave the final tallies
    exact (the r6 thread-safety hole: requests was bumped on caller threads
    while the worker wrote rows/batches, unlocked)."""

    def apply_fn(p, x):
        return x + p

    with ServingEngine(apply_fn, jnp.float32(1.0), max_batch=4) as eng:
        fut = eng.submit(np.zeros((2, 3), np.float32))
        fut.result(timeout=60)
        snap = eng.stats()
        snap["requests"] = 10**9
        snap["latency_s_by_bucket"].setdefault(2, []).append(123.0)
        fresh = eng.stats()
        assert fresh["requests"] == 1
        assert 123.0 not in fresh["latency_s_by_bucket"].get(2, [])

        # hammer: 8 threads x 25 requests, snapshots interleaved throughout
        def client(_):
            for _ in range(25):
                eng.submit(np.zeros((1, 3), np.float32)).result(timeout=60)
                eng.stats()

        threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        final = eng.stats()
        assert final["requests"] == 1 + 8 * 25
        assert final["rows"] == 2 + 8 * 25


def test_mlm_server_stats_shim_shape(mlm_setup):
    """MLMServer.stats() keeps the r6 shape (fused/encode/decode/programs)
    over the registry-backed engines, stays JSON-serializable (the serve CLI
    --stats path), and deep-copies."""
    import json as _json

    tok, model, params = mlm_setup
    with MLMServer(model, params, tok, max_seq_len=16, max_batch=4) as server:
        server.fill_masks(["the movie was [MASK]"], k=2)
        stats = server.stats()
        assert set(stats) == {"fused", "encode", "decode", "programs"}
        assert stats["fused"]["requests"] == 1
        _json.dumps(stats)  # deques would raise here
        for lats in stats["fused"]["latency_s_by_bucket"].values():
            lats.append(999.0)
        assert all(
            999.0 not in v
            for v in server.stats()["fused"]["latency_s_by_bucket"].values()
        )


def test_engine_publishes_registry_instruments():
    """The engine's registry instruments carry the serving telemetry: request
    /row/batch counters, padding waste, occupancy + latency histograms, and
    compile events that stay flat in steady state (the recompile detector)."""
    from perceiver_io_tpu import obs

    reg = obs.MetricsRegistry()

    def apply_fn(p, x):
        return x * p

    with ServingEngine(
        apply_fn, jnp.float32(2.0), max_batch=4, name="obs_t", registry=reg
    ) as eng:
        eng.warmup(np.zeros((1, 2), np.float32))
        compiles_after_warmup = reg.counter(
            "serving_compile_events_total", labels={"engine": "obs_t"}
        ).value
        assert compiles_after_warmup == 3  # buckets 1, 2, 4
        for n in (1, 3, 4):
            eng.submit(np.zeros((n, 2), np.float32)).result(timeout=60)
        snap = reg.snapshot()
        assert snap["counters"]['serving_requests_total{engine="obs_t"}'] == 3
        assert snap["counters"]['serving_rows_total{engine="obs_t"}'] == 8
        # 3 requests → 3 buckets (1, 4, 4): the 3-row one padded by 1
        assert snap["counters"]['serving_padded_rows_total{engine="obs_t"}'] >= 1
        assert reg.counter(
            "serving_compile_events_total", labels={"engine": "obs_t"}
        ).value == compiles_after_warmup, "steady state must not compile"
        lat = reg.histogram(
            "serving_latency_seconds",
            labels={"engine": "obs_t", "bucket": "4"},
        )
        assert lat.count >= 1
        text = reg.prometheus_text()
        assert '# TYPE serving_requests_total counter' in text
        assert 'serving_requests_total{engine="obs_t"} 3' in text


def test_mlm_server_oversized_and_empty(mlm_setup):
    """A request stream larger than max_batch chunks transparently; a
    no-mask text completes without touching the device."""
    tok, model, params = mlm_setup
    texts = ["the movie was [MASK]"] * 9 + ["no mask here"]
    with MLMServer(model, params, tok, max_seq_len=16, max_batch=4) as server:
        got = server.fill_masks(texts, k=2)
    assert got[-1] == []
    assert all(g == got[0] for g in got[:9])


# -- MLMServer: zero-recompile cold start + background warmup (r10) ----------


def test_mlm_server_warm_cache_zero_compiles(mlm_setup, tmp_path):
    """Server-level acceptance: a second MLMServer over a populated compile
    cache warms its ENTIRE (width, batch, K) program family across all three
    engines with ZERO XLA compiles (jax_compilations_total flat), and serves
    fills identical to the freshly-compiled server."""
    from perceiver_io_tpu.obs import install_compile_counter

    tok, model, params = mlm_setup
    cache_dir = str(tmp_path / "cache")
    kwargs = dict(max_seq_len=16, max_batch=1, compile_cache=cache_dir)
    with MLMServer(model, params, tok, **kwargs) as cold:
        n_cold = cold.warmup(query_buckets=(1, 2))
        fresh = cold.fill_masks(TEXTS, k=2)
        cached_lat = cold.encode(TEXTS[:2])
        fresh_cached = cold.fill_masks_cached(cached_lat, k=2)

    counter = install_compile_counter()
    before = counter.value
    with MLMServer(model, params, tok, **kwargs) as warm:
        assert warm.warmup(query_buckets=(1, 2)) == n_cold
        assert counter.value == before, "warm warmup must not compile"
        got = warm.fill_masks(TEXTS, k=2)
        lat = warm.encode(TEXTS[:2])
        got_cached = warm.fill_masks_cached(lat, k=2)
        assert counter.value == before, "warm serving must not compile"
    assert got == fresh
    assert got_cached == fresh_cached


def test_mlm_server_background_warmup_serves_immediately(mlm_setup, tmp_path):
    """warmup(background=True) returns a handle at once; fills submitted
    right away are answered (on-demand builds dedup against the warmup
    threads), and the handle reports the same program count as blocking
    mode. update_params mid-warm composes (r8 semantics preserved)."""
    tok, model, params = mlm_setup
    cache_dir = str(tmp_path / "cache")
    with MLMServer(model, params, tok, max_seq_len=16, max_batch=1,
                   compile_cache=cache_dir) as server:
        handle = server.warmup(query_buckets=(1, 2), background=True)
        got = server.fill_masks(TEXTS, k=2)  # while (possibly) still warming
        server.update_params(params)  # hot-swap composes with warmup
        n = handle.wait(timeout=300)
    # the blocking-mode reference rides the now-warm cache (cheap) — same
    # results, same program count
    with MLMServer(model, params, tok, max_seq_len=16, max_batch=1,
                   compile_cache=cache_dir) as ref:
        expect = ref.fill_masks(TEXTS, k=2)
        n_blocking = ref.warmup(query_buckets=(1, 2))
    assert got == expect
    assert n == n_blocking
