"""Test configuration: force CPU with 8 virtual XLA devices.

Set before jax initializes any backend so SPMD/mesh tests can exercise an
8-device mesh without TPU hardware (the JAX-native way to test sharding,
SURVEY.md §4). Real-TPU runs happen only via bench.py / the driver.
"""

from perceiver_io_tpu.utils.platform import ensure_cpu_only

ensure_cpu_only(device_count=8)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
