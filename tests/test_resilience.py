"""Chaos drills (CPU, fault-injected): the resilience subsystem end to end.

Every recovery path the tunneled-TPU environment will need is provoked here
deterministically via ``resilience.faults``: transient dispatch errors are
retried with backoff, wedged dispatches trip the breaker (via the heartbeat
stall monitor) and flip ``/healthz``, expired/over-quota requests are shed
with terminal results (no future ever hangs), and the trainer survives
injected NaN steps (skip → rollback) and transient device errors (retry →
``fit_with_recovery`` restart) — with the retry/shed/breaker/bad-step
counters asserted against the obs registry.
"""

import random
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.inference import ServingEngine
from perceiver_io_tpu.resilience import (
    BreakerOpen,
    CircuitBreaker,
    DeadlineExceeded,
    FaultInjector,
    FaultSpec,
    InjectedFatalError,
    InjectedTransientError,
    RejectedError,
    RetryPolicy,
    call_with_retry,
    classify_error,
    faults,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends with no injector installed."""
    prev = faults.install(None)
    yield
    faults.install(prev)


class XlaRuntimeError(RuntimeError):
    """Stand-in with jaxlib's type NAME — the taxonomy matches by name, so
    the tests need no jaxlib import."""


# -- taxonomy ----------------------------------------------------------------


def test_error_taxonomy():
    t, f = "transient", "fatal"
    assert classify_error(XlaRuntimeError("UNAVAILABLE: socket closed")) == t
    assert classify_error(XlaRuntimeError("ABORTED: coordination lost")) == t
    assert classify_error(XlaRuntimeError("DEADLINE_EXCEEDED: rpc")) == t
    assert classify_error(XlaRuntimeError("INTERNAL: stream failed")) == t
    assert classify_error(XlaRuntimeError("INVALID_ARGUMENT: bad shape")) == f
    # real scoped-VMEM OOMs (PERF.md r3) must NEVER be retried, even under
    # an infra-looking prefix
    assert classify_error(XlaRuntimeError(
        "INTERNAL: Scoped allocation with size 18.0M exceeded scoped vmem "
        "limit of 16.0M")) == f
    assert classify_error(XlaRuntimeError("RESOURCE_EXHAUSTED: hbm oom")) == f
    assert classify_error(ConnectionResetError("peer reset")) == t
    assert classify_error(TimeoutError("read timed out")) == t
    assert classify_error(InjectedTransientError("chaos")) == t
    assert classify_error(InjectedFatalError("chaos")) == f
    assert classify_error(ValueError("tracing failed")) == f
    assert classify_error(FloatingPointError("non-finite loss")) == f


def test_retry_policy_backoff_caps_and_is_seedable():
    p = RetryPolicy(max_retries=5, base_s=0.1, multiplier=2.0, max_s=0.5,
                    jitter=0.0)
    assert [p.backoff_s(i) for i in (1, 2, 3, 4, 5)] == [0.1, 0.2, 0.4, 0.5, 0.5]
    assert p.backoff_s(0) == 0.0
    j = RetryPolicy(base_s=0.1, jitter=0.5)
    a = j.backoff_s(1, rng=random.Random(7))
    b = j.backoff_s(1, rng=random.Random(7))
    assert a == b, "seeded jitter must be deterministic"
    assert 0.05 <= a <= 0.15
    with pytest.raises(ValueError):
        RetryPolicy(max_retries=-1)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)


def test_call_with_retry_semantics():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise InjectedTransientError("flap")
        return "done"

    out = call_with_retry(
        flaky, RetryPolicy(max_retries=3, base_s=0.01, jitter=0.0),
        sleep=sleeps.append,
    )
    assert out == "done" and calls["n"] == 3
    assert sleeps == [0.01, 0.02]

    # fatal: one attempt, the error propagates untouched
    calls["n"] = 0

    def fatal():
        calls["n"] += 1
        raise InjectedFatalError("stop")

    with pytest.raises(InjectedFatalError):
        call_with_retry(fatal, RetryPolicy(max_retries=5, base_s=0.0))
    assert calls["n"] == 1

    # exhausted budget re-raises the transient error
    def always():
        raise InjectedTransientError("down")

    with pytest.raises(InjectedTransientError):
        call_with_retry(always, RetryPolicy(max_retries=2, base_s=0.0),
                        sleep=lambda s: None)


# -- fault injector ----------------------------------------------------------


def test_fault_injector_is_deterministic():
    inj = FaultInjector([
        # abstract site names: this test pins the injector's counting
        # mechanics, not the registry (which only parse_spec enforces)
        FaultSpec(site="s", kind="transient", at=(2, 4)),  # pitlint: ignore[PIT-FAULT] abstract mechanics fixture
        FaultSpec(site="e", kind="fatal", every=3),  # pitlint: ignore[PIT-FAULT] abstract mechanics fixture
    ])
    fired = []
    for i in range(1, 6):
        try:
            inj.inject("s")
            fired.append(False)
        except InjectedTransientError:
            fired.append(True)
    assert fired == [False, True, False, True, False]
    assert inj.calls("s") == 5
    for i in range(1, 7):
        if i % 3 == 0:
            with pytest.raises(InjectedFatalError):
                inj.inject("e")
        else:
            inj.inject("e")

    # nan corruption poisons floating leaves only, at the named call
    inj2 = FaultInjector([FaultSpec(site="m", kind="nan", at=(2,))])  # pitlint: ignore[PIT-FAULT] abstract mechanics fixture
    clean = {"loss": jnp.float32(1.5), "count": np.int32(3)}
    assert inj2.corrupt("m", clean) is clean
    poisoned = inj2.corrupt("m", clean)
    assert np.isnan(poisoned["loss"]) and poisoned["count"] == 3


def test_fault_env_spec_parses():
    inj = faults.parse_spec(
        "engine.dispatch:transient@2,5;trainer.metrics:nan@every:3;"
        "engine.complete:slow@1@delay:0.25"
    )
    with pytest.raises(InjectedTransientError):
        for _ in range(2):
            inj.inject("engine.dispatch")
    with pytest.raises(ValueError, match="bad PIT_FAULTS clause"):
        faults.parse_spec("nonsense")


# -- circuit breaker ---------------------------------------------------------


def test_breaker_state_machine_and_telemetry():
    now = [0.0]
    reg = obs.MetricsRegistry()
    b = CircuitBreaker("bt", failure_threshold=2, cooldown_s=10.0,
                       registry=reg, clock=lambda: now[0])
    try:
        assert b.state == "closed" and b.allow()
        b.record_failure(RuntimeError("one"))
        assert b.state == "closed"  # below threshold
        b.record_success()
        b.record_failure(RuntimeError("one"))
        b.record_failure(RuntimeError("two"))  # consecutive pair → open
        assert b.state == "open" and not b.allow()
        with pytest.raises(BreakerOpen):
            b.check()
        now[0] = 10.0  # cooldown elapsed → half-open probe admitted
        assert b.allow() and b.state == "half_open"
        b.record_failure(RuntimeError("probe died"))  # probe fails → reopen
        assert b.state == "open"
        now[0] = 20.0
        assert b.allow() and b.state == "half_open"
        b.record_success()
        assert b.state == "closed"
        gauge = reg.gauge("breaker_state", labels={"breaker": "bt"})
        assert gauge.value == 0
        opens = reg.counter("breaker_transitions_total",
                            labels={"breaker": "bt", "to": "open"})
        assert opens.value == 2

        # a trip() while already OPEN extends the cooldown window — the
        # stall monitor re-asserts every poll during a persistent wedge, and
        # the breaker must not drift half-open while the stall continues
        now[0] = 100.0
        b.trip("stall")
        now[0] = 109.0
        b.trip("stall persists")
        now[0] = 112.0  # 12s after the first trip, 3s after the re-trip
        assert not b.allow() and b.state == "open"
        now[0] = 119.5  # cooldown (10s) elapsed since the LAST re-trip
        assert b.allow() and b.state == "half_open"
        b.record_success()
        assert b.state == "closed"

        # healthz reflects an open breaker (the /healthz body)
        b.trip("drill")
        ok, detail = obs.healthz()
        assert not ok and detail["sources"]["breaker:bt"]["state"] == "open"
    finally:
        b.close()
    ok, detail = obs.healthz()
    assert "breaker:bt" not in detail.get("sources", {})


# -- engine chaos ------------------------------------------------------------


def _mul_engine(**kw):
    def apply_fn(p, x):
        return x * p

    kw.setdefault("max_batch", 4)
    return ServingEngine(apply_fn, jnp.float32(2.0), **kw)


def test_engine_transient_dispatch_retried_no_request_fails():
    """One flaky dispatch no longer fails its whole micro-batch: the batch
    re-dispatches with backoff and every future still resolves."""
    reg = obs.MetricsRegistry()
    faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch", kind="transient", at=(2, 3)),
    ]))
    with _mul_engine(name="rt", registry=reg,
                     retry_policy=RetryPolicy(max_retries=3, base_s=0.01,
                                              jitter=0.0),
                     breaker_failures=10) as eng:
        futs = [eng.submit(np.full((1, 2), float(i), np.float32))
                for i in range(6)]
        for i, fut in enumerate(futs):
            np.testing.assert_allclose(fut.result(timeout=60), 2.0 * i)
        assert reg.counter("serving_dispatch_retries_total",
                           labels={"engine": "rt"}).value >= 1
        assert eng.breaker.state == "closed"  # recovered failures don't trip


def test_engine_complete_side_transient_redispatches():
    """A completion-side failure (device_get) re-dispatches the batch too —
    the request still resolves with the right answer."""
    reg = obs.MetricsRegistry()
    faults.install(FaultInjector([
        FaultSpec(site="engine.complete", kind="transient", at=(1,)),
    ]))
    with _mul_engine(name="ct", registry=reg,
                     retry_policy=RetryPolicy(max_retries=2, base_s=0.01,
                                              jitter=0.0)) as eng:
        out = eng.predict(np.full((2, 3), 4.0, np.float32), timeout=60)
        np.testing.assert_allclose(out, 8.0)
        assert reg.counter("serving_dispatch_retries_total",
                           labels={"engine": "ct"}).value == 1


def test_engine_retry_budget_exhausted_fails_with_original_error():
    faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch", kind="transient", every=1),
    ]))
    with _mul_engine(name="ex",
                     retry_policy=RetryPolicy(max_retries=1, base_s=0.01,
                                              jitter=0.0)) as eng:
        with pytest.raises(InjectedTransientError):
            eng.submit(np.ones((1, 2), np.float32)).result(timeout=60)


def test_engine_fatal_dispatch_error_never_retried():
    reg = obs.MetricsRegistry()
    faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch", kind="fatal", at=(1,)),
    ]))
    with _mul_engine(name="ft", registry=reg, dispatch_retries=5) as eng:
        with pytest.raises(InjectedFatalError):
            eng.submit(np.ones((1, 2), np.float32)).result(timeout=60)
        assert reg.counter("serving_dispatch_retries_total",
                           labels={"engine": "ft"}).value == 0
        # the engine survives and keeps serving
        np.testing.assert_allclose(
            eng.predict(np.ones((1, 2), np.float32), timeout=60), 2.0)


def test_engine_deadline_shed_at_admission_and_assembly():
    reg = obs.MetricsRegistry()
    release = threading.Event()
    faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch", kind="hang", at=(1,),
                  release=release, delay_s=30.0),
    ]))
    try:
        with _mul_engine(name="dl", registry=reg) as eng:
            # admission: an already-expired deadline is refused outright
            with pytest.raises(DeadlineExceeded):
                eng.submit(np.ones((1, 2), np.float32), deadline_s=0.0)

            f1 = eng.submit(np.ones((1, 2), np.float32))
            time.sleep(0.1)  # let the worker wedge inside dispatch #1
            f2 = eng.submit(np.full((1, 2), 5.0, np.float32), deadline_s=0.05)
            time.sleep(0.15)  # f2's deadline expires while the tunnel is stuck
            release.set()
            np.testing.assert_allclose(f1.result(timeout=60), 2.0)
            # shed AT ASSEMBLY with a terminal result — not a silent hang and
            # not a burned dispatch
            with pytest.raises(DeadlineExceeded):
                f2.result(timeout=60)
            shed = reg.counter("serving_shed_total",
                               labels={"engine": "dl", "reason": "deadline"})
            assert shed.value == 2  # one admission + one assembly shed
    finally:
        release.set()


def test_engine_queue_limit_sheds_with_fast_fail():
    reg = obs.MetricsRegistry()
    release = threading.Event()
    faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch", kind="hang", at=(1,),
                  release=release, delay_s=30.0),
    ]))
    try:
        with _mul_engine(name="ql", registry=reg, queue_limit=2) as eng:
            first = eng.submit(np.ones((1, 2), np.float32))
            time.sleep(0.1)  # worker wedged in dispatch #1 (backlog drained)
            admitted = [eng.submit(np.ones((1, 2), np.float32))
                        for _ in range(2)]
            with pytest.raises(RejectedError):
                eng.submit(np.ones((1, 2), np.float32))
            assert reg.counter(
                "serving_shed_total",
                labels={"engine": "ql", "reason": "queue_full"}).value == 1
            release.set()
            for fut in [first, *admitted]:
                np.testing.assert_allclose(fut.result(timeout=60), 2.0)
    finally:
        release.set()


def test_wedged_dispatch_trips_breaker_and_healthz_503():
    """THE acceptance drill, detection half: a wedged dispatch (hang fault)
    stalls the heartbeat → the monitor trips the breaker → the obs registry
    shows state 2 and the HTTP /healthz endpoint returns 503 naming it."""
    import json
    import urllib.error
    import urllib.request

    reg = obs.MetricsRegistry()
    release = threading.Event()
    faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch", kind="hang", at=(1,),
                  release=release, delay_s=60.0),
    ]))
    try:
        with obs.ObsServer(registry=reg) as server, _mul_engine(
            name="wedge", registry=reg,
            heartbeat_deadline_s=0.15,
            breaker_failures=3, breaker_cooldown_s=0.2,
        ) as eng:
            f1 = eng.submit(np.ones((1, 2), np.float32))
            deadline = time.monotonic() + 20
            while eng.breaker.state != "open" and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.breaker.state == "open", "stall monitor must trip it"
            assert reg.gauge("breaker_state",
                             labels={"breaker": "wedge"}).value == 2

            ok, detail = obs.healthz()
            assert not ok
            assert detail["sources"]["breaker:wedge"]["state"] == "open"
            try:
                with urllib.request.urlopen(f"{server.url}/healthz"):
                    code, body = 200, {}
            except urllib.error.HTTPError as e:
                code, body = e.code, json.loads(e.read().decode())
            assert code == 503
            assert body["sources"]["breaker:wedge"]["state"] == "open"

            release.set()  # un-wedge: the hung future still resolves
            np.testing.assert_allclose(f1.result(timeout=60), 2.0)
    finally:
        release.set()


def test_wedged_dispatch_breaker_full_cycle():
    """Same drill without the HTTP assertion plumbing: fast-fail while open,
    zero hung futures, half-open probe recovery."""
    reg = obs.MetricsRegistry()
    release = threading.Event()
    faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch", kind="hang", at=(1,),
                  release=release, delay_s=60.0),
    ]))
    try:
        with _mul_engine(
            name="wedge2", registry=reg, heartbeat_deadline_s=0.15,
            breaker_failures=3, breaker_cooldown_s=0.2,
        ) as eng:
            f1 = eng.submit(np.ones((1, 2), np.float32))
            deadline = time.monotonic() + 20
            while eng.breaker.state != "open" and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.breaker.state == "open"
            # fast-fail while open: no queue growth behind a dead device
            with pytest.raises(BreakerOpen):
                eng.submit(np.ones((1, 2), np.float32))
            assert reg.counter(
                "serving_shed_total",
                labels={"engine": "wedge2", "reason": "breaker_open"},
            ).value >= 1

            # cooldown elapses while STILL wedged: one submit may slip into
            # the half-open window, but the stall monitor re-trips every
            # poll — the breaker must not PARK half-open admitting unbounded
            # traffic behind the hung worker
            time.sleep(3 * 0.2)
            probe = None
            try:
                probe = eng.submit(np.ones((1, 2), np.float32))
            except BreakerOpen:
                pass
            deadline = time.monotonic() + 5
            while eng.breaker.state != "open" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eng.breaker.state == "open"

            release.set()  # un-wedge the tunnel
            # the wedged request was never lost: terminal result, right answer
            np.testing.assert_allclose(f1.result(timeout=60), 2.0)

            # after the cooldown the half-open probe flows and closes it
            out = None
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                try:
                    out = eng.submit(
                        np.full((1, 2), 3.0, np.float32)).result(timeout=60)
                    break
                except BreakerOpen:
                    time.sleep(0.05)
            np.testing.assert_allclose(out, 6.0)
            assert eng.breaker.state == "closed"
            if probe is not None:  # the half-open slip still resolved
                np.testing.assert_allclose(probe.result(timeout=60), 2.0)
        ok, _ = obs.healthz()
        assert ok, "breaker deregisters on engine close"
    finally:
        release.set()


# -- trainer chaos -----------------------------------------------------------


def _toy_trainer(tmp_path, *, max_steps=6, **cfg_overrides):
    """A tiny deterministic quadratic-fit trainer (no Perceiver — the drills
    exercise the LOOP, not the model)."""
    import optax

    from perceiver_io_tpu.training import Trainer, TrainerConfig, TrainState

    def train_step(state, batch):
        def loss_fn(params):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), {"loss": loss}

    params = {"w": jnp.zeros((3, 1))}
    state = TrainState.create(params, optax.sgd(0.1), jax.random.key(0))
    cfg = TrainerConfig(
        max_steps=max_steps, log_every_n_steps=100,
        logdir=str(tmp_path / "logs"), experiment="chaos",
        use_tensorboard=False, compute_mfu=False, **cfg_overrides,
    )
    return Trainer(train_step, None, state, cfg,
                   example_batch=_toy_batches()[0])


def _toy_batches(n=8, seed=0):
    rng = np.random.default_rng(seed)
    w_true = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
    batches = []
    for _ in range(n):
        x = rng.normal(0, 1, (4, 3)).astype(np.float32)
        batches.append({"x": x, "y": x @ w_true})
    return batches


def _counter(name):
    return obs.get_registry().counter(name)


def test_trainer_skips_injected_nan_step(tmp_path):
    """An injected NaN step is skipped (pre-step state kept) and the run
    finishes with a finite loss on par with the fault-free run."""
    batches = _toy_batches()

    clean = _toy_trainer(tmp_path / "clean", skip_nonfinite_steps=True)
    with clean:
        clean_state = clean.fit(batches)
    clean_loss = float(jax.device_get(
        jnp.mean((batches[0]["x"] @ clean_state.params["w"]
                  - batches[0]["y"]) ** 2)))

    bad0 = _counter("trainer_bad_steps_total").value
    faults.install(FaultInjector([
        FaultSpec(site="trainer.metrics", kind="nan", at=(3,)),
    ]))
    trainer = _toy_trainer(tmp_path / "faulted", skip_nonfinite_steps=True,
                           rollback_after_bad_steps=0)
    with trainer:
        state = trainer.fit(batches)
    assert int(jax.device_get(state.step)) == 6  # skipped step not counted
    assert _counter("trainer_bad_steps_total").value == bad0 + 1
    faulted_loss = float(jax.device_get(
        jnp.mean((batches[0]["x"] @ state.params["w"]
                  - batches[0]["y"]) ** 2)))
    assert np.isfinite(faulted_loss)
    # loss parity with the fault-free run: both converged well below the
    # w=0 starting loss (~5.0 on this toy); skipping one batch of eight must
    # not change the outcome's order of magnitude, let alone poison it
    assert faulted_loss < 1.0
    assert faulted_loss < 5.0 * max(clean_loss, 0.05)


def test_trainer_rolls_back_after_consecutive_bad_steps(tmp_path):
    batches = _toy_batches()
    bad0 = _counter("trainer_bad_steps_total").value
    rb0 = _counter("trainer_rollbacks_total").value
    faults.install(FaultInjector([
        FaultSpec(site="trainer.metrics", kind="nan", at=(3, 4, 5)),
    ]))
    trainer = _toy_trainer(tmp_path, skip_nonfinite_steps=True,
                           rollback_after_bad_steps=3)
    with trainer:
        state = trainer.fit(batches)
    assert int(jax.device_get(state.step)) == 6  # finished despite the streak
    assert _counter("trainer_bad_steps_total").value == bad0 + 3
    assert _counter("trainer_rollbacks_total").value == rb0 + 1


def test_trainer_transient_dispatch_retry_exact_parity(tmp_path):
    """A transiently-failing dispatch retries the SAME batch — the recovered
    trajectory is bit-identical to the fault-free one."""
    batches = _toy_batches()
    clean = _toy_trainer(tmp_path / "clean", dispatch_error_retries=2)
    with clean:
        clean_state = clean.fit(batches)

    r0 = _counter("trainer_dispatch_retries_total").value
    faults.install(FaultInjector([
        FaultSpec(site="trainer.dispatch", kind="transient", at=(4,)),
    ]))
    trainer = _toy_trainer(tmp_path / "faulted", dispatch_error_retries=2)
    with trainer:
        state = trainer.fit(batches)
    assert _counter("trainer_dispatch_retries_total").value == r0 + 1
    assert int(jax.device_get(state.step)) == 6
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state.params["w"])),
        np.asarray(jax.device_get(clean_state.params["w"])),
    )


def test_trainer_fatal_dispatch_error_raises(tmp_path):
    faults.install(FaultInjector([
        FaultSpec(site="trainer.dispatch", kind="fatal", at=(2,)),
    ]))
    trainer = _toy_trainer(tmp_path, dispatch_error_retries=5)
    with trainer:
        with pytest.raises(InjectedFatalError):
            trainer.fit(_toy_batches())


def test_fit_with_recovery_auto_resumes_transient_crash(tmp_path):
    """A transient failure that escapes the per-step retries kills the fit
    attempt; the supervisor restores the newest checkpoint and finishes."""
    batches = _toy_batches()
    rs0 = _counter("trainer_fit_restarts_total").value
    faults.install(FaultInjector([
        FaultSpec(site="trainer.dispatch", kind="transient", at=(4,)),
    ]))
    trainer = _toy_trainer(tmp_path, skip_nonfinite_steps=True,
                           fit_attempts=3)  # retries=0: the error escapes
    with trainer:
        state = trainer.fit_with_recovery(batches)
    assert int(jax.device_get(state.step)) == 6
    assert _counter("trainer_fit_restarts_total").value == rs0 + 1

    # fatal errors are NOT restarted
    faults.install(FaultInjector([
        FaultSpec(site="trainer.dispatch", kind="fatal", at=(2,)),
    ]))
    trainer2 = _toy_trainer(tmp_path / "fatal", skip_nonfinite_steps=True,
                            fit_attempts=3)
    with trainer2:
        with pytest.raises(InjectedFatalError):
            trainer2.fit_with_recovery(batches)
    assert _counter("trainer_fit_restarts_total").value == rs0 + 1


def test_recovery_mode_disables_donation(tmp_path):
    """The kept pre-step state (and a transient retry's replayed arguments)
    must stay alive: recovery mode must not donate the train state — same
    rule as debug_nans. CPU ignores donation, so assert the trainer's own
    donation decision, which is what the TPU path compiles with."""
    with _toy_trainer(tmp_path / "a", skip_nonfinite_steps=True) as t1:
        assert not t1.donates_state
    with _toy_trainer(tmp_path / "b", dispatch_error_retries=1) as t2:
        assert not t2.donates_state
    with _toy_trainer(tmp_path / "c") as t3:
        assert t3.donates_state
