"""Unified runtime telemetry (perceiver_io_tpu.obs): registry, tracing,
HTTP sidecar, heartbeat health, and the in-loop self-profiling watchdog."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from perceiver_io_tpu import obs
from perceiver_io_tpu.inference import ServingEngine


# -- registry ----------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = obs.MetricsRegistry()
    c = reg.counter("reqs_total", "requests")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("depth")
    g.set(3.5)
    assert g.value == 3.5
    g.inc(-1.5)
    assert g.value == 2.0

    h = reg.histogram("lat_seconds", window=100)
    for v in range(100):
        h.observe(v / 100)
    p = h.percentiles()
    assert h.count == 100 and abs(h.sum - 49.5) < 1e-9
    assert p[0.5] == pytest.approx(0.5) and p[0.95] == pytest.approx(0.95)
    # bounded window: old observations roll off, count/sum stay lifetime
    for _ in range(200):
        h.observe(1.0)
    assert h.count == 300 and len(h.values()) == 100


def test_registry_identity_and_type_conflicts():
    reg = obs.MetricsRegistry()
    a = reg.counter("x_total", labels={"k": "1"})
    b = reg.counter("x_total", labels={"k": "1"})
    other = reg.counter("x_total", labels={"k": "2"})
    assert a is b and a is not other
    with pytest.raises(TypeError):
        reg.gauge("x_total", labels={"k": "1"})
    with pytest.raises(TypeError):  # same name, new labels, wrong kind
        reg.histogram("x_total", labels={"k": "9"})


def test_registry_thread_safety_exact_counts():
    reg = obs.MetricsRegistry()
    c = reg.counter("hammer_total")
    h = reg.histogram("hammer_seconds")

    def worker():
        for _ in range(1000):
            c.inc()
            h.observe(0.001)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


def test_prometheus_text_exposition_format():
    reg = obs.MetricsRegistry()
    reg.counter("serving_requests_total", "reqs", {"engine": "e1"}).inc(7)
    reg.gauge("queue_depth", "depth").set(2)
    h = reg.histogram("lat_seconds", "latency", {"engine": "e1"})
    h.observe(0.25)
    text = reg.prometheus_text()
    assert "# TYPE serving_requests_total counter" in text
    assert 'serving_requests_total{engine="e1"} 7' in text
    assert "# TYPE queue_depth gauge" in text
    assert "queue_depth 2" in text
    assert "# TYPE lat_seconds summary" in text
    assert 'lat_seconds{engine="e1",quantile="0.5"} 0.25' in text
    assert 'lat_seconds_count{engine="e1"} 1' in text
    # every non-comment line: name{labels} value
    import re

    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        assert re.fullmatch(
            r'[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+', line
        ), line


def test_sanitize_metric_name():
    assert obs.sanitize_metric_name("val_loss") == "val_loss"
    assert obs.sanitize_metric_name("bucket64.p95") == "bucket64_p95"
    assert obs.sanitize_metric_name("9lives") == "_9lives"


def test_snapshot_shape():
    reg = obs.MetricsRegistry()
    reg.counter("a_total").inc(2)
    reg.gauge("b").set(1)
    reg.histogram("c_seconds").observe(0.5)
    snap = reg.snapshot()
    assert snap["counters"]["a_total"] == 2
    assert snap["gauges"]["b"] == 1
    assert snap["histograms"]["c_seconds"]["count"] == 1
    json.dumps(snap)  # must stay JSON-able (the /statz body)


# -- tracing -----------------------------------------------------------------


def test_event_log_span_and_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    obs.configure_event_log(path)
    try:
        obs.event("compile", engine="e1", bucket=4)
        with obs.span("warmup", engine="e1"):
            pass
        with pytest.raises(RuntimeError):
            with obs.span("boom"):
                raise RuntimeError("x")
    finally:
        obs.configure_event_log(None)
    obs.event("after_close")  # must be a silent no-op
    rows = [json.loads(l) for l in open(path)]
    assert [r["event"] for r in rows] == ["compile", "warmup", "boom"]
    assert rows[0]["bucket"] == 4 and "t" in rows[0]
    assert rows[1]["ok"] is True and rows[1]["dur_s"] >= 0
    assert rows[2]["ok"] is False and rows[2]["error"] == "RuntimeError"


def test_event_log_size_capped_rotation(tmp_path):
    """A long load run cannot grow events.jsonl unboundedly: the sink
    rotates at max_bytes keeping N numbered segments, every surviving line
    stays valid JSONL, and the oldest segment is dropped."""
    path = str(tmp_path / "events.jsonl")
    log = obs.EventLog(path, max_bytes=2000, backups=2)
    try:
        for i in range(200):
            log.write({"event": "spam", "i": i})
    finally:
        log.close()
    import os

    segments = sorted(f for f in os.listdir(tmp_path)
                      if f.startswith("events.jsonl"))
    assert segments == ["events.jsonl", "events.jsonl.1", "events.jsonl.2"]
    seen = []
    for name in segments:
        p = tmp_path / name
        assert p.stat().st_size <= 2000
        for line in open(p):
            seen.append(json.loads(line)["i"])
    # newest records survive contiguously; the oldest rolled off the end
    assert max(seen) == 199
    assert sorted(seen) == list(range(min(seen), 200))
    assert min(seen) > 0  # something WAS dropped — the cap is real

    # rotation disabled: one unbounded file, nothing dropped
    path2 = str(tmp_path / "nocap.jsonl")
    log = obs.EventLog(path2, max_bytes=None)
    try:
        for i in range(50):
            log.write({"event": "spam", "i": i})
    finally:
        log.close()
    assert len(open(path2).readlines()) == 50


def test_process_metrics_refresh_at_scrape(tmp_path):
    """install_process_metrics registers RSS/uptime/threads/GC gauges that
    refresh via the registry's collector hook at every export."""
    reg = obs.MetricsRegistry()
    obs.install_process_metrics(reg)
    snap = reg.snapshot()
    g = snap["gauges"]
    assert g["process_rss_bytes"] > 1e6  # a python + jax process is > 1 MB
    assert g["process_uptime_seconds"] > 0
    assert g["process_threads"] >= 1
    assert g["process_gc_collections"] >= 0
    text = reg.prometheus_text()
    assert "# TYPE process_rss_bytes gauge" in text
    # the collector refreshes: uptime strictly advances between scrapes
    time.sleep(0.05)
    assert (reg.snapshot()["gauges"]["process_uptime_seconds"]
            > g["process_uptime_seconds"])


def test_registry_collector_errors_never_break_the_scrape():
    reg = obs.MetricsRegistry()
    reg.counter("ok_total").inc()
    calls = []
    reg.register_collector(lambda: calls.append(1))

    def broken():
        raise RuntimeError("collector bug")

    reg.register_collector(broken)
    snap = reg.snapshot()  # must not raise
    assert snap["counters"]["ok_total"] == 1 and calls
    reg.snapshot()  # the broken collector was dropped, the good one stays
    assert len(calls) == 2


# -- health / heartbeat ------------------------------------------------------


def test_heartbeat_stall_detection_and_recovery(capsys):
    diag_called = []
    hb = obs.Heartbeat(
        "t-dispatch", deadline_s=0.15,
        diagnostics=lambda: diag_called.append(1) or {"queue": 3},
    )
    try:
        assert hb.healthy()  # disarmed = healthy
        hb.arm()
        assert hb.healthy()
        time.sleep(0.4)  # no beat within deadline
        assert hb.stalled()
        ok, detail = obs.healthz()
        assert not ok and detail["heartbeats"]["t-dispatch"]["stalled"]
        # the monitor thread dumped a diagnostic snapshot exactly once
        deadline = time.monotonic() + 2
        while not diag_called and time.monotonic() < deadline:
            time.sleep(0.02)
        assert diag_called
        err = capsys.readouterr().err
        assert "STALLED" in err and "queue: 3" in err
        assert "thread" in err  # stack dump present
        hb.beat()  # a completion arrives: healthy again
        assert hb.healthy()
        hb.disarm()
    finally:
        hb.close()
    ok, _ = obs.healthz()
    assert ok  # closed heartbeats leave the aggregate


def test_healthz_empty_is_healthy():
    ok, detail = obs.healthz()
    assert ok and detail["status"] == "ok"


# -- HTTP sidecar ------------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode(), r.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), e.headers.get("Content-Type")


def test_obs_server_endpoints():
    reg = obs.MetricsRegistry()
    reg.counter("hits_total", "hits").inc(3)
    with obs.ObsServer(registry=reg, port=0) as server:
        assert server.port > 0
        code, body, ctype = _get(f"{server.url}/metrics")
        assert code == 200 and "hits_total 3" in body
        assert "text/plain" in ctype
        code, body, _ = _get(f"{server.url}/statz")
        assert code == 200
        assert json.loads(body)["counters"]["hits_total"] == 3
        code, body, _ = _get(f"{server.url}/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, _, _ = _get(f"{server.url}/nope")
        assert code == 404
    assert server.port is None  # closed


def test_healthz_flips_unhealthy_on_stalled_dispatch():
    """The acceptance drill: a dispatch that never completes (stalled fake
    device call) flips /healthz to 503 with the stalled heartbeat named;
    releasing the stall recovers it."""
    release = threading.Event()
    reg = obs.MetricsRegistry()

    def apply_fn(p, x):
        return x + p

    eng = ServingEngine(
        apply_fn, jnp.float32(1.0), max_batch=2, name="stall_t",
        registry=reg, heartbeat_deadline_s=0.2,
    )
    real_jitted = eng._jitted

    def stalling_jitted(p, cols):
        release.wait(30)  # the wedged tunnel: dispatch never returns
        return real_jitted(p, cols)

    eng._jitted = stalling_jitted
    try:
        with obs.ObsServer(registry=reg, port=0) as server:
            fut = eng.submit(np.zeros((1, 2), np.float32))
            deadline = time.monotonic() + 10
            code = None
            while time.monotonic() < deadline:
                code, body, _ = _get(f"{server.url}/healthz")
                if code == 503:
                    break
                time.sleep(0.05)
            assert code == 503, body
            assert json.loads(body)["heartbeats"]["stall_t-dispatch"]["stalled"]
            release.set()  # the tunnel un-wedges: request completes
            out = fut.result(timeout=60)
            np.testing.assert_allclose(out, 1.0)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                code, body, _ = _get(f"{server.url}/healthz")
                if code == 200:
                    break
                time.sleep(0.05)
            assert code == 200, body
    finally:
        release.set()
        eng.close()


# -- self-profiling watchdog -------------------------------------------------


def test_selfprofiler_cpu_window_publishes_host_gauges(monkeypatch):
    """On CPU the xplane analysis finds no TPU plane — the watchdog degrades
    to host timing and still publishes step time + MFU (peak patched in for
    the cpu device kind) through the registry."""
    from perceiver_io_tpu.utils import profiling

    monkeypatch.setitem(profiling._PEAK_FLOPS, "cpu", 1e12)
    reg = obs.MetricsRegistry()
    prof = obs.SelfProfiler(
        every_n=2, trace_steps=2, prefix="t", registry=reg,
        # tiny fake FLOPs so mfu = flops/step_time/peak stays << 1 no
        # matter how fast the window runs
        flops_per_step=1e6, deadline_s=30.0,
    )
    f = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((4, 4))
    published = None
    for _ in range(8):
        f(x).block_until_ready()
        out = prof.tick(sync=lambda: None)
        if out is not None:
            published = out
            break
    assert published is not None, "no capture window closed in 8 ticks"
    assert published["selfprofile_host_step_ms"] > 0
    assert 0 < published["selfprofile_mfu"] < 1
    labels = {"loop": "t"}
    assert reg.gauge("selfprofile_host_step_ms", labels=labels).value > 0
    assert reg.counter("selfprofile_windows_total", labels=labels).value == 1
    # no TPU plane on CPU → the window degraded (counted) but host numbers
    # stand; device gauge untouched
    assert reg.counter("selfprofile_failures_total", labels=labels).value >= 1
    assert "selfprofile_device_step_ms" not in published


def test_selfprofiler_normalizes_multi_step_dispatches():
    """Under steps_per_dispatch=K each trace window is one K-step dispatch:
    the window must close after trace_steps DISPATCHES and publish
    per-OPTIMIZER-STEP host time (elapsed / K*dispatches), not per-dispatch
    — the r4 in-loop-MFU unit bug, pinned here for the watchdog."""
    reg = obs.MetricsRegistry()
    prof = obs.SelfProfiler(
        every_n=4, trace_steps=2, prefix="k", registry=reg, deadline_s=30.0,
    )
    K = 4
    dispatch_s = 0.05
    out = prof.tick(K)  # since_window hits every_n → window opens
    assert out is None
    time.sleep(dispatch_s)
    assert prof.tick(K) is None  # dispatch 1 of 2 — window stays open
    time.sleep(dispatch_s)
    published = prof.tick(K)  # dispatch 2 of 2 → closes, 8 steps total
    assert published is not None
    host_ms = published["selfprofile_host_step_ms"]
    # ~100ms over 8 optimizer steps ⇒ ~12.5ms/step; the per-dispatch bug
    # would report ~50ms. Midpoint bound: clearly per-step, not per-dispatch
    assert host_ms < 30, host_ms
    assert reg.counter("selfprofile_windows_total",
                       labels={"loop": "k"}).value == 1


def test_compile_counter_counts_new_shapes():
    reg = obs.get_registry()
    counter = obs.install_compile_counter(reg)
    before = counter.value
    f = jax.jit(lambda x: x + 1)
    f(jnp.ones((3,))).block_until_ready()
    f(jnp.ones((3,))).block_until_ready()  # cache hit: no new compile
    mid = counter.value
    assert mid >= before + 1
    f(jnp.ones((7,))).block_until_ready()  # new shape: recompile
    assert counter.value >= mid + 1


# -- Trainer / MetricsLogger one-source-of-truth -----------------------------


def test_metrics_logger_publishes_registry_gauges(tmp_path):
    from perceiver_io_tpu.training.metrics import MetricsLogger, read_metrics

    reg = obs.MetricsRegistry()
    with MetricsLogger(str(tmp_path), use_tensorboard=False,
                       registry=reg) as logger:
        logger.log_scalars(7, {"train_loss": 1.25, "mfu": 0.5})
    rows = read_metrics(str(tmp_path))
    assert rows[0]["train_loss"] == 1.25
    assert reg.gauge("train_loss").value == 1.25
    assert reg.gauge("mfu").value == 0.5
    assert reg.gauge("logged_step").value == 7


def test_trainer_smoke_publishes_step_time_and_mfu_gauges(tmp_path, monkeypatch):
    """The acceptance drill: a CPU Trainer run with the watchdog on publishes
    step-time + MFU gauges through the SAME registry that feeds metrics.jsonl
    — and the jsonl rows carry the same selfprofile metrics (one source of
    truth). On CPU the device plane is absent, so the step-time gauge is the
    host fallback; MFU flows once the cost-analysis FLOPs land (peak patched
    in for the cpu device kind)."""
    from test_trainer import _make_parts

    from perceiver_io_tpu.training import Trainer, TrainerConfig
    from perceiver_io_tpu.training.metrics import read_metrics
    from perceiver_io_tpu.utils import profiling

    monkeypatch.setitem(profiling._PEAK_FLOPS, "cpu", 1e12)
    base, (train_loader, _) = _make_parts(tmp_path)
    cfg = TrainerConfig(
        max_steps=6, log_every_n_steps=2,
        logdir=str(tmp_path / "logs_sp"), experiment="sp",
        use_tensorboard=False, compute_mfu=True,
        selfprofile_every_n_steps=2, selfprofile_steps=2,
    )
    trainer = Trainer(
        base._raw_train_step, None, base.state, cfg,
        example_batch=base._example_batch,
    )
    with trainer:
        trainer.fit(train_loader)
        rows = read_metrics(trainer.run_dir)
    base.close()

    sp_rows = [r for r in rows if "selfprofile_host_step_ms" in r]
    assert sp_rows, rows
    assert sp_rows[0]["selfprofile_host_step_ms"] > 0
    assert any("selfprofile_mfu" in r for r in sp_rows)
    assert any("mfu" in r for r in rows)  # the wall-clock in-loop MFU too

    reg = obs.get_registry()  # the registry MetricsLogger fed
    labels = {"loop": "train"}
    assert reg.gauge("selfprofile_host_step_ms", labels=labels).value > 0
    assert reg.gauge("selfprofile_mfu", labels=labels).value > 0
    # the logger mirrored every jsonl scalar into the same registry
    train_rows = [r for r in rows if "train_loss" in r]
    assert reg.gauge("train_loss").value == train_rows[-1]["train_loss"]


# -- per-request phase tracing (SLO observability) ---------------------------


def test_phase_tracing_reconciles_with_end_to_end_latency(tmp_path):
    """The tentpole self-check: every served part records all six lifecycle
    phases; the per-part phase SUM reconciles with the end-to-end latency
    within 5% at p50 (acceptance bar); the phases export as
    serving_phase_seconds{phase=...} histograms AND as JSONL request_phases
    spans; stats() carries the per-phase windows in the same locked deep-copy
    as latency_s_by_bucket."""
    import statistics

    from perceiver_io_tpu.inference import ServingEngine
    from perceiver_io_tpu.inference.engine import PHASES

    events = str(tmp_path / "events.jsonl")
    obs.configure_event_log(events)
    reg = obs.MetricsRegistry()
    eng = ServingEngine(
        lambda p, x: x * p, jnp.float32(2.0), max_batch=1,
        name="phase_t", registry=reg,
        # this test pins the r11 per-part request_phases span flow; traced
        # requests ride the compact per-batch record instead (r15), pinned
        # by tests/test_fabric.py and test_reqtrace.py
        trace_sample=0.0,
    )
    try:
        futs = [eng.submit(np.ones((1, 4), np.float32)) for _ in range(24)]
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60), 2.0)
    finally:
        eng_stats = eng.stats()
        eng.close()
        obs.configure_event_log(None)

    # every future exposes its part's phase record, covering all phases
    recs = futs[0].phases
    assert len(recs) == 1 and set(recs[0]) == set(PHASES)
    assert all(v >= 0 for v in recs[0].values())

    # stats(): phase windows ride the same locked deep-copied snapshot, and
    # (max_batch=1 ⇒ one bucket, appended in completion order) align with
    # the latency window part-for-part — sum reconciles within 5% at p50
    lat = eng_stats["latency_s_by_bucket"][1]
    ph = eng_stats["phase_s"]
    assert set(ph) == set(PHASES)
    assert all(len(ph[k]) == len(lat) for k in PHASES)
    sums = [sum(vals) for vals in zip(*(ph[k] for k in PHASES))]
    ratio = statistics.median(sums) / statistics.median(lat)
    assert 0.95 <= ratio <= 1.05, ratio
    # elementwise too: each part's phase sum brackets its own latency
    for s, l in zip(sums, lat):
        assert s >= l > 0

    # mutating the snapshot never touches live state (deep copy)
    ph["device"].append(1e9)
    assert 1e9 not in eng.stats().get("phase_s", {}).get("device", [])

    # registry: one histogram per phase, observed once per part
    for phase in PHASES:
        h = reg.histogram("serving_phase_seconds",
                          labels={"engine": "phase_t", "phase": phase})
        assert h.count == 24, (phase, h.count)
    assert 0.95 <= reg.gauge(
        "serving_phase_sum_ratio", labels={"engine": "phase_t"}).value <= 1.05

    # JSONL spans: one request_phases event per part with the phase fields
    rows = [json.loads(l) for l in open(events)]
    spans = [r for r in rows if r.get("event") == "request_phases"]
    assert len(spans) == 24
    assert spans[0]["engine"] == "phase_t"
    for phase in PHASES:
        assert phase in spans[0], spans[0]
    assert spans[0]["total_s"] > 0


def test_phase_attribution_separates_queueing_from_dispatch():
    """The attribution claim itself: hold the FIRST dispatch on a gate while
    five more requests queue behind it — the held request's time lands in
    its DISPATCH phase, the queued requests' time lands in their QUEUE
    phase, and device time stays tiny for all. 'p99 is high' is now 'p99 is
    high because queueing', not a guess."""
    from perceiver_io_tpu.inference import ServingEngine

    reg = obs.MetricsRegistry()
    release = threading.Event()
    eng = ServingEngine(lambda p, x: x + p, jnp.float32(1.0), max_batch=1,
                        name="attr_t", registry=reg)
    real_jitted = eng._jitted

    def gated_jitted(p, cols):
        release.wait(30)  # blocks the first dispatch; no-op once released
        return real_jitted(p, cols)

    eng._jitted = gated_jitted
    try:
        futs = [eng.submit(np.zeros((1, 2), np.float32)) for _ in range(6)]
        time.sleep(0.3)  # the gate holds dispatch 1; parts 2..6 queue
        release.set()
        for f in futs:
            f.result(timeout=60)
        first, last = futs[0].phases[0], futs[-1].phases[0]
        assert first["dispatch"] >= 0.25, first
        assert last["queue"] >= 0.25, last
        assert last["queue"] > 10 * max(last["device"], 1e-6), last
    finally:
        release.set()
        eng.close()


# -- SLO: burn rate + capacity model -----------------------------------------


def test_slo_tracker_burn_rate_math_and_health_wire():
    reg = obs.MetricsRegistry()
    # 10% error budget, alert at burn 2.0, health live after 10 samples
    slo = obs.SLO(latency_target_s=0.1, availability_target=0.9,
                  name="unit", burn_alert=2.0, min_samples=10)
    assert slo.error_budget == pytest.approx(0.1)
    tracker = obs.SLOTracker(slo, registry=reg)
    try:
        for _ in range(8):
            tracker.record(latency_s=0.05, ok=True)   # good
        tracker.record(latency_s=0.5, ok=True)        # latency breach
        tracker.record(ok=False)                      # shed/error breach
        assert tracker.good_fraction() == pytest.approx(0.8)
        # bad fraction 0.2 over budget 0.1 = burning 2x
        assert tracker.burn_rate() == pytest.approx(2.0)
        labels = {"slo": "unit"}
        assert reg.gauge("slo_error_budget_burn_rate",
                         labels=labels).value == pytest.approx(2.0)
        assert reg.counter("slo_breaches_total",
                           labels={**labels, "reason": "latency"}).value == 1
        assert reg.counter("slo_breaches_total",
                           labels={**labels, "reason": "error"}).value == 1
        # at burn exactly 2.0 (== alert) health holds; one more bad breaches
        ok, _ = obs.healthz()
        assert ok
        tracker.record(ok=False)
        ok, detail = obs.healthz()
        assert not ok
        assert detail["sources"]["slo:unit"]["burn_rate"] > 2.0
    finally:
        tracker.close()
    ok, _ = obs.healthz()
    assert ok  # closed trackers leave the aggregate


def test_slo_tracker_health_quiet_below_min_samples():
    slo = obs.SLO(latency_target_s=0.1, availability_target=0.9,
                  burn_alert=1.0, min_samples=5, name="quiet")
    tracker = obs.SLOTracker(slo, registry=obs.MetricsRegistry())
    try:
        tracker.record(ok=False)  # 100% bad, but only 1 sample
        name, ok, detail = tracker.health_status()
        assert ok and detail["samples"] == 1
        for _ in range(5):
            tracker.record(ok=False)
        _, ok, _ = tracker.health_status()
        assert not ok
    finally:
        tracker.close()


def test_slo_validation():
    with pytest.raises(ValueError, match="latency_target_s"):
        obs.SLO(latency_target_s=0.0)
    with pytest.raises(ValueError, match="availability_target"):
        obs.SLO(latency_target_s=0.1, availability_target=1.0)


def test_fit_capacity_knee_and_slo_sustainable():
    """The capacity model over a synthetic textbook sweep: p50 floor at
    light load, p99 departing the floor past the knee, shedding at
    overload, achieved plateauing at capacity."""
    floor = 0.010
    points = [
        dict(offered_rps=100, achieved_rps=99, p50_s=floor, p99_s=0.015,
             shed_rate=0.0),
        dict(offered_rps=200, achieved_rps=198, p50_s=0.011, p99_s=0.020,
             shed_rate=0.0),
        dict(offered_rps=400, achieved_rps=390, p50_s=0.014, p99_s=0.040,
             shed_rate=0.0),
        dict(offered_rps=800, achieved_rps=610, p50_s=0.080, p99_s=0.400,
             shed_rate=0.05),   # past the knee: p99 departed, shedding
        dict(offered_rps=1600, achieved_rps=600, p50_s=0.120, p99_s=0.900,
             shed_rate=0.5),    # plateau
    ]
    slo = obs.SLO(latency_target_s=0.050, availability_target=0.99,
                  name="cap")
    fit = obs.fit_capacity(points, slo=slo)
    assert fit["service_floor_s"] == pytest.approx(floor)
    assert fit["p99_floor_s"] == pytest.approx(0.015)
    # 400 sustains (p99 0.040 < 3x floor 0.045, no shed, achieved tracks);
    # 800 does not (shedding, p99 departed)
    assert fit["knee_rps"] == 400
    assert fit["capacity_rps"] == 610
    # SLO: p99 <= 50ms and shed within the 1% budget — 400 qualifies
    assert fit["slo_sustainable_rps"] == 400
    assert fit["slo"]["name"] == "cap"

    # a sweep that starts past saturation: knee/sustainable report 0.0
    fit2 = obs.fit_capacity(points[-1:], slo=slo)
    assert fit2["knee_rps"] == 0.0 and fit2["slo_sustainable_rps"] == 0.0
    with pytest.raises(ValueError):
        obs.fit_capacity([])


def test_engine_slo_wiring_records_completions_and_sheds():
    """ServingEngine(slo=...): completions classify against the latency
    target, queue-full sheds burn the error budget, and the tracker's
    burn-rate gauge rides the engine's registry."""
    from perceiver_io_tpu.inference import ServingEngine
    from perceiver_io_tpu.resilience import RejectedError

    reg = obs.MetricsRegistry()
    release = threading.Event()

    slo = obs.SLO(latency_target_s=60.0, availability_target=0.9,
                  name="wire", burn_alert=None)
    eng = ServingEngine(lambda p, x: x + p, jnp.float32(1.0), max_batch=1,
                        name="slo_t", registry=reg, queue_limit=2, slo=slo)
    real_jitted = eng._jitted

    def gated_jitted(p, cols):
        release.wait(30)  # holds the worker so the backlog bound trips
        return real_jitted(p, cols)

    eng._jitted = gated_jitted
    try:
        futs = [eng.submit(np.zeros((1, 2), np.float32)) for _ in range(2)]
        # queue full (2 parts backlogged; the worker may have pulled one —
        # keep submitting until the bound trips)
        with pytest.raises(RejectedError):
            for _ in range(4):
                futs.append(eng.submit(np.zeros((1, 2), np.float32)))
        release.set()
        for f in futs:
            f.result(timeout=60)
        labels = {"slo": "wire", "engine": "slo_t"}
        good = reg.counter("slo_requests_total", labels=labels).value
        assert good >= 3  # completions + the shed all classified
        assert reg.counter(
            "slo_breaches_total",
            labels={**labels, "reason": "error"}).value >= 1
        assert eng.slo_tracker.good_fraction() < 1.0
    finally:
        release.set()
        eng.close()
