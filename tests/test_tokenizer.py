"""Tests for the first-party WordPiece tokenizer: behavior, persistence,
native-path parity, and encode parity against the HF tokenizers library the
reference uses (same vocab ⇒ same ids)."""

import numpy as np
import pytest

from perceiver_io_tpu.data.tokenizer import (
    MASK_TOKEN,
    PAD_TOKEN,
    SPECIAL_TOKENS,
    UNK_TOKEN,
    WordPieceTokenizer,
    create_tokenizer,
    load_tokenizer,
    normalize,
    pre_tokenize,
    save_tokenizer,
    train_tokenizer,
)

CORPUS = [
    "I have watched this movie and it was awesome",
    "I have watched this film and it was really terrible",
    "the movie was watched by many people and they loved it",
    "watching movies is my favorite thing",
    "this film was unwatchable, truly terrible!",
] * 40


@pytest.fixture(scope="module")
def tok():
    t = create_tokenizer()
    train_tokenizer(t, CORPUS, vocab_size=150)
    return t


def test_special_token_ids(tok):
    assert tok.token_to_id(PAD_TOKEN) == 0
    assert tok.token_to_id(UNK_TOKEN) == 1
    assert tok.token_to_id(MASK_TOKEN) == 2
    assert SPECIAL_TOKENS == [PAD_TOKEN, UNK_TOKEN, MASK_TOKEN]


def test_normalize():
    assert normalize("Résumé NAÏVE Café") == "resume naive cafe"
    assert normalize("a<br />b", [("<br />", " ")]) == "a b"


def test_pre_tokenize():
    assert pre_tokenize("hello, world! it's fine") == [
        "hello", ",", "world", "!", "it", "'", "s", "fine"]


def test_encode_decode_roundtrip(tok):
    text = "i have watched this movie"
    ids = tok.encode_ids(text)
    assert ids, "no ids produced"
    assert tok.decode(ids) == text


def test_unknown_word_maps_to_unk():
    t = WordPieceTokenizer(vocab={PAD_TOKEN: 0, UNK_TOKEN: 1, MASK_TOKEN: 2,
                                  "a": 3, "b": 4, "##b": 5})
    assert t.encode_ids("ab") == [3, 5]
    assert t.encode_ids("zq") == [1]
    assert t.encode_ids("az") == [1]  # whole-word UNK on mid-word failure


def test_truncation_and_padding(tok):
    tok2 = WordPieceTokenizer(vocab=tok.vocab)
    tok2.enable_truncation(4)
    tok2.enable_padding()
    batch = tok2.encode_batch(["i have watched this movie many times", "movie"])
    assert all(len(e) == 4 for e in batch)
    assert batch[1][-1] == 0  # PAD


def test_save_load_roundtrip(tok, tmp_path):
    path = str(tmp_path / "tok.json")
    save_tokenizer(tok, path)
    tok2 = load_tokenizer(path)
    assert tok2.vocab == tok.vocab
    text = "watching this terrible movie"
    assert tok2.encode_ids(text) == tok.encode_ids(text)


def test_load_rejects_unknown_format(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text('{"something": 1}')
    with pytest.raises(ValueError, match="format"):
        load_tokenizer(str(path))


def test_native_matches_python(tok):
    tok._attach_native()
    if not tok._native:
        pytest.skip("native toolchain unavailable")
    words = set()
    for text in CORPUS[:40]:
        words.update(pre_tokenize(normalize(text)))
    words.update(["unwatchablezzz", "a", "é", "movie!!!"])
    for w in words:
        for piece in pre_tokenize(w) or [w]:
            assert tok._native.encode_word(piece) == tok._encode_word_py(piece), piece


def test_matches_hf_tokenizers_encode(tok):
    """Given the same vocab, our greedy WordPiece must produce the same ids as
    the HF implementation the reference wraps."""
    hf_tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import Tokenizer
    from tokenizers.models import WordPiece as HFWordPiece
    from tokenizers.pre_tokenizers import Whitespace

    hf = Tokenizer(HFWordPiece(vocab=tok.vocab, unk_token=UNK_TOKEN,
                               max_input_chars_per_word=100))
    hf.pre_tokenizer = Whitespace()

    for text in CORPUS[:20] + ["unwatchablezzz movie!", "it's a film"]:
        norm = normalize(text)
        ours = tok.encode_ids(norm)
        theirs = hf.encode(norm).ids
        assert ours == theirs, (text, ours, theirs)


def test_trained_vocab_learns_frequent_words(tok):
    # frequent whole words should have become single tokens
    for w in ("movie", "watched", "this"):
        assert tok.token_to_id(w) is not None, w


def test_hash_heavy_corpus_native_parity():
    """A '#'-laden corpus can mint tokens whose string form starts with '##';
    the native encoder must agree with the Python dict-lookup semantics."""
    corpus = ["### header ## sub #### rule", "# one ## two ### three"] * 30
    t = create_tokenizer()
    train_tokenizer(t, corpus, vocab_size=40)
    t._attach_native()
    if not t._native:
        pytest.skip("native toolchain unavailable")
    for w in ["#", "##", "###", "####", "#####", "header", "rule"]:
        assert t._native.encode_word(w) == t._encode_word_py(w), w
    for text in corpus[:4]:
        ids_native = t.encode_ids(text)
        t2 = WordPieceTokenizer(vocab=t.vocab)
        t2._native = False  # force python path
        assert ids_native == t2.encode_ids(text)


@pytest.mark.slow  # tier-1 budget (r11): a scaling smoke over a 3000-doc
# corpus — tokenizer merge/encode correctness stays tier-1 in the roundtrip
# and special-token tests in this file, and every serving/bench path trains
# a real tokenizer tier-1 via test_cli.py::test_serve_cli_end_to_end and
# the inference_bench contract test
def test_training_scales_to_real_vocab_sizes():
    """Incremental trainer: a few thousand docs -> vocab 2000 in seconds."""
    import time

    from perceiver_io_tpu.data.imdb import synthetic_reviews

    texts, _ = synthetic_reviews(3000, seed=7, min_words=40, max_words=160)
    t = create_tokenizer()
    t0 = time.perf_counter()
    train_tokenizer(t, texts, vocab_size=2000)
    elapsed = time.perf_counter() - t0
    # vocabulary saturates below 2000 on this corpus (bounded word set), but
    # every frequent word must have been merged to a single token
    assert t.get_vocab_size() > 200
    for w in ("movie", "terrible", "awesome"):
        assert t.token_to_id(w) is not None
    assert elapsed < 60, f"training took {elapsed:.1f}s"
