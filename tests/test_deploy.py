"""Continuous train→serve deployment loop: atomic publication (no torn
reads), the admission gate's independent rejection layers (digest / finite /
quality), sticky quarantine, post-swap rollback, the trainer's publish
cadence, and the end-to-end chaos drill — trainer-published checkpoints
flowing through gated rolling swaps into a live 3-replica fleet under
open-loop traffic with zero lost accepted requests.

Tier-1 coverage runs IN-PROCESS (trivial jitted engines, LocalReplica
shims); the real-process train+serve drill is ``slow``-marked and names the
tier-1 tests that retain its logic coverage.
"""

import json
import os
import tempfile
import threading
import time

import numpy as np
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.deploy import (
    AdmissionGate,
    CheckpointPublisher,
    CheckpointWatcher,
    DigestMismatchError,
    EngineSwapTarget,
    ModelDeployer,
    RouterSwapTarget,
    list_publications,
    load_publication,
    publish_params,
    read_quarantine,
    swap_window_stats,
    tree_digest,
)
from perceiver_io_tpu.inference import ServingEngine
from perceiver_io_tpu.resilience import (
    FaultInjector,
    FaultSpec,
    InjectedTransientError,
    faults,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(w: float = 2.0):
    return {"w": np.float32(w), "b": np.zeros((3,), np.float32)}


def _infer(p, x):
    return x * p["w"] + p["b"]


def _tamper(pub_path: str) -> None:
    """Flip stored bytes under the manifest's nose (payload corruption
    between publish and load)."""
    npz = os.path.join(pub_path, "params.npz")
    with np.load(npz) as z:
        named = {k: z[k] for k in z.files}
    first = sorted(named)[0]
    named[first] = np.asarray(named[first]) + 1.0
    with open(npz, "wb") as f:
        np.savez(f, **named)


def _publish_tampered(publish_dir: str, step: int, tree) -> str:
    """Publish an already-digest-tampered publication ATOMICALLY: stage +
    tamper out of sight, then rename the whole directory in. A live
    deployer (polling every few ms) must only ever observe the final
    tampered payload — tampering in place races the watcher into reading
    a torn npz ('unreadable' instead of the digest_mismatch under test).
    The staging dir lives INSIDE publish_dir (same filesystem by
    construction, so the rename stays atomic under any --basetemp/TMPDIR
    split) under the ``.tmp-`` prefix list_publications always skips."""
    staging = tempfile.mkdtemp(prefix=".tmp-tamper-", dir=publish_dir)
    pub = publish_params(staging, step, tree)
    _tamper(pub)
    dest = os.path.join(publish_dir, os.path.basename(pub))
    os.rename(pub, dest)
    os.rmdir(staging)
    return dest


@pytest.fixture
def reg():
    return obs.MetricsRegistry()


@pytest.fixture
def no_faults():
    prev = faults.install(None)
    yield
    faults.install(prev)


# -- digest + publication format ---------------------------------------------


def test_tree_digest_stability_and_sensitivity():
    t = {"a": {"kernel": np.arange(6, dtype=np.float32).reshape(2, 3)},
         "bias": np.ones((3,), np.float32)}
    d = tree_digest(t)
    # stable across copies and array types (values define the digest)
    import jax.numpy as jnp

    assert tree_digest({"a": {"kernel": t["a"]["kernel"].copy()},
                        "bias": jnp.ones((3,), jnp.float32)}) == d
    # one flipped bit, a changed shape, or a moved key all change it
    flipped = {"a": {"kernel": t["a"]["kernel"].copy()}, "bias": t["bias"]}
    flipped["a"]["kernel"][0, 0] += 1e-7
    assert tree_digest(flipped) != d
    assert tree_digest({"a": {"kernel": t["a"]["kernel"].reshape(3, 2)},
                        "bias": t["bias"]}) != d
    assert tree_digest({"a2": {"kernel": t["a"]["kernel"]},
                        "bias": t["bias"]}) != d


def test_publication_roundtrip_and_digest_tamper_detection(tmp_path):
    pub = publish_params(str(tmp_path), 40, _tree(), {"val_loss": 1.5})
    tree, manifest = load_publication(pub)
    assert manifest["step"] == 40 and manifest["val_metrics"] == {"val_loss": 1.5}
    assert manifest["digest"] == tree_digest(tree)
    assert np.allclose(tree["w"], 2.0)
    # a publication is immutable: same step refuses
    with pytest.raises(FileExistsError):
        publish_params(str(tmp_path), 40, _tree())
    # tampered payload fails the digest-verified load (the replica-side
    # defense behind serving/replica.py publication specs)
    _tamper(pub)
    with pytest.raises(DigestMismatchError):
        load_publication(pub)


def test_publish_atomic_no_torn_reads(tmp_path, no_faults):
    """A reader racing a publishing thread NEVER observes a half-written
    publication: everything listed loads and digest-verifies. Residue
    (.tmp dirs, manifest-less dirs) is invisible to scanners."""
    d = str(tmp_path)
    # handcrafted residue a crashed publisher could leave behind
    os.makedirs(os.path.join(d, ".tmp-step_00000999-1"))
    os.makedirs(os.path.join(d, "step_00000998"))  # no manifest: incomplete
    with open(os.path.join(d, "step_00000998", "params.npz"), "wb") as f:
        f.write(b"partial")

    stop = threading.Event()
    publish_errors = []

    def publisher():
        try:
            for k in range(1, 9):
                publish_params(d, k, _tree(1.0 + k))
        except Exception as e:  # pragma: no cover
            publish_errors.append(e)
        finally:
            stop.set()

    t = threading.Thread(target=publisher)
    t.start()
    observed = set()
    deadline = time.monotonic() + 60
    while len(observed) < 8 and time.monotonic() < deadline:
        for info in list_publications(d):
            # every visible publication is COMPLETE: digest-verified load
            tree, manifest = load_publication(info.path, verify_digest=True)
            assert manifest["step"] == info.step
            observed.add(info.step)
    t.join(timeout=30)
    assert not publish_errors
    assert observed == set(range(1, 9))
    assert {i.step for i in list_publications(d)} == set(range(1, 9))


def test_publisher_fail_soft_and_fault_site(tmp_path, reg, no_faults):
    """deploy.publish raise-kinds are drillable; the trainer-side
    CheckpointPublisher survives them (warn + counter), the raw API
    raises."""
    faults.install(FaultInjector([
        FaultSpec(site="deploy.publish", kind="transient", at=(1, 2))]))
    with pytest.raises(InjectedTransientError):
        publish_params(str(tmp_path), 1, _tree())
    pub = CheckpointPublisher(str(tmp_path), registry=reg)
    with pytest.warns(UserWarning, match="publication at step 2 failed"):
        assert pub.publish(2, _tree()) is None
    assert pub.publish(3, _tree()) is not None  # fault budget exhausted
    assert reg.counter("deploy_publish_failures_total").value == 1
    assert reg.counter("deploy_published_total").value == 1
    # a failed publish leaves no half-publication behind
    assert [i.step for i in list_publications(str(tmp_path))] == [3]


def test_faults_site_and_kind_validation():
    """Satellite: a typo'd PIT_FAULTS drill fails at install naming the
    valid options — never silently injects nothing."""
    with pytest.raises(ValueError, match=r"unknown fault site.*deploy.gate"):
        faults.validate_site("deploy.gat")
    with pytest.raises(ValueError, match="bad PIT_FAULTS clause"):
        faults.parse_spec("engin.dispatch:transient@1")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse_spec("engine.dispatch:transientt@1")
    # the three deploy sites are registered; per-engine suffixes stay valid
    inj = faults.parse_spec(
        "deploy.publish:nan@1;deploy.gate:transient@1;deploy.swap:fatal@1;"
        "engine.dispatch.myrep-infer:slow@1@delay:0")
    assert inj is not None
    # fire() ticks a site ONCE per call (raise + corrupt kinds share the
    # same 1-based call index)
    inj2 = FaultInjector([
        FaultSpec(site="deploy.publish", kind="transient", at=(1,)),
        FaultSpec(site="deploy.publish", kind="nan", at=(2,)),
    ])
    with pytest.raises(InjectedTransientError):
        inj2.fire("deploy.publish", _tree())
    assert np.isnan(inj2.fire("deploy.publish", _tree())["w"])
    assert not np.isnan(inj2.fire("deploy.publish", _tree())["w"])


# -- the admission gate -------------------------------------------------------


def test_gate_layers_reject_independently(reg, no_faults):
    inc = _tree(2.0)
    golden = [np.ones((2, 3), np.float32)]
    gate = AdmissionGate(_infer, golden, inc, quality_tol=0.25, registry=reg)

    ok = gate.check(_tree(2.001))
    assert ok.ok, ok
    # digest: loaded content != manifest
    r = gate.check(_tree(2.001), {"digest": "0" * 64})
    assert (not r.ok) and r.reason == "digest_mismatch"
    # finite scan catches a NaN tree whose digest VERIFIES (the layer
    # separation: provenance is not health)
    nan_tree = {"w": np.float32("nan"), "b": inc["b"]}
    r = gate.check(nan_tree, {"digest": tree_digest(nan_tree)})
    assert (not r.ok) and r.reason == "nonfinite_params"
    # quality: a finite-but-garbage tree deviates by orders of magnitude
    r = gate.check(_tree(200.0))
    assert (not r.ok) and r.reason == "quality"
    # custom quality_fn: lower-is-better scoring with an absolute tolerance
    gate_q = AdmissionGate(
        _infer, golden, inc, quality_tol=0.1,
        quality_fn=lambda out: float(np.mean(np.abs(out))), registry=reg)
    assert gate_q.check(_tree(1.9)).ok          # scores BETTER than incumbent
    r = gate_q.check(_tree(2.2))                # worse by > tol
    assert (not r.ok) and r.reason == "quality"
    # prewarm failure is a gate failure (fail closed)
    def boom(tree):
        raise RuntimeError("compile exploded")

    gate_p = AdmissionGate(_infer, golden, inc, quality_tol=0.25,
                           prewarm=boom, registry=reg)
    r = gate_p.check(_tree(2.001))
    assert (not r.ok) and r.reason == "prewarm_failed"
    # an injected gate fault fails CLOSED, not open
    faults.install(FaultInjector([
        FaultSpec(site="deploy.gate", kind="fatal", at=(1,))]))
    r = gate.check(_tree(2.001))
    assert (not r.ok) and r.reason == "gate_error"


# -- the deployment loop ------------------------------------------------------


def _engine_stack(reg, incumbent, tmp_path, bake_s=0.05, **gate_kw):
    eng = ServingEngine(_infer, incumbent, max_batch=4, name="dep-eng",
                        registry=reg)
    eng.warmup(np.ones((1, 3), np.float32))
    gate = AdmissionGate(_infer, [np.ones((2, 3), np.float32)], incumbent,
                         registry=reg, **gate_kw)
    target = EngineSwapTarget(eng, incumbent, bake_s=bake_s, poll_s=0.01)
    deployer = ModelDeployer(str(tmp_path), gate, target, poll_s=0.02,
                             registry=reg)
    return eng, deployer


def test_deployer_rejects_nan_and_tamper_quarantine_sticky(
        tmp_path, reg, no_faults):
    """The reject drills through the FULL loop: a NaN-corrupted publication
    (PIT_FAULTS machinery — its digest verifies!) and a digest-tampered one
    are both quarantined and NEVER installed; quarantine is sticky for new
    watchers (a restarted process skips the markers on disk)."""
    inc = _tree(2.0)
    eng, deployer = _engine_stack(reg, inc, tmp_path, quality_tol=0.5)
    # publication 2 NaN-corrupts INSIDE publish (digest matches the NaNs)
    faults.install(FaultInjector([
        FaultSpec(site="deploy.publish", kind="nan", at=(2,))]))
    publish_params(str(tmp_path), 10, _tree(2.001))
    publish_params(str(tmp_path), 20, _tree(2.002))   # the NaN one
    p3 = publish_params(str(tmp_path), 30, _tree(2.003))
    _tamper(p3)
    recs = deployer.poll_once()
    assert [(r["action"], r["step"]) for r in recs] == [
        ("swapped", 10), ("rejected", 20), ("rejected", 30)]
    assert recs[1]["reason"] == "nonfinite_params"
    assert recs[2]["reason"] == "digest_mismatch"
    # the engine serves the ONE admitted tree
    out = eng.predict(np.ones((1, 3), np.float32))
    assert np.allclose(out, 2.001)
    # counters label the reasons
    labels = {"deploy": "deploy"}
    assert reg.counter("deploy_rejected_total",
                       labels={**labels, "reason": "nonfinite_params"}
                       ).value == 1
    assert reg.counter("deploy_rejected_total",
                       labels={**labels, "reason": "digest_mismatch"}
                       ).value == 1
    # sticky: markers on disk — a FRESH watcher (process restart) skips both
    assert read_quarantine(p3)["reason"].startswith("digest_mismatch")
    assert [i.step for i in CheckpointWatcher(str(tmp_path)).poll()] == [10]
    assert deployer.poll_once() == []  # and this process never re-attempts
    eng.close()


def test_deployer_min_step_skips_restart_backlog(tmp_path, reg, no_faults):
    """A restarted serving process must not replay (or quarantine!) the
    backlog of publications older than the checkpoint it booted from:
    min_step floors the watcher, and a lazy gate FACTORY resolves on first
    use (the serve CLI hands one over so the golden compile stays off the
    startup path)."""
    inc = _tree(2.0)
    eng = ServingEngine(_infer, inc, max_batch=4, name="dep-min",
                        registry=reg)
    eng.warmup(np.ones((1, 3), np.float32))
    built = []

    def gate_factory():
        built.append(True)
        return AdmissionGate(_infer, [np.ones((2, 3), np.float32)], inc,
                             quality_tol=0.5, registry=reg)

    target = EngineSwapTarget(eng, inc, bake_s=0.02, poll_s=0.01)
    p_old = publish_params(str(tmp_path), 5, _tree(1.0))  # pre-boot history
    publish_params(str(tmp_path), 50, _tree(2.001))
    deployer = ModelDeployer(str(tmp_path), gate_factory, target,
                             poll_s=0.02, registry=reg, min_step=10)
    assert not built  # factory untouched until a publication is processed
    recs = deployer.poll_once()
    assert [(r["action"], r["step"]) for r in recs] == [("swapped", 50)]
    assert built == [True]
    # the old publication was neither deployed nor mislabeled rejected
    assert read_quarantine(p_old) is None
    # the admitted tree installs between micro-batches — poll for it
    deadline = time.monotonic() + 10
    out = None
    while time.monotonic() < deadline:
        out = eng.predict(np.ones((1, 3), np.float32))
        if np.allclose(out, 2.001):
            break
        time.sleep(0.01)
    assert np.allclose(out, 2.001), out
    eng.close()


def test_engine_target_rollback_on_post_swap_slo_burn(tmp_path, no_faults):
    """Post-swap regression on the single-engine target: dispatch faults
    armed AFTER the swap installs burn the SLO during the bake → the target
    re-installs the incumbent and the publication is quarantined."""
    reg = obs.MetricsRegistry()
    inc = _tree(2.0)
    slo = obs.SLO(latency_target_s=5.0, availability_target=0.9,
                  name="deptgt", burn_alert=None, min_samples=3)
    eng = ServingEngine(_infer, inc, max_batch=4, name="dep-rb",
                        registry=reg, slo=slo, dispatch_retries=0)
    eng.warmup(np.ones((1, 3), np.float32))
    gate = AdmissionGate(_infer, [np.ones((2, 3), np.float32)], inc,
                         quality_tol=0.5, registry=reg)
    target = EngineSwapTarget(eng, inc, bake_s=0.8, poll_s=0.01,
                              min_bake_requests=3)
    deployer = ModelDeployer(str(tmp_path), gate, target, poll_s=0.02,
                             registry=reg)
    publish_params(str(tmp_path), 10, _tree(2.01))

    stop = threading.Event()
    lost = []

    def traffic():
        x = np.ones((1, 3), np.float32)
        while not stop.is_set():
            try:
                eng.submit(x).result(timeout=30)
            except Exception as e:
                lost.append(e)  # expected: the faulted dispatches fail
            time.sleep(0.002)

    installed = threading.Event()

    def arm_after_swap():
        # the regression must be strictly POST-swap: watch the served output
        # flip to the candidate tree, then arm the dispatch faults
        deadline = time.monotonic() + 30
        x = np.ones((1, 3), np.float32)
        while time.monotonic() < deadline:
            if deployer.history:
                return  # deployment already finished: the drill failed
            try:
                out = eng.predict(x)
            except Exception:
                time.sleep(0.005)
                continue
            if np.allclose(out, 2.01):
                faults.install(FaultInjector([FaultSpec(
                    site="engine.dispatch.dep-rb", kind="transient",
                    every=1)]))
                installed.set()
                return
            time.sleep(0.005)

    t = threading.Thread(target=traffic, daemon=True)
    w = threading.Thread(target=arm_after_swap, daemon=True)
    t.start()
    w.start()
    recs = deployer.poll_once()
    faults.install(None)
    stop.set()
    t.join(timeout=10)
    assert installed.is_set(), "faults never armed — the drill did not run"
    assert len(recs) == 1 and recs[0]["action"] == "rolled_back", recs
    assert recs[0]["reason"] == "post_swap_regression"
    assert "SLO burn" in recs[0]["detail"]
    # the incumbent tree is serving again (the rollback INSTALLS between
    # micro-batches — poll until the worker adopted it), and the
    # publication is quarantined
    deadline = time.monotonic() + 10
    out = None
    while time.monotonic() < deadline:
        try:
            out = eng.predict(np.ones((1, 3), np.float32))
        except Exception:
            pass
        if out is not None and np.allclose(out, 2.0):
            break
        time.sleep(0.02)
    assert out is not None and np.allclose(out, 2.0), out
    assert list_publications(str(tmp_path)) == []  # quarantined
    assert deployer.stats()["rollbacks"] == 1
    eng.close()


def test_deployer_stop_waits_for_inflight_swap(tmp_path, reg, no_faults):
    """The SIGTERM-drain contract: stop() does not return while a swap is
    mid-flight — the serving surface is wholly on ONE tree afterwards."""
    inc = _tree(2.0)
    gate = AdmissionGate(_infer, [np.ones((2, 3), np.float32)], inc,
                         quality_tol=0.5, registry=reg)
    release = threading.Event()
    swapped = []

    class SlowTarget:
        def swap(self, tree, info):
            release.wait(30)
            swapped.append(info.step)
            return True, None

    deployer = ModelDeployer(str(tmp_path), gate, SlowTarget(), poll_s=0.02,
                             registry=reg).start()
    publish_params(str(tmp_path), 10, _tree(2.001))
    deadline = time.monotonic() + 10
    while not deployer.history and deployer._busy.acquire(blocking=False):
        deployer._busy.release()  # not yet picked up
        assert time.monotonic() < deadline, "deployment never started"
        time.sleep(0.005)
    # the swap is mid-flight: a bounded stop reports the timeout honestly
    assert deployer.stop(timeout_s=0.2) is False
    assert swapped == []
    release.set()
    assert deployer.stop(timeout_s=10) is True
    assert swapped == [10]  # the in-progress swap COMPLETED before exit


# -- trainer + checkpoint satellites ------------------------------------------


def test_trainer_publishes_on_cadence(tmp_path, no_faults):
    """TrainerConfig.publish_dir/publish_every_n_steps: publications land
    atomically on the step cadence with metrics in the manifest; config
    validation requires both halves."""
    import jax
    import jax.numpy as jnp
    import optax

    from perceiver_io_tpu.training import TrainState
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    def train_step(state, batch):
        def loss_fn(p):
            return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), {"loss": loss}

    state = TrainState.create({"w": jnp.ones((3, 1), jnp.float32)},
                              optax.sgd(1e-2), jax.random.key(0))
    rng = np.random.default_rng(0)
    loader = [{"x": rng.normal(size=(4, 3)).astype(np.float32),
               "y": np.ones((4, 1), np.float32)} for _ in range(9)]
    pub_dir = tmp_path / "pub"
    cfg = TrainerConfig(max_steps=9, log_every_n_steps=3,
                        logdir=str(tmp_path / "logs"), use_tensorboard=False,
                        compute_mfu=False, publish_dir=str(pub_dir),
                        publish_every_n_steps=3)
    with Trainer(train_step, None, state, cfg,
                 example_batch=loader[0]) as tr:
        tr.fit(loader)
    infos = list_publications(str(pub_dir))
    assert [i.step for i in infos] == [3, 6, 9]
    tree, manifest = load_publication(infos[-1].path)  # digest-verified
    assert "train_loss" in manifest["val_metrics"]
    assert np.isfinite(np.asarray(tree["w"])).all()
    with pytest.raises(ValueError, match="publish_every_n_steps"):
        TrainerConfig(max_steps=1, publish_dir=str(pub_dir))


def test_checkpoint_digest_sidecar_detects_silent_corruption(tmp_path):
    """Satellite: save() records a content digest; prefer_latest restore
    verifies it and falls back past a step whose restored bytes no longer
    hash to what was saved (silent bit corruption — the case the r9
    truncated-newest fallback cannot see, because the restore SUCCEEDS)."""
    import jax
    import jax.numpy as jnp
    import optax

    from perceiver_io_tpu.training import (
        CheckpointManager,
        TrainState,
        restore_train_state,
    )
    from perceiver_io_tpu.training.checkpoint import DIGESTS_FILE

    tx = optax.sgd(0.1)
    s1 = TrainState.create({"w": jnp.full((2, 2), 1.0)}, tx,
                           jax.random.key(0))
    s2 = s1.replace(step=2, params={"w": jnp.full((2, 2), 2.0)})
    directory = str(tmp_path / "ckpt")
    with CheckpointManager(directory, max_to_keep=2) as mgr:
        mgr.save(1, s1, {"val_loss": 1.0})
        mgr.save(2, s2, {"val_loss": 0.5})
    sidecar = os.path.join(directory, DIGESTS_FILE)
    with open(sidecar) as f:
        digests = json.load(f)
    assert set(digests) == {"1", "2"}

    like = s1
    # intact: the newest step restores and verifies
    restored = restore_train_state(directory, like, prefer_latest=True)
    assert np.allclose(restored.params["w"], 2.0)
    # "corrupt" step 2: its save-time digest no longer matches the bytes a
    # restore returns (stand-in for bit rot in the stored arrays)
    digests["2"] = "0" * 64
    with open(sidecar, "w") as f:
        json.dump(digests, f)
    with pytest.warns(UserWarning, match="digest.*does not match"):
        restored = restore_train_state(directory, like, prefer_latest=True)
    assert np.allclose(restored.params["w"], 1.0)  # fell back to step 1


# -- the end-to-end chaos drill (tier-1, in-process) --------------------------


def _pub_factory(log):
    def factory(spec):
        if spec.get("kind") != "publication":
            raise ValueError(f"unexpected spec {spec!r}")
        tree, _ = load_publication(spec["path"])  # digest-verified
        log.append(spec["path"])
        return tree

    return factory


@pytest.mark.slow  # tier-1 budget (r22 box drift): every layer of this
# e2e keeps its own tier-1 drill above (publish atomicity/fail-soft,
# gate rejection, NaN/tamper quarantine, post-swap rollback, cadence);
# the real-process variant was already slow (test_train_serve_deploy_
# drill_real_process).
def test_fleet_deploy_chaos_e2e(no_faults):
    """THE acceptance drill, tier-1 in-process: a publisher on a cadence +
    a 3-replica fleet under open-loop traffic. >=3 gated swaps complete with
    lost_accepted=0; one PIT-NaN-corrupted and one digest-tampered
    publication are rejected by the gate and NEVER reach a replica (the
    replicas' publication loader logs every path they realize); one
    injected post-swap SLO burn rolls the whole fleet back to the
    incumbent tree."""
    import tempfile

    from perceiver_io_tpu.serving import LocalReplica, ReplicaApp, Router

    reg = obs.MetricsRegistry()
    inc = _tree(2.0)
    # tight availability: by pub6 the SLO window holds seconds of good
    # traffic, and the bake must see the burn cross within its window — at
    # a 1e-3 error budget a handful of post-swap failures crosses 2.0
    slo = obs.SLO(latency_target_s=5.0, availability_target=0.999,
                  name="depfleet", burn_alert=None, min_samples=5)
    loaded_paths = []
    replicas = []
    for i in range(3):
        eng = ServingEngine(_infer, inc, max_batch=4, name=f"dp{i}-infer",
                            registry=reg, slo=slo, dispatch_retries=0)
        app = ReplicaApp({"infer": eng}, inc,
                         params_factory=_pub_factory(loaded_paths),
                         name=f"dp{i}", registry=reg, assume_ready=True)
        replicas.append(LocalReplica(app))
    router = Router(replicas, scrape_interval_s=0.02, registry=reg,
                    name="depfleet")
    router.refresh()

    publish_dir = tempfile.mkdtemp(prefix="deploy_chaos_")
    gate = AdmissionGate(_infer, [np.ones((2, 3), np.float32)], inc,
                         quality_tol=0.5, registry=reg, name="chaos")
    target = RouterSwapTarget(router, bake_s=0.6, poll_s=0.02,
                              min_bake_requests=3)
    deployer = ModelDeployer(publish_dir, gate, target, poll_s=0.03,
                             registry=reg, name="chaos").start()

    stop = threading.Event()
    lost = []
    x1 = np.ones((1, 3), np.float32)

    def traffic():  # open-loop-ish: constant arrivals, never self-throttled
        futs = []
        while not stop.is_set():
            futs.append(router.submit(x1))
            futs = [f for f in futs if not f.done() or _note(f)]
            time.sleep(0.002)
        for f in futs:
            _note_final(f)

    def _note(f):
        try:
            f.result(0)
        except Exception as e:
            lost.append(e)
        return False  # drop from the outstanding list

    def _note_final(f):
        try:
            f.result(30)
        except Exception as e:
            lost.append(e)

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    try:
        # publications 1-3: good trees -> three gated rolling swaps
        faults.install(FaultInjector([
            FaultSpec(site="deploy.publish", kind="nan", at=(4,))]))
        for k in (1, 2, 3):
            publish_params(publish_dir, 10 * k, _tree(2.0 + 1e-3 * k))
        deadline = time.monotonic() + 60
        while len(deployer.history) < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert [r["action"] for r in deployer.history] == ["swapped"] * 3, \
            deployer.history
        # publication 4: NaN-corrupted by the PIT_FAULTS machinery (digest
        # verifies!); publication 5: digest-tampered (staged + renamed in,
        # so the live watcher can only observe the tampered payload)
        publish_params(publish_dir, 40, _tree(2.004))
        _publish_tampered(publish_dir, 50, _tree(2.005))
        deadline = time.monotonic() + 60
        while len(deployer.history) < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert [r["action"] for r in deployer.history[3:]] == \
            ["rejected", "rejected"], deployer.history
        assert deployer.history[3]["reason"] == "nonfinite_params"
        assert deployer.history[4]["reason"] == "digest_mismatch"

        # publication 6: good tree, but post-swap dispatch faults on dp0
        # burn its SLO during the bake -> the FLEET rolls back
        armed = threading.Event()

        def arm_after_swap():
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if replicas[0].scrape().get("params_version", 0) >= 4:
                    faults.install(FaultInjector([FaultSpec(
                        site="engine.dispatch.dp0-infer", kind="transient",
                        every=1)]))
                    armed.set()
                    return
                time.sleep(0.005)

        w = threading.Thread(target=arm_after_swap, daemon=True)
        w.start()
        publish_params(publish_dir, 60, _tree(2.006))
        deadline = time.monotonic() + 90
        while len(deployer.history) < 6 and time.monotonic() < deadline:
            time.sleep(0.05)
        faults.install(None)
        assert armed.is_set(), "post-swap faults never armed"
        assert len(deployer.history) == 6, deployer.history
        assert deployer.history[5]["action"] == "rolled_back", \
            deployer.history[5]
    finally:
        faults.install(None)
        stop.set()
        t.join(timeout=30)
        deployer.stop(60)

    stats = deployer.stats()
    assert stats["swaps"] == 3
    assert stats["rollbacks"] == 1
    # the rolled-back publication is quarantined too (sticky, like every
    # other rejection — it must never be re-attempted)
    assert stats["rejected"] == {"nonfinite_params": 1, "digest_mismatch": 1,
                                 "post_swap_regression": 1}
    # the rejected publications NEVER reached a replica: every path the
    # replicas realized is a step-10/20/30/60 publication
    bad = {"step_00000040", "step_00000050"}
    assert not bad & {os.path.basename(p) for p in loaded_paths}
    # the WHOLE fleet rolled back to the last admitted tree (publication
    # 3): every replica serves it once the rollback install lands
    deadline = time.monotonic() + 15
    on_pub3 = 0
    while time.monotonic() < deadline and on_pub3 < 6:
        try:
            out = router.predict(x1, timeout=30)
        except Exception:
            time.sleep(0.02)
            continue
        if np.allclose(out, 2.003):
            on_pub3 += 1
        else:
            on_pub3 = 0
            time.sleep(0.02)
    assert on_pub3 >= 6, f"fleet still serving a non-rollback tree: {out}"
    # ZERO lost accepted requests across 3 swaps + 2 rejects + 1 rollback
    assert not lost, f"lost accepted requests: {lost[:3]}"
    router.close()
    for r in replicas:
        r.app.close()


# -- serve CLI wiring ---------------------------------------------------------


@pytest.mark.slow  # tier-1 budget (r13, ~64 s margin at 806 s): trains its
# own tiny MLM. The deployment-loop logic stays tier-1 in
# test_fleet_deploy_chaos_e2e + test_deployer_rejects_nan_and_tamper...,
# the stop-waits-for-inflight-swap drain contract in
# test_deployer_stop_waits_for_inflight_swap, and the bench contract in
# test_cli.py::test_deploy_bench_cpu_gated_swaps_zero_loss; this adds only
# the serve.py flag wiring ride.
def test_serve_watch_checkpoints_single_mode(tmp_path, no_faults):
    """cli/serve.py --watch_checkpoints: a good publication hot-swaps into
    the live server, a NaN one is quarantined, and the drain path stops the
    deployment loop cleanly (stdin stays open until both happened, pinning
    the loop's liveness DURING serving)."""
    import glob
    import sys

    from perceiver_io_tpu.cli import serve, train_mlm
    from perceiver_io_tpu.data.tokenizer import load_tokenizer
    from perceiver_io_tpu.inference import load_mlm_checkpoint

    run_dir = train_mlm.main([
        "--synthetic", "--logdir", str(tmp_path / "logs" / "watch"),
        "--root", str(tmp_path / "cache"),
        "--num_latents", "4", "--num_latent_channels", "16",
        "--num_encoder_layers", "1",
        "--num_self_attention_layers_per_block", "1",
        "--num_cross_attention_heads", "2",
        "--num_self_attention_heads", "2", "--dtype", "float32",
        "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", "32", "--vocab_size", "120",
        "--max_steps", "2", "--log_every_n_steps", "1",
    ])
    ckpt = os.path.join(run_dir, "checkpoints")
    tok = glob.glob(str(tmp_path / "cache" / "*tokenizer*.json"))[0]
    _, params, _ = load_mlm_checkpoint(ckpt, load_tokenizer(tok))

    import jax

    watch_dir = tmp_path / "pub"
    good = jax.tree.map(lambda a: np.asarray(a) * 1.0005, params)
    publish_params(str(watch_dir), 50, good, {"val_loss": 1.0})
    nan_pub = publish_params(
        str(watch_dir), 60,
        jax.tree.map(lambda a: np.full_like(np.asarray(a), np.nan)
                     if np.issubdtype(np.asarray(a).dtype, np.floating)
                     else np.asarray(a), params))

    r_fd, w_fd = os.pipe()
    results, errors = [], []

    def run_serve():
        old = sys.stdin
        sys.stdin = os.fdopen(r_fd, "r")
        try:
            results.extend(serve.main([
                "--checkpoint", ckpt, "--tokenizer", tok, "--stdin",
                "--max_batch", "4", "--k", "2", "--no_warmup",
                "--watch_checkpoints", str(watch_dir),
                "--publish_poll_s", "0.05", "--rolling_bake_s", "0.05",
                "--gate_quality_tol", "0.5",
            ]))
        except BaseException as e:  # pragma: no cover
            errors.append(e)
        finally:
            sys.stdin.close()
            sys.stdin = old

    t = threading.Thread(target=run_serve, daemon=True)
    t.start()
    writer = os.fdopen(w_fd, "w")
    writer.write("a [MASK] b\n")
    writer.flush()
    # hold admission open until the loop processed BOTH publications: the
    # NaN one is quarantined on disk, the good one is serving (gauge)
    gauge = obs.get_registry().gauge("deploy_current_step",
                                     labels={"deploy": "serve"})
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if read_quarantine(nan_pub) is not None and gauge.value == 50:
            break
        time.sleep(0.05)
    writer.close()  # EOF -> drain -> deployer.stop -> exit
    t.join(timeout=120)
    assert not errors, errors
    assert read_quarantine(nan_pub)["reason"].startswith("nonfinite_params")
    assert gauge.value == 50, "the good publication never swapped in"
    assert len(results) == 1 and len(results[0]["fills"]) == 1
    assert len(results[0]["fills"][0]) == 2


# -- the real-process train+serve drill (slow) --------------------------------


_TRAINER_SCRIPT = """
import sys
from perceiver_io_tpu.utils.platform import ensure_cpu_only
ensure_cpu_only()
import numpy as np, jax
from perceiver_io_tpu.models.presets import tiny_mlm
from perceiver_io_tpu.training import (TrainState, OptimizerConfig,
                                       make_optimizer, make_mlm_steps)
from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

publish_dir, logdir = sys.argv[1], sys.argv[2]
vocab, seq = 503, 64
model = tiny_mlm(vocab_size=vocab, max_seq_len=seq)
ids0 = np.zeros((1, seq), np.int32)
params = model.init({"params": jax.random.key(0),
                     "masking": jax.random.key(1)}, ids0, ids0 == 0)["params"]
tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-4))
state = TrainState.create(params, tx, jax.random.key(2))
train_step, _, _ = make_mlm_steps(model, schedule)
rng = np.random.default_rng(0)
loader = [{"token_ids": rng.integers(3, vocab, (4, seq)).astype(np.int32),
           "pad_mask": np.zeros((4, seq), bool)} for _ in range(12)]
cfg = TrainerConfig(max_steps=12, log_every_n_steps=3, logdir=logdir,
                    use_tensorboard=False, compute_mfu=False,
                    publish_dir=publish_dir, publish_every_n_steps=3)
with Trainer(train_step, None, state, cfg, example_batch=loader[0]) as tr:
    tr.fit(loader)
print("TRAINER_DONE", flush=True)
"""


@pytest.mark.slow  # real processes end to end; the gated-swap/reject/
# rollback logic stays tier-1 in test_fleet_deploy_chaos_e2e, the publish
# cadence in test_trainer_publishes_on_cadence, the CLI wiring in
# test_serve_watch_checkpoints_single_mode
def test_train_serve_deploy_drill_real_process(tmp_path):
    """A REAL trainer process publishing on a cadence (with PIT_FAULTS
    NaN-corrupting its second publication) + 3 supervised replica processes
    behind a router: every clean publication flows through the gate into a
    rolling fleet swap, the NaN one and a test-tampered one are rejected and
    never reach any replica, and open-loop traffic loses zero accepted
    requests throughout."""
    import subprocess
    import sys

    from perceiver_io_tpu.models.presets import tiny_mlm
    from perceiver_io_tpu.serving import ReplicaSupervisor, Router

    publish_dir = tmp_path / "pub"
    publish_dir.mkdir()
    env = dict(os.environ)
    env["PIT_FAULTS"] = "deploy.publish:nan@2"
    env.setdefault("JAX_PLATFORMS", "cpu")
    trainer = subprocess.Popen(
        [sys.executable, "-c", _TRAINER_SCRIPT, str(publish_dir),
         str(tmp_path / "logs")],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )

    # meanwhile: the serving fleet (same tiny preset => same tree family)
    import jax

    reg = obs.get_registry()
    vocab, seq = 503, 64
    model = tiny_mlm(vocab_size=vocab, max_seq_len=seq)
    ids0 = np.zeros((1, seq), np.int32)
    params = model.init({"params": jax.random.key(0),
                         "masking": jax.random.key(1)},
                        ids0, ids0 == 0)["params"]

    def gathered_apply(p, token_ids, pad_mask, pos):
        logits, _ = model.apply({"params": p}, token_ids, pad_mask,
                                masking=False, deterministic=True,
                                positions=pos)
        return logits

    sup = ReplicaSupervisor(
        count=3, extra_args=["--preset", "tiny", "--cpu", "--no_warmup"],
        cpu=True)
    try:
        clients = sup.start()
        sup.wait_ready(timeout_s=600.0)
        with Router(clients, name="drill", registry=reg,
                    scrape_interval_s=0.1) as router:
            router.refresh()
            gate = AdmissionGate(
                gathered_apply,
                (ids0, np.zeros((1, seq), bool), np.zeros((1, 2), np.int32)),
                params, quality_tol=0.5, registry=reg, name="drill")
            target = RouterSwapTarget(router, bake_s=0.2, poll_s=0.05)
            deployer = ModelDeployer(str(publish_dir), gate, target,
                                     poll_s=0.2, registry=reg,
                                     name="drill").start()
            stop = threading.Event()
            lost = []

            def traffic():
                rng = np.random.default_rng(1)
                while not stop.is_set():
                    ids = rng.integers(3, vocab, (1, seq)).astype(np.int32)
                    try:
                        router.predict(
                            ids, np.zeros((1, seq), bool),
                            np.zeros((1, 2), np.int32), timeout=120)
                    except Exception as e:
                        lost.append(e)
                    time.sleep(0.02)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()
            try:
                out, _ = trainer.communicate(timeout=600)
                assert trainer.returncode == 0, out[-3000:]
                assert "TRAINER_DONE" in out
                # trainer published steps 3,6,9,12; #2 (step 6) is the NaN
                # one. Add a digest-tampered publication from the test side
                # (staged + renamed in: the live watcher must only ever see
                # the tampered payload, never a torn mid-tamper npz).
                _publish_tampered(str(publish_dir), 100,
                                  jax.tree.map(
                                      lambda a: np.asarray(a) * 1.001,
                                      params))
                deadline = time.monotonic() + 300
                while (len(deployer.history) < 5
                       and time.monotonic() < deadline):
                    time.sleep(0.2)
            finally:
                stop.set()
                t.join(timeout=60)
                deployer.stop(120)
            actions = {r["step"]: r["action"] for r in deployer.history}
            assert actions.get(6) == "rejected", deployer.history
            assert actions.get(100) == "rejected", deployer.history
            swapped = [s for s, a in actions.items() if a == "swapped"]
            assert sorted(swapped) == [3, 9, 12], deployer.history
            stats = deployer.stats()
            assert stats["swaps"] == 3 and stats["rejected"] == {
                "nonfinite_params": 1, "digest_mismatch": 1}
            # every replica is on the final published tree (version: one
            # bump per rolling swap), and no accepted request was lost
            for c in clients:
                assert c.scrape().get("params_version") == 3, c.scrape()
            assert not lost, f"lost accepted requests: {lost[:3]}"
            router.drain(60)
    finally:
        trainer.kill()
        sup.stop()
