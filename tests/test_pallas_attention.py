"""Fused Pallas attention: parity vs the XLA einsum path (interpret mode on
CPU; the same kernel compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.ops.attention import MultiHeadAttention, _dot_product_attention
from perceiver_io_tpu.ops.pallas_attention import (
    fused_attention,
    seq_parallel_fused_attention,
)


def _rand(rng, *shape, dtype=jnp.float32):
    return jnp.asarray(rng.normal(0, 1, shape), dtype=dtype)


def _xla(q, k, v, pad_mask=None):
    return _dot_product_attention(
        q, k, v, pad_mask, None, 0.0, None, True
    )


@pytest.fixture
def lane_aligned():
    """Force the COMPILED lane alignment while kernels run interpreted, so
    the fuzz classes resolve blocks exactly as hardware does (the
    pallas_attention._TEST_ALIGNMENT hook)."""
    import perceiver_io_tpu.ops.pallas_attention as pa

    pa._TEST_ALIGNMENT = 128
    yield
    pa._TEST_ALIGNMENT = None


@pytest.mark.parametrize("masked", [False, True])
@pytest.mark.parametrize("t,s", [(16, 64), (8, 30)])
def test_matches_xla_path(rng, masked, t, s):
    b, h, d = 2, 2, 8
    q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
    pad_mask = jnp.asarray(rng.random((b, s)) < 0.3) if masked else None
    out = fused_attention(q, k, v, pad_mask, kv_block_size=16)
    ref = _xla(q, k, v, pad_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_kv_streaming_multiblock(rng):
    """Online softmax across many KV blocks equals single-pass softmax."""
    b, t, s, h, d = 1, 4, 128, 1, 8
    q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
    blocked = fused_attention(q, k, v, kv_block_size=16)  # 8 blocks
    single = fused_attention(q, k, v, kv_block_size=128)  # 1 block
    ref = _xla(q, k, v)
    np.testing.assert_allclose(np.asarray(blocked), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(blocked), np.asarray(single), atol=1e-6)


def test_padding_path(rng):
    """S with no good divisor gets padded with masked keys — results equal."""
    b, t, s, h, d = 2, 4, 17, 1, 8
    q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
    pad_mask = jnp.zeros((b, s), bool).at[:, -3:].set(True)
    out = fused_attention(q, k, v, pad_mask, kv_block_size=4)  # pads 17 → 20
    ref = _xla(q, k, v, pad_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_padding_path_fully_masked_row(rng):
    """Kernel-padded keys must stay excluded even when a row is fully masked
    (the uniform softmax covers only the real S keys, as on the XLA path)."""
    b, t, s, h, d = 1, 4, 17, 1, 8
    q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
    pad_mask = jnp.ones((b, s), bool)
    out = fused_attention(q, k, v, pad_mask, kv_block_size=4)  # pads 17 → 20
    ref = _xla(q, k, v, pad_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_block_size_selection():
    from perceiver_io_tpu.ops.pallas_attention import _kv_block_size

    # TPU alignment: blocks must be multiples of 128 (or the full dim)
    assert _kv_block_size(4096, 512, 128) == 512
    assert _kv_block_size(512, 512, 128) == 512  # single full block
    assert _kv_block_size(1000, 512, 128) == 0  # no aligned divisor → pad/full
    assert _kv_block_size(1024, 768, 128) == 512  # largest aligned divisor
    # interpret mode: any divisor goes
    assert _kv_block_size(30, 16, 1) == 15
    assert _kv_block_size(17, 16, 1) == 0


def test_auto_q_block_resolution():
    """The q_block auto-default (None) resolves AFTER s_blk, inside its
    measured-safe regime ONLY: resolved s_blk·d within the 256x512 compile
    boundary AND T dividing the big block exactly (PERF.md r3 sweep — both
    guards are load-bearing; the (t_blk 1024, s_blk 512, d 512) combo is a
    measured scoped-VMEM OOM)."""
    import jax.numpy as jnp

    from perceiver_io_tpu.ops import pallas_attention as pa

    def resolve(t, s, d, kv_block=pa.DEFAULT_KV_BLOCK, q_block=None):
        q = jnp.zeros((1, t, 1, d), jnp.bfloat16)
        k = jnp.zeros((1, s, 1, d), jnp.bfloat16)
        bias = jnp.zeros((1, s), jnp.float32)
        _, _, _, _, t_blk, s_blk, _ = pa._prepare_blocks(
            q, k, k, bias, kv_block, q_block, interpret=False
        )
        return t_blk, s_blk

    # flow encoder-cross-like (S has a 256 divisor): safe → big query block
    t_blk, s_blk = resolve(2048, 182528, 512)
    assert (t_blk, s_blk) == (1024, 256)
    # same T/S but s_blk resolves to 512 (S divisible by 512): s_blk·d over
    # the measured boundary at d=512 → stays at the 512 default
    t_blk, s_blk = resolve(2048, 8192, 512)
    assert (s_blk, t_blk) == (512, 512)
    # shallow heads keep the bump at s_blk 512 (s_blk·d = 512·128 is safe)
    t_blk, s_blk = resolve(2048, 8192, 128)
    assert (s_blk, t_blk) == (512, 1024)
    # T not divisible by the big block (would pad / widen the full-residency
    # fallback — unmeasured) → 512 default
    t_blk, _ = resolve(1152, 182528, 128)
    assert t_blk != 1024
    # head dims past the sweep's measured range (d > 512) stay on the 512
    # default even when s_blk·d is small — the 1024-row query block + f32
    # accumulator at d=1024 is an unmeasured VMEM regime
    t_blk, s_blk = resolve(2048, 182528, 1024, kv_block=128)
    assert s_blk * 1024 <= pa.LONG_KV_SAFE_SBLK_D and t_blk == 512
    # explicit q_block_size is always honored
    t_blk, _ = resolve(2048, 182528, 512, q_block=512)
    assert t_blk == 512


def test_auto_kv_block_resolution():
    """``kv_block_size=None`` widens KV streaming for shallow heads at long S
    (PERF.md r3 kv sweep) and caps the q bump by the measured probs-area
    compile boundary — deep heads and short S keep the 512 default."""
    import jax.numpy as jnp

    from perceiver_io_tpu.ops import pallas_attention as pa

    def resolve(t, s, d):
        q = jnp.zeros((1, t, 1, d), jnp.bfloat16)
        k = jnp.zeros((1, s, 1, d), jnp.bfloat16)
        bias = jnp.zeros((1, s), jnp.float32)
        _, _, _, _, t_blk, s_blk, _ = pa._prepare_blocks(
            q, k, k, bias, None, None, interpret=False
        )
        return t_blk, s_blk

    # long-context MLM cross shape: d=16 streams 2048-wide KV blocks
    assert resolve(256, 131072, 16) == (256, 2048)
    # ... and the auto q bump is CAPPED by the probs-area boundary
    # (t 1024 × s 2048 is the measured OOM; kv 2048 + q 512 measured fastest)
    assert resolve(1024, 131072, 16) == (512, 2048)
    # mid-depth heads (ImageNet 8-head): 2048-wide KV requested (r5 re-sweep:
    # 2048 wins 3-12% across in-8h and the TPU-width long-context shapes);
    # 50176 = 1792·28 has no aligned divisor at 2048 itself, so the divisor
    # rule lands on 1792 (≥ half the request — no padding needed)
    assert resolve(512, 50176, 128) == (512, 1792)
    # deep heads keep 512 — flow encoder-cross resolution is UNCHANGED
    # (s_blk 256 from S's divisor structure, q bump still applies)
    assert resolve(2048, 182528, 512) == (1024, 256)
    # short S resolves to its full dim / divisor exactly as an explicit
    # request would (no widening possible at S = 512)
    assert resolve(256, 512, 16)[1] == 512
    # mid-S shallow shapes widen too: flow-self (d=64, S=2048) streams the
    # whole KV in one block per grid step (measured 1.34 → 0.98 ms)
    assert resolve(2048, 2048, 64) == (512, 2048)
    # S with no lane-aligned divisor INSIDE the widened full-residency
    # window keeps the tuned 512 padding path (a widened block would pull
    # s_blk = s = 7000 full residency into unmeasured probs territory) ...
    t_blk, s_blk = resolve(256, 7000, 16)
    assert s_blk <= 512
    # ... but beyond that window (s > 4·kv) the pad-to-block path is safe
    # and keeps the widened block
    assert resolve(256, 12000, 16)[1] == 2048
    # the guard evaluates against the POST-shrink kv: t=904 forces the probs
    # loop to halve 2048 -> 1024, and 2816 has a divisor for 2048 (1408) but
    # none for 1024 — the shrunk block's full-residency window would pull
    # s_blk = 2816 (2.43M-element probs, past the measured OOM) without it
    t_blk, s_blk = resolve(904, 2816, 16)
    assert t_blk * s_blk <= pa.LONG_KV_SAFE_PROBS * 2  # old default path
    assert s_blk <= 512
    # seq-parallel shard-local slices resolve on the LOCAL length
    assert resolve(256, 131072 // 8, 16) == (256, 2048)
    # a query count with no aligned divisor takes the full-residency
    # t_blk = t fallback — the kv widening must shrink so t_blk·s_blk stays
    # inside the measured probs-area boundary (904·2048 would exceed it)
    assert resolve(904, 131072, 16) == (904, 1024)
    # divisible T is unaffected by that bound (t_blk 512 resolves normally)
    assert resolve(1024, 131072, 16) == (512, 2048)

    def resolve_q(t, s, d, q_block):
        q = jnp.zeros((1, t, 1, d), jnp.bfloat16)
        k = jnp.zeros((1, s, 1, d), jnp.bfloat16)
        bias = jnp.zeros((1, s), jnp.float32)
        _, _, _, _, t_blk, s_blk, _ = pa._prepare_blocks(
            q, k, k, bias, None, q_block, interpret=False
        )
        return t_blk, s_blk

    # an EXPLICIT big query block bypasses the auto q-bump guard, so the kv
    # widening itself must shrink to keep t_blk·s_blk inside the boundary
    # (1024×2048 is the measured OOM; 1024×1024 compiles — measured 8.17 ms)
    assert resolve_q(1024, 131072, 16, q_block=1024) == (1024, 1024)


def test_fully_masked_row_uniform(rng):
    """A fully padded sequence softmaxes to uniform — XLA-path parity, no NaN."""
    b, t, s, h, d = 2, 4, 8, 1, 4
    q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
    pad_mask = jnp.zeros((b, s), bool).at[0].set(True)  # row 0 fully masked
    out = fused_attention(q, k, v, pad_mask, kv_block_size=8)
    ref = _xla(q, k, v, pad_mask)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_bfloat16(rng):
    b, t, s, h, d = 2, 8, 32, 2, 8
    q, k, v = (_rand(rng, b, n, h, d, dtype=jnp.bfloat16) for n in (t, s, s))
    out = fused_attention(q, k, v, kv_block_size=16)
    ref = _xla(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
    )


def test_gradients_match_xla(rng):
    b, t, s, h, d = 2, 4, 32, 2, 8
    q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
    pad_mask = jnp.asarray(rng.random((b, s)) < 0.25)

    def loss_fused(q, k, v):
        return jnp.sum(fused_attention(q, k, v, pad_mask, kv_block_size=16) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(_xla(q, k, v, pad_mask) ** 2)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for gf, gx in zip(g_fused, g_xla):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gx), atol=1e-5)


def test_fully_masked_row_zero_qk_grads(rng):
    """XLA-path parity: a fully padded sequence contributes no q/k gradient
    (masking is where-style, not a differentiable additive bias)."""
    b, t, s, h, d = 2, 4, 8, 1, 4
    q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
    pad_mask = jnp.zeros((b, s), bool).at[0].set(True)  # batch row 0 fully masked

    def loss(q, k, v):
        return jnp.sum(fused_attention(q, k, v, pad_mask, kv_block_size=8) ** 2)

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(lambda q, k, v: jnp.sum(_xla(q, k, v, pad_mask) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(dq[0]), 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(dk[0]), 0.0, atol=1e-7)
    for g, gr in zip((dq, dk, dv), ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-5)


def test_module_dispatch_parity(rng):
    """MultiHeadAttention(attn_impl='pallas') == attn_impl='xla' with the same
    params (the production dispatch path, reference ``model.py:66-74``)."""
    b, t, s = 2, 8, 24
    x_q = _rand(rng, b, t, 16)
    x_kv = _rand(rng, b, s, 12)
    pad_mask = jnp.asarray(rng.random((b, s)) < 0.2)

    mha_xla = MultiHeadAttention(num_q_channels=16, num_kv_channels=12, num_heads=4)
    mha_pallas = MultiHeadAttention(
        num_q_channels=16, num_kv_channels=12, num_heads=4, attn_impl="pallas"
    )
    params = mha_xla.init(jax.random.key(0), x_q, x_kv)["params"]
    out_xla = mha_xla.apply({"params": params}, x_q, x_kv, pad_mask=pad_mask)
    out_pallas = mha_pallas.apply({"params": params}, x_q, x_kv, pad_mask=pad_mask)
    np.testing.assert_allclose(
        np.asarray(out_pallas), np.asarray(out_xla), atol=1e-5
    )


@pytest.mark.parametrize("t,s,q_blk", [(16, 32, 4), (12, 32, 4), (7, 32, 3)])
def test_query_blocking_matches_xla(rng, t, s, q_blk):
    """Multi-query-block grid (t_blk < T), including the pad-then-slice path
    when T has no usable divisor (t=7, q_blk=3 → pads to 9)."""
    q = _rand(rng, 2, t, 2, 8)
    k = _rand(rng, 2, s, 2, 8)
    v = _rand(rng, 2, s, 2, 8)
    pad = jnp.asarray(rng.random((2, s)) < 0.2)
    out = fused_attention(q, k, v, pad, kv_block_size=16, q_block_size=q_blk)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_xla(q, k, v, pad)), atol=2e-5
    )


def test_query_blocking_gradients(rng):
    q = _rand(rng, 1, 12, 1, 8)
    k = _rand(rng, 1, 24, 1, 8)
    v = _rand(rng, 1, 24, 1, 8)

    def loss_fused(q, k, v):
        return jnp.sum(fused_attention(q, k, v, kv_block_size=8, q_block_size=4) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(_xla(q, k, v) ** 2)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fused, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_auto_dispatch_threshold(rng, monkeypatch):
    """'auto' picks the fused kernel iff the KV stream is long (>= 4096),
    the heads are shallow, AND the backend is a real TPU (off-TPU the kernel
    would run in interpreter mode)."""
    import perceiver_io_tpu.ops.pallas_attention as pa
    from perceiver_io_tpu.ops import attention as attn_mod

    calls = []
    real = pa.fused_attention

    def spy(*args, **kwargs):
        calls.append(args[1].shape[1])
        kwargs["interpret"] = True  # test runs on CPU
        return real(*args, **kwargs)

    monkeypatch.setattr(pa, "fused_attention", spy)

    mha = MultiHeadAttention(num_q_channels=16, num_kv_channels=16, num_heads=2)
    assert mha.attn_impl == "auto"
    short = _rand(rng, 1, 8, 16)
    long_kv = _rand(rng, 1, attn_mod.AUTO_PALLAS_MIN_KV, 16)
    params = mha.init(jax.random.key(0), short, short)["params"]

    # off-TPU: always xla, even at long KV
    mha.apply({"params": params}, short, long_kv)
    assert calls == []

    monkeypatch.setattr(attn_mod.jax, "default_backend", lambda: "tpu")
    mha.apply({"params": params}, short, short)
    assert calls == []  # S=8 -> xla
    mha.apply({"params": params}, short, long_kv)
    assert calls == [attn_mod.AUTO_PALLAS_MIN_KV]


class TestPackedLatentAttention:
    """Packed-heads small-latent kernel: parity vs the XLA path (fwd + grads).

    End-to-end it currently loses to XLA+bf16-logits at the MLM shapes
    (PERF.md) — kept as an opt-in ('packed') with exact parity coverage.
    """

    def _args(self, rng, B=3, T=16, S=24, H=4, D=8, dtype=jnp.float32):
        E = H * D
        q = jnp.asarray(rng.normal(0, 1, (B, T, E)), dtype)
        k = jnp.asarray(rng.normal(0, 1, (B, S, E)), dtype)
        v = jnp.asarray(rng.normal(0, 1, (B, S, E)), dtype)
        return q, k, v, H

    def _ref(self, q, k, v, h, pad_mask):
        from perceiver_io_tpu.ops.attention import _dot_product_attention

        b, t, e = q.shape
        s = k.shape[1]
        d = e // h
        out = _dot_product_attention(
            q.reshape(b, t, h, d), k.reshape(b, s, h, d), v.reshape(b, s, h, d),
            pad_mask, None, 0.0, None, True,
        )
        return out.reshape(b, t, e)

    @pytest.mark.parametrize("masked", [False, True])
    def test_forward_parity(self, rng, masked):
        from perceiver_io_tpu.ops.pallas_attention import packed_latent_attention

        q, k, v, h = self._args(rng)
        pad = jnp.asarray(rng.random((3, 24)) < 0.3) if masked else None
        out = packed_latent_attention(q, k, v, h, pad_mask=pad, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, k, v, h, pad)), atol=2e-6
        )

    def test_grad_parity(self, rng):
        from perceiver_io_tpu.ops.pallas_attention import packed_latent_attention

        q, k, v, h = self._args(rng)
        pad = jnp.asarray(rng.random((3, 24)) < 0.3)

        def loss_packed(q, k, v):
            out = packed_latent_attention(q, k, v, h, pad_mask=pad, interpret=True)
            return jnp.sum(jnp.sin(out))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(self._ref(q, k, v, h, pad)))

        gp = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)

    def test_validation(self, rng):
        from perceiver_io_tpu.ops.pallas_attention import packed_latent_attention

        q, k, v, h = self._args(rng)
        with pytest.raises(ValueError, match="divisible"):
            packed_latent_attention(q, k, v, 5, interpret=True)
        with pytest.raises(ValueError, match="packed"):
            packed_latent_attention(q[0], k, v, h, interpret=True)

    def test_mha_packed_impl(self, rng):
        """attn_impl='packed' through the module matches the XLA impl."""
        from perceiver_io_tpu.ops.attention import MultiHeadAttention

        xq = jnp.asarray(rng.normal(0, 1, (2, 8, 32)), jnp.float32)
        xkv = jnp.asarray(rng.normal(0, 1, (2, 12, 32)), jnp.float32)
        pad = jnp.asarray(rng.random((2, 12)) < 0.3)
        kw = dict(num_q_channels=32, num_kv_channels=32, num_heads=4)
        m_ref = MultiHeadAttention(**kw, attn_impl="xla")
        params = m_ref.init(jax.random.key(0), xq, xkv)["params"]
        m_packed = MultiHeadAttention(**kw, attn_impl="packed")
        o1 = m_ref.apply({"params": params}, xq, xkv, pad_mask=pad)
        o2 = m_packed.apply({"params": params}, xq, xkv, pad_mask=pad)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=2e-6)

    def test_mha_packed_rejects_oversize(self, rng):
        from perceiver_io_tpu.ops.attention import MultiHeadAttention

        xq = jnp.asarray(rng.normal(0, 1, (1, 2048, 32)), jnp.float32)
        m = MultiHeadAttention(num_q_channels=32, num_kv_channels=32,
                               num_heads=4, attn_impl="packed")
        with pytest.raises(ValueError, match="packed"):
            m.init(jax.random.key(0), xq, xq)

    def test_fully_masked_row_grads_match_xla(self, rng):
        """A fully padded example must give zero dq/dk (XLA where-parity)."""
        from perceiver_io_tpu.ops.pallas_attention import packed_latent_attention

        q, k, v, h = self._args(rng, B=2)
        pad = jnp.zeros((2, 24), bool).at[1].set(True)  # example 1 all-masked

        def loss_packed(q, k, v):
            out = packed_latent_attention(q, k, v, h, pad_mask=pad, interpret=True)
            return jnp.sum(jnp.sin(out))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(self._ref(q, k, v, h, pad)))

        gp = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gp, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-6,
                err_msg=f"d{name} mismatch on fully-masked row",
            )
        np.testing.assert_allclose(np.asarray(gp[0][1]), 0.0, atol=1e-7)
        np.testing.assert_allclose(np.asarray(gp[1][1]), 0.0, atol=1e-7)

    def test_vmem_budget_rejects_oversize(self):
        from perceiver_io_tpu.ops.pallas_attention import packed_fits_vmem

        assert packed_fits_vmem(256, 512, 64)          # MLM cross
        assert not packed_fits_vmem(1024, 1024, 512)   # backward can't fit


# -- sequence-parallel fused attention ---------------------------------------


class TestSeqParallelFusedAttention:
    """seq_parallel_fused_attention == fused_attention with KV sharded over
    the mesh: each device touches only its S/n slice, stats merge via
    pmax/psum, gradients flow through the shard_map'd custom VJP."""

    def _inputs(self, rng, B=2, T=16, S=96, H=2, D=8):
        q = jnp.asarray(rng.normal(0, 1, (B, T, H, D)).astype(np.float32))
        k = jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
        v = jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
        return q, k, v

    def test_forward_matches_single_device(self, rng):
        from perceiver_io_tpu.parallel import make_mesh

        q, k, v = self._inputs(rng)
        pad = jnp.zeros((2, 96), bool).at[0, -13:].set(True)
        ref = fused_attention(q, k, v, pad_mask=pad)

        mesh = make_mesh(dp=2, tp=1, sp=4)
        out = seq_parallel_fused_attention(
            q, k, v, pad_mask=pad, mesh=mesh, axis="seq"
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_forward_with_batch_axis(self, rng):
        from perceiver_io_tpu.parallel import make_mesh

        q, k, v = self._inputs(rng)
        ref = fused_attention(q, k, v)
        mesh = make_mesh(dp=2, tp=1, sp=4)
        out = seq_parallel_fused_attention(
            q, k, v, mesh=mesh, axis="seq", batch_axis="data"
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_fully_padded_shard(self, rng):
        """A shard whose keys are ALL padding must contribute nothing."""
        from perceiver_io_tpu.parallel import make_mesh

        q, k, v = self._inputs(rng)
        pad = jnp.zeros((2, 96), bool).at[:, -24:].set(True)  # last shard
        ref = fused_attention(q, k, v, pad_mask=pad)
        mesh = make_mesh(dp=2, tp=1, sp=4)
        out = seq_parallel_fused_attention(
            q, k, v, pad_mask=pad, mesh=mesh, axis="seq"
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize("dp,tp,sp,batch_axis,head_axis", [
        (1, 1, 8, None, None),
        # replicated non-seq axes of size > 1: the transpose convention
        # double-counted these before the round-2 fix (grads came back
        # exactly dp*tp times too large while the forward stayed correct)
        (2, 1, 4, None, None),
        (1, 2, 4, None, None),
        (2, 2, 2, "data", None),
        # head (tensor-parallel) sharding: each device keeps H/tp heads
        # inside the shard_map instead of all-gathering them
        (1, 2, 4, None, "model"),
        (2, 2, 2, "data", "model"),
    ])
    def test_gradients_match_single_device(self, rng, dp, tp, sp, batch_axis,
                                           head_axis):
        from perceiver_io_tpu.parallel import make_mesh

        q, k, v = self._inputs(rng, S=64)
        pad = jnp.zeros((2, 64), bool).at[1, -9:].set(True)
        mesh = make_mesh(dp=dp, tp=tp, sp=sp)

        def loss_ref(q, k, v):
            return jnp.sum(fused_attention(q, k, v, pad_mask=pad) ** 2)

        def loss_sp(q, k, v):
            return jnp.sum(
                seq_parallel_fused_attention(
                    q, k, v, pad_mask=pad, mesh=mesh, axis="seq",
                    batch_axis=batch_axis, head_axis=head_axis,
                ) ** 2
            )

        ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        got = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(got, ref):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=1e-4)

    def test_head_sharded_forward_and_validation(self, rng):
        from perceiver_io_tpu.parallel import make_mesh

        q, k, v = self._inputs(rng)  # H=2
        pad = jnp.zeros((2, 96), bool).at[0, -13:].set(True)
        mesh = make_mesh(dp=2, tp=2, sp=2)
        ref = fused_attention(q, k, v, pad_mask=pad)
        out = seq_parallel_fused_attention(
            q, k, v, pad_mask=pad, mesh=mesh, axis="seq",
            batch_axis="data", head_axis="model",
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

        q3, k3, v3 = self._inputs(rng, H=3)  # 3 % 2 != 0
        with pytest.raises(ValueError, match="head count"):
            seq_parallel_fused_attention(
                q3, k3, v3, mesh=mesh, axis="seq", head_axis="model"
            )

    def test_under_jit_with_sharded_inputs(self, rng):
        """The intended deployment: jit + pre-sharded global arrays."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from perceiver_io_tpu.parallel import make_mesh

        q, k, v = self._inputs(rng)
        mesh = make_mesh(dp=2, tp=1, sp=4)
        ref = fused_attention(q, k, v)

        q_s = jax.device_put(q, NamedSharding(mesh, P("data")))
        k_s = jax.device_put(k, NamedSharding(mesh, P("data", "seq")))
        v_s = jax.device_put(v, NamedSharding(mesh, P("data", "seq")))
        fn = jax.jit(
            lambda q, k, v: seq_parallel_fused_attention(
                q, k, v, mesh=mesh, axis="seq", batch_axis="data"
            )
        )
        out = fn(q_s, k_s, v_s)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_uneven_kv_rejected(self, rng):
        from perceiver_io_tpu.parallel import make_mesh

        q, k, v = self._inputs(rng, S=90)  # 90 % 4 != 0
        mesh = make_mesh(dp=2, tp=1, sp=4)
        with pytest.raises(ValueError, match="divisible by the 'seq' mesh axis"):
            seq_parallel_fused_attention(q, k, v, mesh=mesh, axis="seq")


class TestRandomGeometryFuzz:
    """Seeded property fuzz over random (B, T, S, H, D) — VERDICT r4 item 8.

    Both resolution bugs on record (the 131k flash-CE row-divisor pathology
    and the awkward-S guard ordering, PERF.md r3) lived in block-RESOLUTION
    code yet were only ever caught by hardware measurement, because interpret
    mode resolves with alignment=1 and so never takes the divisor/padding/
    full-residency branches hardware takes. The `_TEST_ALIGNMENT` hook forces
    the compiled lane alignment while the kernel itself runs interpreted:
    every geometry here resolves its blocks exactly as on TPU, then checks
    numeric parity vs the XLA path, forward AND gradients.
    """

    N_GEOMETRIES = 60

    @staticmethod
    def _draw_dim(rng, lo, hi):
        """Bias toward resolution-interesting structure, not just uniforms:
        lane multiples, powers of two, 'awkward' odd-multiples (no aligned
        divisor above the unit), and plain uniforms."""
        mode = int(rng.integers(0, 4))
        if mode == 0:
            return int(rng.integers(lo, hi + 1))
        if mode == 1:  # lane multiple
            return 128 * int(rng.integers(max(1, lo // 128), max(2, hi // 128) + 1))
        if mode == 2:  # power of two
            cands = [x for x in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                                 2048, 4096) if lo <= x <= hi]
            return int(rng.choice(cands)) if cands else int(rng.integers(lo, hi + 1))
        # awkward: a small aligned factor times a prime-ish odd number
        primes = [7, 11, 13, 23, 31, 61, 127, 251]
        base = int(rng.choice([1, 2, 32, 128]))
        p = int(rng.choice(primes))
        val = base * p
        return int(min(max(val, lo), hi))

    @pytest.mark.slow  # fuzz sweep: the deterministic fwd/grad parity
    # cases above cover the guard boundaries in tier-1
    def test_fuzz_forward_and_grads_match_xla(self, lane_aligned):
        import perceiver_io_tpu.ops.pallas_attention as pa

        rng = np.random.default_rng(20260801)
        checked_branches = set()
        for case in range(self.N_GEOMETRIES):
            b = int(rng.integers(1, 3))
            h = int(rng.integers(1, 3))
            t = self._draw_dim(rng, 1, 640)
            s = self._draw_dim(rng, 1, 3100)
            d = int(rng.choice([16, 32, 64, 100, 128, 256]))
            q, k, v = (_rand(rng, b, n, h, d) for n in (t, s, s))
            pad = None
            if rng.integers(0, 2):
                pad = jnp.asarray(rng.integers(0, 2, (b, s)), bool)
                # keep at least one live key per example: a fully-masked row
                # has its own dedicated tests and NaN-free contract
                pad = pad.at[:, 0].set(False)

            # record which resolution branch this geometry lands in, so the
            # run provably covers them all (asserted below)
            s_blk = pa._kv_block_size(
                s, pa._auto_kv_block(s, d, t, 128, None), 128)
            checked_branches.add(
                ("divisor" if s_blk else
                 ("full" if s <= 4 * pa._auto_kv_block(s, d, t, 128, None)
                  else "padded"),
                 "tdiv" if pa._kv_block_size(t, pa.DEFAULT_Q_BLOCK, 128)
                 else ("tfull" if t <= 2 * pa.DEFAULT_Q_BLOCK else "tpad")))

            out = fused_attention(q, k, v, pad_mask=pad, interpret=True)
            ref = _xla(q, k, v, pad)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=5e-5,
                err_msg=f"fwd mismatch at case {case}: "
                        f"B{b} T{t} S{s} H{h} D{d} masked={pad is not None}")

            if case % 3 == 0:  # gradients on a third of the draws (cost)
                cot = _rand(rng, *out.shape)

                def loss_fused(q, k, v):
                    return jnp.sum(
                        fused_attention(q, k, v, pad_mask=pad, interpret=True)
                        * cot)

                def loss_xla(q, k, v):
                    return jnp.sum(_xla(q, k, v, pad) * cot)

                gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
                gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
                for name, a, bb in zip("qkv", gf, gx):
                    np.testing.assert_allclose(
                        np.asarray(a), np.asarray(bb), atol=1e-4,
                        err_msg=f"d{name} mismatch at case {case}: "
                                f"B{b} T{t} S{s} H{h} D{d}")
        # the fuzz is only worth its runtime if it actually visits the
        # branches hardware takes
        s_branches = {br[0] for br in checked_branches}
        t_branches = {br[1] for br in checked_branches}
        assert {"divisor", "full", "padded"} <= s_branches, s_branches
        assert {"tdiv", "tfull"} <= t_branches, t_branches

    def test_fuzz_resolution_invariants(self, lane_aligned):
        """Pure-resolution sweep (no kernel run — hundreds of geometries):
        every resolved block triple must be tiling-legal and free of the
        tiny-sequential-grid pathology by construction."""
        import perceiver_io_tpu.ops.pallas_attention as pa

        rng = np.random.default_rng(7)
        for _ in range(400):
            t = self._draw_dim(rng, 1, 4096)
            s = self._draw_dim(rng, 1, 200_000)
            d = int(rng.choice([16, 32, 64, 128, 256, 512]))
            explicit = rng.integers(0, 2)
            kv_req = int(rng.choice([256, 512, 1024, 2048])) if explicit else None
            q_req = int(rng.choice([256, 512, 1024])) if rng.integers(0, 2) else None

            # eval_shape: the resolution + padding decisions trace without
            # materializing the (up to 400 MB) zero arrays — this keeps the
            # 400-geometry sweep at seconds, not minutes
            q = jax.ShapeDtypeStruct((1, t, 1, d), jnp.float32)
            k = jax.ShapeDtypeStruct((1, s, 1, d), jnp.float32)
            bias = jax.ShapeDtypeStruct((1, s), jnp.float32)
            blks = {}

            def probe(q, k, v, bias):
                qq, kk, vv, bb, t_blk, s_blk, t_pad = pa._prepare_blocks(
                    q, k, v, bias, kv_req, q_req, interpret=True)
                blks.update(t_blk=t_blk, s_blk=s_blk, t_pad=t_pad)
                return qq, kk

            qq, kk = jax.eval_shape(probe, q, k, k, bias)
            t_blk, s_blk, t_pad = blks["t_blk"], blks["s_blk"], blks["t_pad"]
            s_total, t_total = kk.shape[2], qq.shape[2]
            # tiling legality: every block divides its (possibly padded) axis
            # and is lane-aligned unless it IS the full axis
            assert s_total % s_blk == 0 and t_total % t_blk == 0
            assert s_blk == s_total or s_blk % 128 == 0, (s, s_blk, s_total)
            assert t_blk == t_total or t_blk % 128 == 0, (t, t_blk, t_total)
            assert t_total == t + t_pad
            # no tiny-grid pathology: the sequential KV grid may not exceed
            # ~2x what the requested block implies (the 131k bug shape ran
            # 12,290 steps where ~77 were needed)
            req = kv_req or pa._auto_kv_block(s, d, t, 128, q_req)
            assert s_total // s_blk <= max(2 * -(-s // req), 1), (
                s, d, kv_req, s_blk, s_total)
            # the auto q-bump only inside its measured-safe envelope
            if q_req is None and t_blk > pa.DEFAULT_Q_BLOCK and t > 2 * pa.DEFAULT_Q_BLOCK:
                assert t_blk == pa.LONG_KV_Q_BLOCK
                assert s_blk * d <= pa.LONG_KV_SAFE_SBLK_D
                assert t_blk * s_blk <= pa.LONG_KV_SAFE_PROBS
                assert d <= pa.LONG_KV_MAX_D


class TestSeqParallelGeometryFuzz:
    """Random-geometry sweep for the SEQUENCE-PARALLEL kernel path
    (VERDICT r4 item 8 extended to the shard_map wrapper): shard-local
    S/n slices resolve their own blocks, and the pmax/psum statistic merge
    must agree with the single-device kernel — forward AND gradients — at
    lane-aligned resolution, for pad masks that straddle shard boundaries."""

    N_GEOMETRIES = 12

    @pytest.mark.slow  # fuzz sweep: tests/test_sharding.py::
    # test_pallas_sp_step_matches_xla_and_shards_kv stays tier-1
    def test_fuzz_sp_matches_single_device(self, lane_aligned):
        from perceiver_io_tpu.parallel import make_mesh

        mesh = make_mesh(dp=2, tp=1, sp=4)
        rng = np.random.default_rng(20260803)
        for case in range(self.N_GEOMETRIES):
            b = 2
            h = int(rng.integers(1, 3))
            t = int(rng.choice([8, 64, 129, 256]))
            # S must divide sp=4; sizes chosen so shard-local S/4 exercises
            # full-dim, divisor, and (at 6500/4=1625) the padding path
            s = int(rng.choice([128, 512, 1024, 4096, 6500]))
            d = int(rng.choice([16, 64, 128]))
            q = jnp.asarray(rng.normal(0, 1, (b, t, h, d)).astype(np.float32))
            k = jnp.asarray(rng.normal(0, 1, (b, s, h, d)).astype(np.float32))
            v = jnp.asarray(rng.normal(0, 1, (b, s, h, d)).astype(np.float32))
            pad = None
            if rng.integers(0, 2):
                pad = jnp.asarray(rng.integers(0, 2, (b, s)), bool)
                pad = pad.at[:, 0].set(False)

            ref = fused_attention(q, k, v, pad_mask=pad, interpret=True)
            out = seq_parallel_fused_attention(
                q, k, v, pad_mask=pad, mesh=mesh, axis="seq", interpret=True)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=5e-5,
                err_msg=f"sp fwd mismatch case {case}: B{b} T{t} S{s} H{h} D{d}")

            if case % 3 == 0:
                cot = jnp.asarray(
                    rng.normal(0, 1, ref.shape).astype(np.float32))

                def loss_sp(q, k, v):
                    return jnp.sum(seq_parallel_fused_attention(
                        q, k, v, pad_mask=pad, mesh=mesh, axis="seq",
                        interpret=True) * cot)

                def loss_ref(q, k, v):
                    return jnp.sum(fused_attention(
                        q, k, v, pad_mask=pad, interpret=True) * cot)

                gs = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
                gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
                for name, a, bb in zip("qkv", gs, gr):
                    np.testing.assert_allclose(
                        np.asarray(a), np.asarray(bb), atol=1e-4,
                        err_msg=f"sp d{name} mismatch case {case}: "
                                f"B{b} T{t} S{s} H{h} D{d}")
