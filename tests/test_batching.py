"""Continuous batching for Perceiver-AR decode: the slotted cache arena +
one-batched-dispatch scheduler (`inference/batching.py`).

The correctness spine is STREAM IDENTITY: every continuation served out of
the shared arena — greedy or sampled, crossing episode boundaries, admitted
and retired mid-sweep, resumed off a resident slot — must be bit-identical
to the r18 per-session engine serving the same request alone. The
position-folded sampling keys make that a hard equality, not a
distribution-level claim. Around it: incremental parity through the arena
install path (2e-5 vs a dense forward), the admission-wave program family
(closed and AOT-warmable), retire-reason accounting on the session store,
and the serving drill: router generate through a batched replica with a
mid-stream kill — content-lossless, lost_accepted=0.
"""

import threading
import time

import jax
import numpy as np
import pytest

from perceiver_io_tpu.inference.batching import ArenaSession, ContinuousBatcher
from perceiver_io_tpu.inference.generate import (
    ARGenerator,
    GenerateSessionStore,
    SamplingConfig,
)
from perceiver_io_tpu.models.presets import tiny_ar
import perceiver_io_tpu.obs as obs

VOCAB = 503


@pytest.fixture(scope="module")
def tiny():
    model = tiny_ar()
    ids = np.zeros((1, 64), np.int32)
    params = model.init({"params": jax.random.key(0)}, ids, ids == 0)[
        "params"]
    return model, params


@pytest.fixture(scope="module")
def oracle(tiny):
    model, params = tiny
    return ARGenerator(model, params, max_seq_len=64, chunk=4, name="b-orc")


@pytest.fixture(scope="module")
def batcher(tiny):
    model, params = tiny
    # capacity pinned: growth (and its extra per-(width, slots) compile
    # family) is pinned by test_continuous_admit_retire_mid_sweep
    bat = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                            slots=4, max_slots=4, name="b-arena")
    yield bat
    bat.close()


def _fan_out(bat, cases):
    """Run every (prefix, max_new, sampling) case concurrently through the
    batcher; returns tokens per case in order."""
    got = [None] * len(cases)
    errs = []

    def one(i):
        prefix, max_new, sampling = cases[i]
        try:
            got[i], _ = bat.generate(list(prefix), max_new, sampling)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=one, args=(i,), daemon=True)
               for i in range(len(cases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[0]
    return got


# -- stream identity: the correctness spine -----------------------------------


@pytest.mark.slow  # tier-1 budget (r21): admission-churn + slot lifecycle
# stay tier-1 in test_continuous_admit_retire_mid_sweep and the stream-
# observability reconciliation tests (tests/test_stream_obs.py, which run
# the same batcher end to end); the 8-stream token-identity oracle sweep
# runs in the full tier
def test_batched_streams_match_per_session_oracle(tiny, oracle, batcher,
                                                  rng):
    """8 concurrent mixed streams (greedy + sampled, episode-crossing
    budgets, more streams than slots so admission churns) are each
    bit-identical to the per-session engine serving them alone. The band
    stays inside widths 16/31 — two full episode families compile here,
    which is where the wall of this test goes; width 46 adds nothing but a
    third compile family."""
    cases = []
    for i in range(8):
        plen = int(rng.integers(2, 10))
        prefix = [int(t) for t in rng.integers(3, VOCAB, plen)]
        max_new = int(rng.integers(1, 22))  # crosses the 16->31 boundary
        temp = float(rng.choice([0.0, 0.8]))
        cases.append((prefix, max_new,
                      SamplingConfig(temperature=temp, top_k=16, seed=i)))
    want = [oracle.generate(list(p), mn, s)[0] for p, mn, s in cases]
    got = _fan_out(batcher, cases)
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, f"stream {i} diverged: {g} vs {w}"
    # the sweep exercised continuous admission: slots are scarcer than
    # streams, so placements churned rather than running a fixed cohort
    stats = batcher.stats()
    assert stats["admitted"] >= 8
    assert stats["dispatches"] > 0


def test_arena_session_adoption_skips_prefill(tiny, oracle, batcher, rng):
    """A follow-up on the returned ArenaSession adopts the resident slot
    (ZERO further prefix encodes) and continues the identical stream the
    per-session engine produces across the same split."""
    prefix = [int(t) for t in rng.integers(3, VOCAB, 7)]
    sampling = SamplingConfig(temperature=0.8, top_k=16, seed=41)
    # 4+4 stays inside the width-16 episode: adoption must not re-encode
    a, ses = batcher.generate(prefix, 4, sampling)
    assert isinstance(ses, ArenaSession) and ses.seq == prefix + a
    o1, os1 = oracle.generate(list(prefix), 4, sampling)
    assert a == o1
    prefills_before = batcher._m_prefills.value
    b, _ = batcher.generate(prefix + a, 4, sampling, session=ses)
    assert batcher._m_prefills.value == prefills_before  # adopted, no encode
    o2, _ = oracle.generate(prefix + o1, 4, sampling, session=os1)
    assert b == o2
    # a diverged prefix must NOT be trusted: fresh encode instead
    other = [int(t) for t in rng.integers(3, VOCAB, 7)]
    c, _ = batcher.generate(other, 3, sampling, session=ses)
    assert batcher._m_prefills.value > prefills_before
    assert c == oracle.generate(list(other), 3, sampling)[0]


def test_arena_parity_peek_logits_vs_dense(tiny, batcher, rng):
    """Incremental parity THROUGH the arena path: the resident slot's
    next-token logits after a generate equal a dense full-prefix forward
    within 2e-5 (f32) — the install + batched-step pipeline preserves the
    per-session cache algebra exactly."""
    import jax.numpy as jnp

    model, params = tiny
    prefix = [int(t) for t in rng.integers(3, VOCAB, 6)]
    toks, ses = batcher.generate(prefix, 5, SamplingConfig())  # greedy
    assert ses is not None
    peek = batcher.peek_logits(ses)
    assert peek is not None
    seq = prefix + toks
    w = ses.width
    cap = model.num_latents
    ids = np.zeros((1, w), np.int32)
    ids[0, :len(seq)] = seq
    pad = np.zeros((1, w), bool)
    pad[0, len(seq):] = True
    dense = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids), jnp.asarray(pad)),
        np.float32)
    row = (len(seq) - 1) - (w - min(cap, w))
    err = float(np.max(np.abs(peek - dense[0, row])))
    assert err < 2e-5, f"arena parity error {err}"


def test_streamed_chunks_still_flow(tiny, batcher, rng):
    """An on_chunk consumer still receives the per-chunk frames (pos /
    steps / chunk_ms / batched) and their concatenation equals the final
    return — the no-consumer fast path must not leak into streaming."""
    prefix = [int(t) for t in rng.integers(3, VOCAB, 5)]
    frames = []
    toks, _ = batcher.generate(
        prefix, 6, SamplingConfig(temperature=0.8, top_k=16, seed=9),
        on_chunk=lambda t, info: frames.append((t, info)))
    assert [t for ts, _ in frames for t in ts] == toks and len(toks) == 6
    for _, info in frames:
        assert {"pos", "steps", "chunk_ms", "batched"} <= set(info)


# -- the scheduler: continuous admission, growth, lifecycle -------------------


def test_continuous_admit_retire_mid_sweep(tiny, rng):
    """A sweep with 4x more streams than slots completes with every stream
    placed (admissions wait at chunk boundaries, never starve) and the
    arena sized within its power-of-two cap."""
    model, params = tiny
    bat = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                            slots=2, max_slots=4, name="b-churn")
    try:
        cases = []
        for i in range(16):
            prefix = [int(t) for t in rng.integers(3, VOCAB, 4)]
            cases.append((prefix, 6,
                          SamplingConfig(temperature=0.8, top_k=16,
                                         seed=100 + i)))
        got = _fan_out(bat, cases)
        assert all(len(g) == 6 for g in got)
        stats = bat.stats()
        assert stats["admitted"] >= 16
        assert stats["retired"] >= 16
        assert 0 < stats["slot_occupancy_mean"] <= 1
        # demand outran 2 slots: the width-16 arena doubled to the cap
        assert stats["slots"] <= 4
        # lifecycle rides the same compiled batcher: close() rejects new
        # work instead of hanging callers on a dead dispatcher
        bat.close()
        with pytest.raises(RuntimeError):
            bat.generate([3, 7], 2, SamplingConfig())
    finally:
        bat.close()


def test_warmup_closes_the_program_family(tiny):
    """warmup() compiles the ENTIRE (width x wave-bucket) admission family
    plus the batched decode program — afterwards a mixed burst triggers
    zero new compiles (the finite-program-family contract)."""
    model, params = tiny
    bat = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                            slots=2, max_slots=2, name="b-warm")
    try:
        n = bat.warmup(widths=[16])
        keys = set(bat._programs)
        assert ("decode", 16, 2) in keys
        for k_n in (1, 2, 4, 8):
            assert ("prefill", 16, k_n) in keys
            assert (f"install_rows{k_n}", 16, 2) in keys
        assert n == 9  # 4 buckets x (prefill + install) + 1 decode
        # serve a burst against the warmed width: no program beyond the
        # warmed family may appear
        cases = [([3 + i, 7], 4, SamplingConfig(seed=i)) for i in range(5)]
        _fan_out(bat, cases)
        assert set(bat._programs) == keys
    finally:
        bat.close()


@pytest.mark.slow  # coverage retained: test_warmup_closes_the_program_family
# pins the family the cache persists tier-1, and tests/test_aot_cache.py
# pins the ExecutableCache round-trip mechanics; this drill only composes
# the two (a second compile family's wall for a composition check)
def test_warmup_aot_cache_round_trip(tiny, tmp_path):
    """With compile_cache set, a second batcher warms the same family from
    disk (fingerprint hits, no recompiles) — zero-recompile restarts."""
    model, params = tiny
    reg1 = obs.MetricsRegistry()
    bat1 = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                             slots=2, max_slots=2, name="b-aot1",
                             registry=reg1, compile_cache=str(tmp_path))
    try:
        n1 = bat1.warmup(widths=[16])
    finally:
        bat1.close()
    stored = list(tmp_path.rglob("*"))
    assert stored, "warmup persisted nothing to the executable cache"
    reg2 = obs.MetricsRegistry()
    bat2 = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                             slots=2, max_slots=2, name="b-aot2",
                             registry=reg2, compile_cache=str(tmp_path))
    try:
        assert bat2.warmup(widths=[16]) == n1
        hits = [m.value for m in reg2.instruments_by_key().values()
                if m.name == "aot_cache_hits_total"]
        assert hits and sum(hits) >= n1 - 1  # prefills re-execute, all load
    finally:
        bat2.close()


# -- the session store: retire-reason accounting ------------------------------


def test_store_retire_reason_counters_and_release_hook():
    """Every exit path is labeled: overwrite/overflow -> evicted, explicit
    remove -> finished, clear (replica death) -> killed — and the on_evict
    hook sees each dropped session exactly once."""
    reg = obs.MetricsRegistry()
    released = []
    store = GenerateSessionStore(max_sessions=2, registry=reg, name="t",
                                 on_evict=lambda s, r: released.append(
                                     (s.seq[0], r)))

    class FakeSession:
        def __init__(self, seq):
            self.seq = seq

    def count(reason):
        return sum(m.value for m in reg.instruments_by_key().values()
                   if m.name == "generate_sessions_retired_total"
                   and m.label_dict.get("reason") == reason)

    a, b, c = FakeSession([1]), FakeSession([2]), FakeSession([3])
    store.put("a", a)
    store.put("b", b)
    store.put("a", FakeSession([10]))          # overwrite -> evicted
    store.put("c", c)                          # FIFO overflow pops "a"
    assert count("evicted") == 2
    assert store.remove("b", "finished") is True
    assert store.remove("b") is False          # already gone: no double count
    assert count("finished") == 1
    store.clear()                              # replica death wipe
    assert count("killed") == 1
    assert sorted(released) == [(1, "evicted"), (2, "finished"),
                                (3, "killed"), (10, "evicted")]


# -- serving integration: the batched replica under chaos ---------------------


def test_batched_replica_router_chaos_drill(tiny, oracle, rng):
    """The r19 kill drill THROUGH the arena: router generate against
    replicas whose engine is the ContinuousBatcher; the pinned replica is
    killed mid-stream; the stream reroutes, re-encodes from the accepted
    prefix on the survivor's arena, and the assembled continuation equals
    the uninterrupted per-session oracle exactly — lost_accepted=0 by
    content through the batched path."""
    from perceiver_io_tpu.inference.engine import ServingEngine
    from perceiver_io_tpu.serving.replica import LocalReplica, ReplicaApp
    from perceiver_io_tpu.serving.router import Router

    model, params = tiny
    shared = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                               slots=4, name="b-fleet")

    def apply_fn(p, token_ids, pad_mask):
        return model.apply({"params": p}, token_ids, pad_mask)

    reps = []
    for name in ("b0", "b1"):
        eng = ServingEngine(apply_fn, params, name=f"{name}-inf",
                            max_batch=2)
        reps.append(LocalReplica(ReplicaApp(
            {"infer": eng}, params, name=name, assume_ready=True,
            generator=shared)))
    by_name = {r.name: r for r in reps}
    router = Router(reps, name="b-chaos", scrape_interval_s=0.05)
    time.sleep(0.12)
    try:
        prefix = [int(t) for t in rng.integers(3, VOCAB, 9)]
        want, _ = oracle.generate(list(prefix), 7, SamplingConfig(
            temperature=0.8, top_k=16, seed=11))

        got = []
        killed = {"name": None}

        def on_tokens(toks, frame):
            got.extend(toks)
            if len(got) >= 4 and killed["name"] is None:
                for name, r in by_name.items():
                    if r.app._gen_active > 0:
                        killed["name"] = name
                        r.kill()

        res = router.generate(prefix, session="bdrill", max_new=7,
                              temperature=0.8, top_k=16, seed=11,
                              on_tokens=on_tokens)
        assert killed["name"] is not None, "the kill never landed"
        assert res["tokens"] == want, "diverged across the kill"
        assert got == want
        assert res["reroutes"] >= 1
        assert int(router._m_gen_failed.value) == 0  # lost_accepted=0
        # the replica reports its arena aggregates for autoscale/debug
        surv = by_name[res["replica"]]
        status = surv.app.status()
        assert status["decode_batching"]["dispatches"] > 0
    finally:
        router.close()
        for r in reps:
            r.app.close()
        shared.close()


# -- quantized decode: the weight stream must not touch the token stream -----


def test_quantized_arena_stream_identity(tiny, oracle, rng):
    """int8w decode (r24): the quantized arena serves streams bit-identical
    to the quantized per-session engine (the scheduler never sees the
    weight format), and on the tiny preset int8's logit perturbation is
    small enough that GREEDY argmaxes still match the f32 oracle exactly —
    the serving-level parity that matters. (Sampled top-k picks are NOT
    cross-checked against f32: temperature reshapes the softmax enough
    that a ~2e-2 logit perturbation legitimately flips draws.) The
    prefix/budget band stays inside the width-16 episode: one compile
    family per arm."""
    model, params = tiny
    cases = []
    for i in range(4):
        plen = int(rng.integers(2, 5))
        prefix = [int(t) for t in rng.integers(3, VOCAB, plen)]
        temp = 0.0 if i % 2 == 0 else 0.8
        cases.append((prefix, int(rng.integers(3, 8)),
                      SamplingConfig(temperature=temp, top_k=16, seed=i)))
    seq8 = ARGenerator(model, params, max_seq_len=64, chunk=4,
                       quantize="int8", name="q8-seq")
    bat8 = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                             slots=2, max_slots=2, quantize="int8",
                             name="q8-arena")
    try:
        assert bat8.quantize == "int8" and seq8.quantize == "int8"
        from perceiver_io_tpu import quant

        assert quant.is_quantized(seq8.params)
        want = [seq8.generate(list(p), mn, s)[0] for p, mn, s in cases]
        got = _fan_out(bat8, cases)
        for i, (g, w) in enumerate(zip(got, want)):
            assert g == w, f"int8 stream {i} diverged: {g} vs {w}"
        for i, (p, mn, s) in enumerate(cases):
            if s.temperature != 0.0:
                continue
            w = oracle.generate(list(p), mn, s)[0]
            assert got[i] == w, f"int8 greedy vs f32 {i}: {got[i]} vs {w}"
    finally:
        bat8.close()


@pytest.mark.slow  # coverage retained: test_quantized_arena_stream_identity
# pins the quantized seq==batched identity tier-1 on int8; this is the same
# assertion on the grouped-int4 tree (whose f32 divergence is expected —
# 4-bit weights on a random tiny model move argmaxes)
def test_int4_arena_matches_int4_sequential(tiny, rng):
    model, params = tiny
    cases = []
    for i in range(3):
        prefix = [int(t) for t in rng.integers(3, VOCAB, 3)]
        cases.append((prefix, 5,
                      SamplingConfig(temperature=0.8, top_k=16, seed=i)))
    seq4 = ARGenerator(model, params, max_seq_len=64, chunk=4,
                       quantize="int4", name="q4-seq")
    bat4 = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                             slots=2, max_slots=2, quantize="int4",
                             name="q4-arena")
    try:
        assert seq4.group_size == bat4.group_size and seq4.group_size
        want = [seq4.generate(list(p), mn, s)[0] for p, mn, s in cases]
        got = _fan_out(bat4, cases)
        assert got == want
    finally:
        bat4.close()


# -- the perf contract (slow: the tier-1 signal is the bench's JSON line) -----


@pytest.mark.slow  # coverage retained: test_batched_streams_match_per_session
# _oracle pins stream identity tier-1 and tools/decode_batching_bench.py is
# the measured A/B (2.1x median on the r20 CPU box, occupancy 0.90); this
# drill re-runs a shortened sweep and asserts a conservative floor
def test_decode_batching_ab_floor(tiny):
    """Shortened same-process interleaved A/B: batched aggregate tokens/s
    must beat per-session chains by a clear margin at concurrency (the
    bench's own defaults demonstrate the 2x acceptance; this floor guards
    against structural regressions, not scheduler noise)."""
    import argparse

    import tools.decode_batching_bench as ab

    ns = argparse.Namespace(
        dry=False, cpu=False, streams=96, concurrency=32, chunk=4,
        slots=16, pairs=3, mean_new=24, max_new_cap=12,
        prefix_lens="2,3,4", stagger_s=0.002, temperature=0.8, top_k=16,
        seed=0)
    sched = ab._schedule(ns, vocab=VOCAB, max_seq_len=64)
    model, params = tiny
    sampling = SamplingConfig(temperature=0.8, top_k=16, seed=0)
    seq = ARGenerator(model, params, max_seq_len=64, chunk=4, name="ab-s")
    bat = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                            slots=16, max_slots=16, name="ab-b")
    try:
        ab._run_arm(seq, sched, sampling, ns.concurrency)   # warm
        ab._run_arm(bat, sched, sampling, ns.concurrency)
        speedups = []
        for p in range(ns.pairs):
            order = ("bat", "seq") if p % 2 == 0 else ("seq", "bat")
            rates = {}
            toks = {}
            for arm in order:
                gen = bat if arm == "bat" else seq
                wall, total, res = ab._run_arm(gen, sched, sampling,
                                               ns.concurrency)
                rates[arm] = total / wall
                toks[arm] = res
            assert toks["bat"] == toks["seq"]  # identity rides the A/B
            speedups.append(rates["bat"] / rates["seq"])
        median = sorted(speedups)[len(speedups) // 2]
        # under the conftest's 8-virtual-device CPU partitioning the ratio
        # compresses vs the standalone bench (2.1x there, ~1.4x here) — the
        # floor guards batched-must-clearly-beat-sequential structurally,
        # not the acceptance number (that is the bench's own JSON record)
        assert median >= 1.15, f"batched speedup regressed: {speedups}"
    finally:
        bat.close()
