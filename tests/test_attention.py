"""Parity tests for attention primitives against torch (CPU) ground truth.

We verify our MultiHeadAttention reproduces torch.nn.MultiheadAttention
(embed_dim=q channels, kdim=vdim=kv channels, batch_first) — the exact native
op the reference wraps (reference model.py:59-74) — by copying weights across
frameworks and comparing outputs. MLP/LayerNorm likewise.
"""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from perceiver_io_tpu.ops.attention import (
    MLP,
    CrossAttention,
    CrossAttentionLayer,
    MultiHeadAttention,
    SelfAttention,
)

B, T, S, E, K, H = 3, 5, 11, 16, 24, 4


def _np(x):
    return np.asarray(x, dtype=np.float32)


def make_torch_mha():
    torch.manual_seed(0)
    return torch.nn.MultiheadAttention(
        embed_dim=E, num_heads=H, kdim=K, vdim=K, batch_first=True
    )


def mha_params_from_torch(t_mha):
    """Map torch MHA weights into our flax param tree."""
    sd = {k: v.detach().numpy() for k, v in t_mha.state_dict().items()}
    b_in = sd["in_proj_bias"]
    return {
        "q_proj": {"kernel": sd["q_proj_weight"].T, "bias": b_in[:E]},
        "k_proj": {"kernel": sd["k_proj_weight"].T, "bias": b_in[E : 2 * E]},
        "v_proj": {"kernel": sd["v_proj_weight"].T, "bias": b_in[2 * E :]},
        "out_proj": {"kernel": sd["out_proj.weight"].T, "bias": sd["out_proj.bias"]},
    }


@pytest.mark.parametrize("use_pad_mask", [False, True])
def test_mha_matches_torch(use_pad_mask, rng):
    x_q = rng.standard_normal((B, T, E)).astype(np.float32)
    x_kv = rng.standard_normal((B, S, K)).astype(np.float32)
    pad = np.zeros((B, S), dtype=bool)
    if use_pad_mask:
        pad[0, -3:] = True
        pad[2, -1:] = True

    t_mha = make_torch_mha()
    with torch.no_grad():
        t_out, _ = t_mha(
            torch.tensor(x_q),
            torch.tensor(x_kv),
            torch.tensor(x_kv),
            key_padding_mask=torch.tensor(pad) if use_pad_mask else None,
        )

    mod = MultiHeadAttention(num_q_channels=E, num_kv_channels=K, num_heads=H)
    params = {"params": jax.tree.map(jnp.asarray, mha_params_from_torch(t_mha))}
    j_out = mod.apply(params, x_q, x_kv, pad_mask=jnp.asarray(pad) if use_pad_mask else None)

    np.testing.assert_allclose(_np(j_out), t_out.numpy(), atol=1e-5)


def test_mha_attn_mask(rng):
    x_q = rng.standard_normal((B, T, E)).astype(np.float32)
    x_kv = rng.standard_normal((B, S, K)).astype(np.float32)
    attn_mask = np.zeros((T, S), dtype=bool)
    attn_mask[:, S // 2 :] = True  # queries may not look at second half

    t_mha = make_torch_mha()
    with torch.no_grad():
        t_out, _ = t_mha(
            torch.tensor(x_q),
            torch.tensor(x_kv),
            torch.tensor(x_kv),
            attn_mask=torch.tensor(attn_mask),
        )

    mod = MultiHeadAttention(num_q_channels=E, num_kv_channels=K, num_heads=H)
    params = {"params": jax.tree.map(jnp.asarray, mha_params_from_torch(t_mha))}
    j_out = mod.apply(params, x_q, x_kv, attn_mask=jnp.asarray(attn_mask))
    np.testing.assert_allclose(_np(j_out), t_out.numpy(), atol=1e-5)


def test_mlp_matches_torch(rng):
    x = rng.standard_normal((B, T, E)).astype(np.float32)

    torch.manual_seed(1)
    ln = torch.nn.LayerNorm(E)
    l1 = torch.nn.Linear(E, E)
    l2 = torch.nn.Linear(E, E)
    with torch.no_grad():
        t_out = l2(torch.nn.functional.gelu(l1(ln(torch.tensor(x)))))

    params = {
        "params": {
            "norm": {"scale": jnp.asarray(ln.weight.detach().numpy()),
                     "bias": jnp.asarray(ln.bias.detach().numpy())},
            "dense_1": {"kernel": jnp.asarray(l1.weight.detach().numpy().T),
                        "bias": jnp.asarray(l1.bias.detach().numpy())},
            "dense_2": {"kernel": jnp.asarray(l2.weight.detach().numpy().T),
                        "bias": jnp.asarray(l2.bias.detach().numpy())},
        }
    }
    j_out = MLP(E).apply(params, x)
    np.testing.assert_allclose(_np(j_out), t_out.numpy(), atol=1e-5)


def test_cross_attention_pre_ln(rng):
    """Cross-attention = LN(q), LN(kv) then MHA — verified against torch composition."""
    x_q = rng.standard_normal((B, T, E)).astype(np.float32)
    x_kv = rng.standard_normal((B, S, K)).astype(np.float32)

    t_mha = make_torch_mha()
    q_ln = torch.nn.LayerNorm(E)
    kv_ln = torch.nn.LayerNorm(K)
    # non-trivial LN affine
    with torch.no_grad():
        q_ln.weight.uniform_(0.5, 1.5)
        kv_ln.bias.uniform_(-0.5, 0.5)
        t_out, _ = t_mha(
            q_ln(torch.tensor(x_q)), kv_ln(torch.tensor(x_kv)), kv_ln(torch.tensor(x_kv))
        )

    params = {
        "params": {
            "q_norm": {"scale": jnp.asarray(q_ln.weight.detach().numpy()),
                       "bias": jnp.asarray(q_ln.bias.detach().numpy())},
            "kv_norm": {"scale": jnp.asarray(kv_ln.weight.detach().numpy()),
                        "bias": jnp.asarray(kv_ln.bias.detach().numpy())},
            "attention": jax.tree.map(jnp.asarray, mha_params_from_torch(t_mha)),
        }
    }
    mod = CrossAttention(num_q_channels=E, num_kv_channels=K, num_heads=H)
    j_out = mod.apply(params, x_q, x_kv)
    np.testing.assert_allclose(_np(j_out), t_out.numpy(), atol=5e-5)


def test_self_attention_single_norm(rng):
    x = rng.standard_normal((B, T, E)).astype(np.float32)
    torch.manual_seed(2)
    t_mha = torch.nn.MultiheadAttention(embed_dim=E, num_heads=H, batch_first=True)
    ln = torch.nn.LayerNorm(E)
    with torch.no_grad():
        xt = ln(torch.tensor(x))
        t_out, _ = t_mha(xt, xt, xt)

    sd = {k: v.detach().numpy() for k, v in t_mha.state_dict().items()}
    w = sd["in_proj_weight"]
    b = sd["in_proj_bias"]
    params = {
        "params": {
            "norm": {"scale": jnp.asarray(ln.weight.detach().numpy()),
                     "bias": jnp.asarray(ln.bias.detach().numpy())},
            "attention": {
                "q_proj": {"kernel": jnp.asarray(w[:E].T), "bias": jnp.asarray(b[:E])},
                "k_proj": {"kernel": jnp.asarray(w[E : 2 * E].T), "bias": jnp.asarray(b[E : 2 * E])},
                "v_proj": {"kernel": jnp.asarray(w[2 * E :].T), "bias": jnp.asarray(b[2 * E :])},
                "out_proj": {"kernel": jnp.asarray(sd["out_proj.weight"].T),
                             "bias": jnp.asarray(sd["out_proj.bias"])},
            },
        }
    }
    mod = SelfAttention(num_channels=E, num_heads=H)
    j_out = mod.apply(params, x)
    np.testing.assert_allclose(_np(j_out), t_out.numpy(), atol=1e-5)


def test_residual_applies_to_first_arg(rng):
    """CrossAttentionLayer output must equal mlp_res(attn_res) where each
    residual adds its own first input (reference model.py:47-56)."""
    x_q = rng.standard_normal((B, T, E)).astype(np.float32)
    x_kv = rng.standard_normal((B, S, K)).astype(np.float32)

    layer = CrossAttentionLayer(num_q_channels=E, num_kv_channels=K, num_heads=H)
    variables = layer.init(jax.random.key(0), x_q, x_kv)
    out = layer.apply(variables, x_q, x_kv)

    # recompute manually from the sublayers
    ca = CrossAttention(num_q_channels=E, num_kv_channels=K, num_heads=H)
    attn = ca.apply({"params": variables["params"]["cross_attention"]}, x_q, x_kv)
    h = np.asarray(attn) + x_q
    mlp_out = MLP(E).apply({"params": variables["params"]["mlp"]}, h)
    expected = np.asarray(mlp_out) + h
    np.testing.assert_allclose(_np(out), expected, atol=1e-5)


def test_dropout_zero_is_deterministic(rng):
    x_q = rng.standard_normal((B, T, E)).astype(np.float32)
    x_kv = rng.standard_normal((B, S, K)).astype(np.float32)
    layer = CrossAttentionLayer(num_q_channels=E, num_kv_channels=K, num_heads=H, dropout=0.0)
    variables = layer.init(jax.random.key(0), x_q, x_kv)
    o1 = layer.apply(variables, x_q, x_kv, deterministic=False,
                     rngs={"dropout": jax.random.key(1)})
    o2 = layer.apply(variables, x_q, x_kv, deterministic=True)
    np.testing.assert_allclose(_np(o1), _np(o2), atol=1e-6)


def test_dropout_nonzero_varies_and_preserves_mean(rng):
    x_q = rng.standard_normal((B, T, E)).astype(np.float32)
    x_kv = rng.standard_normal((B, S, K)).astype(np.float32)
    layer = CrossAttentionLayer(num_q_channels=E, num_kv_channels=K, num_heads=H, dropout=0.5)
    variables = layer.init(jax.random.key(0), x_q, x_kv)
    o1 = layer.apply(variables, x_q, x_kv, deterministic=False,
                     rngs={"dropout": jax.random.key(1)})
    o2 = layer.apply(variables, x_q, x_kv, deterministic=False,
                     rngs={"dropout": jax.random.key(2)})
    assert not np.allclose(_np(o1), _np(o2))


def test_auto_attention_impl_rule():
    """The 'auto' dispatch table (ops/attention.py constants encode real
    attn_shapes_bench measurements — PERF.md). Covers BOTH arms: the long-KV
    trigger and the round-2 big-logits area trigger with its d >= 32 guard."""
    from perceiver_io_tpu.ops.attention import auto_attention_impl as impl

    # off-TPU: always XLA (the kernel would run in interpreter mode)
    assert impl(2, 2048, 2048, 8, 64, backend="cpu") == "xla"

    # long-KV arm (streaming cross-attention)
    assert impl(2, 512, 50176, 8, 128, backend="tpu") == "pallas"   # in-8h
    assert impl(1, 2048, 182528, 1, 512, backend="tpu") == "pallas" # flow-cross
    assert impl(2, 512, 50176, 1, 1024, backend="tpu") == "xla"     # d>512
    assert impl(8, 256, 512, 4, 16, backend="tpu") == "xla"         # mlm-cross

    # big-logits arm (self-attention stacks under the KV threshold)
    assert impl(2, 2048, 2048, 8, 64, backend="tpu") == "pallas"    # flow-self
    assert impl(2, 182528, 2048, 1, 512, backend="tpu") == "pallas" # flow dec
    assert impl(16, 512, 512, 8, 128, backend="tpu") == "pallas"    # in-self b16
    # d >= 32 guard: MXU-hostile d=16 text shapes stay on XLA at ANY batch
    # (B*H*T*S = 512*4*256*256 = 134M would otherwise trigger)
    assert impl(512, 256, 256, 4, 16, backend="tpu") == "xla"
    # area below threshold: ImageNet self-attn at batch 8 stays on XLA
    assert impl(8, 512, 512, 8, 128, backend="tpu") == "xla"
