"""Unit tests for Fourier position encodings (reference adapter.py:53-97 semantics)."""

import numpy as np
import jax.numpy as jnp

from perceiver_io_tpu.ops.fourier import (
    fourier_position_encodings,
    num_position_encoding_channels,
    spatial_positions,
)


def test_spatial_positions_range_and_shape():
    pos = spatial_positions((5, 7))
    assert pos.shape == (5, 7, 2)
    # corners span [-1, 1] in each dim, 'ij' indexing
    np.testing.assert_allclose(pos[0, 0], [-1.0, -1.0], atol=1e-6)
    np.testing.assert_allclose(pos[-1, -1], [1.0, 1.0], atol=1e-6)
    np.testing.assert_allclose(pos[-1, 0], [1.0, -1.0], atol=1e-6)
    # dim 0 varies along axis 0 only
    np.testing.assert_allclose(pos[2, :, 0], np.full(7, pos[2, 0, 0]), atol=1e-6)


def test_spatial_positions_1d():
    pos = spatial_positions((4,))
    assert pos.shape == (4, 1)
    np.testing.assert_allclose(pos[:, 0], [-1, -1 / 3, 1 / 3, 1], atol=1e-6)


def test_channel_count():
    assert num_position_encoding_channels(2, 32) == 2 * (2 * 32 + 1)
    assert num_position_encoding_channels(3, 8, include_positions=False) == 3 * 16


def test_encoding_structure():
    bands = 4
    pos = spatial_positions((6, 8))
    enc = np.asarray(fourier_position_encodings(pos, bands))
    assert enc.shape == (6, 8, 2 * (2 * bands + 1))

    # layout: [positions (2)] [sin dim0 (bands)] [sin dim1 (bands)] [cos dim0] [cos dim1]
    np.testing.assert_allclose(enc[..., :2], np.asarray(pos), atol=1e-6)

    p = np.asarray(pos)
    # frequencies linspace(1.0, size/2, bands) with max_freq = spatial size per dim
    f0 = np.linspace(1.0, 6 / 2.0, bands)
    f1 = np.linspace(1.0, 8 / 2.0, bands)
    sin0 = np.sin(np.pi * p[..., :1] * f0)
    sin1 = np.sin(np.pi * p[..., 1:2] * f1)
    cos0 = np.cos(np.pi * p[..., :1] * f0)
    cos1 = np.cos(np.pi * p[..., 1:2] * f1)
    np.testing.assert_allclose(enc[..., 2 : 2 + bands], sin0, atol=1e-5)
    np.testing.assert_allclose(enc[..., 2 + bands : 2 + 2 * bands], sin1, atol=1e-5)
    np.testing.assert_allclose(enc[..., 2 + 2 * bands : 2 + 3 * bands], cos0, atol=1e-5)
    np.testing.assert_allclose(enc[..., 2 + 3 * bands :], cos1, atol=1e-5)


def test_max_frequencies_override():
    pos = spatial_positions((6,))
    enc = fourier_position_encodings(pos, 3, max_frequencies=(10,))
    f = np.linspace(1.0, 5.0, 3)
    expected_sin = np.sin(np.pi * np.asarray(pos)[..., :1] * f)
    np.testing.assert_allclose(np.asarray(enc)[..., 1:4], expected_sin, atol=1e-5)


def test_exclude_positions():
    pos = spatial_positions((5, 5))
    enc = fourier_position_encodings(pos, 2, include_positions=False)
    assert enc.shape == (5, 5, 2 * 2 * 2)
