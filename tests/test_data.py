"""Tests for the data layer: loader sharding/shuffling, collation, data modules."""

import gzip
import os
import struct

import numpy as np
import pytest

from perceiver_io_tpu.data.imdb import Collator, IMDBDataModule, synthetic_reviews
from perceiver_io_tpu.data.mnist import (
    MNISTDataModule,
    MNISTDataset,
    _read_idx,
    synthetic_digits,
)
from perceiver_io_tpu.data.pipeline import DataLoader
from perceiver_io_tpu.data.tokenizer import create_tokenizer, train_tokenizer


class RangeDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return i


def collate_ids(batch):
    return {"x": np.asarray(batch)}


def collate_width(idx, width=None):
    """Width-aware collate for the grouped-loader tests (the loader passes
    the GLOBAL batch's bucket width when group_widths is set)."""
    return {"i": np.asarray(idx), "w": np.asarray(width)}


def test_loader_drop_last_and_shapes():
    dl = DataLoader(RangeDataset(103), batch_size=10, collate=collate_ids, prefetch=0)
    batches = list(dl)
    assert len(batches) == 10 == len(dl)
    assert all(b["x"].shape == (10,) for b in batches)


def test_loader_sharding_partitions_batches():
    """Two shards see disjoint halves of each global batch, together covering it."""
    mk = lambda shard: DataLoader(
        RangeDataset(40), batch_size=8, collate=collate_ids,
        shuffle=True, seed=3, shard_id=shard, num_shards=2, prefetch=0,
    )
    b0 = list(mk(0))
    b1 = list(mk(1))
    assert all(b["x"].shape == (4,) for b in b0 + b1)
    for x0, x1 in zip(b0, b1):
        merged = np.concatenate([x0["x"], x1["x"]])
        assert len(np.unique(merged)) == 8
    all_seen = np.concatenate([b["x"] for b in b0 + b1])
    assert len(np.unique(all_seen)) == 40


def test_loader_shuffle_deterministic_and_epoch_varying():
    dl1 = DataLoader(RangeDataset(30), batch_size=10, collate=collate_ids,
                     shuffle=True, seed=5, prefetch=0)
    dl2 = DataLoader(RangeDataset(30), batch_size=10, collate=collate_ids,
                     shuffle=True, seed=5, prefetch=0)
    e1 = np.concatenate([b["x"] for b in dl1])
    e2 = np.concatenate([b["x"] for b in dl2])
    np.testing.assert_array_equal(e1, e2)
    e1b = np.concatenate([b["x"] for b in dl1])  # second epoch reshuffles
    assert not np.array_equal(e1, e1b)
    assert sorted(e1b) == sorted(e1)


def test_loader_prefetch_propagates_errors():
    class Bad(RangeDataset):
        def __getitem__(self, i):
            if i == 5:
                raise RuntimeError("boom")
            return i

    dl = DataLoader(Bad(10), batch_size=2, collate=collate_ids, prefetch=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(dl)


def test_loader_validates_args():
    with pytest.raises(ValueError, match="divisible"):
        DataLoader(RangeDataset(10), batch_size=5, collate=collate_ids, num_shards=2)
    with pytest.raises(ValueError, match="shard_id"):
        DataLoader(RangeDataset(10), batch_size=4, collate=collate_ids,
                   shard_id=2, num_shards=2)


@pytest.fixture(scope="module")
def imdb_tok():
    texts, _ = synthetic_reviews(200, seed=0)
    t = create_tokenizer(("<br />", " "))
    train_tokenizer(t, texts, vocab_size=200)
    return t


def test_collator_contract(imdb_tok):
    col = Collator(imdb_tok, max_seq_len=16)
    batch = col.collate([(1, "an awesome delightful movie"), (0, "terrible")])
    assert batch["token_ids"].shape == (2, 16)
    assert batch["pad_mask"].shape == (2, 16)
    assert batch["label"].tolist() == [1, 0]
    np.testing.assert_array_equal(batch["pad_mask"], batch["token_ids"] == 0)
    assert batch["pad_mask"][1].sum() > batch["pad_mask"][0].sum()

    ids, mask = col.encode(["just one sample"])
    assert ids.shape == (1, 16) and mask.shape == (1, 16)


def test_imdb_synthetic_module(tmp_path):
    dm = IMDBDataModule(root=str(tmp_path), max_seq_len=32, vocab_size=200,
                        batch_size=8, synthetic=True, synthetic_size=64)
    dm.prepare_data()
    assert os.path.exists(dm.tokenizer_path)
    dm.prepare_data()  # idempotent
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["token_ids"].shape == (8, 32)
    assert batch["token_ids"].dtype == np.int32
    val = next(iter(dm.val_dataloader()))
    assert set(val) == {"label", "token_ids", "pad_mask"}


def test_collator_bucket_widths(imdb_tok):
    """Bucketed padding: each batch lands in the smallest width that fits its
    longest sequence; max_seq_len is always the final cap."""
    col = Collator(imdb_tok, max_seq_len=32, bucket_widths=[8, 16])
    assert col.bucket_widths == [8, 16, 32]  # cap appended

    def expected_width(texts):
        longest = max(
            min(len(e), 32) for e in imdb_tok.encode_batch(list(texts))
        )
        return next(w for w in col.bucket_widths if w >= longest)

    for texts in (
        ["terrible"],
        ["terrible", "awesome movie"],
        [" ".join(["movie"] * 6)],
        [" ".join(["movie"] * 100)],  # truncated at the cap
    ):
        batch = col.collate([(0, t) for t in texts])
        assert batch["token_ids"].shape[1] == expected_width(texts)
        # contract invariants hold at every width
        np.testing.assert_array_equal(
            batch["pad_mask"], batch["token_ids"] == 0
        )
    assert col.collate([(0, " ".join(["movie"] * 100))])[
        "token_ids"].shape[1] == 32

    with pytest.raises(ValueError, match="bucket_widths"):
        Collator(imdb_tok, max_seq_len=16, bucket_widths=[8, 64])


def test_loader_length_grouped_windows():
    """sort_key + sort_window: every example still appears exactly once per
    epoch, examples cannot migrate across windows, batches become
    length-homogeneous inside each window, and the order is deterministic."""
    n, bs, win = 64, 8, 2
    lengths = np.arange(n)[::-1].copy()  # strictly decreasing keys

    def mk():
        return DataLoader(
            RangeDataset(n), batch_size=bs, collate=collate_ids,
            shuffle=True, seed=11, prefetch=0,
            sort_key=lengths, sort_window=win,
        )

    batches = [b["x"] for b in mk()]
    seen = np.concatenate(batches)
    assert sorted(seen.tolist()) == list(range(n))  # coverage, no dupes
    np.testing.assert_array_equal(np.concatenate([b["x"] for b in mk()]), seen)

    # window locality: reconstruct the pre-sort shuffle and check each
    # window's examples stay within it
    base = np.random.default_rng(np.uint32(11) + np.uint32(0)).permutation(n)
    for w in range(0, n // (bs * win)):
        window_members = set(base[w * bs * win : (w + 1) * bs * win])
        got = set(seen[w * bs * win : (w + 1) * bs * win].tolist())
        assert got == window_members
    # within a window, each batch is a contiguous run of the sorted order
    for w in range(0, n // (bs * win)):
        window_batches = batches[w * win : (w + 1) * win]
        spans = sorted(
            (min(lengths[b]), max(lengths[b])) for b in window_batches
        )
        for (lo1, hi1), (lo2, hi2) in zip(spans, spans[1:]):
            assert hi1 < lo2  # non-overlapping length ranges

    with pytest.raises(ValueError, match="sort_key"):
        DataLoader(RangeDataset(8), batch_size=4, collate=collate_ids,
                   sort_window=2)
    with pytest.raises(ValueError, match="sort_key length"):
        DataLoader(RangeDataset(8), batch_size=4, collate=collate_ids,
                   sort_key=np.arange(5), sort_window=2)


def test_bucketed_module_multihost_width_agreement(tmp_path):
    """Bucketed widths now COMPOSE with sharded loading (r4): the loader
    decides each GLOBAL batch's width from the shared token-length table, so
    two shard instances of the same module collate identical shapes step for
    step (the r3 guard this replaces existed because per-SHARD width choice
    diverged)."""
    mods = []
    for shard in (0, 1):
        dm = IMDBDataModule(root=str(tmp_path), max_seq_len=256, vocab_size=200,
                            batch_size=8, synthetic=True, synthetic_size=128,
                            bucket_widths=[128], length_sort_window=4,
                            shard_id=shard, num_shards=2)
        dm.prepare_data()
        dm.setup()
        mods.append(dm)
    # controlled corpus: half short, half long reviews, so both buckets are
    # guaranteed to fire (the synthetic generator's reviews are all long)
    from perceiver_io_tpu.data.imdb import IMDBDataset

    texts = ["a good movie"] * 64 + [" ".join(["word"] * 200)] * 64
    labels = [0, 1] * 64
    for dm in mods:
        dm.ds_train = IMDBDataset(texts, labels)
        dm._train_token_lengths = np.asarray(
            [len(e) for e in dm.tokenizer.encode_batch(texts)], dtype=np.int64
        )
    steps = [list(dm.train_dataloader()) for dm in mods]
    assert len(steps[0]) == len(steps[1]) > 0
    widths = []
    for b0, b1 in zip(*steps):
        assert b0["token_ids"].shape == b1["token_ids"].shape  # agree
        assert b0["token_ids"].shape[0] == 4  # half the global batch each
        widths.append(b0["token_ids"].shape[1])
    assert set(widths) == {128, 256}  # both buckets actually exercised


def test_loader_width_groups_of_k():
    """group_widths + group_size=K: every batch window of K consecutive
    batches that the trainer would stack has ONE width (same-width runs are
    emitted in chunks of K), and every example still appears exactly once."""
    rng = np.random.default_rng(0)
    n = 512
    lengths = rng.integers(1, 33, n)

    loader = DataLoader(
        RangeDataset(n), batch_size=4, collate=collate_width, shuffle=True,
        sort_key=lengths, sort_window=8, group_widths=[16, 32], group_size=2,
    )
    batches = list(loader)
    seen = np.sort(np.concatenate([b["i"] for b in batches]))
    np.testing.assert_array_equal(seen, np.arange(n))
    for b in batches:
        # the width the loader passes is the bucket of the batch's longest
        assert int(b["w"]) == (16 if lengths[b["i"]].max() <= 16 else 32)
    # simulate the trainer's stacker (greedy, flush on width change): K-group
    # emission must yield MORE full dispatch windows than permuting single
    # batches does — that is the whole point of grouping
    def full_window_count(batch_widths, k=2):
        windows, run = [], 1
        for i in range(1, len(batch_widths)):
            if batch_widths[i] == batch_widths[i - 1] and run < k:
                run += 1
            else:
                windows.append(run)
                run = 1
        windows.append(run)
        return sum(w == k for w in windows)

    ungrouped = DataLoader(
        RangeDataset(n), batch_size=4, collate=collate_width, shuffle=True,
        sort_key=lengths, sort_window=8, group_widths=[16, 32], group_size=1,
    )
    grouped_full = full_window_count([int(b["w"]) for b in batches])
    ungrouped_full = full_window_count([int(b["w"]) for b in ungrouped])
    assert grouped_full > ungrouped_full, (grouped_full, ungrouped_full)


def test_imdb_bucketed_module_and_predict_parity(tmp_path):
    """End to end: the module with buckets yields mixed widths whose batches
    all satisfy the contract, and the MLM predict logits for a short text are
    identical whether the batch was padded to a small bucket or to the cap
    (padding is masked out of attention, so width must not change results)."""
    import jax
    import jax.numpy as jnp

    import perceiver_io_tpu as pit
    from perceiver_io_tpu.ops.masking import TextMasking

    dm = IMDBDataModule(root=str(tmp_path), max_seq_len=32, vocab_size=200,
                        batch_size=8, synthetic=True, synthetic_size=128,
                        bucket_widths=[16], length_sort_window=2)
    dm.prepare_data()
    dm.setup()
    widths = {b["token_ids"].shape[1] for b in dm.train_dataloader()}
    assert widths <= {16, 32}
    for batch in dm.train_dataloader():
        np.testing.assert_array_equal(
            batch["pad_mask"], batch["token_ids"] == 0
        )

    vocab = dm.tokenizer.get_vocab_size()
    C, NLAT = 16, 4
    model = pit.PerceiverMLM(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=vocab, max_seq_len=32, num_channels=C),
            latent_shape=(NLAT, C), num_layers=1,
            num_cross_attention_heads=2, num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=vocab, max_seq_len=32, num_output_channels=C),
            latent_shape=(NLAT, C), num_cross_attention_heads=2,
        ),
        masking=TextMasking(vocab, 1, 2, 3),
    )
    text = "an awesome movie"
    col_bucket = dm.collator
    col_full = Collator(dm.tokenizer, max_seq_len=32)
    ids_b, mask_b = col_bucket.encode([text])
    ids_f, mask_f = col_full.encode([text])
    assert ids_b.shape[1] == 16 and ids_f.shape[1] == 32

    params = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        jnp.asarray(ids_f), jnp.asarray(mask_f),
    )["params"]
    out_b, _ = model.apply({"params": params}, jnp.asarray(ids_b),
                           jnp.asarray(mask_b), masking=False)
    out_f, _ = model.apply({"params": params}, jnp.asarray(ids_f),
                           jnp.asarray(mask_f), masking=False)
    np.testing.assert_allclose(
        np.asarray(out_b), np.asarray(out_f)[:, :16], atol=1e-5
    )


def test_imdb_missing_data_raises(tmp_path):
    dm = IMDBDataModule(root=str(tmp_path), synthetic=False, download=False)
    with pytest.raises(FileNotFoundError, match="aclImdb"):
        dm.prepare_data()


def test_idx_reader_roundtrip(tmp_path):
    arr = np.arange(2 * 5 * 4, dtype=np.uint8).reshape(2, 5, 4)
    path = tmp_path / "test-idx3-ubyte.gz"
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">III", 2, 5, 4))
        f.write(arr.tobytes())
    out = _read_idx(str(path))
    np.testing.assert_array_equal(out, arr)


def test_mnist_dataset_normalization():
    images, labels = synthetic_digits(16, seed=0)
    ds = MNISTDataset(images, labels)
    img, lab = ds[0]
    assert img.shape == (28, 28, 1)
    assert img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert img.max() > 0  # actually uses the range
    assert 0 <= lab < 10


def test_mnist_random_crop():
    images, labels = synthetic_digits(4, seed=0)
    ds = MNISTDataset(images, labels, crop=20)
    img, _ = ds[0]
    assert img.shape == (20, 20, 1)
    assert ds.image_shape == (20, 20, 1)


def test_mnist_val_crop_matches_dims():
    """random_crop module: val batches must match `dims` (center crop)."""
    dm = MNISTDataModule(
        batch_size=8, synthetic=True, synthetic_size=128, random_crop=24
    )
    dm.setup()
    assert dm.dims == (24, 24, 1)
    tb = next(iter(dm.train_dataloader()))
    vb = next(iter(dm.val_dataloader()))
    assert tb["image"].shape[1:] == (24, 24, 1)
    assert vb["image"].shape[1:] == (24, 24, 1)
    # center crop is deterministic: same example → same array every epoch
    a, _ = dm.ds_valid[0]
    b, _ = dm.ds_valid[0]
    np.testing.assert_array_equal(a, b)


def test_mnist_synthetic_module():
    dm = MNISTDataModule(batch_size=16, synthetic=True, synthetic_size=256)
    dm.prepare_data()
    dm.setup()
    assert dm.dims == (28, 28, 1)
    assert dm.num_classes == 10
    tb = next(iter(dm.train_dataloader()))
    assert tb["image"].shape == (16, 28, 28, 1)
    assert tb["label"].dtype == np.int32
    # train/val from disjoint slices
    assert len(dm.ds_train) + len(dm.ds_valid) == 256


def test_mnist_missing_data_raises(tmp_path):
    dm = MNISTDataModule(root=str(tmp_path), synthetic=False, download=False)
    with pytest.raises(FileNotFoundError, match="MNIST"):
        dm.prepare_data()


def test_synthetic_digits_learnable_structure():
    """Same class ⇒ similar images across draws (there is signal to learn)."""
    images, labels = synthetic_digits(512, seed=0)
    images = images.astype(np.float32) / 255.0
    same = []
    diff = []
    by_class = {c: images[labels == c] for c in range(10)}
    for c in range(10):
        if len(by_class[c]) >= 2:
            same.append(np.abs(by_class[c][0] - by_class[c][1]).mean())
        other = (c + 1) % 10
        if len(by_class[other]):
            diff.append(np.abs(by_class[c][0] - by_class[other][0]).mean())
    assert np.mean(same) < np.mean(diff)


def test_loader_rejects_partial_batches_with_shards():
    with pytest.raises(ValueError, match="drop_last"):
        DataLoader(RangeDataset(10), batch_size=4, collate=collate_ids,
                   num_shards=2, drop_last=False)


def test_loader_epoch_advances_on_early_break():
    dl = DataLoader(RangeDataset(40), batch_size=8, collate=collate_ids,
                    shuffle=True, seed=1, prefetch=2)
    seen = []
    for batch in dl:
        seen.append(batch["x"])
        break  # fixed-step loop abandons the epoch early
    first_epoch_start = seen[0]
    second = next(iter(dl))["x"]
    assert not np.array_equal(first_epoch_start, second)


def test_mnist_val_split_zero_keeps_all_training_data():
    from perceiver_io_tpu.data.mnist import MNISTDataModule

    dm = MNISTDataModule(batch_size=8, synthetic=True, synthetic_size=128)
    dm.setup()
    # synthetic mode uses its own split; emulate real behavior directly
    images, labels = synthetic_digits(100, seed=0)
    ds_train = MNISTDataset(images[: len(images) - 0], labels[: len(labels) - 0])
    assert len(ds_train) == 100


def test_val_loader_keeps_partial_batches():
    dm = MNISTDataModule(batch_size=30, synthetic=True, synthetic_size=256)
    dm.setup()  # val size = 32 -> one full batch of 30 + partial of 2
    batches = list(dm.val_dataloader())
    total = sum(len(b["label"]) for b in batches)
    assert total == len(dm.ds_valid)


def test_loader_skip_next_resume_parity():
    """skip_next(k) + epoch alignment reproduces an uninterrupted run's
    stream exactly — the trainer's deterministic mid-epoch resume."""
    mk = lambda: DataLoader(RangeDataset(40), batch_size=10, collate=collate_ids,
                            shuffle=True, seed=7, prefetch=0)
    full = mk()
    stream = [b["x"] for _ in range(2) for b in full]  # 2 epochs, 8 batches

    resumed = mk()
    resumed.epoch = 1      # crash at global step 6 -> epoch 1, offset 2
    resumed.skip_next(2)
    tail = [b["x"] for b in resumed]
    np.testing.assert_array_equal(np.stack(tail), np.stack(stream[6:8]))
    # next epoch is clean (skip consumed once)
    again = [b["x"] for b in resumed]
    assert len(again) == 4


def test_grouped_loader_resume_prefix_property():
    """Mid-epoch resume exactness under width grouping: skip_next(k) must
    yield exactly the batches an uninterrupted iteration yields after its
    first k — the property Trainer's deterministic-resume arithmetic rests
    on (grouping reorders the epoch, but the order itself must be a stable
    function of (seed, epoch)). Checked across random corpora and group
    sizes."""
    rng = np.random.default_rng(3)

    for trial in range(4):
        n = int(rng.integers(96, 257)) // 8 * 8
        lengths = rng.integers(1, 40, n)
        k = int(rng.integers(2, 5))
        make = lambda: DataLoader(
            RangeDataset(n), batch_size=8, collate=collate_width, shuffle=True,
            seed=trial, sort_key=lengths, sort_window=3,
            group_widths=[16, 40], group_size=k,
        )
        full = [b["i"] for b in make()]
        skip = int(rng.integers(1, max(len(full) - 1, 2)))
        resumed_loader = make()
        resumed_loader.skip_next(skip)
        resumed = [b["i"] for b in resumed_loader]
        assert len(resumed) == len(full) - skip, (trial, skip)
        for a, b in zip(full[skip:], resumed):
            np.testing.assert_array_equal(a, b)


def test_bucketed_module_multihost_EVAL_width_agreement(tmp_path):
    """Eval rides the same width oracle as train (r5 — the last reference
    behavior without an equivalent: pad-to-longest eval batches, reference
    data/imdb.py:55-57). Two shard instances must collate identical eval
    shapes step for step, order untouched (sort_window=0), with short
    batches actually landing in the small bucket."""
    from perceiver_io_tpu.data.imdb import IMDBDataset

    texts = ["a good movie"] * 64 + [" ".join(["word"] * 200)] * 64
    labels = [0, 1] * 64
    mods = []
    for shard in (0, 1):
        dm = IMDBDataModule(root=str(tmp_path), max_seq_len=256, vocab_size=200,
                            batch_size=8, synthetic=True, synthetic_size=128,
                            bucket_widths=[128], length_sort_window=4,
                            shard_id=shard, num_shards=2)
        dm.prepare_data()
        dm.setup()
        dm.ds_valid = IMDBDataset(texts, labels)
        dm._valid_token_lengths = np.asarray(
            [len(e) for e in dm.tokenizer.encode_batch(texts)], dtype=np.int64
        )
        mods.append(dm)
    steps = [list(dm.val_dataloader()) for dm in mods]
    assert len(steps[0]) == len(steps[1]) > 0
    widths = []
    for b0, b1 in zip(*steps):
        assert b0["token_ids"].shape == b1["token_ids"].shape  # hosts agree
        assert b0["token_ids"].shape[0] == 4
        widths.append(b0["token_ids"].shape[1])
    # order is NOT sorted (sort_window=0): the corpus lays shorts first, longs
    # second, so the width sequence is a prefix of 128s then 256s — and both
    # buckets fire
    assert set(widths) == {128, 256}
    assert widths == sorted(widths)  # shorts (128) precede longs (256)
    # eval labels arrive in dataset order (no reordering)
    flat = np.concatenate([b["label"] for b in steps[0]])
    assert flat.tolist() == [l for i, l in enumerate(labels) if i % 8 < 4]


def test_eval_bucketing_single_host_keeps_full_set(tmp_path):
    """Single-host bucketed eval: every example present, order preserved,
    partial tail batch allowed (drop_last=False), widths from the oracle."""
    from perceiver_io_tpu.data.imdb import IMDBDataset

    dm = IMDBDataModule(root=str(tmp_path), max_seq_len=256, vocab_size=200,
                        batch_size=8, synthetic=True, synthetic_size=64,
                        bucket_widths=[128], length_sort_window=4)
    dm.prepare_data()
    dm.setup()
    texts = ["short text"] * 21 + [" ".join(["word"] * 200)] * 14  # 35 = 4*8+3
    dm.ds_valid = IMDBDataset(texts, [0] * 35)
    dm._valid_token_lengths = np.asarray(
        [len(e) for e in dm.tokenizer.encode_batch(texts)], dtype=np.int64
    )
    batches = list(dm.val_dataloader())
    assert sum(b["token_ids"].shape[0] for b in batches) == 35
    assert batches[-1]["token_ids"].shape[0] == 3  # tail kept
    assert batches[0]["token_ids"].shape[1] == 128  # shorts in the small bucket
    assert batches[-1]["token_ids"].shape[1] == 256
