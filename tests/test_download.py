"""Guarded dataset downloaders, exercised against a localhost HTTP server
(zero-egress-safe end-to-end: fetch → verify → extract → data module loads
real, non-synthetic data)."""

import gzip
import hashlib
import io
import os
import struct
import tarfile
import threading
from functools import partial
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from perceiver_io_tpu.data.download import (
    DownloadError,
    download_any,
    download_file,
    ensure_imdb,
    ensure_mnist,
)


@pytest.fixture
def http_root(tmp_path):
    """Serve tmp_path/srv over localhost; yields (base_url, srv_dir)."""
    srv = tmp_path / "srv"
    srv.mkdir()
    handler = partial(SimpleHTTPRequestHandler, directory=str(srv))
    handler.log_message = lambda *a, **k: None
    server = HTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}/", srv
    server.shutdown()
    thread.join()


def _write_imdb_tarball(path):
    """A miniature aclImdb tree, tarred like the real aclImdb_v1.tar.gz."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for split in ("train", "test"):
            for label in ("neg", "pos"):
                for i in range(3):
                    text = f"{label} review {i} for {split}: the movie was a movie".encode()
                    info = tarfile.TarInfo(f"aclImdb/{split}/{label}/{i}_7.txt")
                    info.size = len(text)
                    tar.addfile(info, io.BytesIO(text))
    path.write_bytes(buf.getvalue())


def _write_mnist_files(srv):
    """Four tiny-but-valid idx gz files; returns [(name, md5)] to pin."""
    rng = np.random.default_rng(0)
    entries = []
    for prefix, n in (("train", 64), ("t10k", 16)):
        images = rng.integers(0, 256, size=(n, 28, 28)).astype(np.uint8)
        labels = rng.integers(0, 10, size=n).astype(np.uint8)
        payloads = {
            f"{prefix}-images-idx3-ubyte.gz":
                struct.pack(">IIII", 0x00000803, n, 28, 28) + images.tobytes(),
            f"{prefix}-labels-idx1-ubyte.gz":
                struct.pack(">II", 0x00000801, n) + labels.tobytes(),
        }
        for name, raw in payloads.items():
            data = gzip.compress(raw)
            (srv / name).write_bytes(data)
            entries.append((name, hashlib.md5(data).hexdigest()))
    return entries


def test_download_file_and_checksum(http_root, tmp_path):
    base, srv = http_root
    (srv / "blob.bin").write_bytes(b"hello dataset")
    md5 = hashlib.md5(b"hello dataset").hexdigest()
    dest = tmp_path / "out" / "blob.bin"
    download_file(base + "blob.bin", str(dest), md5=md5)
    assert dest.read_bytes() == b"hello dataset"
    with pytest.raises(DownloadError, match="checksum"):
        download_file(base + "blob.bin", str(tmp_path / "bad.bin"), md5="0" * 32)
    assert not (tmp_path / "bad.bin").exists()  # atomic: no partial file


def test_download_any_mirror_fallback(http_root, tmp_path):
    base, srv = http_root
    (srv / "file.txt").write_bytes(b"mirror two wins")
    dest = tmp_path / "file.txt"
    download_any([base + "missing.txt", base + "file.txt"], str(dest))
    assert dest.read_bytes() == b"mirror two wins"
    with pytest.raises(DownloadError, match="all mirrors failed"):
        download_any([base + "nope1", base + "nope2"], str(tmp_path / "x"))


def test_ensure_imdb_end_to_end(http_root, tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl
    from perceiver_io_tpu.data.imdb import IMDBDataModule

    base, srv = http_root
    _write_imdb_tarball(srv / "aclImdb_v1.tar.gz")
    monkeypatch.setattr(dl, "IMDB_URLS", [base + "aclImdb_v1.tar.gz"])
    monkeypatch.setattr(
        dl, "IMDB_MD5", hashlib.md5((srv / "aclImdb_v1.tar.gz").read_bytes()).hexdigest()
    )

    root = tmp_path / "cache"
    target = ensure_imdb(str(root))
    assert os.path.isdir(os.path.join(target, "train", "pos"))
    # idempotent: second call is a no-op (no server needed)
    assert ensure_imdb(str(root)) == target

    # the data module consumes the downloaded tree end to end
    dm = IMDBDataModule(root=str(root), max_seq_len=16, vocab_size=60,
                        batch_size=4)
    dm.prepare_data()
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["token_ids"].shape == (4, 16)
    assert len(dm.ds_train) == 6  # 3 neg + 3 pos


def test_ensure_mnist_end_to_end(http_root, tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl
    from perceiver_io_tpu.data.mnist import MNISTDataModule

    base, srv = http_root
    entries = _write_mnist_files(srv)
    monkeypatch.setattr(dl, "MNIST_FILES", entries)
    monkeypatch.setattr(dl, "MNIST_MIRRORS", [base])

    root = tmp_path / "cache"
    raw = ensure_mnist(str(root))
    for name, _ in entries:
        assert os.path.exists(os.path.join(raw, name[:-3]))  # unpacked

    dm = MNISTDataModule(root=str(root), batch_size=8, val_split=16)
    dm.prepare_data()
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["image"].shape == (8, 28, 28, 1)
    assert len(dm.ds_train) == 48 and len(dm.ds_valid) == 16


def test_ensure_imdb_offline_error_names_alternatives(tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl

    # a closed port: connection refused immediately, no egress attempted
    monkeypatch.setattr(dl, "IMDB_URLS", ["http://127.0.0.1:1/x.tar.gz"])
    with pytest.raises(DownloadError, match="synthetic"):
        ensure_imdb(str(tmp_path), timeout=2.0)


def test_tarball_path_traversal_rejected(http_root, tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl

    base, srv = http_root
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        info = tarfile.TarInfo("../evil.txt")
        info.size = 4
        tar.addfile(info, io.BytesIO(b"evil"))
    (srv / "aclImdb_v1.tar.gz").write_bytes(buf.getvalue())
    monkeypatch.setattr(dl, "IMDB_URLS", [base + "aclImdb_v1.tar.gz"])
    monkeypatch.setattr(dl, "IMDB_MD5", hashlib.md5(buf.getvalue()).hexdigest())
    with pytest.raises(DownloadError, match="unsafe tar member"):
        ensure_imdb(str(tmp_path / "cache"))
    assert not (tmp_path / "evil.txt").exists()
