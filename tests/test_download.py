"""Guarded dataset downloaders, exercised against a localhost HTTP server
(zero-egress-safe end-to-end: fetch → verify → extract → data module loads
real, non-synthetic data)."""

import gzip
import hashlib
import io
import os
import struct
import tarfile
import threading
from functools import partial
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from perceiver_io_tpu.data.download import (
    DownloadError,
    download_any,
    download_file,
    ensure_imdb,
    ensure_mnist,
)


@pytest.fixture
def http_root(tmp_path):
    """Serve tmp_path/srv over localhost; yields (base_url, srv_dir)."""
    srv = tmp_path / "srv"
    srv.mkdir()
    handler = partial(SimpleHTTPRequestHandler, directory=str(srv))
    handler.log_message = lambda *a, **k: None
    server = HTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}/", srv
    server.shutdown()
    thread.join()


def _write_imdb_tarball(path):
    """A miniature aclImdb tree, tarred like the real aclImdb_v1.tar.gz."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for split in ("train", "test"):
            for label in ("neg", "pos"):
                for i in range(3):
                    text = f"{label} review {i} for {split}: the movie was a movie".encode()
                    info = tarfile.TarInfo(f"aclImdb/{split}/{label}/{i}_7.txt")
                    info.size = len(text)
                    tar.addfile(info, io.BytesIO(text))
    path.write_bytes(buf.getvalue())


def _write_mnist_files(srv):
    """Four tiny-but-valid idx gz files; returns [(name, md5)] to pin."""
    rng = np.random.default_rng(0)
    entries = []
    for prefix, n in (("train", 64), ("t10k", 16)):
        images = rng.integers(0, 256, size=(n, 28, 28)).astype(np.uint8)
        labels = rng.integers(0, 10, size=n).astype(np.uint8)
        payloads = {
            f"{prefix}-images-idx3-ubyte.gz":
                struct.pack(">IIII", 0x00000803, n, 28, 28) + images.tobytes(),
            f"{prefix}-labels-idx1-ubyte.gz":
                struct.pack(">II", 0x00000801, n) + labels.tobytes(),
        }
        for name, raw in payloads.items():
            data = gzip.compress(raw)
            (srv / name).write_bytes(data)
            entries.append((name, hashlib.md5(data).hexdigest()))
    return entries


@pytest.fixture
def flaky_http_root(tmp_path):
    """Serve tmp_path/srv, failing each path's first N requests with a 503
    (N set per-test via the returned dict); yields (base_url, srv, counts)."""
    srv = tmp_path / "srv"
    srv.mkdir()
    counts = {}
    fail_times = {"n": 0}

    class Handler(SimpleHTTPRequestHandler):
        def __init__(self, *a, **k):
            super().__init__(*a, directory=str(srv), **k)

        def log_message(self, *a, **k):
            pass

        def do_GET(self):
            seen = counts.get(self.path, 0)
            counts[self.path] = seen + 1
            if seen < fail_times["n"]:
                self.send_error(503, "injected transient failure")
                return
            super().do_GET()

    server = HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}/", srv, counts, fail_times
    server.shutdown()
    thread.join()


def test_download_retries_transient_5xx(flaky_http_root, tmp_path):
    """A mirror that 503s twice then serves must succeed through the capped
    backoff retry — and a 404 must NOT be retried (deterministic failure)."""
    from perceiver_io_tpu.resilience import RetryPolicy

    base, srv, counts, fail_times = flaky_http_root
    (srv / "blob.bin").write_bytes(b"eventually consistent")
    fail_times["n"] = 2
    policy = RetryPolicy(max_retries=2, base_s=0.01, jitter=0.0)
    dest = tmp_path / "out" / "blob.bin"
    download_file(base + "blob.bin", str(dest), retry_policy=policy)
    assert dest.read_bytes() == b"eventually consistent"
    assert counts["/blob.bin"] == 3  # two 503s + the success

    # budget exhausted: the 5xx propagates (as a mirror failure upstream)
    fail_times["n"] = 10
    with pytest.raises(Exception, match="503|all mirrors failed"):
        download_any([base + "blob.bin"], str(tmp_path / "x.bin"),
                     retry_policy=policy)
    assert counts["/blob.bin"] == 3 + 3  # one attempt + two retries, then out

    # a 404 is deterministic: exactly ONE request, no backoff stalls
    fail_times["n"] = 0
    with pytest.raises(Exception, match="404|Not Found"):
        download_file(base + "missing.bin", str(tmp_path / "y.bin"),
                      retry_policy=policy)
    assert counts["/missing.bin"] == 1


def test_download_file_and_checksum(http_root, tmp_path):
    base, srv = http_root
    (srv / "blob.bin").write_bytes(b"hello dataset")
    md5 = hashlib.md5(b"hello dataset").hexdigest()
    dest = tmp_path / "out" / "blob.bin"
    download_file(base + "blob.bin", str(dest), md5=md5)
    assert dest.read_bytes() == b"hello dataset"
    with pytest.raises(DownloadError, match="checksum"):
        download_file(base + "blob.bin", str(tmp_path / "bad.bin"), md5="0" * 32)
    assert not (tmp_path / "bad.bin").exists()  # atomic: no partial file


def test_download_any_mirror_fallback(http_root, tmp_path):
    base, srv = http_root
    (srv / "file.txt").write_bytes(b"mirror two wins")
    dest = tmp_path / "file.txt"
    download_any([base + "missing.txt", base + "file.txt"], str(dest))
    assert dest.read_bytes() == b"mirror two wins"
    with pytest.raises(DownloadError, match="all mirrors failed"):
        download_any([base + "nope1", base + "nope2"], str(tmp_path / "x"))


def test_ensure_imdb_end_to_end(http_root, tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl
    from perceiver_io_tpu.data.imdb import IMDBDataModule

    base, srv = http_root
    _write_imdb_tarball(srv / "aclImdb_v1.tar.gz")
    monkeypatch.setattr(dl, "IMDB_URLS", [base + "aclImdb_v1.tar.gz"])
    monkeypatch.setattr(
        dl, "IMDB_MD5", hashlib.md5((srv / "aclImdb_v1.tar.gz").read_bytes()).hexdigest()
    )

    root = tmp_path / "cache"
    target = ensure_imdb(str(root))
    assert os.path.isdir(os.path.join(target, "train", "pos"))
    # idempotent: second call is a no-op (no server needed)
    assert ensure_imdb(str(root)) == target

    # the data module consumes the downloaded tree end to end
    dm = IMDBDataModule(root=str(root), max_seq_len=16, vocab_size=60,
                        batch_size=4)
    dm.prepare_data()
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["token_ids"].shape == (4, 16)
    assert len(dm.ds_train) == 6  # 3 neg + 3 pos


def test_ensure_mnist_end_to_end(http_root, tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl
    from perceiver_io_tpu.data.mnist import MNISTDataModule

    base, srv = http_root
    entries = _write_mnist_files(srv)
    monkeypatch.setattr(dl, "MNIST_FILES", entries)
    monkeypatch.setattr(dl, "MNIST_MIRRORS", [base])

    root = tmp_path / "cache"
    raw = ensure_mnist(str(root))
    for name, _ in entries:
        assert os.path.exists(os.path.join(raw, name[:-3]))  # unpacked

    dm = MNISTDataModule(root=str(root), batch_size=8, val_split=16)
    dm.prepare_data()
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["image"].shape == (8, 28, 28, 1)
    assert len(dm.ds_train) == 48 and len(dm.ds_valid) == 16


def test_ensure_imdb_offline_error_names_alternatives(tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl

    # a closed port: connection refused immediately, no egress attempted
    monkeypatch.setattr(dl, "IMDB_URLS", ["http://127.0.0.1:1/x.tar.gz"])
    with pytest.raises(DownloadError, match="synthetic"):
        ensure_imdb(str(tmp_path), timeout=2.0)


def test_tarball_path_traversal_rejected(http_root, tmp_path, monkeypatch):
    from perceiver_io_tpu.data import download as dl

    base, srv = http_root
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        info = tarfile.TarInfo("../evil.txt")
        info.size = 4
        tar.addfile(info, io.BytesIO(b"evil"))
    (srv / "aclImdb_v1.tar.gz").write_bytes(buf.getvalue())
    monkeypatch.setattr(dl, "IMDB_URLS", [base + "aclImdb_v1.tar.gz"])
    monkeypatch.setattr(dl, "IMDB_MD5", hashlib.md5(buf.getvalue()).hexdigest())
    with pytest.raises(DownloadError, match="unsafe tar member"):
        ensure_imdb(str(tmp_path / "cache"))
    assert not (tmp_path / "evil.txt").exists()
