"""Multi-replica serving fabric: least-loaded routing, failover with zero
lost accepted requests, latent-cache affinity spill-on-death, graceful
drain, rolling rollout with auto-rollback, and fleet-aware health.

Tier-1 coverage runs IN-PROCESS over trivial jitted engines behind
``LocalReplica`` shims (seconds, not minutes); the real-process drills —
``kill -9`` under open-loop load_bench traffic, supervisor restart+rejoin,
the serve CLI fleet mode — are ``slow``-marked, each naming the tier-1 test
that retains its logic coverage.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.inference import ServingEngine
from perceiver_io_tpu.resilience import (
    AffinityLost,
    BreakerOpen,
    DeadlineExceeded,
    FailoverPolicy,
    FaultInjector,
    FaultSpec,
    RejectedError,
    faults,
)
from perceiver_io_tpu.serving import (
    HttpReplicaClient,
    LocalReplica,
    ReplicaApp,
    ReplicaServer,
    Router,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _router(replicas, **kw):
    """A Router over a FRESH registry: router counters are keyed by name in
    the process-global registry, and absolute-value asserts must not see
    other tests' traffic."""
    kw.setdefault("scrape_interval_s", 0.02)
    kw.setdefault("registry", obs.MetricsRegistry())
    return Router(replicas, **kw)


def _make_replica(name, scale=2.0, slo=None, **engine_kw):
    """One in-process replica over trivial jitted apply fns (no flax model:
    the fabric's logic is model-agnostic and tier-1 time is precious)."""

    def infer(p, x):
        return x * p

    def encode(p, x):
        return x + p

    def decode(p, latents, positions):
        return latents * positions

    engines = {
        kind: ServingEngine(fn, np.float32(scale), max_batch=4,
                            name=f"{name}-{kind}", slo=slo, **engine_kw)
        for kind, fn in (("infer", infer), ("encode", encode),
                         ("decode", decode))
    }

    def params_factory(spec):
        return np.float32(spec.get("seed", 0) + 1.0)

    app = ReplicaApp(engines, np.float32(scale),
                     params_factory=params_factory, name=name,
                     assume_ready=True)
    return LocalReplica(app)


@pytest.fixture
def x():
    return np.ones((2, 3), np.float32)


def _close(router, *replicas):
    router.close()
    for r in replicas:
        r.app.close()


# -- failover policy (pure) ---------------------------------------------------


def test_failover_policy_classification():
    """Rejections and dead-replica transport errors re-route; deadline
    expiry and lost affinity never do (DeadlineExceeded subclasses
    TimeoutError, which the transient classifier would otherwise retry)."""
    p = FailoverPolicy(max_attempts=3)
    assert p.classify(RejectedError("queue full")) == "reroute"
    assert p.classify(BreakerOpen("open")) == "reroute"
    assert p.classify(ConnectionError("connection closed")) == "reroute"
    assert p.classify(DeadlineExceeded("expired")) == "fail"
    assert p.classify(AffinityLost("gone")) == "fail"
    assert p.classify(ValueError("shape mismatch")) == "fail"
    # attempt budget: 1-based attempt index, max_attempts total placements
    err = ConnectionError("connection closed")
    assert p.should_reroute(err, 1) and p.should_reroute(err, 2)
    assert not p.should_reroute(err, 3)
    assert not FailoverPolicy(
        max_attempts=2, reroute_rejections=False
    ).should_reroute(RejectedError("full"), 1)
    # the mirrored-error contract: a self-declared bool wins over message text
    from perceiver_io_tpu.serving import RemoteEngineError

    assert p.classify(RemoteEngineError("UNAVAILABLE: x", transient=True)) \
        == "reroute"
    assert p.classify(
        RemoteEngineError("connection reset", transient=False)) == "fail"


# -- routing ------------------------------------------------------------------


def test_router_least_loaded_routing_skewed(x):
    """A replica with an artificially slow dispatch path accumulates queue
    depth; the router's load score must steer traffic to the fast one.

    Runs under the lock-order sanitizer (analysis/): this traffic crosses
    the engine worker / submitter / router dispatch-pool / scrape-thread
    lock soup, and the recorded acquisition graph must stay cycle-free —
    an inconsistent ordering is a deadlock waiting for the interleaving
    even when this run never blocks."""
    from perceiver_io_tpu.analysis import record_lock_order

    with record_lock_order() as lock_rec:
        slow = _make_replica("slowrep")
        fast = _make_replica("fastrep")
        prev = faults.install(FaultInjector([
            FaultSpec(site="engine.dispatch.slowrep-infer", kind="slow",
                      every=1, delay_s=0.05),
        ]))
        try:
            router = _router([slow, fast])
            futs = []
            for _ in range(24):
                futs.append(router.submit(x))
                time.sleep(0.005)  # let queue depth become observable
            for f in futs:
                f.result(30)
            served_fast = fast.app.engines["infer"].requests_served
            served_slow = slow.app.engines["infer"].requests_served
            assert served_fast + served_slow == 24
            assert served_fast > served_slow, (served_fast, served_slow)
            _close(router, slow, fast)
        finally:
            faults.install(prev)
    assert lock_rec.acquisitions > 0  # the recorder really saw the traffic


def test_router_failover_zero_lost_accepted(x):
    """Kill one of three replicas with traffic in flight: every accepted
    request must still be answered (re-routed via the transient taxonomy),
    none duplicated, none lost — the tier-1 twin of the kill -9 drill."""
    reps = [_make_replica(f"fo{i}") for i in range(3)]
    router = _router(reps)
    futs = [router.submit(x) for _ in range(10)]
    reps[0].kill()
    futs += [router.submit(x) for _ in range(30)]
    results = [f.result(30) for f in futs]  # raises if any was lost
    assert len(results) == 40
    assert all(np.allclose(r, 2.0) for r in results)
    stats = router.stats()
    assert stats["failed"] == 0
    assert stats["completed"] == 40
    # each future delivered exactly once, by exactly one replica
    assert all(f.replica in {"fo1", "fo2"} or f.attempts == 1 for f in futs)
    time.sleep(0.05)  # scrape loop observes the corpse
    assert router.statuses()["fo0"]["state"] == "down"
    _close(router, *reps)


def test_router_all_replicas_down_sheds(x):
    reps = [_make_replica(f"dead{i}") for i in range(2)]
    router = _router(reps)
    for r in reps:
        r.kill()
    router.refresh()
    fut = router.submit(x)
    with pytest.raises(RejectedError, match="no replica available"):
        fut.result(10)
    _close(router, *reps)


# -- latent-cache affinity ----------------------------------------------------


def test_router_affinity_spill_on_death(x):
    """Sessions pin to the replica holding their latents; a dead pin
    surfaces as AffinityLost (never a silent wrong-latents decode), and
    re-encoding re-pins on a live replica."""
    reps = [_make_replica(f"aff{i}") for i in range(2)]
    router = _router(reps)
    router.refresh()
    ack = router.encode(x, session="s", timeout=30)
    assert list(ack) == [2, 3]  # latents stay ON the replica; shape ack only
    first = router.pinned("s")
    assert first in ("aff0", "aff1")
    pos = np.ones((2, 3), np.float32)
    decoded = router.decode(pos, session="s", timeout=30)
    assert decoded.shape == (2, 3)
    # decode always follows the pin, even under load skew
    for _ in range(4):
        router.decode(pos, session="s", timeout=30)
    assert router.pinned("s") == first

    dict(zip(("aff0", "aff1"), reps))[first].kill()
    router.refresh()
    with pytest.raises(AffinityLost):
        router.decode(pos, session="s", timeout=30)
    assert router.pinned("s") is None  # the pin spilled
    assert router.stats()["affinity_spills"] >= 1
    router.encode(x, session="s", timeout=30)  # re-encode re-pins...
    assert router.pinned("s") != first  # ...on the surviving replica
    router.decode(pos, session="s", timeout=30)
    _close(router, *reps)


# -- graceful drain -----------------------------------------------------------


def test_router_drain_completes_inflight_then_refuses(x):
    """Drain: accepted work finishes (a slow in-flight dispatch included),
    new work is refused at the drained replica, and with the whole fleet
    drained the router sheds; resume restores service."""
    rep = _make_replica("dr0")
    router = _router([rep])
    prev = faults.install(FaultInjector([
        FaultSpec(site="engine.dispatch.dr0-infer", kind="slow",
                  at=(1,), delay_s=0.2),
    ]))
    try:
        futs = [router.submit(x) for _ in range(6)]
        time.sleep(0.02)  # the slow first dispatch is now in flight
        assert router.drain_replica("dr0", timeout_s=30)
        for f in futs:  # everything accepted before the drain completed
            assert np.allclose(f.result(30), 2.0)
        assert router.statuses()["dr0"]["state"] == "draining"
        fut = router.submit(x)
        with pytest.raises(RejectedError):
            fut.result(10)
        router.resume_replica("dr0")
        router.refresh()
        assert np.allclose(router.predict(x, timeout=30), 2.0)
    finally:
        faults.install(prev)
    _close(router, rep)


def test_engine_drain_is_reentrant_and_observable(x):
    """The engine-level drain surface the replica shim and serve.py share."""
    eng = ServingEngine(lambda p, a: a * p, np.float32(3.0), max_batch=4,
                        name="drain-unit")
    assert np.allclose(eng.predict(x), 3.0)
    assert eng.drain(timeout=10)
    assert eng.draining
    with pytest.raises(RejectedError, match="draining"):
        eng.submit(x)
    assert eng.drain(timeout=10)  # idempotent
    eng.resume_admission()
    assert not eng.draining
    assert np.allclose(eng.predict(x), 3.0)
    shed = eng.registry.counter(
        "serving_shed_total", labels={"engine": "drain-unit",
                                      "reason": "draining"})
    assert shed.value == 1
    eng.close()


# -- rolling rollout ----------------------------------------------------------


def test_rolling_update_swaps_fleet_and_rolls_params(x):
    reps = [_make_replica(f"ru{i}", scale=2.0) for i in range(2)]
    router = _router(reps)
    router.refresh()
    report = router.rolling_update({"kind": "scale", "factor": 2.0},
                                   bake_s=0.1, poll_s=0.02)
    assert report["updated"] == ["ru0", "ru1"]
    assert not report["rolled_back"]
    # both replicas now serve the scaled tree (params 4.0)
    for _ in range(4):
        assert np.allclose(router.predict(x, timeout=30), 4.0)
    _close(router, *reps)


def test_rolling_swap_auto_rollback_on_injected_slo_burn(x):
    """The acceptance rollback drill, tier-1: swap replica ru0, inject
    post-swap dispatch faults (PIT_FAULTS machinery targeting ONLY ru0's
    per-engine site) under live traffic — its SLO burn crosses the
    threshold during the bake, the rollout rolls the WHOLE fleet back, and
    no router-accepted request is lost (failures re-route)."""
    slo = obs.SLO(latency_target_s=5.0, availability_target=0.9,
                  name="fabric", burn_alert=None, min_samples=5)
    reps = [_make_replica(f"rb{i}", slo=slo, dispatch_retries=0)
            for i in range(2)]
    router = _router(reps)
    router.refresh()
    x1 = np.ones((1, 3), np.float32)

    stop = threading.Event()
    lost = []

    def traffic():
        while not stop.is_set():
            try:
                fut = router.submit(x1)
                fut.result(30)
            except Exception as e:
                lost.append(e)
            time.sleep(0.002)

    injector = FaultInjector([FaultSpec(
        site="engine.dispatch.rb0-infer", kind="transient", every=1)])
    swapped = threading.Event()

    def arm_faults_after_swap():
        # the regression is strictly POST-swap: wait for ru0's version bump
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if reps[0].scrape().get("params_version", 0) >= 1:
                faults.install(injector)
                swapped.set()
                return
            time.sleep(0.005)

    prev = faults.install(None)
    t = threading.Thread(target=traffic, daemon=True)
    watcher = threading.Thread(target=arm_faults_after_swap, daemon=True)
    t.start()
    watcher.start()
    try:
        report = router.rolling_update(
            {"kind": "scale", "factor": 2.0}, bake_s=1.5,
            burn_threshold=2.0, poll_s=0.02, min_bake_requests=5,
        )
        assert swapped.is_set(), "faults never armed — the drill did not run"
        assert report["rolled_back"], report
        assert report["regressed"] == "rb0"
        assert "SLO burn" in report["reason"]
    finally:
        stop.set()
        t.join(timeout=10)
        faults.install(prev)
    # the fleet rolled back: serving the ORIGINAL tree again
    router.refresh()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:  # rb0 sheds its last faulted calls
        try:
            out = router.predict(x1, timeout=30)
            break
        except Exception:
            time.sleep(0.02)
    assert np.allclose(out, 2.0), "rollback must restore the previous params"
    assert not lost, f"accepted requests lost during rollout: {lost[:3]}"
    _close(router, *reps)


# -- fleet-aware health (the healthz fix) -------------------------------------


def test_fleet_health_degrades_label_not_router(x):
    """One replica's open breaker (or burning SLO) must degrade THAT
    replica's label in the fleet detail — never flip the router process's
    healthz() to unhealthy while other replicas serve. Only a fleet below
    min_serving goes unhealthy."""
    reps = [_make_replica(f"fh{i}", breaker_failures=1) for i in range(2)]
    router = _router(reps)
    # adopt the per-engine breakers under the fleet: without adoption they
    # would 503 the router's global healthz the moment one opens
    for rep in reps:
        router.fleet_health.adopt_source(
            rep.name,
            rep.app.engines["infer"].breaker,
        )
    router.refresh()
    ok, detail = obs.healthz()
    assert ok

    reps[0].app.engines["infer"].breaker.trip("test outage")
    router.refresh()
    ok, detail = obs.healthz()
    assert ok, f"one degraded replica must not 503 the router: {detail}"
    fleet = detail["sources"][f"fleet:{router.name}"]
    assert fleet["status"] == "degraded"
    assert fleet["replicas"]["fh0"]["state"] == "degraded"
    assert fleet["replicas"]["fh1"]["state"] == "serving"
    # traffic still flows around the degraded replica
    assert np.allclose(router.predict(x, timeout=30), 2.0)

    reps[1].kill()
    router.refresh()
    ok, detail = obs.healthz()
    assert not ok, "a fleet with nothing serving IS down"
    _close(router, *reps)


# -- scrape staleness + the fleet time-series (ISSUE 12) ----------------------


def test_router_scrape_staleness_degrades_placement(x):
    """A stale-but-up replica's frozen gauges must stop steering
    least-loaded dispatch: past ``stale_after_intervals`` the slot is
    DEGRADED for placement (routed around while any fresh replica serves),
    ``fleet_scrape_age_s{replica=}`` rides the registry, and the next
    completed scrape reinstates it."""
    reps = [_make_replica(f"st{i}") for i in range(2)]
    reg = obs.MetricsRegistry()
    # a long interval parks the background loop; refresh() drives scrapes
    router = Router(reps, registry=reg, scrape_interval_s=60.0,
                    stale_after_intervals=0.05)  # stale past the 0.5s floor
    try:
        router.refresh()
        labels = {"fleet": router.name, "replica": "st0"}
        age_key = obs.series_key("fleet_scrape_age_s", labels)
        assert reg.snapshot()["gauges"][age_key] < 0.5
        assert reg.gauge(
            "fleet_replica_requests_total",
            labels={"fleet": router.name, "replica": "st0"}).value >= 0
        assert all(s["state"] == "serving"
                   for s in router.statuses().values())
        # st0's view goes stale (the observation aged, not the replica)
        with router._lock:
            router._slots["st0"].last_scrape_mono -= 10.0
        st = router.statuses()
        assert st["st0"]["state"] == "degraded"
        assert st["st0"]["scrape_age_s"] > 0.5
        assert st["st1"]["state"] == "serving"
        # the exported gauge reports the LIVE age (computed at export by
        # the registry collector): a wedged scrape loop — which is exactly
        # when refresh() stops running — cannot freeze it near zero
        assert reg.snapshot()["gauges"][age_key] > 0.5
        # placement routes around the stale slot while a fresh one serves
        for _ in range(4):
            fut = router.submit(x)
            fut.result(timeout=30)
            assert fut.replica == "st1"
        # a completed scrape is a fresh observation: reinstated
        router.refresh()
        assert router.statuses()["st0"]["state"] == "serving"
    finally:
        _close(router, *reps)


def test_router_feeds_fleet_series_store(x):
    """The scrape loop feeds per-replica series into one fleet store
    (labels ``replica=``): a scraped LocalReplica leaves a queryable
    up/queue-depth/requests history instead of a point read."""
    reps = [_make_replica(f"ts{i}") for i in range(2)]
    router = _router(reps)
    try:
        router.refresh()
        assert np.allclose(router.predict(x, timeout=30), 2.0)
        router.refresh()
        router.refresh()
        labels = {"fleet": router.name, "replica": "ts0"}
        up = obs.series_key("fleet_replica_up", labels)
        pts = router.series.points(up)
        assert len(pts) >= 3 and all(v == 1.0 for _, v in pts)
        # the replica's lifetime request counter ingests counter-kind:
        # windowed delta answers "how much did this replica serve lately"
        served = 0.0
        for r in ("ts0", "ts1"):
            key = obs.series_key("fleet_replica_requests_total",
                                 {"fleet": router.name, "replica": r})
            assert router.series.kind(key) == "counter"
            served += router.series.delta(key, window_s=3600.0) or 0.0
        assert served >= 1.0
        # a killed replica's outage is visible IN the history (up drops
        # to 0), not a gap in it
        reps[0].kill()
        router.refresh()
        assert router.series.last(up) == 0.0
    finally:
        _close(router, *reps)


def test_bake_judges_burn_history_not_point_reads(x):
    """A burn spike the bake's own polls never catch (landed in the fleet
    series between polls — e.g. by the background scrape loop) must still
    roll the swap back: the bake judges the windowed MAX since the swap,
    not whatever the latest poll happened to read."""
    rep = _make_replica("bk0")
    router = _router([rep])
    try:
        router.refresh()
        assert router._bake(router._slots["bk0"], bake_s=0.1,
                            burn_threshold=2.0, poll_s=0.02,
                            min_requests=0) is None  # clean bake
        # a spike stamped inside the upcoming bake window, invisible to
        # every direct scrape (the replica's own gauge reads 0 throughout)
        router.series.record(
            obs.series_key("fleet_replica_slo_burn",
                           {"fleet": router.name, "replica": "bk0"}),
            9.0, "gauge", mono=time.monotonic() + 0.03)
        reason = router._bake(router._slots["bk0"], bake_s=0.3,
                              burn_threshold=2.0, poll_s=0.02,
                              min_requests=0)
        assert reason is not None and "SLO burn" in reason
    finally:
        _close(router, rep)


# -- the RPC shim over real HTTP (in-process server) --------------------------


def test_replica_http_rpc_roundtrip(x):
    """The wire protocol end to end against a live in-process ReplicaServer:
    arrays round-trip, sessions stay resident, admin verbs work, and error
    classes survive the hop (the mirrored-exception contract)."""
    rep = _make_replica("httprep", queue_limit=64)
    server = ReplicaServer(rep.app)
    url = server.start()
    client = HttpReplicaClient("httprep", url, timeout_s=30)
    try:
        out = client.call("infer", [x])
        assert np.allclose(out[0], 2.0)
        ack = client.call("encode", [x], session="s1")
        assert list(ack[0]) == [2, 3]
        dec = client.call("decode", [np.ones((2, 3), np.float32)],
                          session="s1")
        assert dec[0].shape == (2, 3)
        with pytest.raises(AffinityLost):
            client.call("decode", [np.ones((2, 3), np.float32)],
                        session="never-encoded")
        status = client.scrape()
        assert status["up"] and status["ready"]
        assert status["sessions"] == 1
        assert client.update_params({"kind": "scale", "factor": 0.5}) == 1
        assert np.allclose(client.call("infer", [x])[0], 1.0)
        assert client.update_params({"kind": "rollback"}) == 2
        assert np.allclose(client.call("infer", [x])[0], 2.0)
        assert client.drain(timeout_s=10)
        with pytest.raises(RejectedError, match="draining"):
            client.call("infer", [x])
        client.resume()
        assert np.allclose(client.call("infer", [x])[0], 2.0)
    finally:
        server.close()
        rep.app.close()
    # the dead-server signature is the failover taxonomy's transient class
    with pytest.raises(ConnectionError):
        client.call("infer", [x])


def test_serve_drain_handler_contract():
    """First SIGTERM raises _DrainRequested (stops admission, even out of a
    blocked read); later signals are absorbed so finish-in-flight cannot be
    aborted. restore() reinstates the host's handlers."""
    from perceiver_io_tpu.cli.serve import (
        _DrainRequested,
        _install_drain_handlers,
    )

    state, restore = _install_drain_handlers()
    try:
        with pytest.raises(_DrainRequested):
            os.kill(os.getpid(), signal.SIGTERM)
        assert state["draining"]
        os.kill(os.getpid(), signal.SIGTERM)  # absorbed, no raise
    finally:
        restore()


def test_load_bench_dry_fleet_schema():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "load_bench.py"),
         "--dry"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["fleet"] is None
    assert record["fleet_keys"] == [
        "replicas", "mode", "transport", "killed", "kill_at_frac",
        "kill_point", "reroutes", "affinity_spills", "lost_accepted",
        "restarts"]
    # r15: the tracing-overhead A/B block is declared in the schema
    assert record["trace"] is None
    assert record["trace_keys"] == [
        "ab_waves", "untraced_rps", "traced_rps", "overhead_pct",
        "spans_recorded", "generate_ab"]
    # r22: the transport A/B block (--trace_ab --transport uds|shmem)
    assert record["transport"] is None
    assert record["transport_keys"] == [
        "transport", "ab_waves", "wave_size", "http_rps", "transport_rps",
        "throughput_speedup", "http_rpc_p50_ms", "http_rpc_p99_ms",
        "rpc_p50_ms", "rpc_p99_ms", "rpc_p50_speedup", "spans_http",
        "spans_transport"]


# -- distributed request tracing (r15) ----------------------------------------


def test_rpc_phase_attribution_and_trace_cross_the_wire(x, tmp_path):
    """Satellite pin: the replica returns the engine future's ``phases``
    through the RPC (response header) and the router-side clients surface
    them — HTTP and LocalReplica in parity — while the propagated
    TraceContext parents the replica's spans under the caller's."""
    from perceiver_io_tpu.inference.engine import PHASES

    events = tmp_path / "ev.jsonl"
    obs.configure_event_log(str(events))
    rep = _make_replica("wire")
    server = ReplicaServer(rep.app)
    url = server.start()
    client = HttpReplicaClient("wire", url, timeout_s=30)
    try:
        ctx = obs.TraceContext.mint()
        meta = {}
        out = client.call("infer", [x], trace=ctx, meta=meta)
        assert np.allclose(out[0], 2.0)
        assert meta["phases"] and set(meta["phases"][0]) == set(PHASES)
        assert all(v >= 0 for v in meta["phases"][0].values())
        # LocalReplica parity: same meta/trace surface, same phase keys
        meta_local = {}
        LocalReplica(rep.app).call("infer", [x],
                                   trace=obs.TraceContext.mint(),
                                   meta=meta_local)
        assert meta_local["phases"] \
            and set(meta_local["phases"][0]) == set(PHASES)
        # attribution is unconditional — untraced calls carry phases too
        meta_untraced = {}
        client.call("infer", [x], meta=meta_untraced)
        assert meta_untraced["phases"]
    finally:
        server.close()
        rep.app.close()
        obs.configure_event_log(None)
    rows = [json.loads(l) for l in open(events)]
    serves = [r for r in rows if r.get("event") == "span"
              and r.get("name") == "replica_serve"]
    mine = [s for s in serves if s["trace"] == ctx.trace_id]
    assert mine and mine[0]["parent"] == ctx.span_id  # header roundtrip
    traces, _ = obs.assemble_traces(rows)
    engine_spans = [s for s in traces[ctx.trace_id]["spans"]
                    if s["name"] == "engine"]
    assert engine_spans and engine_spans[0]["parent"] == mine[0]["span"]


def test_fleet_tracing_assembles_and_reconciles(x, tmp_path):
    """THE r15 acceptance pin: every routed request's spans — router root,
    placement attempt, replica serve, engine + six phases — assemble into
    one tree whose durations reconcile with the latency histograms the SLO
    machinery already exports (the r11 5%-at-p50 bar, now cross-process),
    and the histograms' exemplars resolve to assembled traces."""
    import statistics

    events = tmp_path / "ev.jsonl"
    obs.configure_event_log(str(events))
    try:
        reg = obs.MetricsRegistry()  # shared by engines AND router so the
        # reconciliation reads histograms and exemplars from one place
        reps = [_make_replica(f"tr{i}", registry=reg) for i in range(2)]
        router = _router(reps, registry=reg)
        router.refresh()
        futs = [router.submit(x) for _ in range(24)]
        for f in futs:
            assert np.allclose(f.result(30), 2.0)
        # every router future carries a trace and the replica's phases
        from perceiver_io_tpu.inference.engine import PHASES

        assert all(f.trace is not None for f in futs)
        assert all(f.phases and set(f.phases[0]) == set(PHASES)
                   for f in futs)
        # close() joins the dispatch pool — the post-delivery root-span
        # bookkeeping (buffer add, exemplar) is complete after it
        _close(router, *reps)
        assert len(router.traces) == 24  # the exemplar-linked ring
    finally:
        obs.configure_event_log(None)

    traces, _ = obs.assemble_traces([json.loads(l) for l in open(events)])
    for f in futs:
        t = traces[f.trace.trace_id]
        names = [s["name"] for s in t["spans"]]
        assert t["root"]["name"] == "router_request"
        assert "router_attempt" in names and "replica_serve" in names
        assert "engine" in names
        assert sum(n.startswith("phase:") for n in names) >= 6
        # exclusive self-times reconcile with the root duration (5% bar)
        assert abs(t["span_sum_s"] - t["total_s"]) <= 0.05 * t["total_s"]
        # nesting: attempt within root, serve within attempt (one clock
        # here — the cross-clock alignment case is pinned in test_reqtrace)
        by = {s["name"]: s for s in t["spans"]}
        assert by["router_attempt"]["dur_s"] <= t["total_s"]
        assert by["replica_serve"]["dur_s"] \
            <= by["router_attempt"]["dur_s"] + 1e-6

    # root durations vs the router latency histogram: the SAME e2e the SLO
    # machinery measures, within 5% at p50
    hist = reg.histogram("router_latency_seconds",
                         labels={"router": "router"})
    assert hist.count == 24
    p50_hist = statistics.median(hist.values())
    p50_root = statistics.median(
        traces[f.trace.trace_id]["total_s"] for f in futs)
    assert abs(p50_root - p50_hist) <= 0.05 * p50_hist, (p50_root, p50_hist)

    # engine span (phase sum, assembled from the replica side of the RPC)
    # vs serving_latency_seconds: the r11 reconciliation, now cross-process
    engine_durs = []
    for f in futs:
        engine_durs.extend(
            s["dur_s"] for s in traces[f.trace.trace_id]["spans"]
            if s["name"] == "engine")
    served = []
    for i in range(2):
        for bucket in (1, 2, 4):
            served.extend(reg.histogram(
                "serving_latency_seconds",
                labels={"engine": f"tr{i}-infer",
                        "bucket": str(bucket)}).values())
    assert len(served) == 24
    p50_engine = statistics.median(engine_durs)
    p50_served = statistics.median(served)
    assert abs(p50_engine - p50_served) <= 0.05 * p50_served, \
        (p50_engine, p50_served)

    # exemplars: the p99-gauge → concrete-trace link
    exemplars = hist.exemplars()
    assert exemplars
    assert all(e["trace"] in traces for e in exemplars)


def test_chaos_kill_trace_shows_reroute_hop_zero_lost(x, tmp_path):
    """Chaos drill with tracing: kill one of three replicas under traffic —
    zero accepted requests lost, and every rerouted request's ASSEMBLED
    trace shows the failover hop (failed attempt on the victim, reroute
    span, successful attempt elsewhere)."""
    events = tmp_path / "ev.jsonl"
    obs.configure_event_log(str(events))
    try:
        reps = [_make_replica(f"ck{i}") for i in range(3)]
        router = _router(reps)
        futs = [router.submit(x) for _ in range(10)]
        reps[0].kill()
        futs += [router.submit(x) for _ in range(30)]
        for f in futs:
            assert np.allclose(f.result(30), 2.0)
        stats = router.stats()
        assert stats["failed"] == 0  # lost_accepted = 0
        assert stats["reroutes"] >= 1
        rerouted = [f for f in futs if f.attempts > 1]
        assert rerouted, "the kill never displaced a request"
        _close(router, *reps)
    finally:
        obs.configure_event_log(None)
    traces, _ = obs.assemble_traces([json.loads(l) for l in open(events)])
    for f in rerouted:
        t = traces[f.trace.trace_id]
        assert t["flags"]["reroute"], t["trace"]
        names = [s["name"] for s in t["spans"]]
        assert "router_reroute" in names
        attempts = [s for s in t["spans"] if s["name"] == "router_attempt"]
        assert any(s.get("ok") is False and s.get("replica") == "ck0"
                   for s in attempts), attempts
        ok_attempts = [s for s in attempts if s.get("ok")]
        assert ok_attempts and all(s["replica"] != "ck0"
                                   for s in ok_attempts)
        assert t["root"]["ok"] and t["root"]["replica"] != "ck0"
    # tail sampling always retains the failover traces
    kept = obs.tail_sample(traces, slow_pct=1.0, sample=0.0)
    assert {f.trace.trace_id for f in rerouted} <= set(kept)


# -- real-process drills (slow tier) ------------------------------------------


@pytest.mark.slow  # tier-1 budget (r12): real 3-process fleet + open-loop
# traffic + SIGKILL — the failover/zero-lost/reroute LOGIC stays tier-1 in
# test_router_failover_zero_lost_accepted; the load_bench fleet schema stays
# tier-1 in test_load_bench_dry_fleet_schema. This drill adds only the real
# process/socket/SIGKILL layer.
def test_chaos_drill_kill9_under_load_bench_traffic():
    """THE acceptance drill: open-loop load through the router over 3 real
    replica processes; kill -9 one mid-window; zero lost accepted requests
    and the supervisor restarts the victim."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "load_bench.py"),
         "--cpu", "--replicas", "3", "--replica_mode", "process",
         "--kill_replica_at", "0.5", "--kill_point", "0",
         "--duration_s", "2", "--rate_factors", "0.8",
         "--calibration_waves", "2", "--calibration_wave_size", "12"],
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout  # one-JSON-line contract holds
    record = json.loads(lines[0])
    fleet = record["fleet"]
    assert fleet["replicas"] == 3 and fleet["mode"] == "process"
    assert fleet["killed"] is not None
    assert fleet["lost_accepted"] == 0, fleet  # the drill's verdict
    assert fleet["reroutes"] >= 1
    assert fleet["restarts"] >= 1  # the supervisor brought the victim back
    point = record["sweep"][0]
    assert point["failed"] == 0
    assert point["completed"] > 0


@pytest.mark.slow  # tier-1 budget (r12): 2-process fleet bring-up + restart
# + rejoin gating + rolling swap over real sockets (~90s). The rejoin/ready
# gating LOGIC is tier-1 via LocalReplica scrapes (router JOINING state in
# test_fleet_health_degrades_label_not_router) and the rollback logic via
# test_rolling_swap_auto_rollback_on_injected_slo_burn.
def test_supervisor_restart_rejoins_only_when_ready_and_rolls():
    from perceiver_io_tpu.serving import ReplicaSupervisor

    with ReplicaSupervisor(
        count=2,
        extra_args=["--cpu", "--preset", "tiny", "--max_batch", "4"],
    ) as sup:
        clients = sup.start()
        sup.wait_ready(timeout_s=600)
        with Router(clients, scrape_interval_s=0.1) as router:
            router.refresh()
            ids = np.zeros((1, 64), np.int32)
            pad = np.zeros((1, 64), bool)
            pos = np.zeros((1, 2), np.int32)
            out = router.predict(ids, pad, pos, timeout=120)
            assert out.shape == (1, 2, 503)

            victim = clients[0].name
            sup.kill(victim)  # SIGKILL; babysitter restarts with backoff
            futs = [router.submit(ids, pad, pos) for _ in range(8)]
            for f in futs:  # zero lost through the kill
                assert f.result(120).shape == (1, 2, 503)
            # the restarted replica must pass through JOINING (ready=False)
            # before the router dispatches to it again: first wait for the
            # scrape loop to observe the death (the pre-kill "serving" view
            # is stale), then for the gated rejoin
            deadline = time.monotonic() + 600
            saw_down = saw_joining = False
            while time.monotonic() < deadline:
                state = router.statuses()[victim]["state"]
                saw_down = saw_down or state == "down"
                saw_joining = saw_joining or state == "joining"
                if saw_down and state == "serving":
                    break
                time.sleep(0.05)
            assert saw_down, "the scrape loop never observed the kill"
            assert router.statuses()[victim]["state"] == "serving"
            assert saw_joining, "rejoin must gate on engine_ready"
            assert sup.restarts(victim) == 1

            # rolling swap across the process fleet: zero dropped requests
            report = router.rolling_update({"kind": "reinit", "seed": 3},
                                           bake_s=0.3)
            assert report["updated"] and not report["rolled_back"]
            assert router.predict(ids, pad, pos,
                                  timeout=120).shape == (1, 2, 503)
            assert router.stats()["failed"] == 0


@pytest.mark.slow  # tier-1 budget (r12): trains a checkpoint and brings up
# a 2-process checkpoint-replica fleet (~2 min). Routing/affinity/rollout
# logic stays tier-1 in the in-process router tests above; the wire
# protocol in test_replica_http_rpc_roundtrip.
def test_serve_cli_fleet_matches_single_process(tmp_path):
    """serve.py --replicas 2 end to end over a real checkpoint: the fleet's
    fills equal the single-process engine's, --cached affinity works, and
    --rolling_swap_step hot-swaps the fleet without a rollback."""
    import glob

    from perceiver_io_tpu.cli import serve, train_mlm

    run_dir = train_mlm.main([
        "--synthetic", "--no_tensorboard",
        "--root", str(tmp_path / "cache"),
        "--logdir", str(tmp_path / "logs"), "--experiment", "fleetmlm",
        "--num_latents", "4", "--num_latent_channels", "16",
        "--num_encoder_layers", "1",
        "--num_self_attention_layers_per_block", "1",
        "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
        "--dtype", "float32", "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", "32", "--vocab_size", "120", "--max_steps", "2",
        "--log_every_n_steps", "1",
    ])
    ckpt = os.path.join(run_dir, "checkpoints")
    tok = glob.glob(str(tmp_path / "cache" / "*tokenizer*.json"))[0]
    base = ["--cpu", "--checkpoint", ckpt, "--tokenizer", tok,
            "--max_batch", "4", "--k", "3", "--no_warmup"]
    texts = ["a [MASK] b", "no mask here"]

    events = str(tmp_path / "fleet_events.jsonl")
    single = serve.main(base + ["--texts", *texts])
    fleet = serve.main(base + ["--replicas", "2", "--drain_timeout_s", "30",
                               "--rolling_swap_step", "2",
                               "--rolling_bake_s", "0.2",
                               "--events_jsonl", events,
                               "--texts", *texts])
    assert [l["fills"] for l in fleet] == [l["fills"] for l in single]

    # r15 tracing e2e: the router's log plus each replica process's own
    # <events>.<name> log assemble into CROSS-PROCESS traces for the served
    # requests (one text has a mask -> one routed request)
    import glob as _glob

    log_paths = sorted(_glob.glob(events + "*"))
    assert events in log_paths and len(log_paths) >= 3, log_paths
    records = []
    for p in log_paths:
        records.extend(json.loads(l) for l in open(p) if l.strip())
    traces, _ = obs.assemble_traces(records)
    assert traces, "no traces assembled from the fleet run"
    routed = [t for t in traces.values()
              if t["root"]["name"] == "router_request"]
    assert routed
    full = [t for t in routed
            if len(t["processes"]) > 1
            and any(s["name"] == "replica_serve" for s in t["spans"])
            and any(s["name"] == "engine" for s in t["spans"])]
    assert full, "no cross-process trace with replica+engine spans"
    for t in full:  # the reconciliation bar holds over the real RPC too
        assert abs(t["span_sum_s"] - t["total_s"]) <= 0.05 * t["total_s"]

    cached = serve.main(base + ["--replicas", "2", "--cached",
                                "--drain_timeout_s", "30",
                                "--texts", texts[0]])
    assert cached[0]["fills"] == single[0]["fills"]


@pytest.mark.slow  # tier-1 budget (r12): trains a checkpoint and runs a
# serve.py subprocess (~60s). The signal-handler contract stays tier-1 in
# test_serve_drain_handler_contract; fleet routing logic in the in-process
# router tests above.
def test_serve_cli_sigterm_drains_and_exits_zero(tmp_path):
    """serve.py --stdin under SIGTERM: admission stops, every line already
    submitted is ANSWERED on stdout, and the process exits 0 — a supervisor
    rotation never drops the queue."""
    import glob

    from perceiver_io_tpu.cli import train_mlm

    run_dir = train_mlm.main([
        "--synthetic", "--no_tensorboard",
        "--root", str(tmp_path / "cache"),
        "--logdir", str(tmp_path / "logs"), "--experiment", "drainmlm",
        "--num_latents", "4", "--num_latent_channels", "16",
        "--num_encoder_layers", "1",
        "--num_self_attention_layers_per_block", "1",
        "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
        "--dtype", "float32", "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", "32", "--vocab_size", "120", "--max_steps", "2",
        "--log_every_n_steps", "1",
    ])
    ckpt = os.path.join(run_dir, "checkpoints")
    tok = glob.glob(str(tmp_path / "cache" / "*tokenizer*.json"))[0]
    events = tmp_path / "events.jsonl"
    err_path = tmp_path / "serve.stderr"
    with open(err_path, "w") as err_file:
        proc = subprocess.Popen(
            [sys.executable, "-m", "perceiver_io_tpu.cli.serve", "--cpu",
             "--checkpoint", ckpt, "--tokenizer", tok, "--stdin",
             "--no_warmup", "--k", "2", "--drain_timeout_s", "60",
             "--events_jsonl", str(events)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=err_file, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            # signal only once admission is LIVE (the marker line): a
            # SIGTERM during startup is its own — also graceful — path
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                if "admitting stdin" in err_path.read_text():
                    break
                assert proc.poll() is None, "serve died during startup"
                time.sleep(0.2)
            proc.stdin.write("a [MASK] b\nthe [MASK] was\n")
            proc.stdin.flush()
            time.sleep(0.5)  # let the two lines be read and submitted
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=240)
        finally:
            if proc.poll() is None:
                proc.kill()
                out, _ = proc.communicate()
    err = err_path.read_text()
    assert proc.returncode == 0, f"drain must exit 0\n{err[-3000:]}"
    lines = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert len(lines) == 2, f"accepted lines dropped: {out!r}\n{err[-2000:]}"
    assert all(len(l["fills"]) == 1 for l in lines)
    assert "drain requested" in err
    assert events.exists()  # the event log was flushed on the drain path
