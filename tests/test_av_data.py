"""AV data module: synthetic cross-modal structure, npz-tree reader, loaders."""

import os

import numpy as np
import pytest

from perceiver_io_tpu.data.av import (
    AVDataModule,
    load_av_tree,
    synthetic_av_clips,
)


def test_synthetic_clips_class_structure():
    videos, audios, labels = synthetic_av_clips(
        8, (4, 8, 8, 1), num_audio_samples=256, num_classes=3, seed=0
    )
    assert videos.shape == (8, 4, 8, 8, 1)
    assert audios.shape == (8, 256, 1)
    assert labels.shape == (8,) and labels.max() < 3
    assert np.isfinite(videos).all() and np.isfinite(audios).all()
    # audio tones are class-conditioned: same class ⇒ same dominant frequency
    spectra = np.abs(np.fft.rfft(audios[..., 0], axis=1))
    peak = spectra[:, 1:].argmax(axis=1)
    for k in np.unique(labels):
        assert len(set(peak[labels == k])) == 1
    # distinct classes get distinct tones
    if len(np.unique(labels)) > 1:
        assert len(set(peak)) > 1


def test_data_module_loaders():
    dm = AVDataModule(
        video_shape=(2, 8, 8, 1), num_audio_samples=64, num_classes=3,
        batch_size=4, synthetic=True, synthetic_size=16,
    )
    dm.prepare_data()
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["video"].shape == (4, 2, 8, 8, 1)
    assert batch["audio"].shape == (4, 64, 1)
    assert batch["label"].shape == (4,)
    val = list(dm.val_dataloader())
    assert len(val) >= 1


def _write_clip(path, t, h, w, c, s, value):
    np.savez(
        path,
        video=np.full((t, h, w, c), value, np.float32),
        audio=np.full((s, 1), value, np.float32),
    )


def test_load_av_tree(tmp_path):
    root = tmp_path / "av"
    for cls, value in (("drumming", 0.25), ("singing", 0.75)):
        d = root / "train" / cls
        os.makedirs(d)
        _write_clip(d / "a.npz", 4, 8, 8, 3, 128, value)
        _write_clip(d / "b.npz", 4, 8, 8, 3, 128, value)
    videos, audios, labels, classes = load_av_tree(
        str(root), "train", (2, 8, 8, 3), 64, 1
    )
    assert classes == ["drumming", "singing"]
    assert videos.shape == (4, 2, 8, 8, 3)
    assert audios.shape == (4, 64, 1)
    np.testing.assert_array_equal(np.sort(labels), [0, 0, 1, 1])
    # class name order fixes label ids; values distinguish the classes
    assert videos[labels == 0].max() == 0.25
    assert videos[labels == 1].max() == 0.75

    # integer-dtype clips are rescaled to [0, 1] (dtype-dispatched, so even
    # an all-dark uint8 clip scales consistently)
    d8 = root / "train" / "uint8clips"
    os.makedirs(d8)
    np.savez(d8 / "c.npz",
             video=np.full((4, 8, 8, 3), 128, np.uint8),
             audio=np.zeros((128, 1), np.float32))
    v8, _, l8, classes8 = load_av_tree(str(root), "train", (2, 8, 8, 3), 64, 1)
    uint8_label = classes8.index("uint8clips")
    uint8_videos = v8[l8 == uint8_label]
    np.testing.assert_allclose(uint8_videos, 128 / 255, atol=1e-6)

    with pytest.raises(FileNotFoundError):
        load_av_tree(str(root), "missing_split", (2, 8, 8, 3), 64, 1)
    # clips smaller than the request are skipped; all-skipped raises
    with pytest.raises(FileNotFoundError):
        load_av_tree(str(root), "train", (8, 64, 64, 3), 64, 1)


def test_data_module_real_tree_fallback_val(tmp_path):
    root = tmp_path / "cache"
    d = root / "av" / "train" / "only"
    os.makedirs(d)
    for i in range(12):
        _write_clip(d / f"{i}.npz", 2, 8, 8, 1, 64, i / 12)
    dm = AVDataModule(
        root=str(root), video_shape=(2, 8, 8, 1), num_audio_samples=64,
        batch_size=4, synthetic=False,
    )
    dm.prepare_data()
    dm.setup()
    assert dm.num_classes == 1
    assert len(dm.ds_train) == 11 and len(dm.ds_valid) == 1
