"""End-to-end training-step tests: loss decreases on tiny synthetic tasks,
freezing semantics, loss masking."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from perceiver_io_tpu.models.adapters import (
    ClassificationOutputAdapter,
    ImageInputAdapter,
    TextInputAdapter,
    TextOutputAdapter,
)
from perceiver_io_tpu.models.perceiver import (
    PerceiverDecoder,
    PerceiverEncoder,
    PerceiverIO,
    PerceiverMLM,
)
from perceiver_io_tpu.ops.masking import IGNORE_LABEL, TextMasking
from perceiver_io_tpu.training import (
    TrainState,
    OptimizerConfig,
    cross_entropy_with_ignore,
    freeze_subtrees,
    make_classifier_steps,
    make_mlm_steps,
    make_optimizer,
)

VOCAB, L, C = 40, 16, 32


def build_image_classifier(image_shape=(8, 8, 1), num_classes=4):
    enc = PerceiverEncoder(
        input_adapter=ImageInputAdapter(image_shape=image_shape, num_frequency_bands=6),
        latent_shape=(8, C),
        num_layers=2,
    )
    dec = PerceiverDecoder(
        output_adapter=ClassificationOutputAdapter(
            num_classes=num_classes, num_output_channels=C
        ),
        latent_shape=(8, C),
    )
    return PerceiverIO(encoder=enc, decoder=dec)


def build_text_classifier(num_classes=2, dropout=0.0):
    enc = PerceiverEncoder(
        input_adapter=TextInputAdapter(vocab_size=VOCAB, max_seq_len=L, num_channels=C),
        latent_shape=(8, C),
        num_layers=2,
        dropout=dropout,
    )
    dec = PerceiverDecoder(
        output_adapter=ClassificationOutputAdapter(
            num_classes=num_classes, num_output_channels=C
        ),
        latent_shape=(8, C),
        dropout=dropout,
    )
    return PerceiverIO(encoder=enc, decoder=dec)


def build_mlm():
    enc = PerceiverEncoder(
        input_adapter=TextInputAdapter(vocab_size=VOCAB, max_seq_len=L, num_channels=C),
        latent_shape=(8, C),
        num_layers=2,
    )
    dec = PerceiverDecoder(
        output_adapter=TextOutputAdapter(
            vocab_size=VOCAB, max_seq_len=L, num_output_channels=C
        ),
        latent_shape=(8, C),
    )
    masking = TextMasking(
        vocab_size=VOCAB, unk_token_id=1, mask_token_id=2, num_special_tokens=3
    )
    return PerceiverMLM(encoder=enc, decoder=dec, masking=masking)


@pytest.mark.slow  # convergence smoke duplicated by the trainer fit
# tests, which train the same tiny classifier to a falling loss
def test_image_classifier_learns(rng):
    model = build_image_classifier()
    # learnable synthetic task: class = brightest quadrant
    n = 64
    images = rng.standard_normal((n, 8, 8, 1)).astype(np.float32) * 0.1
    labels = rng.integers(0, 4, n)
    for i, lab in enumerate(labels):
        r, c = divmod(int(lab), 2)
        images[i, r * 4 : r * 4 + 4, c * 4 : c * 4 + 4, 0] += 1.0
    batch = {"image": jnp.asarray(images), "label": jnp.asarray(labels)}

    variables = model.init(jax.random.key(0), batch["image"])
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=3e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(1))
    train_step, eval_step = make_classifier_steps(model, schedule, input_kind="image")
    train_step = jax.jit(train_step)

    first = None
    for _ in range(40):
        state, metrics = train_step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first * 0.5, (first, last)
    ev = eval_step(state, batch)
    assert float(ev["acc"]) > 0.5
    np.testing.assert_allclose(float(metrics["lr"]), 3e-3, rtol=1e-6)


@pytest.mark.slow  # tier-1 budget (r10): convergence coverage retained by
# tests/test_inference.py::test_mlm_fill_masks_learns_pattern (end-to-end
# learning) and the trainer fit tests (tests/test_trainer.py)
def test_mlm_learns(rng):
    model = build_mlm()
    # strongly structured data: token depends on position
    ids = np.tile(np.arange(L) % (VOCAB - 3) + 3, (32, 1)).astype(np.int32)
    pad = np.zeros((32, L), dtype=bool)
    batch = {"token_ids": jnp.asarray(ids), "pad_mask": jnp.asarray(pad)}

    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=3e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    train_step, eval_step, predict_fn = make_mlm_steps(model, schedule)
    train_step = jax.jit(train_step)

    losses = []
    for _ in range(60):
        state, metrics = train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    ev = eval_step(state, batch, jax.random.key(9))
    assert np.isfinite(ev["loss"])

    # predict path: no masking, logits over full vocab
    logits = predict_fn(state.params, batch["token_ids"], batch["pad_mask"])
    assert logits.shape == (32, L, VOCAB)


def test_frozen_encoder_transfer(rng):
    """Encoder params must not move when frozen; decoder must (reference
    train_seq_clf.py:18-24 + train/utils.py:5-8 semantics)."""
    model = build_text_classifier(dropout=0.1)
    ids = jnp.asarray(rng.integers(3, VOCAB, (16, L)).astype(np.int32))
    pad = jnp.zeros((16, L), dtype=bool)
    labels = jnp.asarray(rng.integers(0, 2, 16))
    batch = {"token_ids": ids, "pad_mask": pad, "label": labels}

    variables = model.init(jax.random.key(0), ids, pad)
    params = variables["params"]
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    tx = freeze_subtrees(tx, params, ["encoder"])
    state = TrainState.create(params, tx, jax.random.key(1))
    train_step, _ = make_classifier_steps(model, input_kind="text", frozen_encoder=True)
    train_step = jax.jit(train_step)

    for _ in range(3):
        state, metrics = train_step(state, batch)

    enc_before = jax.tree.leaves(params["encoder"])
    enc_after = jax.tree.leaves(state.params["encoder"])
    for a, b in zip(enc_before, enc_after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    dec_before = np.concatenate([np.ravel(x) for x in jax.tree.leaves(params["decoder"])])
    dec_after = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state.params["decoder"])])
    assert not np.allclose(dec_before, dec_after)


def test_cross_entropy_ignore_matches_torch(rng):
    import torch

    logits = rng.standard_normal((4, 10, 7)).astype(np.float32)
    labels = rng.integers(0, 7, (4, 10)).astype(np.int64)
    labels[:, ::3] = IGNORE_LABEL

    ours = float(cross_entropy_with_ignore(jnp.asarray(logits), jnp.asarray(labels)))
    theirs = float(
        torch.nn.functional.cross_entropy(
            torch.tensor(logits).permute(0, 2, 1), torch.tensor(labels)
        )
    )
    np.testing.assert_allclose(ours, theirs, rtol=1e-5)


def test_cross_entropy_all_ignored():
    logits = jnp.zeros((2, 3, 5))
    labels = jnp.full((2, 3), IGNORE_LABEL)
    assert float(cross_entropy_with_ignore(logits, labels)) == 0.0


def test_train_state_rng_streams():
    tx, _ = make_optimizer(OptimizerConfig())
    state = TrainState.create({"w": jnp.zeros(3)}, tx, jax.random.key(0))
    r1 = state.step_rngs("masking", "dropout")
    r2 = state.step_rngs("masking", "dropout")
    # same step → same keys; different streams differ
    assert jnp.array_equal(jax.random.key_data(r1["masking"]), jax.random.key_data(r2["masking"]))
    assert not jnp.array_equal(
        jax.random.key_data(r1["masking"]), jax.random.key_data(r1["dropout"])
    )
    state2 = state.replace(step=state.step + 1)
    r3 = state2.step_rngs("masking", "dropout")
    assert not jnp.array_equal(
        jax.random.key_data(r1["masking"]), jax.random.key_data(r3["masking"])
    )


def test_lean_ce_matches_optax(rng):
    """softmax_ce_integer (custom-VJP, no f32 logits materialization) matches
    optax's value and gradient in f32 and bf16."""
    import optax
    from perceiver_io_tpu.training.losses import softmax_ce_integer

    logits32 = jnp.asarray(rng.standard_normal((4, 7, 50)).astype(np.float32)) * 3
    labels = jnp.asarray(rng.integers(0, 50, (4, 7)))

    for dtype, atol in ((jnp.float32, 1e-6), (jnp.bfloat16, 3e-2)):
        logits = logits32.astype(dtype)
        ours = softmax_ce_integer(logits, labels)
        ref = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        )
        np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=atol)

        w = jnp.asarray(rng.standard_normal((4, 7)).astype(np.float32))
        g_ours = jax.grad(
            lambda l: jnp.sum(softmax_ce_integer(l, labels) * w)
        )(logits)
        g_ref = jax.grad(
            lambda l: jnp.sum(
                optax.softmax_cross_entropy_with_integer_labels(
                    l.astype(jnp.float32), labels
                ) * w
            )
        )(logits)
        assert g_ours.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(g_ours, np.float32), np.asarray(g_ref, np.float32),
            atol=atol,
        )


def test_fused_head_matches_unfused(rng):
    """fused_linear_cross_entropy_with_ignore == Dense + cross_entropy_with_ignore
    in value AND gradients (all inputs), f32."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.training.losses import (
        cross_entropy_with_ignore,
        fused_linear_cross_entropy_with_ignore,
    )

    B, K, C, V = 3, 7, 16, 1003  # V deliberately not a chunk multiple
    x = jnp.asarray(rng.normal(0, 1, (B, K, C)).astype(np.float32))
    w = jnp.asarray(rng.normal(0, 0.1, (C, V)).astype(np.float32))
    b = jnp.asarray(rng.normal(0, 0.1, (V,)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, V, (B, K)).astype(np.int32))
    labels = labels.at[0, :3].set(-100).at[2, -1].set(-100)

    def unfused(x, w, b):
        return cross_entropy_with_ignore(x @ w + b, labels)

    def fused(x, w, b):
        return fused_linear_cross_entropy_with_ignore(
            x, w, b, labels, chunk=256
        )

    ref, ref_grads = jax.value_and_grad(unfused, argnums=(0, 1, 2))(x, w, b)
    got, got_grads = jax.value_and_grad(fused, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    for g, r in zip(got_grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=1e-6)


@pytest.mark.slow  # tier-1 budget (r21): fused-vs-unfused value+grad parity
# stays tier-1 at the op level (test_fused_head_matches_unfused,
# test_fused_head_with_padded_vocab); the CLI flag e2e stays in
# tests/test_cli.py::test_train_mlm_fused_head_flag
def test_mlm_step_fused_head_matches_unfused(rng):
    """Full MLM train step: fused_head=True tracks the unfused loss/grads."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import perceiver_io_tpu as pit
    from perceiver_io_tpu.ops.masking import TextMasking
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
    )

    VOCAB, L, C, NLAT = 60, 24, 16, 8
    model = pit.PerceiverMLM(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_channels=C),
            latent_shape=(NLAT, C), num_layers=2,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_output_channels=C),
            latent_shape=(NLAT, C),
        ),
        masking=TextMasking(VOCAB, 1, 2, 3),
    )
    ids = jnp.asarray(rng.integers(3, VOCAB, (4, L)).astype(np.int32))
    batch = {"token_ids": ids, "pad_mask": jnp.zeros((4, L), bool)}
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)}, ids,
        batch["pad_mask"],
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))

    losses = {}
    params_out = {}
    for fused in (False, True):
        state = TrainState.create(
            jax.tree.map(jnp.copy, variables["params"]), tx, jax.random.key(2)
        )
        step, eval_step, _ = make_mlm_steps(
            model, sched, loss_gather_capacity=8, fused_head=fused
        )
        jit_step = jax.jit(step)
        ls = []
        for _ in range(3):
            state, m = jit_step(state, batch)
            ls.append(float(m["loss"]))
        losses[fused] = ls
        params_out[fused] = state.params
        # eval path too
        losses[(fused, "eval")] = float(
            eval_step(state, batch, jax.random.key(9))["loss"]
        )
    # the loss trajectory is the tight assertion: a wrong gradient would
    # compound through the 3 Adam steps and break it
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
    np.testing.assert_allclose(
        losses[(True, "eval")], losses[(False, "eval")], rtol=1e-5
    )
    # params agree to Adam noise: where a gradient is ~0, float-level
    # association differences (chunked vs full reductions) decide the
    # update's sign, bounding per-step divergence at O(lr) — the same
    # tolerance reasoning as test_golden_model's trajectory test
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2.5e-3
        ),
        params_out[True], params_out[False],
    )


@pytest.mark.slow  # tier-1 budget (r10): fused-head parity stays tier-1 in
# test_mlm_step_fused_head_matches_unfused; padded-vocab head behavior in
# tests/test_sharding.py::test_padded_vocab_projection_shards_under_tp
def test_fused_head_with_padded_vocab(rng):
    """pad_classes_to: padded columns must not leak into the fused lse."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import perceiver_io_tpu as pit
    from perceiver_io_tpu.ops.masking import TextMasking
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
    )

    VOCAB, L, C, NLAT = 60, 16, 16, 8
    def build(pad):
        return pit.PerceiverMLM(
            encoder=pit.PerceiverEncoder(
                input_adapter=pit.TextInputAdapter(
                    vocab_size=VOCAB, max_seq_len=L, num_channels=C),
                latent_shape=(NLAT, C), num_layers=1,
            ),
            decoder=pit.PerceiverDecoder(
                output_adapter=pit.TextOutputAdapter(
                    vocab_size=VOCAB, max_seq_len=L, num_output_channels=C,
                    pad_classes_to=pad),
                latent_shape=(NLAT, C),
            ),
            masking=TextMasking(VOCAB, 1, 2, 3),
        )

    padded = build(64)
    ids = jnp.asarray(rng.integers(3, VOCAB, (4, L)).astype(np.int32))
    batch = {"token_ids": ids, "pad_mask": jnp.zeros((4, L), bool)}
    variables = padded.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)}, ids,
        batch["pad_mask"],
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    out = {}
    for fused in (False, True):
        state = TrainState.create(
            jax.tree.map(jnp.copy, variables["params"]), tx, jax.random.key(2)
        )
        step, _, _ = make_mlm_steps(padded, sched, fused_head=fused)
        state, m = jax.jit(step)(state, batch)
        out[fused] = float(m["loss"])
    np.testing.assert_allclose(out[True], out[False], rtol=1e-5)
