"""Elastic autoscaling + admission control: the serving control loop.

Tier-1 coverage runs IN-PROCESS over trivial jitted engines behind
``LocalReplica`` shims (the test_fabric idiom): WFQ/token-bucket units, the
admission gate's shed taxonomy, the noisy-neighbor isolation pin, the
policy's hold-down/hysteresis state machine with injected clocks, the
end-to-end scale-up/scale-down loop over a live router, the spawn-failure
backoff chaos drill, and the supervisor's drain-then-SIGTERM retire path
(stub child processes — no jax import in the children, so the real
SIGTERM/port semantics stay tier-1 cheap).
"""

import os
import socket
import sys
import textwrap
import time

import numpy as np
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.inference import ServingEngine
from perceiver_io_tpu.resilience import (
    FaultInjector,
    FaultSpec,
    RejectedError,
    faults,
)
from perceiver_io_tpu.serving import (
    AdmissionController,
    Autoscaler,
    AutoscalePolicy,
    CallbackPool,
    LocalReplica,
    PriorityClass,
    ReplicaApp,
    ReplicaSupervisor,
    Router,
    TokenBucket,
    parse_priority_classes,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_replica(name, scale=2.0, registry=None, **engine_kw):
    """One in-process replica over a trivial jitted apply fn (the
    test_fabric idiom: the control loop is model-agnostic)."""

    def infer(p, x):
        return x * p

    engines = {
        "infer": ServingEngine(infer, np.float32(scale), max_batch=4,
                               name=f"{name}-infer",
                               **({"registry": registry}
                                  if registry is not None else {}),
                               **engine_kw)
    }
    app = ReplicaApp(engines, np.float32(scale), name=name,
                     assume_ready=True,
                     **({"registry": registry}
                        if registry is not None else {}))
    return LocalReplica(app)


def _router(replicas, **kw):
    kw.setdefault("scrape_interval_s", 0.02)
    kw.setdefault("registry", obs.MetricsRegistry())
    return Router(replicas, **kw)


@pytest.fixture
def x():
    return np.ones((2, 3), np.float32)


# -- units: token bucket + WFQ ------------------------------------------------


def test_token_bucket_rate_and_burst():
    b = TokenBucket(rate_per_s=10.0, burst=5.0, now=0.0)
    # a fresh bucket holds a full burst
    assert all(b.try_take(now=0.0) for _ in range(5))
    assert not b.try_take(now=0.0)
    # refill at the sustained rate, capped at the burst ceiling
    assert b.try_take(now=0.1)  # 1 token accrued
    assert not b.try_take(now=0.1)
    assert sum(b.try_take(now=10.0) for _ in range(8)) == 5  # capped at burst
    with pytest.raises(ValueError):
        TokenBucket(rate_per_s=0.0, burst=1.0)
    with pytest.raises(ValueError):
        TokenBucket(rate_per_s=1.0, burst=0.5)


def test_wfq_shares_service_by_weight():
    """Under backlog, pops interleave classes proportionally to weight
    (start-time fair queueing), FIFO within a class."""
    adm = AdmissionController(
        classes=[PriorityClass("gold", weight=4.0),
                 PriorityClass("bronze", weight=1.0)],
        queue_limit=1000, registry=obs.MetricsRegistry())
    for i in range(100):
        t = adm.admit(priority="gold")
        adm.enqueue(t, ("gold", i))
    for i in range(100):
        t = adm.admit(priority="bronze")
        adm.enqueue(t, ("bronze", i))
    popped = [adm.pop()[1][0] for _ in range(50)]
    gold = [p for p in popped if p[0] == "gold"]
    bronze = [p for p in popped if p[0] == "bronze"]
    # 4:1 weights → ~40 gold / ~10 bronze among the first 50
    assert len(gold) == pytest.approx(40, abs=2), (len(gold), len(bronze))
    # FIFO within each class
    assert [g[1] for g in gold] == sorted(g[1] for g in gold)
    assert [b[1] for b in bronze] == sorted(b[1] for b in bronze)
    # idle queue → None, and drain_queue empties the rest
    drained = adm.drain_queue()
    assert len(drained) == 150
    assert adm.pop() is None
    assert adm.queued() == 0


def test_parse_priority_classes_and_validation():
    assert [c.weight for c in parse_priority_classes("a:2,b")] == [2.0, 1.0]
    with pytest.raises(ValueError):
        parse_priority_classes("a:1,a:2")
    with pytest.raises(ValueError):
        AdmissionController(classes=[PriorityClass("x")], default_class="y",
                            registry=obs.MetricsRegistry())
    with pytest.raises(ValueError):
        PriorityClass("x", weight=0.0)


def test_admission_sheds_with_reason_and_burns_own_class():
    """Over-quota sheds carry reason='quota' and burn the CLIENT'S class
    SLO; a full class queue sheds reason='class_queue_full' while the
    other class's slots stay free."""
    reg = obs.MetricsRegistry()
    adm = AdmissionController(
        classes=[PriorityClass("gold", weight=4.0),
                 PriorityClass("bronze", weight=1.0)],
        default_class="gold",
        quota=(10.0, 2.0),
        client_classes={"abuser": "bronze"},
        queue_limit=10,  # gold share 8, bronze share 2
        slo=obs.SLO(latency_target_s=0.1, name="adm"),
        registry=reg)
    now = time.monotonic()
    # the abuser's burst (2 tokens) admits, the third sheds on quota
    for _ in range(2):
        adm.admit(client="abuser", now=now)
    with pytest.raises(RejectedError) as ei:
        adm.admit(client="abuser", now=now)
    assert ei.value.reason == "quota"
    # the quota shed burned the ABUSER'S class only: gold is untouched
    assert adm.stats()["slo_burn"]["bronze"] > 0.0
    assert adm.stats()["slo_burn"]["gold"] == 0.0
    # quota-less traffic (no client id) never draws a bucket; gold's share
    # of the queue (8 of 10) fills, then sheds name the class bound — while
    # bronze's 2 slots stay ITS slots (the abuser's earlier admits hold
    # them: the bound is per-class, not global)
    t_gold = [adm.admit(priority="gold", now=now) for _ in range(8)]
    with pytest.raises(RejectedError) as ei:
        adm.admit(priority="gold", now=now)
    assert ei.value.reason == "class_queue_full"
    assert "gold" in str(ei.value)
    # gold's own shed burns gold's budget — self-inflicted, by design
    for t in t_gold:
        adm.on_result(t, 0.01, ok=True)
    stats = adm.stats()
    assert stats["slo_burn"]["bronze"] > 0.0
    assert stats["slo_burn"]["gold"] > 0.0
    assert stats["shed"]["bronze:quota"] == 1
    assert stats["shed"]["gold:class_queue_full"] == 1
    assert stats["classes"]["gold"]["queue_limit"] == 8
    assert stats["classes"]["bronze"]["queue_limit"] == 2
    adm.close()


# -- router integration: noisy neighbor ---------------------------------------


def test_router_admission_isolates_noisy_neighbor(x):
    """The tier-1 noisy-neighbor pin: an abuser flooding past its quota
    sheds in ITS class while the victim's requests all complete and the
    victim's class burns nothing."""
    reg = obs.MetricsRegistry()
    adm = AdmissionController(
        classes=[PriorityClass("gold", weight=4.0),
                 PriorityClass("bronze", weight=1.0)],
        client_quotas={"abuser": (50.0, 8.0)},  # the victim is unlimited
        queue_limit=400,
        slo=obs.SLO(latency_target_s=5.0, name="nn"),
        registry=reg)
    r0, r1 = _make_replica("nn0", registry=reg), _make_replica(
        "nn1", registry=reg)
    router = _router([r0, r1], registry=reg, admission=adm)
    try:
        victim_futs, abuser_shed, abuser_futs = [], 0, []
        for i in range(120):
            # the abuser floods 4x the victim's rate from one client id
            for _ in range(2):
                try:
                    abuser_futs.append(router.submit(
                        x, client="abuser", priority="bronze"))
                except RejectedError as e:
                    assert e.reason in ("quota", "class_queue_full")
                    abuser_shed += 1
            if i % 2 == 0:
                victim_futs.append(router.submit(
                    x, client="victim", priority="gold"))
        for f in victim_futs:  # every victim request completes
            np.testing.assert_allclose(f.result(timeout=30), x * 2.0)
        for f in abuser_futs:
            f.result(timeout=30)
        assert abuser_shed > 0  # the flood DID overrun the quota
        stats = adm.stats()
        assert stats["slo_burn"]["gold"] == 0.0  # the victim paid nothing
        assert stats["slo_burn"]["bronze"] > 0.0  # the abuser paid itself
        assert stats["classes"]["gold"]["admitted"] == len(victim_futs)
    finally:
        router.close()
        r0.app.close()
        r1.app.close()


def test_router_admit_fault_site_sheds_cleanly(x):
    """The router.admit fault site: an injected raise at the gate sheds
    the request without leaking a pending slot or a queue token."""
    reg = obs.MetricsRegistry()
    adm = AdmissionController(queue_limit=8, registry=reg)
    rep = _make_replica("fs0", registry=reg)
    router = _router([rep], registry=reg, admission=adm)
    prev = faults.install(FaultInjector([
        FaultSpec(site="router.admit", kind="fatal", at=(2,))]))
    try:
        np.testing.assert_allclose(
            router.submit(x).result(timeout=30), x * 2.0)
        with pytest.raises(faults.InjectedFatalError):
            router.submit(x)
        # accounting is clean: the shed request was never pending, and the
        # next request flows
        np.testing.assert_allclose(
            router.submit(x).result(timeout=30), x * 2.0)
        assert router.stats()["pending"] == 0
        assert adm.queued() == 0
    finally:
        faults.install(prev)
        router.close()
        rep.app.close()


# -- the policy state machine (injected clock) --------------------------------


def _policy(**kw):
    kw.setdefault("rps_per_replica", 100.0)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("window_s", 5.0)
    kw.setdefault("hold_up_s", 1.0)
    kw.setdefault("hold_down_s", 3.0)
    kw.setdefault("cooldown_up_s", 2.0)
    kw.setdefault("cooldown_down_s", 5.0)
    return AutoscalePolicy(**kw)


class _FakeRouter:
    """The autoscaler's router surface over a hand-fed series store."""

    def __init__(self):
        self.series = obs.SeriesStore()
        self.name = "fake"
        self._replicas = ["r0"]
        self.drained = []

    def replicas(self):
        return list(self._replicas)

    def statuses(self):
        return {n: {"state": "serving", "router_inflight": 0,
                    "queue_depth": 0} for n in self._replicas}

    def add_replica(self, client):
        self._replicas.append(client.name)

    def drain_replica(self, name, timeout_s=None, detach=False):
        self.drained.append(name)
        if detach:
            self._replicas.remove(name)
        return True

    def latency_exemplars(self, n=4):
        return []


class _FakeClient:
    def __init__(self, name):
        self.name = name


class _FakePool:
    def __init__(self, fail=0):
        self.spawned = 0
        self.retired = []
        self.fail = fail  # first N spawns raise

    def spawn(self):
        self.spawned += 1
        if self.spawned <= self.fail:
            raise OSError("fork failed (injected)")
        return _FakeClient(f"s{self.spawned}")

    def retire(self, name):
        self.retired.append(name)


def _feed_demand(router, rps, n_replicas, t0, now, step=0.5):
    """Write a requests_total counter ramp at ``rps`` per replica into the
    fake fleet store between monotonic stamps t0..now."""
    for i, name in enumerate(router.replicas()[:n_replicas]):
        key = obs.series_key("fleet_replica_requests_total",
                             {"fleet": router.name, "replica": name})
        t = t0
        while t <= now:
            router.series.record(key, rps * (t - t0), "counter",
                                 t=t, mono=t)
            t += step


def test_policy_validation():
    with pytest.raises(ValueError):
        _policy(rps_per_replica=0.0)
    with pytest.raises(ValueError):
        _policy(scale_down_utilization=0.8, target_utilization=0.7)
    with pytest.raises(ValueError):
        _policy(down_burn=2.0, up_burn=1.0)
    with pytest.raises(ValueError):
        _policy(min_replicas=3, max_replicas=2)
    # the capacity-fit seed: sustainable rps over the measured fleet size
    p = AutoscalePolicy.from_capacity(
        {"slo_sustainable_rps": 300.0}, replicas_measured=3)
    assert p.rps_per_replica == 100.0


def test_autoscaler_hold_down_blocks_one_tick_spike():
    """A demand spike shorter than hold_up_s never scales (the bursty-
    minute flap guard); sustained demand does — bounded by max_step — and
    the cooldown blocks an immediate second step."""
    router, pool = _FakeRouter(), _FakePool()
    auto = Autoscaler(router, pool, _policy(), registry=obs.MetricsRegistry())
    t0 = 1000.0
    # sustained 300 rps against 100 rps/replica @ 0.7 target → desired 4
    _feed_demand(router, 300.0, 1, t0 - 6.0, t0 + 4.0)
    # first tick: condition starts holding — no action yet (hold_up_s=1)
    assert auto.tick(now=t0) is None
    assert pool.spawned == 0
    # still inside the hold window
    assert auto.tick(now=t0 + 0.5) is None
    # held long enough → acts (max_step=2 bounds the jump below desired 4)
    dec = auto.tick(now=t0 + 1.2)
    assert dec is not None and dec["action"] == "scale_up"
    assert pool.spawned == 2 and len(router.replicas()) == 3
    # demand still wants 4, the hold re-arms...
    assert auto.tick(now=t0 + 1.4) is None
    # ...and even with the hold satisfied again, the cooldown (until
    # t0+3.2) blocks the second step
    assert auto.tick(now=t0 + 2.5) is None
    assert pool.spawned == 2
    # past the cooldown the held condition finally takes the last step
    dec2 = auto.tick(now=t0 + 3.3)
    assert dec2 is not None and dec2["action"] == "scale_up"
    assert len(router.replicas()) == 4
    auto.close()


def test_autoscaler_scale_down_hysteresis_and_drain():
    """Scale-down engages only after the low condition holds hold_down_s,
    via drain-then-retire (never a kill), and the dead band between the
    up/down utilization bounds never flaps."""
    router, pool = _FakeRouter(), _FakePool()
    router._replicas = ["r0", "r1", "r2"]
    auto = Autoscaler(router, pool, _policy(), registry=obs.MetricsRegistry())
    t0 = 2000.0
    # 40 rps over 3 replicas → demand/(2*100) = 0.2 < 0.45: down territory
    _feed_demand(router, 40.0 / 3, 3, t0 - 6.0, t0 + 4.0)
    assert auto.tick(now=t0) is None  # hold starts
    assert auto.tick(now=t0 + 1.0) is None  # still holding
    dec = auto.tick(now=t0 + 3.1)
    assert dec is not None and dec["action"] == "scale_down"
    assert router.drained == pool.retired  # drain-THEN-retire, same victim
    assert len(router.replicas()) == 2
    # the dead band: utilization between down (0.45) and up (0.7) bounds
    # with 2 replicas — 120 rps → desired ceil(120/70)=2 == n, and the
    # down check 120/(1*100)=1.2 > 0.45 → neither direction ever moves
    router2, pool2 = _FakeRouter(), _FakePool()
    router2._replicas = ["r0", "r1"]
    auto2 = Autoscaler(router2, pool2, _policy(),
                       registry=obs.MetricsRegistry())
    _feed_demand(router2, 60.0, 2, t0 + 14.0, t0 + 32.0)
    for dt in (0.0, 1.5, 3.5, 6.0, 10.0):
        assert auto2.tick(now=t0 + 20.0 + dt) is None
    assert pool2.spawned == 0 and pool2.retired == []
    auto.close()
    auto2.close()


def test_autoscaler_spawn_failure_backs_off_capped(x):
    """The chaos drill's core: failing spawns defer the next attempt with
    capped exponential backoff — the autoscaler never hammers spawn in a
    tight loop, and recovery resets the failure count."""
    from perceiver_io_tpu.resilience import RetryPolicy

    router = _FakeRouter()
    pool = _FakePool(fail=3)
    reg = obs.MetricsRegistry()
    auto = Autoscaler(router, pool, _policy(hold_up_s=0.0, cooldown_up_s=0.0),
                      spawn_backoff=RetryPolicy(max_retries=8, base_s=0.5,
                                                max_s=30.0, jitter=0.0),
                      registry=reg)
    t0 = 3000.0
    _feed_demand(router, 300.0, 1, t0 - 6.0, t0 + 60.0)
    dec = auto.tick(now=t0)
    assert dec["action"] == "spawn_failed" and pool.spawned == 1
    backoff1 = dec["backoff_s"]
    # inside the backoff window: NO spawn attempt despite demand
    assert auto.tick(now=t0 + backoff1 / 2) is None
    assert pool.spawned == 1
    # past it: the next attempt fires, fails again, backs off LONGER
    dec2 = auto.tick(now=t0 + backoff1 + 0.01)
    assert dec2["action"] == "spawn_failed" and pool.spawned == 2
    assert dec2["backoff_s"] > backoff1
    dec3 = auto.tick(now=t0 + backoff1 + dec2["backoff_s"] + 0.1)
    assert dec3["action"] == "spawn_failed" and pool.spawned == 3
    # recovery: the 4th attempt succeeds, failure state resets
    t_ok = t0 + backoff1 + dec2["backoff_s"] + dec3["backoff_s"] + 0.2
    dec4 = auto.tick(now=t_ok)
    assert dec4["action"] == "scale_up"
    assert reg.gauge("autoscale_spawn_backoff_s",
                     labels={"router": "fake"}).value == 0.0
    assert auto.stats()["spawn_failures"] == 3
    auto.close()


# -- end-to-end over a live router --------------------------------------------


def test_autoscaler_scales_live_fleet_up_and_down(x):
    """The closed loop over real engines: offered load grows the fleet
    (spawned replica JOINs and serves), load stops and the fleet drains
    back down — with the retired replica's gauges and series leaving the
    fleet store, and zero lost accepted requests throughout."""
    reg = obs.MetricsRegistry()
    made = []

    def spawn():
        rep = _make_replica(f"dyn{len(made)}", registry=reg)
        made.append(rep)
        return rep

    def retire(name):
        for rep in made:
            if rep.name == name:
                rep.app.close()

    first = spawn()
    router = _router([first], registry=reg)
    policy = AutoscalePolicy(
        rps_per_replica=200.0, min_replicas=1, max_replicas=3,
        window_s=2.0, hold_up_s=0.05, hold_down_s=0.2,
        cooldown_up_s=0.1, cooldown_down_s=0.2, max_step=1,
        drain_timeout_s=10.0)
    auto = Autoscaler(router, CallbackPool(spawn, retire), policy,
                      registry=reg)
    futs = []
    try:
        deadline = time.monotonic() + 20.0
        # offered load well past one replica's 200 rps fit
        while len(router.replicas()) < 2 and time.monotonic() < deadline:
            for _ in range(8):
                futs.append(router.submit(x))
            router.refresh()
            auto.tick()
            time.sleep(0.02)
        assert len(router.replicas()) >= 2, "never scaled up"
        assert auto.stats()["scale_ups"] >= 1
        assert reg.gauge("fleet_target_replicas",
                         labels={"router": router.name}).value >= 2
        for f in futs:  # nothing accepted was lost across the scale event
            np.testing.assert_allclose(f.result(timeout=30), x * 2.0)
        # demand stops → the fleet drains back to min, drain-then-retire
        deadline = time.monotonic() + 20.0
        while len(router.replicas()) > 1 and time.monotonic() < deadline:
            router.refresh()
            auto.tick()
            time.sleep(0.02)
        assert len(router.replicas()) == 1, "never scaled down"
        assert auto.stats()["scale_downs"] >= 1
        gone = [r.name for r in made if r.name not in router.replicas()]
        assert gone, "no replica retired"
        victim = gone[0]
        # the retired replica's telemetry left the fleet store with it
        assert not router.series.match(obs.series_key(
            "fleet_replica_up", {"fleet": router.name, "replica": victim}))
        snap_keys = [k for k in reg.snapshot()["gauges"]
                     if "fleet_replica_up" in k and f'"{victim}"' in k]
        assert snap_keys == []
        assert int(router.stats()["failed"]) == 0  # lost_accepted == 0
    finally:
        auto.close()
        router.close()
        for rep in made:
            rep.app.close()


def test_autoscale_chaos_injected_spawn_failure_no_flap(x):
    """The acceptance chaos drill (satellite 1): PIT-FAULTS-style injected
    spawn failure at autoscale.scale → backoff engages, the fleet never
    flaps (no retire follows the failed grow), and lost_accepted stays 0."""
    reg = obs.MetricsRegistry()
    made = []

    def spawn():
        rep = _make_replica(f"cx{len(made)}", registry=reg)
        made.append(rep)
        return rep

    first = spawn()
    router = _router([first], registry=reg)
    policy = AutoscalePolicy(
        rps_per_replica=200.0, min_replicas=1, max_replicas=2,
        window_s=2.0, hold_up_s=0.0, hold_down_s=5.0,
        cooldown_up_s=0.0, cooldown_down_s=5.0, max_step=1)
    auto = Autoscaler(router, CallbackPool(spawn), policy, registry=reg)
    prev = faults.install(FaultInjector([
        FaultSpec(site="autoscale.scale", kind="transient", at=(1,))]))
    futs = []
    try:
        replica_counts = set()
        spawned_ok = False
        deadline = time.monotonic() + 20.0
        while not spawned_ok and time.monotonic() < deadline:
            for _ in range(8):
                futs.append(router.submit(x))
            router.refresh()
            dec = auto.tick()
            replica_counts.add(len(router.replicas()))
            if dec is not None and dec["action"] == "scale_up":
                spawned_ok = True
            time.sleep(0.02)
        st = auto.stats()
        assert st["spawn_failures"] == 1  # the injected failure fired
        assert spawned_ok, "never recovered past the injected spawn failure"
        assert st["scale_downs"] == 0  # no flap: growth pressure never
        # produced a retire, and the count moved monotonically 1 → 2
        assert replica_counts <= {1, 2}
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=30), x * 2.0)
        assert int(router.stats()["failed"]) == 0  # lost_accepted == 0
    finally:
        faults.install(prev)
        auto.close()
        router.close()
        for rep in made:
            rep.app.close()


# -- supervisor retire path (stub children: real signals, no jax) -------------

_STUB_REPLICA = textwrap.dedent("""\
    import json, signal, sys
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    port = int(sys.argv[sys.argv.index("--port") + 1])
    state = {"drained": False}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass
        def _reply(self, body):
            body = json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        def do_GET(self):
            self._reply({"replica": {"ready": True, "up": True}})
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            if self.path.startswith("/admin/drain"):
                state["drained"] = True
            self._reply({"drained": True})

    httpd = ThreadingHTTPServer(("127.0.0.1", port), H)
    httpd.daemon_threads = True
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
    print("stub replica on", port, file=sys.stderr, flush=True)
    httpd.serve_forever()
""")


@pytest.mark.slow  # tier-1 budget (r21): drain-then-retire scale-down
# semantics (lost_accepted == 0) stay tier-1 in the in-process
# CallbackPool autoscale tests; the real-process SIGTERM/port drill runs
# in the full tier
def test_supervisor_retire_drains_sigterms_and_releases_port(tmp_path):
    """The retire path (satellite 3): graceful drain RPC → SIGTERM exit 0
    → port released; the babysitter never restarts a retirement; and
    add_replica grows the supervised set at runtime."""
    stub = tmp_path / "stub_replica.py"
    stub.write_text(_STUB_REPLICA)

    def argv(name, port):
        return [sys.executable, str(stub), "--port", str(port),
                "--name", name]

    reg = obs.MetricsRegistry()
    sup = ReplicaSupervisor(count=1, argv_builder=argv, cpu=True,
                            poll_s=0.05, registry=reg,
                            log_dir=str(tmp_path))
    try:
        clients = sup.start()
        sup.wait_ready(timeout_s=20.0)
        # runtime growth: a second replica joins the supervised set
        extra = sup.add_replica()
        sup.wait_ready(timeout_s=20.0, names=[extra.name])
        assert {c.name for c in sup.clients()} == {clients[0].name,
                                                   extra.name}
        port = next(rep.port for n, rep in sup._replicas.items()
                    if n == extra.name)
        proc = sup._replicas[extra.name].proc
        # retire: drain-then-SIGTERM; the child's handler exits 0
        assert sup.retire(extra.name, drain_timeout_s=5.0) is True
        assert proc.poll() == 0, "SIGTERM did not produce a graceful exit 0"
        assert extra.name not in {c.name for c in sup.clients()}
        with pytest.raises(KeyError):
            sup.retire(extra.name)
        # the port is RELEASED (bindable again)
        deadline = time.monotonic() + 5.0
        while True:
            try:
                with socket.socket() as s:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind(("127.0.0.1", port))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)
        # the babysitter never restarted the retirement
        time.sleep(0.3)
        assert extra.name not in sup._replicas
        # ...and its restart counter left /metrics with it (autoscale churn
        # mints new names forever — dead counters must not accumulate)
        assert not any(f'replica="{extra.name}"' in k
                       for k in reg.snapshot()["counters"])
        # the surviving replica is untouched
        assert clients[0].scrape().get("ready")
    finally:
        sup.stop(timeout_s=10.0)


def test_serve_cli_autoscale_flag_validation():
    """serve.py refuses --autoscale without a fleet or without a MEASURED
    per-replica capacity fit (a guessed fit is how fleets flap), before
    touching any backend."""
    from perceiver_io_tpu.cli import serve

    base = ["--checkpoint", "/nonexistent", "--tokenizer", "/nonexistent",
            "--texts", "x"]
    with pytest.raises(SystemExit, match="--replicas"):
        serve.main([*base, "--autoscale",
                    "--autoscale_rps_per_replica", "100"])
    with pytest.raises(SystemExit, match="rps_per_replica"):
        serve.main([*base, "--replicas", "2", "--autoscale"])
    with pytest.raises(SystemExit, match="--replicas"):
        serve.main([*base, "--priority_classes", "gold:2,bronze:1"])


@pytest.mark.slow  # tier-1 budget (r17): a real load_bench schedule run is
# ~60 s of open-loop traffic; the control loop's logic coverage is retained
# tier-1 by test_autoscaler_scales_live_fleet_up_and_down and
# test_autoscale_chaos_injected_spawn_failure_no_flap above, and the dry
# schema by test_cli.test_load_bench_dry_emits_schema_json_line
def test_load_bench_autoscale_schedule_contract():
    """The acceptance run end-to-end through the CLI: a step schedule with
    --autoscale emits ONE JSON line whose autoscale block shows the fleet
    growing and shrinking with zero lost accepted requests and fewer
    replica-seconds than the static peak fleet."""
    import json
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "load_bench.py"),
         "--cpu", "--replicas", "1", "--autoscale", "--schedule", "step",
         "--schedule_period_s", "3", "--max_replicas", "3"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    a = record["autoscale"]
    assert a["enabled"] and a["schedule"] == "step"
    assert a["scale_ups"] >= 1 and a["peak_replicas"] >= 2
    assert a["lost_accepted"] == 0
    assert a["replica_seconds"] < a["static_replica_seconds"]
    assert record["sweep"], "schedule segments must ride the sweep array"


def test_router_detach_removes_gauges_and_series(x):
    """Router.drain_replica(detach=True): the replica's per-replica gauges
    leave /metrics and its history leaves the fleet series store (the
    scale-down cleanup contract, pinned at the router level)."""
    reg = obs.MetricsRegistry()
    r0, r1 = _make_replica("dt0", registry=reg), _make_replica(
        "dt1", registry=reg)
    router = _router([r0, r1], registry=reg)
    try:
        for _ in range(4):
            router.submit(x).result(timeout=30)
        router.refresh()
        up_key = obs.series_key(
            "fleet_replica_up", {"fleet": router.name, "replica": "dt1"})
        assert router.series.match(up_key)
        assert any(k.startswith("fleet_") and 'replica="dt1"' in k
                   for k in reg.snapshot()["gauges"])
        assert router.drain_replica("dt1", timeout_s=10.0, detach=True)
        assert "dt1" not in router.replicas()
        assert not router.series.match(up_key)
        router.refresh()  # a post-detach sweep must not resurrect it
        assert not router.series.match(up_key)
        assert not any(k.startswith("fleet_") and 'replica="dt1"' in k
                       for k in reg.snapshot()["gauges"])
        # the tombstone: a scrape sweep that snapshotted the fleet BEFORE
        # the removal (simulated by publishing directly) must not
        # re-register the retired replica's gauges
        router._gauges.publish("dt1", up=1.0, queue_depth=3.0)
        assert not any(k.startswith("fleet_") and 'replica="dt1"' in k
                       for k in reg.snapshot()["gauges"])
    finally:
        router.close()
        r0.app.close()
        r1.app.close()
