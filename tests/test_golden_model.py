"""Full-model golden parity vs a torch oracle (SURVEY.md §4 test tier (b)).

Assembles the reference's documented composition (reference
``perceiver/model.py``: pre-LN cross/self attention via
``torch.nn.MultiheadAttention``, residual-on-first-arg, constant-width MLP,
encoder layer_1 unique + layer_n weight-shared recurrence, learned
latent/output query arrays, text adapter = embedding·√C + learned positions)
out of torch primitives, ports every weight into the flax model, and asserts
the two frameworks produce the same numbers end to end.
"""

import math

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

import perceiver_io_tpu as pit

B, L, VOCAB, C, N_LATENT, HEADS = 2, 10, 40, 16, 6, 4
NUM_LAYERS, SELF_PER_BLOCK = 3, 2


# -- torch oracle (reference semantics, built from torch primitives) ---------


class TorchMLP(torch.nn.Module):
    def __init__(self, c):
        super().__init__()
        self.norm = torch.nn.LayerNorm(c)
        self.l1 = torch.nn.Linear(c, c)
        self.l2 = torch.nn.Linear(c, c)

    def forward(self, x):
        return self.l2(torch.nn.functional.gelu(self.l1(self.norm(x))))


class TorchCrossLayer(torch.nn.Module):
    """Residual(pre-LN cross-attention) + Residual(MLP), residual on the
    query stream (reference model.py:29-34,47-56,77-99)."""

    def __init__(self, q_ch, kv_ch, heads):
        super().__init__()
        self.q_norm = torch.nn.LayerNorm(q_ch)
        self.kv_norm = torch.nn.LayerNorm(kv_ch)
        self.attn = torch.nn.MultiheadAttention(
            embed_dim=q_ch, num_heads=heads, kdim=kv_ch, vdim=kv_ch,
            batch_first=True,
        )
        self.mlp = TorchMLP(q_ch)

    def forward(self, x_q, x_kv, pad_mask=None):
        q, kv = self.q_norm(x_q), self.kv_norm(x_kv)
        attn_out, _ = self.attn(q, kv, kv, key_padding_mask=pad_mask)
        x = attn_out + x_q
        return self.mlp(x) + x


class TorchSelfLayer(torch.nn.Module):
    def __init__(self, c, heads):
        super().__init__()
        self.norm = torch.nn.LayerNorm(c)
        self.attn = torch.nn.MultiheadAttention(
            embed_dim=c, num_heads=heads, batch_first=True
        )
        self.mlp = TorchMLP(c)

    def forward(self, x):
        h = self.norm(x)
        attn_out, _ = self.attn(h, h, h)
        x = attn_out + x
        return self.mlp(x) + x


class TorchPerceiverLayer(torch.nn.Module):
    def __init__(self, q_ch, kv_ch, heads, self_layers):
        super().__init__()
        self.cross = TorchCrossLayer(q_ch, kv_ch, heads)
        self.selfs = torch.nn.ModuleList(
            [TorchSelfLayer(q_ch, heads) for _ in range(self_layers)]
        )

    def forward(self, latent, x, pad_mask=None):
        latent = self.cross(latent, x, pad_mask)
        for layer in self.selfs:
            latent = layer(latent)
        return latent


class TorchOracle(torch.nn.Module):
    """Text classifier: embed·√C + pos enc → encoder (layer_1 unique,
    layer_n shared × num_layers−1) → decoder cross-attn → linear head."""

    def __init__(self, num_classes=3):
        super().__init__()
        self.embed = torch.nn.Embedding(VOCAB, C)
        self.pos = torch.nn.Parameter(torch.rand(L, C) - 0.5)
        self.latent = torch.nn.Parameter(torch.randn(N_LATENT, C) * 0.02)
        self.layer_1 = TorchPerceiverLayer(C, C, HEADS, SELF_PER_BLOCK)
        self.layer_n = TorchPerceiverLayer(C, C, HEADS, SELF_PER_BLOCK)
        self.output = torch.nn.Parameter(torch.randn(1, C) * 0.02)
        self.dec_cross = TorchCrossLayer(C, C, HEADS)
        self.head = torch.nn.Linear(C, num_classes)

    def forward(self, ids, pad_mask=None):
        b = ids.shape[0]
        x = self.embed(ids) * math.sqrt(C) + self.pos[: ids.shape[1]]
        latent = self.latent.expand(b, -1, -1)
        latent = self.layer_1(latent, x, pad_mask)
        for _ in range(NUM_LAYERS - 1):
            latent = self.layer_n(latent, x, pad_mask)
        out = self.output.expand(b, -1, -1)
        out = self.dec_cross(out, latent)
        return self.head(out).squeeze(1)


# -- weight port: torch oracle → flax param tree -----------------------------


def _t(x):
    # np.array (copy), NOT np.asarray: .numpy() returns a VIEW of the torch
    # tensor's buffer, and on CPU jnp.asarray can zero-copy alias it — an
    # in-place torch opt.step() would then silently mutate the "jax" params
    return np.array(x.detach().numpy())


def _mha(attn: torch.nn.MultiheadAttention, e: int):
    sd = attn.state_dict()
    if "in_proj_weight" in sd:  # merged projections (q/k/v dims equal)
        w = _t(sd["in_proj_weight"])
        qw, kw, vw = w[:e], w[e : 2 * e], w[2 * e :]
    else:
        qw, kw, vw = _t(sd["q_proj_weight"]), _t(sd["k_proj_weight"]), _t(sd["v_proj_weight"])
    b_in = _t(sd["in_proj_bias"])
    return {
        "q_proj": {"kernel": qw.T, "bias": b_in[:e]},
        "k_proj": {"kernel": kw.T, "bias": b_in[e : 2 * e]},
        "v_proj": {"kernel": vw.T, "bias": b_in[2 * e :]},
        "out_proj": {"kernel": _t(sd["out_proj.weight"]).T,
                     "bias": _t(sd["out_proj.bias"])},
    }


def _ln(ln):
    return {"scale": _t(ln.weight), "bias": _t(ln.bias)}


def _mlp(mlp: TorchMLP):
    return {
        "norm": _ln(mlp.norm),
        "dense_1": {"kernel": _t(mlp.l1.weight).T, "bias": _t(mlp.l1.bias)},
        "dense_2": {"kernel": _t(mlp.l2.weight).T, "bias": _t(mlp.l2.bias)},
    }


def _cross_layer(cl: TorchCrossLayer):
    return {
        "cross_attention": {
            "q_norm": _ln(cl.q_norm),
            "kv_norm": _ln(cl.kv_norm),
            "attention": _mha(cl.attn, C),
        },
        "mlp": _mlp(cl.mlp),
    }


def _perceiver_layer(pl_: TorchPerceiverLayer):
    tree = {"cross_attention_layer": _cross_layer(pl_.cross), "self_attention_block": {}}
    for i, sl in enumerate(pl_.selfs):
        tree["self_attention_block"][f"layer_{i}"] = {
            "self_attention": {"norm": _ln(sl.norm), "attention": _mha(sl.attn, C)},
            "mlp": _mlp(sl.mlp),
        }
    return tree


def flax_params_from_oracle(oracle: TorchOracle):
    return {
        "encoder": {
            "input_adapter": {
                "text_embedding": {"embedding": _t(oracle.embed.weight)},
                "pos_encoding": _t(oracle.pos),
            },
            "latent": _t(oracle.latent),
            "layer_1": _perceiver_layer(oracle.layer_1),
            "layer_n": _perceiver_layer(oracle.layer_n),
        },
        "decoder": {
            "output": _t(oracle.output),
            "cross_attention_layer": _cross_layer(oracle.dec_cross),
            "output_adapter": {
                "linear": {"kernel": _t(oracle.head.weight).T,
                           "bias": _t(oracle.head.bias)},
            },
        },
    }


def build_flax_model(num_classes=3):
    return pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_channels=C
            ),
            latent_shape=(N_LATENT, C),
            num_layers=NUM_LAYERS,
            num_cross_attention_heads=HEADS,
            num_self_attention_heads=HEADS,
            num_self_attention_layers_per_block=SELF_PER_BLOCK,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=num_classes, num_output_channels=C
            ),
            latent_shape=(N_LATENT, C),
            num_cross_attention_heads=HEADS,
        ),
    )


@pytest.mark.parametrize("use_pad_mask", [False, True])
def test_full_model_matches_torch_oracle(use_pad_mask, rng):
    torch.manual_seed(0)
    oracle = TorchOracle().eval()

    ids = rng.integers(0, VOCAB, size=(B, L)).astype(np.int64)
    pad = np.zeros((B, L), dtype=bool)
    if use_pad_mask:
        pad[0, -4:] = True
        pad[1, -1:] = True

    with torch.no_grad():
        t_logits = oracle(
            torch.tensor(ids), torch.tensor(pad) if use_pad_mask else None
        ).numpy()

    model = build_flax_model()
    params = jax.tree.map(jnp.asarray, flax_params_from_oracle(oracle))
    j_logits = model.apply(
        {"params": params},
        jnp.asarray(ids.astype(np.int32)),
        pad_mask=jnp.asarray(pad) if use_pad_mask else None,
    )

    assert j_logits.shape == t_logits.shape
    np.testing.assert_allclose(np.asarray(j_logits), t_logits, atol=2e-5)


def test_oracle_weight_port_is_exhaustive(rng):
    """Every flax param is covered by the port (no silently-initialized
    leaves): tree structures must match exactly."""
    torch.manual_seed(1)
    oracle = TorchOracle()
    ported = flax_params_from_oracle(oracle)
    model = build_flax_model()
    init = model.init(jax.random.key(0), jnp.zeros((1, L), jnp.int32), None)["params"]
    ported_paths = {jax.tree_util.keystr(p) for p, _ in
                    jax.tree_util.tree_leaves_with_path(ported)}
    init_paths = {jax.tree_util.keystr(p) for p, _ in
                  jax.tree_util.tree_leaves_with_path(init)}
    assert ported_paths == init_paths
    # shapes agree leaf-by-leaf
    jax.tree.map(lambda a, b: None if a.shape == b.shape else 1 / 0, ported, init)


def test_training_trajectory_matches_torch(rng):
    """End-to-end TRAINING parity: identical ported params, identical batches,
    Adam on both frameworks — per-step losses must track each other. This
    covers forward, backward (incl. gradient accumulation through the shared
    layer_n recurrence, SURVEY.md §7 hard part) and the optimizer in one
    assertion chain."""
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_classifier_steps,
        make_optimizer,
    )

    torch.manual_seed(0)
    oracle = TorchOracle().train()  # dropout is 0 everywhere; mode irrelevant

    steps = 5
    batches = [
        (
            rng.integers(0, VOCAB, size=(B, L)).astype(np.int64),
            rng.integers(0, 3, size=(B,)).astype(np.int64),
        )
        for _ in range(steps)
    ]

    lr = 1e-3
    opt = torch.optim.Adam(oracle.parameters(), lr=lr)
    model = build_flax_model()
    params = jax.tree.map(jnp.asarray, flax_params_from_oracle(oracle))
    tx, _ = make_optimizer(OptimizerConfig(optimizer="Adam", learning_rate=lr))
    state = TrainState.create(params, tx, jax.random.key(0))
    train_step, _ = make_classifier_steps(model, input_kind="text")
    jit_step = jax.jit(train_step)

    torch_losses, jax_losses = [], []
    for ids, labels in batches:
        opt.zero_grad()
        t_logits = oracle(torch.tensor(ids))
        t_loss = torch.nn.functional.cross_entropy(t_logits, torch.tensor(labels))
        t_loss.backward()
        opt.step()
        torch_losses.append(float(t_loss))

        batch = {
            "token_ids": jnp.asarray(ids.astype(np.int32)),
            "pad_mask": jnp.zeros((B, L), bool),
            "label": jnp.asarray(labels.astype(np.int32)),
        }
        state, metrics = jit_step(state, batch)
        jax_losses.append(float(metrics["loss"]))

    np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-4, atol=2e-5)
    # The final params agree to ~2 Adam steps' worth of drift: Adam divides
    # by sqrt(v), normalizing away gradient MAGNITUDE — where a gradient is
    # near zero, float-level noise (1e-7) decides the update's sign, so the
    # worst-case per-step divergence is O(lr) on isolated entries. The tight
    # assertion is the loss trajectory above; this one catches gross drift
    # (a wrong gradient path would blow past it immediately).
    final_torch = flax_params_from_oracle(oracle)
    for path, ours in jax.tree_util.tree_flatten_with_path(state.params)[0]:
        theirs = final_torch
        for key in path:
            theirs = theirs[key.key]
        np.testing.assert_allclose(
            np.asarray(ours), theirs, atol=2.5 * lr,
            err_msg=f"param drift at {jax.tree_util.keystr(path)}",
        )


@pytest.mark.slow  # tier-1 budget (r10): the torch-trajectory oracle stays
# tier-1 at base scale in test_training_trajectory_matches_torch; this is
# the schedule-scale variant of the same assertion
def test_training_trajectory_matches_torch_at_schedule_scale(rng):
    """Trajectory parity over 80 steps with the PRODUCTION training recipe:
    AdamW + decoupled weight decay + OneCycle LR (pct_start 0.25 → a full
    20-step warmup phase plus most of the anneal engage). At 5 steps
    (the test above) schedule effects barely move the LR; this run covers
    the regime the reference's north-star config actually trains in
    (reference lightning.py:59-79: OneCycleLR stepped per optimizer step)
    and asserts the per-step loss ratio holds THROUGHOUT, not just at the
    end."""
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_classifier_steps,
        make_optimizer,
    )

    torch.manual_seed(0)
    oracle = TorchOracle().train()

    steps = 80
    lr, wd, pct_start = 3e-3, 0.01, 0.25
    batches = [
        (
            rng.integers(0, VOCAB, size=(B, L)).astype(np.int64),
            rng.integers(0, 3, size=(B,)).astype(np.int64),
        )
        for _ in range(steps)
    ]

    opt = torch.optim.AdamW(oracle.parameters(), lr=lr, weight_decay=wd)
    sched = torch.optim.lr_scheduler.OneCycleLR(
        opt, max_lr=lr, total_steps=steps, pct_start=pct_start,
        cycle_momentum=False,
    )
    model = build_flax_model()
    params = jax.tree.map(jnp.asarray, flax_params_from_oracle(oracle))
    tx, schedule = make_optimizer(OptimizerConfig(
        optimizer="AdamW", learning_rate=lr, weight_decay=wd,
        one_cycle_lr=True, one_cycle_pct_start=pct_start, max_steps=steps,
    ))
    state = TrainState.create(params, tx, jax.random.key(0))
    train_step, _ = make_classifier_steps(model, schedule, input_kind="text")
    jit_step = jax.jit(train_step)

    torch_losses, jax_losses = [], []
    torch_lrs, jax_lrs = [], []
    for ids, labels in batches:
        opt.zero_grad()
        torch_lrs.append(opt.param_groups[0]["lr"])
        t_logits = oracle(torch.tensor(ids))
        t_loss = torch.nn.functional.cross_entropy(t_logits, torch.tensor(labels))
        t_loss.backward()
        opt.step()
        sched.step()
        torch_losses.append(float(t_loss))

        batch = {
            "token_ids": jnp.asarray(ids.astype(np.int32)),
            "pad_mask": jnp.zeros((B, L), bool),
            "label": jnp.asarray(labels.astype(np.int32)),
        }
        state, metrics = jit_step(state, batch)
        jax_losses.append(float(metrics["loss"]))
        jax_lrs.append(float(metrics["lr"]))

    # the schedules themselves agree step-for-step (warmup, peak, anneal)
    np.testing.assert_allclose(jax_lrs, torch_lrs, rtol=5e-4, atol=1e-10)
    # per-step loss parity through the whole run. Tolerance reasoning: the
    # 5-step test holds 2e-4; over 80 steps at a 3x higher peak LR,
    # float-level Adam sign-noise on near-zero gradients accumulates into
    # the params, and losses drift by O(1e-3) relative while remaining
    # lockstep in shape — a wrong decay coupling or schedule off-by-one
    # diverges 10-100x faster than this bound.
    np.testing.assert_allclose(jax_losses, torch_losses, rtol=4e-3, atol=1e-3)
    # the schedule actually engaged (warmup rose to the peak, anneal fell
    # well below it) — the parity above isn't a trivially-flat-LR run
    peak = max(jax_lrs)
    assert peak == pytest.approx(lr, rel=1e-3)
    assert jax_lrs[0] < 0.1 * peak and jax_lrs[-1] < 0.01 * peak
