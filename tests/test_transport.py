"""Zero-copy replica transport (r22): the raw array codec, the shmem slot
state machine, and ONE parametrized fabric-contract suite that runs the r12
wire contract — taxonomy round-trip, session pins, trace propagation, phase
attribution, drain, piggybacked health, at-most-once — identically over all
three transports (http / uds / shmem).

Tier-1 coverage is IN-PROCESS (real sockets + real shared memory, but one
process); the real-fleet kill -9 drills per transport are ``slow``-marked,
each naming the tier-1 test that retains its logic coverage.
"""

import json
import os
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.inference import ServingEngine
from perceiver_io_tpu.resilience import (
    AffinityLost,
    FailoverPolicy,
    FaultInjector,
    FaultSpec,
    RejectedError,
    faults,
)
from perceiver_io_tpu.serving import (
    HttpReplicaClient,
    LocalReplica,
    ReplicaApp,
    ReplicaServer,
)
from perceiver_io_tpu.serving.supervisor import default_replica_argv
from perceiver_io_tpu.serving.transport import (
    FREE,
    LOST,
    READING,
    READY,
    TRANSPORTS,
    WRITING,
    SlotRing,
    attach_slab,
    create_slab,
    make_client,
    pack_raw_arrays,
    raw_arrays_nbytes,
    read_raw_arrays,
    serve_transport,
    shm_slab_name,
    uds_path_for,
    write_raw_arrays,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_replica(name, scale=2.0, **engine_kw):
    """One in-process replica over trivial jitted apply fns (the fabric's
    transport layer is model-agnostic and tier-1 time is precious)."""

    def infer(p, x):
        return x * p

    def encode(p, x):
        return x + p

    def decode(p, latents, positions):
        return latents * positions

    engines = {
        kind: ServingEngine(fn, np.float32(scale), max_batch=4,
                            name=f"{name}-{kind}", **engine_kw)
        for kind, fn in (("infer", infer), ("encode", encode),
                         ("decode", decode))
    }

    def params_factory(spec):
        return np.float32(spec.get("seed", 0) + 1.0)

    app = ReplicaApp(engines, np.float32(scale),
                     params_factory=params_factory, name=name,
                     assume_ready=True)
    return LocalReplica(app)


@pytest.fixture
def x():
    return np.ones((2, 3), np.float32)


# -- raw array codec (the framed wire format) ---------------------------------


def test_raw_codec_roundtrip_preserves_dtype_and_shape():
    """Every array shape class the engines emit survives the framed codec:
    0-d scalars (np.ascontiguousarray would promote them to 1-d — the
    guarded path must not), empty arrays, bools, and non-contiguous inputs."""
    arrays = [
        np.arange(6, dtype=np.float32).reshape(2, 3),
        np.float64(3.5).reshape(()),          # 0-d
        np.empty((0, 3), np.float64),          # empty
        np.array([True, False, True]),
        np.arange(12, dtype=np.int32).reshape(3, 4).T,  # non-contiguous
        np.arange(4, dtype=np.float16),
    ]
    buf = pack_raw_arrays(arrays)
    out = read_raw_arrays(buf)
    assert len(out) == len(arrays)
    for a, b in zip(arrays, out):
        assert b.dtype == a.dtype and b.shape == a.shape
        assert np.array_equal(b, np.asarray(a))
    assert out[1].shape == ()  # the 0-d guard held
    out[0][0, 0] = 99.0  # copy=True arrays are owned and writable


def test_raw_codec_zero_copy_views_alias_the_buffer():
    """copy=False returns frombuffer views INTO the buffer — the shmem
    read path: mutating the slab under a held slot changes the view."""
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    backing = bytearray(raw_arrays_nbytes([a]))
    n = write_raw_arrays(memoryview(backing), [a])
    view = read_raw_arrays(memoryview(backing)[:n], copy=False)[0]
    assert view.base is not None  # a view, not an owned copy
    assert np.array_equal(view, a)
    struct_off = len(backing) - a.nbytes  # payload bytes sit at the tail
    backing[struct_off:struct_off + 4] = np.float32(42.0).tobytes()
    assert view[0, 0] == 42.0  # the view saw the slab write


def test_write_raw_arrays_rejects_oversized_payload():
    a = np.ones((8, 8), np.float32)
    with pytest.raises(ValueError, match="exceeds buffer"):
        write_raw_arrays(memoryview(bytearray(16)), [a])


# -- SlotRing: the shmem slot state machine -----------------------------------


def _ring(slots=3, slot_bytes=64):
    shm = types.SimpleNamespace(
        buf=bytearray(64 + slots * slot_bytes), close=lambda: None)
    return SlotRing(shm, slots, slot_bytes)


def test_slot_ring_forward_transitions_and_release():
    ring = _ring()
    idx = ring.acquire(timeout_s=0.1)
    assert ring.counts()[WRITING] == 1
    ring.mark_ready(idx)
    ring.mark_reading(idx)
    ring.release(idx)
    assert ring.counts() == {FREE: 3}
    ring.release(idx)  # idempotent: double release is a no-op
    assert ring.counts() == {FREE: 3}


def test_slot_ring_illegal_transition_raises():
    """An out-of-order touch is a protocol bug, not a recoverable state."""
    ring = _ring()
    idx = ring.acquire(timeout_s=0.1)
    with pytest.raises(RuntimeError, match="illegal slot transition"):
        ring.mark_reading(idx)  # WRITING -> READING skips READY
    ring.mark_ready(idx)
    with pytest.raises(RuntimeError, match="illegal slot transition"):
        ring.mark_ready(idx)  # READY -> READY replays


def test_slot_ring_quarantine_survives_release():
    """A LOST slot (response never arrived on a live connection — the
    replica may still write into it) is never handed to a new request;
    only invalidate() reclaims it."""
    ring = _ring(slots=2)
    idx = ring.acquire(timeout_s=0.1)
    ring.mark_ready(idx)
    ring.quarantine(idx)
    ring.release(idx)  # the call's finally-release must NOT free it
    assert ring.counts()[LOST] == 1
    other = ring.acquire(timeout_s=0.1)
    assert other != idx
    ring.release(other)
    ring.invalidate()
    assert ring.counts() == {FREE: 2}


def test_slot_ring_acquire_times_out_under_pressure():
    ring = _ring(slots=2)
    held = [ring.acquire(timeout_s=0.1) for _ in range(2)]
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="no free shmem slot"):
        ring.acquire(timeout_s=0.05)
    assert time.monotonic() - t0 < 2.0
    for idx in held:
        ring.release(idx)
    assert ring.acquire(timeout_s=0.1) in held


def test_slot_ring_views_are_disjoint():
    ring = _ring(slots=2, slot_bytes=32)
    a, b = ring.acquire(timeout_s=0.1), ring.acquire(timeout_s=0.1)
    va, vb = ring.view(a), ring.view(b)
    va[:] = b"\xaa" * 32
    vb[:] = b"\xbb" * 32
    assert bytes(va) == b"\xaa" * 32  # no overlap tore the first slot


# -- slab geometry discovery --------------------------------------------------


def test_slab_header_geometry_discovery():
    """Clients DISCOVER slots/slot_bytes from the slab header rather than
    assuming them; a torn/foreign segment (bad magic) is a ConnectionError."""
    port = 49000 + (os.getpid() % 1000)
    slab = create_slab(port, slots=3, slot_bytes=128)
    try:
        shm, slots, slot_bytes = attach_slab(port)
        assert (slots, slot_bytes) == (3, 128)
        shm.close()
        slab.buf[0:8] = b"GARBAGE!"  # tear the magic
        with pytest.raises(ConnectionError, match="no geometry header"):
            attach_slab(port)
    finally:
        slab.unlink()
        slab.close()


def test_endpoint_names_keyed_by_port():
    """uds path and slab name derive from the replica's (host-unique) HTTP
    port, so a restart on the same port lands on the same endpoints."""
    assert uds_path_for(1234).endswith("pit-uds-1234.sock")
    assert uds_path_for(1234, root="/x") == "/x/pit-uds-1234.sock"
    assert shm_slab_name(1234) == "pit_shm_1234"


def test_default_replica_argv_carries_transport():
    argv = default_replica_argv("r0", 1234, extra=("--cpu",),
                                transport="shmem")
    assert argv[argv.index("--transport") + 1] == "shmem"
    assert argv[-1] == "--cpu"
    assert "--transport" not in default_replica_argv("r0", 1234)


# -- the fabric contract, identical over all three transports -----------------


class _Fabric:
    """One live in-process replica serving HTTP plus the selected data
    plane, and the matching router-side client."""

    def __init__(self, transport, slots=4, slot_bytes=1 << 16, **app_kw):
        self.transport = transport
        self.rep = _make_replica(f"t-{transport}", **app_kw)
        self.server = ReplicaServer(self.rep.app)
        self.server.start()
        self.extra = serve_transport(self.rep.app, transport,
                                     self.server.port, slots=slots,
                                     slot_bytes=slot_bytes)
        self.client = make_client(transport, f"t-{transport}",
                                  self.server.port, timeout_s=30)

    def close(self):
        self.client.close()
        if self.extra is not None:
            self.extra.close()
        self.server.close()
        self.rep.app.close()


@pytest.fixture(params=TRANSPORTS)
def fabric(request):
    fab = _Fabric(request.param)
    yield fab
    fab.close()


def test_transport_contract_roundtrip(fabric, x):
    """The r12 wire contract over every transport: arrays round-trip,
    sessions stay resident (and AffinityLost mirrors for unknown pins),
    admin verbs work, drain rejects with the draining taxonomy, and phases
    ride the response metadata."""
    from perceiver_io_tpu.inference.engine import PHASES

    client = fabric.client
    meta = {}
    out = client.call("infer", [x], meta=meta)
    assert np.allclose(out[0], 2.0)
    assert meta["phases"] and set(meta["phases"][0]) == set(PHASES)
    # session pins: encode establishes residency, decode consumes it
    ack = client.call("encode", [x], session="s1")
    assert list(ack[0]) == [2, 3]
    dec = client.call("decode", [np.ones((2, 3), np.float32)], session="s1")
    assert dec[0].shape == (2, 3)
    with pytest.raises(AffinityLost):
        client.call("decode", [np.ones((2, 3), np.float32)],
                    session="never-encoded")
    status = client.scrape()
    assert status["up"] and status["ready"]
    assert client.update_params({"kind": "scale", "factor": 0.5}) == 1
    assert np.allclose(client.call("infer", [x])[0], 1.0)
    assert client.update_params({"kind": "rollback"}) == 2
    assert client.drain(timeout_s=10)
    with pytest.raises(RejectedError, match="draining"):
        client.call("infer", [x])
    client.resume()
    assert np.allclose(client.call("infer", [x])[0], 2.0)


def test_transport_trace_headers_parent_replica_spans(fabric, x, tmp_path):
    """The propagated TraceContext parents the replica_serve span on every
    transport — the assembled-trace reconciliation the r15 pin depends on."""
    events = tmp_path / "ev.jsonl"
    obs.configure_event_log(str(events))
    try:
        ctx = obs.TraceContext.mint()
        assert np.allclose(fabric.client.call("infer", [x], trace=ctx)[0],
                           2.0)
    finally:
        obs.configure_event_log(None)
    rows = [json.loads(l) for l in open(events)]
    serves = [r for r in rows if r.get("event") == "span"
              and r.get("name") == "replica_serve"
              and r.get("trace") == ctx.trace_id]
    assert serves and serves[0]["parent"] == ctx.span_id


def test_transport_pipelined_concurrency(fabric, x):
    """16 threads over ONE client: responses are id-matched on the shared
    pipelined connections (uds/shmem) and every caller gets ITS result.
    Values are thread-distinct so a cross-matched response would be seen.
    For shmem, 16 > 4 slots also exercises pressure fallback inline."""
    errs = []

    def worker(i):
        xi = np.full((2, 3), float(i + 1), np.float32)
        try:
            for _ in range(4):
                out = fabric.client.call("infer", [xi])
                assert np.allclose(out[0], 2.0 * (i + 1))
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errs, errs
    if fabric.transport == "shmem":
        assert fabric.client.ring().counts() == {FREE: 4}  # no leaks


def test_transport_dead_replica_is_reroutable_connection_error(x):
    """A dead replica raises ConnectionError on every transport — the
    failover taxonomy's reroute class (vs DeadlineExceeded, which FAILs:
    at-most-once means never re-route work that may have executed)."""
    policy = FailoverPolicy()
    for transport in TRANSPORTS:
        fab = _Fabric(transport)
        assert np.allclose(fab.client.call("infer", [x])[0], 2.0)
        fab.close()  # server down; the client outlives it
        with pytest.raises(ConnectionError) as ei:
            fab.client.call("infer", [x])
        assert policy.should_reroute(ei.value, 1), (transport, ei.value)


# -- shmem specifics ----------------------------------------------------------


def test_shmem_oversized_payload_falls_back_inline(x):
    """Payloads that outgrow a slot ride the inline uds frames — geometry
    bounds memory, never request size — and no slot leaks either way."""
    fab = _Fabric("shmem", slots=4, slot_bytes=1 << 16)
    try:
        big = np.ones((300, 300), np.float32)  # 360 KB > 64 KB slots
        assert raw_arrays_nbytes([big]) > fab.client.ring().slot_bytes
        out = fab.client.call("infer", [big])
        assert out[0].shape == (300, 300) and np.allclose(out[0], 2.0)
        assert np.allclose(fab.client.call("infer", [x])[0], 2.0)  # slotted
        assert fab.client.ring().counts() == {FREE: 4}
    finally:
        fab.close()


def test_shmem_health_piggybacks_on_responses(x):
    """Every uds/shmem response frame carries a liveness sample — the
    router gets a fresh read with every reply, between scrapes."""
    fab = _Fabric("shmem")
    try:
        assert fab.client.health is None
        fab.client.call("infer", [x])
        assert fab.client.health is not None
        assert set(fab.client.health) == {"ready", "draining", "queue_depth"}
        assert fab.client.health["ready"] and not fab.client.health["draining"]
        assert fab.client.health_stamp > 0
    finally:
        fab.close()


def test_shmem_severed_replica_drops_ring_and_reattaches(x):
    """The restart contract: a dead replica's slab can never be reused (its
    restart creates a FRESH segment under the same name), so the client
    drops its mapping on ConnectionError and lazily re-attaches the new
    slab — with every slot FREE — once the data plane is back."""
    fab = _Fabric("shmem")
    try:
        assert np.allclose(fab.client.call("infer", [x])[0], 2.0)
        assert fab.client.ring() is not None
        fab.extra.close()  # the data plane dies (slab unlinked)
        with pytest.raises(ConnectionError):
            fab.client.call("infer", [x])
        assert fab.client._ring is None  # mapping dropped, not reused
        # the replica restarts its data plane on the same port
        fab.extra = serve_transport(fab.rep.app, "shmem", fab.server.port,
                                    slots=4, slot_bytes=1 << 16)
        assert np.allclose(fab.client.call("infer", [x])[0], 2.0)
        assert fab.client.ring().counts() == {FREE: 4}  # fresh slab, no LOST
    finally:
        fab.close()


# -- fault sites --------------------------------------------------------------


def test_transport_fault_sites_registered():
    assert "transport.send" in faults.SITES
    assert "transport.recv" in faults.SITES


@pytest.mark.parametrize("site", ["transport.send", "transport.recv"])
def test_transport_fault_injection_releases_slots(site, x):
    """An injected failure on the data plane surfaces to the caller —
    raised locally (client-side send) or mirrored over the wire (the
    server's recv hook) — and, the shmem invariant, the slot held across
    the exchange is still released (the finally-release covers the error
    path). The site counter is shared by both halves of the exchange, so
    the injector is armed AFTER the warm call: the next site hit is the
    client's send (or the server's recv) of the faulted call."""
    fab = _Fabric("shmem")
    try:
        assert np.allclose(fab.client.call("infer", [x])[0], 2.0)
        prev = faults.install(FaultInjector([
            FaultSpec(site=site, kind="transient", at=(1,)),
        ]))
        try:
            with pytest.raises(Exception, match="injected"):
                fab.client.call("infer", [x])
        finally:
            faults.install(prev)
        assert fab.client.ring().counts() == {FREE: 4}, \
            "injected fault leaked a slot"
        assert np.allclose(fab.client.call("infer", [x])[0], 2.0)
    finally:
        fab.close()


# -- the no-40ms pin (satellite: pooled HTTP connections, TCP_NODELAY) --------


def test_http_small_frames_have_no_40ms_mode(x):
    """Regression pin for the delayed-ACK/Nagle interaction: small framed
    requests on the pooled HTTP connections must not show the ~40 ms
    latency mode. Warm p50 well under that bound proves TCP_NODELAY is on
    the pooled sockets (without it, this suite measured p50 >= 40 ms)."""
    rep = _make_replica("nodelay")
    server = ReplicaServer(rep.app)
    url = server.start()
    client = HttpReplicaClient("nodelay", url, timeout_s=30)
    try:
        for _ in range(3):  # warm the pool + jit
            client.call("infer", [x])
        lat = []
        for _ in range(30):
            t0 = time.monotonic()
            client.call("infer", [x])
            lat.append(time.monotonic() - t0)
        lat.sort()
        p50 = lat[len(lat) // 2]
        assert p50 < 0.035, f"p50 {p50 * 1e3:.1f} ms: the 40 ms mode is back"
    finally:
        server.close()
        rep.app.close()


# -- real-process drills (slow tier) ------------------------------------------


@pytest.mark.slow  # tier-1 budget (r22): real 2-process fleet + SIGKILL per
# transport (~60s each). The zero-lost/reroute LOGIC stays tier-1 in
# test_transport_dead_replica_is_reroutable_connection_error and
# test_shmem_severed_replica_drops_ring_and_reattaches; the wire contract in
# test_transport_contract_roundtrip. This drill adds only the real
# process/SIGKILL/slab-across-processes layer.
@pytest.mark.parametrize("transport", ["uds", "shmem"])
def test_chaos_drill_kill9_transport_fleet_zero_lost(transport):
    """kill -9 one replica mid-window with open-loop traffic on the uds or
    shmem data plane: zero lost accepted requests, the supervisor restarts
    the victim, and (shmem) no request ever lands on the stale slab."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "load_bench.py"),
         "--cpu", "--replicas", "2", "--replica_mode", "process",
         "--transport", transport,
         "--kill_replica_at", "0.5", "--kill_point", "0",
         "--duration_s", "2", "--rate_factors", "0.8",
         "--calibration_waves", "2", "--calibration_wave_size", "12"],
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout  # one-JSON-line contract holds
    record = json.loads(lines[0])
    fleet = record["fleet"]
    assert fleet["transport"] == transport
    assert fleet["killed"] is not None
    assert fleet["lost_accepted"] == 0, fleet  # the drill's verdict
    assert fleet["restarts"] >= 1
    point = record["sweep"][0]
    assert point["failed"] == 0 and point["completed"] > 0
