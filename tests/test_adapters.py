"""Adapter contract tests (reference adapter.py semantics)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from perceiver_io_tpu.models.adapters import (
    ClassificationOutputAdapter,
    ImageInputAdapter,
    TextInputAdapter,
    TextOutputAdapter,
)


def test_image_adapter_channels_and_shape(rng):
    adapter = ImageInputAdapter(image_shape=(28, 28, 1), num_frequency_bands=32)
    # 1 pixel channel + 2 spatial dims * (2*32 + 1) = 131 (reference call stack §3.3)
    assert adapter.num_input_channels == 131

    x = rng.standard_normal((4, 28, 28, 1)).astype(np.float32)
    variables = adapter.init(jax.random.key(0), x)
    out = adapter.apply(variables, x)
    assert out.shape == (4, 28 * 28, 131)
    # first channel is the raw pixels, row-major flattened
    np.testing.assert_allclose(
        np.asarray(out[..., 0]), x.reshape(4, -1), atol=1e-6
    )
    # position-encoding channels identical across batch
    np.testing.assert_allclose(np.asarray(out[0, :, 1:]), np.asarray(out[3, :, 1:]), atol=1e-6)


def test_image_adapter_shape_validation(rng):
    adapter = ImageInputAdapter(image_shape=(28, 28, 1), num_frequency_bands=8)
    x = jnp.zeros((2, 14, 14, 1))
    with pytest.raises(ValueError, match="different from required"):
        adapter.init(jax.random.key(0), x)


def test_image_adapter_3d():
    adapter = ImageInputAdapter(image_shape=(8, 8, 4, 2), num_frequency_bands=6)
    assert adapter.num_input_channels == 2 + 3 * 13
    x = jnp.zeros((2, 8, 8, 4, 2))
    out = adapter.apply(adapter.init(jax.random.key(0), x), x)
    assert out.shape == (2, 8 * 8 * 4, 2 + 3 * 13)


def test_text_adapter_scale_and_pos(rng):
    vocab, max_len, c = 50, 16, 8
    adapter = TextInputAdapter(vocab_size=vocab, max_seq_len=max_len, num_channels=c)
    x = jnp.asarray(rng.integers(0, vocab, size=(3, 10)).astype(np.int32))
    variables = adapter.init(jax.random.key(0), x)
    out = adapter.apply(variables, x)
    assert out.shape == (3, 10, c)

    emb = np.asarray(variables["params"]["text_embedding"]["embedding"])
    pos = np.asarray(variables["params"]["pos_encoding"])
    expected = emb[np.asarray(x)] * np.sqrt(c) + pos[:10]
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)

    # init ranges (reference adapter.py:122-125)
    assert np.abs(emb).max() <= 0.1 + 1e-6
    assert np.abs(pos).max() <= 0.5 + 1e-6
    assert np.abs(pos).max() > 0.25  # actually uses the range


def test_text_adapter_rejects_overlong():
    adapter = TextInputAdapter(vocab_size=10, max_seq_len=4, num_channels=8)
    x = jnp.zeros((1, 5), dtype=jnp.int32)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        adapter.init(jax.random.key(0), x)


def test_classification_adapter_squeezes_single_query(rng):
    adapter = ClassificationOutputAdapter(num_classes=10, num_output_channels=32)
    assert adapter.output_shape == (1, 32)
    x = rng.standard_normal((5, 1, 32)).astype(np.float32)
    out = adapter.apply(adapter.init(jax.random.key(0), x), x)
    assert out.shape == (5, 10)


def test_classification_adapter_default_channels():
    adapter = ClassificationOutputAdapter(num_classes=7)
    assert adapter.output_shape == (1, 7)


def test_text_output_adapter_keeps_positions(rng):
    adapter = TextOutputAdapter(vocab_size=100, max_seq_len=12, num_output_channels=16)
    assert adapter.output_shape == (12, 16)
    x = rng.standard_normal((2, 12, 16)).astype(np.float32)
    out = adapter.apply(adapter.init(jax.random.key(0), x), x)
    assert out.shape == (2, 12, 100)


def test_padded_classification_adapter_parity(rng):
    """pad_classes_to pads the projection width; with the unpadded weights
    embedded, logits/argmax/CE over the real classes are unchanged and the
    padding can never win."""
    from perceiver_io_tpu.training.losses import softmax_ce_integer

    x = jnp.asarray(rng.standard_normal((2, 1, 32)).astype(np.float32))
    base = ClassificationOutputAdapter(num_classes=10, num_output_channels=32)
    padded = ClassificationOutputAdapter(
        num_classes=10, num_output_channels=32, pad_classes_to=8
    )
    assert padded.padded_num_classes == 16

    p_base = base.init(jax.random.key(0), x)["params"]
    p_pad = padded.init(jax.random.key(1), x)["params"]
    kernel = np.array(p_pad["linear"]["kernel"])
    bias = np.array(p_pad["linear"]["bias"])
    kernel[:, :10] = np.asarray(p_base["linear"]["kernel"])
    bias[:10] = np.asarray(p_base["linear"]["bias"])
    p_pad = {"linear": {"kernel": jnp.asarray(kernel), "bias": jnp.asarray(bias)}}

    out_base = base.apply({"params": p_base}, x)
    out_pad = padded.apply({"params": p_pad}, x)
    assert out_pad.shape[-1] == 16
    np.testing.assert_allclose(
        np.asarray(out_pad[..., :10]), np.asarray(out_base), atol=1e-6
    )
    assert np.all(np.asarray(out_pad[..., 10:]) <= -1e29)

    labels = jnp.asarray(rng.integers(0, 10, (2,)))
    np.testing.assert_allclose(
        np.asarray(softmax_ce_integer(out_pad, labels)),
        np.asarray(softmax_ce_integer(out_base, labels)),
        atol=1e-5,
    )
    assert np.array_equal(
        np.argmax(np.asarray(out_pad), -1), np.argmax(np.asarray(out_base), -1)
    )


def test_pad_classes_to_validates():
    bad = ClassificationOutputAdapter(num_classes=10, pad_classes_to=0)
    with pytest.raises(ValueError, match="pad_classes_to"):
        _ = bad.padded_num_classes
