"""Declarative alerting: rule validation, fire → hold-down → resolve
hysteresis, absence/rate kinds, healthz degradation, EventLog + exemplar
linkage, and the end-to-end SLO-burn drill over a real engine (ISSUE 12)."""

import json

import numpy as np
import pytest
import jax.numpy as jnp

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.inference import ServingEngine
from perceiver_io_tpu.resilience import RejectedError


def _store_with(key, samples):
    """A store pre-loaded with (mono, value) samples for one gauge key."""
    s = obs.SeriesStore()
    for mono, v in samples:
        s.record(key, v, "gauge", t=1000.0 + mono, mono=mono)
    return s


# -- rule validation ----------------------------------------------------------


def test_rule_validation_rejects_malformed_rules():
    ok = obs.AlertRule(name="r", metric="m", threshold=2.0)
    assert ok.effective_resolve_threshold == 2.0
    assert ok.effective_resolve_for_s == 0.0
    for bad in (
        dict(name="", metric="m"),
        dict(name="r", metric=""),
        dict(name="r", metric="m", kind="nope"),
        dict(name="r", metric="m", op="=="),
        dict(name="r", metric="m", agg="median"),
        dict(name="r", metric="m", severity="fatal"),
        dict(name="r", metric="m", window_s=0),
        dict(name="r", metric="m", for_s=-1),
        # hysteresis must widen AGAINST the firing direction
        dict(name="r", metric="m", op=">", threshold=2.0,
             resolve_threshold=3.0),
        dict(name="r", metric="m", op="<", threshold=1.0,
             resolve_threshold=0.5),
    ):
        with pytest.raises(ValueError):
            obs.AlertRule(**bad)


def test_load_rules_json_and_unknown_field_rejection(tmp_path):
    path = tmp_path / "rules.json"
    path.write_text(json.dumps({"rules": [
        {"name": "burn", "metric": "slo_error_budget_burn_rate",
         "threshold": 2.0, "for_s": 1.0, "resolve_threshold": 1.0,
         "severity": "page"},
        {"name": "quiet", "metric": "serving_requests_total",
         "kind": "absence", "window_s": 60, "severity": "warn"},
    ]}))
    rules = obs.load_alert_rules(str(path))
    assert [r.name for r in rules] == ["burn", "quiet"]
    assert rules[0].effective_resolve_threshold == 1.0
    # a misspelled field must fail loudly, not become a default silently
    path.write_text(json.dumps([{"name": "x", "metric": "m", "fors": 3}]))
    with pytest.raises(ValueError, match="unknown fields"):
        obs.load_alert_rules(str(path))
    path.write_text(json.dumps([{"name": "x", "metric": "m"},
                                {"name": "x", "metric": "m2"}]))
    with pytest.raises(ValueError, match="duplicate"):
        obs.load_alert_rules(str(path))
    # a top-level typo (or an empty file) must not silently disable all
    # alerting
    path.write_text(json.dumps({"alert_rules": [{"name": "x",
                                                 "metric": "m"}]}))
    with pytest.raises(ValueError, match="'rules' key"):
        obs.load_alert_rules(str(path))
    path.write_text(json.dumps([]))
    with pytest.raises(ValueError, match="zero rules"):
        obs.load_alert_rules(str(path))


# -- the state machine --------------------------------------------------------


def test_threshold_fire_hold_down_resolve_hysteresis():
    """The full lifecycle, deterministically clocked: breach → hold-down
    (no fire yet) → fire → dip below threshold but above the resolve
    threshold (still firing — hysteresis) → below resolve threshold →
    resolve hold-down → resolved."""
    key = "burn"
    store = obs.SeriesStore()
    rule = obs.AlertRule(name="hot", metric=key, op=">", threshold=2.0,
                         window_s=10.0, agg="last", for_s=2.0,
                         resolve_threshold=1.0, resolve_for_s=2.0)
    eng = obs.AlertEngine(store, [rule], registry=obs.MetricsRegistry(),
                          name="t1")
    try:
        def tick(mono, value):
            store.record(key, value, "gauge", mono=mono)
            return eng.evaluate(now=mono)

        assert tick(100.0, 3.0) == []            # breached, hold-down starts
        assert tick(101.0, 3.0) == []            # 1s < for_s
        trans = tick(102.5, 3.0)                 # held 2.5s >= 2.0 → FIRE
        assert [t["action"] for t in trans] == ["firing"]
        assert trans[0]["rule"] == "hot" and trans[0]["value"] == 3.0
        assert eng.firing() == {"hot": [key]}
        # hysteresis: 1.5 is below the firing threshold but above the
        # resolve threshold — the alert must NOT resolve (no flap)
        assert tick(103.0, 1.5) == []
        assert tick(110.0, 1.5) == []            # however long it lingers
        assert eng.firing() == {"hot": [key]}
        # below the resolve threshold starts the resolve hold-down
        assert tick(111.0, 0.5) == []
        # a bounce back above resolve_threshold resets the hold-down
        assert tick(112.0, 1.5) == []
        assert tick(113.0, 0.5) == []
        assert tick(114.0, 0.5) == []            # 1s < resolve_for_s
        trans = tick(115.5, 0.5)                 # held 2.5s → RESOLVED
        assert [t["action"] for t in trans] == ["resolved"]
        assert eng.firing() == {}
        # a breach that recovers before the hold-down never fires
        assert tick(120.0, 9.0) == []
        assert tick(121.0, 0.0) == []
        assert tick(130.0, 0.0) == []
        assert eng.stats()["fired"] == 1 and eng.stats()["resolved"] == 1
    finally:
        eng.close()


def test_flapping_gauge_cannot_flap_the_alert():
    """A gauge oscillating across the firing threshold (but never below
    the resolve threshold) produces exactly ONE firing transition."""
    key = "flappy"
    store = obs.SeriesStore()
    rule = obs.AlertRule(name="f", metric=key, threshold=2.0,
                         window_s=10.0, for_s=0.0, resolve_threshold=0.5,
                         resolve_for_s=1.0)
    eng = obs.AlertEngine(store, [rule], registry=obs.MetricsRegistry(),
                          name="t2")
    try:
        transitions = []
        value = [3.0, 1.0]  # straddles threshold=2, never crosses 0.5
        for i in range(20):
            store.record(key, value[i % 2], "gauge", mono=100.0 + i)
            transitions += eng.evaluate(now=100.0 + i)
        assert [t["action"] for t in transitions] == ["firing"]
        assert eng.firing() == {"f": [key]}
        g = eng.registry.gauge("alert_state", labels={"rule": "f"})
        assert g.value == 1.0
    finally:
        eng.close()


def test_absence_rule_fires_when_the_series_goes_quiet():
    key = "heartbeat_metric"
    store = obs.SeriesStore()
    rule = obs.AlertRule(name="gone", metric=key, kind="absence",
                         window_s=5.0, for_s=0.0)
    eng = obs.AlertEngine(store, [rule], registry=obs.MetricsRegistry(),
                          name="t3")
    try:
        store.record(key, 1.0, "gauge", mono=100.0)
        assert eng.evaluate(now=101.0) == []       # fresh
        assert eng.evaluate(now=104.0) == []       # still inside the window
        trans = eng.evaluate(now=106.0)            # 6s > 5s → absent
        assert [t["action"] for t in trans] == ["firing"]
        store.record(key, 2.0, "gauge", mono=107.0)  # samples resume
        trans = eng.evaluate(now=107.5)
        assert [t["action"] for t in trans] == ["resolved"]
    finally:
        eng.close()


def test_absence_rule_fires_for_a_series_that_never_arrived():
    """An explicit key nothing ever produced IS the alert — but only after
    the engine has watched a full window (no page at boot)."""
    store = obs.SeriesStore()
    rule = obs.AlertRule(name="never", metric="never_produced",
                         kind="absence", window_s=5.0)
    eng = obs.AlertEngine(store, [rule], registry=obs.MetricsRegistry(),
                          name="t4")
    try:
        t0 = eng._start_mono
        assert eng.evaluate(now=t0 + 1.0) == []    # grace: window not over
        trans = eng.evaluate(now=t0 + 6.0)
        assert [t["action"] for t in trans] == ["firing"]
        detail = eng.health_status()[2]
        assert detail["never_matched"] == ["never"]
    finally:
        eng.close()


def test_phantom_absence_instance_resolves_when_labeled_series_arrive():
    """A bare-name absence rule fires on its phantom key while NOTHING
    matches; once the real (labeled) series arrives, the phantom must
    RESOLVE — not page forever on a key match() will never return again."""
    store = obs.SeriesStore()
    rule = obs.AlertRule(name="hb", metric="heartbeat_total",
                         kind="absence", window_s=5.0, severity="page")
    eng = obs.AlertEngine(store, [rule], registry=obs.MetricsRegistry(),
                          name="t4b")
    try:
        t0 = eng._start_mono
        trans = eng.evaluate(now=t0 + 6.0)
        assert [t["action"] for t in trans] == ["firing"]
        assert not eng.health_status()[1]
        # the series starts arriving — labeled, as package instruments are
        key = obs.series_key("heartbeat_total", {"engine": "e"})
        store.record(key, 1.0, "counter", mono=t0 + 7.0)
        trans = eng.evaluate(now=t0 + 7.5)
        assert [(t["metric"], t["action"]) for t in trans] \
            == [("heartbeat_total", "resolved")]
        assert eng.firing() == {}
        assert eng.health_status()[1]
        # and the labeled instance now tracks absence on its own
        trans = eng.evaluate(now=t0 + 20.0)
        assert [(t["metric"], t["action"]) for t in trans] \
            == [(key, "firing")]
    finally:
        eng.close()


def test_rate_rule_over_a_counter():
    key = "sheds_total"
    store = obs.SeriesStore()
    rule = obs.AlertRule(name="shedding", metric=key, kind="rate",
                         op=">", threshold=0.5, window_s=10.0,
                         resolve_threshold=0.0)
    eng = obs.AlertEngine(store, [rule], registry=obs.MetricsRegistry(),
                          name="t5")
    try:
        store.record(key, 0, "counter", mono=100.0)
        store.record(key, 0, "counter", mono=101.0)
        assert eng.evaluate(now=101.0) == []       # flat counter: rate 0
        store.record(key, 8, "counter", mono=102.0)  # 8 sheds in 2s
        trans = eng.evaluate(now=102.0)
        assert [t["action"] for t in trans] == ["firing"]
        assert trans[0]["value"] > 0.5
        # the window slides past the burst: rate back to 0 → resolves
        store.record(key, 8, "counter", mono=112.0)
        store.record(key, 8, "counter", mono=113.0)
        trans = eng.evaluate(now=113.0)
        assert [t["action"] for t in trans] == ["resolved"]
    finally:
        eng.close()


def test_bare_metric_name_alerts_per_label_set():
    """One rule over a bare instrument name maintains independent state
    per labeled series — replica r1 firing does not mask r0's later fire,
    and each resolves on its own."""
    store = obs.SeriesStore()
    keys = {r: obs.series_key("fleet_replica_queue_depth",
                              {"fleet": "f", "replica": r})
            for r in ("r0", "r1")}
    rule = obs.AlertRule(name="qd", metric="fleet_replica_queue_depth",
                         threshold=10.0, window_s=10.0)
    eng = obs.AlertEngine(store, [rule], registry=obs.MetricsRegistry(),
                          name="t6")
    try:
        store.record(keys["r0"], 1.0, "gauge", mono=100.0)
        store.record(keys["r1"], 99.0, "gauge", mono=100.0)
        trans = eng.evaluate(now=100.0)
        assert [(t["action"], t["metric"]) for t in trans] \
            == [("firing", keys["r1"])]
        store.record(keys["r0"], 88.0, "gauge", mono=101.0)
        store.record(keys["r1"], 0.0, "gauge", mono=101.0)
        trans = eng.evaluate(now=101.0)
        actions = {(t["action"], t["metric"]) for t in trans}
        assert actions == {("firing", keys["r0"]),
                           ("resolved", keys["r1"])}
        assert eng.firing() == {"qd": [keys["r0"]]}
    finally:
        eng.close()


# -- healthz + events + exemplars ---------------------------------------------


def test_firing_page_alert_degrades_healthz_warn_does_not():
    store = obs.SeriesStore()
    store.record("pager_metric", 9.0, "gauge", mono=100.0)
    store.record("warner_metric", 9.0, "gauge", mono=100.0)
    reg = obs.MetricsRegistry()
    page = obs.AlertEngine(
        store, [obs.AlertRule(name="p", metric="pager_metric",
                              threshold=1.0, window_s=1e6,
                              severity="page")],
        registry=reg, name="pageeng")
    warn = obs.AlertEngine(
        store, [obs.AlertRule(name="w", metric="warner_metric",
                              threshold=1.0, window_s=1e6,
                              severity="warn")],
        registry=reg, name="warneng")
    try:
        warn.evaluate(now=100.0)
        ok, detail = obs.healthz()  # the same aggregation path as stalls
        assert ok  # a warn-severity alert never 503s the process
        assert detail["sources"]["alerts:warneng"]["firing"] == {
            "w": ["warner_metric"]}
        page.evaluate(now=100.0)
        ok, detail = obs.healthz()
        assert not ok
        assert detail["sources"]["alerts:pageeng"]["paging"] == ["p"]
    finally:
        page.close()
        warn.close()
    ok, detail = obs.healthz()  # close() unregisters both sources
    assert "alerts:pageeng" not in detail.get("sources", {})


def test_transitions_land_in_the_event_log_with_exemplar_traces(tmp_path):
    """alert_firing/alert_resolved ride the EventLog; a histogram-derived
    alert carries the instrument's r15 exemplar trace ids — the page links
    straight to the traces that breached it."""
    reg = obs.MetricsRegistry()
    h = reg.histogram("router_lat_seconds", labels={"router": "x"})
    for i in range(8):
        h.observe(0.1 * i, exemplar=f"trace{i}")
    store = obs.SeriesStore()
    sam = obs.Sampler(registry=reg, store=store, name="ev")
    sam.sample_once()
    p99_key = obs.series_key("router_lat_seconds", {"router": "x"},
                             field="p99")
    rule = obs.AlertRule(name="tail", metric=p99_key, threshold=0.5,
                         window_s=1e6, resolve_threshold=0.1)
    eng = obs.AlertEngine(store, [rule], registry=reg, name="t7")
    path = tmp_path / "events.jsonl"
    try:
        obs.configure_event_log(str(path))
        trans = eng.evaluate()
        assert [t["action"] for t in trans] == ["firing"]
        assert trans[0]["trace_exemplars"][0] == "trace7"  # slowest first
        store.record(p99_key, 0.0, "gauge")  # the tail recovered
        trans = eng.evaluate()
        assert [t["action"] for t in trans] == ["resolved"]
    finally:
        obs.configure_event_log(None)  # flush + close
        eng.close()
        sam.close()
    events = [json.loads(l) for l in path.read_text().splitlines()]
    by_name = {e["event"]: e for e in events}
    assert by_name["alert_firing"]["rule"] == "tail"
    assert by_name["alert_firing"]["severity"] == "page"
    assert by_name["alert_firing"]["trace_exemplars"][0] == "trace7"
    assert by_name["alert_resolved"]["rule"] == "tail"
    # counters rode the registry too
    assert reg.counter("alerts_fired_total",
                       labels={"rule": "tail"}).value == 1


# -- the end-to-end drill -----------------------------------------------------


def test_e2e_slo_burn_episode_fires_degrades_healthz_and_resolves(tmp_path):
    """The ISSUE 12 acceptance drill, tier-1: open-loop load past the knee
    of a real (trivially-jitted) engine injects an SLO-burn episode — the
    burn-rate alert fires within one evaluation window, degrades /healthz
    through the standard aggregation, lands in the EventLog, and resolves
    with hysteresis once the episode ends."""
    reg = obs.MetricsRegistry()
    slo = obs.SLO(latency_target_s=5.0, availability_target=0.9,
                  name="drill", burn_alert=None, min_samples=5)

    def apply_fn(p, x):
        return x * p

    store = obs.SeriesStore()
    sampler = obs.Sampler(registry=reg, store=store, name="drill")
    burn_key = obs.series_key("slo_error_budget_burn_rate",
                              {"engine": "drill", "slo": "drill"})
    rule = obs.AlertRule(name="burn_rate", metric=burn_key, op=">",
                         threshold=2.0, window_s=30.0, agg="last",
                         for_s=0.0, resolve_threshold=0.5,
                         severity="page",
                         description="error budget burning >2x accrual")
    alerts = obs.AlertEngine(store, [rule], registry=reg, name="drill")
    events_path = tmp_path / "drill_events.jsonl"
    with ServingEngine(apply_fn, jnp.float32(2.0), max_batch=4,
                       name="drill", registry=reg, queue_limit=4,
                       slo=slo, slo_window=64) as engine:
        engine.predict(np.ones((1, 3), np.float32), timeout=60)  # warm
        try:
            obs.configure_event_log(str(events_path))
            # -- the episode: open-loop burst far past the 4-part queue —
            # arrivals the engine refuses are shed, and every shed burns
            futs, sheds = [], 0
            for i in range(80):
                try:
                    futs.append(engine.submit(
                        np.ones((1, 3), np.float32)))
                except RejectedError:
                    sheds += 1
            for f in futs:
                f.result(timeout=60)
            assert sheds > 20, "the burst never exceeded the queue bound"
            assert engine.slo_tracker.burn_rate() > 2.0
            # ONE sample + ONE evaluation window: the alert must fire
            sampler.sample_once()
            trans = alerts.evaluate()
            assert [(t["rule"], t["action"]) for t in trans] \
                == [("burn_rate", "firing")]
            ok, detail = obs.healthz()
            assert not ok  # a firing page alert degrades /healthz
            assert detail["sources"]["alerts:drill"]["paging"] \
                == ["burn_rate"]
            # -- the episode ends: good traffic refills the SLO window
            for _ in range(25):
                waves = [engine.submit(np.ones((1, 3), np.float32))
                         for _ in range(3)]
                for f in waves:
                    f.result(timeout=60)
            assert engine.slo_tracker.burn_rate() < 0.5
            sampler.sample_once()
            trans = alerts.evaluate()
            assert [(t["rule"], t["action"]) for t in trans] \
                == [("burn_rate", "resolved")]
            ok, _ = obs.healthz()
            assert ok
        finally:
            obs.configure_event_log(None)
            alerts.close()
            sampler.close()
    events = [json.loads(l) for l in events_path.read_text().splitlines()]
    names = [e["event"] for e in events]
    assert "alert_firing" in names and "alert_resolved" in names
    firing = events[names.index("alert_firing")]
    assert firing["rule"] == "burn_rate" and firing["value"] > 2.0
    assert names.index("alert_firing") < names.index("alert_resolved")
