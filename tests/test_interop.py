"""Reference-artifact interop: Lightning .ckpt import + HF tokenizer JSON.

The torch models here rebuild the reference's exact MODULE STRUCTURE —
positional ``Sequential`` children, ``Residual.module`` wrappers, the
``MultiHeadAttention`` wrapper holding ``nn.MultiheadAttention`` (reference
``perceiver/model.py:29-116``) — so their ``state_dict`` keys are
byte-identical to a published checkpoint's. The importer
(``perceiver_io_tpu/interop.py``) must map those keys onto the flax tree and
golden-match logits at 2e-5.
"""

import json
import math
import os

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

import perceiver_io_tpu as pit
from perceiver_io_tpu.interop import (
    convert_hparams,
    convert_state_dict,
    export_lightning_checkpoint,
    export_orbax_checkpoint,
    export_state_dict,
    import_lightning_checkpoint,
    load_lightning_checkpoint,
)

B, L, VOCAB, C, N_LATENT, HEADS = 2, 10, 40, 16, 6, 4
NUM_LAYERS, SELF_PER_BLOCK = 3, 2

REF_TOKENIZER_JSON = "/root/reference/.cache/imdb-tokenizer-10003.json"


# -- reference-shaped torch modules (state_dict keys match published ckpts) --


class TupleSequential(torch.nn.Sequential):
    """Threads a tuple of inputs through children (reference utils.py:4-11)."""

    def forward(self, *args):
        out = args if len(args) > 1 else args[0]
        for module in self:
            out = module(*out) if isinstance(out, tuple) else module(out)
        return out


class Residual(torch.nn.Module):
    def __init__(self, module):
        super().__init__()
        self.module = module
        self.dropout = torch.nn.Dropout(p=0.0)

    def forward(self, *args):
        return self.dropout(self.module(*args)) + args[0]


class MHAWrapper(torch.nn.Module):
    def __init__(self, q_ch, kv_ch, heads):
        super().__init__()
        self.attention = torch.nn.MultiheadAttention(
            embed_dim=q_ch, num_heads=heads, kdim=kv_ch, vdim=kv_ch,
            batch_first=True,
        )

    def forward(self, x_q, x_kv, pad_mask=None):
        return self.attention(x_q, x_kv, x_kv, key_padding_mask=pad_mask)[0]


class CrossAttention(torch.nn.Module):
    def __init__(self, q_ch, kv_ch, heads):
        super().__init__()
        self.q_norm = torch.nn.LayerNorm(q_ch)
        self.kv_norm = torch.nn.LayerNorm(kv_ch)
        self.attention = MHAWrapper(q_ch, kv_ch, heads)

    def forward(self, x_q, x_kv, pad_mask=None):
        return self.attention(self.q_norm(x_q), self.kv_norm(x_kv), pad_mask)


class SelfAttention(torch.nn.Module):
    def __init__(self, ch, heads):
        super().__init__()
        self.norm = torch.nn.LayerNorm(ch)
        self.attention = MHAWrapper(ch, ch, heads)

    def forward(self, x):
        h = self.norm(x)
        return self.attention(h, h)


def _mlp(ch):
    return torch.nn.Sequential(
        torch.nn.LayerNorm(ch),
        torch.nn.Linear(ch, ch),
        torch.nn.GELU(),
        torch.nn.Linear(ch, ch),
    )


def _cross_layer(q_ch, kv_ch, heads):
    return TupleSequential(
        Residual(CrossAttention(q_ch, kv_ch, heads)), Residual(_mlp(q_ch))
    )


def _self_block(n_layers, ch, heads):
    return TupleSequential(*[
        TupleSequential(Residual(SelfAttention(ch, heads)), Residual(_mlp(ch)))
        for _ in range(n_layers)
    ])


def _perceiver_layer(q_ch, kv_ch, heads, self_layers):
    return TupleSequential(
        _cross_layer(q_ch, kv_ch, heads), _self_block(self_layers, q_ch, heads)
    )


class RefTextAdapter(torch.nn.Module):
    def __init__(self, vocab, max_len, ch):
        super().__init__()
        self.text_embedding = torch.nn.Embedding(vocab, ch)
        self.pos_encoding = torch.nn.Parameter(torch.rand(max_len, ch) - 0.5)
        self.scale = math.sqrt(ch)

    def forward(self, x):
        return self.text_embedding(x) * self.scale + self.pos_encoding[: x.shape[1]]


class RefEncoder(torch.nn.Module):
    def __init__(self, adapter, num_layers):
        super().__init__()
        self.input_adapter = adapter
        self.num_layers = num_layers
        self.layer_1 = _perceiver_layer(C, C, HEADS, SELF_PER_BLOCK)
        self.layer_n = _perceiver_layer(C, C, HEADS, SELF_PER_BLOCK)
        self.latent = torch.nn.Parameter(torch.randn(N_LATENT, C) * 0.02)

    def forward(self, x, pad_mask=None):
        x = self.input_adapter(x)
        latent = self.latent.expand(x.shape[0], -1, -1)
        latent = self.layer_1(latent, x, pad_mask)
        for _ in range(self.num_layers - 1):
            latent = self.layer_n(latent, x, pad_mask)
        return latent


class RefOutputAdapter(torch.nn.Module):
    def __init__(self, num_classes, ch):
        super().__init__()
        self.linear = torch.nn.Linear(ch, num_classes)

    def forward(self, x):
        return self.linear(x).squeeze(dim=1)


class RefDecoder(torch.nn.Module):
    def __init__(self, output_adapter, output_shape):
        super().__init__()
        self.output_adapter = output_adapter
        self.cross_attention = _cross_layer(C, C, HEADS)
        self.output = torch.nn.Parameter(torch.randn(*output_shape) * 0.02)

    def forward(self, x):
        out = self.output.expand(x.shape[0], -1, -1)
        out = self.cross_attention(out, x)
        return self.output_adapter(out)


class RefMLM(torch.nn.Module):
    """PerceiverMLM layout: named encoder/decoder/masking children
    (reference model.py:296-303)."""

    def __init__(self):
        super().__init__()
        self.encoder = RefEncoder(RefTextAdapter(VOCAB, L, C), NUM_LAYERS)
        self.decoder = RefDecoder(RefOutputAdapter(VOCAB, C), (L, C))
        self.masking = torch.nn.Identity()  # no params, like TextMasking

    def forward(self, ids, pad_mask=None):
        logits = self.decoder(self.encoder(ids, pad_mask))
        return logits[:, : ids.shape[1], :]


class RefIO(TupleSequential):
    """PerceiverIO layout: positional encoder/decoder (model.py:321-325)."""

    def __init__(self, num_classes=3):
        super().__init__(
            RefEncoder(RefTextAdapter(VOCAB, L, C), NUM_LAYERS),
            RefDecoder(RefOutputAdapter(num_classes, C), (1, C)),
        )


def _lightning_ckpt(module, hparams):
    return {
        "state_dict": {f"model.{k}": v for k, v in module.state_dict().items()},
        "hyper_parameters": dict(hparams),
    }


REF_HPARAMS = {
    "num_latents": N_LATENT,
    "num_latent_channels": C,
    "num_encoder_layers": NUM_LAYERS,
    "num_encoder_cross_attention_heads": HEADS,
    "num_encoder_self_attention_heads": HEADS,
    "num_encoder_self_attention_layers_per_block": SELF_PER_BLOCK,
    "num_decoder_cross_attention_heads": HEADS,
    "dropout": 0.0,
    "max_seq_len": L,
    "vocab_size": VOCAB,
}


def _build_flax_mlm():
    from perceiver_io_tpu.models.presets import flagship_mlm

    return flagship_mlm(
        vocab_size=VOCAB, max_seq_len=L, num_latents=N_LATENT,
        num_channels=C, num_layers=NUM_LAYERS,
        num_self_attention_layers_per_block=SELF_PER_BLOCK,
    )


def _build_flax_classifier(num_classes=3):
    return pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_channels=C
            ),
            latent_shape=(N_LATENT, C),
            num_layers=NUM_LAYERS,
            num_cross_attention_heads=HEADS,
            num_self_attention_heads=HEADS,
            num_self_attention_layers_per_block=SELF_PER_BLOCK,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=num_classes, num_output_channels=C
            ),
            latent_shape=(N_LATENT, C),
            num_cross_attention_heads=HEADS,
        ),
    )


# -- checkpoint import -------------------------------------------------------


def test_mlm_ckpt_import_golden(tmp_path, rng):
    torch.manual_seed(0)
    ref = RefMLM().eval()
    path = tmp_path / "mlm.ckpt"
    torch.save(_lightning_ckpt(ref, REF_HPARAMS), path)

    params, hparams = import_lightning_checkpoint(str(path))
    assert hparams["num_cross_attention_heads"] == HEADS
    assert hparams["num_self_attention_layers_per_block"] == SELF_PER_BLOCK

    model = _build_flax_mlm()
    init = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        jnp.zeros((1, L), jnp.int32), jnp.zeros((1, L), bool),
    )["params"]
    # exhaustive: every init leaf imported, no extras, shapes agree
    got = {jax.tree_util.keystr(p): v.shape
           for p, v in jax.tree_util.tree_leaves_with_path(params)}
    want = {jax.tree_util.keystr(p): v.shape
            for p, v in jax.tree_util.tree_leaves_with_path(init)}
    assert got == want

    ids = rng.integers(0, VOCAB, size=(B, L)).astype(np.int64)
    pad = np.zeros((B, L), dtype=bool)
    pad[0, -3:] = True
    with torch.no_grad():
        t_logits = ref(torch.tensor(ids), torch.tensor(pad)).numpy()
    j_logits, _ = model.apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        jnp.asarray(ids.astype(np.int32)), jnp.asarray(pad), masking=False,
    )
    np.testing.assert_allclose(np.asarray(j_logits), t_logits, atol=2e-5)


def test_perceiver_io_positional_root(rng):
    """Classifier ckpts store the PerceiverIO Sequential as model.0/model.1."""
    torch.manual_seed(1)
    ref = RefIO().eval()
    sd = {f"model.{k}": v for k, v in ref.state_dict().items()}
    params = convert_state_dict(sd)
    assert set(params) == {"encoder", "decoder"}

    ids = rng.integers(0, VOCAB, size=(B, L)).astype(np.int64)
    with torch.no_grad():
        t_logits = ref(torch.tensor(ids), None).numpy()
    model = _build_flax_classifier()
    j_logits = model.apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        jnp.asarray(ids.astype(np.int32)), pad_mask=None,
    )
    np.testing.assert_allclose(np.asarray(j_logits), t_logits, atol=2e-5)


def test_encoder_only_import(tmp_path):
    torch.manual_seed(2)
    ref = RefMLM()
    path = tmp_path / "mlm.ckpt"
    torch.save(_lightning_ckpt(ref, REF_HPARAMS), path)
    full, _ = import_lightning_checkpoint(str(path))
    enc_only, _ = import_lightning_checkpoint(str(path), encoder_only=True)
    assert set(enc_only) == {"encoder"}
    jax.tree.map(np.testing.assert_array_equal, enc_only["encoder"], full["encoder"])


def test_export_orbax_roundtrip(tmp_path):
    from perceiver_io_tpu.training.checkpoint import (
        load_hparams,
        restore_encoder_params,
        restore_params,
    )

    torch.manual_seed(3)
    ref = RefMLM()
    ckpt = tmp_path / "mlm.ckpt"
    torch.save(_lightning_ckpt(ref, REF_HPARAMS), ckpt)
    out = tmp_path / "imported"
    params, hparams = import_lightning_checkpoint(str(ckpt))
    export_orbax_checkpoint(params, str(out), hparams=hparams)

    assert load_hparams(str(out))["num_latents"] == N_LATENT
    restored = restore_params(str(out), params)
    jax.tree.map(np.testing.assert_array_equal, restored, params)
    enc = restore_encoder_params(str(out), params["encoder"])
    jax.tree.map(np.testing.assert_array_equal, enc, params["encoder"])


@pytest.mark.slow  # tier-1 budget (r10): torch-checkpoint import parity
# stays tier-1 in the import_reference tests here; encoder-transfer
# semantics in tests/test_train_steps.py::test_frozen_encoder_transfer
def test_seq_clf_cli_accepts_torch_ckpt(tmp_path):
    """The reference's pretrained-weights entry (README.md:46-48): hand a
    Lightning .ckpt straight to --mlm_checkpoint."""
    from perceiver_io_tpu.cli import train_seq_clf
    from perceiver_io_tpu.training import read_metrics

    torch.manual_seed(4)
    ref = RefMLM()
    ckpt = tmp_path / "ref-mlm.ckpt"
    torch.save(_lightning_ckpt(ref, REF_HPARAMS), ckpt)

    run = train_seq_clf.main([
        "--synthetic", "--logdir", str(tmp_path / "logs"),
        "--root", str(tmp_path / "cache"),
        "--dtype", "float32",
        "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", str(L), "--vocab_size", str(VOCAB),
        "--max_steps", "2", "--log_every_n_steps", "1",
        "--num_latents", "32",  # must be overridden by the ckpt's hparams
        "--mlm_checkpoint", str(ckpt), "--freeze_encoder",
    ])
    rows = read_metrics(run)
    assert any("train_loss" in r for r in rows)


def test_import_rejects_unknown_keys():
    with pytest.raises(KeyError):
        convert_state_dict({"model.bogus.weight": torch.zeros(2)})


def test_convert_hparams_renames():
    out = convert_hparams({
        "num_encoder_cross_attention_heads": 8,
        "num_latents": 64,
        "learning_rate": 1e-3,
    })
    assert out["num_cross_attention_heads"] == 8
    assert out["num_latents"] == 64
    assert out["learning_rate"] == 1e-3


# -- HF tokenizer JSON -------------------------------------------------------


@pytest.mark.skipif(
    not os.path.exists(REF_TOKENIZER_JSON),
    reason="reference cached tokenizer not present",
)
def test_load_reference_hf_tokenizer():
    from perceiver_io_tpu.data.tokenizer import WordPieceTokenizer

    tok = WordPieceTokenizer.from_file(REF_TOKENIZER_JSON)
    assert tok.get_vocab_size() == 10003
    assert tok.token_to_id("[PAD]") == 0
    assert tok.token_to_id("[UNK]") == 1
    assert tok.token_to_id("[MASK]") == 2
    assert tok.replacements == [("<br />", " ")]
    ids = tok.encode_ids("This movie was great!<br />Loved it.")
    assert ids and all(0 <= i < 10003 for i in ids)
    assert "movie" in tok.decode(ids)


@pytest.mark.skipif(
    not os.path.exists(REF_TOKENIZER_JSON),
    reason="reference cached tokenizer not present",
)
def test_reference_tokenizer_matches_hf_library():
    """Token-id parity with the HF Rust library on the reference's own
    artifact — ids index embedding rows, so exactness is the contract."""
    tokenizers = pytest.importorskip("tokenizers")

    from perceiver_io_tpu.data.tokenizer import WordPieceTokenizer

    ours = WordPieceTokenizer.from_file(REF_TOKENIZER_JSON)
    theirs = tokenizers.Tokenizer.from_file(REF_TOKENIZER_JSON)
    samples = [
        "This movie was great!<br /><br />I loved it.",
        "Café au lait, naïve résumé — ÅNGSTRÖM.",
        "unbelievably overacted... 10/10 would NOT recommend :-)",
        "short",
        "word-with-hyphens and CAPS and numbers 12345 67890",
        "supercalifragilisticexpialidocious antidisestablishmentarianism",
    ]
    for text in samples:
        assert ours.encode_ids(text) == theirs.encode(text).ids, text


def test_hf_roundtrip_via_our_writer(tmp_path, rng):
    """Train a tiny tokenizer, save in the HF schema, reload with both our
    loader and (if present) the HF library — ids must agree."""
    from perceiver_io_tpu.data.tokenizer import (
        WordPieceTokenizer,
        create_tokenizer,
        train_tokenizer,
    )

    corpus = [
        "the quick brown fox jumps over the lazy dog",
        "pack my box with five dozen liquor jugs",
        "sphinx of black quartz judge my vow",
    ] * 5
    tok = create_tokenizer(("<br />", " "))
    train_tokenizer(tok, corpus, vocab_size=80)
    path = tmp_path / "tok.json"
    tok.save(str(path), format="hf")

    reloaded = WordPieceTokenizer.from_file(str(path))
    assert reloaded.vocab == tok.vocab
    assert reloaded.replacements == [("<br />", " ")]
    text = "the quick liquor sphinx<br />judge"
    assert reloaded.encode_ids(text) == tok.encode_ids(text)

    try:
        import tokenizers
    except ImportError:
        return
    theirs = tokenizers.Tokenizer.from_file(str(path))
    assert theirs.encode(text).ids == tok.encode_ids(text)


def test_from_hf_dict_rejects_unsupported():
    from perceiver_io_tpu.data.tokenizer import WordPieceTokenizer

    ok_vocab = {"[PAD]": 0, "[UNK]": 1, "[MASK]": 2, "a": 3}
    ok_normalizer = {
        "type": "Sequence",
        "normalizers": [
            {"type": "NFD"}, {"type": "Lowercase"}, {"type": "StripAccents"},
        ],
    }

    def hf(**overrides):
        payload = {
            "model": {"type": "WordPiece", "vocab": dict(ok_vocab)},
            "normalizer": {
                "type": "Sequence",
                "normalizers": [dict(n) for n in ok_normalizer["normalizers"]],
            },
            "pre_tokenizer": {"type": "Whitespace"},
        }
        payload.update(overrides)
        return payload

    WordPieceTokenizer.from_hf_dict(hf())  # baseline accepted

    with pytest.raises(ValueError, match="unsupported tokenizer model"):
        WordPieceTokenizer.from_hf_dict({"model": {"type": "BPE", "vocab": {}}})
    with pytest.raises(ValueError, match="unsupported normalizer"):
        WordPieceTokenizer.from_hf_dict(hf(normalizer={"type": "NFC"}))
    with pytest.raises(ValueError, match="normalizer pipeline must be"):
        # a PARTIAL pipeline (e.g. cased vocab, no Lowercase) would silently
        # diverge from the HF library — must be rejected, not accepted
        WordPieceTokenizer.from_hf_dict(hf(normalizer={"type": "NFD"}))
    with pytest.raises(ValueError, match="normalizer pipeline must be"):
        WordPieceTokenizer.from_hf_dict(hf(normalizer=None))
    with pytest.raises(ValueError, match="pre-tokenizer must be Whitespace"):
        WordPieceTokenizer.from_hf_dict(hf(pre_tokenizer=None))
    with pytest.raises(ValueError, match="added tokens"):
        WordPieceTokenizer.from_hf_dict(hf(added_tokens=[
            {"id": 3, "content": "[CLS]", "special": True},
        ]))
    with pytest.raises(ValueError, match="post_processor"):
        WordPieceTokenizer.from_hf_dict(
            hf(post_processor={"type": "TemplateProcessing"})
        )
    with pytest.raises(ValueError, match="unk_token"):
        payload = hf()
        payload["model"]["unk_token"] = "<unk>"
        WordPieceTokenizer.from_hf_dict(payload)
    with pytest.raises(ValueError, match="must have id"):
        # specials not at ids 0/1/2 would break the masking op's
        # first-ids assumption
        payload = hf()
        payload["model"]["vocab"] = {"[PAD]": 0, "[UNK]": 5, "[MASK]": 2, "a": 1}
        WordPieceTokenizer.from_hf_dict(payload)
    with pytest.raises(ValueError, match="Replace normalizers after"):
        WordPieceTokenizer.from_hf_dict(hf(normalizer={
            "type": "Sequence",
            "normalizers": [
                {"type": "NFD"}, {"type": "Lowercase"},
                {"type": "Replace", "pattern": {"String": "x"}, "content": "y"},
                {"type": "StripAccents"},
            ],
        }))


def test_mlm_predictor_from_imported_checkpoint(tmp_path, rng):
    """The full imported-artifact inference path: reference .ckpt -> Orbax
    export -> MLMPredictor.from_checkpoint rebuilds the model from the
    RENAMED hparams and serves fill-mask predictions that match the torch
    model's logits."""
    from perceiver_io_tpu.data.tokenizer import WordPieceTokenizer
    from perceiver_io_tpu.inference import MLMPredictor

    torch.manual_seed(5)
    ref = RefMLM().eval()
    ckpt = tmp_path / "mlm.ckpt"
    torch.save(_lightning_ckpt(ref, REF_HPARAMS), ckpt)
    out = tmp_path / "imported"
    params, hparams = import_lightning_checkpoint(str(ckpt))
    export_orbax_checkpoint(params, str(out), hparams=hparams)

    # a VOCAB-sized tokenizer: specials + simple word tokens
    vocab = {"[PAD]": 0, "[UNK]": 1, "[MASK]": 2}
    for i in range(3, VOCAB):
        vocab[f"w{i}"] = i
    tok = WordPieceTokenizer(vocab=vocab)

    pred = MLMPredictor.from_checkpoint(str(out), tok)
    assert pred.max_seq_len == L

    texts = ["w3 w4 [MASK] w6"]
    results = pred.fill_masks(texts, k=3)
    assert len(results) == 1 and len(results[0]) == 1  # one mask position
    assert len(results[0][0]) == 3  # top-3 candidates

    # logits parity at the masked position vs the torch model
    ids = np.full((1, L), 0, np.int64)
    ids[0, :4] = [3, 4, 2, 6]
    pad = ids == 0
    with torch.no_grad():
        t_logits = ref(torch.tensor(ids), torch.tensor(pad)).numpy()
    j_logits, j_ids = pred.logits(texts)
    np.testing.assert_array_equal(j_ids[0, :4], [3, 4, 2, 6])
    np.testing.assert_allclose(j_logits[0, 2], t_logits[0, 2], atol=2e-5)


# -- reverse interop: flax params → reference torch checkpoint ----------------


def _init_flax_mlm_params(rng):
    model = _build_flax_mlm()
    ids = jnp.asarray(rng.integers(3, VOCAB, (1, L)).astype(np.int32))
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        ids, jnp.zeros((1, L), bool),
    )
    return variables["params"]


def test_export_state_dict_round_trips_exactly(rng):
    """convert_state_dict(export_state_dict(p)) == p, array-EXACT — the
    inverse really inverts (incl. the MHA merge/split and every transpose)."""
    params = _init_flax_mlm_params(rng)
    sd = export_state_dict(params, layout="mlm")
    back = convert_state_dict(sd)
    flat_a = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(back)[0]
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    for (path, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(path))


def _export_load_and_compare(rng, torch_mlm, **forward_kwargs):
    """Shared reverse-golden body: export flax params, strict-load them into
    ``torch_mlm``, and assert torch forward == flax forward at 2e-5."""
    params = _init_flax_mlm_params(rng)
    sd = export_state_dict(params, layout="mlm", lightning_prefix=False)
    torch_mlm.load_state_dict(
        {k: torch.from_numpy(v.copy()) for k, v in sd.items()}, strict=True)
    torch_mlm.eval()

    model = _build_flax_mlm()
    ids = rng.integers(3, VOCAB, (2, L)).astype(np.int64)
    with torch.no_grad():
        out = torch_mlm(torch.from_numpy(ids), **forward_kwargs)
    theirs = (out[0] if isinstance(out, tuple) else out).numpy()
    ours, _ = model.apply(
        {"params": params}, jnp.asarray(ids.astype(np.int32)),
        jnp.zeros((2, L), bool), masking=False,
    )
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-5)


def test_export_loads_into_reference_module_strict(rng):
    """The exported state_dict loads into the reference-shaped torch MLM with
    strict=True — key set and shapes are EXACTLY the reference's — and the
    loaded torch model's forward matches the flax forward (the golden check
    run in reverse)."""
    _export_load_and_compare(rng, RefMLM())


def test_export_classifier_layout_round_trip(rng):
    """'classifier' layout: positional 0./1. keys load strict into the
    reference PerceiverIO Sequential and re-import to the same tree."""
    model = _build_flax_classifier()
    ids = jnp.asarray(rng.integers(3, VOCAB, (1, L)).astype(np.int32))
    params = model.init({"params": jax.random.key(0)}, ids,
                        pad_mask=jnp.zeros((1, L), bool))["params"]
    sd = export_state_dict(params, layout="classifier", lightning_prefix=False)
    ref = RefIO(num_classes=3)
    ref.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()},
                        strict=True)
    back = convert_state_dict(sd)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(back)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))


def test_export_lightning_checkpoint_full_cycle(tmp_path, rng):
    """export_lightning_checkpoint → import_lightning_checkpoint closes the
    loop THROUGH A FILE: safe weights_only load, params array-exact, and the
    hparams renamed back to the reference spellings then forward again."""
    params = _init_flax_mlm_params(rng)
    hparams = {"num_latents": N_LATENT, "num_latent_channels": C,
               "num_cross_attention_heads": HEADS,
               "num_self_attention_layers_per_block": SELF_PER_BLOCK,
               "ignored_fn": lambda: None}  # non-JSONable values are dropped
    path = tmp_path / "exported.ckpt"
    export_lightning_checkpoint(params, str(path), hparams=hparams,
                                epoch=7, global_step=1234)

    # the reference spelling landed in the file...
    raw_sd, raw_hp = load_lightning_checkpoint(str(path))  # safe loader only
    assert "num_encoder_self_attention_layers_per_block" in raw_hp
    assert "ignored_fn" not in raw_hp
    assert all(k.startswith("model.") for k in raw_sd)

    # ...and the full import path round-trips params + hparams
    back, hp = import_lightning_checkpoint(str(path))
    assert hp["num_self_attention_layers_per_block"] == SELF_PER_BLOCK
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(back)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))


def test_export_rejects_non_text_adapters(rng):
    """Image-adapter params have no reference-side tensors to export — the
    error must say so instead of emitting a half-checkpoint."""
    model = pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.ImageInputAdapter(image_shape=(8, 8, 1),
                                                num_frequency_bands=4),
            latent_shape=(4, 16), num_layers=1,
            num_self_attention_layers_per_block=1,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=2, num_output_channels=16),
            latent_shape=(4, 16),
        ),
    )
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8, 8, 1)))["params"]
    # the flax image adapter holds no params at all (its Fourier encoding is
    # a deterministic buffer) — export must raise the explanatory error, not
    # a bare KeyError
    with pytest.raises(ValueError, match="TEXT models"):
        export_state_dict(params, layout="classifier")


@pytest.mark.skipif(not os.path.isdir("/root/reference/perceiver"),
                    reason="reference source tree not mounted")
def test_export_loads_into_the_actual_reference_model(rng):
    """The strongest export proof: strict ``load_state_dict`` into the
    REFERENCE'S OWN ``PerceiverMLM`` (its source imported read-only from the
    mounted tree — model/adapter modules only; ``perceiver/__init__`` pulls
    Lightning deps this environment doesn't ship) and forward-match at 2e-5.
    The replica-module tests above cover environments without the mount."""
    import importlib.util
    import sys
    import types

    # the reference's modules need deps this repo doesn't depend on
    pytest.importorskip("einops")
    pytest.importorskip("tokenizers")
    if "perceiver.model" not in sys.modules:
        inserted = ["perceiver"]
        pkg = types.ModuleType("perceiver")
        pkg.__path__ = ["/root/reference/perceiver"]
        sys.modules["perceiver"] = pkg
        try:
            for name in ("utils", "tokenizer", "adapter", "model"):
                spec = importlib.util.spec_from_file_location(
                    f"perceiver.{name}", f"/root/reference/perceiver/{name}.py")
                mod = importlib.util.module_from_spec(spec)
                sys.modules[f"perceiver.{name}"] = mod
                inserted.append(f"perceiver.{name}")
                spec.loader.exec_module(mod)
        except Exception:
            # never leave half-initialized fakes shadowing real imports
            for name in inserted:
                sys.modules.pop(name, None)
            raise
    M = sys.modules["perceiver.model"]
    A = sys.modules["perceiver.adapter"]

    ref = M.PerceiverMLM(
        M.PerceiverEncoder(
            input_adapter=A.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_input_channels=C),
            latent_shape=(N_LATENT, C), num_layers=NUM_LAYERS,
            num_cross_attention_heads=HEADS, num_self_attention_heads=HEADS,
            num_self_attention_layers_per_block=SELF_PER_BLOCK, dropout=0.0),
        M.PerceiverDecoder(
            output_adapter=A.TextOutputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_output_channels=C),
            latent_shape=(N_LATENT, C), num_cross_attention_heads=HEADS,
            dropout=0.0),
        M.TextMasking(VOCAB, unk_token_id=1, mask_token_id=2,
                      num_special_tokens=3),
    )

    _export_load_and_compare(rng, ref, masking=False)
