"""Structural tests for the Perceiver core: weight sharing, shapes, masking flow."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from perceiver_io_tpu.models.adapters import (
    ClassificationOutputAdapter,
    ImageInputAdapter,
    TextInputAdapter,
    TextOutputAdapter,
)
from perceiver_io_tpu.models.perceiver import (
    PerceiverDecoder,
    PerceiverEncoder,
    PerceiverIO,
    PerceiverMLM,
)
from perceiver_io_tpu.ops.masking import IGNORE_LABEL, TextMasking

VOCAB, MAX_LEN, C = 60, 24, 32
LATENT_SHAPE = (8, C)


def make_text_encoder(num_layers=3):
    return PerceiverEncoder(
        input_adapter=TextInputAdapter(vocab_size=VOCAB, max_seq_len=MAX_LEN, num_channels=C),
        latent_shape=LATENT_SHAPE,
        num_layers=num_layers,
        num_self_attention_layers_per_block=2,
    )


def test_encoder_output_shape(rng):
    enc = make_text_encoder()
    x = jnp.asarray(rng.integers(0, VOCAB, size=(4, MAX_LEN)).astype(np.int32))
    pad = jnp.zeros((4, MAX_LEN), dtype=bool)
    variables = enc.init(jax.random.key(0), x, pad)
    out = enc.apply(variables, x, pad)
    assert out.shape == (4, *LATENT_SHAPE)


def test_encoder_weight_sharing(rng):
    """Layers 2..N share one weight set: params contain exactly layer_1 and
    layer_n (reference model.py:162-166)."""
    enc = make_text_encoder(num_layers=5)
    x = jnp.zeros((2, MAX_LEN), dtype=jnp.int32)
    variables = enc.init(jax.random.key(0), x, None)
    layer_keys = {k for k in variables["params"] if k.startswith("layer")}
    assert layer_keys == {"layer_1", "layer_n"}


def test_encoder_single_layer_has_no_layer_n():
    enc = make_text_encoder(num_layers=1)
    x = jnp.zeros((2, MAX_LEN), dtype=jnp.int32)
    variables = enc.init(jax.random.key(0), x, None)
    layer_keys = {k for k in variables["params"] if k.startswith("layer")}
    assert layer_keys == {"layer_1"}


def test_encoder_depth_changes_output(rng):
    """Recurrent applications of layer_n must actually run (same params,
    different depth ⇒ different output)."""
    x = jnp.asarray(rng.integers(0, VOCAB, size=(2, MAX_LEN)).astype(np.int32))
    enc3 = make_text_encoder(num_layers=3)
    enc5 = make_text_encoder(num_layers=5)
    v = enc3.init(jax.random.key(0), x, None)
    out3 = enc3.apply(v, x, None)
    out5 = enc5.apply(v, x, None)  # same params, more recurrence
    assert not np.allclose(np.asarray(out3), np.asarray(out5), atol=1e-4)


def test_encoder_gradients_flow_through_shared_layers(rng):
    enc = make_text_encoder(num_layers=3)
    x = jnp.asarray(rng.integers(0, VOCAB, size=(2, MAX_LEN)).astype(np.int32))
    variables = enc.init(jax.random.key(0), x, None)

    def loss(params):
        return jnp.sum(enc.apply({"params": params}, x, None) ** 2)

    grads = jax.grad(loss)(variables["params"])
    flat = jax.tree.leaves(jax.tree.map(lambda g: float(jnp.abs(g).sum()), grads))
    assert all(np.isfinite(flat))
    # shared layer and latent both receive gradient
    g_latent = jnp.abs(grads["latent"]).sum()
    assert float(g_latent) > 0
    g_layer_n = sum(jax.tree.leaves(jax.tree.map(lambda g: float(jnp.abs(g).sum()),
                                                 grads["layer_n"])))
    assert g_layer_n > 0


def test_latent_init_distribution():
    enc = make_text_encoder()
    x = jnp.zeros((1, MAX_LEN), dtype=jnp.int32)
    variables = enc.init(jax.random.key(0), x, None)
    latent = np.asarray(variables["params"]["latent"])
    assert np.abs(latent).max() <= 2.0
    assert 0.005 < latent.std() < 0.05  # ~N(0, 0.02)


def test_decoder_validates_latent_shape(rng):
    dec = PerceiverDecoder(
        output_adapter=ClassificationOutputAdapter(num_classes=10, num_output_channels=C),
        latent_shape=LATENT_SHAPE,
    )
    good = jnp.zeros((2, *LATENT_SHAPE))
    variables = dec.init(jax.random.key(0), good)
    with pytest.raises(ValueError, match="Latent shape"):
        dec.apply(variables, jnp.zeros((2, 4, C)))


def test_perceiver_io_text_classification(rng):
    enc = make_text_encoder()
    dec = PerceiverDecoder(
        output_adapter=ClassificationOutputAdapter(num_classes=2, num_output_channels=C),
        latent_shape=LATENT_SHAPE,
    )
    model = PerceiverIO(encoder=enc, decoder=dec)
    x = jnp.asarray(rng.integers(0, VOCAB, size=(4, MAX_LEN)).astype(np.int32))
    pad = jnp.zeros((4, MAX_LEN), dtype=bool)
    variables = model.init(jax.random.key(0), x, pad)
    logits = model.apply(variables, x, pad)
    assert logits.shape == (4, 2)


def test_perceiver_io_image_classification(rng):
    enc = PerceiverEncoder(
        input_adapter=ImageInputAdapter(image_shape=(14, 14, 1), num_frequency_bands=8),
        latent_shape=(16, 64),
        num_layers=2,
        num_self_attention_layers_per_block=2,
    )
    dec = PerceiverDecoder(
        output_adapter=ClassificationOutputAdapter(num_classes=10, num_output_channels=64),
        latent_shape=(16, 64),
    )
    model = PerceiverIO(encoder=enc, decoder=dec)
    x = jnp.asarray(rng.standard_normal((2, 14, 14, 1)).astype(np.float32))
    variables = model.init(jax.random.key(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 10)


def make_mlm(num_layers=2):
    enc = make_text_encoder(num_layers)
    dec = PerceiverDecoder(
        output_adapter=TextOutputAdapter(vocab_size=VOCAB, max_seq_len=MAX_LEN,
                                         num_output_channels=C),
        latent_shape=LATENT_SHAPE,
    )
    masking = TextMasking(vocab_size=VOCAB, unk_token_id=1, mask_token_id=2,
                          num_special_tokens=3)
    return PerceiverMLM(encoder=enc, decoder=dec, masking=masking)


def test_mlm_forward_with_masking(rng):
    model = make_mlm()
    x = jnp.asarray(rng.integers(3, VOCAB, size=(4, MAX_LEN)).astype(np.int32))
    pad = jnp.zeros((4, MAX_LEN), dtype=bool)
    variables = model.init({"params": jax.random.key(0), "masking": jax.random.key(1)},
                           x, pad)
    logits, labels = model.apply(variables, x, pad,
                                 rngs={"masking": jax.random.key(2)})
    assert logits.shape == (4, MAX_LEN, VOCAB)
    assert labels.shape == (4, MAX_LEN)
    assert (np.asarray(labels) != IGNORE_LABEL).any()


def test_mlm_truncates_logits_to_input_length(rng):
    model = make_mlm()
    x_full = jnp.asarray(rng.integers(3, VOCAB, size=(2, MAX_LEN)).astype(np.int32))
    variables = model.init({"params": jax.random.key(0), "masking": jax.random.key(1)},
                           x_full, jnp.zeros((2, MAX_LEN), dtype=bool))
    l = MAX_LEN // 2
    x = x_full[:, :l]
    pad = jnp.zeros((2, l), dtype=bool)
    logits, labels = model.apply(variables, x, pad, masking=False)
    assert logits.shape == (2, l, VOCAB)
    assert labels is None


def test_mlm_no_masking_is_deterministic(rng):
    model = make_mlm()
    x = jnp.asarray(rng.integers(3, VOCAB, size=(2, MAX_LEN)).astype(np.int32))
    pad = jnp.zeros((2, MAX_LEN), dtype=bool)
    variables = model.init({"params": jax.random.key(0), "masking": jax.random.key(1)},
                           x, pad)
    l1, _ = model.apply(variables, x, pad, masking=False)
    l2, _ = model.apply(variables, x, pad, masking=False)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2))


def test_pad_mask_affects_output(rng):
    enc = make_text_encoder()
    x = jnp.asarray(rng.integers(0, VOCAB, size=(2, MAX_LEN)).astype(np.int32))
    variables = enc.init(jax.random.key(0), x, None)
    pad_none = jnp.zeros((2, MAX_LEN), dtype=bool)
    pad_half = pad_none.at[:, MAX_LEN // 2 :].set(True)
    o1 = enc.apply(variables, x, pad_none)
    o2 = enc.apply(variables, x, pad_half)
    assert not np.allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


def test_bfloat16_compute(rng):
    enc = PerceiverEncoder(
        input_adapter=TextInputAdapter(vocab_size=VOCAB, max_seq_len=MAX_LEN,
                                       num_channels=C, dtype=jnp.bfloat16),
        latent_shape=LATENT_SHAPE,
        num_layers=2,
        dtype=jnp.bfloat16,
    )
    x = jnp.asarray(rng.integers(0, VOCAB, size=(2, MAX_LEN)).astype(np.int32))
    variables = enc.init(jax.random.key(0), x, None)
    # params stay f32
    assert variables["params"]["latent"].dtype == jnp.float32
    out = enc.apply(variables, x, None)
    assert out.dtype == jnp.bfloat16


def test_decoder_positions_match_full_decode(rng):
    """Decoding a subset of output-query positions equals the corresponding
    rows of the full decode (each query attends to the latents independently)."""
    dec = PerceiverDecoder(
        output_adapter=TextOutputAdapter(vocab_size=VOCAB, max_seq_len=MAX_LEN,
                                         num_output_channels=C),
        latent_shape=LATENT_SHAPE,
    )
    latent = jnp.asarray(rng.standard_normal((3, *LATENT_SHAPE)), jnp.float32)
    variables = dec.init(jax.random.key(0), latent)
    full = np.asarray(dec.apply(variables, latent))
    positions = jnp.asarray(rng.integers(0, MAX_LEN, size=(3, 5)).astype(np.int32))
    subset = np.asarray(dec.apply(variables, latent, positions=positions))
    expected = np.take_along_axis(full, np.asarray(positions)[:, :, None], axis=1)
    np.testing.assert_allclose(subset, expected, rtol=1e-5, atol=1e-5)


def test_mlm_gathered_loss_matches_full(rng):
    """CE over the gathered masked positions equals CE over the full decode
    (label -100 positions contribute nothing), and so do the gradients."""
    from perceiver_io_tpu.training.losses import cross_entropy_with_ignore

    model = make_mlm()
    x = jnp.asarray(rng.integers(3, VOCAB, size=(4, MAX_LEN)).astype(np.int32))
    pad = jnp.zeros((4, MAX_LEN), dtype=bool)
    variables = model.init({"params": jax.random.key(0), "masking": jax.random.key(1)},
                           x, pad)
    mask_key = jax.random.key(7)

    def loss(params, capacity):
        logits, labels = model.apply(
            {"params": params}, x, pad, rngs={"masking": mask_key},
            loss_gather_capacity=capacity,
        )
        return cross_entropy_with_ignore(logits, labels)

    # capacity = MAX_LEN - 1 forces the gather path; every masked position
    # fits (15% of 24 positions), so the result must match the full decode
    full_loss, full_grads = jax.value_and_grad(loss)(variables["params"], None)
    gath_loss, gath_grads = jax.value_and_grad(loss)(variables["params"], MAX_LEN - 1)
    np.testing.assert_allclose(float(full_loss), float(gath_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6),
        full_grads, gath_grads,
    )


def test_mlm_gather_capacity_helper():
    from perceiver_io_tpu.training.steps import mlm_gather_capacity

    assert mlm_gather_capacity(512) == 160  # 2·0.15·512 = 153.6 → 160
    assert mlm_gather_capacity(512) % 32 == 0
    assert mlm_gather_capacity(24) == 24  # capped at seq_len... still ≥ 32 rule
    assert mlm_gather_capacity(4096, 0.15) >= int(2 * 0.15 * 4096)


def test_flagship_tpu_preset_shapes():
    """The TPU-widths preset keeps the reference recipe SHAPE (3 encoder
    layers x 6 self-attention layers, shared layer_n, text in/out adapters)
    and only widens: 256 latents x 512 channels, 4 heads => head depth 128
    (models/presets.py flagship_tpu_mlm; the BASELINE.md north-star closed
    at TPU-native widths)."""
    from perceiver_io_tpu.models.presets import flagship_tpu_mlm

    model = flagship_tpu_mlm(vocab_size=97, max_seq_len=32, dtype=jnp.float32)
    tok = jnp.zeros((1, 32), jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        tok, jnp.zeros((1, 32), bool),
    )
    params = variables["params"]
    assert params["encoder"]["latent"].shape == (256, 512)
    sa = params["encoder"]["layer_n"]["self_attention_block"]
    assert sorted(sa) == [f"layer_{i}" for i in range(6)]
    q = sa["layer_0"]["self_attention"]["attention"]["q_proj"]["kernel"]
    assert q.shape == (512, 512)  # 4 heads x depth 128 (full MXU contraction)
    assert model.encoder.num_cross_attention_heads == 4
    # 3 encoder layers = layer_1 + shared layer_n applied twice
    assert model.encoder.num_layers == 3


class TestSharedLayerKVReuse:
    """reuse_kv=True (the default) caches the shared layer_n cross-attention
    K/V projections across recurrent applications — identical weights on the
    identical input make the repeat pure recompute (models/perceiver.py).
    The cache is the SAME tensor reused, so the forward must be bit-exact
    against recompute; gradients reassociate one near-cancelling reduction
    (dk1+dk2 summed before vs after the dW matmul) and agree to fp noise."""

    def _encoder(self, reuse, remat=False):
        return PerceiverEncoder(
            input_adapter=TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=MAX_LEN, num_channels=C,
                dtype=jnp.float32,
            ),
            latent_shape=(8, C),
            num_layers=3,
            num_self_attention_layers_per_block=2,
            reuse_kv=reuse,
            remat=remat,
        )

    def test_forward_bit_exact_and_grads_close(self):
        x = jnp.asarray(
            np.random.default_rng(3).integers(0, VOCAB, (2, MAX_LEN)), jnp.int32
        )
        enc_a, enc_b = self._encoder(True), self._encoder(False)
        va = enc_a.init({"params": jax.random.key(0)}, x)
        # param trees identical: the cache changes no module structure
        vb = enc_b.init({"params": jax.random.key(0)}, x)
        assert all(
            bool((a == b).all())
            for a, b in zip(
                jax.tree_util.tree_leaves(va), jax.tree_util.tree_leaves(vb)
            )
        )
        out_a = enc_a.apply(va, x)
        out_b = enc_b.apply(va, x)
        assert bool((out_a == out_b).all())

        def loss(params, enc):
            return jnp.sum(enc.apply({"params": params}, x) ** 2)

        ga = jax.grad(loss)(va["params"], enc_a)
        gb = jax.grad(loss)(va["params"], enc_b)
        for a, b in zip(
            jax.tree_util.tree_leaves(ga), jax.tree_util.tree_leaves(gb)
        ):
            # atol floor: leaves whose true grad nearly cancels (k_proj/bias)
            # sit at ~1e-6 magnitude, where the dk1+dk2 reassociation IS the
            # signal — only relative structure above the noise floor matters
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b),
                rtol=2e-5, atol=max(1e-5, 1e-4 * float(jnp.abs(b).max())),
            )

    @pytest.mark.slow  # tier-1 budget (r10): reuse parity and remat are each
    # pinned tier-1 on their own in this class; this is their composition
    def test_remat_composes_with_reuse(self):
        """The kv cache crosses the nn.remat boundary as a pytree argument
        (no static bool — PerceiverLayer always returns (latent, kv))."""
        x = jnp.asarray(
            np.random.default_rng(4).integers(0, VOCAB, (2, MAX_LEN)), jnp.int32
        )
        enc, enc_r = self._encoder(True), self._encoder(True, remat=True)
        v = enc.init({"params": jax.random.key(0)}, x)
        assert bool((enc_r.apply(v, x) == enc.apply(v, x)).all())

        def loss(params, e):
            return jnp.sum(e.apply({"params": params}, x) ** 2)

        g, gr = jax.grad(loss)(v["params"], enc), jax.grad(loss)(v["params"], enc_r)
        # atol 2e-4: remat's recompute reassociates f32 reductions on this
        # compiler. Large-|g| leaves (~1e2) agree to rtol; the absolute floor
        # covers small-magnitude elements produced by heavy cancellation,
        # where the run-to-run reassociation noise is ~1e-4 regardless of the
        # element's own size (observed 9e-5 on a 0.05-scale element).
        for a, b in zip(jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-4)


def test_scaled_embed_matches_post_scale_bitwise():
    """_ScaledEmbed pre-scales the (vocab, C) table before the gather —
    bit-identical to gathering then multiplying by sqrt(C) (the reference
    formula, adapter.py:112-133) in both f32 and bf16 compute, while moving
    the multiply off the (B, L, C) stream (PERF.md r5)."""
    from perceiver_io_tpu.models.adapters import _ScaledEmbed

    for dtype in (jnp.float32, jnp.bfloat16):
        adapter = TextInputAdapter(
            vocab_size=VOCAB, max_seq_len=MAX_LEN, num_channels=C, dtype=dtype
        )
        x = jnp.asarray(
            np.random.default_rng(5).integers(0, VOCAB, (3, MAX_LEN)), jnp.int32
        )
        v = adapter.init({"params": jax.random.key(7)}, x)
        out = adapter.apply(v, x)
        table = v["params"]["text_embedding"]["embedding"].astype(dtype)
        pos = v["params"]["pos_encoding"][:MAX_LEN].astype(dtype)
        ref = jnp.take(table, x, axis=0) * jnp.asarray(C**0.5, dtype) + pos
        # same per-element multiply either side of the gather
        assert bool((out == ref).all()) or np.allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=0
        )
