"""Statistical tests for MLM text masking (reference model.py:240-293
semantics), plus the causal-mask family the Perceiver-AR decode path is
built on — dense-oracle parity for the causal + padding composition on BOTH
attention impls (the XLA masked einsum and the Pallas kernel's in-kernel
``causal_offset`` flag, forward AND gradients). The same composition also
rides the 8-device SPMD dry run: ``dryrun_multichip`` trains the AR preset
on the mesh under the 'auto' impl (where causal dispatch resolves, and
where the r18 shifted-labels partitioner miscompile lived), and
``tools/kernel_smoke.py`` owns the kernel-path causal geometries on real
hardware."""

import numpy as np
import jax
import jax.numpy as jnp

from perceiver_io_tpu.ops.masking import (
    IGNORE_LABEL,
    TextMasking,
    apply_text_masking,
    causal_mask,
    combine_attention_masks,
    shift_ar_labels,
)

VOCAB = 100
UNK, MASK = 1, 2
NUM_SPECIAL = 3


def make_batch(rng, b=64, l=256, pad_frac=0.2):
    x = rng.integers(NUM_SPECIAL, VOCAB, size=(b, l)).astype(np.int32)
    pad_mask = np.zeros((b, l), dtype=bool)
    n_pad = int(l * pad_frac)
    pad_mask[:, -n_pad:] = True
    x[pad_mask] = 0
    # sprinkle some UNKs
    unk_pos = rng.random((b, l)) < 0.01
    x[unk_pos & ~pad_mask] = UNK
    return jnp.asarray(x), jnp.asarray(pad_mask)


def run_masking(key, x, pad_mask, mask_p=0.15):
    return apply_text_masking(
        key, x, pad_mask,
        vocab_size=VOCAB, unk_token_id=UNK, mask_token_id=MASK,
        num_special_tokens=NUM_SPECIAL, mask_p=mask_p,
    )


def test_marginal_distribution(rng):
    x, pad = make_batch(rng, b=128, l=512)
    xm, labels = run_masking(jax.random.key(0), x, pad)
    x, pad, xm, labels = map(np.asarray, (x, pad, xm, labels))

    candidates = (x != UNK) & ~pad
    selected = labels != IGNORE_LABEL
    frac_selected = selected.sum() / candidates.sum()
    assert 0.13 < frac_selected < 0.17

    # of selected: ~80% MASK, ~10% random(!=orig, mostly), ~10% unchanged
    sel_masked = selected & (xm == MASK)
    sel_unchanged = selected & (xm == x)
    frac_masked = sel_masked.sum() / selected.sum()
    frac_unchanged = sel_unchanged.sum() / selected.sum()
    assert 0.76 < frac_masked < 0.84
    # unchanged includes the 10% kept + random draws that hit the original (~1/97)
    assert 0.07 < frac_unchanged < 0.14


def test_labels_preserve_originals(rng):
    x, pad = make_batch(rng)
    xm, labels = run_masking(jax.random.key(1), x, pad)
    x, labels = np.asarray(x), np.asarray(labels)
    selected = labels != IGNORE_LABEL
    np.testing.assert_array_equal(labels[selected], x[selected])


def test_specials_never_selected(rng):
    x, pad = make_batch(rng)
    xm, labels = run_masking(jax.random.key(2), x, pad)
    x, pad, xm, labels = map(np.asarray, (x, pad, xm, labels))
    specials = (x == UNK) | pad
    assert (labels[specials] == IGNORE_LABEL).all()
    # special positions are untouched in the corrupted input
    np.testing.assert_array_equal(xm[specials], x[specials])


def test_random_tokens_in_valid_range(rng):
    x, pad = make_batch(rng, b=256)
    xm, labels = run_masking(jax.random.key(3), x, pad)
    xm = np.asarray(xm)
    assert xm.min() >= 0 and xm.max() < VOCAB
    # corrupted tokens that are neither MASK nor original must be >= NUM_SPECIAL
    x, labels = np.asarray(x), np.asarray(labels)
    randomized = (labels != IGNORE_LABEL) & (xm != MASK) & (xm != x)
    if randomized.any():
        assert xm[randomized].min() >= NUM_SPECIAL


def test_deterministic_given_key(rng):
    x, pad = make_batch(rng)
    a = run_masking(jax.random.key(7), x, pad)
    b = run_masking(jax.random.key(7), x, pad)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    c = run_masking(jax.random.key(8), x, pad)
    assert not np.array_equal(np.asarray(a[0]), np.asarray(c[0]))


def test_mask_p_zero(rng):
    x, pad = make_batch(rng)
    xm, labels = run_masking(jax.random.key(0), x, pad, mask_p=0.0)
    np.testing.assert_array_equal(np.asarray(xm), np.asarray(x))
    assert (np.asarray(labels) == IGNORE_LABEL).all()


def test_jit_compatible(rng):
    x, pad = make_batch(rng, b=8, l=32)
    masking = TextMasking(
        vocab_size=VOCAB, unk_token_id=UNK, mask_token_id=MASK, num_special_tokens=NUM_SPECIAL
    )
    f = jax.jit(masking.__call__)
    xm, labels = f(jax.random.key(0), x, pad)
    assert xm.shape == x.shape and labels.shape == x.shape


# -- causal masks (the Perceiver-AR decode path) ------------------------------


def test_causal_mask_rule():
    m = np.asarray(causal_mask(3, 5, offset=1))
    # query row i (position offset+i) attends keys <= offset+i
    want = np.array([
        [False, False, True, True, True],
        [False, False, False, True, True],
        [False, False, False, False, True],
    ])
    np.testing.assert_array_equal(m, want)
    sq = np.asarray(causal_mask(4, 4))
    np.testing.assert_array_equal(sq, np.triu(np.ones((4, 4), bool), k=1))


def test_combine_attention_masks_composition(rng):
    pad = jnp.asarray(rng.random((2, 6)) < 0.3)
    cm = causal_mask(4, 6, offset=2)
    eff = np.asarray(combine_attention_masks(pad, cm, num_queries=4))
    assert eff.shape == (2, 4, 6)
    # OR composition: masked when padded OR acausal
    want = np.asarray(pad)[:, None, :] | np.asarray(cm)[None]
    np.testing.assert_array_equal(eff, want)
    assert combine_attention_masks(None, None) is None
    only_pad = np.asarray(combine_attention_masks(pad, None, num_queries=4))
    np.testing.assert_array_equal(only_pad, np.broadcast_to(
        np.asarray(pad)[:, None, :], (2, 4, 6)))


def test_causal_pad_parity_xla_vs_dense_oracle(rng):
    """MultiHeadAttention with causal_offset (XLA path) == the dense oracle
    applying combine_attention_masks by hand."""
    from perceiver_io_tpu.ops.attention import MultiHeadAttention

    b, t, s, e, h = 2, 5, 12, 16, 2
    off = s - t
    x_q = jnp.asarray(rng.normal(0, 1, (b, t, e)), jnp.float32)
    x_kv = jnp.asarray(rng.normal(0, 1, (b, s, e)), jnp.float32)
    pad = jnp.asarray(rng.random((b, s)) < 0.25)
    mha = MultiHeadAttention(num_q_channels=e, num_kv_channels=e,
                             num_heads=h, attn_impl="xla")
    params = mha.init(jax.random.key(0), x_q, x_kv)
    got = mha.apply(params, x_q, x_kv, pad_mask=pad, causal_offset=off)
    # oracle: the same call with the composed (B, T, S) mask passed as
    # attn_mask (and no pad/causal args) must be identical
    eff = combine_attention_masks(pad, causal_mask(t, s, off), num_queries=t)
    want = mha.apply(params, x_q, x_kv, attn_mask=eff)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_causal_pad_parity_pallas_kernel(rng):
    """The Pallas in-kernel causal flag (fwd + both backward kernels,
    interpret mode) matches the XLA masked-softmax oracle under a composed
    causal + padding mask — including a lane-unaligned S (the pad-to-block
    path) and a q_len=1 decode-step shape."""
    from perceiver_io_tpu.ops.pallas_attention import fused_attention

    for (b, t, s, h, d, off) in [(1, 5, 16, 2, 8, 11), (1, 1, 19, 2, 4, 18)]:
        q = jnp.asarray(rng.normal(0, 1, (b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(0, 1, (b, s, h, d)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (b, s, h, d)), jnp.float32)
        pad = jnp.asarray(rng.random((b, s)) < 0.2)

        def ref_loss(q, k, v):
            logits = jnp.einsum(
                "bthd,bshd->bhts", q * (d ** -0.5), k,
                precision=jax.lax.Precision.HIGHEST)
            eff = combine_attention_masks(
                pad, causal_mask(t, s, off), num_queries=t)
            logits = jnp.where(eff[:, None], jnp.finfo(jnp.float32).min,
                               logits)
            p = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("bhts,bshd->bthd", p, v,
                             precision=jax.lax.Precision.HIGHEST)
            return jnp.sum(out ** 2)

        def ker_loss(q, k, v):
            out = fused_attention(q, k, v, pad_mask=pad, causal_offset=off)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        # gradients through BOTH backward kernels on the first (blocked)
        # shape; the q_len=1 decode-step shape checks forward parity (its
        # backward never runs in serving — decode steps are inference)
        if t > 1:
            lr, gr = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
            lk, gk = jax.value_and_grad(ker_loss, argnums=(0, 1, 2))(q, k, v)
            for name, a, bb in zip(("dq", "dk", "dv"), gr, gk):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(bb), atol=2e-5,
                    err_msg=f"{name} mismatch at {(b, t, s, h, d, off)}")
        else:
            lr, lk = ref_loss(q, k, v), ker_loss(q, k, v)
        assert abs(float(lr) - float(lk)) < 1e-4 * max(1.0, abs(float(lr)))


def test_auto_dispatch_conservative_for_causal(rng):
    """attn_impl='auto' must resolve causal calls to XLA until the decode
    sweep lands (dispatch thresholds move only with measurements): the
    causal output is bit-identical between 'auto' and 'xla' even at shapes
    whose NON-causal auto dispatch would pick the kernel on TPU."""
    from perceiver_io_tpu.ops.attention import MultiHeadAttention

    b, t, s, e, h = 1, 4, 8, 8, 2
    x_q = jnp.asarray(rng.normal(0, 1, (b, t, e)), jnp.float32)
    x_kv = jnp.asarray(rng.normal(0, 1, (b, s, e)), jnp.float32)
    outs = {}
    for impl in ("auto", "xla"):
        mha = MultiHeadAttention(num_q_channels=e, num_kv_channels=e,
                                 num_heads=h, attn_impl=impl)
        params = mha.init(jax.random.key(0), x_q, x_kv)
        outs[impl] = np.asarray(mha.apply(
            params, x_q, x_kv, causal_offset=s - t))
    np.testing.assert_array_equal(outs["auto"], outs["xla"])


def test_shift_ar_labels(rng):
    ids = rng.integers(3, 60, (3, 12)).astype(np.int32)
    pad = np.zeros((3, 12), bool)
    pad[1, 9:] = True
    for o in (0, 4):
        got = np.asarray(shift_ar_labels(jnp.asarray(ids), jnp.asarray(pad), o))
        n = 12 - o
        want = np.full((3, n), IGNORE_LABEL, np.int32)
        for row in range(3):
            for i in range(n - 1):
                tgt = o + i + 1
                if not pad[row, tgt]:
                    want[row, i] = ids[row, tgt]
        np.testing.assert_array_equal(got, want)
    # no pad mask: only the final slot is ignored
    got = np.asarray(shift_ar_labels(jnp.asarray(ids), None, 2))
    assert (got[:, -1] == IGNORE_LABEL).all()
    assert (got[:, :-1] != IGNORE_LABEL).all()
