"""Statistical tests for MLM text masking (reference model.py:240-293 semantics)."""

import numpy as np
import jax
import jax.numpy as jnp

from perceiver_io_tpu.ops.masking import IGNORE_LABEL, TextMasking, apply_text_masking

VOCAB = 100
UNK, MASK = 1, 2
NUM_SPECIAL = 3


def make_batch(rng, b=64, l=256, pad_frac=0.2):
    x = rng.integers(NUM_SPECIAL, VOCAB, size=(b, l)).astype(np.int32)
    pad_mask = np.zeros((b, l), dtype=bool)
    n_pad = int(l * pad_frac)
    pad_mask[:, -n_pad:] = True
    x[pad_mask] = 0
    # sprinkle some UNKs
    unk_pos = rng.random((b, l)) < 0.01
    x[unk_pos & ~pad_mask] = UNK
    return jnp.asarray(x), jnp.asarray(pad_mask)


def run_masking(key, x, pad_mask, mask_p=0.15):
    return apply_text_masking(
        key, x, pad_mask,
        vocab_size=VOCAB, unk_token_id=UNK, mask_token_id=MASK,
        num_special_tokens=NUM_SPECIAL, mask_p=mask_p,
    )


def test_marginal_distribution(rng):
    x, pad = make_batch(rng, b=128, l=512)
    xm, labels = run_masking(jax.random.key(0), x, pad)
    x, pad, xm, labels = map(np.asarray, (x, pad, xm, labels))

    candidates = (x != UNK) & ~pad
    selected = labels != IGNORE_LABEL
    frac_selected = selected.sum() / candidates.sum()
    assert 0.13 < frac_selected < 0.17

    # of selected: ~80% MASK, ~10% random(!=orig, mostly), ~10% unchanged
    sel_masked = selected & (xm == MASK)
    sel_unchanged = selected & (xm == x)
    frac_masked = sel_masked.sum() / selected.sum()
    frac_unchanged = sel_unchanged.sum() / selected.sum()
    assert 0.76 < frac_masked < 0.84
    # unchanged includes the 10% kept + random draws that hit the original (~1/97)
    assert 0.07 < frac_unchanged < 0.14


def test_labels_preserve_originals(rng):
    x, pad = make_batch(rng)
    xm, labels = run_masking(jax.random.key(1), x, pad)
    x, labels = np.asarray(x), np.asarray(labels)
    selected = labels != IGNORE_LABEL
    np.testing.assert_array_equal(labels[selected], x[selected])


def test_specials_never_selected(rng):
    x, pad = make_batch(rng)
    xm, labels = run_masking(jax.random.key(2), x, pad)
    x, pad, xm, labels = map(np.asarray, (x, pad, xm, labels))
    specials = (x == UNK) | pad
    assert (labels[specials] == IGNORE_LABEL).all()
    # special positions are untouched in the corrupted input
    np.testing.assert_array_equal(xm[specials], x[specials])


def test_random_tokens_in_valid_range(rng):
    x, pad = make_batch(rng, b=256)
    xm, labels = run_masking(jax.random.key(3), x, pad)
    xm = np.asarray(xm)
    assert xm.min() >= 0 and xm.max() < VOCAB
    # corrupted tokens that are neither MASK nor original must be >= NUM_SPECIAL
    x, labels = np.asarray(x), np.asarray(labels)
    randomized = (labels != IGNORE_LABEL) & (xm != MASK) & (xm != x)
    if randomized.any():
        assert xm[randomized].min() >= NUM_SPECIAL


def test_deterministic_given_key(rng):
    x, pad = make_batch(rng)
    a = run_masking(jax.random.key(7), x, pad)
    b = run_masking(jax.random.key(7), x, pad)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    c = run_masking(jax.random.key(8), x, pad)
    assert not np.array_equal(np.asarray(a[0]), np.asarray(c[0]))


def test_mask_p_zero(rng):
    x, pad = make_batch(rng)
    xm, labels = run_masking(jax.random.key(0), x, pad, mask_p=0.0)
    np.testing.assert_array_equal(np.asarray(xm), np.asarray(x))
    assert (np.asarray(labels) == IGNORE_LABEL).all()


def test_jit_compatible(rng):
    x, pad = make_batch(rng, b=8, l=32)
    masking = TextMasking(
        vocab_size=VOCAB, unk_token_id=UNK, mask_token_id=MASK, num_special_tokens=NUM_SPECIAL
    )
    f = jax.jit(masking.__call__)
    xm, labels = f(jax.random.key(0), x, pad)
    assert xm.shape == x.shape and labels.shape == x.shape
