"""Regression sentinel: noise-floor-aware bench record comparison
(tools/bench_compare.py, ISSUE 12). The floors come from PERF.md's recorded
null-control numbers — device trace ±0.04%, CPU paired interleave ±1.5
points, host-clock cross-session ±2x — never re-derived at compare time."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.bench_compare import (  # noqa: E402
    classify,
    compare,
    flatten,
    load_record,
    summarize,
)


def _headline(value, device_ms=9.8, host_ms=9.9):
    return {
        "metric": "mlm_tokens_per_sec_per_chip", "value": value,
        "unit": "tokens/s/chip", "method": "device_trace",
        "device_ms_per_step": device_ms, "host_ms_per_step": host_ms,
    }


# -- classification + verdicts ------------------------------------------------


def test_synthetic_regression_improvement_within_noise_triple():
    """The acceptance triple: a −5% regression, a +5% improvement, and a
    +0.01% wiggle on the device-trace headline classify correctly against
    the ±0.04% floor."""
    base = _headline(3_300_000.0)
    cases = {
        "regressed": _headline(3_300_000.0 * 0.95),
        "improved": _headline(3_300_000.0 * 1.05),
        "within_noise": _headline(3_300_000.0 * 1.0001),
    }
    for expected, cand in cases.items():
        comp = compare(base, cand)
        by_key = {c["key"]: c for c in comp}
        assert by_key["value"]["verdict"] == expected, (expected, comp)
        assert summarize(comp)["verdict"] == expected
        assert "0.04%" in by_key["value"]["floor"]


def test_host_clock_metrics_get_the_brutal_cross_session_floor():
    """A 30% 'win' on a host-clock number is within the ±2x session swing
    and must read within_noise; only a >2x change clears the floor.
    Lower-is-better direction holds for latency keys."""
    base = {"calibrated_rps": 1000.0, "p99_ms": 10.0}
    small = compare(base, {"calibrated_rps": 1300.0, "p99_ms": 7.0})
    assert all(c["verdict"] == "within_noise" for c in small)
    big = compare(base, {"calibrated_rps": 2500.0, "p99_ms": 30.0})
    by_key = {c["key"]: c for c in big}
    assert by_key["calibrated_rps"]["verdict"] == "improved"
    assert by_key["p99_ms"]["verdict"] == "regressed"  # latency UP is bad


def test_paired_interleave_percent_floor_is_absolute_points():
    """overhead_pct compares on the ±1.5 absolute-point null-control floor
    (a relative floor on a ~2% number would be meaningless)."""
    base = {"trace": {"overhead_pct": 1.8}}
    assert compare(base, {"trace": {"overhead_pct": 2.9}})[0]["verdict"] \
        == "within_noise"
    worse = compare(base, {"trace": {"overhead_pct": 4.0}})[0]
    assert worse["verdict"] == "regressed"
    assert "1.5" in worse["floor"]
    assert compare(base, {"trace": {"overhead_pct": 0.5}})[0]["verdict"] \
        == "within_noise"  # a 1.3-point drop is still inside ±1.5
    assert compare(base, {"trace": {"overhead_pct": 0.1}})[0]["verdict"] \
        == "improved"      # a 1.7-point drop clears the floor


def test_headline_value_floor_depends_on_the_record_method():
    """'value' is device-trace-tight only when the record SAYS it was
    measured from the device trace; a host-clock headline gets the host
    floor."""
    mode, floor, direction, _ = classify("value", _headline(1.0))
    assert (mode, floor, direction) == ("frac", 0.0004, "higher")
    host = dict(_headline(1.0), method="host_clock")
    _, floor_host, _, _ = classify("value", host)
    assert floor_host == 1.0
    # unrecognized keys are not measurements → not classified
    assert classify("seed", _headline(1.0)) is None
    assert classify("sweep.0.submitted", {}) is None


def test_flatten_dot_paths_and_record_loading(tmp_path):
    rec = {"a": 1, "b": {"c": 2.5, "d": [3, {"e": 4}]},
           "skip": True, "s": "x"}
    assert flatten(rec) == {"a": 1.0, "b.c": 2.5, "b.d.0": 3.0,
                            "b.d.1.e": 4.0}
    # the driver's BENCH_rNN wrapper unwraps to its parsed record
    p = tmp_path / "wrapped.json"
    p.write_text(json.dumps({"n": 4, "tail": "...",
                             "parsed": _headline(2.0)}))
    assert load_record(str(p))["value"] == 2.0
    # a JSONL log compares by its newest parseable record
    p2 = tmp_path / "log.jsonl"
    p2.write_text('not json\n{"value": 1.0}\n{"value": 2.0}\n')
    assert load_record(str(p2))["value"] == 2.0


# -- the CLI contract ---------------------------------------------------------


def test_cli_one_json_line_and_fail_on_regress(tmp_path):
    base, cand = tmp_path / "base.json", tmp_path / "cand.json"
    base.write_text(json.dumps(_headline(3_300_000.0)))
    cand.write_text(json.dumps(_headline(3_000_000.0)))

    def run(*extra):
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "bench_compare.py"),
             str(base), str(cand), *extra],
            capture_output=True, text=True, cwd=ROOT, timeout=120)

    proc = run()
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout  # exactly ONE JSON line
    record = json.loads(lines[0])
    assert record["tool"] == "bench_compare"
    assert record["verdict"] == "regressed"
    assert record["candidates"][0]["summary"]["regressed"] >= 1
    # per-metric detail (incl. the floor provenance) rides stderr
    assert "PERF.md" in proc.stderr
    proc = run("--fail_on_regress")
    assert proc.returncode == 1
    assert json.loads(proc.stdout.strip())["ok"] is False


def test_no_comparable_metrics_cannot_pass_the_regression_gate(tmp_path):
    """A comparison that checked NOTHING (schema drift, a --dry record as
    baseline) must say so — and fail under --fail_on_regress instead of
    silently waving the candidate through."""
    assert summarize([]) == {
        "improved": 0, "regressed": 0, "within_noise": 0, "changed": 0,
        "verdict": "no_comparable_metrics",
    }
    base, cand = tmp_path / "base.json", tmp_path / "cand.json"
    base.write_text(json.dumps({"metric": "load_bench", "dry": True}))
    cand.write_text(json.dumps(_headline(3_300_000.0)))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_compare.py"),
         str(base), str(cand), "--fail_on_regress"],
        capture_output=True, text=True, cwd=ROOT, timeout=120)
    assert proc.returncode == 1, proc.stdout
    record = json.loads(proc.stdout.strip())
    assert record["verdict"] == "no_comparable_metrics"
    assert record["compared"] == 0 and record["ok"] is False
    assert "NO comparable metrics" in proc.stderr
    # the gate is per CANDIDATE: one record that compared fine must not
    # wave an unchecked sibling through
    good_base = tmp_path / "gbase.json"
    good_base.write_text(json.dumps(_headline(3_300_000.0)))
    drifted = tmp_path / "drifted.json"
    drifted.write_text(json.dumps({"renamed": 1.0}))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_compare.py"),
         str(good_base), str(good_base), str(drifted),
         "--fail_on_regress"],
        capture_output=True, text=True, cwd=ROOT, timeout=120)
    assert proc.returncode == 1, proc.stdout
    record = json.loads(proc.stdout.strip())
    assert record["compared"] > 0 and record["ok"] is False
    assert record["candidates"][1]["summary"]["verdict"] \
        == "no_comparable_metrics"
    # without the gate flag it reports honestly but exits 0
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_compare.py"),
         str(base), str(cand)],
        capture_output=True, text=True, cwd=ROOT, timeout=120)
    assert proc.returncode == 0
    assert json.loads(proc.stdout.strip())["verdict"] \
        == "no_comparable_metrics"
