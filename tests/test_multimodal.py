"""Multimodal audio/video adapters + Kinetics-style autoencoder (framework
extension; second proof the adapter contract generalizes beyond the
reference's text/image scope)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from perceiver_io_tpu.models.multimodal import (
    AudioInputAdapter,
    AudioOutputAdapter,
    MultimodalInputAdapter,
    MultimodalOutputAdapter,
    VideoInputAdapter,
    VideoOutputAdapter,
    build_multimodal_autoencoder,
    multimodal_autoencoding_loss,
)
from perceiver_io_tpu.models.adapters import ClassificationOutputAdapter
from perceiver_io_tpu.training import (
    TrainState,
    make_multimodal_steps,
)


def test_audio_input_adapter_shape(rng):
    adapter = AudioInputAdapter(
        num_samples=64, samples_per_patch=8, num_audio_channels=2,
        num_frequency_bands=4,
    )
    assert adapter.num_tokens == 8
    assert adapter.num_input_channels == 8 * 2 + (2 * 4 + 1)
    x = jnp.asarray(rng.normal(0, 1, (3, 64, 2)), jnp.float32)
    out = adapter.apply({}, x)
    assert out.shape == (3, 8, adapter.num_input_channels)
    # first token's sample channels are the first 8 samples interleaved by channel
    np.testing.assert_allclose(
        np.asarray(out[0, 0, :16]), np.asarray(x[0, :8]).reshape(-1), atol=1e-6
    )
    with pytest.raises(ValueError):
        adapter.apply({}, jnp.zeros((3, 65, 2)))
    with pytest.raises(ValueError):
        AudioInputAdapter(num_samples=65, samples_per_patch=8).num_tokens


def test_video_input_adapter_patchify(rng):
    adapter = VideoInputAdapter(
        video_shape=(4, 8, 8, 3), patch_shape=(2, 4, 4), num_frequency_bands=4
    )
    assert adapter.grid_shape == (2, 2, 2)
    assert adapter.num_tokens == 8
    assert adapter.num_patch_channels == 2 * 4 * 4 * 3
    x = jnp.asarray(rng.normal(0, 1, (2, 4, 8, 8, 3)), jnp.float32)
    out = adapter.apply({}, x)
    assert out.shape == (2, 8, adapter.num_input_channels)
    # token 0 = voxels [t 0:2, h 0:4, w 0:4] in (t, h, w, c) order
    expected = np.asarray(x[0, 0:2, 0:4, 0:4, :]).reshape(-1)
    np.testing.assert_allclose(
        np.asarray(out[0, 0, : adapter.num_patch_channels]), expected, atol=1e-6
    )
    with pytest.raises(ValueError):
        adapter.apply({}, jnp.zeros((2, 4, 8, 9, 3)))


def test_video_output_adapter_inverts_patchify(rng):
    """VideoOutputAdapter's un-patchify must be the exact inverse of
    VideoInputAdapter's patchify (identity head ⇒ reconstruction)."""
    video_shape, patch_shape = (4, 8, 8, 3), (2, 4, 4)
    in_adapter = VideoInputAdapter(
        video_shape=video_shape, patch_shape=patch_shape, num_frequency_bands=2
    )
    voxels = int(np.prod(patch_shape)) * video_shape[-1]
    out_adapter = VideoOutputAdapter(
        video_shape=video_shape, patch_shape=patch_shape, num_output_channels=voxels
    )
    x = jnp.asarray(rng.normal(0, 1, (2, *video_shape)), jnp.float32)
    tokens = in_adapter.apply({}, x)[..., :voxels]  # strip position encodings
    params = {
        "linear": {
            "kernel": jnp.eye(voxels, dtype=jnp.float32),
            "bias": jnp.zeros((voxels,), jnp.float32),
        }
    }
    recon = out_adapter.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(recon), np.asarray(x), atol=1e-6)


def test_multimodal_input_adapter_fuses_streams(rng):
    video = VideoInputAdapter(
        video_shape=(2, 4, 4, 1), patch_shape=(1, 2, 2), num_frequency_bands=2
    )
    audio = AudioInputAdapter(
        num_samples=32, samples_per_patch=4, num_frequency_bands=2
    )
    fused = MultimodalInputAdapter(
        adapters=(("video", video), ("audio", audio)), num_modality_channels=4
    )
    common = max(video.num_input_channels, audio.num_input_channels)
    assert fused.num_input_channels == common + 4
    assert fused.num_tokens == video.num_tokens + audio.num_tokens

    batch = {
        "video": jnp.asarray(rng.normal(0, 1, (2, 2, 4, 4, 1)), jnp.float32),
        "audio": jnp.asarray(rng.normal(0, 1, (2, 32, 1)), jnp.float32),
    }
    params = fused.init({"params": jax.random.key(0)}, batch)["params"]
    out = fused.apply({"params": params}, batch)
    assert out.shape == (2, fused.num_tokens, fused.num_input_channels)
    # modality embedding occupies the trailing channels of every token
    v_emb = np.asarray(out[0, 0, -4:])
    a_emb = np.asarray(out[0, video.num_tokens, -4:])
    np.testing.assert_allclose(np.asarray(out[0, 1, -4:]), v_emb, atol=1e-6)
    assert not np.allclose(v_emb, a_emb)


def test_multimodal_output_adapter_routes_spans(rng):
    audio = AudioOutputAdapter(
        num_samples=32, samples_per_patch=4, num_output_channels=16
    )
    label = ClassificationOutputAdapter(
        num_classes=5, num_outputs=1, num_output_channels=16
    )
    routed = MultimodalOutputAdapter(adapters=(("audio", audio), ("label", label)))
    assert routed.output_shape == (8 + 1, 16)

    x = jnp.asarray(rng.normal(0, 1, (2, 9, 16)), jnp.float32)
    params = routed.init({"params": jax.random.key(0)}, x)["params"]
    out = routed.apply({"params": params}, x)
    assert out["audio"].shape == (2, 32, 1)
    assert out["label"].shape == (2, 5)


def test_multimodal_output_adapter_rejects_mixed_widths():
    with pytest.raises(ValueError):
        MultimodalOutputAdapter(
            adapters=(
                ("a", AudioOutputAdapter(num_samples=8, samples_per_patch=4,
                                         num_output_channels=16)),
                ("b", ClassificationOutputAdapter(num_classes=3, num_outputs=1,
                                                  num_output_channels=8)),
            )
        ).output_shape


def _tiny_autoencoder():
    return build_multimodal_autoencoder(
        video_shape=(2, 8, 8, 1),
        num_audio_samples=64,
        samples_per_patch=8,
        num_classes=3,
        latent_shape=(8, 32),
        video_patch_shape=(1, 4, 4),
        num_self_attention_layers_per_block=1,
        num_self_attention_heads=2,
        num_modality_channels=4,
        video_frequency_bands=2,
        audio_frequency_bands=2,
    )


def test_autoencoder_forward_shapes(rng):
    model = _tiny_autoencoder()
    batch = {
        "video": jnp.asarray(rng.normal(0, 1, (2, 2, 8, 8, 1)), jnp.float32),
        "audio": jnp.asarray(rng.normal(0, 1, (2, 64, 1)), jnp.float32),
    }
    params = model.init({"params": jax.random.key(0)}, batch)["params"]
    out = model.apply({"params": params}, batch)
    assert out["video"].shape == (2, 2, 8, 8, 1)
    assert out["audio"].shape == (2, 64, 1)
    assert out["label"].shape == (2, 3)
    for v in out.values():
        assert np.isfinite(np.asarray(v)).all()


@pytest.mark.slow  # tier-1 budget (r10): multimodal forward/loss parity
# stays tier-1 (test_video_patch_loss_matches_pixel_loss, sharded variant
# in tests/test_sharding.py) and the CLI e2e in test_cli.py runs the loop
def test_autoencoder_learns(rng):
    model = _tiny_autoencoder()
    batch = {
        "video": jnp.asarray(rng.normal(0, 1, (4, 2, 8, 8, 1)), jnp.float32),
        "audio": jnp.asarray(rng.normal(0, 1, (4, 64, 1)), jnp.float32),
        "label": jnp.asarray([0, 1, 2, 0], jnp.int32),
    }
    params = model.init(
        {"params": jax.random.key(0)},
        {"video": batch["video"], "audio": batch["audio"]},
    )["params"]
    state = TrainState.create(params, optax.adam(1e-3), jax.random.key(1))
    train_step, eval_step = make_multimodal_steps(model)
    step = jax.jit(train_step)

    losses = []
    for _ in range(15):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert {"video_loss", "audio_loss", "label_loss", "video_psnr",
            "acc"} <= metrics.keys()
    # PSNR must be consistent with the video MSE it derives from
    np.testing.assert_allclose(
        float(metrics["video_psnr"]),
        -10 * np.log10(max(float(metrics["video_loss"]), 1e-10)), rtol=1e-4,
    )

    ev = eval_step(state, batch)
    assert np.isfinite(float(ev["loss"]))


def test_loss_weights():
    outputs = {
        "video": jnp.zeros((1, 1, 2, 2, 1)),
        "audio": jnp.zeros((1, 4, 1)),
        "label": jnp.asarray([[10.0, 0.0]]),
    }
    batch = {
        "video": jnp.ones((1, 1, 2, 2, 1)),
        "audio": jnp.ones((1, 4, 1)) * 2,
        "label": jnp.asarray([0], jnp.int32),
    }
    loss, metrics = multimodal_autoencoding_loss(
        outputs, batch, video_weight=2.0, audio_weight=0.5, label_weight=1.0
    )
    expected = 2.0 * 1.0 + 0.5 * 4.0 + float(metrics["label_loss"])
    np.testing.assert_allclose(float(loss), expected, rtol=1e-5)
    assert float(metrics["acc"]) == 1.0


@pytest.mark.slow  # tier-1 budget (r21): multimodal loss plumbing stays
# tier-1 via the autoencoder tests here and tests/test_sharding.py::
# test_multimodal_autoencoder_sharded; the patch==pixel equivalence sweep
# runs in the full tier
def test_video_patch_loss_matches_pixel_loss():
    """video_patch_loss=True computes the SAME reconstruction loss (to fp
    reassociation) without the un-patchify transpose pair: the adapter keeps
    the head output in patch space and the loss patchifies the target with
    the exact inverse permutation. Params and gradients are unchanged
    (modulo reassociation); a checkpoint moves freely between the modes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.models.multimodal import (
        build_multimodal_autoencoder,
        patchify_video,
    )
    from perceiver_io_tpu.training.steps import make_multimodal_steps
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_optimizer,
    )

    kwargs = dict(
        video_shape=(4, 8, 8, 3), num_audio_samples=64, samples_per_patch=8,
        num_classes=5, latent_shape=(8, 16), video_patch_shape=(2, 4, 4),
        num_layers=1, num_self_attention_layers_per_block=1,
        num_self_attention_heads=2, video_frequency_bands=2,
        audio_frequency_bands=2, dtype=jnp.float32,
    )
    pixel = build_multimodal_autoencoder(**kwargs)
    patch = build_multimodal_autoencoder(video_patch_loss=True, **kwargs)

    rng = np.random.default_rng(0)
    batch = {
        "video": jnp.asarray(rng.normal(0, 1, (2, 4, 8, 8, 3)), jnp.float32),
        "audio": jnp.asarray(rng.normal(0, 1, (2, 64, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 5, 2).astype(np.int32)),
    }
    inputs = {"video": batch["video"], "audio": batch["audio"]}
    v = pixel.init({"params": jax.random.key(0)}, inputs)
    # identical param trees: as_patches only skips the output relayout
    v2 = patch.init({"params": jax.random.key(0)}, inputs)
    for a, b in zip(jax.tree_util.tree_leaves(v), jax.tree_util.tree_leaves(v2)):
        assert bool((a == b).all())

    # the adapter pair is an exact inverse: unpatchified(pred_patches) == pred
    out_pix = pixel.apply(v, inputs, deterministic=True)
    out_pat = patch.apply(v, inputs, deterministic=True)
    grid, pshape = (2, 2, 2), (2, 4, 4)
    assert bool(
        (patchify_video(out_pix["video"], grid, pshape) == out_pat["video"]).all()
    )

    # loss parity through make_multimodal_steps (reads geometry off the model)
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    losses = {}
    for name, model in (("pixel", pixel), ("patch", patch)):
        train_step, eval_step = make_multimodal_steps(model)
        state = TrainState.create(v["params"], tx, jax.random.key(2))
        _, metrics = jax.jit(train_step)(state, batch)
        losses[name] = {k: float(val) for k, val in metrics.items()
                        if k.startswith(("loss", "video", "audio"))}
    for k in losses["pixel"]:
        np.testing.assert_allclose(
            losses["pixel"][k], losses["patch"][k], rtol=1e-5,
            err_msg=f"metric {k} diverged between pixel and patch loss",
        )
