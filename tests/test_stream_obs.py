"""Token-level streaming observability (r21): the TTFT/ITL/goodput surface.

The reconciliation spine: the engine-side instruments (decode_ttft_seconds /
decode_itl_seconds histograms, decode_stream spans, decode_tokens_total
goodput counters) must agree with what a CALLER measures from the streamed
frames — within 5% at p50 for the latency pair, exactly for the token
accounting. Around it: the scheduler flight recorder's kill drill (a stream
dying mid-flight lands as an eviction row with its cause attributed, >= 95%
of idle slot-rounds attributed overall), the stream-shaped SLO (TTFT/ITL
burn rates, health degradation), and the control wiring (autoscale pressure
from fleet_replica_stream_burn, alert-rule resolvability over the fleet
scrape).
"""

import importlib.util
import json
import os
import threading
import time

import jax
import numpy as np
import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.inference.batching import ContinuousBatcher
from perceiver_io_tpu.inference.generate import ARGenerator, SamplingConfig
from perceiver_io_tpu.models.presets import tiny_ar
from perceiver_io_tpu.serving.autoscale import Autoscaler, AutoscalePolicy

VOCAB = 503


@pytest.fixture(scope="module")
def tiny():
    model = tiny_ar()
    ids = np.zeros((1, 64), np.int32)
    params = model.init({"params": jax.random.key(0)}, ids, ids == 0)[
        "params"]
    return model, params


def _decode_flight_tool():
    """Import tools/decode_flight.py (not a package) — the kill drill must
    flow through the SAME offline analysis a real crash artifact gets."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "decode_flight_tool", os.path.join(root, "tools", "decode_flight.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- TTFT/ITL reconciliation: engine instruments vs caller ground truth -------


def test_ttft_itl_histograms_reconcile_with_callback_ground_truth(tiny, rng):
    """The engine's decode_ttft/itl histograms must reconcile with the
    caller-clock ground truth stamped from the on_chunk frames — within 5%
    at p50 (the ISSUE's acceptance bar). Anything looser means the stamps
    sit on the wrong side of a dispatch."""
    model, params = tiny
    reg = obs.MetricsRegistry()
    gen = ARGenerator(model, params, max_seq_len=64, chunk=4,
                      name="so-recon", registry=reg)
    sampling = SamplingConfig(temperature=0.8, top_k=16, seed=3)
    truth_ttft, truth_itl = [], []
    for i in range(12):
        plen = int(rng.integers(2, 10))
        prefix = [int(t) for t in rng.integers(3, VOCAB, plen)]
        frames = {"t_first": None, "t_prev": None}
        t0 = time.monotonic()

        def on_chunk(tokens, info, _f=frames):
            now = time.monotonic()
            if not tokens:
                return
            if _f["t_first"] is None:
                _f["t_first"] = now
            else:
                # per-chunk, same unit the engine observes: the gap to the
                # previous chunk divided by this chunk's tokens
                truth_itl.append((now - _f["t_prev"]) / len(tokens))
            _f["t_prev"] = now

        toks, _ = gen.generate(prefix, 12, sampling, on_chunk=on_chunk)
        assert toks and frames["t_first"] is not None
        truth_ttft.append(frames["t_first"] - t0)

    med = lambda v: sorted(v)[len(v) // 2]
    h_ttft = gen._m_ttft_s.percentiles((0.5,))[0.5]
    h_itl = gen._m_itl_s.percentiles((0.5,))[0.5]
    assert gen._m_ttft_s.count == 12
    assert abs(h_ttft - med(truth_ttft)) <= 0.05 * med(truth_ttft), (
        h_ttft, med(truth_ttft))
    # the ITL histogram observes per-chunk (gap / tokens-in-chunk); the
    # callback stamps the identical events from the caller's side of the
    # dispatch, so the medians must sit in the same 5% band
    assert gen._m_itl_s.count == len(truth_itl)
    assert abs(h_itl - med(truth_itl)) <= 0.05 * med(truth_itl), (
        h_itl, med(truth_itl))
    # goodput accounting: every produced token was delivered
    ts = gen.token_stats()
    assert ts["tokens"]["generated"] == ts["tokens"]["delivered"] > 0
    assert ts["goodput"] == 1.0
    # exemplar link: the TTFT histogram carries no exemplars here (no
    # trace context was minted) — the traced test below pins the link


def test_decode_stream_spans_reconcile_with_histograms(tiny, rng, tmp_path):
    """A traced stream emits ONE decode_stream span whose duration covers
    its decode_chunk children, the chunk count matches the dispatch math,
    and the TTFT histogram's exemplar links back to the same trace — the
    p99→trace join tools/trace_assemble.py resolves."""
    model, params = tiny
    reg = obs.MetricsRegistry()
    gen = ARGenerator(model, params, max_seq_len=64, chunk=4,
                      name="so-span", registry=reg)
    sampling = SamplingConfig(temperature=0.8, top_k=16, seed=5)
    gen.generate([5, 7, 9], 4, sampling)  # warm the program family untraced
    path = str(tmp_path / "events.jsonl")
    ctx = obs.TraceContext.mint()
    try:
        obs.configure_event_log(path)
        prefix = [int(t) for t in rng.integers(3, VOCAB, 6)]
        toks, _ = gen.generate(prefix, 12, sampling, trace=ctx)
    finally:
        obs.configure_event_log(None)
    assert len(toks) == 12
    spans = [json.loads(l) for l in open(path) if l.strip()]
    spans = [s for s in spans if s.get("event") == "span"]
    streams = [s for s in spans if s["name"] == "decode_stream"]
    chunks = [s for s in spans if s["name"] == "decode_chunk"]
    assert len(streams) == 1
    st = streams[0]
    assert st["trace"] == ctx.trace_id and st["tokens"] == 12 and st["ok"]
    # 12 tokens at chunk 4: at least three dispatches (an episode boundary
    # splits one), their step counts summing to the tokens delivered, all
    # children of the stream's trace, each inside the stream span's window
    assert len(chunks) >= 3
    assert sum(c["steps"] for c in chunks) == 12
    for c in chunks:
        assert c["trace"] == ctx.trace_id
        assert c["mono_start"] >= st["mono_start"] - 1e-6
        assert (c["mono_start"] + c["dur_s"]
                <= st["mono_start"] + st["dur_s"] + 1e-6)
    # span/histogram reconciliation: the stream span covers the TTFT the
    # histogram recorded for this (sole traced) stream, and that
    # observation's exemplar IS this trace
    ex = gen._m_ttft_s.exemplars()
    assert any(e["trace"] == ctx.trace_id for e in ex), ex
    ttft = [e["value"] for e in ex if e["trace"] == ctx.trace_id][0]
    assert ttft <= st["dur_s"] + 1e-6
    assert sum(c["dur_s"] for c in chunks) <= st["dur_s"] + 1e-6


# -- the batched engine: queue wait, goodput, flight attribution --------------


def test_batched_queue_wait_and_flight_attribution(tiny, rng):
    """Oversubscribed admission (6 streams on 2 slots) records a nonzero
    queue wait for the streams that waited, TTFT for every stream, and the
    flight recorder attributes >= 95% of idle slot-rounds (the acceptance
    bar — structurally 100%: the cause tree is exhaustive)."""
    model, params = tiny
    reg = obs.MetricsRegistry()
    bat = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                            slots=2, max_slots=2, name="so-arena",
                            registry=reg)
    try:
        sampling = SamplingConfig(temperature=0.8, top_k=16, seed=7)
        got = [None] * 6

        def one(i):
            prefix = [int(t) for t in rng.integers(3, VOCAB, 4)]
            got[i], _ = bat.generate(prefix, 6, sampling)

        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(g for g in got)
        assert bat._m_ttft_s.count == 6
        assert bat._m_queue_wait_s.count == 6
        # with 6 streams on 2 slots, somebody waited measurably longer
        # than the winners who bound a slot immediately
        waits = bat._m_queue_wait_s.values()
        assert max(waits) > min(waits)
        stats = bat.stats()
        assert stats["goodput"] == 1.0
        assert stats["tokens"]["delivered"] == sum(len(g) for g in got)
        flight = stats["flight"]
        assert flight["rounds"] > 0
        assert flight["attribution_frac"] >= 0.95
    finally:
        bat.close()


def test_flight_recorder_kill_drill_finds_eviction_and_cause(tiny, rng,
                                                             tmp_path):
    """The post-mortem drill: close the engine under a live stream. The
    dump (the SIGTERM/watchdog artifact) must carry the eviction row with
    its reason, the goodput counters must book the dead stream's tokens as
    wasted, and the offline analyzer (tools/decode_flight.py — the same
    path a real crash artifact takes) must find the eviction AND attribute
    >= 95% of idle slot-rounds."""
    model, params = tiny
    reg = obs.MetricsRegistry()
    bat = ContinuousBatcher(model, params, max_seq_len=64, chunk=4,
                            slots=2, max_slots=2, name="so-drill",
                            registry=reg)
    path = str(tmp_path / "flight.jsonl")
    errs = []

    def doomed():
        try:
            bat.generate([int(t) for t in rng.integers(3, VOCAB, 4)],
                         400, SamplingConfig(temperature=0.8, top_k=16))
        except Exception as e:
            errs.append(type(e).__name__)

    try:
        obs.configure_event_log(path)
        t = threading.Thread(target=doomed, daemon=True)
        t.start()
        time.sleep(0.4)  # let it bind a slot and decode a few chunks
        bat.close()
        t.join(timeout=10)
        bat.flight.dump("test_drill")
    finally:
        obs.configure_event_log(None)
    # the stream observed its own death ...
    assert errs == ["RuntimeError"]
    # ... the goodput ledger booked its tokens as wasted, not delivered ...
    ts = bat.token_stats()
    wasted = sum(v for o, v in ts["tokens"].items()
                 if o.startswith("wasted_"))
    assert wasted > 0 and ts["goodput"] is not None and ts["goodput"] < 1.0
    # ... the dump event carries the eviction row with its cause ...
    dumps = [json.loads(l) for l in open(path) if l.strip()]
    dumps = [r for r in dumps if r.get("event") == "decode_flight_dump"]
    assert any(r.get("reason") == "test_drill" for r in dumps)
    # ... and the offline analyzer (replaying batch + dump rows, deduped)
    # attributes the idleness and finds the eviction
    rec = _decode_flight_tool().analyze_events(path)
    assert rec["evicts"].get("draining", 0) >= 1, rec["evicts"]
    assert rec["attribution_frac"] >= 0.95, rec
    assert rec["dump_reasons"] == ["test_drill"]


# -- stream-shaped SLO: burn, health, control wiring --------------------------


def test_slo_stream_burn_and_health_degradation():
    """TTFT/ITL each burn independently against the shared availability
    budget; an ok=False stream is bad on EVERY configured signal; a
    burning stream signal degrades health exactly like a burning request
    signal (after min_samples)."""
    slo = obs.SLO(latency_target_s=1.0, availability_target=0.99,
                  name="so-slo", burn_alert=2.0, min_samples=10,
                  ttft_target_s=0.05, itl_target_s=0.01)
    assert slo.stream_signals == {"ttft": 0.05, "itl": 0.01}
    reg = obs.MetricsRegistry()
    tr = obs.SLOTracker(slo, registry=reg)
    try:
        for _ in range(9):
            tr.record_stream(ttft_s=0.01, itl_s=0.005)
        assert tr.stream_burn_rate() == 0.0
        # one TTFT breach in 10: bad fraction 0.1 over budget 0.01 -> 10
        tr.record_stream(ttft_s=0.5, itl_s=0.005)
        assert tr.stream_burn_rate("ttft") == pytest.approx(10.0)
        assert tr.stream_burn_rate("itl") == 0.0
        assert tr.stream_burn_rate() == pytest.approx(10.0)  # max across
        # an unmeasured signal on a good stream is SKIPPED, not bad
        tr.record_stream(ttft_s=0.01, itl_s=None)
        assert tr.stream_sample_count("ttft") == 11
        assert tr.stream_sample_count("itl") == 10
        # a killed stream is bad on every signal, measured or not
        tr.record_stream(ok=False)
        assert tr.stream_burn_rate("itl") > 0.0
        # health: ttft burn 2/12 / 0.01 ≈ 16.7 > alert 2.0 with >= 10
        # samples -> the process degrades
        name, ok, detail = tr.health_status()
        assert not ok
        assert detail["stream_ttft_burn_rate"] > 2.0
        assert detail["stream_ttft_samples"] == 12
    finally:
        tr.close()


def test_slo_stream_validation_and_request_only_noop():
    with pytest.raises(ValueError):
        obs.SLO(latency_target_s=1.0, ttft_target_s=0.0)
    slo = obs.SLO(latency_target_s=1.0, burn_alert=None)
    assert slo.stream_signals == {}
    tr = obs.SLOTracker(slo, registry=obs.MetricsRegistry())
    tr.record_stream(ttft_s=99.0, itl_s=99.0)  # no-op, never raises
    assert tr.stream_burn_rate() == 0.0
    tr.close()


class _FakeRouter:
    """The autoscaler's router surface over a hand-fed series store."""

    def __init__(self):
        self.series = obs.SeriesStore()
        self.name = "so-fake"
        self._replicas = ["r0", "r1"]
        self.drained = []

    def replicas(self):
        return list(self._replicas)

    def drain_replica(self, name, timeout_s=None, detach=False):
        self.drained.append(name)
        if detach:
            self._replicas.remove(name)
        return True

    def add_replica(self, client):
        self._replicas.append(client.name)

    def latency_exemplars(self, n=4):
        return []

    def statuses(self):
        return {n: {"state": "serving", "router_inflight": 0,
                    "queue_depth": 0} for n in self._replicas}


class _FakePool:
    def __init__(self):
        self.spawned = 0
        self.retired = []

    def spawn(self):
        self.spawned += 1

        class _C:
            name = f"s{self.spawned}"

        return _C()

    def retire(self, name):
        self.retired.append(name)


def _feed_stream_burn(router, value, t0, now, step=0.5):
    for name in router.replicas():
        key = obs.series_key("fleet_replica_stream_burn",
                             {"fleet": router.name, "replica": name})
        t = t0
        while t <= now:
            router.series.record(key, value, "gauge", t=t, mono=t)
            t += step


def test_autoscale_stream_burn_pressure_and_hysteresis():
    """Token-latency burn is scale-up pressure even with zero demand (the
    failure mode request-rate scaling misses: few streams, each stalling),
    and the down path is blocked while stream burn sits above the down
    threshold — the hysteresis band validated at construction."""
    with pytest.raises(ValueError):
        AutoscalePolicy(rps_per_replica=100.0, up_stream_burn=1.0,
                        down_stream_burn=2.0)
    policy = AutoscalePolicy(
        rps_per_replica=100.0, min_replicas=1, max_replicas=4,
        window_s=5.0, hold_up_s=1.0, hold_down_s=1.0,
        cooldown_up_s=1.0, cooldown_down_s=1.0,
        up_stream_burn=1.0, down_stream_burn=0.5)
    router, pool = _FakeRouter(), _FakePool()
    auto = Autoscaler(router, pool, policy, registry=obs.MetricsRegistry())
    try:
        t0 = 1000.0
        _feed_stream_burn(router, 50.0, t0 - 6.0, t0 + 3.0)
        sig = auto.signals(now=t0)
        assert sig["stream_burn"] == pytest.approx(50.0)
        assert auto.tick(now=t0) is None  # hold starts
        dec = auto.tick(now=t0 + 1.1)
        assert dec is not None and dec["action"] == "scale_up"
        assert dec["stream_burn"] == pytest.approx(50.0)
        assert pool.spawned >= 1
        # burn falls into the hysteresis band (0.5 < 0.8 < 1.0): no more
        # up pressure, but down stays BLOCKED
        router2, pool2 = _FakeRouter(), _FakePool()
        auto2 = Autoscaler(router2, pool2, policy,
                           registry=obs.MetricsRegistry())
        try:
            _feed_stream_burn(router2, 0.8, t0 - 6.0, t0 + 6.0)
            for t in (t0, t0 + 1.1, t0 + 2.5, t0 + 4.0):
                assert auto2.tick(now=t) is None
            assert pool2.spawned == 0 and router2.drained == []
            # burn clears below down_stream_burn: the down path opens
            router3, pool3 = _FakeRouter(), _FakePool()
            auto3 = Autoscaler(router3, pool3, policy,
                               registry=obs.MetricsRegistry())
            try:
                _feed_stream_burn(router3, 0.1, t0 - 6.0, t0 + 6.0)
                assert auto3.tick(now=t0) is None  # hold starts
                dec3 = auto3.tick(now=t0 + 1.1)
                assert dec3 is not None and dec3["action"] == "scale_down"
            finally:
                auto3.close()
        finally:
            auto2.close()
    finally:
        auto.close()


def test_fleet_stream_burn_alert_rule_fires_over_the_scrape_key():
    """An AlertRule on the bare fleet_replica_stream_burn name resolves
    the per-replica labeled series (the fleet scraper's registration) and
    fires on the worst replica — the wiring a pager rides."""
    store = obs.SeriesStore()
    keys = {r: obs.series_key("fleet_replica_stream_burn",
                              {"fleet": "f", "replica": r})
            for r in ("r0", "r1")}
    t = 100.0
    for i in range(8):
        store.record(keys["r0"], 0.2, "gauge", t=t + i, mono=t + i)
        store.record(keys["r1"], 30.0 if i >= 4 else 0.2, "gauge",
                     t=t + i, mono=t + i)
    rule = obs.AlertRule(name="stream_burn_high",
                         metric="fleet_replica_stream_burn",
                         threshold=2.0, agg="max", window_s=4.0,
                         severity="page")
    eng = obs.AlertEngine(store, [rule], name="so-alerts")
    try:
        eng.evaluate(now=t + 8)
        st = eng.stats()
        assert st["fired"] >= 1
        firing = [k for k in st["firing"].get("stream_burn_high", [])]
        assert any("r1" in k for k in firing), st["firing"]
        assert not any("r0" in k for k in firing), st["firing"]
    finally:
        eng.close()


def test_replica_scrape_carries_stream_burn(tiny, rng):
    """End to end through the serving layer: a replica built with a
    stream SLO classifies its streams from the caller-visible frame clock
    and scrapes stream_burn once min_samples streams landed — the number
    the router's DEGRADED check and the fleet store consume."""
    from perceiver_io_tpu.inference.engine import ServingEngine
    from perceiver_io_tpu.serving.replica import ReplicaApp

    model, params = tiny
    reg = obs.MetricsRegistry()
    gen = ARGenerator(model, params, max_seq_len=64, chunk=4,
                      name="so-rep-gen", registry=reg)

    def apply_fn(p, token_ids, pad_mask):
        return model.apply({"params": p}, token_ids, pad_mask)

    eng = ServingEngine(apply_fn, params, name="so-rep-inf", max_batch=2,
                        registry=reg)
    # ttft_target_s deliberately impossible (0 is rejected; 1ns is not):
    # every stream breaches, so burn saturates once min_samples land
    slo = obs.SLO(latency_target_s=1.0, availability_target=0.5,
                  name="so-rep", burn_alert=None, min_samples=4,
                  ttft_target_s=1e-9)
    app = ReplicaApp({"infer": eng}, params, name="so-rep",
                     assume_ready=True, generator=gen, stream_slo=slo)
    try:
        assert app.stream_slo_tracker is not None
        # below min_samples the scrape stays quiet (a fresh process must
        # not degrade on its first stream)
        app.generate([3, 5, 7], max_new=4, seed=1)
        assert app.status()["stream_burn"] == 0.0
        for i in range(4):
            prefix = [int(t) for t in rng.integers(3, VOCAB, 4)]
            app.generate(prefix, max_new=4, seed=i)
        # 5 streams, all breaching the 1ns TTFT: bad fraction 1.0 over
        # budget 0.5 -> burn 2.0
        assert app.status()["stream_burn"] == pytest.approx(2.0)
    finally:
        app.close()
