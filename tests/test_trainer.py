"""Trainer loop: logging, checkpointing, eval averaging, mesh mode."""

import os
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import perceiver_io_tpu as pit
from perceiver_io_tpu.data.pipeline import DataLoader
from perceiver_io_tpu.parallel.mesh import make_mesh
from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    Trainer,
    TrainerConfig,
    make_classifier_steps,
    make_optimizer,
    read_metrics,
    restore_train_state,
)


class _Blobs:
    """Tiny deterministic image dataset (class-dependent mean)."""

    def __init__(self, n, seed=0):
        rng = np.random.default_rng(seed)
        self.labels = rng.integers(0, 2, size=n).astype(np.int32)
        base = self.labels.astype(np.float32)[:, None, None] * 0.8 - 0.4
        self.images = base[..., None] + rng.normal(0, 0.1, (n, 8, 8, 1)).astype(
            np.float32
        )

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return self.images[i], int(self.labels[i])


def _collate(batch):
    return {
        "image": np.stack([x for x, _ in batch]),
        "label": np.asarray([y for _, y in batch], dtype=np.int32),
    }


def _make_parts(tmp_path, mesh=None):
    model = pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.ImageInputAdapter(image_shape=(8, 8, 1),
                                               num_frequency_bands=4),
            latent_shape=(4, 16),
            num_layers=1,
            num_self_attention_layers_per_block=1,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=2, num_output_channels=16
            ),
            latent_shape=(4, 16),
        ),
    )
    example = _collate([_Blobs(2)[i] for i in range(2)])
    params = model.init({"params": jax.random.key(0)}, example["image"])["params"]
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(params, tx, jax.random.key(1))
    train_step, eval_step = make_classifier_steps(model, schedule)
    config = TrainerConfig(
        max_epochs=2,
        log_every_n_steps=2,
        logdir=str(tmp_path / "logs"),
        experiment="t",
        use_tensorboard=False,
        compute_mfu=False,
    )
    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        config,
        example_batch=example,
        mesh=mesh,
    )
    loaders = (
        DataLoader(_Blobs(64), 16, _collate, shuffle=True, prefetch=0),
        DataLoader(_Blobs(32, seed=1), 16, _collate, prefetch=0),
    )
    return trainer, loaders


def test_fit_logs_and_checkpoints(tmp_path):
    trainer, (train_loader, val_loader) = _make_parts(tmp_path)
    with trainer:
        state = trainer.fit(train_loader, val_loader)
        assert int(jax.device_get(state.step)) == 8  # 2 epochs × 4 batches
        rows = read_metrics(trainer.run_dir)
        train_rows = [r for r in rows if "train_loss" in r]
        val_rows = [r for r in rows if "val_loss" in r]
        assert len(train_rows) == 4  # every 2 steps
        assert len(val_rows) == 2  # per epoch
        assert all("lr" in r and "examples_per_sec" in r for r in train_rows)
        best = trainer.checkpoints.best_step
        losses = {r["step"]: r["val_loss"] for r in val_rows}
        assert best == min(losses, key=losses.get)


def test_fit_max_steps_and_resume(tmp_path):
    trainer, (train_loader, val_loader) = _make_parts(tmp_path)
    cfg = TrainerConfig(
        max_steps=3,
        log_every_n_steps=1,
        logdir=str(tmp_path / "logs2"),
        experiment="t",
        use_tensorboard=False,
        compute_mfu=False,
    )
    trainer2 = Trainer(
        trainer._raw_train_step,
        trainer._eval_step and (lambda s, b, k: {"loss": s.step * 0.0}),
        trainer.state,
        cfg,
        example_batch=trainer._example_batch,
    )
    with trainer2:
        state = trainer2.fit(train_loader, val_loader)
    assert int(jax.device_get(state.step)) == 3
    # resume from the checkpoint directory
    like = trainer2.state
    restored = restore_train_state(
        os.path.join(trainer2.run_dir, "checkpoints"), like
    )
    assert int(jax.device_get(restored.step)) == 3


def test_fit_sharded_mesh(tmp_path):
    mesh = make_mesh(dp=4, tp=2)
    trainer, (train_loader, val_loader) = _make_parts(tmp_path, mesh=mesh)
    with trainer:
        state = trainer.fit(train_loader, val_loader)
    assert int(jax.device_get(state.step)) == 8
    rows = read_metrics(trainer.run_dir)
    assert any("val_loss" in r for r in rows)


def test_eval_weighted_average(tmp_path):
    trainer, _ = _make_parts(tmp_path)
    # two batches of different size: mean must be weighted by batch size
    loader = [
        _collate([_Blobs(8)[i] for i in range(8)]),
        _collate([_Blobs(4, seed=2)[i] for i in range(4)]),
    ]
    with trainer:
        out = trainer._run_eval(loader)
    assert set(out) == {"val_loss", "val_acc"}

    per_batch = [trainer._eval_step(trainer.state, b, jax.random.key(0)) for b in loader]
    expected = (float(per_batch[0]["loss"]) * 8 + float(per_batch[1]["loss"]) * 4) / 12
    assert out["val_loss"] == pytest.approx(expected, rel=1e-5)


def test_eval_every_n_steps_checkpoints_tail(tmp_path):
    """A run ending between eval intervals must still validate + checkpoint."""
    trainer, (train_loader, val_loader) = _make_parts(tmp_path)
    cfg = TrainerConfig(
        max_steps=5,
        eval_every_n_steps=3,
        log_every_n_steps=1,
        logdir=str(tmp_path / "logs3"),
        experiment="t",
        use_tensorboard=False,
        compute_mfu=False,
    )
    trainer3 = Trainer(
        trainer._raw_train_step,
        trainer._eval_step and (lambda s, b, k: trainer._eval_step(s, b, k)),
        trainer.state,
        cfg,
        example_batch=trainer._example_batch,
    )
    with trainer3:
        trainer3.fit(train_loader, val_loader)
        steps = trainer3.checkpoints.all_steps
    rows = read_metrics(trainer3.run_dir)
    val_steps = sorted({r["step"] for r in rows if "val_loss" in r})
    assert val_steps == [3, 5]  # interval hit + final tail
    assert 5 in steps or 3 in steps  # best-of kept one of them


def test_config_requires_limit():
    with pytest.raises(ValueError):
        TrainerConfig()


def test_resume_fast_forwards_data_stream(tmp_path):
    """A restored trainer continues with exactly the batches the
    uninterrupted run would have seen (loader epoch + offset fast-forward)."""
    from perceiver_io_tpu.data.pipeline import DataLoader

    class Records(list):
        pass

    def make_loader(log):
        class Ds:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return i

        def collate(items):
            log.append(tuple(items))
            return {"x": np.asarray(items, np.float32)[:, None]}

        return DataLoader(Ds(), batch_size=2, collate=collate,
                          shuffle=True, seed=3, prefetch=0)

    def make_trainer(logdir):
        def train_step(state, batch):
            new_params = jax.tree.map(lambda p: p - 0.0, state.params)
            return state.replace(step=state.step + 1, params=new_params), {
                "loss": jnp.sum(batch["x"]) * 0.0
            }

        tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-2))
        state = TrainState.create({"w": jnp.zeros((1,))}, tx, jax.random.key(0))
        cfg = TrainerConfig(max_steps=6, log_every_n_steps=100,
                            logdir=logdir, experiment="r",
                            use_tensorboard=False, compute_mfu=False)
        return Trainer(train_step, None, state, cfg,
                       example_batch={"x": np.zeros((2, 1), np.float32)})

    # uninterrupted: 6 steps (epoch 0: 4 batches, epoch 1: 2 batches)
    log_full = Records()
    t1 = make_trainer(str(tmp_path / "full"))
    with t1:
        t1.fit(make_loader(log_full))

    # interrupted at step 5 (mid-epoch-1), then resumed for step 6
    log_a = Records()
    t2 = make_trainer(str(tmp_path / "a"))
    t2.config = dataclasses.replace(t2.config, max_steps=5)
    with t2:
        state5 = t2.fit(make_loader(log_a))

    log_b = Records()
    t3 = make_trainer(str(tmp_path / "b"))
    t3.state = state5  # restored checkpoint
    with t3:
        t3.fit(make_loader(log_b))

    np.testing.assert_array_equal(
        np.asarray(log_a + log_b, object), np.asarray(log_full, object)
    )


def test_test_pass_logs_test_metrics(tmp_path):
    trainer, (train_loader, val_loader) = _make_parts(tmp_path)
    with trainer:
        trainer.fit(train_loader, val_loader)
        metrics = trainer.test(val_loader)
    assert "test_loss" in metrics
    logged = read_metrics(trainer.run_dir)
    assert any("test_loss" in row for row in logged)


def test_halt_on_nonfinite_loss(tmp_path):
    # trainer whose step reports a NaN loss; log every step so the guard
    # fires immediately
    trainer2, loaders = _make_parts(tmp_path)
    trainer2.config = dataclasses.replace(trainer2.config, log_every_n_steps=1)
    original = trainer2._train_step
    trainer2._train_step = lambda s, b: (
        (lambda st, m: (st, {**m, "loss": m["loss"] * jnp.nan}))(*original(s, b))
    )
    with trainer2:
        with pytest.raises(FloatingPointError, match="non-finite"):
            trainer2.fit(loaders[0], loaders[1])

    # and the escape hatch
    trainer3, loaders3 = _make_parts(tmp_path)
    trainer3.config = dataclasses.replace(
        trainer3.config, log_every_n_steps=1, halt_on_nonfinite=False,
        max_epochs=1,
    )
    original3 = trainer3._train_step
    trainer3._train_step = lambda s, b: (
        (lambda st, m: (st, {**m, "loss": m["loss"] * jnp.nan}))(*original3(s, b))
    )
    with trainer3:
        trainer3.fit(loaders3[0], loaders3[1])  # completes without raising


def test_sigterm_saves_last_and_resumes(tmp_path):
    """SIGTERM mid-fit: the trainer saves the newest state to last/ and stops
    cleanly; restore_train_state(prefer_latest=True) resumes from it."""
    import os as _os
    import signal as _signal

    from perceiver_io_tpu.training import restore_train_state

    trainer, loaders = _make_parts(tmp_path)
    trainer.config = dataclasses.replace(trainer.config, max_epochs=50)

    count = {"n": 0}
    original = trainer._train_step

    def step_then_sigterm(s, b):
        out = original(s, b)
        count["n"] += 1
        if count["n"] == 3:
            _os.kill(_os.getpid(), _signal.SIGTERM)
        return out

    trainer._train_step = step_then_sigterm
    with trainer:
        state = trainer.fit(loaders[0], loaders[1])
    assert count["n"] == 3  # stopped right after the signal, not 50 epochs
    assert _os.path.isdir(_os.path.join(trainer.run_dir, "checkpoints", "last"))

    like = jax.tree.map(jnp.zeros_like, state)
    restored = restore_train_state(
        _os.path.join(trainer.run_dir, "checkpoints"), like, prefer_latest=True
    )
    assert int(restored.step) == int(state.step) == 3
    # the normal SIGTERM disposition is restored after fit
    assert _signal.getsignal(_signal.SIGTERM) == _signal.SIG_DFL


@pytest.mark.slow  # tier-1 budget (r10): trainer-level resume stays tier-1
# in test_fit_max_steps_and_resume; the stricter CLI resume contract in
# tests/test_cli.py::test_bucketed_stacked_resume_is_bit_for_bit
def test_cli_resume_continues_run(tmp_path):
    """--resume picks up the newest checkpoint and logs into the same dir."""
    from perceiver_io_tpu.cli import train_img_clf
    from perceiver_io_tpu.training import read_metrics

    argv = [
        "--synthetic", "--logdir", str(tmp_path / "logs"),
        "--root", str(tmp_path / "cache"),
        "--num_latents", "4", "--num_latent_channels", "16",
        "--num_encoder_layers", "1", "--num_self_attention_layers_per_block", "1",
        "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
        "--dtype", "float32", "--synthetic_size", "64", "--batch_size", "16",
        "--max_steps", "3", "--log_every_n_steps", "1",
    ]
    run_dir = train_img_clf.main(argv)
    steps1 = {r["step"] for r in read_metrics(run_dir) if "train_loss" in r}

    # resume passes NO model/data args: every one must come back from the
    # run's embedded hparams; only the explicitly-given flags change
    resumed_dir = train_img_clf.main(
        ["--resume", run_dir, "--max_steps", "6", "--log_every_n_steps", "1"]
    )
    assert resumed_dir == run_dir
    steps2 = {r["step"] for r in read_metrics(run_dir) if "train_loss" in r}
    assert max(steps2) == 6 and steps1 < steps2


@pytest.mark.parametrize("mesh", [
    None,
    # tier-1 budget (r10): the dp x scan composition also rides
    # test_eval_shardings_unstacked_with_multistep_dispatch and the
    # bucketed+stacked CLI resume test; the K-step arithmetic itself
    # stays tier-1 via the mesh-free variant
    pytest.param("dp", marks=pytest.mark.slow),
])
def test_steps_per_dispatch_matches_per_step(tmp_path, mesh):
    """Multi-step dispatch (lax.scan over K stacked batches) must reproduce
    the per-step loop: same step count, same final loss trajectory, eval
    cadence honored, max_steps never overshot — incl. a partial tail window
    (7 steps at K=4) and mesh mode with stacked batch shardings."""
    mesh = make_mesh() if mesh else None

    def run(k):
        trainer, _ = _make_parts(tmp_path / f"k{k}", mesh=mesh)
        cfg = dataclasses.replace(
            trainer.config, max_epochs=None, max_steps=7,
            log_every_n_steps=2, steps_per_dispatch=k,
        )
        t = Trainer(
            trainer._raw_train_step,
            None,
            trainer.state,
            cfg,
            example_batch=trainer._example_batch,
            mesh=mesh,
        )
        loader = DataLoader(_Blobs(64), 8, _collate, shuffle=True, prefetch=0)
        with t:
            state = t.fit(loader, None)
            rows = read_metrics(t.run_dir)
        return state, [r for r in rows if "train_loss" in r]

    s1, rows1 = run(1)
    s4, rows4 = run(4)
    assert int(jax.device_get(s1.step)) == 7
    assert int(jax.device_get(s4.step)) == 7
    # identical data order (same seed) -> identical final params. Mesh mode
    # compiles different programs for the two dispatch shapes, so collective
    # reduction order differs at float level and Adam amplifies near-zero
    # grads to O(lr) per step — same tolerance reasoning as the golden
    # trajectory test; single-device stays tight.
    atol = 2.5e-3 if mesh is not None else 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=atol
        ),
        s1.params, s4.params,
    )
    # logging cadence: K=1 logs at steps 2,4,6; K=4 logs at the dispatch
    # edges that cross those boundaries (4 and 7)
    assert [r["step"] for r in rows1] == [2, 4, 6]
    assert [r["step"] for r in rows4] == [4, 7]


def test_eval_shardings_unstacked_with_multistep_dispatch(tmp_path):
    """With steps_per_dispatch>1 in mesh mode, the TRAIN batch shardings carry
    a leading scan axis but eval batches never do — the trainer must keep a
    separate unstacked plan for eval (ADVICE r2: multi-host eval crashed when
    both were combined, because make_array_from_process_local_data got a spec
    one rank longer than the eval array). The multi-process leg runs in
    tests/test_multihost.py (worker uses steps_per_dispatch=2 + val_loader);
    this checks the plan structurally."""
    mesh = make_mesh()
    trainer, (train_loader, val_loader) = _make_parts(tmp_path, mesh=mesh)
    cfg = dataclasses.replace(trainer.config, steps_per_dispatch=4)
    t = Trainer(
        trainer._raw_train_step,
        trainer._eval_step and (lambda s, b, k: trainer._eval_step(s, b, k)),
        trainer.state,
        cfg,
        example_batch=trainer._example_batch,
        mesh=mesh,
    )
    for key, example in t._example_batch.items():
        train_spec = t._batch_shardings[key].spec
        eval_spec = t._eval_batch_shardings[key].spec
        # train plan: leading None for the scan axis, then the eval plan
        assert len(train_spec) == np.ndim(example) + 1
        assert train_spec[0] is None
        assert tuple(train_spec[1:]) == tuple(eval_spec)
        assert len(eval_spec) <= np.ndim(example)
    # and eval actually runs (single-process: batches pass through unchanged)
    with t:
        t.fit(train_loader, val_loader)
        rows = read_metrics(t.run_dir)
    assert any("val_loss" in r for r in rows)


def test_debug_nans_localizes_at_dispatch(tmp_path):
    """debug_nans=True (CLI --debug_nans) raises FloatingPointError at the
    FIRST dispatch that produces a NaN — inside jit, at the originating op —
    not at the next log boundary the way halt_on_nonfinite does (the log
    cadence here is far beyond max_steps, so only the sanitizer can fire)."""
    import dataclasses

    import optax

    from perceiver_io_tpu.training import TrainState

    params = {"w": jnp.ones((2,))}
    state = TrainState.create(params, optax.sgd(1e-3), jax.random.key(0))

    def nan_step(state, batch):
        # sqrt of a large negative: a NaN born inside the jitted body
        loss = jnp.sqrt(jnp.sum(batch["x"]) - 1e9)
        return state, {"loss": loss}

    batch = {"x": np.ones((2, 1), np.float32)}
    cfg = TrainerConfig(
        max_steps=3, log_every_n_steps=1000, logdir=str(tmp_path / "logs"),
        experiment="nan", use_tensorboard=False, compute_mfu=False,
        debug_nans=True,
    )
    try:
        trainer = Trainer(nan_step, None, state, cfg, example_batch=batch)
        with trainer:
            with pytest.raises(FloatingPointError):
                trainer.fit([batch, batch, batch])
    finally:
        jax.config.update("jax_debug_nans", False)

    # same step without the flag: the NaN flows through silently (log
    # boundary never reached), proving the raise above came from the
    # sanitizer and not the halt guard
    cfg2 = dataclasses.replace(cfg, debug_nans=False,
                               logdir=str(tmp_path / "logs2"))
    trainer2 = Trainer(nan_step, None, state, cfg2, example_batch=batch)
    with trainer2:
        trainer2.fit([batch, batch, batch])


def test_empty_profile_trace_warns(tmp_path, monkeypatch):
    """A profiler capture whose xplane export came back EMPTY (the silent
    overflow mode of very long windows, r4) must warn at capture time, not
    fail silently until analysis."""
    trainer, loaders = _make_parts(tmp_path)
    trainer.config = dataclasses.replace(
        trainer.config, profile_steps=1, profile_start_step=1, max_epochs=1,
    )

    # stand in for the overflow: stop_trace leaves a 0-byte xplane.pb
    def fake_start(logdir):
        d = os.path.join(logdir, "plugins", "profile", "x")
        os.makedirs(d, exist_ok=True)
        open(os.path.join(d, "host.xplane.pb"), "wb").close()

    monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    with trainer:
        with pytest.warns(UserWarning, match="EMPTY xplane"):
            trainer.fit(loaders[0], loaders[1])
