"""Optical-flow adapters + dense 2D-query decoding (BASELINE extension
configs; validates the adapter contract generalizes beyond the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import perceiver_io_tpu as pit
from perceiver_io_tpu.models.flow import (
    DenseSpatialOutputAdapter,
    OpticalFlowInputAdapter,
    build_optical_flow_model,
    end_point_error,
    extract_patches,
)


def test_extract_patches_values(rng):
    x = jnp.asarray(rng.normal(0, 1, (1, 5, 5, 2)), jnp.float32)
    p = extract_patches(x, 3)
    assert p.shape == (1, 5, 5, 9 * 2)
    # center pixel (2,2): patch = rows 1..3 × cols 1..3 flattened in shift order
    expected = np.asarray(x[0, 1:4, 1:4, :]).reshape(-1)
    np.testing.assert_allclose(np.asarray(p[0, 2, 2]), expected, atol=1e-6)
    # corner (0,0): top-left neighbors are zero padding
    np.testing.assert_allclose(np.asarray(p[0, 0, 0, :2]), 0.0)


def test_extract_patches_rejects_even():
    with pytest.raises(ValueError):
        extract_patches(jnp.zeros((1, 4, 4, 1)), 2)


def test_input_adapter_shape(rng):
    adapter = OpticalFlowInputAdapter(
        image_shape=(8, 8, 3), patch_size=3, num_frequency_bands=4
    )
    assert adapter.num_input_channels == 2 * 9 * 3 + 2 * (2 * 4 + 1)
    x = jnp.asarray(rng.normal(0, 1, (2, 2, 8, 8, 3)), jnp.float32)
    out = adapter.apply({}, x)
    assert out.shape == (2, 64, adapter.num_input_channels)

    with pytest.raises(ValueError):
        adapter.apply({}, jnp.zeros((2, 2, 8, 9, 3)))


def test_flow_model_forward_and_train_step(rng):
    model = build_optical_flow_model(
        image_shape=(8, 8, 1),
        latent_shape=(16, 32),
        num_self_attention_layers_per_block=1,
        num_self_attention_heads=2,
        num_frequency_bands=4,
    )
    frames = jnp.asarray(rng.normal(0, 1, (2, 2, 8, 8, 1)), jnp.float32)
    target = jnp.asarray(rng.normal(0, 1, (2, 8, 8, 2)), jnp.float32)
    params = model.init({"params": jax.random.key(0)}, frames)["params"]
    flow = model.apply({"params": params}, frames)
    assert flow.shape == (2, 8, 8, 2)
    assert np.isfinite(np.asarray(flow)).all()

    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: end_point_error(model.apply({"params": p}, frames), target)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # dense queries actually learn


def test_end_point_error():
    pred = jnp.asarray([[[[3.0, 4.0]]]])
    target = jnp.zeros((1, 1, 1, 2))
    assert float(end_point_error(pred, target)) == pytest.approx(5.0)


def test_imagenet_scale_construction():
    """BASELINE's ImageNet-1k 224² config: construct + shape-check the full
    model at scale without allocating (eval_shape only)."""
    model = pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.ImageInputAdapter(
                image_shape=(224, 224, 3), num_frequency_bands=64
            ),
            latent_shape=(512, 1024),
            num_layers=1,
            num_cross_attention_heads=1,
            num_self_attention_heads=8,
            num_self_attention_layers_per_block=6,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=1000, num_output_channels=1024
            ),
            latent_shape=(512, 1024),
        ),
    )
    x = jax.ShapeDtypeStruct((2, 224, 224, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init({"params": jax.random.key(0)}, jnp.zeros(x.shape, x.dtype))
    )
    out = jax.eval_shape(
        lambda v: model.apply({"params": v["params"]}, jnp.zeros(x.shape, x.dtype)),
        variables,
    )
    assert out.shape == (2, 1000)
    # M = 50176 input positions with C_in = 3 + 2*(2*64+1) = 261
    adapter = pit.ImageInputAdapter(image_shape=(224, 224, 3), num_frequency_bands=64)
    assert adapter.num_input_channels == 261


def test_dense_output_adapter_shapes(rng):
    adapter = DenseSpatialOutputAdapter(
        spatial_shape=(4, 6), num_output_features=2, num_output_channels=8
    )
    assert adapter.output_shape == (24, 8)
    x = jnp.asarray(rng.normal(0, 1, (3, 24, 8)), jnp.float32)
    params = adapter.init(jax.random.key(0), x)["params"]
    out = adapter.apply({"params": params}, x)
    assert out.shape == (3, 4, 6, 2)
