"""Flow data module: .flo IO, warp consistency, module surface, CLI smoke."""

import os
import struct

import numpy as np
import pytest

from perceiver_io_tpu.data.flow import (
    FlowDataModule,
    read_flo,
    synthetic_flow_pairs,
    warp_backward,
)


def test_read_flo_roundtrip(tmp_path):
    flow = np.random.default_rng(0).normal(0, 2, (6, 5, 2)).astype("<f4")
    path = tmp_path / "x.flo"
    with open(path, "wb") as f:
        f.write(struct.pack("<f", 202021.25))
        f.write(struct.pack("<ii", 5, 6))  # width, height
        f.write(flow.tobytes())
    out = read_flo(str(path))
    np.testing.assert_array_equal(out, flow)

    with open(path, "wb") as f:
        f.write(struct.pack("<f", 1.0))
    with pytest.raises(ValueError):
        read_flo(str(path))


def test_warp_zero_flow_identity():
    img = np.random.default_rng(0).random((8, 8, 3)).astype(np.float32)
    out = warp_backward(img, np.zeros((8, 8, 2), np.float32))
    np.testing.assert_allclose(out, img, atol=1e-6)


def test_warp_integer_shift():
    img = np.random.default_rng(0).random((8, 8, 1)).astype(np.float32)
    flow = np.zeros((8, 8, 2), np.float32)
    flow[..., 0] = 1.0  # sample one pixel to the right
    out = warp_backward(img, flow)
    np.testing.assert_allclose(out[:, :-2], img[:, 1:-1], atol=1e-6)


def test_synthetic_pairs_consistent():
    frames, flows = synthetic_flow_pairs(2, (16, 16, 1), seed=0)
    assert frames.shape == (2, 2, 16, 16, 1)
    assert flows.shape == (2, 16, 16, 2)
    # frame2 must equal frame1 warped by the flow (that is the label signal)
    np.testing.assert_allclose(
        frames[0, 1], warp_backward(frames[0, 0], flows[0]), atol=1e-5
    )


def test_data_module_loaders():
    dm = FlowDataModule(image_shape=(8, 8, 1), batch_size=4, synthetic=True,
                        synthetic_size=16)
    dm.prepare_data()
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["frames"].shape == (4, 2, 8, 8, 1)
    assert batch["flow"].shape == (4, 8, 8, 2)


@pytest.mark.slow  # tier-1 budget (r22 box drift): the flow model
# forward/train-step and adapters stay tier-1 in tests/test_flow.py;
# the synthetic data pipeline in the tests above. This is the CLI shell.
def test_train_flow_cli(tmp_path):
    from perceiver_io_tpu.cli import train_flow
    from perceiver_io_tpu.training import read_metrics

    run_dir = train_flow.main([
        "--synthetic", "--synthetic_size", "32", "--batch_size", "8",
        "--image_height", "8", "--image_width", "8", "--image_channels", "1",
        "--num_latents", "8", "--num_latent_channels", "16",
        "--num_self_attention_layers_per_block", "1",
        "--num_self_attention_heads", "2", "--num_frequency_bands", "4",
        "--dtype", "float32", "--max_epochs", "2", "--log_every_n_steps", "2",
        "--logdir", str(tmp_path / "logs"), "--root", str(tmp_path / "cache"),
    ])
    rows = read_metrics(run_dir)
    assert any("val_loss" in r for r in rows)
    assert os.path.isdir(os.path.join(run_dir, "checkpoints"))
