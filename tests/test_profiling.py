"""utils/profiling.py (MFU accounting, deadline-guarded tracing) and
training/metrics.py next_version_dir — the previously-untested host-side
observability helpers."""

import os
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import pytest

from perceiver_io_tpu.training.metrics import next_version_dir
from perceiver_io_tpu.utils import profiling


# -- FLOPs / MFU accounting --------------------------------------------------


def test_compiled_flops_from_cost_analysis():
    f = jax.jit(lambda a, b: a @ b)
    a, b = jnp.ones((8, 16)), jnp.ones((16, 4))
    flops = profiling.compiled_flops(f, a, b)
    # CPU XLA exposes a cost model: 8*16*4 MACs = 1024 flops (2x under some
    # conventions) — pin "positive and sane", not the backend's convention
    assert flops is not None and 512 <= flops <= 4096


def test_compiled_flops_none_on_failure():
    assert profiling.compiled_flops(lambda x: x, 1.0) is None  # not jitted


def test_device_peak_flops_unknown_device_is_none():
    # the CPU backend's device_kind is not in the public TPU peak table
    assert profiling.device_peak_flops() is None
    assert profiling.mfu(1e12, 0.1) is None  # unknown peak → undefined MFU


def test_device_peak_flops_known_kinds(monkeypatch):
    class FakeDevice:
        device_kind = "TPU v5e"

    assert profiling.device_peak_flops(FakeDevice()) == 197e12


def test_mfu_arithmetic(monkeypatch):
    monkeypatch.setitem(profiling._PEAK_FLOPS, "cpu", 1e12)
    # 5e11 flops in 1s on a 1e12-peak chip = 50%
    assert profiling.mfu(5e11, 1.0) == pytest.approx(0.5)
    # whole-program flops over 2 chips: peak doubles
    assert profiling.mfu(5e11, 1.0, num_devices=2) == pytest.approx(0.25)
    assert profiling.mfu(5e11, 0.0) is None  # degenerate step time


# -- call_with_deadline / deadline-guarded trace -----------------------------


def test_call_with_deadline_completes_and_times_out():
    ok, result = profiling.call_with_deadline(lambda: 41 + 1, 5.0)
    assert ok and result == 42
    ok, result = profiling.call_with_deadline(lambda: 7, None)  # inline path
    assert ok and result == 7

    release = threading.Event()
    try:
        t0 = time.monotonic()
        ok, result = profiling.call_with_deadline(
            lambda: release.wait(30), 0.2, "wedged")
        assert not ok and result is None
        assert time.monotonic() - t0 < 5  # returned at the deadline, not 30s
    finally:
        release.set()

    with pytest.raises(ZeroDivisionError):  # errors inside fn propagate
        profiling.call_with_deadline(lambda: 1 / 0, 5.0)


def test_trace_degrades_on_wedged_start(tmp_path, monkeypatch):
    """A hanging start_trace (wedged tunnel) must not freeze the caller: the
    context yields after the deadline with a warning, and the body runs."""
    release = threading.Event()
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda logdir: release.wait(30)
    )
    stopped = []
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: stopped.append(1)
    )
    ran = []
    try:
        t0 = time.monotonic()
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with profiling.trace(str(tmp_path), deadline_s=0.2):
                ran.append(1)
        assert time.monotonic() - t0 < 10
        assert ran
        assert any("start_trace" in str(w.message) for w in caught)
    finally:
        release.set()


def test_trace_real_roundtrip(tmp_path):
    """The undamaged path still captures a real (CPU) trace."""
    with profiling.trace(str(tmp_path / "tr"), deadline_s=60.0):
        jax.jit(lambda x: x * 2)(jnp.ones((4,))).block_until_ready()
    profile_dir = tmp_path / "tr" / "plugins" / "profile"
    assert profile_dir.is_dir() and any(profile_dir.iterdir())


# -- next_version_dir --------------------------------------------------------


def test_next_version_dir_picks_smallest_unused(tmp_path):
    logdir = str(tmp_path)
    first = next_version_dir(logdir, "exp")
    assert first.endswith(os.path.join("exp", "version_0"))
    assert os.path.isdir(first)
    # existing versions (with gaps and junk) → max + 1, junk ignored
    os.makedirs(os.path.join(logdir, "exp", "version_7"))
    os.makedirs(os.path.join(logdir, "exp", "not_a_version"))
    open(os.path.join(logdir, "exp", "version_x"), "w").close()
    nxt = next_version_dir(logdir, "exp")
    assert nxt.endswith("version_8")
    # a different experiment starts fresh
    other = next_version_dir(logdir, "other")
    assert other.endswith(os.path.join("other", "version_0"))
