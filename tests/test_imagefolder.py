"""ImageFolder data module: folder scanning, transforms, synthetic mode."""

import os

import numpy as np
import pytest

from perceiver_io_tpu.data.imagefolder import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    ImageFolderDataModule,
    ImageFolderDataset,
    SyntheticImageDataset,
    list_image_folder,
)


def _write_tree(base, split, classes, per_class=3, size=40):
    from PIL import Image

    rng = np.random.default_rng(0)
    for cls in classes:
        d = os.path.join(base, split, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img_{i}.jpeg"))


def test_list_image_folder(tmp_path):
    _write_tree(tmp_path, "train", ["cat", "dog"])
    samples, classes = list_image_folder(str(tmp_path / "train"))
    assert classes == ["cat", "dog"]
    assert len(samples) == 6
    assert all(os.path.exists(p) for p, _ in samples)
    labels = {lbl for _, lbl in samples}
    assert labels == {0, 1}


def test_list_image_folder_empty_raises(tmp_path):
    os.makedirs(tmp_path / "train" / "cat")
    with pytest.raises(FileNotFoundError):
        list_image_folder(str(tmp_path / "train"))


def test_dataset_shapes_and_normalization(tmp_path):
    _write_tree(tmp_path, "train", ["a"], per_class=2, size=48)
    samples, _ = list_image_folder(str(tmp_path / "train"))
    for train in (True, False):
        ds = ImageFolderDataset(samples, image_size=32, train=train)
        img, label = ds[0]
        assert img.shape == (32, 32, 3)
        assert img.dtype == np.float32
        assert label == 0
        # normalized: plausible standardized range
        assert np.abs(img).max() < 5


def test_train_augmentation_varies_but_eval_is_deterministic(tmp_path):
    _write_tree(tmp_path, "train", ["a"], per_class=1, size=64)
    samples, _ = list_image_folder(str(tmp_path / "train"))
    train_ds = ImageFolderDataset(samples, image_size=32, train=True)
    a, _ = train_ds[0]
    b, _ = train_ds[0]
    assert not np.allclose(a, b)  # random crop/flip differ across draws
    val_ds = ImageFolderDataset(samples, image_size=32, train=False)
    c, _ = val_ds[0]
    d, _ = val_ds[0]
    np.testing.assert_array_equal(c, d)


def test_synthetic_dataset_is_lazy_and_learnable():
    ds = SyntheticImageDataset(64, num_classes=4, image_size=32, seed=0)
    img, label = ds[0]
    assert img.shape == (32, 32, 3)
    assert 0 <= label < 4
    # deterministic per index
    img2, label2 = ds[0]
    np.testing.assert_array_equal(img, img2)
    assert label == label2
    # same class, different index → same template, different noise
    same = [i for i in range(64) if int(ds.labels[i]) == label and i != 0]
    if same:
        other, _ = ds[same[0]]
        assert not np.allclose(img, other)
        # denormalize: class template should correlate strongly
        raw1 = img * IMAGENET_STD + IMAGENET_MEAN
        raw2 = other * IMAGENET_STD + IMAGENET_MEAN
        corr = np.corrcoef(raw1.ravel(), raw2.ravel())[0, 1]
        assert corr > 0.5


def test_datamodule_synthetic_loaders():
    dm = ImageFolderDataModule(
        synthetic=True, synthetic_size=64, synthetic_classes=3,
        image_size=16, batch_size=8, num_workers=2,
    )
    dm.prepare_data()
    dm.setup()
    assert dm.num_classes == 3
    batch = next(iter(dm.train_dataloader()))
    assert batch["image"].shape == (8, 16, 16, 3)
    assert batch["label"].shape == (8,)
    assert batch["label"].dtype == np.int32


def test_datamodule_folder_with_val_split(tmp_path):
    _write_tree(tmp_path / "imagenet", "train", ["a", "b"], per_class=4)
    _write_tree(tmp_path / "imagenet", "val", ["a", "b"], per_class=2)
    dm = ImageFolderDataModule(root=str(tmp_path), image_size=24,
                               batch_size=2, num_workers=0)
    dm.prepare_data()
    dm.setup()
    assert dm.num_classes == 2
    assert len(dm.ds_train) == 8
    assert len(dm.ds_valid) == 4
    batch = next(iter(dm.val_dataloader()))
    assert batch["image"].shape == (2, 24, 24, 3)


def test_datamodule_carves_val_from_train_when_missing(tmp_path):
    _write_tree(tmp_path / "imagenet", "train", ["a", "b"], per_class=10)
    dm = ImageFolderDataModule(root=str(tmp_path), image_size=24, batch_size=2)
    dm.prepare_data()
    dm.setup()
    assert len(dm.ds_train) + len(dm.ds_valid) == 20
    assert len(dm.ds_valid) >= 1


def test_datamodule_class_mismatch_raises(tmp_path):
    _write_tree(tmp_path / "imagenet", "train", ["a", "b"])
    _write_tree(tmp_path / "imagenet", "val", ["a"])
    dm = ImageFolderDataModule(root=str(tmp_path))
    with pytest.raises(ValueError):
        dm.setup()


def test_datamodule_missing_tree_raises(tmp_path):
    dm = ImageFolderDataModule(root=str(tmp_path))
    with pytest.raises(FileNotFoundError):
        dm.prepare_data()


def test_loader_num_workers_matches_serial():
    dm_args = dict(synthetic=True, synthetic_size=32, synthetic_classes=2,
                   image_size=8, batch_size=4)
    serial = ImageFolderDataModule(num_workers=0, **dm_args)
    pooled = ImageFolderDataModule(num_workers=4, **dm_args)
    for dm in (serial, pooled):
        dm.setup()
    b1 = next(iter(serial.val_dataloader()))
    b2 = next(iter(pooled.val_dataloader()))
    np.testing.assert_array_equal(b1["image"], b2["image"])
    np.testing.assert_array_equal(b1["label"], b2["label"])
