"""Shared benchmark harness + model presets (the PERF.md-table sources)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.models.presets import flagship_mlm
from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    make_mlm_steps,
    make_optimizer,
)
from perceiver_io_tpu.utils.benchmarking import time_train_step


def _tiny_setup():
    model = flagship_mlm(
        vocab_size=50, max_seq_len=16, num_latents=4, num_channels=16,
        num_layers=1, num_self_attention_layers_per_block=1,
    )
    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(rng.integers(3, 50, (2, 16)).astype(np.int32)),
        "pad_mask": jnp.zeros((2, 16), bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    train_step, _, _ = make_mlm_steps(model)
    return train_step, state, batch


@pytest.mark.slow  # tier-1 budget (r10): the chained-window timing harness
# stays tier-1 via test_time_train_step_accepts_prebuilt_jit (same loop,
# prebuilt-jit path) and the bench contract tests
def test_time_train_step_returns_positive_and_advances_state():
    train_step, state, batch = _tiny_setup()
    seconds, final_state = time_train_step(train_step, state, batch, steps=2)
    assert seconds > 0
    # warmup (3) + t_one (1) + at least `steps`+1 timed iterations ran
    assert int(jax.device_get(final_state.step)) >= 7


@pytest.mark.slow  # tier-1 budget (r21): the timing-harness contract
# (scan-chained on-device iteration, per-step normalization) stays tier-1
# in test_scanned_step_cost_analysis_is_per_step; this is the prebuilt-
# jit entry-point variant
def test_time_train_step_accepts_prebuilt_jit():
    train_step, state, batch = _tiny_setup()
    jitted = jax.jit(train_step, donate_argnums=(0,))
    seconds, _ = time_train_step(
        train_step, state, batch, steps=2, windows=2, jitted=jitted
    )
    assert seconds > 0


def test_flagship_preset_matches_graft_entry():
    """__graft_entry__ must build exactly the preset (the driver's compile
    check and the benches must agree on the flagship model)."""
    import __graft_entry__ as g

    entry_model = g._build_flagship(
        vocab_size=50, max_seq_len=16, num_latents=4, num_channels=16,
        num_layers=1, blocks=1,
    )
    preset = flagship_mlm(
        vocab_size=50, max_seq_len=16, num_latents=4, num_channels=16,
        num_layers=1, num_self_attention_layers_per_block=1,
    )
    ids = jnp.zeros((1, 16), jnp.int32)
    pad = jnp.zeros((1, 16), bool)
    rngs = {"params": jax.random.key(0), "masking": jax.random.key(1)}
    p1 = entry_model.init(rngs, ids, pad)["params"]
    p2 = preset.init(rngs, ids, pad)["params"]
    assert jax.tree_util.tree_structure(p1) == jax.tree_util.tree_structure(p2)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scanned_step_cost_analysis_is_per_step():
    """XLA cost analysis counts a lax.scan body ONCE regardless of trip
    count, so the K-step scanned executable's flops are PER-STEP flops —
    the contract Trainer._maybe_compute_flops relies on (it must NOT divide
    by K; dividing made the in-loop MFU metric K x too low, r4)."""
    from perceiver_io_tpu.training.steps import make_scanned_step
    from perceiver_io_tpu.utils.profiling import compiled_flops

    train_step, state, batch = _tiny_setup()
    single = compiled_flops(jax.jit(train_step), state, batch)

    scanned = make_scanned_step(train_step)
    # one K suffices to pin the once-not-K-times contract (tier-1 budget,
    # r11: the k=4 point only re-proved the same scan-body invariance at
    # another trip count for an extra compile)
    for k in (2,):
        stacked = {key: jnp.stack([v] * k) for key, v in batch.items()}
        k_flops = compiled_flops(jax.jit(scanned), state, stacked)
        assert single is not None and k_flops is not None
        # identical body => identical per-step count (ratio 1, not K); allow
        # a few % for scan plumbing
        assert abs(k_flops / single - 1.0) < 0.05, (k, k_flops, single)
