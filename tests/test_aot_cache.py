"""Persistent AOT executable cache (perceiver_io_tpu.aot): warm starts
deserialize instead of compiling (bit-identical, zero XLA compiles),
fingerprint drift and corrupt entries fall back to a normal compile, shared
cache directories don't race, and background warmup serves traffic before
the full bucket family is warm."""

import os
import threading

import numpy as np
import pytest
import jax
import flax.linen as nn

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.aot import (
    ExecutableCache,
    callable_sources,
    fingerprint,
    resolve_cache,
)
from perceiver_io_tpu.inference import ServingEngine
from perceiver_io_tpu.obs import install_compile_counter


class _Net(nn.Module):
    width: int = 32

    @nn.compact
    def __call__(self, x):
        return nn.Dense(8)(nn.tanh(nn.Dense(self.width)(x)))


def _setup(width: int = 32):
    model = _Net(width)
    params = model.init(jax.random.key(0), np.ones((1, 16), np.float32))[
        "params"]
    apply_fn = lambda p, x: model.apply({"params": p}, x)
    return apply_fn, params


def _entries(directory):
    return [n for n in os.listdir(directory) if n.endswith(".pitx")]


def test_warm_start_bit_identical_and_zero_compiles(tmp_path):
    """The acceptance drill: with a warm cache, warmup() performs ZERO XLA
    compiles (pinned via the r7 jax_compilations_total counter) and the
    deserialized executables produce BIT-identical outputs to the freshly
    compiled ones on the f32 parity path."""
    cache_dir = str(tmp_path / "cache")
    apply_fn, params = _setup()
    x = np.random.default_rng(0).normal(size=(3, 16)).astype(np.float32)

    with ServingEngine(apply_fn, params, max_batch=8,
                       compile_cache=cache_dir, name="aot_cold") as cold:
        warmed = cold.warmup(np.ones((1, 16), np.float32))
        out_fresh = cold.predict(x)
    assert warmed == [1, 2, 4, 8]
    assert len(_entries(cache_dir)) == len(warmed)

    counter = install_compile_counter()
    before = counter.value
    with ServingEngine(apply_fn, params, max_batch=8,
                       compile_cache=cache_dir, name="aot_warm") as warm:
        assert warm.warmup(np.ones((1, 16), np.float32)) == warmed
        assert counter.value == before, "warm warmup must not compile"
        out_cached = warm.predict(x)
        assert counter.value == before, "warm serving must not compile"
    assert out_fresh.dtype == np.float32
    assert np.array_equal(np.asarray(out_fresh), np.asarray(out_cached))


def test_fingerprint_change_is_a_miss(tmp_path):
    """Any drift in the fingerprinted identity — here the caller salt, the
    hook model/config changes ride on — lands in a DIFFERENT entry: the old
    executable is never served for a new program."""
    cache_dir = str(tmp_path / "cache")
    apply_fn, params = _setup()
    for salt in ("model-v1", "model-v2"):
        with ServingEngine(apply_fn, params, max_batch=2,
                           compile_cache=cache_dir, cache_salt=salt,
                           name=f"aot_{salt}") as eng:
            eng.warmup(np.ones((1, 16), np.float32), buckets=[1])
    assert len(_entries(cache_dir)) == 2  # one per salt: the change missed

    # input-shape drift misses too (same salt, new signature)
    with ServingEngine(apply_fn, params, max_batch=2,
                       compile_cache=cache_dir, cache_salt="model-v1",
                       name="aot_shape") as eng:
        eng.warmup(np.ones((1, 16), np.float32), buckets=[2])
    assert len(_entries(cache_dir)) == 3


def test_corrupt_entry_warns_and_falls_back(tmp_path):
    """A truncated/garbage cache entry must degrade to a fresh compile with
    a warning — never an outage, never a wrong answer."""
    cache_dir = str(tmp_path / "cache")
    apply_fn, params = _setup()
    x = np.random.default_rng(1).normal(size=(2, 16)).astype(np.float32)
    with ServingEngine(apply_fn, params, max_batch=2,
                       compile_cache=cache_dir, name="aot_pre") as eng:
        eng.warmup(np.ones((1, 16), np.float32))
        expect = eng.predict(x)
    paths = _entries(cache_dir)
    assert paths
    for name in paths:
        with open(os.path.join(cache_dir, name), "wb") as f:
            f.write(b"not a serialized executable")

    with pytest.warns(UserWarning, match="corrupt"):
        with ServingEngine(apply_fn, params, max_batch=2,
                           compile_cache=cache_dir, name="aot_post") as eng:
            eng.warmup(np.ones((1, 16), np.float32))
            got = eng.predict(x)
    assert np.array_equal(np.asarray(expect), np.asarray(got))
    # the corrupt entries were replaced by good ones (fresh compile stored)
    with ServingEngine(apply_fn, params, max_batch=2,
                       compile_cache=cache_dir, name="aot_post2") as eng:
        eng.warmup(np.ones((1, 16), np.float32))
        assert np.array_equal(np.asarray(expect), np.asarray(eng.predict(x)))


def test_concurrent_engines_share_one_cache_dir(tmp_path):
    """Two engines warming the same family against one directory — the
    background-warmup-races-the-worker shape, and the multi-replica shape —
    must both finish and serve correctly (atomic writes, claim dedup)."""
    cache_dir = str(tmp_path / "cache")
    apply_fn, params = _setup()
    x = np.random.default_rng(2).normal(size=(2, 16)).astype(np.float32)
    engines = [
        ServingEngine(apply_fn, params, max_batch=4,
                      compile_cache=cache_dir, name=f"aot_cc{i}")
        for i in range(2)
    ]
    errors = []

    def warm(eng):
        try:
            eng.warmup(np.ones((1, 16), np.float32))
        except BaseException as e:  # surfaced below
            errors.append(e)

    threads = [threading.Thread(target=warm, args=(e,)) for e in engines]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    expect = np.asarray(apply_fn(params, x))
    for eng in engines:
        np.testing.assert_allclose(np.asarray(eng.predict(x)), expect,
                                   rtol=0, atol=0)
        eng.close()
    assert len(_entries(cache_dir)) == 3  # 4-buckets: 1, 2, 4 — once each


def test_background_warmup_answers_before_family_is_warm(tmp_path):
    """The serve-before-warm claim: with a deliberately large bucket family,
    a request submitted right after warmup(background=True) starts is
    answered while the family is still warming (priority order puts the
    request's small bucket first), and the handle later reports the full
    family + flips engine_ready."""
    cache_dir = str(tmp_path / "cache")
    apply_fn, params = _setup(width=192)  # heavy enough to compile slowly
    x = np.random.default_rng(3).normal(size=(1, 16)).astype(np.float32)
    with ServingEngine(apply_fn, params, max_batch=64,
                       compile_cache=cache_dir, name="aot_bg") as eng:
        handle = eng.warmup(np.ones((1, 16), np.float32), background=True)
        got = eng.submit(x).result(timeout=300)
        family_was_warm = handle.done()
        assert handle.wait(timeout=300) == [1, 2, 4, 8, 16, 32, 64]
        assert eng._m_ready.value == 1.0
    assert np.array_equal(np.asarray(got), np.asarray(apply_fn(params, x)))
    assert not family_was_warm, (
        "first answer should land before the 7-bucket family finishes "
        "warming; if this is flaky the family is too small/fast"
    )


def test_cache_open_fail_soft(tmp_path):
    """An uncreatable cache path (here: nested under a regular file) warns
    and disables caching instead of raising — serving must never be refused
    over a cache problem."""
    blocker = tmp_path / "a_file"
    blocker.write_text("x")
    with pytest.warns(UserWarning, match="unusable"):
        cache = ExecutableCache.open(str(blocker / "cache"))
    assert cache is None
    # an engine handed the bad path serves uncached
    apply_fn, params = _setup()
    with pytest.warns(UserWarning, match="unusable"):
        eng = ServingEngine(apply_fn, params, max_batch=2,
                            compile_cache=str(blocker / "cache"),
                            name="aot_soft")
    try:
        out = eng.predict(np.ones((1, 16), np.float32))
        assert np.asarray(out).shape == (1, 8)
    finally:
        eng.close()


def test_fingerprint_is_stable_and_sensitive():
    """Same inputs → same digest; any component changing → different."""
    apply_fn, params = _setup()
    avals = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    base = {"platform": "cpu", "donate": False}
    srcs = callable_sources(apply_fn)
    a = fingerprint(base, avals=avals, extra=srcs)
    assert a == fingerprint(base, avals=avals, extra=srcs)
    assert a != fingerprint({**base, "donate": True}, avals=avals, extra=srcs)
    assert a != fingerprint(base, avals=avals, extra=srcs + ["more"])
    other = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((7, *s.shape), s.dtype), avals)
    assert a != fingerprint(base, avals=other, extra=srcs)
    # closure walk reaches the model hyperparameters through the apply fn
    assert any("_Net" in s for s in srcs)


def test_resolve_cache_passthrough(tmp_path):
    cache = ExecutableCache.open(str(tmp_path / "c"))
    assert resolve_cache(cache) is cache
    assert resolve_cache(None) is None
    opened = resolve_cache(str(tmp_path / "c2"))
    assert isinstance(opened, ExecutableCache)
    assert os.path.isdir(tmp_path / "c2")


def test_store_refused_while_persistent_cache_active(tmp_path, monkeypatch):
    """The two tiers must never both serialize one compile (the measured
    jaxlib-corruption negative, PERF.md §Cold start): with jax's persistent
    compilation cache active in-process, AOT stores are refused with one
    warning — loads stay enabled, serving stays up."""
    from perceiver_io_tpu.aot import cache as cache_mod

    c = ExecutableCache.open(str(tmp_path / "c"))
    monkeypatch.setattr(cache_mod, "_TIER2_DIR", "/somewhere")
    monkeypatch.setattr(cache_mod, "_DOUBLE_TIER_WARNED", False)
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: x + 1).lower(jnp.ones(2)).compile()
    with pytest.warns(UserWarning, match="persistent compilation cache"):
        assert c.store("deadbeef", compiled) is False
    assert c.entries() == []
    # once-only warning: the second refusal is silent
    assert c.store("deadbeef", compiled) is False
