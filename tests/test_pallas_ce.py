"""Fused flash-CE Pallas kernel (ops/pallas_ce.py): exactness vs the unfused
XLA path, gradients, vocab padding, ignore-label semantics, and the MLM
fused_head='pallas' integration. Runs in interpreter mode on the CPU
conftest; the compiled path is exercised on hardware by bench.py (its
default head) and tools/tpu_pallas_spmd_check.py."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from perceiver_io_tpu.ops.pallas_ce import pallas_linear_ce_integer
from perceiver_io_tpu.training.losses import (
    cross_entropy_with_ignore,
    pallas_linear_cross_entropy_with_ignore,
    softmax_ce_integer,
)


def _setup(rng, B=2, K=24, C=16, V=275):
    x = jnp.asarray(rng.normal(0, 1, (B, K, C)).astype(np.float32))
    w = jnp.asarray(rng.normal(0, 0.1, (C, V)).astype(np.float32))
    b = jnp.asarray(rng.normal(0, 0.1, V).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, V, (B, K)).astype(np.int32))
    return x, w, b, labels


class TestPallasLinearCE:
    @pytest.mark.parametrize("v_blk", [128, 512])
    def test_matches_unfused_with_grads(self, rng, v_blk):
        """Loss and all three gradients vs logits-materializing XLA CE —
        incl. a vocab (275) that forces kernel-side padding at v_blk=128."""
        x, w, b, labels = _setup(rng)

        def ref(x, w, b):
            return softmax_ce_integer(x @ w + b, labels).sum()

        def ker(x, w, b):
            return pallas_linear_ce_integer(
                x, w, b, labels, v_block_size=v_blk
            ).sum()

        ref_l, ref_g = jax.value_and_grad(ref, argnums=(0, 1, 2))(x, w, b)
        ker_l, ker_g = jax.value_and_grad(ker, argnums=(0, 1, 2))(x, w, b)
        np.testing.assert_allclose(float(ker_l), float(ref_l), rtol=1e-5)
        for name, got, want in zip("x w b".split(), ker_g, ref_g):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_awkward_row_count_pads_not_shrinks(self, rng):
        """A row count with no aligned divisor (B·K = 2·31 = 62, prime-ish)
        must PAD rows to the block rather than shrink the block to a tiny
        exact divisor (the seq-131072 regression: R = 32·1229 drove the grid
        to 12,290 steps). Dead rows carry zero cotangent, so loss and all
        three grads still match the unfused path exactly."""
        x, w, b, labels = _setup(rng, B=2, K=31)

        def ref(x, w, b):
            return softmax_ce_integer(x @ w + b, labels).sum()

        def ker(x, w, b):
            # r_block_size forces the padded-rows path even in interpret
            # mode (align=1 would otherwise allow r_blk=62 exactly)
            return pallas_linear_ce_integer(
                x, w, b, labels, r_block_size=16
            ).sum()

        ref_l, ref_g = jax.value_and_grad(ref, argnums=(0, 1, 2))(x, w, b)
        ker_l, ker_g = jax.value_and_grad(ker, argnums=(0, 1, 2))(x, w, b)
        np.testing.assert_allclose(float(ker_l), float(ref_l), rtol=1e-5)
        for name, got, want in zip("x w b".split(), ker_g, ref_g):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_single_block_vocab(self, rng):
        """V smaller than the block size → one full-dim block."""
        x, w, b, labels = _setup(rng, V=64)
        ref = softmax_ce_integer(x @ w + b, labels)
        got = pallas_linear_ce_integer(x, w, b, labels)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)

    def test_bf16_features(self, rng):
        """bf16 compute path: kernel loss tracks the bf16 XLA loss."""
        x, w, b, labels = _setup(rng)
        xb = x.astype(jnp.bfloat16)
        ref = softmax_ce_integer(xb @ w.astype(jnp.bfloat16) + b.astype(jnp.bfloat16), labels)
        got = pallas_linear_ce_integer(xb, w, b, labels, v_block_size=128)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=3e-2, atol=3e-2
        )

    def test_ignore_label_semantics(self, rng):
        """The with-ignore wrapper == cross_entropy_with_ignore on the
        materialized logits, incl. zero grads for ignored rows."""
        x, w, b, labels = _setup(rng)
        labels = labels.at[0, :7].set(-100)

        def ref(x):
            return cross_entropy_with_ignore(x @ w + b, labels)

        def ker(x):
            return pallas_linear_cross_entropy_with_ignore(x, w, b, labels)

        ref_l, ref_g = jax.value_and_grad(ref)(x)
        ker_l, ker_g = jax.value_and_grad(ker)(x)
        np.testing.assert_allclose(float(ker_l), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ker_g), np.asarray(ref_g), atol=2e-5)
        # ignored rows get exactly zero feature gradient
        np.testing.assert_allclose(np.asarray(ker_g)[0, :7], 0.0, atol=0)

    def test_shape_validation(self, rng):
        x, w, b, labels = _setup(rng)
        with pytest.raises(ValueError, match="disagree"):
            pallas_linear_ce_integer(x, w, b, labels[:, :3])
        with pytest.raises(ValueError, match="does not match"):
            pallas_linear_ce_integer(x, w[:, :-1], b, labels)


class TestMLMFusedHeadPallas:
    @pytest.mark.slow  # near-duplicate of tests/test_train_steps.py::
    # test_mlm_step_fused_head_matches_unfused (full tier); op-level
    # fused-head value+grad parity stays tier-1 in
    # test_train_steps.py::test_fused_head_matches_unfused
    def test_train_step_matches_unfused(self, rng):
        """fused_head='pallas' must reproduce the unfused loss trajectory
        (gradient equivalence through Adam updates)."""
        import perceiver_io_tpu as pit
        from perceiver_io_tpu.ops.masking import TextMasking
        from perceiver_io_tpu.training import (
            OptimizerConfig,
            TrainState,
            make_mlm_steps,
            make_optimizer,
        )

        VOCAB, L, C, NLAT = 50, 32, 64, 16
        enc = pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_channels=C),
            latent_shape=(NLAT, C), num_layers=2,
        )
        dec = pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_output_channels=C),
            latent_shape=(NLAT, C),
        )
        model = pit.PerceiverMLM(
            encoder=enc, decoder=dec, masking=TextMasking(VOCAB, 1, 2, 3)
        )
        rng_np = np.random.default_rng(0)
        batch = {
            "token_ids": jnp.asarray(
                rng_np.integers(3, VOCAB, (8, L)).astype(np.int32)),
            "pad_mask": jnp.zeros((8, L), dtype=bool),
        }
        variables = model.init(
            {"params": jax.random.key(0), "masking": jax.random.key(1)},
            batch["token_ids"], batch["pad_mask"],
        )
        tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))

        def run(fused):
            step, _, _ = make_mlm_steps(
                model, sched, loss_gather_capacity=16, fused_head=fused
            )
            state = TrainState.create(
                jax.tree.map(jnp.copy, variables["params"]), tx,
                jax.random.key(2),
            )
            jitted = jax.jit(step)
            losses = []
            for _ in range(3):
                state, m = jitted(state, batch)
                losses.append(float(m["loss"]))
            return losses

        np.testing.assert_allclose(run("pallas"), run(False), atol=2e-5)

    def test_invalid_fused_head_rejected(self):
        from perceiver_io_tpu.training import make_mlm_steps

        with pytest.raises(ValueError, match="fused_head"):
            make_mlm_steps(object(), fused_head="nope")


class TestRandomGeometryFuzz:
    """Seeded property fuzz over random (B, K, C, V) head geometries —
    VERDICT r4 item 8, the flash-CE half. `_TEST_ALIGNMENT` forces the
    compiled 8-row sublane alignment while the kernels run interpreted, so
    the row-block pad-don't-shrink rule (the 131k pathology fix, PERF.md r3)
    resolves exactly as on hardware for every draw; parity is asserted vs
    the unfused XLA formula, forward and all three gradients."""

    N_GEOMETRIES = 50

    @pytest.fixture
    def sublane_aligned(self):
        import perceiver_io_tpu.ops.pallas_ce as pc

        pc._TEST_ALIGNMENT = 8
        yield
        pc._TEST_ALIGNMENT = None

    @pytest.mark.slow  # fuzz sweep: deterministic fused-CE parity stays
    # in TestMLMFusedHeadPallas + tests/test_train_steps.py (tier-1)
    def test_fuzz_matches_unfused(self, sublane_aligned):
        import perceiver_io_tpu.ops.pallas_ce as pc

        rng = np.random.default_rng(20260802)
        saw_row_pad = saw_vocab_pad = saw_ignore = False
        for case in range(self.N_GEOMETRIES):
            b = int(rng.integers(1, 3))
            # row counts biased toward awkward factorizations (the bug class:
            # 32·prime has no aligned divisor above 32)
            k_rows = int(rng.choice([
                rng.integers(1, 700),
                8 * rng.choice([7, 11, 13, 31, 61]),
                32 * rng.choice([7, 13, 31]),
                rng.choice([1, 2, 8, 64, 512]),
            ]))
            c = int(rng.choice([8, 16, 64, 128]))
            vocab = int(rng.integers(16, 1200))
            v_blk = int(rng.choice([128, 256, 512]))
            r_blk = int(rng.choice([64, 128, 512]))
            x = jnp.asarray(rng.normal(0, 1, (b, k_rows, c)).astype(np.float32))
            w = jnp.asarray(rng.normal(0, 0.1, (c, vocab)).astype(np.float32))
            bias = jnp.asarray(rng.normal(0, 0.1, vocab).astype(np.float32))
            labels = jnp.asarray(rng.integers(0, vocab, (b, k_rows)).astype(np.int32))
            if rng.integers(0, 2):
                ignore = rng.integers(0, 2, (b, k_rows)).astype(bool)
                labels = jnp.where(jnp.asarray(ignore), -100, labels)
                saw_ignore = saw_ignore or bool(ignore.any())

            resolved_r = pc._row_block(b * k_rows, r_blk, interpret=True)
            saw_row_pad = saw_row_pad or (b * k_rows) % resolved_r != 0
            saw_vocab_pad = saw_vocab_pad or vocab % v_blk != 0

            def ref(x, w, bias):
                logits = x @ w + bias
                return cross_entropy_with_ignore(logits, labels)

            def ker(x, w, bias):
                per_row = pallas_linear_ce_integer(
                    x, w, bias, labels, r_block_size=r_blk, v_block_size=v_blk,
                    interpret=True)
                valid = labels != -100
                per_row = jnp.where(valid, per_row, 0.0)
                return per_row.sum() / jnp.maximum(valid.sum(), 1)

            ref_l, ref_g = jax.value_and_grad(ref, argnums=(0, 1, 2))(x, w, bias)
            ker_l, ker_g = jax.value_and_grad(ker, argnums=(0, 1, 2))(x, w, bias)
            np.testing.assert_allclose(
                float(ker_l), float(ref_l), rtol=2e-5,
                err_msg=f"loss mismatch at case {case}: "
                        f"B{b} K{k_rows} C{c} V{vocab} r{r_blk} v{v_blk}")
            for name, got, want in zip(("dx", "dw", "db"), ker_g, ref_g):
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=3e-4,
                    err_msg=f"{name} mismatch at case {case}: "
                            f"B{b} K{k_rows} C{c} V{vocab} r{r_blk} v{v_blk}")
        assert saw_row_pad and saw_vocab_pad and saw_ignore

    def test_fuzz_row_block_rule_invariants(self, sublane_aligned):
        """The pad-don't-shrink rule, swept: the resolved block is never an
        exact-divisor shrink (the 12,290-step-grid pathology class), always
        sublane-aligned or the full padded row count, and the sequential row
        grid never exceeds ~1 more step than the request implies."""
        import perceiver_io_tpu.ops.pallas_ce as pc

        rng = np.random.default_rng(11)
        for _ in range(600):
            r = int(rng.choice([
                rng.integers(1, 200_000),
                32 * rng.choice([7, 13, 31, 1229]),
                8 * rng.choice([61, 127, 4919]),
            ]))
            requested = int(rng.choice([64, 128, 512, 1024]))
            blk = pc._row_block(r, requested, interpret=True)
            assert blk % 8 == 0 or blk == -(-r // 8) * 8
            padded = -(-r // blk) * blk
            assert padded % blk == 0
            # grid steps bounded by the request (never the divisor explosion)
            assert padded // blk <= -(-r // requested) + 1, (r, requested, blk)
