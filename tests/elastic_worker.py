"""Worker for the elastic 4→3→4 chaos drill — one pool member.

Run as ``python elastic_worker.py --rank R --pool 5 --port P --workdir D``.
Ranks 0–3 train a toy sharded linear regression as a 4-process world;
rank 4 parks as a hot spare on the invite key. The drill script:

1. rank ``--die_rank`` exits hard at step ``--die_at`` (mid-epoch kill);
2. survivors' next dispatch wedges/errors → fence → monitor verdict →
   ``shrink_until_stable`` rebuilds the world at 3 IN-PROCESS;
3. the dead rank's buddy restores its in-memory mirror (digest-verified)
   and the survivors REPLAY the failed step from the same deterministic
   global batch — zero steps lost, loss parity with an unkilled control;
4. at ``--grow_at`` the leader invites the spare; everyone rebuilds at 4
   via the same resize path, the spare pulling state from its buddy.

Every rank writes ``rank<R>_elastic.json`` with losses, walls, digests and
generation history; assertions live on the pytest side
(``tests/test_multihost_recovery.py``). The fault drills (mid-resize death,
corrupted buddy mirror, flaky spare join) ride PIT_FAULTS in the
environment — this worker only adds the exit-on-fatal behavior at the
resize site.

Not named test_* on purpose: pytest must not collect it.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--rank", type=int, required=True)
    parser.add_argument("--pool", type=int, default=5)
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--steps", type=int, default=12)
    parser.add_argument("--die_rank", type=int, default=3)
    parser.add_argument("--die_at", type=int, default=4,
                        help="global step at which --die_rank exits; -1 never")
    parser.add_argument("--grow_at", type=int, default=-2,
                        help="leader posts the spare invite at this step; "
                        "-1 never, -2 auto (die_at+3)")
    parser.add_argument("--quorum", type=int, default=3)
    parser.add_argument("--sync_timeout_ms", type=int, default=60_000,
                        help="rendezvous sync timeout; drills that expect a "
                        "mid-resize death shorten it so the retry path runs "
                        "inside the test budget")
    parser.add_argument("--park_timeout_s", type=float, default=120.0)
    args = parser.parse_args()
    if args.grow_at == -2:
        args.grow_at = (args.die_at + 3) if args.die_at >= 0 else 4

    from perceiver_io_tpu.utils.platform import ensure_cpu_only

    ensure_cpu_only(device_count=2)
    run(args)


BATCH = 24  # divides every world size the drill resizes through (4, 3)
N_EXAMPLES = 96
TRAIN_WORLD = (0, 1, 2, 3)


def _dataset():
    import numpy as np

    rng = np.random.default_rng(0)  # identical on every node
    w_true = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
    x = rng.normal(0, 1, (N_EXAMPLES, 3)).astype(np.float32)
    return list(zip(x, x @ w_true))


def _collate(batch):
    import numpy as np

    return {"x": np.stack([e[0] for e in batch]),
            "y": np.stack([e[1] for e in batch])}


def run(args) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from perceiver_io_tpu.data.pipeline import DataLoader
    from perceiver_io_tpu.parallel import make_mesh, make_sharded_train_step
    from perceiver_io_tpu.parallel.mesh import WorldDescriptor
    from perceiver_io_tpu.resilience import faults
    from perceiver_io_tpu.resilience.elastic import (
        BuddyMirror,
        BuddyStore,
        ElasticConfig,
        ElasticRuntime,
        fetch_with_deadline,
        note_progress,
        progress_path,
    )
    from perceiver_io_tpu.training import TrainState
    from perceiver_io_tpu.training.checkpoint import (
        host_state_snapshot,
        restore_from_snapshot,
        snapshot_digest,
    )

    rank = args.rank
    out = {"node_id": rank, "losses": {}, "walls": {}, "events": [],
           "generations": []}

    rt = ElasticRuntime(ElasticConfig(
        node_id=rank, n_max=args.pool,
        coordinator_address=f"localhost:{args.port}",
        quorum=args.quorum,
        sync_timeout_ms=args.sync_timeout_ms)).start()
    store = BuddyStore(rank, root=args.workdir).start()
    mirror = BuddyMirror(rank, root=args.workdir)
    examples = _dataset()

    def fresh_state():
        return TrainState.create(
            {"w": jnp.zeros((3, 1))}, optax.sgd(0.1), jax.random.key(0))

    def train_step(state, batch):
        def loss_fn(params):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), {"loss": loss}

    class Session:
        """One generation's device-side artifacts: mesh, jitted step,
        placed state, loader slice. Rebuilt whole after every resize."""

        def __init__(self, world, snapshot):
            self.world = world
            self.mesh = make_mesh()  # over the rebuilt global device set
            state = fresh_state()
            if snapshot is not None:
                state = restore_from_snapshot(snapshot, state)
            self.loader = DataLoader(
                examples, batch_size=BATCH, collate=_collate, shuffle=True,
                seed=0, drop_last=True, shard_id=world.process_id,
                num_shards=world.num_processes)
            per_shard = BATCH // world.num_processes
            # donation OFF: the pre-step state must survive a failed
            # dispatch — it IS the elastic resume point
            self.step, self.state, self.b_shardings = make_sharded_train_step(
                train_step, self.mesh, state,
                _collate(examples[:per_shard]), donate_state=False)
            out["generations"].append(
                {"gen": world.generation, "ranks": list(world.ranks)})

        def to_global(self, batch):
            return {
                k: jax.make_array_from_process_local_data(
                    self.b_shardings[k], v, (BATCH,) + v.shape[1:])
                for k, v in batch.items()
            }

    def batch_iter(loader, start_step):
        """Deterministic handoff: position the (possibly re-sharded) loader
        at global step ``start_step`` and stream batches from there."""
        per_epoch = len(loader)
        loader.epoch = start_step // per_epoch
        loader.skip_next(start_step % per_epoch)
        while True:
            yield from loader

    def snapshot_of(state):
        return host_state_snapshot(state)

    def mirror_out(world, snap, step):
        """Push this host's snapshot to its ring buddy AND to its own store
        (the self-copy is what a joining spare pulls from its buddy)."""
        mirror.flush()
        meta = dict(generation=world.generation, step=step)
        mirror.mirror_to(world.buddy_of(rank), snap, **meta)
        mirror.mirror_to(rank, snap, **meta)

    dead_ids = set()  # ranks with a death verdict — never re-invited

    def train_loop(world, sess, global_step):
        it = batch_iter(sess.loader, global_step)
        t_resume_timer = None
        grew = False
        while global_step < args.steps:
            if rank == args.die_rank and global_step == args.die_at:
                out["events"].append({"kind": "die", "step": global_step})
                _flush_json(args, out)
                os._exit(1)

            # -- grow: act on a pending invite at its agreed boundary ------
            invite = rt.check_invite()
            if invite is not None and global_step >= invite.get(
                    "at_step", global_step):
                t0 = time.monotonic()
                snap = snapshot_of(sess.state)
                mirror_out(world, snap, global_step)
                sess = None  # drop device refs before the demolish
                world = rt.accept_invite(invite)
                rt.rebuild(world)
                sess = Session(world, snap)
                it = batch_iter(sess.loader, global_step)
                out["walls"]["grow_s"] = round(time.monotonic() - t0, 3)
                grew = True
                continue
            if (rank == world.leader and not grew and args.grow_at >= 0
                    and global_step == args.grow_at
                    and args.pool > len(TRAIN_WORLD)):
                spares = [i for i in range(args.pool)
                          if i not in world.ranks and i not in dead_ids]
                if spares:
                    rt.post_invite(spares, at_step=global_step + 2)

            # -- one guarded step ------------------------------------------
            batch = next(it)
            try:
                new_state, metrics = sess.step(
                    sess.state, sess.to_global(batch))
                status, v = fetch_with_deadline(
                    metrics["loss"], rt.cfg.fetch_deadline_s)
            except Exception as e:  # noqa: BLE001 — peer death surfaces here
                status, v = "err", e
            if status == "ok":
                sess.state = new_state
                out["losses"][str(global_step)] = float(v)
                global_step += 1
                if t_resume_timer is not None:
                    out["walls"]["decision_to_resume_s"] = round(
                        time.monotonic() - t_resume_timer, 3)
                    t_resume_timer = None
                snap = snapshot_of(sess.state)
                mirror_out(world, snap, global_step)
                if rank == world.leader:
                    note_progress(progress_path(args.workdir),
                                  generation=world.generation,
                                  step=global_step,
                                  world_size=world.num_processes)
                time.sleep(0.05)
                continue

            # -- shrink: fence, verdict, rebuild, buddy-restore, replay ----
            t_detect = time.monotonic()
            dead = rt.await_death_verdict()
            dead_ids.update(dead)
            out["events"].append({"kind": "death_verdict",
                                  "step": global_step, "dead": list(dead),
                                  "status": status})
            # pre-failed-step state: replicated + host-local read, no
            # collective — safe even with the fleet half dead
            snap = snapshot_of(sess.state)
            own_digest = snapshot_digest(snap)
            new_state = metrics = None
            sess = None
            prev_ranks = set(world.ranks)
            try:
                world = rt.shrink_until_stable()
            except faults.InjectedFatalError:
                # the multihost.resize kill drill: die MID-RESIZE
                out["events"].append({"kind": "die_in_resize"})
                _flush_json(args, out)
                os._exit(1)
            # ranks discovered dead DURING the resize (a second death
            # mid-rebuild) also leave the invite pool
            dead_ids.update(prev_ranks - set(world.ranks))
            # peer-redundant restore: the dead rank's buddy resumes from
            # the digest-verified in-memory mirror it holds
            for d in dead:
                meta = store.mirror_meta(d)
                if meta is None:
                    continue
                try:
                    got = mirror.fetch_from(rank, d, snap)
                except (ConnectionError, OSError):
                    got = None
                if got is None:
                    out["events"].append(
                        {"kind": "mirror_rejected", "owner": d,
                         "digest": meta["digest"]})
                else:
                    restored, rmeta = got
                    out["events"].append(
                        {"kind": "mirror_restored", "owner": d,
                         "digest": rmeta["digest"],
                         "own_digest": own_digest,
                         "bytes": int(sum(np.asarray(x).nbytes for x in
                                          jax.tree.leaves(restored)))})
                    snap = restored
            sess = Session(world, snap)
            it = batch_iter(sess.loader, global_step)  # REPLAY failed step
            t_resume_timer = t_detect
        return world, sess, global_step

    # -- role dispatch ---------------------------------------------------------
    if rank in TRAIN_WORLD:
        world = WorldDescriptor(0, TRAIN_WORLD, rank)
        rt.adopt(world)  # before the first jax.devices(): gen-0 bring-up
        sess = Session(world, None)
        world, sess, step = train_loop(world, sess, 0)
        out["final_step"] = step
        out["final_w"] = np.asarray(
            sess.state.params["w"].addressable_data(0)).ravel().tolist()
        out["final_digest"] = snapshot_digest(snapshot_of(sess.state))
    else:
        # hot spare: park on the invite key, join through the resize path
        invite = None
        deadline = time.monotonic() + args.park_timeout_s
        while invite is None and time.monotonic() < deadline:
            invite = rt.await_invite(timeout_ms=1000)
        if invite is None:
            out["events"].append({"kind": "park_timeout"})
            _flush_json(args, out)
            os._exit(0)
        t0 = time.monotonic()
        while True:
            try:
                rt.join(invite)
                break
            except faults.InjectedTransientError:
                # flaky-join drill: re-attempt the SAME invite (survivors
                # are parked in the rendezvous until we arrive)
                out["events"].append({"kind": "join_retry"})
                time.sleep(0.2)
        world = rt.world
        buddy = world.buddy_of(rank)
        template = snapshot_of(fresh_state())
        got = None
        for _ in range(50):  # the buddy's self-copy lands at its boundary
            got = mirror.fetch_from(buddy, buddy, template)
            if got is not None:
                break
            time.sleep(0.1)
        assert got is not None, f"no state mirror on buddy {buddy}"
        snap, meta = got
        out["walls"]["join_s"] = round(time.monotonic() - t0, 3)
        out["events"].append({"kind": "joined", "from_buddy": buddy,
                              "at_step": meta["step"],
                              "digest": meta["digest"]})
        sess = Session(world, snap)
        world, sess, step = train_loop(world, sess, meta["step"])
        out["final_step"] = step
        out["final_w"] = np.asarray(
            sess.state.params["w"].addressable_data(0)).ravel().tolist()
        out["final_digest"] = snapshot_digest(snapshot_of(sess.state))

    _flush_json(args, out)
    print(f"rank {rank} elastic done", flush=True)
    # Skip interpreter teardown: the distributed client's C++ destructor can
    # raise on a world that resized under it (terminate without exception).
    # The JSON above is the contract; exit codes must stay deterministic.
    os._exit(0)


def _flush_json(args, out) -> None:
    path = os.path.join(args.workdir, f"rank{args.rank}_elastic.json")
    with open(path, "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()
