"""Worker for tests/test_multihost.py — one simulated host.

Run as ``python multihost_worker.py --rank R --nprocs N --port P --workdir D``.
Two CPU devices per process; ``jax.distributed`` over a localhost
coordinator. Each rank writes a ``rank<R>.json`` with everything the test
harness cross-checks, so assertions live in ONE place (the pytest side).

``--phase recovery`` runs the r19 fault-tolerance drills instead of the
base topology/fit battery: the PIT_FAULTS-driven NaN-agreement fit (rank 1
corrupts its OWN batch shard; the psum-carried verdict must make both hosts
skip the same step), the coordinated-SIGTERM preemption fit (only rank 1 is
signalled; both ranks must save the same ``last/`` step and exit 0), and a
real-KV peer-liveness round — reports land in ``rank<R>_recovery.json``.

Not named test_* on purpose: pytest must not collect it.
"""

from __future__ import annotations

import argparse
import json
import os


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--rank", type=int, required=True)
    parser.add_argument("--nprocs", type=int, required=True)
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--phase", choices=("base", "recovery"),
                        default="base")
    args = parser.parse_args()

    from perceiver_io_tpu.utils.platform import ensure_cpu_only

    ensure_cpu_only(device_count=2)

    from perceiver_io_tpu.parallel import initialize_distributed

    initialize_distributed(
        coordinator_address=f"localhost:{args.port}",
        num_processes=args.nprocs,
        process_id=args.rank,
    )

    if args.phase == "recovery":
        run_recovery(args)
        return

    import jax
    import numpy as np

    out = {
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }

    # -- per-host loader shards (reference DistributedSampler semantics) -----
    from perceiver_io_tpu.data.pipeline import DataLoader

    data = list(range(64))
    loader = DataLoader(
        data, batch_size=4, collate=lambda b: {"x": np.asarray(b)},
        shuffle=True, seed=0, shard_id=jax.process_index(),
        num_shards=jax.process_count(),
    )
    out["shard_items"] = sorted(
        int(x) for batch in loader for x in batch["x"]
    )

    # -- next_version_dir: process-0's index must win over divergent scans ---
    from perceiver_io_tpu.training.metrics import next_version_dir

    logdir = os.path.join(args.workdir, "logs")
    real_listdir = os.listdir
    if jax.process_index() == 1:
        # make rank 1's local directory scan LIE (as a raced mkdir would):
        # the broadcast from process 0 must override the divergent local n
        def lying_listdir(path):
            names = real_listdir(path)
            if os.path.basename(path) == "exp":
                names = list(names) + ["version_7"]
            return names

        os.listdir = lying_listdir
    try:
        out["version_dir"] = next_version_dir(logdir, "exp")
    finally:
        os.listdir = real_listdir

    # -- a real sharded fit: train + eval reduction + checkpoint -------------
    import jax.numpy as jnp

    import perceiver_io_tpu as pit
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_classifier_steps,
        make_optimizer,
    )
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    VOCAB, L, C, NLAT = 31, 16, 16, 4
    model = pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_channels=C),
            latent_shape=(NLAT, C), num_layers=1,
            num_cross_attention_heads=2, num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=2, num_output_channels=C),
            latent_shape=(NLAT, C), num_cross_attention_heads=2,
        ),
    )

    rng = np.random.default_rng(0)  # same on every host
    n_examples = 64
    ids_all = rng.integers(3, VOCAB, (n_examples, L)).astype(np.int32)
    labels_all = (ids_all.sum(axis=1) % 2).astype(np.int32)
    examples = [
        {"token_ids": ids_all[i], "pad_mask": np.zeros(L, bool),
         "label": labels_all[i]}
        for i in range(n_examples)
    ]

    def collate(batch):
        return {
            k: np.stack([ex[k] for ex in batch]) for k in batch[0]
        }

    def make_loader(shuffle):
        return DataLoader(
            examples, batch_size=8, collate=collate, shuffle=shuffle, seed=0,
            shard_id=jax.process_index(), num_shards=jax.process_count(),
            drop_last=True,
        )

    variables = model.init(
        jax.random.key(0), jnp.asarray(ids_all[:1]),
        pad_mask=jnp.zeros((1, L), bool),
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(1))
    train_step, eval_step = make_classifier_steps(model, sched, input_kind="text")

    # -- hybrid ICI×DCN layout: process granules, tp stays process-local -----
    hybrid = make_mesh(tp=2, dcn_dp=2)  # dp = 2: one replica per host granule
    out["hybrid_rows_process"] = [
        sorted({d.process_index for d in row.flat})
        for row in np.asarray(hybrid.devices)
    ]

    mesh = make_mesh()  # all 4 global devices on the data axis
    run_dir = os.path.join(args.workdir, "run")
    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        TrainerConfig(
            logdir=os.path.join(args.workdir, "fitlogs"), experiment="mh",
            max_steps=4, log_every_n_steps=2, use_tensorboard=False,
            compute_mfu=False, async_checkpoint=False,
            # K>1 + multi-host + a val_loader: the eval path must use its own
            # UNSTACKED batch shardings — with the train plan (built with a
            # leading scan axis) make_array_from_process_local_data would get
            # a spec one rank longer than the eval array and crash (ADVICE r2)
            steps_per_dispatch=2,
        ),
        example_batch=next(iter(make_loader(False))),
        mesh=mesh,
        run_dir=run_dir,
    )
    trainer.fit(make_loader(True), make_loader(False))
    # test() runs the same weighted cross-host reduction as validation and
    # RETURNS the reduced metrics on every rank — both ranks must agree
    test_metrics = trainer.test(make_loader(False))
    out["val_metrics"] = {
        k.replace("test_", "val_", 1): float(v) for k, v in test_metrics.items()
    }
    steps = trainer.checkpoints.all_steps
    out["ckpt_steps"] = sorted(int(s) for s in (steps() if callable(steps) else steps))
    trainer.checkpoints.close()

    with open(os.path.join(args.workdir, f"rank{args.rank}.json"), "w") as f:
        json.dump(out, f)
    print(f"rank {args.rank} done")


def run_recovery(args) -> None:
    """The r19 multi-host fault-tolerance drills (2 real processes)."""
    import signal

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.resilience import faults
    from perceiver_io_tpu.resilience.multihost import PeerLivenessMonitor
    from perceiver_io_tpu.training import TrainState
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    rank = jax.process_index()
    out = {"process_index": rank, "process_count": jax.process_count()}
    reg = obs.get_registry()
    mesh = make_mesh()  # all 4 global devices on the data axis

    def train_step(state, batch):
        def loss_fn(params):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), {"loss": loss}

    # deterministic GLOBAL batches, identically generated on both hosts;
    # each host feeds its own half (the per-host loader-shard contract)
    rng = np.random.default_rng(0)
    w_true = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
    half = 4

    def local_batches(n):
        out_batches = []
        for _ in range(n):
            x = rng.normal(0, 1, (2 * half, 3)).astype(np.float32)
            y = x @ w_true
            sl = slice(rank * half, (rank + 1) * half)
            out_batches.append({"x": x[sl], "y": y[sl]})
        return out_batches

    def fresh_state():
        return TrainState.create(
            {"w": jnp.zeros((3, 1))}, optax.sgd(0.1), jax.random.key(0))

    def cfg(run_name, **overrides):
        kw = dict(
            max_steps=6, log_every_n_steps=100,
            logdir=os.path.join(args.workdir, "rlogs"), experiment=run_name,
            use_tensorboard=False, compute_mfu=False, async_checkpoint=False,
        )
        kw.update(overrides)
        return TrainerConfig(**kw)

    # -- real-KV peer liveness: both hosts beat over the coordinator store --
    peer_events = []
    monitor = PeerLivenessMonitor(
        interval_s=0.1, deadline_s=3.0,
        on_peer_down=peer_events.append).start()

    # -- drill A: NaN-agreement fit (PIT_FAULTS on rank 1 ONLY) -------------
    bad0 = reg.counter("trainer_bad_steps_total").value
    if rank == 1:
        # corrupt THIS host's batch shard at the 3rd collective dispatch:
        # its NaN rides the global loss psum, so the skip verdict must come
        # back identically on BOTH hosts
        faults.install(faults.parse_spec("trainer.collective:nan@3"))
    trainer = Trainer(
        train_step, None, fresh_state(),
        cfg("agree", skip_nonfinite_steps=True, rollback_after_bad_steps=0),
        example_batch=local_batches(1)[0], mesh=mesh,
        run_dir=os.path.join(args.workdir, "agree_run"),
    )
    with trainer:
        state = trainer.fit(local_batches(12))
    faults.install(None)
    out["agree_step"] = int(jax.device_get(state.step))
    out["agree_bad_steps"] = (
        reg.counter("trainer_bad_steps_total").value - bad0)
    out["agree_w"] = np.asarray(
        jax.device_get(state.params["w"])).ravel().tolist()
    out["peer_events_mid"] = list(peer_events)
    monitor.close()

    # -- drill B: coordinated SIGTERM preemption (signal rank 1 ONLY) -------
    class SigtermAt(list):
        def __iter__(self):
            for i, b in enumerate(list.__iter__(self)):
                if i == 4 and rank == 1:
                    os.kill(os.getpid(), signal.SIGTERM)
                yield b

    saves0 = reg.counter("trainer_preempt_saves_total").value
    preempt_dir = os.path.join(args.workdir, "preempt_run")
    trainer2 = Trainer(
        train_step, None, fresh_state(), cfg("preempt", max_steps=40),
        example_batch=local_batches(1)[0], mesh=mesh, run_dir=preempt_dir,
    )
    with trainer2:
        state2 = trainer2.fit(SigtermAt(local_batches(16)))
    out["preempt_step"] = int(jax.device_get(state2.step))
    out["preempt_saves"] = (
        reg.counter("trainer_preempt_saves_total").value - saves0)
    out["agreed_gauge"] = reg.gauge("multihost_last_step_agreed").value
    last_dir = os.path.join(preempt_dir, "checkpoints", "last")
    out["preempt_last_steps"] = sorted(
        int(d) for d in os.listdir(last_dir) if d.isdigit()
    ) if os.path.isdir(last_dir) else []

    path = os.path.join(args.workdir, f"rank{args.rank}_recovery.json")
    with open(path, "w") as f:
        json.dump(out, f)
    print(f"rank {args.rank} recovery done")


if __name__ == "__main__":
    main()
