"""Worker for tests/test_multihost.py — one simulated host.

Run as ``python multihost_worker.py --rank R --nprocs N --port P --workdir D``.
Two CPU devices per process; ``jax.distributed`` over a localhost
coordinator. Each rank writes a ``rank<R>.json`` with everything the test
harness cross-checks, so assertions live in ONE place (the pytest side).

Not named test_* on purpose: pytest must not collect it.
"""

from __future__ import annotations

import argparse
import json
import os


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--rank", type=int, required=True)
    parser.add_argument("--nprocs", type=int, required=True)
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--workdir", required=True)
    args = parser.parse_args()

    from perceiver_io_tpu.utils.platform import ensure_cpu_only

    ensure_cpu_only(device_count=2)

    from perceiver_io_tpu.parallel import initialize_distributed

    initialize_distributed(
        coordinator_address=f"localhost:{args.port}",
        num_processes=args.nprocs,
        process_id=args.rank,
    )

    import jax
    import numpy as np

    out = {
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }

    # -- per-host loader shards (reference DistributedSampler semantics) -----
    from perceiver_io_tpu.data.pipeline import DataLoader

    data = list(range(64))
    loader = DataLoader(
        data, batch_size=4, collate=lambda b: {"x": np.asarray(b)},
        shuffle=True, seed=0, shard_id=jax.process_index(),
        num_shards=jax.process_count(),
    )
    out["shard_items"] = sorted(
        int(x) for batch in loader for x in batch["x"]
    )

    # -- next_version_dir: process-0's index must win over divergent scans ---
    from perceiver_io_tpu.training.metrics import next_version_dir

    logdir = os.path.join(args.workdir, "logs")
    real_listdir = os.listdir
    if jax.process_index() == 1:
        # make rank 1's local directory scan LIE (as a raced mkdir would):
        # the broadcast from process 0 must override the divergent local n
        def lying_listdir(path):
            names = real_listdir(path)
            if os.path.basename(path) == "exp":
                names = list(names) + ["version_7"]
            return names

        os.listdir = lying_listdir
    try:
        out["version_dir"] = next_version_dir(logdir, "exp")
    finally:
        os.listdir = real_listdir

    # -- a real sharded fit: train + eval reduction + checkpoint -------------
    import jax.numpy as jnp

    import perceiver_io_tpu as pit
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_classifier_steps,
        make_optimizer,
    )
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    VOCAB, L, C, NLAT = 31, 16, 16, 4
    model = pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=L, num_channels=C),
            latent_shape=(NLAT, C), num_layers=1,
            num_cross_attention_heads=2, num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=2, num_output_channels=C),
            latent_shape=(NLAT, C), num_cross_attention_heads=2,
        ),
    )

    rng = np.random.default_rng(0)  # same on every host
    n_examples = 64
    ids_all = rng.integers(3, VOCAB, (n_examples, L)).astype(np.int32)
    labels_all = (ids_all.sum(axis=1) % 2).astype(np.int32)
    examples = [
        {"token_ids": ids_all[i], "pad_mask": np.zeros(L, bool),
         "label": labels_all[i]}
        for i in range(n_examples)
    ]

    def collate(batch):
        return {
            k: np.stack([ex[k] for ex in batch]) for k in batch[0]
        }

    def make_loader(shuffle):
        return DataLoader(
            examples, batch_size=8, collate=collate, shuffle=shuffle, seed=0,
            shard_id=jax.process_index(), num_shards=jax.process_count(),
            drop_last=True,
        )

    variables = model.init(
        jax.random.key(0), jnp.asarray(ids_all[:1]),
        pad_mask=jnp.zeros((1, L), bool),
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(1))
    train_step, eval_step = make_classifier_steps(model, sched, input_kind="text")

    # -- hybrid ICI×DCN layout: process granules, tp stays process-local -----
    hybrid = make_mesh(tp=2, dcn_dp=2)  # dp = 2: one replica per host granule
    out["hybrid_rows_process"] = [
        sorted({d.process_index for d in row.flat})
        for row in np.asarray(hybrid.devices)
    ]

    mesh = make_mesh()  # all 4 global devices on the data axis
    run_dir = os.path.join(args.workdir, "run")
    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        TrainerConfig(
            logdir=os.path.join(args.workdir, "fitlogs"), experiment="mh",
            max_steps=4, log_every_n_steps=2, use_tensorboard=False,
            compute_mfu=False, async_checkpoint=False,
            # K>1 + multi-host + a val_loader: the eval path must use its own
            # UNSTACKED batch shardings — with the train plan (built with a
            # leading scan axis) make_array_from_process_local_data would get
            # a spec one rank longer than the eval array and crash (ADVICE r2)
            steps_per_dispatch=2,
        ),
        example_batch=next(iter(make_loader(False))),
        mesh=mesh,
        run_dir=run_dir,
    )
    trainer.fit(make_loader(True), make_loader(False))
    # test() runs the same weighted cross-host reduction as validation and
    # RETURNS the reduced metrics on every rank — both ranks must agree
    test_metrics = trainer.test(make_loader(False))
    out["val_metrics"] = {
        k.replace("test_", "val_", 1): float(v) for k, v in test_metrics.items()
    }
    steps = trainer.checkpoints.all_steps
    out["ckpt_steps"] = sorted(int(s) for s in (steps() if callable(steps) else steps))
    trainer.checkpoints.close()

    with open(os.path.join(args.workdir, f"rank{args.rank}.json"), "w") as f:
        json.dump(out, f)
    print(f"rank {args.rank} done")


if __name__ == "__main__":
    main()
