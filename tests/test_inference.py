"""Inference subsystem: batch-bucketed Predictor, fill-mask API, AOT export."""

import numpy as np
import optax
import pytest
import jax
import jax.numpy as jnp

import perceiver_io_tpu as pit
from perceiver_io_tpu.data.tokenizer import (
    MASK_TOKEN,
    PAD_TOKEN,
    UNK_TOKEN,
    WordPieceTokenizer,
)
from perceiver_io_tpu.inference import (
    MLMPredictor,
    Predictor,
    bucket_size,
    encode_masked_texts,
    export_forward,
    load_exported,
)
from perceiver_io_tpu.ops.masking import TextMasking


def test_bucket_size():
    assert bucket_size(1, 64) == 1
    assert bucket_size(3, 64) == 4
    assert bucket_size(8, 64) == 8
    assert bucket_size(100, 64) == 64
    with pytest.raises(ValueError):
        bucket_size(0, 64)


def _tiny_classifier():
    enc = pit.PerceiverEncoder(
        input_adapter=pit.ImageInputAdapter(image_shape=(6, 6, 1), num_frequency_bands=3),
        latent_shape=(4, 16),
        num_layers=1,
        num_self_attention_layers_per_block=1,
        num_cross_attention_heads=2,
        num_self_attention_heads=2,
    )
    dec = pit.PerceiverDecoder(
        output_adapter=pit.ClassificationOutputAdapter(num_classes=3, num_output_channels=16),
        latent_shape=(4, 16),
        num_cross_attention_heads=2,
    )
    return pit.PerceiverIO(encoder=enc, decoder=dec)


def test_predictor_bucketing_matches_direct(rng):
    model = _tiny_classifier()
    x = jnp.asarray(rng.normal(0, 1, (16, 6, 6, 1)), jnp.float32)
    params = model.init({"params": jax.random.key(0)}, x)["params"]
    direct = np.asarray(model.apply({"params": params}, x))

    pred = Predictor.for_model(model, params, max_batch=8)
    # padded bucket (5 → 8), exact bucket, chunked oversize (16 → 2×8)
    for n in (5, 8, 16):
        out = pred(np.asarray(x[:n]))
        assert out.shape == (n, 3)
        np.testing.assert_allclose(out, direct[:n], atol=1e-5)

    with pytest.raises(ValueError):
        pred(np.asarray(x[:3]), np.asarray(x[:2]))

    # empty request: empty result, not a crash
    out = pred(np.zeros((0, 6, 6, 1), np.float32))
    assert out.shape == (0, 3)


def test_predictor_pytree_outputs(rng):
    """Dict-returning models (multimodal) slice/concat per leaf."""
    from perceiver_io_tpu.models.multimodal import build_multimodal_autoencoder

    model = build_multimodal_autoencoder(
        video_shape=(2, 8, 8, 1), num_audio_samples=32, samples_per_patch=8,
        num_classes=3, latent_shape=(4, 16), video_patch_shape=(1, 4, 4),
        num_self_attention_layers_per_block=1, num_self_attention_heads=2,
        num_modality_channels=4, video_frequency_bands=2, audio_frequency_bands=2,
    )
    batch = {
        "video": jnp.asarray(rng.normal(0, 1, (5, 2, 8, 8, 1)), jnp.float32),
        "audio": jnp.asarray(rng.normal(0, 1, (5, 32, 1)), jnp.float32),
    }
    params = model.init({"params": jax.random.key(0)}, batch)["params"]

    def apply_fn(p, video, audio):
        return model.apply({"params": p}, {"video": video, "audio": audio})

    pred = Predictor(apply_fn, params, max_batch=4)  # 5 → chunk 4 + pad 1
    out = pred(np.asarray(batch["video"]), np.asarray(batch["audio"]))
    assert out["video"].shape == (5, 2, 8, 8, 1)
    assert out["label"].shape == (5, 3)
    direct = model.apply({"params": params}, batch)
    np.testing.assert_allclose(out["label"], np.asarray(direct["label"]), atol=1e-5)


def _word_tokenizer():
    words = ["movie", "great", "terrible", "watch", "the", "was"]
    vocab = {PAD_TOKEN: 0, UNK_TOKEN: 1, MASK_TOKEN: 2}
    for w in words:
        vocab[w] = len(vocab)
    return WordPieceTokenizer(vocab=vocab)


def test_encode_masked_texts():
    tok = _word_tokenizer()
    ids, pad = encode_masked_texts(tok, ["the movie was [MASK]"], 8)
    assert ids.shape == (1, 8)
    mask_id = tok.token_to_id(MASK_TOKEN)
    assert list(ids[0, :4]) == [
        tok.token_to_id("the"), tok.token_to_id("movie"),
        tok.token_to_id("was"), mask_id,
    ]
    assert pad[0, 4:].all() and not pad[0, :4].any()


def _tiny_mlm(vocab_size, max_seq_len=8):
    c = 16
    return pit.PerceiverMLM(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len, num_channels=c
            ),
            latent_shape=(4, c),
            num_layers=1,
            num_self_attention_layers_per_block=1,
            num_cross_attention_heads=2,
            num_self_attention_heads=2,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len, num_output_channels=c
            ),
            latent_shape=(4, c),
            num_cross_attention_heads=2,
        ),
        masking=TextMasking(vocab_size, 1, 2, 3),
    )


@pytest.mark.slow  # tier-1 budget (r11): a convergence smoke — fill-mask
# DECODE correctness stays tier-1 in test_fill_masks_gathered_matches_full_
# decode and test_mlm_predictor_from_checkpoint below; that training learns
# stays tier-1 in test_golden_model.py::test_training_trajectory_matches_
# torch and the train-CLI e2es' finite-loss assertions
def test_mlm_fill_masks_learns_pattern():
    tok = _word_tokenizer()
    vocab = tok.get_vocab_size()
    model = _tiny_mlm(vocab)
    # corpus where [MASK] after "was" is always "great"
    ids, pad = encode_masked_texts(tok, ["the movie was great"] * 8, 8)
    params = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        jnp.asarray(ids), jnp.asarray(pad),
    )["params"]

    # supervised overfit: predict the clean sequence from itself (no masking)
    tx = optax.adam(5e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            logits, _ = model.apply(
                {"params": p}, jnp.asarray(ids), jnp.asarray(pad), masking=False
            )
            labels = jnp.asarray(ids)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            return jnp.mean(jnp.where(jnp.asarray(pad), 0.0, ce))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt)
        return optax.apply_updates(params, updates), opt, loss

    for _ in range(60):
        params, opt, loss = step(params, opt)

    pred = MLMPredictor(model, params, tok, max_seq_len=8, max_batch=4)
    preds = pred.fill_masks(["the movie was [MASK]"], k=2)
    assert len(preds) == 1 and len(preds[0]) == 1
    assert preds[0][0][0] == "great"


def test_mlm_predictor_from_checkpoint(tmp_path):
    """End-to-end: train a tiny MLM via the CLI, reload it by checkpoint dir."""
    from perceiver_io_tpu.cli import train_mlm
    from perceiver_io_tpu.data.tokenizer import load_tokenizer
    import glob
    import os

    run_dir = train_mlm.main([
        "--synthetic", "--logdir", str(tmp_path / "logs"),
        "--root", str(tmp_path / "cache"),
        "--num_latents", "4", "--num_latent_channels", "16",
        "--num_encoder_layers", "1", "--num_self_attention_layers_per_block", "1",
        "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
        "--dtype", "float32",
        "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", "32", "--vocab_size", "120",
        "--max_steps", "2", "--log_every_n_steps", "1",
        "--num_predictions", "2",
    ])
    tok_path = glob.glob(str(tmp_path / "cache" / "*tokenizer*.json"))[0]
    tok = load_tokenizer(tok_path)
    pred = MLMPredictor.from_checkpoint(
        os.path.join(run_dir, "checkpoints"), tok, max_batch=4
    )
    preds = pred.fill_masks(["a [MASK] b", "no mask here"], k=3)
    assert len(preds) == 2
    assert len(preds[0]) == 1 and len(preds[0][0]) == 3
    assert preds[1] == []
    assert all(isinstance(t, str) for t in preds[0][0])


def test_export_roundtrip(rng, tmp_path):
    model = _tiny_classifier()
    x = jnp.asarray(rng.normal(0, 1, (2, 6, 6, 1)), jnp.float32)
    params = model.init({"params": jax.random.key(0)}, x)["params"]
    direct = np.asarray(model.apply({"params": params}, x))

    path = str(tmp_path / "clf.stablehlo")
    export_forward(model, params, (x,), path=path)
    restored = load_exported(path)
    out = np.asarray(restored(x))
    np.testing.assert_allclose(out, direct, atol=1e-5)


def test_fill_masks_gathered_matches_full_decode():
    """The gathered fill-mask path (positions= decode) must produce exactly
    the predictions the full (B, L, vocab) decode implies — across rows with
    different mask counts (capacity bucketing + filler slots) and an
    unmasked row."""
    tok = _word_tokenizer()
    vocab = tok.get_vocab_size()
    model = _tiny_mlm(vocab)
    texts = [
        "the [MASK] was [MASK]",     # 2 masks
        "[MASK] movie great the a",  # 1 mask
        "no mask here",              # 0 masks
    ]
    ids, pad = encode_masked_texts(tok, texts, 8)
    params = model.init(
        {"params": jax.random.key(3), "masking": jax.random.key(4)},
        jnp.asarray(ids), jnp.asarray(pad),
    )["params"]
    pred = MLMPredictor(model, params, tok, max_seq_len=8, max_batch=4)

    got = pred.fill_masks(texts, k=3)

    # reference: full decode via .logits(), argsorted at the mask positions
    logits, token_ids = pred.logits(texts)
    mask_id = tok.token_to_id(MASK_TOKEN)
    for row, text in enumerate(texts):
        positions = np.nonzero(token_ids[row] == mask_id)[0]
        assert len(got[row]) == len(positions)
        for slot, pos in enumerate(positions):
            top = np.argsort(-logits[row, pos])[:3]
            want = [tok.id_to_token(int(t)) for t in top]
            assert got[row][slot] == want, (row, slot)
