"""Metrics time-series: bounded ring-buffer store, windowed queries, the
cadenced registry sampler with JSONL persistence, /seriesz, and the
eventlog-loss instruments (ISSUE 12)."""

import json
import threading
import urllib.request

import pytest

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.obs.timeseries import split_series_key


# -- keys ---------------------------------------------------------------------


def test_series_key_matches_registry_snapshot_keys():
    """series_key() must be byte-identical to the registry's snapshot()
    keys — hand-built queries and sampled series meet on the same strings."""
    reg = obs.MetricsRegistry()
    reg.gauge("queue_depth", labels={"engine": "e1", "zone": "a"})
    snap_key = next(iter(reg.snapshot()["gauges"]))
    assert obs.series_key(
        "queue_depth", {"engine": "e1", "zone": "a"}) == snap_key
    assert obs.series_key("queue_depth", {"zone": "a", "engine": "e1"}) \
        == snap_key  # label order never matters
    hist_key = obs.series_key("lat", {"e": "x"}, field="p99")
    assert split_series_key(hist_key) == ("lat", '{e="x"}', "p99")
    assert split_series_key("plain") == ("plain", "", "")
    # a non-field colon suffix stays part of the name, not a field
    assert split_series_key("ns:custom") == ("ns:custom", "", "")


# -- bounded store ------------------------------------------------------------


def test_store_rings_are_bounded_and_series_capped():
    """Sustained sampling cannot grow memory: per-series rings hold
    max_samples, the key table holds max_series, overflow is counted."""
    s = obs.SeriesStore(max_samples=8, max_series=3)
    for i in range(10_000):
        s.record("a", i, "counter", t=float(i), mono=float(i))
    assert len(s.points("a")) == 8
    assert [v for _, v in s.points("a")] == list(range(9992, 10000))
    assert s.record("b", 1) and s.record("c", 1)
    assert not s.record("d", 1)  # the cap refuses, never grows
    assert s.dropped_series == 1
    assert s.keys() == ["a", "b", "c"]
    assert s.n_series() == 3


def test_windowed_queries_and_counter_reset_awareness():
    s = obs.SeriesStore()
    # a counter climbing 0..9 at 1 Hz, resetting to 0 at t=6 (a restarted
    # process re-publishing from zero)
    values = [0, 1, 2, 3, 4, 5, 0, 1, 2, 3]
    for i, v in enumerate(values):
        s.record("c", v, "counter", t=1000.0 + i, mono=100.0 + i)
    now = 109.0
    assert s.last("c") == 3
    assert s.last("c", window_s=0.5, now=now) == 3
    assert s.last("missing") is None
    # reset-aware delta over the whole run: 5 increments before the reset,
    # 3 after — never negative
    assert s.delta("c", window_s=100, now=now) == 8
    assert s.rate("c", window_s=100, now=now) == pytest.approx(8 / 9)
    # a window past the reset sees only the new segment
    assert s.delta("c", window_s=3.5, now=now) == 3
    assert s.age_s("c", now=now) == pytest.approx(0.0)
    assert s.age_s("missing") is None
    # gauges: plain last-minus-first, window aggregations
    for i, v in enumerate([5.0, 1.0, 3.0]):
        s.record("g", v, "gauge", mono=200.0 + i)
    assert s.delta("g", window_s=100, now=203.0) == -2.0
    assert s.window_agg("g", 100, "max", now=203.0) == 5.0
    assert s.window_agg("g", 100, "mean", now=203.0) == 3.0
    assert s.window_agg("g", 100, "min", now=203.0) == 1.0
    assert s.window_agg("g", 100, "last", now=203.0) == 3.0
    assert s.window_agg("g", 0.5, "max", now=300.0) is None  # empty window
    with pytest.raises(ValueError):
        s.window_agg("g", 1.0, "median", now=203.0)
    # two-sample floor for derivatives
    s.record("one", 1, "counter", mono=1.0)
    assert s.delta("one", 100, now=2.0) is None
    assert s.rate("one", 100, now=2.0) is None


def test_match_selects_label_sets_of_a_bare_name():
    s = obs.SeriesStore()
    for r in ("r0", "r1"):
        s.record(obs.series_key("fleet_replica_queue_depth",
                                {"fleet": "f", "replica": r}), 1.0)
    s.record("other", 1.0)
    s.record(obs.series_key("lat", {"e": "a"}, field="p99"), 1.0)
    assert s.match("fleet_replica_queue_depth") == [
        obs.series_key("fleet_replica_queue_depth",
                       {"fleet": "f", "replica": "r0"}),
        obs.series_key("fleet_replica_queue_depth",
                       {"fleet": "f", "replica": "r1"}),
    ]
    # a field suffix narrows to that field's series; exact keys match only
    # themselves; unknown names match nothing
    assert s.match("lat:p99") == [obs.series_key("lat", {"e": "a"},
                                                 field="p99")]
    assert s.match("lat") == []
    assert s.match(obs.series_key("other")) == ["other"]
    assert s.match('nope{replica="r0"}') == []


# -- sampler ------------------------------------------------------------------


def test_sampler_snapshots_every_instrument_kind(tmp_path):
    reg = obs.MetricsRegistry()
    c = reg.counter("reqs_total", labels={"e": "s"})
    g = reg.gauge("depth")
    h = reg.histogram("lat_seconds", labels={"e": "s"})
    c.inc(10)
    g.set(3.0)
    for v in range(100):
        h.observe(v / 100.0)
    jsonl = tmp_path / "series.jsonl"
    sam = obs.Sampler(registry=reg, store=obs.SeriesStore(),
                      jsonl_path=str(jsonl), name="t")
    n = sam.sample_once()
    c.inc(5)
    sam.sample_once()
    store = sam.store
    ck = obs.series_key("reqs_total", {"e": "s"})
    assert store.kind(ck) == "counter"
    assert [v for _, v in store.points(ck)] == [10.0, 15.0]
    assert store.delta(ck, window_s=3600) == 5.0
    assert store.last(obs.series_key("depth")) == 3.0
    # histograms land as :p50/:p95/:p99 gauges + a :count counter
    p99 = obs.series_key("lat_seconds", {"e": "s"}, field="p99")
    cnt = obs.series_key("lat_seconds", {"e": "s"}, field="count")
    assert store.last(p99) == pytest.approx(0.99)
    assert store.kind(cnt) == "counter"
    assert store.last(cnt) == 100.0
    # the sampler observes itself (sweeps + series count)
    assert sam.sweeps == 2
    assert store.last(obs.series_key("series_count", {"sampler": "t"})) >= n
    sam.close()  # drains the JSONL
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["event"] == "series_sample"
    assert lines[0]["series"][ck] == 10.0
    assert lines[1]["series"][ck] == 15.0
    # persisted records carry the event log's dual clock stamps
    assert "t" in lines[0] and "mono" in lines[0]


def test_sampler_cadence_thread_and_bounded_store():
    reg = obs.MetricsRegistry()
    reg.gauge("depth").set(1.0)
    with obs.Sampler(registry=reg, store=obs.SeriesStore(max_samples=4),
                     interval_s=0.02, name="cad") as sam:
        sam.start()
        deadline = threading.Event()
        for _ in range(200):
            if sam.sweeps >= 6:
                break
            deadline.wait(0.02)
        assert sam.sweeps >= 6
    # bounded despite more sweeps than the ring holds
    assert len(sam.store.points(obs.series_key("depth"))) <= 4


# -- /seriesz -----------------------------------------------------------------


def test_seriesz_endpoint_serves_the_installed_store():
    reg = obs.MetricsRegistry()
    reg.gauge("depth").set(7.0)
    store = obs.SeriesStore()
    sam = obs.Sampler(registry=reg, store=store, name="sz")
    sam.sample_once()
    sam.sample_once()
    with obs.ObsServer(registry=reg, series_store=store) as srv:
        body = json.loads(urllib.request.urlopen(
            srv.url + "/seriesz", timeout=10).read())
        assert body["series"][obs.series_key("depth")]["last"] == 7.0
        assert body["series"][obs.series_key("depth")]["n"] == 2
        # ?window_s bounds the returned points (a far-future-only window
        # is empty but the key survives with n=0)
        narrow = json.loads(urllib.request.urlopen(
            srv.url + "/seriesz?window_s=0.000001", timeout=10).read())
        assert narrow["window_s"] == pytest.approx(1e-6)
    sam.close()


def test_seriesz_404_until_a_store_is_installed():
    reg = obs.MetricsRegistry()
    assert obs.get_series_store() is None
    with obs.ObsServer(registry=reg) as srv:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/seriesz", timeout=10)
        assert e.value.code == 404
        # installing the process default makes the same endpoint live
        store = obs.SeriesStore()
        store.record("x", 1.0)
        try:
            obs.install_series_store(store)
            body = json.loads(urllib.request.urlopen(
                srv.url + "/seriesz", timeout=10).read())
            assert "x" in body["series"]
        finally:
            obs.install_series_store(None)


# -- fleet ingestion ----------------------------------------------------------


def test_ingest_scrape_builds_replica_labeled_series():
    s = obs.SeriesStore()
    scrape = {"up": True, "ready": True, "queue_depth": 5, "inflight": 2,
              "breaker_open": False, "slo_burn": 1.5, "requests_total": 42}
    s.ingest_scrape("fleet", "r0", scrape, scrape_age_s=0.1)
    s.ingest_scrape("fleet", "r0",
                    {**scrape, "queue_depth": 9, "requests_total": 50},
                    scrape_age_s=0.2)
    labels = {"fleet": "fleet", "replica": "r0"}
    qd = obs.series_key("fleet_replica_queue_depth", labels)
    assert [v for _, v in s.points(qd)] == [5.0, 9.0]
    assert s.last(obs.series_key("fleet_replica_slo_burn", labels)) == 1.5
    assert s.last(obs.series_key("fleet_replica_up", labels)) == 1.0
    rt = obs.series_key("fleet_replica_requests_total", labels)
    assert s.kind(rt) == "counter"
    assert s.delta(rt, window_s=3600) == 8.0
    assert s.last(obs.series_key("fleet_scrape_age_s", labels)) \
        == pytest.approx(0.2)
    # a dead replica's scrape ({"up": False}) still records up=0 — the
    # outage is visible in the history, not a gap
    s.ingest_scrape("fleet", "r0", {"up": False, "error": "gone"})
    assert s.last(obs.series_key("fleet_replica_up", labels)) == 0.0


# -- eventlog loss instruments (satellite) ------------------------------------


def test_eventlog_drops_and_queue_depth_ride_the_registry(tmp_path):
    """EventLog.dropped was counted only on the object — invisible to
    /metrics and to alerting. Now eventlog_dropped_total / queue depth are
    registry instruments refreshed at scrape time."""
    path = tmp_path / "drops_unique.jsonl"
    log = obs.EventLog(str(path), queue_depth=3)
    # stop the writer so the bound is hit deterministically, then overfill
    log._stop.set()
    log._writer.join(timeout=10)
    for i in range(10):
        log.write({"event": "e", "i": i})
    assert log.dropped == 7
    reg = obs.get_registry()  # EventLog publishes to the process registry
    labels = {"log": "drops_unique.jsonl"}
    snap = reg.snapshot()  # runs the collector → syncs the instruments
    key = obs.series_key("eventlog_dropped_total", labels)
    qkey = obs.series_key("eventlog_queue_depth", labels)
    assert snap["counters"][key] == 7.0
    assert snap["gauges"][qkey] == 3.0
    log.close()  # drains the 3 buffered records, zeroes the gauge
    assert reg.gauge("eventlog_queue_depth", labels=labels).value == 0.0
    lines = path.read_text().splitlines()
    assert len(lines) == 3
    # the collector for a closed log drops itself from later exports
    reg.snapshot()
    assert reg.counter("eventlog_dropped_total", labels=labels).value == 7.0
