"""The Perceiver-AR generative decode subsystem: causal model, incremental
engine, and the serving workload.

The correctness spine is INCREMENTAL PARITY (the acceptance bar): for every
AR preset on the f32 path, token-t logits from the cached incremental step
must match a dense full-prefix forward within 2e-5 — pinned here per preset,
per step. Around it: structural causality (a future-token perturbation
cannot move an earlier prediction), split-consistent sampling (the
position-folded key stream reproduces identically across ANY re-encode
point — what makes spill-on-death content-lossless), the streamed replica
RPC on both transports, and THE end-to-end drill: train_ar on synthetic
data → checkpoint → serve on a 2-replica fleet → streamed
``generate(session=...)`` with a mid-stream ``kill()`` of the pinned
replica → the assembled continuation is bit-identical to the uninterrupted
oracle (``lost_accepted=0`` by content, not just by count).
"""

import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.inference.generate import (
    ARGenerator,
    GenerateSessionStore,
    SamplingConfig,
)
from perceiver_io_tpu.models.presets import flagship_ar, tiny_ar

VOCAB = 503


def _init(model, max_seq_len, seed=0):
    ids = np.zeros((1, max_seq_len), np.int32)
    return model.init({"params": jax.random.key(seed)}, ids, ids == 0)[
        "params"]


@pytest.fixture(scope="module")
def tiny():
    model = tiny_ar()
    return model, _init(model, 64)


# -- incremental parity: the correctness spine --------------------------------


# every preset on the f32 path (flagship_ar at its structural config —
# C=512, 3 layers x 6-block — with seq/window shrunk for CPU runtime; the
# parity property is per-position algebra, not width-dependent)
PRESETS = {
    "tiny_ar": (lambda: tiny_ar(), 64),
    # blocks shrunk 6 -> 3 for CPU compile wall; the parity property is
    # per-position algebra over the same module structure
    "flagship_ar": (lambda: flagship_ar(
        max_seq_len=64, num_latents=16,
        num_self_attention_layers_per_block=3, dtype=jnp.float32), 64),
}


@pytest.mark.parametrize("name", [
    "tiny_ar",
    # tier-1 budget (r21): the incremental==dense parity property stays
    # tier-1 on tiny_ar (same per-position algebra); the structural
    # flagship config runs in the full tier
    pytest.param("flagship_ar", marks=pytest.mark.slow),
])
def test_incremental_matches_dense_forward(name, rng):
    """Token-t logits from the cached step == dense full-prefix forward at
    2e-5 (f32) — for every step of a short generation, including across
    the prefill's padded width."""
    build, max_seq_len = PRESETS[name]
    model = build()
    params = _init(model, max_seq_len)
    cap = model.num_latents
    # steps 0 and 1 cover the two structural step regimes (first append,
    # subsequent ring append); extra steps add wall, not coverage
    b, p, steps = (2, 9, 3) if name == "tiny_ar" else (1, 9, 2)
    w = p + steps + 3  # a padded prefill width inside the window constraint
    assert w <= p - 1 + cap
    ids = np.zeros((b, w), np.int32)
    ids[:, :p] = rng.integers(3, VOCAB, (b, p))
    pad = np.broadcast_to(np.arange(w)[None, :] >= p, (b, w)).copy()

    # deliberately UNJITTED: at a handful of calls, eager execution is
    # cheaper than compiling three programs of a C=512 model on CPU
    _, cache = model.apply(
        {"params": params}, jnp.asarray(ids), jnp.asarray(pad),
        length=jnp.asarray(p, jnp.int32), method="prefill")

    cur = ids.copy()
    for t in range(steps):
        tok = rng.integers(3, VOCAB, (b, 1)).astype(np.int32)
        step_logits, cache = model.apply(
            {"params": params}, cache, jnp.asarray(tok), method="step")
        cur[:, p + t] = tok[:, 0]
        pad_t = np.broadcast_to(
            np.arange(w)[None, :] >= p + t + 1, (b, w))
        dense = model.apply(
            {"params": params}, jnp.asarray(cur), jnp.asarray(pad_t))
        row = (p + t) - (w - min(cap, w))
        err = float(np.max(np.abs(
            np.asarray(step_logits, np.float32)
            - np.asarray(dense[:, row], np.float32))))
        assert err < 2e-5, f"{name} step {t}: parity error {err}"


def test_dense_forward_is_causal(tiny, rng):
    """Perturbing a suffix token must leave every earlier window row's
    logits EXACTLY unchanged — causality is structural, not approximate."""
    model, params = tiny
    b, l = 2, 24
    ids = rng.integers(3, VOCAB, (b, l)).astype(np.int32)
    pad = np.zeros((b, l), bool)
    base = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids), jnp.asarray(pad)))
    o = l - model.num_latents
    flip = 20
    ids2 = ids.copy()
    ids2[:, flip] = (ids2[:, flip] + 7) % (VOCAB - 3) + 3
    out2 = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids2), jnp.asarray(pad)))
    for i in range(base.shape[1]):
        if o + i < flip:
            np.testing.assert_array_equal(
                base[:, i], out2[:, i],
                err_msg=f"future token leaked into window row {i}")
    # and the perturbed position itself must move (the model is not inert)
    assert np.abs(base[:, flip - o:] - out2[:, flip - o:]).max() > 0


def test_prefill_width_invariance(tiny, rng):
    """The same prefix prefilled at two padded widths with the SAME
    latent-window anchor yields identical next-token logits — padding is
    masked dead weight, not signal."""
    model, params = tiny
    p, anchor = 9, 8  # window [8, w) fits num_latents=16 for both widths
    prefix = rng.integers(3, VOCAB, (1, p)).astype(np.int32)
    rows = []
    for w in (20, 24):
        ids = np.zeros((1, w), np.int32)
        ids[:, :p] = prefix
        pad = np.arange(w)[None, :] >= p
        logits, _ = model.apply(
            {"params": params}, jnp.asarray(ids), jnp.asarray(pad),
            length=jnp.asarray(p, jnp.int32), latent_offset=anchor,
            method="prefill")
        rows.append(np.asarray(logits[:, p - 1 - anchor], np.float32))
    np.testing.assert_allclose(rows[0], rows[1], atol=2e-5)


# -- the generation engine ----------------------------------------------------


@pytest.fixture(scope="module")
def generator(tiny):
    model, params = tiny
    return ARGenerator(model, params, max_seq_len=64, chunk=4, name="t-gen")


@pytest.mark.slow  # coverage retained: test_router_generate_chaos_drill
# pins the same position-folded re-encode property tier-1 — a SAMPLED
# stream split by a mid-stream kill continues byte-identically — and
# test_generate_session_fast_path pins the no-re-encode continuation
def test_generate_split_consistency(generator, rng):
    """Re-encoding from the prefix at a split point continues the identical
    SAMPLED stream (the position-folded key property) — including a cut
    that crosses an episode-grid re-prefill."""
    prefix = [int(t) for t in rng.integers(3, VOCAB, 9)]
    sampling = SamplingConfig(temperature=0.8, top_k=16, seed=3)
    full, _ = generator.generate(prefix, 12, sampling)
    assert len(full) == 12
    for cut in (3, 7):  # 7 = the width-16 episode boundary for a 9-prefix
        a, _ = generator.generate(prefix, cut, sampling)
        b, _ = generator.generate(prefix + a, 12 - cut, sampling)
        assert a + b == full, f"diverged at cut {cut}"


def test_generate_session_fast_path(generator, rng):
    """Passing the session back continues WITHOUT a re-encode and yields
    the same stream; a diverged session re-encodes instead of serving a
    stale cache."""
    prefix = [int(t) for t in rng.integers(3, VOCAB, 7)]
    sampling = SamplingConfig(temperature=0.8, top_k=16, seed=5)
    # 8 steps stay inside one episode (width 16 for a 7-token prefix), so
    # the resumed continuation must take ZERO further prefix encodes
    full, _ = generator.generate(prefix, 8, sampling)
    a, ses = generator.generate(prefix, 4, sampling)
    prefills_before = generator._m_prefills.value
    b, _ = generator.generate(prefix + a, 4, sampling, session=ses)
    assert a + b == full
    assert generator._m_prefills.value == prefills_before  # no re-encode
    # diverged prefix: the session must NOT be trusted
    other = [int(t) for t in rng.integers(3, VOCAB, 7)]
    c, _ = generator.generate(other, 4, sampling, session=ses)
    assert generator._m_prefills.value > prefills_before


def test_sampling_modes(generator, rng):
    prefix = [int(t) for t in rng.integers(3, VOCAB, 8)]
    greedy, _ = generator.generate(prefix, 8, SamplingConfig())
    greedy2, _ = generator.generate(prefix, 8, SamplingConfig(seed=99))
    assert greedy == greedy2  # temperature 0 ignores the seed
    s1, _ = generator.generate(prefix, 8, SamplingConfig(0.8, 16, seed=1))
    s2, _ = generator.generate(prefix, 8, SamplingConfig(0.8, 16, seed=2))
    assert s1 != s2  # different seeds diverge (astronomically likely)
    assert all(0 <= t < VOCAB for t in s1)
    with pytest.raises(ValueError):
        SamplingConfig(temperature=-1).normalized()


def test_session_store_contract():
    store = GenerateSessionStore(max_sessions=2, name="t")

    class FakeSession:
        def __init__(self, seq):
            self.seq = seq

    store.put("a", FakeSession([1, 2]))
    store.put("b", FakeSession([3]))
    assert store.match("a", [1, 2]).seq == [1, 2]
    assert store.match("a", [1, 2, 3]) is None   # diverged -> re-encode
    assert store.match(None, [1, 2]) is None
    store.put("c", FakeSession([4]))             # FIFO eviction
    assert store.match("a", [1, 2]) is None
    assert len(store) == 2
    store.clear()
    assert len(store) == 0


# -- serving: the streamed RPC + the chaos drill ------------------------------


def _make_fleet(model, params, names=("r0", "r1"), shared_gen=None):
    """In-process replicas. ``shared_gen``: one ARGenerator shared across
    replicas — it is stateless (sessions live in each app's store, the jit
    cache is thread-safe), so sharing is semantically a fleet whose
    replicas compiled the same programs, at one compile family's cost."""
    from perceiver_io_tpu.inference.engine import ServingEngine
    from perceiver_io_tpu.serving.replica import LocalReplica, ReplicaApp

    def apply_fn(p, token_ids, pad_mask):
        return model.apply({"params": p}, token_ids, pad_mask)

    reps = []
    for name in names:
        gen = shared_gen if shared_gen is not None else ARGenerator(
            model, params, max_seq_len=64, chunk=4, name=f"{name}-gen")
        eng = ServingEngine(apply_fn, params, name=f"{name}-inf",
                            max_batch=2)
        reps.append(LocalReplica(ReplicaApp(
            {"infer": eng}, params, name=name, assume_ready=True,
            generator=gen)))
    return reps


def test_generate_http_twin_parity(tiny, generator, rng):
    """The HTTP transport streams the same tokens the in-process engine
    produces (length-prefixed frames under chunked encoding), frames carry
    the per-step phase stamps, a session follow-up resumes over the wire,
    and the scrape surfaces the stateful class for autoscale/least-loaded
    placement."""
    from perceiver_io_tpu.serving.replica import (
        HttpReplicaClient,
        ReplicaServer,
    )

    model, params = tiny
    (remote,) = _make_fleet(model, params, names=("rem",),
                            shared_gen=generator)
    server = ReplicaServer(remote.app)
    client = HttpReplicaClient("rem", server.start())
    prefix = [int(t) for t in rng.integers(3, VOCAB, 8)]
    h_frames = []
    client.generate_stream(prefix, session="h", max_new=5, seed=4,
                           on_frame=h_frames.append)
    h_toks = [t for f in h_frames for t in f.get("tokens", [])]
    # transport parity: the wire stream equals the in-process engine (the
    # module generator shares the model/params — and its warm programs)
    want, _ = generator.generate(prefix, 5, SamplingConfig(seed=4))
    assert h_toks == want and len(h_toks) == 5
    # chunk frames carry the per-step phase stamps (tail attribution)
    chunk_frames = [f for f in h_frames if "tokens" in f]
    assert chunk_frames and h_frames[-1]["done"]
    assert all("chunk_ms" in f and "pos" in f for f in chunk_frames)
    s2 = client.generate_stream(prefix + h_toks, session="h", max_new=2,
                                seed=4)
    assert s2["resumed"] is True
    # scrape surfaces the stateful class for autoscale/least-loaded
    sc = client.scrape()
    assert sc["generate_sessions"] == 1
    assert sc["requests_total"] >= 2
    server.close()
    remote.app.close()


def test_router_generate_chaos_drill(tiny, generator, rng):
    """THE acceptance drill: streamed generate(session=...) through the
    router; the pinned replica is killed MID-STREAM; the stream reroutes,
    re-encodes from the accepted prefix on the survivor, and the assembled
    continuation equals the uninterrupted oracle exactly —
    lost_accepted=0 by content. Plus: the follow-up call resumes on the
    new pin, and retiring a replica tombstones its pins."""
    from perceiver_io_tpu.serving.router import Router

    model, params = tiny
    reps = _make_fleet(model, params, names=("c0", "c1"),
                       shared_gen=generator)
    by_name = {r.name: r for r in reps}
    router = Router(reps, name="chaos", scrape_interval_s=0.05)
    time.sleep(0.12)
    prefix = [int(t) for t in rng.integers(3, VOCAB, 9)]

    # the module generator doubles as the uninterrupted oracle (same
    # model/params, warm programs — no third compile family)
    oracle = generator
    want, _ = oracle.generate(prefix, 7, SamplingConfig(
        temperature=0.8, top_k=16, seed=11))

    got = []
    killed = {"name": None}

    def on_tokens(toks, frame):
        got.extend(toks)
        if len(got) >= 4 and killed["name"] is None:
            for name, r in by_name.items():
                if r.app._gen_active > 0:
                    killed["name"] = name
                    r.kill()

    res = router.generate(prefix, session="drill", max_new=7,
                          temperature=0.8, top_k=16, seed=11,
                          on_tokens=on_tokens)
    assert killed["name"] is not None, "the kill never landed mid-stream"
    assert res["tokens"] == want, "continuation diverged across the kill"
    assert got == want
    assert res["reroutes"] >= 1
    assert res["replica"] != killed["name"]
    # lost_accepted=0: every streamed token is in the final sequence, and
    # the router recorded no failed generate streams
    assert int(router._m_gen_failed.value) == 0

    # the pin moved to the survivor (a follow-up resumes there — the
    # resumed fast path itself is pinned by test_generate_http_twin_parity)
    pinned = router.pinned("drill")
    assert pinned == res["replica"]
    # tombstone: retiring the pinned replica drops its session pins
    router.remove_replica(pinned)
    assert router.pinned("drill") is None
    router.close()
    for r in reps:
        r.app.close()


@pytest.mark.slow  # coverage retained: test_router_generate_chaos_drill
# pins the kill/reroute/content contract on LocalReplicas; this variant
# only adds the real checkpoint + real train loop around the same path
def test_e2e_train_checkpoint_serve_stream(tmp_path, rng):
    """train_ar (synthetic, offline) → checkpoint → fleet serve → streamed
    session with a mid-stream kill → content-lossless continuation."""
    from perceiver_io_tpu.cli import train_ar
    from perceiver_io_tpu.data.imdb import IMDBDataModule
    from perceiver_io_tpu.inference.generate import load_ar_checkpoint
    from perceiver_io_tpu.serving.router import Router

    # batch divisible by the conftest's 8-device data axis
    run_dir = train_ar.main([
        "--synthetic", "--max_steps", "8", "--batch_size", "8",
        "--max_seq_len", "48", "--vocab_size", "200",
        "--synthetic_size", "32", "--num_latents", "16",
        "--num_latent_channels", "32", "--num_encoder_layers", "2",
        "--num_self_attention_layers_per_block", "1",
        "--logdir", str(tmp_path), "--root", str(tmp_path / "data"),
        "--dtype", "float32", "--sample_prefix_len", "0",
    ])
    dm = IMDBDataModule(root=str(tmp_path / "data"), max_seq_len=48,
                        vocab_size=200, batch_size=4, synthetic=True,
                        synthetic_size=32)
    dm.prepare_data()
    dm.setup()
    model, params, msl = load_ar_checkpoint(
        str(Path(str(run_dir)) / "checkpoints"), dm.tokenizer)
    from perceiver_io_tpu.inference.engine import ServingEngine
    from perceiver_io_tpu.serving.replica import LocalReplica, ReplicaApp

    def apply_fn(p, token_ids, pad_mask):
        return model.apply({"params": p}, token_ids, pad_mask)

    reps = []
    for name in ("e0", "e1"):
        gen = ARGenerator(model, params, max_seq_len=msl, chunk=4,
                          name=f"{name}-gen")
        eng = ServingEngine(apply_fn, params, name=f"{name}-inf",
                            max_batch=2)
        reps.append(LocalReplica(ReplicaApp(
            {"infer": eng}, params, name=name, assume_ready=True,
            generator=gen)))
    by_name = {r.name: r for r in reps}
    router = Router(reps, name="e2e", scrape_interval_s=0.05)
    time.sleep(0.12)
    prefix = dm.tokenizer.encode_ids("the movie was")[:8] or [5, 6, 7]
    oracle = ARGenerator(model, params, max_seq_len=msl, chunk=4,
                         name="e-oracle")
    want, _ = oracle.generate(prefix, 12, SamplingConfig(seed=3))

    got = []
    killed = {"name": None}

    def on_tokens(toks, frame):
        got.extend(toks)
        if len(got) >= 4 and killed["name"] is None:
            for name, r in by_name.items():
                if r.app._gen_active > 0:
                    killed["name"] = name
                    r.kill()

    res = router.generate(prefix, session="e2e", max_new=12, seed=3,
                          on_tokens=on_tokens)
    assert res["tokens"] == want and got == want
    assert killed["name"] is not None and res["reroutes"] >= 1
    assert int(router._m_gen_failed.value) == 0
    router.close()
    for r in reps:
        r.app.close()


def test_generate_drain_refuses_new_streams(tiny, generator, rng):
    from perceiver_io_tpu.resilience import RejectedError

    model, params = tiny
    (rep,) = _make_fleet(model, params, names=("d0",),
                         shared_gen=generator)
    prefix = [int(t) for t in rng.integers(3, VOCAB, 6)]
    assert rep.app.drain(timeout_s=5.0)
    with pytest.raises(RejectedError):
        rep.app.generate(prefix, max_new=2)
    rep.app.resume()
    rep.app.generate(prefix, max_new=2)  # admitted again
    rep.app.close()
