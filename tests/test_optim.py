"""Optimizer / LR-schedule parity against torch (reference lightning.py:59-79)."""

import numpy as np
import pytest
import torch

import jax.numpy as jnp
import optax

from perceiver_io_tpu.training.optim import OptimizerConfig, make_optimizer


def test_one_cycle_requires_max_steps():
    with pytest.raises(ValueError, match="max_steps"):
        make_optimizer(OptimizerConfig(one_cycle_lr=True, max_steps=None))


def test_unknown_optimizer():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(OptimizerConfig(optimizer="LBFGS"))


def test_one_cycle_schedule_matches_torch():
    total, max_lr, pct = 200, 3e-3, 0.1
    _, schedule = make_optimizer(
        OptimizerConfig(learning_rate=max_lr, one_cycle_lr=True,
                        one_cycle_pct_start=pct, max_steps=total)
    )

    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.Adam([p], lr=max_lr)
    sched = torch.optim.lr_scheduler.OneCycleLR(
        opt, max_lr=max_lr, pct_start=pct, total_steps=total, cycle_momentum=False
    )
    torch_lrs = []
    for _ in range(total):
        torch_lrs.append(opt.param_groups[0]["lr"])
        opt.step()
        sched.step()

    ours = [float(schedule(i)) for i in range(total)]
    np.testing.assert_allclose(ours, torch_lrs, rtol=5e-4, atol=1e-10)


def _run_optax(tx, w0, grads_seq):
    w = jnp.asarray(w0)
    st = tx.init(w)
    out = []
    for g in grads_seq:
        updates, st = tx.update(jnp.asarray(g), st, w)
        w = optax.apply_updates(w, updates)
        out.append(np.asarray(w).copy())
    return out


def _run_torch(opt_cls, w0, grads_seq, **kwargs):
    p = torch.nn.Parameter(torch.tensor(w0))
    opt = opt_cls([p], **kwargs)
    out = []
    for g in grads_seq:
        opt.zero_grad()
        p.grad = torch.tensor(g)
        opt.step()
        out.append(p.detach().numpy().copy())
    return out


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adam_matches_torch(rng, wd):
    """'Adam' = coupled L2 weight decay, exactly torch.optim.Adam."""
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(10)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="Adam", learning_rate=1e-2, weight_decay=wd)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.Adam, w0, grads, lr=1e-2, weight_decay=wd)
    np.testing.assert_allclose(ours[-1], theirs[-1], rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adamw_matches_torch(rng, wd):
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(10)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="AdamW", learning_rate=1e-2, weight_decay=wd)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.AdamW, w0, grads, lr=1e-2, weight_decay=wd)
    np.testing.assert_allclose(ours[-1], theirs[-1], rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("wd", [0.0, 0.1])
@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_sgd_matches_torch(rng, wd, momentum):
    """'SGD' incl. momentum-buffer semantics (buf = m·buf + g, step lr·buf)
    and coupled L2 weight decay — reference lightning.py:60 getattr surface."""
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(10)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="SGD", learning_rate=1e-2, weight_decay=wd,
                        momentum=momentum)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.SGD, w0, grads, lr=1e-2, weight_decay=wd,
                        momentum=momentum)
    np.testing.assert_allclose(ours[-1], theirs[-1], rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_rmsprop_matches_torch(rng, wd):
    """'RMSprop' with torch defaults (alpha 0.99, eps 1e-8 outside the sqrt)."""
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(10)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="RMSprop", learning_rate=1e-2, weight_decay=wd)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.RMSprop, w0, grads, lr=1e-2, weight_decay=wd)
    np.testing.assert_allclose(ours[-1], theirs[-1], rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adagrad_matches_torch(rng, wd):
    """'Adagrad' with torch defaults (eps 1e-10 outside the sqrt, zero
    initial accumulator) — incl. the first step, where optax's scale_by_rss
    would diverge from torch."""
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(10)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="Adagrad", learning_rate=1e-2, weight_decay=wd)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.Adagrad, w0, grads, lr=1e-2, weight_decay=wd)
    for step_ours, step_theirs in zip(ours, theirs):
        np.testing.assert_allclose(step_ours, step_theirs, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adamax_matches_torch(rng, wd):
    """'Adamax' with torch defaults (infinity norm with eps inside the max,
    first-moment bias correction only, coupled L2 weight decay)."""
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(10)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="Adamax", learning_rate=1e-2, weight_decay=wd)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.Adamax, w0, grads, lr=1e-2, weight_decay=wd)
    for step_ours, step_theirs in zip(ours, theirs):
        np.testing.assert_allclose(step_ours, step_theirs, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_nadam_matches_torch(rng, wd):
    """'NAdam' with torch defaults — the 0.96^(t·ψ) momentum-decay schedule
    and the running mu_product are torch-specific (optax's nesterov Adam is
    Dozat's formulation without them); coupled L2 weight decay (torch's
    decoupled_weight_decay=False default)."""
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(12)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="NAdam", learning_rate=2e-3, weight_decay=wd)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.NAdam, w0, grads, lr=2e-3, weight_decay=wd)
    for step_ours, step_theirs in zip(ours, theirs):
        np.testing.assert_allclose(step_ours, step_theirs, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_radam_matches_torch(rng, wd):
    """'RAdam' with torch defaults. Runs long enough to cross the rho_t > 5
    rectification boundary (at beta2=0.999 the first 4 steps are the
    unrectified SGD-momentum branch, step 5+ the rectified adaptive one), so
    both branches and the switch itself are covered."""
    w0 = rng.standard_normal(16).astype(np.float32)
    grads = [rng.standard_normal(16).astype(np.float32) for _ in range(12)]
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="RAdam", learning_rate=1e-2, weight_decay=wd)
    )
    ours = _run_optax(tx, w0, grads)
    theirs = _run_torch(torch.optim.RAdam, w0, grads, lr=1e-2, weight_decay=wd)
    for step_ours, step_theirs in zip(ours, theirs):
        # rtol 5e-5, not the 1e-5 of the other optimizers: torch evaluates
        # the rho_t/rect scalars in python f64, while under jit they are f32
        # — near the rectification boundary (rho_inf - ~rho_inf cancellation)
        # that costs a few ulps more than the elementwise-only updates
        np.testing.assert_allclose(step_ours, step_theirs, rtol=5e-5, atol=1e-7)


def test_unknown_optimizer_error_lists_supported_set():
    """The reference accepts any torch.optim name via getattr; this repo's
    deliberate narrowing must fail with the full supported list and a
    pointer to the migration doc, not just 'unknown'."""
    with pytest.raises(ValueError) as e:
        make_optimizer(OptimizerConfig(optimizer="LBFGS"))
    msg = str(e.value)
    for name in ("Adam", "AdamW", "SGD", "RMSprop", "Adagrad",
                 "Adamax", "NAdam", "RAdam"):
        assert name in msg
    assert "MIGRATION.md" in msg


def test_constant_schedule_without_one_cycle():
    _, schedule = make_optimizer(OptimizerConfig(learning_rate=5e-4))
    assert float(schedule(0)) == pytest.approx(5e-4)
    assert float(schedule(10_000)) == pytest.approx(5e-4)


def test_grad_clip_norm():
    tx, _ = make_optimizer(
        OptimizerConfig(optimizer="AdamW", learning_rate=1.0, grad_clip_norm=1.0)
    )
    params = {"w": jnp.zeros(4)}
    state = tx.init(params)
    huge = {"w": jnp.full(4, 1e6)}
    updates, _ = tx.update(huge, state, params)
    # clipped to unit norm before Adam: finite, sane update
    assert np.isfinite(np.asarray(updates["w"])).all()

    with pytest.raises(ValueError, match="grad_clip_norm"):
        make_optimizer(OptimizerConfig(grad_clip_norm=-1.0))


def test_accumulate_steps_averages_micro_batches(rng):
    """k micro-steps with accumulation ≡ one step on the mean gradient."""
    k = 4
    params = {"w": jnp.asarray(rng.normal(0, 1, (8,)), jnp.float32)}
    grads = [
        {"w": jnp.asarray(rng.normal(0, 1, (8,)), jnp.float32)} for _ in range(k)
    ]
    mean_grad = {"w": sum(g["w"] for g in grads) / k}

    ref_tx, _ = make_optimizer(OptimizerConfig(optimizer="AdamW", learning_rate=1e-2))
    ref_state = ref_tx.init(params)
    ref_updates, _ = ref_tx.update(mean_grad, ref_state, params)
    ref_params = optax.apply_updates(params, ref_updates)

    acc_tx, _ = make_optimizer(
        OptimizerConfig(optimizer="AdamW", learning_rate=1e-2, accumulate_steps=k)
    )
    acc_state = acc_tx.init(params)
    acc_params = params
    for i, g in enumerate(grads):
        updates, acc_state = acc_tx.update(g, acc_state, acc_params)
        acc_params = optax.apply_updates(acc_params, updates)
        if i < k - 1:
            # no-op micro steps: params unchanged until the k-th
            np.testing.assert_allclose(
                np.asarray(acc_params["w"]), np.asarray(params["w"]), atol=1e-7
            )
    np.testing.assert_allclose(
        np.asarray(acc_params["w"]), np.asarray(ref_params["w"]), atol=1e-6
    )

    with pytest.raises(ValueError, match="accumulate_steps"):
        make_optimizer(OptimizerConfig(accumulate_steps=0))


def test_accumulate_steps_schedule_counts_optimizer_updates():
    k, total = 4, 40
    _, schedule = make_optimizer(
        OptimizerConfig(learning_rate=1e-2, one_cycle_lr=True, max_steps=total,
                        accumulate_steps=k)
    )
    _, ref_schedule = make_optimizer(
        OptimizerConfig(learning_rate=1e-2, one_cycle_lr=True, max_steps=total // k)
    )
    # micro-step s maps onto optimizer update s // k
    for s in (0, 3, 4, 17, 39):
        np.testing.assert_allclose(
            float(schedule(s)), float(ref_schedule(s // k)), rtol=1e-6
        )
