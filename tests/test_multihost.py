"""Multi-host simulation: 2 real ``jax.distributed`` CPU processes.

VERDICT r1 weak-spot 4: ``initialize_distributed``, per-host loader shards,
the ``next_version_dir`` process-0 broadcast, weighted eval reduction, and
multi-host checkpointing had never executed with ``jax.process_count() > 1``.
This spawns two subprocess workers (2 virtual CPU devices each → a 4-device
global mesh) over a localhost coordinator and cross-checks their reports.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def reports(tmp_path_factory):
    workdir = tmp_path_factory.mktemp("multihost")
    # pre-seed the version-dir scan so both ranks see version_0 locally
    os.makedirs(workdir / "logs" / "exp" / "version_0")
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # each worker forces CPU itself (ensure_cpu_only) before jax init
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--rank", str(r), "--nprocs", "2",
             "--port", str(port), "--workdir", str(workdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
    loaded = []
    for r in range(2):
        with open(workdir / f"rank{r}.json") as f:
            loaded.append(json.load(f))
    return workdir, loaded


def test_distributed_topology(reports):
    _, (r0, r1) = reports
    assert r0["process_count"] == r1["process_count"] == 2
    assert {r0["process_index"], r1["process_index"]} == {0, 1}
    assert r0["local_devices"] == r1["local_devices"] == 2
    assert r0["global_devices"] == r1["global_devices"] == 4


def test_hybrid_dcn_mesh_granules_are_process_local(reports):
    """make_mesh(dcn_dp=2) on 2 real processes: each data-axis row must hold
    exactly one process's devices (tp collectives never cross the slow
    network), DCN-major — row 0 is process 0, row 1 is process 1."""
    _, (r0, r1) = reports
    assert r0["hybrid_rows_process"] == r1["hybrid_rows_process"] == [[0], [1]]


def test_loader_shards_disjoint_and_complete(reports):
    _, (r0, r1) = reports
    s0, s1 = set(r0["shard_items"]), set(r1["shard_items"])
    assert s0 and s1
    assert not (s0 & s1)
    assert s0 | s1 == set(range(64))


def test_version_dir_agrees_despite_divergent_scans(reports):
    workdir, (r0, r1) = reports
    # rank 1's local scan was made to lie (fake version_7 → local n=8);
    # the process-0 broadcast must still force agreement on version_1
    assert r0["version_dir"] == r1["version_dir"]
    assert r0["version_dir"].endswith("version_1")


def test_eval_metrics_identical_across_hosts(reports):
    _, (r0, r1) = reports
    assert r0["val_metrics"].keys() == r1["val_metrics"].keys()
    assert "val_loss" in r0["val_metrics"]
    for k in r0["val_metrics"]:
        assert abs(r0["val_metrics"][k] - r1["val_metrics"][k]) < 1e-9, k


def test_checkpoint_written_once_and_loadable(reports):
    workdir, (r0, r1) = reports
    assert r0["ckpt_steps"] == r1["ckpt_steps"]
    assert len(r0["ckpt_steps"]) >= 1
    ckpt_dir = workdir / "run" / "checkpoints"
    step_dirs = [d for d in os.listdir(ckpt_dir) if d.isdigit()]
    assert len(step_dirs) == len(r0["ckpt_steps"])
    # exactly one copy on disk (both ranks wrote collaboratively, not twice):
    # Orbax's commit manifest exists and is unique per step
    for d in step_dirs:
        assert os.path.exists(ckpt_dir / d / "_CHECKPOINT_METADATA")


def _run_spawn_hosts(tmp_path, extra_args, max_steps=3,
                     synthetic_size=32, seq=32):
    """Launch train_mlm via --spawn_hosts 2 on the shared tiny model and
    return (completed process, combined output tail, parsed train losses)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    logdir = tmp_path / "logs"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "train", "train_mlm.py"),
         "--spawn_hosts", "2", "--synthetic",
         "--synthetic_size", str(synthetic_size),
         "--batch_size", "16", "--max_seq_len", str(seq),
         "--vocab_size", "90",
         "--num_latents", "8", "--num_latent_channels", "16",
         "--num_encoder_layers", "2",
         "--num_self_attention_layers_per_block", "1",
         "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
         "--dtype", "float32", "--max_steps", str(max_steps),
         "--log_every_n_steps", "1",
         "--logdir", str(logdir), "--root", str(tmp_path / "cache"),
         *extra_args],
        env=env, capture_output=True, text=True, timeout=600,
    )
    tail = (proc.stdout + proc.stderr)[-4000:]
    losses = []
    metrics = list(logdir.glob("mlm/version_*/metrics.jsonl"))
    if metrics:
        rows = [json.loads(l) for l in open(metrics[0])]
        losses = [r["train_loss"] for r in rows if "train_loss" in r]
    return proc, tail, losses


@pytest.mark.slow  # launcher UX variant; real 2-process jax.distributed
# coverage stays tier-1 via the reports-fixture tests above
def test_spawn_hosts_single_command_launch(tmp_path):
    """--spawn_hosts 2: ONE command forks both ranks with coordinator flags
    (the reference's one-command DDP UX, train_mlm.py:102-103). The launcher
    must exit 0, both ranks must join a process_count=2 cluster, and rank 0
    must produce a normal run dir with finite losses."""
    import numpy as np

    proc, tail, losses = _run_spawn_hosts(tmp_path, [])
    assert proc.returncode == 0, tail
    assert "launched 2 processes" in proc.stderr, tail
    assert "[distributed] process 0/2" in proc.stderr, tail
    assert losses and np.isfinite(losses).all(), tail


@pytest.mark.slow  # deep spawn variant (slow, like all spawn tests);
# real 2-process coverage stays tier-1 via the reports-fixture tests
def test_spawn_hosts_buckets_and_multi_step_dispatch(tmp_path):
    """The r3 exclusivity is gone: --bucket_widths x --steps_per_dispatch 2 x
    2 real processes trains end to end (loader-decided global widths keep
    hosts in shape lockstep; K-grouped same-width runs keep dispatch windows
    homogeneous)."""
    import numpy as np

    proc, tail, losses = _run_spawn_hosts(
        tmp_path,
        ["--bucket_widths", "128", "--length_sort_window", "2",
         "--steps_per_dispatch", "2"],
        max_steps=4, synthetic_size=64, seq=256,
    )
    assert proc.returncode == 0, tail
    assert losses and np.isfinite(losses).all(), tail


@pytest.mark.slow  # deep spawn variant (slow, like all spawn tests);
# real 2-process coverage stays tier-1 via the reports-fixture tests
def test_spawn_hosts_sequence_parallel_kernel_path(tmp_path):
    """2 real processes x --sp 2 --shard_seq --attn_impl pallas_sp: the
    distributed-flash route (shard_map'd kernel, S/n KV per device) trains
    across a multi-host mesh — the long-context deployment shape. The sp
    gradient canary must skip itself on multi-host (it probes eagerly with
    host-local arrays) without blocking the run."""
    import numpy as np

    proc, tail, losses = _run_spawn_hosts(
        tmp_path,
        ["--sp", "2", "--shard_seq", "--attn_impl", "pallas_sp"],
        max_steps=2, synthetic_size=64,
    )
    assert proc.returncode == 0, tail
    assert losses and np.isfinite(losses).all(), tail
