"""Multi-host simulation: 2 real ``jax.distributed`` CPU processes.

VERDICT r1 weak-spot 4: ``initialize_distributed``, per-host loader shards,
the ``next_version_dir`` process-0 broadcast, weighted eval reduction, and
multi-host checkpointing had never executed with ``jax.process_count() > 1``.
This spawns two subprocess workers (2 virtual CPU devices each → a 4-device
global mesh) over a localhost coordinator and cross-checks their reports.
"""

import json
import os
import signal
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def reports(tmp_path_factory):
    workdir = tmp_path_factory.mktemp("multihost")
    # pre-seed the version-dir scan so both ranks see version_0 locally
    os.makedirs(workdir / "logs" / "exp" / "version_0")
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # each worker forces CPU itself (ensure_cpu_only) before jax init
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--rank", str(r), "--nprocs", "2",
             "--port", str(port), "--workdir", str(workdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
    loaded = []
    for r in range(2):
        with open(workdir / f"rank{r}.json") as f:
            loaded.append(json.load(f))
    return workdir, loaded


def test_distributed_topology(reports):
    _, (r0, r1) = reports
    assert r0["process_count"] == r1["process_count"] == 2
    assert {r0["process_index"], r1["process_index"]} == {0, 1}
    assert r0["local_devices"] == r1["local_devices"] == 2
    assert r0["global_devices"] == r1["global_devices"] == 4


def test_hybrid_dcn_mesh_granules_are_process_local(reports):
    """make_mesh(dcn_dp=2) on 2 real processes: each data-axis row must hold
    exactly one process's devices (tp collectives never cross the slow
    network), DCN-major — row 0 is process 0, row 1 is process 1."""
    _, (r0, r1) = reports
    assert r0["hybrid_rows_process"] == r1["hybrid_rows_process"] == [[0], [1]]


def test_loader_shards_disjoint_and_complete(reports):
    _, (r0, r1) = reports
    s0, s1 = set(r0["shard_items"]), set(r1["shard_items"])
    assert s0 and s1
    assert not (s0 & s1)
    assert s0 | s1 == set(range(64))


def test_version_dir_agrees_despite_divergent_scans(reports):
    workdir, (r0, r1) = reports
    # rank 1's local scan was made to lie (fake version_7 → local n=8);
    # the process-0 broadcast must still force agreement on version_1
    assert r0["version_dir"] == r1["version_dir"]
    assert r0["version_dir"].endswith("version_1")


def test_eval_metrics_identical_across_hosts(reports):
    _, (r0, r1) = reports
    assert r0["val_metrics"].keys() == r1["val_metrics"].keys()
    assert "val_loss" in r0["val_metrics"]
    for k in r0["val_metrics"]:
        assert abs(r0["val_metrics"][k] - r1["val_metrics"][k]) < 1e-9, k


def test_checkpoint_written_once_and_loadable(reports):
    workdir, (r0, r1) = reports
    assert r0["ckpt_steps"] == r1["ckpt_steps"]
    assert len(r0["ckpt_steps"]) >= 1
    ckpt_dir = workdir / "run" / "checkpoints"
    step_dirs = [d for d in os.listdir(ckpt_dir) if d.isdigit()]
    assert len(step_dirs) == len(r0["ckpt_steps"])
    # exactly one copy on disk (both ranks wrote collaboratively, not twice):
    # Orbax's commit manifest exists and is unique per step
    for d in step_dirs:
        assert os.path.exists(ckpt_dir / d / "_CHECKPOINT_METADATA")


def _run_spawn_hosts(tmp_path, extra_args, max_steps=3,
                     synthetic_size=32, seq=32):
    """Launch train_mlm via --spawn_hosts 2 on the shared tiny model and
    return (completed process, combined output tail, parsed train losses)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    logdir = tmp_path / "logs"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "train", "train_mlm.py"),
         "--spawn_hosts", "2", "--synthetic",
         "--synthetic_size", str(synthetic_size),
         "--batch_size", "16", "--max_seq_len", str(seq),
         "--vocab_size", "90",
         "--num_latents", "8", "--num_latent_channels", "16",
         "--num_encoder_layers", "2",
         "--num_self_attention_layers_per_block", "1",
         "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
         "--dtype", "float32", "--max_steps", str(max_steps),
         "--log_every_n_steps", "1",
         "--logdir", str(logdir), "--root", str(tmp_path / "cache"),
         *extra_args],
        env=env, capture_output=True, text=True, timeout=600,
    )
    tail = (proc.stdout + proc.stderr)[-4000:]
    losses = []
    metrics = list(logdir.glob("mlm/version_*/metrics.jsonl"))
    if metrics:
        rows = [json.loads(l) for l in open(metrics[0])]
        losses = [r["train_loss"] for r in rows if "train_loss" in r]
    return proc, tail, losses


@pytest.mark.slow  # launcher UX variant; real 2-process jax.distributed
# coverage stays tier-1 via the reports-fixture tests above
def test_spawn_hosts_single_command_launch(tmp_path):
    """--spawn_hosts 2: ONE command forks both ranks with coordinator flags
    (the reference's one-command DDP UX, train_mlm.py:102-103). The launcher
    must exit 0, both ranks must join a process_count=2 cluster, and rank 0
    must produce a normal run dir with finite losses."""
    import numpy as np

    proc, tail, losses = _run_spawn_hosts(tmp_path, [])
    assert proc.returncode == 0, tail
    assert "launched 2 processes" in proc.stderr, tail
    assert "[distributed] process 0/2" in proc.stderr, tail
    assert losses and np.isfinite(losses).all(), tail


@pytest.mark.slow  # deep spawn variant (slow, like all spawn tests);
# real 2-process coverage stays tier-1 via the reports-fixture tests
def test_spawn_hosts_buckets_and_multi_step_dispatch(tmp_path):
    """The r3 exclusivity is gone: --bucket_widths x --steps_per_dispatch 2 x
    2 real processes trains end to end (loader-decided global widths keep
    hosts in shape lockstep; K-grouped same-width runs keep dispatch windows
    homogeneous)."""
    import numpy as np

    proc, tail, losses = _run_spawn_hosts(
        tmp_path,
        ["--bucket_widths", "128", "--length_sort_window", "2",
         "--steps_per_dispatch", "2"],
        max_steps=4, synthetic_size=64, seq=256,
    )
    assert proc.returncode == 0, tail
    assert losses and np.isfinite(losses).all(), tail


# -- r19: multi-host training fault tolerance ---------------------------------


@pytest.fixture(scope="module")
def recovery_reports(tmp_path_factory):
    """Two real jax.distributed CPU processes through the r19 recovery
    drills (multihost_worker.py --phase recovery). Only slow-marked tests
    consume this, so tier-1 wall is untouched — the in-process agreement /
    preemption / bounded-exit units live in tests/test_multihost_recovery.py.
    """
    workdir = tmp_path_factory.mktemp("multihost_recovery")
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--rank", str(r), "--nprocs", "2",
             "--port", str(port), "--workdir", str(workdir),
             "--phase", "recovery"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"recovery worker failed:\n{out[-4000:]}"
    loaded = []
    for r in range(2):
        with open(workdir / f"rank{r}_recovery.json") as f:
            loaded.append(json.load(f))
    return workdir, loaded


@pytest.mark.slow  # 2-process cluster drill; tier-1 keeps the agreement
# math + device-side skip units in tests/test_multihost_recovery.py
def test_nan_on_one_host_skips_same_step_on_both(recovery_reports):
    """The psum-agreement acceptance drill: PIT_FAULTS corrupts ONE host's
    batch shard, and BOTH hosts must skip the same step — bit-identical
    final params, identical skip counts, identical step counters."""
    _, (r0, r1) = recovery_reports
    assert r0["agree_bad_steps"] == r1["agree_bad_steps"] == 1
    assert r0["agree_step"] == r1["agree_step"] == 6
    assert r0["agree_w"] == r1["agree_w"]  # bit-identical trajectories
    assert all(abs(w) > 0 for w in r0["agree_w"])  # it actually trained


@pytest.mark.slow  # 2-process cluster drill; tier-1 keeps the coordinated
# preemption plumbing unit (force_coordination) in test_multihost_recovery.py
def test_sigterm_on_one_host_coordinates_save_on_all(recovery_reports):
    """SIGTERM lands on rank 1 ONLY; the agreement channel must carry the
    preemption to rank 0, every rank saves the SAME last/ step, counts one
    preempt save, and exits 0 (the fixture already asserted return codes)."""
    _, (r0, r1) = recovery_reports
    assert r0["preempt_step"] == r1["preempt_step"] > 0
    assert r0["preempt_step"] < 40  # stopped well before the schedule end
    assert r0["preempt_saves"] == r1["preempt_saves"] == 1
    assert r0["preempt_last_steps"] == r1["preempt_last_steps"] \
        == [r0["preempt_step"]]
    # the KV peer-liveness round saw both hosts alive throughout drill A
    assert r0["peer_events_mid"] == r1["peer_events_mid"] == []


_DRILL_MODULE = None


def _drill_helpers():
    """The chaos-drill plumbing (pid-of-rank /proc scan, poll-until,
    metrics.jsonl merge) lives in tools/multihost_drill.py — ONE source, so
    the measured drill and these pinned tests can never scan different
    things. Loaded lazily (only the slow drills pay the import) and ONCE
    (the wrappers run inside 50 ms poll loops)."""
    global _DRILL_MODULE
    if _DRILL_MODULE is None:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "multihost_drill",
            os.path.join(REPO, "tools", "multihost_drill.py"))
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _DRILL_MODULE = module
    return _DRILL_MODULE


def _find_spawned_rank_pid(rank: int):
    return _drill_helpers()._pid_of_rank(rank)


def _wait_for(predicate, timeout_s, poll_s=0.05):
    return _drill_helpers().wait_for(predicate, timeout_s, poll_s)


def _read_losses(logdir):
    return _drill_helpers()._losses(str(logdir))


_TINY_MLM = [
    "--synthetic", "--synthetic_size", "64", "--batch_size", "16",
    "--max_seq_len", "32", "--vocab_size", "90", "--num_latents", "8",
    "--num_latent_channels", "16", "--num_encoder_layers", "2",
    "--num_self_attention_layers_per_block", "1",
    "--num_cross_attention_heads", "2", "--num_self_attention_heads", "2",
    "--dtype", "float32", "--log_every_n_steps", "1",
]


def _spawned_mlm_cmd(tmp_path, extra):
    return [sys.executable, os.path.join(REPO, "train", "train_mlm.py"),
            "--spawn_hosts", "2", *_TINY_MLM,
            "--logdir", str(tmp_path / "logs"),
            "--root", str(tmp_path / "cache"), *extra]


@pytest.mark.slow  # full-stack chaos drill (kill -9 + world restart ≈ two
# spawned cluster runs); the supervisor policy itself is tier-1 with fake
# children in tests/test_multihost_recovery.py
def test_spawn_supervisor_restarts_world_after_kill9(tmp_path):
    """Kill -9 one of two spawned hosts mid-fit: the supervisor kills the
    world, relaunches all ranks with --resume from the newest checkpoint,
    the job completes with exit 0, and the final loss trajectory matches an
    uninterrupted run at checkpoint granularity."""
    import numpy as np

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # throttle steps so the kill window after the first checkpoint is wide
    env["PIT_FAULTS"] = "trainer.collective:slow@every:1@delay:0.4"
    schedule = ["--max_steps", "10", "--eval_every_n_steps", "2",
                "--max_to_keep", "3", "--step_timeout_s", "8"]

    # the uninterrupted reference (same seed, same schedule, no kill)
    ref = subprocess.run(
        _spawned_mlm_cmd(tmp_path / "ref", schedule),
        env=env, capture_output=True, text=True, timeout=600)
    assert ref.returncode == 0, (ref.stdout + ref.stderr)[-4000:]
    ref_losses = _read_losses(tmp_path / "ref" / "logs")
    assert set(ref_losses) == set(range(1, 11))

    proc = subprocess.Popen(
        _spawned_mlm_cmd(tmp_path / "chaos", schedule
                         + ["--spawn_attempts", "3"]),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        # wait for a COMMITTED checkpoint through the supervisor's own
        # scanner (an in-flight orbax tmp dir must not count — the drill
        # needs the restart to actually resume)
        from perceiver_io_tpu.cli.common import _newest_resumable_run

        committed = _wait_for(
            lambda: _newest_resumable_run(
                str(tmp_path / "chaos" / "logs"), "mlm"),
            timeout_s=240)
        assert committed, "no checkpoint committed before the kill window"
        victim = _wait_for(lambda: _find_spawned_rank_pid(1), timeout_s=30)
        assert victim, "spawned rank-1 process not found"
        os.kill(victim, signal.SIGKILL)
        out, err = proc.communicate(timeout=480)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, (out + err)[-4000:]
    assert "restarting all 2 hosts" in err, err[-4000:]
    assert "--resume" in err, err[-4000:]
    chaos_losses = _read_losses(tmp_path / "chaos" / "logs")
    assert set(chaos_losses) >= set(range(1, 11)), sorted(chaos_losses)
    # checkpoint-granularity trajectory parity: every step's (final) loss
    # matches the uninterrupted run — the resumed world replayed the exact
    # batches the dead one would have seen (deterministic resume). rtol:
    # null-controlled clean repros are BIT-identical, but loaded
    # multi-process CPU runs occasionally show reassociation-order drift in
    # the cross-host reductions (measured ≤2.5e-4 relative over 10 steps);
    # a wrong-checkpoint resume or a skipped batch moves losses by >>1e-2
    for step in sorted(ref_losses):
        np.testing.assert_allclose(
            chaos_losses[step], ref_losses[step], rtol=1e-3,
            err_msg=f"step {step} diverged after the world restart")


@pytest.mark.slow  # full-stack preemption drill (spawned cluster + resume
# run); the coordinated-save plumbing is tier-1 in test_multihost_recovery.py
def test_spawn_sigterm_preempts_cleanly_and_resumes(tmp_path):
    """SIGTERM one spawned host mid-fit: the preemption is agreed cross-host,
    every rank saves and exits 0 (launcher exit 0, no restart), and --resume
    continues from the preemption step to schedule end."""
    import numpy as np

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PIT_FAULTS"] = "trainer.collective:slow@every:1@delay:0.4"
    schedule = ["--max_steps", "12"]
    proc = subprocess.Popen(
        _spawned_mlm_cmd(tmp_path, schedule),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        logdir = tmp_path / "logs"
        # wait until training is demonstrably underway on rank 0
        started = _wait_for(
            lambda: len(_read_losses(logdir)) >= 2, timeout_s=240)
        assert started, "training never produced metrics rows"
        victim = _wait_for(lambda: _find_spawned_rank_pid(1), timeout_s=30)
        assert victim, "spawned rank-1 process not found"
        os.kill(victim, signal.SIGTERM)
        out, err = proc.communicate(timeout=480)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, (out + err)[-4000:]
    assert "restarting" not in err  # a clean preemption is NOT a failure
    losses = _read_losses(logdir)
    preempt_step = max(losses)
    assert preempt_step < 12, "run completed before the preemption landed"
    run_dir = sorted(logdir.glob("mlm/version_*"))[0]
    last = run_dir / "checkpoints" / "last" / str(preempt_step)
    assert last.is_dir(), f"no coordinated last/ save at {preempt_step}"

    resumed = subprocess.run(
        _spawned_mlm_cmd(tmp_path, schedule + ["--resume", str(run_dir)]),
        env=env, capture_output=True, text=True, timeout=600)
    assert resumed.returncode == 0, (resumed.stdout + resumed.stderr)[-4000:]
    final = _read_losses(logdir)
    assert set(final) >= set(range(preempt_step, 13)) - {0}
    assert max(final) == 12
    assert np.isfinite(list(final.values())).all()


@pytest.mark.slow  # deep spawn variant (slow, like all spawn tests);
# real 2-process coverage stays tier-1 via the reports-fixture tests
def test_spawn_hosts_sequence_parallel_kernel_path(tmp_path):
    """2 real processes x --sp 2 --shard_seq --attn_impl pallas_sp: the
    distributed-flash route (shard_map'd kernel, S/n KV per device) trains
    across a multi-host mesh — the long-context deployment shape. The sp
    gradient canary must skip itself on multi-host (it probes eagerly with
    host-local arrays) without blocking the run."""
    import numpy as np

    proc, tail, losses = _run_spawn_hosts(
        tmp_path,
        ["--sp", "2", "--shard_seq", "--attn_impl", "pallas_sp"],
        max_steps=2, synthetic_size=64,
    )
    assert proc.returncode == 0, tail
    assert losses and np.isfinite(losses).all(), tail
