"""Weight-only int8 quantization: roundtrip bounds, tree key-path identity,
engine parity vs the f32 oracle on the tiny preset, and sharding-rule
resolution against the quantized tree on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu import quant
from perceiver_io_tpu.models.presets import tiny_mlm


@pytest.fixture(scope="module")
def tiny_setup():
    model = tiny_mlm()
    ids = np.zeros((1, 64), np.int32)
    params = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        jnp.asarray(ids), jnp.asarray(ids == 1),
    )["params"]
    return model, params


# -- per-channel quant/dequant core -------------------------------------------


def test_quantize_array_roundtrip_bound(rng):
    """Round-to-nearest per-channel symmetric int8: the elementwise
    reconstruction error is bounded by scale/2, scales are per LAST-axis
    channel, and channel maxima reconstruct exactly (they sit on the grid)."""
    for shape in [(8, 16), (64, 32), (128,)]:
        w = rng.normal(0, 1, shape).astype(np.float32) * rng.uniform(
            0.01, 10.0, shape[-1]
        ).astype(np.float32)
        q, scale = quant.quantize_array(w)
        assert q.dtype == np.int8 and scale.dtype == np.float32
        assert scale.shape == (shape[-1],)
        deq = np.asarray(
            quant.dequantize_array(jnp.asarray(q), jnp.asarray(scale),
                                   jnp.float32)
        )
        assert np.all(np.abs(deq - w) <= scale / 2 + 1e-7)
        # the per-channel absolute max is exactly representable: q = ±127
        amax_idx = np.argmax(np.abs(w.reshape(-1, shape[-1])), axis=0)
        flat, flat_q = w.reshape(-1, shape[-1]), deq.reshape(-1, shape[-1])
        np.testing.assert_allclose(
            flat_q[amax_idx, np.arange(shape[-1])],
            flat[amax_idx, np.arange(shape[-1])], rtol=1e-6,
        )


def test_quantize_array_zero_channel():
    """An all-zero channel must not divide by zero and reconstructs to 0."""
    w = np.zeros((4, 3), np.float32)
    w[:, 0] = [1, -2, 3, -4]
    q, scale = quant.quantize_array(w)
    assert np.all(np.isfinite(scale)) and np.all(scale > 0)
    deq = np.asarray(quant.dequantize_array(
        jnp.asarray(q), jnp.asarray(scale), jnp.float32))
    assert np.all(deq[:, 1:] == 0)


# -- tree contract: key paths, dtypes, policy ---------------------------------


def _paths(tree):
    from perceiver_io_tpu.utils.treepath import simple_keystr

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [simple_keystr(p) for p, _ in flat]


def test_quantized_tree_mirrors_keypaths(tiny_setup):
    """The quantized values tree has EXACTLY the f32 tree's key paths and
    shapes (the invariant sharding rules and torch-parity names ride on);
    2-D kernels become int8, everything else keeps/casts its float dtype."""
    _, params = tiny_setup
    qp = quant.quantize_tree(params, compute_dtype="float32")
    assert _paths(qp.values) == _paths(params)
    shapes = jax.tree.map(lambda x: x.shape, params)
    q_shapes = jax.tree.map(lambda x: x.shape, qp.values)
    assert shapes == q_shapes

    from perceiver_io_tpu.utils.treepath import simple_keystr

    kernels = [p for p in _paths(params) if p.endswith("kernel")]
    assert kernels and len(qp.scales) == len(kernels)
    flat, _ = jax.tree_util.tree_flatten_with_path(qp.values)
    for path, leaf in flat:
        name = simple_keystr(path)
        if name.endswith("kernel"):
            assert leaf.dtype == jnp.int8, name
            assert qp.scales[name].shape == (leaf.shape[-1],)
        else:
            assert leaf.dtype != jnp.int8, name
    # gathered tables are deliberately NOT quantized (dequantizing a full
    # table per dispatch would ADD HBM traffic on the serving path)
    emb = qp.values["encoder"]["input_adapter"]["text_embedding"]["embedding"]
    assert emb.dtype == jnp.float32

    # dequant reconstructs the full tree at the compute dtype
    deq = quant.dequantize_tree(qp)
    assert _paths(deq) == _paths(params)
    assert all(
        leaf.dtype == jnp.float32 for leaf in jax.tree_util.tree_leaves(deq)
    )


def test_quantize_tree_casts_nonquantized_to_compute_dtype(tiny_setup):
    _, params = tiny_setup
    qp = quant.quantize_tree(params, compute_dtype="bfloat16")
    bias = qp.values["decoder"]["output_adapter"]["linear"]["bias"]
    assert bias.dtype == jnp.bfloat16
    assert all(s.dtype == jnp.float32 for s in qp.scales.values())
    acct = quant.bytes_summary(params, qp)
    assert acct["param_bytes_int8w"] < acct["param_bytes_f32"] / 2
    assert 0 < acct["predicted_weight_stream_ratio"] < 1


# -- engine parity vs the f32 oracle (tiny preset) ----------------------------


def test_int8w_engine_parity_vs_f32_oracle(tiny_setup):
    """The int8w serving path (quantize at load, dequant inside the jitted
    dispatch) tracks the f32 oracle within the documented bound on the tiny
    preset: ≤ 0.03 rel-to-peak on the gathered fill-mask logits (measured
    0.019 — PERF.md §Quantization; the bf16 baseline alone measures 0.009)."""
    from perceiver_io_tpu.inference import ServingEngine

    model, params = tiny_setup
    rng = np.random.default_rng(1)
    ids = rng.integers(3, 503, (4, 64)).astype(np.int32)
    pad = np.zeros((4, 64), bool)
    positions = np.tile(np.arange(2, dtype=np.int32), (4, 1))

    def gathered_apply(p, token_ids, pad_mask, pos):
        logits, _ = model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=pos,
        )
        return logits

    oracle = np.asarray(
        jax.jit(gathered_apply)(params, ids, pad, positions), np.float32
    )
    peak = float(np.max(np.abs(oracle)))

    # f32 compute over int8 weights: quantization error alone
    with ServingEngine(
        gathered_apply, params, max_batch=4, quantize="int8"
    ) as eng:
        got = np.asarray(eng.predict(ids, pad, positions, timeout=120),
                         np.float32)
        assert float(np.max(np.abs(got - oracle))) / peak <= 0.03

    # the int8w shorthand (bf16 compute + int8 weights): the serving mode
    with ServingEngine(
        gathered_apply, params, max_batch=4, compute_dtype="int8w"
    ) as eng:
        assert eng.quantize == "int8"
        assert quant.is_quantized(eng.params)
        got = np.asarray(eng.predict(ids, pad, positions, timeout=120),
                         np.float32)
        assert float(np.max(np.abs(got - oracle))) / peak <= 0.05


@pytest.mark.slow  # the same int8w-top-k==f32 assertion runs at CLI
# level in tests/test_cli.py::test_serve_cli_end_to_end (tier-1)
def test_mlm_server_int8w_top_k_matches_f32(tiny_setup):
    """MLMServer(quantize='int8') serves fill-mask through ONE shared
    quantized tree; its top-k token picks on the tiny preset match the f32
    server (rank stability is the serving-level parity that matters)."""
    from perceiver_io_tpu.data.tokenizer import (
        MASK_TOKEN,
        PAD_TOKEN,
        UNK_TOKEN,
        WordPieceTokenizer,
    )
    from perceiver_io_tpu.inference import MLMServer

    vocab = {PAD_TOKEN: 0, UNK_TOKEN: 1, MASK_TOKEN: 2}
    for w in ["movie", "great", "plot", "the", "was", "a", "b"]:
        vocab[w] = len(vocab)
    tok = WordPieceTokenizer(vocab=vocab)
    model = tiny_mlm(vocab_size=tok.get_vocab_size(), max_seq_len=16)
    ids = np.zeros((1, 16), np.int32)
    params = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        jnp.asarray(ids), jnp.asarray(ids == 1),
    )["params"]

    texts = ["the movie was [MASK]", "a [MASK] plot"]
    with MLMServer(model, params, tok, max_seq_len=16, max_batch=4) as server:
        want = server.fill_masks(texts, k=3)
    with MLMServer(
        model, params, tok, max_seq_len=16, max_batch=4, quantize="int8"
    ) as server:
        # all three engines serve the quantized tree (quantized ONCE by the
        # server; each engine's device_put of committed arrays is a no-op)
        for eng in (server.engine, server.encoder, server.decoder):
            assert quant.is_quantized(eng.params)
            assert eng.quantize == "int8"
        assert server.warmup() > 0
        assert server.fill_masks(texts, k=3) == want


def test_prequantized_compute_dtype_mismatch_rejected(tiny_setup):
    """An engine handed a pre-quantized tree whose baked compute dtype
    differs from the engine's resolved one must fail LOUDLY at construction
    — silently serving mixed precision (and recompiling every warmed bucket
    on the next update_params) is the failure mode this guards."""
    from perceiver_io_tpu.inference import ServingEngine

    _, params = tiny_setup
    qp = quant.quantize_tree(params, compute_dtype="float32")
    with pytest.raises(ValueError, match="compute_dtype"):
        ServingEngine(lambda p, x: x, qp, compute_dtype="bfloat16")

    # the same guard covers the hot-swap path (and a quantized tree handed
    # to a NON-quantized engine) — update_params must reject, not install
    with ServingEngine(lambda p, x: x, params) as eng:
        with pytest.raises(ValueError, match="do not match"):
            eng.update_params(qp)
    with ServingEngine(lambda p, x: x, params, quantize="int8") as eng:
        with pytest.raises(ValueError, match="do not match"):
            eng.update_params(
                quant.quantize_tree(params, compute_dtype="bfloat16")
            )
    # a typo'd quantize mode is rejected even under the int8w shorthand
    # ('int4' became a real mode in r24, so the typo probe moved to 'int2')
    with pytest.raises(ValueError, match="unknown quantize mode"):
        ServingEngine(
            lambda p, x: x, params, compute_dtype="int8w", quantize="int2"
        )
    # mixed int modes across construction and hot-swap are a mode mismatch
    with ServingEngine(lambda p, x: x, params, quantize="int8") as eng:
        with pytest.raises(ValueError, match="do not match"):
            eng.update_params(
                quant.quantize_tree(params, compute_dtype="float32", bits=4)
            )


def test_mlm_server_update_params_swaps_all_engines(tiny_setup):
    """MLMServer.update_params prepares ONE tree under the server's mode and
    stages it on all three engines — after the swap drains, fills reflect
    the new weights on the fused AND the latent-cache paths."""
    import time

    from perceiver_io_tpu.data.tokenizer import (
        MASK_TOKEN,
        PAD_TOKEN,
        UNK_TOKEN,
        WordPieceTokenizer,
    )
    from perceiver_io_tpu.inference import MLMServer

    vocab = {PAD_TOKEN: 0, UNK_TOKEN: 1, MASK_TOKEN: 2}
    for w in ["movie", "great", "plot", "the", "was"]:
        vocab[w] = len(vocab)
    tok = WordPieceTokenizer(vocab=vocab)
    model = tiny_mlm(vocab_size=tok.get_vocab_size(), max_seq_len=16)
    ids = np.zeros((1, 16), np.int32)

    def init(seed):
        return model.init(
            {"params": jax.random.key(seed), "masking": jax.random.key(1)},
            jnp.asarray(ids), jnp.asarray(ids == 1),
        )["params"]

    p_a, p_b = init(0), init(7)
    text = ["the movie was [MASK]"]
    with MLMServer(
        model, p_b, tok, max_seq_len=16, max_batch=4, quantize="int8"
    ) as server:
        want_b = server.fill_masks(text, k=3)
    with MLMServer(
        model, p_a, tok, max_seq_len=16, max_batch=4, quantize="int8"
    ) as server:
        server.fill_masks(text, k=3)
        server.update_params(p_b)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if server.fill_masks(text, k=3) == want_b:
                break
            time.sleep(0.05)
        assert server.fill_masks(text, k=3) == want_b
        # the latent-cache path swapped too (fresh encode AFTER the update)
        cached = server.encode(text)
        assert server.fill_masks_cached(cached, k=3) == want_b


# -- grouped int4 core --------------------------------------------------------


def test_grouped_int4_roundtrip_bound(rng):
    """Grouped int4 (AWQ-style, scale per (group, channel)): reconstruction
    error bounded by the GROUP's scale/2 — the per-group grid is what makes
    4 bits usable; int4 with one per-channel scale is strictly worse on
    scale-varying rows."""
    k, n, gs = 256, 32, 64
    # magnitude varies BETWEEN K-groups (each block of gs rows shares one):
    # the structure grouped scales exploit and a single per-channel scale
    # cannot — small-magnitude groups get crushed onto the channel-max grid
    block_scale = np.exp(rng.uniform(-4, 4, (k // gs, 1))).astype(np.float32)
    row_scale = np.repeat(block_scale, gs, axis=0)
    w = (rng.normal(0, 1, (k, n)).astype(np.float32)) * row_scale
    q, scale = quant.quantize_array(w, bits=4, group_size=gs)
    assert scale.shape == (k // gs, n) and scale.dtype == np.float32
    assert np.all(np.abs(q) <= 7)
    deq = np.asarray(quant.dequantize_array(
        jnp.asarray(q, jnp.int4), jnp.asarray(scale), jnp.float32))
    bound = np.repeat(scale, gs, axis=0) / 2
    assert np.all(np.abs(deq - w) <= bound + 1e-7)

    q_pc, scale_pc = quant.quantize_array(w, bits=4)  # per-channel int4
    deq_pc = np.asarray(quant.dequantize_array(
        jnp.asarray(q_pc, jnp.int4), jnp.asarray(scale_pc), jnp.float32))
    # the win lives in the SMALL-magnitude groups: per-channel int4 crushes
    # them onto the channel-max grid (step ~ amax/7) while the grouped grid
    # steps at the group's own max/7 — orders of magnitude finer here. (The
    # biggest group errs ~equally under both grids, so whole-matrix means
    # only show the aggregate, not the mechanism.)
    # (the factor is bounded: once the coarse grid rounds a whole block to
    # zero, per-channel error saturates at |w| itself — measured ~8.6x)
    lo = int(np.argmin(block_scale[:, 0]))
    rows = slice(lo * gs, (lo + 1) * gs)
    assert (np.abs(deq - w)[rows].mean()
            < np.abs(deq_pc - w)[rows].mean() / 5)


def test_quantize_tree_int4_grouped(tiny_setup):
    """bits=4 trees: kernels store int4 with 2-D grouped scales (or 1-D
    per-channel when K doesn't divide), key paths/shapes still mirror f32,
    and predicted bytes land under the int8w tree's."""
    _, params = tiny_setup
    qp = quant.quantize_tree(params, compute_dtype="bfloat16", bits=4)
    assert qp.bits == 4 and qp.group_size == quant.DEFAULT_GROUP_SIZE
    assert _paths(qp.values) == _paths(params)
    from perceiver_io_tpu.utils.treepath import simple_keystr

    flat, _ = jax.tree_util.tree_flatten_with_path(qp.values)
    for path, leaf in flat:
        name = simple_keystr(path)
        if name.endswith("kernel"):
            assert leaf.dtype == jnp.int4, name
            scale = qp.scales[name]
            if leaf.shape[0] % quant.DEFAULT_GROUP_SIZE == 0:
                assert scale.ndim == 2, name
            else:  # per-channel fallback for awkward K
                assert scale.shape == (leaf.shape[-1],), name
    acct8 = quant.bytes_summary(params, compute_dtype="bfloat16")
    acct4 = quant.bytes_summary(params, qp, compute_dtype="bfloat16")
    assert acct4["param_bytes_int4w"] < acct8["param_bytes_int8w"]


# -- fused kernel parity vs the XLA lowering, per _LinearParams site ----------


def test_qmm_kernel_parity_per_site(tiny_setup):
    """The fused dequant-matmul kernel (ops/pallas_matmul, interpret mode on
    CPU) vs the XLA dequant-then-matmul over the SAME quantized operands, at
    EVERY quantized kernel site of the tiny tree (q/k/v/out_proj,
    dense_1/dense_2, the vocab head), f32 compute: ≤ 2e-5 rel-to-peak —
    both lowerings of one expression."""
    from perceiver_io_tpu.ops.pallas_matmul import quantized_matmul
    from perceiver_io_tpu.quant.int8 import QKernel

    _, params = tiny_setup
    rng = np.random.default_rng(3)
    for bits in (8, 4):
        qp = quant.quantize_tree(params, compute_dtype="float32", bits=bits)
        flat, _ = jax.tree_util.tree_flatten_with_path(qp.values)
        from perceiver_io_tpu.utils.treepath import simple_keystr

        sites = {simple_keystr(p): leaf for p, leaf in flat
                 if simple_keystr(p).endswith("kernel")}
        assert len(sites) >= 7  # q/k/v/out_proj + dense_1/2 + head(s)
        for name, leaf in sites.items():
            w = QKernel(leaf, qp.scales[name], "float32")
            x = jnp.asarray(rng.normal(0, 1, (5, leaf.shape[0])),
                            jnp.float32)
            got = np.asarray(quantized_matmul(x, w, impl="pallas"),
                             np.float32)
            ref = np.asarray(quantized_matmul(x, w, impl="xla"), np.float32)
            peak = float(np.max(np.abs(ref))) or 1.0
            err = float(np.max(np.abs(got - ref))) / peak
            assert err <= 2e-5, f"int{bits} {name}: {err}"


def test_qmm_env_dispatch_and_typo_rejection(tiny_setup, monkeypatch):
    """PIT_QMM_IMPL steers linear_apply's kernel dispatch at trace time
    (the PIT_DRYRUN_ATTN pattern) and a typo'd impl fails loudly instead of
    silently benchmarking the wrong branch."""
    from perceiver_io_tpu.ops.pallas_matmul import (
        linear_apply,
        quantized_matmul,
    )
    from perceiver_io_tpu.quant.int8 import QKernel

    _, params = tiny_setup
    qp = quant.quantize_tree(params, compute_dtype="float32")
    leaf = qp.values["decoder"]["output_adapter"]["linear"]["kernel"]
    w = QKernel(leaf, qp.scales["decoder/output_adapter/linear/kernel"],
                "float32")
    x = jnp.asarray(np.random.default_rng(0).normal(0, 1, (3, leaf.shape[0])),
                    jnp.float32)
    monkeypatch.setenv("PIT_QMM_IMPL", "pallas")
    got = np.asarray(linear_apply(x, w, None, jnp.float32), np.float32)
    monkeypatch.setenv("PIT_QMM_IMPL", "xla")
    ref = np.asarray(linear_apply(x, w, None, jnp.float32), np.float32)
    peak = float(np.max(np.abs(ref))) or 1.0
    assert float(np.max(np.abs(got - ref))) / peak <= 2e-5
    with pytest.raises(ValueError, match="unknown quantized-matmul impl"):
        quantized_matmul(x, w, impl="palas")
    monkeypatch.setenv("PIT_QMM_IMPL", "mosaic")
    with pytest.raises(ValueError, match="unknown quantized-matmul impl"):
        quantized_matmul(x, w)


# -- sharding-rule resolution on the quantized tree ---------------------------


def test_sharding_rules_resolve_identically_on_quantized_tree(tiny_setup):
    """parallel/sharding.py path-regex rules resolve the SAME PartitionSpecs
    on QuantizedParams.values as on the f32 tree (8-device CPU mesh) — the
    key-path/shape identity doing its job."""
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.parallel.sharding import sharding_for_tree

    _, params = tiny_setup
    qp = quant.quantize_tree(params, compute_dtype="bfloat16")
    mesh = make_mesh(dp=4, tp=2)
    want = jax.tree.map(lambda s: s.spec, sharding_for_tree(params, mesh))
    got = jax.tree.map(lambda s: s.spec, sharding_for_tree(qp.values, mesh))
    assert want == got
    # and the rules actually bit: the q_proj kernel resolved model-sharded
    # on the int8 tree, not replicated
    from jax.sharding import PartitionSpec as P

    layer = got["encoder"]["layer_1"]["cross_attention_layer"]
    assert layer["cross_attention"]["attention"]["q_proj"]["kernel"] == P(
        None, "model"
    )


def test_sharding_rules_resolve_identically_on_int4_tree(tiny_setup):
    """Same property on the grouped-int4 tree: the path-regex rules see only
    key paths and leaf ranks, both of which the int4 values tree preserves
    exactly (scales ride OUTSIDE the values tree) — so int4w serving under
    tp > 1 inherits the same placement as f32, no new rules needed."""
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.parallel.sharding import sharding_for_tree

    _, params = tiny_setup
    qp = quant.quantize_tree(params, compute_dtype="bfloat16", bits=4)
    mesh = make_mesh(dp=4, tp=2)
    want = jax.tree.map(lambda s: s.spec, sharding_for_tree(params, mesh))
    got = jax.tree.map(lambda s: s.spec, sharding_for_tree(qp.values, mesh))
    assert want == got
