#!/usr/bin/env python
"""Fill-mask serving over the micro-batching engine (``cli/serve.py``)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from perceiver_io_tpu.cli.serve import main

if __name__ == "__main__":
    main()
