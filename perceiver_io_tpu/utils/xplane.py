"""Minimal xplane (jax.profiler trace) reader for DEVICE-measured step time.

The tunneled PJRT backend this dev environment uses makes host-side timing
unreliable (PERF.md: ``block_until_ready`` lies ~10x, scalar fetches cost a
~100 ms round trip, and the tunnel's throughput swings ±2x between sessions).
The device trace is the one clock the tunnel cannot distort: the TPU itself
records each step's start/duration, and this module extracts them.

Used by ``bench.py`` (the headline metric rides the device clock, VERDICT r2
item 2) and ``tools/hbm_roofline.py`` (roofline analysis on the same trace).

Requires the tensorflow protobufs for xplane decoding (baked into this image);
callers should catch ImportError/RuntimeError and fall back to host timing.
"""

from __future__ import annotations

import glob
import os
from typing import List, Tuple


def load_tpu_plane(trace_dir: str):
    """The first TPU device plane of the newest xplane.pb under trace_dir."""
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb")
    )
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xs.ParseFromString(f.read())
    tpu_planes = [p for p in xs.planes if "/device:TPU" in p.name and p.lines]
    if not tpu_planes:
        raise RuntimeError("no TPU device plane in trace (ran on CPU?)")
    return tpu_planes[0]


def step_windows(plane) -> List[Tuple[int, int]]:
    """(start_ps, end_ps) per step from the plane's Steps line."""
    step_lines = [l for l in plane.lines if l.name == "Steps"]
    if not step_lines:
        raise RuntimeError("trace has no Steps line")
    return [
        (e.offset_ps, e.offset_ps + e.duration_ps)
        for e in step_lines[0].events
    ]


def device_step_seconds(trace_dir: str, skip_first: int = 2) -> Tuple[float, int]:
    """Device-measured seconds/step: the LOWER QUARTILE of per-step durations.

    On a time-shared chip the per-step distribution is (true program
    duration) + (occasional co-tenant interference): measured on the bench
    step, ~half the steps land in a ±0.1% cluster at the true duration and
    the rest are inflated up to ~1.7x by contention (PERF.md round 3). The
    mean/median move with whoever else is on the chip; the lower quartile
    sits inside the tight cluster and reproduces across sessions — it is the
    program's capability on this chip, which is what the headline metric
    claims.

    ``skip_first`` leading steps are dropped (warm caches / first-dispatch
    effects) when enough remain. Returns ``(seconds_per_step, n_steps_used)``.
    """
    windows = step_windows(load_tpu_plane(trace_dir))
    if len(windows) > skip_first + 2:
        windows = windows[skip_first:]
    if not windows:
        raise RuntimeError("trace recorded zero steps")
    durations = sorted(b - a for a, b in windows)
    lower_quartile = durations[len(durations) // 4]
    return lower_quartile / 1e12, len(durations)
