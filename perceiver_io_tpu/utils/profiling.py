"""Tracing / profiling utilities — the observability layer the reference lacks.

The reference ships nothing beyond Lightning's progress bar (SURVEY.md §5);
here profiling is first-class and TPU-native:

- ``start_profiler_server`` / ``trace``: the ``jax.profiler`` trace server and
  programmatic trace capture, viewable in TensorBoard's profile plugin or
  Perfetto.
- ``annotate_step``: ``StepTraceAnnotation`` wrapper so each training step
  shows up as a named step in the trace timeline.
- ``compiled_flops`` + ``device_peak_flops`` + ``mfu``: model-FLOPs-utilization
  accounting from XLA's own cost analysis of the compiled step — the number
  the BASELINE.md target (≥45% MFU on v5e) is measured in.
"""

from __future__ import annotations

import contextlib
import threading
import warnings
from typing import Callable, Iterator, Optional, Tuple

import jax

# Peak dense matmul throughput per chip, bf16, FLOP/s. Public figures from
# cloud.google.com/tpu/docs (v2/v3 are per-chip = 2 cores).
_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def start_profiler_server(port: int = 9012) -> None:
    """Start the profiler server so TensorBoard can capture live traces."""
    jax.profiler.start_server(port)


def call_with_deadline(
    fn: Callable[[], object],
    deadline_s: Optional[float],
    name: str = "call",
) -> Tuple[bool, object]:
    """Run ``fn`` with a wall-clock deadline: ``(completed, result)``.

    Any device call can hang forever when the axon tunnel wedges (CLAUDE.md),
    so watchdog-adjacent code must never call the profiler API bare. The call
    runs on a daemon worker thread; on timeout the caller gets ``(False,
    None)`` and moves on — the stuck thread is abandoned (it holds no locks
    of ours and dies with the process). ``deadline_s=None`` calls inline.
    Exceptions raised by ``fn`` before the deadline propagate unchanged.
    """
    if deadline_s is None:
        return True, fn()
    box: dict = {}
    done = threading.Event()

    def _run() -> None:
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(
        target=_run, name=f"deadline-{name}", daemon=True
    )
    worker.start()
    if not done.wait(deadline_s):
        return False, None
    if "error" in box:
        raise box["error"]
    return True, box.get("result")


@contextlib.contextmanager
def trace(logdir: str, deadline_s: Optional[float] = None) -> Iterator[None]:
    """Capture a profiler trace into ``logdir`` (TensorBoard-compatible).

    With ``deadline_s``, ``start_trace``/``stop_trace`` each run under a
    deadline: if either hangs (wedged tunnel), the context degrades to a
    no-op with a warning instead of freezing the loop — callers keep their
    host timing and simply get no trace to analyze.
    """
    started, _ = call_with_deadline(
        lambda: jax.profiler.start_trace(logdir), deadline_s, "start_trace"
    )
    if not started:
        warnings.warn(
            f"jax.profiler.start_trace did not complete within {deadline_s}s "
            "(wedged device tunnel?) — proceeding WITHOUT a trace",
            stacklevel=2,
        )
    try:
        yield
    finally:
        # even when start timed out it may have completed late on its worker
        # thread — best-effort stop either way, never letting a profiler
        # session leak into the process (stop on a never-started trace raises
        # harmlessly into the except arm)
        try:
            stopped, _ = call_with_deadline(
                jax.profiler.stop_trace, deadline_s, "stop_trace"
            )
            if not stopped:
                warnings.warn(
                    f"jax.profiler.stop_trace did not complete within "
                    f"{deadline_s}s (wedged device tunnel?) — the trace "
                    f"under {logdir!r} may be unusable",
                    stacklevel=2,
                )
        except Exception:
            if started:
                raise


def annotate_step(step_num: int) -> jax.profiler.StepTraceAnnotation:
    """Mark a training step in the trace timeline."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step_num)


def compiled_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one invocation, from XLA's cost analysis of the lowered
    computation. None when the backend doesn't expose an estimate."""
    try:
        cost = jitted_fn.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax: one dict per device
            cost = cost[0]
        flops = cost.get("flops")
        return float(flops) if flops else None
    except Exception:
        return None


def device_peak_flops(device: Optional[jax.Device] = None) -> Optional[float]:
    """Peak bf16 FLOP/s for a device, or None when unknown (e.g. CPU)."""
    device = device or jax.devices()[0]
    return _PEAK_FLOPS.get(getattr(device, "device_kind", ""))


def mfu(
    flops_per_step: float,
    step_time_s: float,
    num_devices: int = 1,
    device: Optional[jax.Device] = None,
) -> Optional[float]:
    """Model FLOPs utilization in [0, 1]: achieved / peak.

    ``flops_per_step`` is the whole program's FLOPs (all devices), so peak is
    scaled by ``num_devices``.
    """
    peak = device_peak_flops(device)
    if peak is None or step_time_s <= 0:
        return None
    return flops_per_step / step_time_s / (peak * num_devices)
