"""The sanctioned stdout channel for ``tools/`` and ``bench.py``.

The driver parses ONE JSON line from each tool's stdout (CLAUDE.md); every
human-readable table, progress note, and warning rides stderr. pitlint's
PIT-CONTRACT rule enforces the split statically — :func:`emit_json_line` is
the only stdout writer it sanctions — and this helper enforces at runtime
what the AST cannot: the record really serializes, to really one line.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Mapping


def emit_json_line(record: Mapping[str, Any]) -> str:
    """Serialize ``record`` as exactly one JSON line on stdout (flushed).

    Raises ``ValueError`` when the payload would violate the contract (not
    JSON-serializable, or an embedded newline from a weird string value) —
    loudly at the emitter, not silently at the driver's parser.
    """
    try:
        line = json.dumps(record)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"emit_json_line: record is not JSON-serializable: {e}"
        ) from e
    if "\n" in line or "\r" in line:
        raise ValueError(
            "emit_json_line: serialized record contains a newline — the "
            "one-JSON-line stdout contract would break"
        )
    print(line, file=sys.stdout, flush=True)  # pitlint: ignore[PIT-CONTRACT] the sanctioned emitter itself
    return line


def log(message: str) -> None:
    """Human-readable tool output (stderr — never the JSON channel)."""
    print(message, file=sys.stderr, flush=True)
