from perceiver_io_tpu.utils.platform import ensure_cpu_only

__all__ = ["ensure_cpu_only"]
