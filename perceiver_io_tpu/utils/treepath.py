"""Canonical pytree key-path rendering shared by the path-matching layers.

``simple_keystr`` produces the bare-name "/"-joined form that BOTH
``parallel/sharding.py``'s PARAM_RULES regexes and
``perceiver_io_tpu.quant``'s scale map are keyed by. The two must stay
bit-identical — the quantized-tree contract (scales found at dequant time,
sharding specs resolving identically on the int8 tree) rides on it — so
there is exactly ONE definition. Inlined rather than
``jax.tree_util.keystr(path, simple=True, separator='/')`` because not
every jax build this runs under has the simple/separator kwargs.
"""

from __future__ import annotations


def simple_keystr(path) -> str:
    """Bare-name "/"-joined key path (``params/encoder/.../kernel``)."""
    parts = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)
