"""Canonical pytree key-path rendering shared by the path-matching layers.

``simple_keystr`` produces the bare-name "/"-joined form that BOTH
``parallel/sharding.py``'s PARAM_RULES regexes and
``perceiver_io_tpu.quant``'s scale map are keyed by. The two must stay
bit-identical — the quantized-tree contract (scales found at dequant time,
sharding specs resolving identically on the int8 tree) rides on it — so
there is exactly ONE definition. Inlined rather than
``jax.tree_util.keystr(path, simple=True, separator='/')`` because not
every jax build this runs under has the simple/separator kwargs.

``tree_digest`` is the content digest over a param tree that the deploy
subsystem's publication manifests (``perceiver_io_tpu.deploy``) and the
checkpoint digest sidecars (``training/checkpoint.py``) both carry — one
definition here so a digest computed at train time verifies at serve time.
"""

from __future__ import annotations


def simple_keystr(path) -> str:
    """Bare-name "/"-joined key path (``params/encoder/.../kernel``)."""
    parts = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


def flatten_named(tree) -> dict:
    """``{simple_keystr(path): host numpy leaf}`` in sorted-path order —
    the serialization form publications store and digests hash."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {
        simple_keystr(path): np.asarray(jax.device_get(leaf))
        for path, leaf in sorted(leaves, key=lambda pl: simple_keystr(pl[0]))
    }


def digest_named(named: dict) -> str:
    """sha256 over an already-flattened ``{path: host array}`` dict (the
    :func:`flatten_named` form) — callers that hold the flattened payload
    anyway (publication writers) must not pay a second flatten + per-leaf
    device fetch just to hash it."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for name in sorted(named):
        a = np.ascontiguousarray(named[name])
        if a.dtype.byteorder == ">":  # hash a platform-stable byte order
            a = a.astype(a.dtype.newbyteorder("<"))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def tree_digest(tree) -> str:
    """sha256 over the tree's CONTENT: sorted key paths, dtypes, shapes, and
    raw little-endian leaf bytes. Two trees digest equal iff they hold the
    same values at the same paths — placement, donation state, and leaf
    array type (np vs jax.Array) do not enter."""
    return digest_named(flatten_named(tree))
