"""Tunnel-safe train-step timing (the load-bearing measurement discipline).

On tunneled/remote PJRT backends naive timing lies (PERF.md):
``block_until_ready`` can return before device work completes, per-call
scalar fetches cost a ~100 ms round trip, and dispatches whose outputs are
never consumed get DCE'd. The one honest recipe, shared by ``bench.py`` and
``tools/e2e_configs_bench.py``:

- jit with a donated state and CHAIN iterations through it (nothing is dead),
- sync by fetching the loss scalar (never ``block_until_ready``),
- subtract a 1-iteration run so the fetch round trip doesn't count.
"""

from __future__ import annotations

import time
from typing import Tuple

import jax


def time_train_step(
    train_step, state, batch, steps: int, windows: int = 1, jitted=None
) -> Tuple[float, object]:
    """Seconds per step of ``(state, batch) → (state, metrics)``; returns
    ``(seconds_per_step, final_state)``. Compiles/warms once before timing.

    ``steps`` is a lower bound: when the measured delta doesn't dwarf the
    fetch round trip (sub-millisecond steps on a ~100 ms tunnel), the
    iteration count grows until it does — otherwise round-trip jitter swamps
    the signal (and can even make the subtraction negative).

    ``windows``: number of measurement windows; the MEDIAN is returned. A
    shared/tunneled chip shows occasional 1.5x-slow windows (contention);
    with one window a single outlier becomes the recorded number.

    ``jitted``: pass a pre-built ``jax.jit(train_step, donate_argnums=(0,))``
    wrapper to reuse its compiled executable (e.g. when the caller already
    lowered it for cost analysis) — a fresh wrapper would compile again."""
    step = jitted if jitted is not None else jax.jit(train_step, donate_argnums=(0,))

    for _ in range(3):
        state, metrics = step(state, batch)
    float(metrics["loss"])  # the only reliable device sync here

    def timed(n: int) -> float:
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        return time.perf_counter() - t0

    def one_window() -> float:
        t_one = timed(1)  # fetch round trip + one step
        n = steps
        while True:
            delta = timed(n + 1) - t_one
            if delta > max(4.0 * t_one, 0.25) or n >= 65536:
                return max(delta, 0.0) / n
            n *= 4

    samples = sorted(one_window() for _ in range(max(windows, 1)))
    return samples[len(samples) // 2], state


def time_train_step_device(
    train_step, state, batch, steps: int, jitted=None, trace_dir=None
) -> Tuple[float, int, object]:
    """DEVICE-measured seconds/step via a ``jax.profiler`` trace.

    The host-clock recipe above is honest but still rides the tunnel: its
    number moves with session-to-session tunnel throughput (PERF.md documents
    ±2x swings). The device trace records each step's hardware duration on
    the TPU itself, so this measurement is tunnel-insensitive — it is the
    basis of the headline metric (``bench.py``), with the host clock kept as
    the fallback for backends whose traces lack a TPU plane.

    Returns ``(seconds_per_step, n_steps_used, final_state)``. Raises on
    backends/toolchains where the trace cannot be captured or parsed
    (caller falls back to :func:`time_train_step`).
    """
    import tempfile

    from perceiver_io_tpu.utils.xplane import device_step_seconds

    step = jitted if jitted is not None else jax.jit(train_step, donate_argnums=(0,))
    for _ in range(3):
        state, metrics = step(state, batch)
    float(metrics["loss"])  # sync before the trace window opens

    if trace_dir is None:
        trace_dir = tempfile.mkdtemp(prefix="pit_bench_trace_")
    jax.profiler.start_trace(trace_dir)
    try:
        for _ in range(steps):
            state, metrics = step(state, batch)
        float(metrics["loss"])  # device sync INSIDE the trace window
    finally:
        jax.profiler.stop_trace()

    seconds, n_used = device_step_seconds(trace_dir)
    return seconds, n_used, state
