"""Backend/platform helpers.

This JAX build initializes *every* registered PJRT backend on first device
access, even when ``JAX_PLATFORMS=cpu`` — so a wedged/absent accelerator
plugin can hang CPU-only test runs. ``ensure_cpu_only`` drops non-CPU backend
factories before the first device query, making CPU runs (tests, the
multi-chip dry-run on a virtual device mesh) independent of accelerator
plugin health.

Call it BEFORE anything touches ``jax.devices()`` / creates arrays.
"""

from __future__ import annotations

import os


def ensure_cpu_only(device_count: int | None = None) -> None:
    """Force this process to use only the CPU backend.

    Optionally requests ``device_count`` virtual CPU devices (must run before
    backends initialize; the XLA flag is ignored afterwards).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={device_count}"
            )

    # Site customization (e.g. an accelerator tunnel) may have imported jax at
    # interpreter boot, caching jax_platforms from the env before we ran —
    # override the live config too, not just the env var.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    try:
        import jax._src.xla_bridge as xb

        # Drop only third-party plugin factories (e.g. a tunneled accelerator);
        # standard platforms must stay registered — parts of jax (checkify's
        # MLIR lowerings) validate against the known-platform set at import.
        standard = {"cpu", "tpu", "cuda", "gpu", "rocm", "metal"}
        for name in list(xb._backend_factories):
            if name not in standard:
                xb._backend_factories.pop(name, None)
    except Exception:
        pass  # private API moved — JAX_PLATFORMS alone may still suffice
