"""Backend/platform helpers.

This JAX build initializes *every* registered PJRT backend on first device
access, even when ``JAX_PLATFORMS=cpu`` — so a wedged/absent accelerator
plugin can hang CPU-only test runs. ``ensure_cpu_only`` drops non-CPU backend
factories before the first device query, making CPU runs (tests, the
multi-chip dry-run on a virtual device mesh) independent of accelerator
plugin health.

Call it BEFORE anything touches ``jax.devices()`` / creates arrays.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional


def drop_unselected_plugin_backends() -> None:
    """Drop third-party PJRT plugin factories not named in ``JAX_PLATFORMS``.

    Multi-host bring-up (``jax.distributed.initialize``) must complete before
    any backend initializes — but probing a registered third-party plugin can
    initialize backends mid-call, leaving the distributed client unattached
    (``jax.process_count()`` stays 1 and every process trains alone). When the
    user explicitly selected platforms via ``JAX_PLATFORMS``, unselected
    plugins have no business initializing; standard platforms (cpu/tpu/...)
    are left alone. No-op when ``JAX_PLATFORMS`` is unset (e.g. real TPU
    pods, where auto-detection is the point).
    """
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if not platforms:
        return
    allowed = {p.strip().lower() for p in platforms.split(",") if p.strip()}
    standard = {"cpu", "tpu", "cuda", "gpu", "rocm", "metal"}
    try:
        import jax

        # plugin registration at interpreter boot may have overridden the
        # live config (e.g. to the plugin's own name) — realign with the env
        # so the scrubbed factory is never requested
        jax.config.update("jax_platforms", platforms)
    except Exception:
        pass
    try:
        import jax._src.xla_bridge as xb

        for name in list(xb._backend_factories):
            if name not in standard and name not in allowed:
                xb._backend_factories.pop(name, None)
    except Exception:
        pass  # private API moved — JAX_PLATFORMS alone may still suffice


def ensure_cpu_only(device_count: int | None = None) -> None:
    """Force this process to use only the CPU backend.

    Optionally requests ``device_count`` virtual CPU devices (must run before
    backends initialize; the XLA flag is ignored afterwards).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if device_count is not None:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={device_count}"
        if "xla_force_host_platform_device_count" in flags:
            # replace an inherited count (e.g. a test harness spawning
            # subprocesses with a different virtual-device topology)
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = f"{flags} {flag}"

    # Site customization (e.g. an accelerator tunnel) may have imported jax at
    # interpreter boot, caching jax_platforms from the env before we ran —
    # override the live config too, not just the env var.
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    try:
        import jax._src.xla_bridge as xb

        # Drop only third-party plugin factories (e.g. a tunneled accelerator);
        # standard platforms must stay registered — parts of jax (checkify's
        # MLIR lowerings) validate against the known-platform set at import.
        standard = {"cpu", "tpu", "cuda", "gpu", "rocm", "metal"}
        for name in list(xb._backend_factories):
            if name not in standard:
                xb._backend_factories.pop(name, None)
    except Exception:
        pass  # private API moved — JAX_PLATFORMS alone may still suffice


class BackendProbeTimeout(RuntimeError):
    """The backend gave no answer within the deadline (wedged tunnel?)."""


@dataclasses.dataclass(frozen=True)
class BackendInfo:
    """One deadline-bounded snapshot of the selected jax backend."""

    backend: str        # jax.default_backend(): "cpu" / "tpu" / ...
    device_kind: str    # e.g. "TPU v5 lite"
    device_count: int


_PROBE_CACHE: Optional[BackendInfo] = None
_PROBE_LOCK = threading.Lock()


def probe_backend(deadline_s: float = 60.0) -> BackendInfo:
    """Resolve the jax backend under a wall-clock deadline.

    On this container any first device touch rides the tunneled PJRT plugin
    and can hang forever when the tunnel wedges (CLAUDE.md) — so tools never
    call ``jax.devices()`` / ``jax.default_backend()`` bare (pitlint's
    PIT-CONTRACT rule enforces it). The probe runs on an abandonable daemon
    thread (:func:`~perceiver_io_tpu.utils.profiling.call_with_deadline`);
    on timeout it raises :class:`BackendProbeTimeout` instead of freezing
    the tool. The first successful answer is cached for the process — a
    backend does not change identity mid-run, and repeat calls must not
    spawn probe threads on a hot path.

    ``PIT_BENCH_BACKEND_DEADLINE_S`` overrides ``deadline_s`` when set (the
    same knob ``bench.py`` honors).
    """
    global _PROBE_CACHE
    if _PROBE_CACHE is not None:
        return _PROBE_CACHE

    def _probe() -> BackendInfo:
        import jax

        devices = jax.devices()
        return BackendInfo(
            backend=jax.default_backend(),
            device_kind=getattr(devices[0], "device_kind", "unknown"),
            device_count=len(devices),
        )

    from perceiver_io_tpu.utils.profiling import call_with_deadline

    deadline_s = float(
        os.environ.get("PIT_BENCH_BACKEND_DEADLINE_S", deadline_s))
    done, info = call_with_deadline(_probe, deadline_s, "backend_probe")
    if not done:
        raise BackendProbeTimeout(
            f"jax backend gave no answer within {deadline_s:g}s "
            f"(wedged axon tunnel?)"
        )
    with _PROBE_LOCK:
        if _PROBE_CACHE is None:
            _PROBE_CACHE = info
    return _PROBE_CACHE
