"""Audio/video data module for the multimodal autoencoder.

The reference has no audio/video data layer (its modules stop at IMDB and
MNIST); this feeds the multimodal extension (``models/multimodal.py``). The
box has zero egress, so there is no Kinetics downloader: ``synthetic=True``
(the default) generates class-conditioned clips with real cross-modal
structure — each class fixes an audio tone frequency and a video drift
direction, so classification, audio reconstruction, and video reconstruction
all have learnable signal. A directory layout reader
(``<root>/av/<split>/<class>/<clip>.npz`` with arrays ``video`` (T, H, W, C)
float in [0, 1] — integer-dtype clips are auto-rescaled by 1/255 — and ``audio``
(S, C_a)) covers real pre-extracted data. The [0, 1] video contract is what
makes the logged ``video_psnr`` comparable to published numbers.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from perceiver_io_tpu.data.pipeline import DataLoader


def synthetic_av_clips(
    n: int,
    video_shape: Tuple[int, int, int, int],
    num_audio_samples: int,
    num_audio_channels: int = 1,
    num_classes: int = 4,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(video (N, T, H, W, C), audio (N, S, C_a), labels (N,)) — class k
    drives both a drifting 2D sinusoid pattern in the video and a pure tone of
    class-dependent frequency in the audio."""
    t, h, w, c = video_shape
    rng = np.random.default_rng(seed)
    videos = np.empty((n, *video_shape), np.float32)
    audios = np.empty((n, num_audio_samples, num_audio_channels), np.float32)
    labels = rng.integers(0, num_classes, n).astype(np.int32)

    ys = np.linspace(0, 2 * np.pi, h)[None, :, None]
    xs = np.linspace(0, 2 * np.pi, w)[None, None, :]
    ts = np.arange(t, dtype=np.float32)[:, None, None]
    s = np.arange(num_audio_samples)[:, None] / num_audio_samples
    for i in range(n):
        k = labels[i]
        angle = 2 * np.pi * k / num_classes
        phase = rng.uniform(0, 2 * np.pi)
        drift_y = 0.4 * np.cos(angle) * ts
        drift_x = 0.4 * np.sin(angle) * ts
        pattern = 0.5 + 0.5 * np.sin(
            (k % 3 + 1) * (ys + drift_y) + (k % 2 + 1) * (xs + drift_x) + phase
        )  # (T, H, W)
        videos[i] = np.repeat(pattern[..., None], c, axis=-1)
        videos[i] += rng.normal(0, 0.02, videos[i].shape)
        freq = 20.0 * (k + 1)
        tone = np.sin(2 * np.pi * freq * s + phase)
        audios[i] = np.repeat(tone, num_audio_channels, axis=-1)
        audios[i] += rng.normal(0, 0.02, audios[i].shape)
    return videos.astype(np.float32), audios.astype(np.float32), labels


class AVDataset:
    def __init__(self, videos: np.ndarray, audios: np.ndarray, labels: np.ndarray):
        assert len(videos) == len(audios) == len(labels)
        self.videos = videos
        self.audios = audios
        self.labels = labels

    def __len__(self) -> int:
        return len(self.videos)

    def __getitem__(self, i: int):
        return self.videos[i], self.audios[i], self.labels[i]


def _collate(batch: Sequence) -> Dict[str, np.ndarray]:
    return {
        "video": np.stack([v for v, _, _ in batch]),
        "audio": np.stack([a for _, a, _ in batch]),
        "label": np.asarray([l for _, _, l in batch], np.int32),
    }


def load_av_tree(
    root: str,
    split: str,
    video_shape: Tuple[int, int, int, int],
    num_audio_samples: int,
    num_audio_channels: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, list]:
    """Read ``<root>/<split>/<class>/*.npz`` clips; class names sorted →
    label ids. Clips are center-cropped/truncated to the requested shapes."""
    classes = sorted(
        d for d in glob.glob(os.path.join(root, split, "*")) if os.path.isdir(d)
    )
    if not classes:
        raise FileNotFoundError(
            f"no class directories under {root}/{split} — place "
            "<class>/<clip>.npz clips there, or use synthetic=True"
        )
    t, h, w, c = video_shape
    videos, audios, labels = [], [], []
    for label, class_dir in enumerate(classes):
        for path in sorted(glob.glob(os.path.join(class_dir, "*.npz"))):
            with np.load(path) as z:
                video, audio = z["video"], z["audio"]
            if video.ndim != 4 or audio.ndim != 2:
                raise ValueError(f"{path}: need video (T,H,W,C) + audio (S,C)")
            vt, vh, vw, vc = video.shape
            if (vt < t or vh < h or vw < w or vc < c
                    or len(audio) < num_audio_samples
                    or audio.shape[1] < num_audio_channels):
                continue
            # crop first (a float copy of an uncropped 1080p clip would be
            # GBs), then enforce the [0, 1] contract the model and the
            # video_psnr metric expect — integer-dtype clips are pixel-valued
            top, left = (vh - h) // 2, (vw - w) // 2
            crop = video[:t, top : top + h, left : left + w, :c]
            if np.issubdtype(crop.dtype, np.integer):
                crop = crop.astype(np.float32) / 255.0
            else:
                crop = crop.astype(np.float32)
            videos.append(crop)
            audios.append(audio[:num_audio_samples, :num_audio_channels])
            labels.append(label)
    if not videos:
        raise FileNotFoundError(
            f"no usable clips under {root}/{split}: every clip was smaller "
            f"than the requested video {video_shape} / audio {num_audio_samples}"
        )
    return (
        np.stack(videos).astype(np.float32),
        np.stack(audios).astype(np.float32),
        np.asarray(labels, np.int32),
        [os.path.basename(c) for c in classes],
    )


class AVDataModule:
    """prepare/setup/loader surface matching the other data modules."""

    def __init__(
        self,
        root: str = ".cache",
        video_shape: Tuple[int, int, int, int] = (16, 224, 224, 3),
        num_audio_samples: int = 30720,
        num_audio_channels: int = 1,
        num_classes: int = 4,
        batch_size: int = 8,
        synthetic: bool = True,
        synthetic_size: int = 256,
        seed: int = 0,
        shard_id: int = 0,
        num_shards: int = 1,
    ):
        self.root = root
        self.video_shape = video_shape
        self.num_audio_samples = num_audio_samples
        self.num_audio_channels = num_audio_channels
        self.num_classes = num_classes
        self.batch_size = batch_size
        self.synthetic = synthetic
        self.synthetic_size = synthetic_size
        self.seed = seed
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.ds_train: Optional[AVDataset] = None
        self.ds_valid: Optional[AVDataset] = None

    def prepare_data(self):
        if not self.synthetic:
            av = os.path.join(self.root, "av")
            if not os.path.isdir(os.path.join(av, "train")):
                raise FileNotFoundError(
                    f"no AV data under {av} — place <split>/<class>/<clip>.npz "
                    "clips there, or use synthetic=True"
                )

    def setup(self):
        if self.synthetic:
            videos, audios, labels = synthetic_av_clips(
                self.synthetic_size,
                self.video_shape,
                self.num_audio_samples,
                self.num_audio_channels,
                self.num_classes,
                seed=self.seed,
            )
            if self.synthetic_size < 2:
                raise ValueError(
                    f"synthetic_size must be >= 2 to split train/val, got "
                    f"{self.synthetic_size}"
                )
            val = max(self.synthetic_size // 8, 1)
            val = min(val, len(videos) - 1)
            split = len(videos) - val
            self.ds_train = AVDataset(videos[:split], audios[:split], labels[:split])
            self.ds_valid = AVDataset(videos[split:], audios[split:], labels[split:])
        else:
            av = os.path.join(self.root, "av")
            vt, at, lt, classes = load_av_tree(
                av, "train", self.video_shape,
                self.num_audio_samples, self.num_audio_channels,
            )
            self.num_classes = len(classes)
            try:
                vv, av_, lv, val_classes = load_av_tree(
                    av, "val", self.video_shape,
                    self.num_audio_samples, self.num_audio_channels,
                )
                # label ids come from each split's own sorted class dirs; a
                # val split missing (or adding) a class would silently shift
                # every val label
                if val_classes != classes:
                    raise ValueError(
                        f"train/val class mismatch under {av}: "
                        f"train={classes} val={val_classes}"
                    )
            except FileNotFoundError:
                # no val split on disk: hold out a seeded-shuffled tail (the
                # tree reader returns clips class-by-class, so an unshuffled
                # tail would be all one class)
                if len(vt) < 2:
                    raise ValueError(
                        f"need at least 2 clips to split train/val, got {len(vt)}"
                    )
                order = np.random.default_rng(self.seed).permutation(len(vt))
                vt, at, lt = vt[order], at[order], lt[order]
                val = max(len(vt) // 10, 1)
                vv, av_, lv = vt[-val:], at[-val:], lt[-val:]
                vt, at, lt = vt[:-val], at[:-val], lt[:-val]
            self.ds_train = AVDataset(vt, at, lt)
            self.ds_valid = AVDataset(vv, av_, lv)

    def train_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_train, self.batch_size, _collate, shuffle=True,
            seed=self.seed, shard_id=self.shard_id, num_shards=self.num_shards,
        )

    def val_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_valid, self.batch_size, _collate, shuffle=False,
            drop_last=self.num_shards > 1,
            shard_id=self.shard_id, num_shards=self.num_shards,
        )
