"""Host-side input pipeline: sharded batching + background prefetch.

The framework's replacement for the reference's torch ``DataLoader`` worker
pool (reference ``data/imdb.py:136-149``): a lightweight first-party loader
tuned for SPMD training —

- deterministic per-epoch shuffling (seed ⊕ epoch),
- **per-host sharding**: each process sees only its ``1/num_shards`` slice of
  every batch (multi-host data parallelism; pair with
  ``jax.make_array_from_process_local_data``),
- ``drop_last`` so every step sees identical static shapes (no recompiles),
- background-thread prefetch overlapping host work with device steps,
- optional ``device_put`` with a target sharding for device prefetch.

Batches are dicts of numpy arrays (the step-function contract).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Iterator, Optional, Sequence

import numpy as np

Batch = Dict[str, np.ndarray]


def resolve_bucket_width(length: int, widths: Sequence[int]) -> int:
    """Smallest of the (sorted, ascending) ``widths`` holding ``length``;
    lengths beyond the final width (the cap) truncate to it.

    THE bucket rule — shared by the training collator (``data/imdb.py``),
    this loader's global-batch width oracle (``group_widths``), and the
    serving engine's variable-length text frontend (``inference/engine.py``),
    so train-time and serve-time programs land on identical shapes (one
    compiled executable per width, reused across both paths).
    """
    cap = widths[-1]
    length = min(max(int(length), 1), cap)
    return next(w for w in widths if w >= length)


def image_label_collate(batch) -> Batch:
    """(image, label) examples → {'image': (B, ...), 'label': (B,) int32} —
    the classifier step-function contract, shared by the image data modules."""
    images = np.stack([img for img, _ in batch])
    labels = np.asarray([y for _, y in batch], dtype=np.int32)
    return {"image": images, "label": labels}


class DataLoader:
    """Minibatch iterator over an indexable dataset.

    ``dataset`` must support ``len()`` and integer indexing; ``collate``
    maps a list of examples to a dict-of-arrays batch.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate: Callable[[list], Batch],
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        shard_id: int = 0,
        num_shards: int = 1,
        prefetch: int = 2,
        num_workers: int = 0,
        sort_key: Optional[np.ndarray] = None,
        sort_window: int = 0,
        group_widths: Optional[Sequence[int]] = None,
        group_size: int = 1,
    ):
        if not (0 <= shard_id < num_shards):
            raise ValueError(f"shard_id {shard_id} out of range for {num_shards} shards")
        if sort_window and sort_key is None:
            raise ValueError("sort_window requires a sort_key array")
        if sort_key is not None and len(sort_key) != len(dataset):
            raise ValueError(
                f"sort_key length {len(sort_key)} != dataset size {len(dataset)}"
            )
        if batch_size % num_shards != 0:
            raise ValueError(
                f"global batch_size {batch_size} not divisible by num_shards {num_shards}"
            )
        if num_shards > 1 and not drop_last:
            # A final partial batch would give hosts different step counts /
            # shapes and deadlock multi-host collectives.
            raise ValueError("drop_last=False is only supported with num_shards=1")
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate = collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.prefetch = prefetch
        # Decode pool for datasets whose __getitem__ is expensive (JPEG
        # decode + resize for ImageNet-scale folders). Threads, not processes:
        # PIL/numpy release the GIL in the hot parts, and threads share the
        # dataset's page cache / mmap state for free.
        self.num_workers = num_workers
        # Length-grouped batching: within each window of ``sort_window``
        # batches of the shuffled order, examples are sorted by ``sort_key``
        # (e.g. text length) so batches become length-homogeneous — the
        # enabler for the collator-side width buckets (short batches land in
        # small buckets instead of being dragged to the cap by one long
        # example). Batch ORDER within the window is re-shuffled so training
        # sees no short-to-long curriculum; the window bounds how far
        # examples can migrate, preserving shuffle quality. Deterministic in
        # (seed, epoch) and applied to the GLOBAL order before host sharding,
        # so multi-host stays consistent.
        self.sort_key = None if sort_key is None else np.asarray(sort_key)
        self.sort_window = sort_window
        # Width-bucketed batching (set by text modules): ``group_widths`` are
        # the bucket edges; each batch's width is the smallest bucket holding
        # its longest GLOBAL example (``sort_key`` must then be token
        # lengths), computed here — before host sharding — so every host
        # collates the same width for the same global batch (the multi-host
        # agreement VERDICT r3 item 2 asked for). ``group_size`` additionally
        # arranges same-width batches in runs of K within each sort window
        # (permuting K-GROUPS, not batches, to keep shuffle quality), so a
        # K-step dispatch window never mixes widths AND the consumed batches
        # remain an exact prefix of this loader's order — which is what keeps
        # mid-epoch resume arithmetic (skip_next) exact.
        if group_widths is not None and sort_key is None:
            raise ValueError("group_widths requires a sort_key of token lengths")
        self.group_widths = (
            None if group_widths is None else sorted(int(w) for w in group_widths)
        )
        self.group_size = max(1, int(group_size))
        self.epoch = 0
        self._skip = 0

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(np.uint32(self.seed) + np.uint32(epoch))
            idx = rng.permutation(n)
        else:
            idx = np.arange(n)
        if self.sort_key is not None and self.sort_window > 0:
            idx = self._length_grouped(idx, epoch)
        return idx

    def _length_grouped(self, idx: np.ndarray, epoch: int) -> np.ndarray:
        window = max(self.sort_window, 1) * self.batch_size
        rng = np.random.default_rng(
            (np.uint32(self.seed) ^ np.uint32(0x9E3779B9)) + np.uint32(epoch)
        )
        batches, tails = [], []
        for start in range(0, len(idx), window):
            win = idx[start : start + window]
            win = win[np.argsort(self.sort_key[win], kind="stable")]
            nb = len(win) // self.batch_size
            batches.extend(
                win[i * self.batch_size : (i + 1) * self.batch_size]
                for i in range(nb)
            )
            tails.append(win[nb * self.batch_size :])  # only the last window's
            # tail can be non-empty (every full window is a batch multiple)
        if self.group_widths is None or self.group_size <= 1:
            # permute batches WITHIN each window (the r3 behavior): the
            # window bounds how far an example migrated, so batch order must
            # not leak a short-to-long curriculum beyond it
            per_win = max(self.sort_window, 1)
            out = []
            for start in range(0, len(batches), per_win):
                chunk = batches[start : start + per_win]
                out.extend(chunk[j] for j in rng.permutation(len(chunk)))
        else:
            # Dispatch grouping: collect same-width batches ACROSS the whole
            # epoch into runs of K, then permute the RUNS. A K-step dispatch
            # window then almost always sees one width (<= one partial run
            # per width per epoch, vs one per sort window — measured 25% vs
            # ~100% full windows at K=16), batch COMPOSITION is untouched
            # (widths/examples per batch are exactly the windowed sort's),
            # and the emission order stays deterministic in (seed, epoch) —
            # which keeps multi-host lockstep and prefix-resume exact. Run-
            # granular global permutation also means no width curriculum.
            by_width: Dict[int, list] = {}
            for b in batches:
                by_width.setdefault(self._batch_width(b), []).append(b)
            full, partial = [], []
            for w in sorted(by_width):
                group = by_width[w]
                for i in range(0, len(group), self.group_size):
                    run = group[i : i + self.group_size]
                    (full if len(run) == self.group_size else partial).append(run)
            out = []
            # full runs first: every run is exactly K batches, so the
            # trainer's greedy stacker stays K-aligned no matter how the
            # permutation abuts same-width runs; the <= one-partial-run-per-
            # width remainder goes last, where misalignment cannot cascade
            for r in rng.permutation(len(full)):
                out.extend(full[r])
            for r in rng.permutation(len(partial)):
                out.extend(partial[r])
        out.extend(tails)
        return np.concatenate(out) if out else idx

    def _batch_width(self, batch_idx: np.ndarray) -> int:
        """Bucket width of a GLOBAL batch — identical on every host, because
        it reads the shared ``sort_key`` (token lengths) for the full batch
        rather than any host-local slice."""
        longest = int(self.sort_key[batch_idx].max(initial=1))
        return resolve_bucket_width(longest, self.group_widths)

    def reshard(self, shard_id: int, num_shards: int) -> None:
        """Re-point this loader at a new world slice (elastic resize).

        The GLOBAL batch order is a pure function of (seed, epoch, dataset),
        independent of the shard layout — ``_epoch_indices`` never reads
        ``shard_id``/``num_shards``; only the per-host contiguous slice of
        each global batch does. So after an elastic shrink/grow every
        survivor calls this with its new dense rank and the new world size,
        and the NEXT iteration (or a mid-epoch restart positioned with
        ``epoch`` + :meth:`skip_next`) re-slices the SAME global batches at
        the new width — the dead host's examples land back in the
        survivors' slices deterministically, with no coordination beyond
        agreeing on the world. Same validation as construction: the global
        batch size must divide by every world size the run can resize
        through (pick e.g. a multiple of lcm(4, 3) for a 4→3→4 drill).
        """
        if not (0 <= shard_id < num_shards):
            raise ValueError(
                f"shard_id {shard_id} out of range for {num_shards} shards")
        if self.batch_size % num_shards != 0:
            raise ValueError(
                f"global batch_size {self.batch_size} not divisible by "
                f"num_shards {num_shards}")
        if num_shards > 1 and not self.drop_last:
            raise ValueError(
                "drop_last=False is only supported with num_shards=1")
        self.shard_id = shard_id
        self.num_shards = num_shards

    def skip_next(self, num_batches: int) -> None:
        """Skip the first ``num_batches`` of the NEXT iteration — deterministic
        mid-epoch resume: the skipped examples are never loaded, and the
        remaining batches are exactly what an uninterrupted run would yield."""
        self._skip = num_batches

    def _batches(self) -> Iterator[Batch]:
        # consume the epoch number up front so an early `break` (fixed-step
        # training loops) still advances the shuffle for the next iteration
        epoch = self.epoch
        self.epoch += 1
        skip = self._skip
        self._skip = 0
        idx = self._epoch_indices(epoch)
        n = len(idx)
        per_shard = self.batch_size // self.num_shards
        stop = n - self.batch_size + 1 if self.drop_last else n
        pool = (
            ThreadPoolExecutor(self.num_workers, thread_name_prefix="loader")
            if self.num_workers > 0
            else None
        )
        try:
            for start in range(skip * self.batch_size, max(stop, 0), self.batch_size):
                batch_idx = idx[start : start + self.batch_size]
                # this host's contiguous slice of the global batch
                local = batch_idx[self.shard_id * per_shard : (self.shard_id + 1) * per_shard]
                if len(local) == 0:
                    continue
                if pool is not None:
                    examples = list(pool.map(self.dataset.__getitem__, map(int, local)))
                else:
                    examples = [self.dataset[int(i)] for i in local]
                if self.group_widths is not None:
                    # width decided from the GLOBAL batch (host-consistent);
                    # the collate callable must accept the width kwarg
                    yield self.collate(examples, width=self._batch_width(batch_idx))
                else:
                    yield self.collate(examples)
        finally:
            if pool is not None:
                pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self) -> Iterator[Batch]:
        if self.prefetch <= 0:
            yield from self._batches()
            return
        yield from _prefetch_thread(self._batches(), self.prefetch)


def _prefetch_thread(it: Iterator, size: int) -> Iterator:
    q: queue.Queue = queue.Queue(maxsize=size)
    _END = object()
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not put(item):
                    return
            put(_END)
        except BaseException as e:  # surface errors in the consumer
            put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # consumer broke early: release the (possibly blocked) worker
        stop.set()


def prefetch_to_device(
    it: Iterator[Batch], sharding=None, size: int = 2
) -> Iterator[Batch]:
    """Move batches onto device(s) ahead of consumption.

    With a ``jax.sharding.Sharding``, arrays land pre-sharded (the device-side
    half of the input pipeline); otherwise default placement.
    """
    import jax

    def put(batch: Batch):
        if sharding is None:
            return jax.device_put(batch)
        return jax.device_put(batch, sharding)

    buffer = []
    for batch in it:
        buffer.append(put(batch))
        if len(buffer) > size:
            yield buffer.pop(0)
    yield from buffer
