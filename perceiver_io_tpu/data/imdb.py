"""IMDB sentiment / MLM text data module.

Mirrors the reference data layer's surface and on-disk layout (reference
``data/imdb.py``): reads the ``aclImdb/{split}/{neg,pos}/*.txt`` tree under
``<root>/IMDB`` (so an existing download cache drops in unchanged), trains and
caches a WordPiece tokenizer at ``<root>/imdb-tokenizer-<vocab>.json`` on
first use, and collates batches by padding/truncating to ``max_seq_len`` with
``pad_mask = token_ids == pad_id``.

Differences, by design:

- tokenization is first-party (``data/tokenizer.py``) — no Rust dependency;
- the download step (reference ``imdb.py:115-117`` via torchtext) is a
  first-party guarded fetch (``data/download.py``): attempted only when the
  local tree is absent, with ``download=False`` and ``synthetic=True`` as
  offline modes (a zero-egress box gets one clear error naming both);
- batches are dicts of numpy arrays feeding the SPMD input pipeline
  (``data/pipeline.py``) instead of torch tensors.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from perceiver_io_tpu.data.pipeline import DataLoader, resolve_bucket_width
from perceiver_io_tpu.data.tokenizer import (
    PAD_TOKEN,
    WordPieceTokenizer,
    create_tokenizer,
    load_tokenizer,
    save_tokenizer,
    train_tokenizer,
)

_POSITIVE_WORDS = (
    "awesome brilliant captivating delightful excellent fantastic great "
    "inspiring lovely masterful moving outstanding perfect powerful stunning "
    "superb touching wonderful gripping charming"
).split()
_NEGATIVE_WORDS = (
    "awful boring clumsy disappointing dreadful horrible lazy mediocre "
    "miserable painful pointless predictable shallow sloppy terrible tedious "
    "unwatchable weak wooden forgettable"
).split()
_NEUTRAL_WORDS = (
    "movie film story plot actor actress director scene script camera music "
    "ending character dialogue performance production audience screen watch "
    "time people year minute way thing life world night day man woman"
).split()


def synthetic_reviews(
    n: int, seed: int = 0, min_words: int = 20, max_words: int = 120
) -> Tuple[List[str], List[int]]:
    """Deterministic sentiment-labelled word-soup corpus (zero-egress stand-in
    for the IMDB download)."""
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for i in range(n):
        label = int(rng.integers(0, 2))
        length = int(rng.integers(min_words, max_words))
        sentiment = _POSITIVE_WORDS if label else _NEGATIVE_WORDS
        words = [
            str(rng.choice(sentiment)) if rng.random() < 0.3 else str(rng.choice(_NEUTRAL_WORDS))
            for _ in range(length)
        ]
        texts.append(" ".join(words))
        labels.append(label)
    return texts, labels


def load_split(root: str, split: str) -> Tuple[List[str], List[int]]:
    """Read the aclImdb directory tree (reference ``data/imdb.py:24-38`` layout)."""
    if split not in ("train", "test"):
        raise ValueError(f"invalid split: {split}")
    texts: List[str] = []
    labels: List[int] = []
    for label, name in enumerate(("neg", "pos")):
        pattern = os.path.join(root, "IMDB", "aclImdb", split, name, "*.txt")
        for path in sorted(glob.glob(pattern)):
            with open(path, encoding="utf-8") as f:
                texts.append(f.read())
            labels.append(label)
    if not texts:
        raise FileNotFoundError(
            f"no IMDB data under {os.path.join(root, 'IMDB', 'aclImdb', split)} — "
            "place the aclImdb tree there, or use synthetic=True"
        )
    return texts, labels


class IMDBDataset:
    def __init__(self, texts: Sequence[str], labels: Sequence[int]):
        assert len(texts) == len(labels)
        self.texts = list(texts)
        self.labels = list(labels)

    def __len__(self) -> int:
        return len(self.texts)

    def __getitem__(self, i: int) -> Tuple[int, str]:
        return self.labels[i], self.texts[i]


class Collator:
    """Pad/truncate to ``max_seq_len``; emit labels, ids and pad mask
    (reference ``data/imdb.py:52-68`` contract, dict-of-arrays form).

    ``bucket_widths``: optional sorted set of sequence widths — each batch is
    padded to the SMALLEST bucket that fits its longest (truncated) sequence
    instead of always to ``max_seq_len``. This is the SPMD-safe version of
    the reference's pad-to-longest (``enable_padding``, reference
    ``data/imdb.py:56-57``): shapes stay static per bucket (one compiled
    executable each — 2-3 compiles, cached), while short batches skip most of
    the padded-token work. Pair with the loader's length-sorted windows
    (``DataLoader(sort_key=..., sort_window=...)``) so batches are
    length-homogeneous and actually land in small buckets — under plain
    shuffling the per-batch MAX length is near the cap almost always.
    ``max_seq_len`` is always included as the final bucket.
    """

    def __init__(
        self,
        tokenizer: WordPieceTokenizer,
        max_seq_len: int,
        bucket_widths: Optional[Sequence[int]] = None,
    ):
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len
        self.pad_id = tokenizer.token_to_id(PAD_TOKEN)
        if bucket_widths:
            widths = sorted({int(w) for w in bucket_widths})
            if widths[0] <= 0 or widths[-1] > max_seq_len:
                raise ValueError(
                    f"bucket_widths must lie in [1, max_seq_len={max_seq_len}], "
                    f"got {widths}"
                )
            if widths[-1] != max_seq_len:
                widths.append(max_seq_len)
            self.bucket_widths: Optional[List[int]] = widths
        else:
            self.bucket_widths = None
        # truncation only: collate writes ids into a pre-filled pad_id array,
        # so tokenizer-level padding would be duplicated work on the hot path
        tokenizer.enable_truncation(max_seq_len)

    def collate(
        self,
        batch: Sequence[Tuple[int, str]],
        width: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """``width``: externally-decided bucket width (the DataLoader passes
        the GLOBAL batch's width so multi-host shards collate identical
        shapes); None = decide locally from this batch's encoded lengths
        (single-host behavior, and the predict-path ``encode``)."""
        labels = np.asarray([y for y, _ in batch], dtype=np.int32)
        encoded = self.tokenizer.encode_batch([x for _, x in batch])
        if width is None:
            width = self.max_seq_len  # static: SPMD-friendly, no recompiles
            if self.bucket_widths is not None:
                longest = max((len(e) for e in encoded), default=1)
                width = resolve_bucket_width(longest, self.bucket_widths)
        ids = np.full((len(batch), width), self.pad_id, dtype=np.int32)
        for i, e in enumerate(encoded):
            ids[i, : min(len(e), width)] = e[:width]
        pad_mask = ids == self.pad_id
        return {"label": labels, "token_ids": ids, "pad_mask": pad_mask}

    def encode(self, samples: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Wrap raw strings for the predict path (reference ``imdb.py:66-68``)."""
        batch = self.collate([(0, s) for s in samples])
        return batch["token_ids"], batch["pad_mask"]


class IMDBDataModule:
    """Prepare/setup/loader surface mirroring the reference module
    (``data/imdb.py:71-149``), backed by the first-party pipeline."""

    def __init__(
        self,
        root: str = ".cache",
        max_seq_len: int = 512,
        vocab_size: int = 10003,
        batch_size: int = 64,
        synthetic: bool = False,
        synthetic_size: int = 2048,
        seed: int = 0,
        shard_id: int = 0,
        num_shards: int = 1,
        download: bool = True,
        bucket_widths: Optional[Sequence[int]] = None,
        length_sort_window: int = 8,
        dispatch_group: int = 1,
    ):
        self.root = root
        self.download = download
        self.max_seq_len = max_seq_len
        self.vocab_size = vocab_size
        self.batch_size = batch_size
        self.synthetic = synthetic
        self.synthetic_size = synthetic_size
        self.seed = seed
        self.shard_id = shard_id
        self.num_shards = num_shards
        # width buckets (see Collator) + the loader-side length grouping that
        # makes them effective; the sort window only applies when buckets are
        # on, so the default path is byte-identical to previous rounds.
        # Multi-host: the LOADER decides each global batch's width from the
        # shared token-length table (DataLoader.group_widths), so per-host
        # collation shapes always agree — the r3 incompatibility guard is
        # gone. dispatch_group (= the trainer's steps_per_dispatch) arranges
        # same-width batches in K-runs so stacked dispatch windows never mix
        # widths.
        self.bucket_widths = bucket_widths
        self.length_sort_window = length_sort_window
        self.dispatch_group = max(1, int(dispatch_group))
        self._train_token_lengths: Optional[np.ndarray] = None
        self._valid_token_lengths: Optional[np.ndarray] = None

        suffix = "synthetic-" if synthetic else ""
        self.tokenizer_path = os.path.join(root, f"imdb-{suffix}tokenizer-{vocab_size}.json")
        self.tokenizer: Optional[WordPieceTokenizer] = None
        self.collator: Optional[Collator] = None
        self.ds_train: Optional[IMDBDataset] = None
        self.ds_valid: Optional[IMDBDataset] = None

    @classmethod
    def create(cls, args) -> "IMDBDataModule":
        return cls(
            root=args.root,
            max_seq_len=args.max_seq_len,
            vocab_size=args.vocab_size,
            batch_size=args.batch_size,
            synthetic=getattr(args, "synthetic", False),
            bucket_widths=getattr(args, "bucket_widths", None),
            length_sort_window=getattr(args, "length_sort_window", 8),
            dispatch_group=getattr(args, "steps_per_dispatch", 1),
        )

    def _train_texts(self) -> Tuple[List[str], List[int]]:
        if self.synthetic:
            return synthetic_reviews(self.synthetic_size, seed=self.seed)
        return load_split(self.root, "train")

    def _valid_texts(self) -> Tuple[List[str], List[int]]:
        if self.synthetic:
            return synthetic_reviews(max(self.synthetic_size // 8, 64), seed=self.seed + 1)
        return load_split(self.root, "test")  # val = test split, as the reference

    def prepare_data(self):
        """Download-if-absent, then train + cache the WordPiece tokenizer on
        first run. Rank-0 work with a cross-host barrier (the reference runs
        ``prepare_data`` on rank 0 only, ``imdb.py:114-126``; here every rank
        calls it and non-zero ranks wait instead of racing the filesystem)."""
        import jax

        if jax.process_index() == 0:
            if not self.synthetic and self.download and not os.path.isdir(
                os.path.join(self.root, "IMDB", "aclImdb", "train")
            ):
                from perceiver_io_tpu.data.download import ensure_imdb

                ensure_imdb(self.root)
            if not os.path.exists(self.tokenizer_path):
                os.makedirs(self.root, exist_ok=True)
                texts, _ = self._train_texts()
                tokenizer = create_tokenizer(("<br />", " "))
                train_tokenizer(tokenizer, texts, vocab_size=self.vocab_size)
                save_tokenizer(tokenizer, self.tokenizer_path)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("imdb_prepare_data")

    def setup(self):
        self.tokenizer = load_tokenizer(self.tokenizer_path)
        self.collator = Collator(
            self.tokenizer, self.max_seq_len, bucket_widths=self.bucket_widths
        )
        self.ds_train = IMDBDataset(*self._train_texts())
        self.ds_valid = IMDBDataset(*self._valid_texts())
        if self.bucket_widths:
            # One-time TOKEN-length table over the full train split (every
            # host computes the identical table — the dataset is replicated;
            # ~seconds at tokenizer encode rates, PERF.md). This is both the
            # length-sort key (tighter grouping than the r3 char-count proxy)
            # and the loader's width oracle: widths derive from GLOBAL
            # lengths, so multi-host shards agree by construction.
            self._train_token_lengths = np.asarray(
                [len(e) for e in self.tokenizer.encode_batch(self.ds_train.texts)],
                dtype=np.int64,
            )

    def _valid_lengths(self) -> np.ndarray:
        """The SAME oracle for the eval split, built lazily on the first
        ``val_dataloader()`` call (cached): the reference pads eval batches to
        their longest sequence (reference ``data/imdb.py:55-57``,
        enable_padding with no fixed length); the SPMD-safe equivalent is the
        smallest bucket that fits the GLOBAL batch's longest example, decided
        loader-side from this shared table so every host collates identical
        shapes (VERDICT r4 missing item). Lazy (ADVICE r5): train-only
        bucketed runs never pay for tokenizing the whole validation split."""
        if self._valid_token_lengths is None:
            self._valid_token_lengths = np.asarray(
                [len(e) for e in self.tokenizer.encode_batch(self.ds_valid.texts)],
                dtype=np.int64,
            )
        return self._valid_token_lengths

    def train_dataloader(self) -> DataLoader:
        sort_key = None
        sort_window = 0
        group_widths = None
        if self.bucket_widths:
            sort_key = self._train_token_lengths
            sort_window = self.length_sort_window
            group_widths = self.collator.bucket_widths  # incl. appended cap
        return DataLoader(
            self.ds_train,
            batch_size=self.batch_size,
            collate=self.collator.collate,
            shuffle=True,
            seed=self.seed,
            shard_id=self.shard_id,
            num_shards=self.num_shards,
            sort_key=sort_key,
            sort_window=sort_window,
            group_widths=group_widths,
            group_size=self.dispatch_group,
        )

    def val_dataloader(self) -> DataLoader:
        sort_key = None
        group_widths = None
        if self.bucket_widths:
            # Eval rides the same width oracle as train (see _valid_lengths;
            # the per-width device-step savings are the r3 bucketed-width
            # table's; the eval-split measurement is PERF.md r5's eval-width
            # row).
            sort_key = self._valid_lengths()
            group_widths = self.collator.bucket_widths  # incl. appended cap
        return DataLoader(
            self.ds_valid,
            batch_size=self.batch_size,
            collate=self.collator.collate,
            shuffle=False,
            # evaluate the full set when single-host (multi-host must drop for
            # lockstep collectives)
            drop_last=self.num_shards > 1,
            shard_id=self.shard_id,
            num_shards=self.num_shards,
            sort_key=sort_key,
            sort_window=0,
            group_widths=group_widths,
        )
