from perceiver_io_tpu.data.tokenizer import (
    PAD_TOKEN,
    UNK_TOKEN,
    MASK_TOKEN,
    SPECIAL_TOKENS,
)

__all__ = ["PAD_TOKEN", "UNK_TOKEN", "MASK_TOKEN", "SPECIAL_TOKENS"]
