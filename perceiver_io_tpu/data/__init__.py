from perceiver_io_tpu.data.tokenizer import (
    PAD_TOKEN,
    UNK_TOKEN,
    MASK_TOKEN,
    SPECIAL_TOKENS,
    WordPieceTokenizer,
    create_tokenizer,
    train_tokenizer,
    save_tokenizer,
    load_tokenizer,
)
from perceiver_io_tpu.data.pipeline import DataLoader, prefetch_to_device
from perceiver_io_tpu.data.imdb import (
    Collator,
    IMDBDataModule,
    IMDBDataset,
    load_split,
    synthetic_reviews,
)
from perceiver_io_tpu.data.mnist import (
    MNISTDataModule,
    MNISTDataset,
    load_mnist,
    synthetic_digits,
)
from perceiver_io_tpu.data.av import (
    AVDataModule,
    AVDataset,
    load_av_tree,
    synthetic_av_clips,
)
from perceiver_io_tpu.data.imagefolder import (
    ImageFolderDataModule,
    ImageFolderDataset,
    SyntheticImageDataset,
    list_image_folder,
)

__all__ = [
    "PAD_TOKEN",
    "UNK_TOKEN",
    "MASK_TOKEN",
    "SPECIAL_TOKENS",
    "WordPieceTokenizer",
    "create_tokenizer",
    "train_tokenizer",
    "save_tokenizer",
    "load_tokenizer",
    "DataLoader",
    "prefetch_to_device",
    "Collator",
    "IMDBDataModule",
    "IMDBDataset",
    "load_split",
    "synthetic_reviews",
    "MNISTDataModule",
    "MNISTDataset",
    "load_mnist",
    "synthetic_digits",
    "AVDataModule",
    "AVDataset",
    "load_av_tree",
    "synthetic_av_clips",
    "ImageFolderDataModule",
    "ImageFolderDataset",
    "SyntheticImageDataset",
    "list_image_folder",
]
