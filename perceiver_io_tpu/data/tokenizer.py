"""Tokenizer constants (full first-party WordPiece pipeline lands with the
data layer; see SURVEY.md §7 step 4).

Special-token contract matches the reference (``perceiver/tokenizer.py:10-15``):
``[PAD]``, ``[UNK]``, ``[MASK]`` occupy ids 0, 1, 2 — the masking op relies on
special tokens filling the first ids (reference ``model.py:284-289``).
"""

PAD_TOKEN = "[PAD]"
UNK_TOKEN = "[UNK]"
MASK_TOKEN = "[MASK]"

SPECIAL_TOKENS = [PAD_TOKEN, UNK_TOKEN, MASK_TOKEN]
