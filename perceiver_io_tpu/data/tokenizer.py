"""First-party WordPiece tokenizer pipeline (host-side, off the device path).

The reference delegates tokenization to the HuggingFace ``tokenizers`` Rust
library (reference ``perceiver/tokenizer.py:10-36``); this framework supplies
its own implementation — a pure-Python trainer plus an optional C++ fast
encode path (``perceiver_io_tpu/native``) bound via ctypes — so the data layer
has no third-party native dependency.

Behavioral contract (matching the reference surface):

- special tokens ``[PAD]``, ``[UNK]``, ``[MASK]`` at ids 0, 1, 2 (the masking
  op assumes specials occupy the first ids, reference ``model.py:284-289``),
- normalization: optional literal replacements (e.g. ``'<br />' → ' '``, as the
  IMDB module adds at ``data/imdb.py:124``), then NFD → lowercase → strip
  accents (reference ``tokenizer.py:33``),
- pre-tokenization: contiguous word characters or contiguous
  non-word/non-space punctuation (the ``Whitespace`` pre-tokenizer's
  ``\\w+|[^\\w\\s]+`` rule),
- WordPiece: greedy longest-match-first with ``##`` continuation prefix,
  whole-word ``[UNK]`` fallback, likelihood-scored pair merges in training
  (score = freq(ab) / freq(a)·freq(b)),
- decoding joins tokens and strips ``##`` continuations.
"""

from __future__ import annotations

import json
import re
import unicodedata
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PAD_TOKEN = "[PAD]"
UNK_TOKEN = "[UNK]"
MASK_TOKEN = "[MASK]"

SPECIAL_TOKENS = [PAD_TOKEN, UNK_TOKEN, MASK_TOKEN]

_PRETOKENIZE_RE = re.compile(r"\w+|[^\w\s]+")

CONTINUATION_PREFIX = "##"
MAX_CHARS_PER_WORD = 100


def normalize(text: str, replacements: Sequence[Tuple[str, str]] = ()) -> str:
    """Literal replacements, then NFD → lowercase → strip combining marks."""
    for old, new in replacements:
        text = text.replace(old, new)
    text = unicodedata.normalize("NFD", text)
    text = text.lower()
    return "".join(c for c in text if unicodedata.category(c) != "Mn")


def pre_tokenize(text: str) -> List[str]:
    """Split normalized text into word / punctuation chunks."""
    return _PRETOKENIZE_RE.findall(text)


class WordPieceTokenizer:
    """Trainable WordPiece tokenizer with the reference pipeline's surface
    (create/train/save/load/encode/decode, reference ``tokenizer.py:18-36``)."""

    def __init__(
        self,
        vocab: Optional[Dict[str, int]] = None,
        replacements: Sequence[Tuple[str, str]] = (),
    ):
        self.vocab: Dict[str, int] = dict(vocab) if vocab else {}
        self.replacements: List[Tuple[str, str]] = [tuple(r) for r in replacements]
        self._ids_to_tokens: Dict[int, str] = {}
        self._word_cache: Dict[str, List[int]] = {}
        self._native = None  # lazily attached C++ encoder
        self._truncation: Optional[int] = None
        self._padding: bool = False
        if self.vocab:
            self._rebuild()

    # -- vocab bookkeeping -------------------------------------------------

    def _rebuild(self):
        self._ids_to_tokens = {i: t for t, i in self.vocab.items()}
        self._word_cache.clear()
        self._native = None

    def get_vocab_size(self) -> int:
        return len(self.vocab)

    def token_to_id(self, token: str) -> Optional[int]:
        return self.vocab.get(token)

    def id_to_token(self, idx: int) -> Optional[str]:
        return self._ids_to_tokens.get(idx)

    # -- reference-surface config (Collator uses these, imdb.py:55-57) ----

    def enable_truncation(self, max_length: int):
        self._truncation = max_length

    def enable_padding(self):
        self._padding = True

    # -- training ----------------------------------------------------------

    def train_from_iterator(self, data: Iterable[str], vocab_size: int):
        """Likelihood-scored WordPiece training (the algorithm behind the HF
        WordPieceTrainer the reference calls at ``tokenizer.py:26-28``).

        Incremental: symbol/pair frequencies and a pair→words index are
        maintained across merges (only words containing the merged pair are
        touched), with a lazily-revalidated max-heap over pair scores — a
        10k-vocab training over a real corpus runs in minutes, not hours.
        """
        import heapq

        word_freqs: Dict[str, int] = {}
        for text in data:
            for w in pre_tokenize(normalize(text, self.replacements)):
                if len(w) <= MAX_CHARS_PER_WORD:
                    word_freqs[w] = word_freqs.get(w, 0) + 1

        # split each word into symbols: first char bare, rest ## prefixed
        splits: Dict[str, List[str]] = {
            w: [w[0]] + [CONTINUATION_PREFIX + c for c in w[1:]] for w in word_freqs
        }

        vocab: Dict[str, int] = {t: i for i, t in enumerate(SPECIAL_TOKENS)}
        alphabet = sorted({s for symbols in splits.values() for s in symbols})
        for sym in alphabet:
            if sym not in vocab and len(vocab) < vocab_size:
                vocab[sym] = len(vocab)

        sym_freq: Dict[str, int] = {}
        pair_freq: Dict[Tuple[str, str], int] = {}
        pair_words: Dict[Tuple[str, str], set] = {}
        for w, freq in word_freqs.items():
            symbols = splits[w]
            for s in symbols:
                sym_freq[s] = sym_freq.get(s, 0) + freq
            for p in zip(symbols, symbols[1:]):
                pair_freq[p] = pair_freq.get(p, 0) + freq
                pair_words.setdefault(p, set()).add(w)

        def score(p: Tuple[str, str]) -> float:
            f = pair_freq.get(p, 0)
            if f <= 0:
                return 0.0
            return f / (sym_freq[p[0]] * sym_freq[p[1]])

        # max-heap with lazy revalidation: entries carry the score at push
        # time; on pop, a stale score is recomputed and re-pushed.
        heap = [(-score(p), p) for p in pair_freq]
        heapq.heapify(heap)

        def add_word(w: str, freq: int):
            symbols = splits[w]
            for s in symbols:
                sym_freq[s] = sym_freq.get(s, 0) + freq
            for p in zip(symbols, symbols[1:]):
                was = pair_freq.get(p, 0)
                pair_freq[p] = was + freq
                pair_words.setdefault(p, set()).add(w)
                if was == 0:
                    heapq.heappush(heap, (-score(p), p))

        def remove_word(w: str, freq: int):
            symbols = splits[w]
            for s in symbols:
                sym_freq[s] -= freq
            for p in zip(symbols, symbols[1:]):
                pair_freq[p] -= freq
                pair_words.get(p, set()).discard(w)

        while len(vocab) < vocab_size and heap:
            neg, best = heapq.heappop(heap)
            current = score(best)
            if current <= 0.0:
                continue
            if -neg != current:  # stale — revalidate
                heapq.heappush(heap, (-current, best))
                continue
            a, b = best
            stripped = b[len(CONTINUATION_PREFIX):] if b.startswith(CONTINUATION_PREFIX) else b
            merged = a + stripped
            if merged in vocab:
                pair_freq[best] = 0  # degenerate duplicate — retire the pair
                continue
            vocab[merged] = len(vocab)

            affected = list(pair_words.get(best, ()))
            for w in affected:
                freq = word_freqs[w]
                remove_word(w, freq)
                symbols = splits[w]
                out: List[str] = []
                i = 0
                while i < len(symbols):
                    if i + 1 < len(symbols) and symbols[i] == a and symbols[i + 1] == b:
                        out.append(merged)
                        i += 2
                    else:
                        out.append(symbols[i])
                        i += 1
                splits[w] = out
                add_word(w, freq)
            pair_words.pop(best, None)
            pair_freq.pop(best, None)
            if merged not in sym_freq:
                sym_freq[merged] = 0

        self.vocab = vocab
        self._rebuild()

    # -- encoding ----------------------------------------------------------

    def _encode_word_py(self, word: str) -> List[int]:
        """Greedy longest-match-first; whole-word [UNK] on failure."""
        ids: List[int] = []
        start = 0
        n = len(word)
        while start < n:
            end = n
            found = None
            while end > start:
                piece = word[start:end]
                if start > 0:
                    piece = CONTINUATION_PREFIX + piece
                idx = self.vocab.get(piece)
                if idx is not None:
                    found = idx
                    break
                end -= 1
            if found is None:
                return [self.vocab[UNK_TOKEN]]
            ids.append(found)
            start = end
        return ids

    def _encode_word(self, word: str) -> List[int]:
        if len(word) > MAX_CHARS_PER_WORD:
            return [self.vocab[UNK_TOKEN]]
        cached = self._word_cache.get(word)
        if cached is None:
            if self._native is None:
                self._attach_native()
            if self._native:
                cached = self._native.encode_word(word)
            else:
                cached = self._encode_word_py(word)
            self._word_cache[word] = cached
        return cached

    def _attach_native(self):
        """Try the C++ fast path once; fall back to pure Python silently."""
        if self._native is not None:
            return
        try:
            from perceiver_io_tpu.native.wordpiece import NativeWordPiece

            self._native = NativeWordPiece(self.vocab, self.vocab[UNK_TOKEN])
        except Exception:
            self._native = False

    def encode_ids(self, text: str) -> List[int]:
        ids: List[int] = []
        for w in pre_tokenize(normalize(text, self.replacements)):
            ids.extend(self._encode_word(w))
        if self._truncation is not None:
            ids = ids[: self._truncation]
        return ids

    def encode_batch(self, texts: Sequence[str]) -> List[List[int]]:
        """Encode many texts; pads to the longest (or truncation length) with
        PAD id when padding is enabled — the Collator contract
        (reference ``data/imdb.py:52-64``)."""
        encoded = [self.encode_ids(t) for t in texts]
        if self._padding:
            width = max((len(e) for e in encoded), default=0)
            if self._truncation is not None:
                width = min(max(width, 0), self._truncation)
            pad_id = self.vocab[PAD_TOKEN]
            encoded = [e + [pad_id] * (width - len(e)) for e in encoded]
        return encoded

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        special_ids = {self.vocab.get(t) for t in SPECIAL_TOKENS}
        parts: List[str] = []
        for i in ids:
            if skip_special_tokens and i in special_ids:
                continue
            tok = self._ids_to_tokens.get(int(i))
            if tok is None:
                continue
            if tok.startswith(CONTINUATION_PREFIX) and parts:
                parts[-1] += tok[len(CONTINUATION_PREFIX):]
            else:
                parts.append(tok)
        return " ".join(parts)

    # -- persistence -------------------------------------------------------

    def save(self, path: str, format: str = "native"):
        """Write the tokenizer as JSON.

        ``format='native'`` is this framework's compact schema;
        ``format='hf'`` emits the HuggingFace ``tokenizers`` schema the
        reference caches (reference ``tokenizer.py:26-36``), loadable by the
        HF Rust library and by :meth:`from_file` alike.
        """
        if format == "native":
            payload = {
                "format": "perceiver_io_tpu.wordpiece.v1",
                "vocab": self.vocab,
                "replacements": self.replacements,
            }
        elif format == "hf":
            payload = self.to_hf_dict()
        else:
            raise ValueError(f"format must be 'native' or 'hf', got {format!r}")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, ensure_ascii=False)

    def to_hf_dict(self) -> dict:
        """This tokenizer in the HF ``tokenizers`` JSON schema (the pipeline
        the reference builds at ``tokenizer.py:26-36``: Replace* → NFD →
        Lowercase → StripAccents, Whitespace pre-tokenizer, WordPiece model +
        decoder, specials registered as added tokens)."""
        normalizers = [
            {"type": "Replace", "pattern": {"String": old}, "content": new}
            for old, new in self.replacements
        ] + [{"type": "NFD"}, {"type": "Lowercase"}, {"type": "StripAccents"}]
        return {
            "version": "1.0",
            "truncation": None,
            "padding": None,
            "added_tokens": [
                {
                    "id": self.vocab[t], "special": True, "content": t,
                    "single_word": False, "lstrip": False, "rstrip": False,
                    "normalized": False,
                }
                for t in SPECIAL_TOKENS if t in self.vocab
            ],
            "normalizer": {"type": "Sequence", "normalizers": normalizers},
            "pre_tokenizer": {"type": "Whitespace"},
            "post_processor": None,
            "decoder": {
                "type": "WordPiece",
                "prefix": CONTINUATION_PREFIX,
                "cleanup": True,
            },
            "model": {
                "type": "WordPiece",
                "unk_token": UNK_TOKEN,
                "continuing_subword_prefix": CONTINUATION_PREFIX,
                "max_input_chars_per_word": MAX_CHARS_PER_WORD,
                "vocab": self.vocab,
            },
        }

    @classmethod
    def from_hf_dict(cls, payload: dict) -> "WordPieceTokenizer":
        """Build from the HF ``tokenizers`` JSON schema — the format of the
        reference's cached artifact (``.cache/imdb-tokenizer-10003.json``).
        Token ids index embedding rows, so loading the exact reference vocab
        is what makes an imported reference checkpoint usable.

        Raises on pipeline components this implementation does not reproduce
        (anything beyond Replace/NFD/Lowercase/StripAccents normalizers, the
        Whitespace pre-tokenizer, and a WordPiece model) — silently dropping
        one would change token ids.
        """
        model = payload.get("model") or {}
        if model.get("type") != "WordPiece":
            raise ValueError(
                f"unsupported tokenizer model {model.get('type')!r} (need WordPiece)"
            )
        prefix = model.get("continuing_subword_prefix", CONTINUATION_PREFIX)
        if prefix != CONTINUATION_PREFIX:
            raise ValueError(f"unsupported continuation prefix {prefix!r}")
        unk = model.get("unk_token", UNK_TOKEN)
        if unk != UNK_TOKEN:
            raise ValueError(f"unsupported unk_token {unk!r} (need {UNK_TOKEN!r})")
        max_chars = model.get("max_input_chars_per_word", MAX_CHARS_PER_WORD)
        if max_chars != MAX_CHARS_PER_WORD:
            raise ValueError(
                f"unsupported max_input_chars_per_word {max_chars} "
                f"(need {MAX_CHARS_PER_WORD})"
            )
        if payload.get("post_processor") is not None:
            raise ValueError(
                "post_processor pipelines are not supported (they add tokens "
                "this implementation would not reproduce)"
            )

        # encode() unconditionally applies Replace* → NFD → Lowercase →
        # StripAccents then Whitespace splitting, so the file must declare
        # EXACTLY that pipeline (leading Replaces + those three, in order) —
        # anything else (normalizer: null, cased vocab, different order)
        # would produce different ids than the HF library
        normalizer = payload.get("normalizer")
        entries = []
        if normalizer is not None:
            entries = (
                normalizer.get("normalizers", [])
                if normalizer.get("type") == "Sequence" else [normalizer]
            )
        replacements = []
        tail = []
        for entry in entries:
            kind = entry.get("type")
            if kind == "Replace":
                if tail:
                    # normalize() applies replacements FIRST; a Replace after
                    # case-folding would see different text than here
                    raise ValueError(
                        "Replace normalizers after NFD/Lowercase/StripAccents "
                        "are not supported"
                    )
                pattern = entry.get("pattern", {})
                if "String" not in pattern:
                    raise ValueError("only literal-string Replace is supported")
                replacements.append((pattern["String"], entry.get("content", "")))
            elif kind in ("NFD", "Lowercase", "StripAccents"):
                tail.append(kind)
            else:
                raise ValueError(f"unsupported normalizer {kind!r}")
        if tail != ["NFD", "Lowercase", "StripAccents"]:
            raise ValueError(
                f"normalizer pipeline must be Replace* -> NFD -> Lowercase -> "
                f"StripAccents (this implementation always applies all "
                f"three), got {tail or None}"
            )

        pre = payload.get("pre_tokenizer")
        if pre is None or pre.get("type") != "Whitespace":
            raise ValueError(
                f"pre-tokenizer must be Whitespace, got "
                f"{pre.get('type') if pre else None!r}"
            )

        extra_added = [
            t.get("content") for t in payload.get("added_tokens") or []
            if t.get("content") not in SPECIAL_TOKENS
        ]
        if extra_added:
            raise ValueError(
                f"added tokens beyond {SPECIAL_TOKENS} are not supported: "
                f"{extra_added}"
            )

        vocab = model["vocab"]
        for i, tok in enumerate(SPECIAL_TOKENS):
            if vocab.get(tok) != i:
                # the masking op assumes specials occupy the first ids
                # (reference model.py:284-289) — a vocab violating that would
                # silently corrupt MLM training
                raise ValueError(
                    f"special token {tok!r} must have id {i}, got {vocab.get(tok)}"
                )
        return cls(vocab=vocab, replacements=replacements)

    @classmethod
    def from_file(cls, path: str) -> "WordPieceTokenizer":
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        if payload.get("format") == "perceiver_io_tpu.wordpiece.v1":
            return cls(
                vocab=payload["vocab"],
                replacements=payload.get("replacements", ()),
            )
        if isinstance(payload.get("model"), dict):  # HF tokenizers schema
            return cls.from_hf_dict(payload)
        raise ValueError(f"unrecognized tokenizer file format in {path}")


# -- module-level API mirroring the reference surface (tokenizer.py:18-36) --

def create_tokenizer(*replacements: Tuple[str, str]) -> WordPieceTokenizer:
    return WordPieceTokenizer(replacements=replacements)


def train_tokenizer(tokenizer: WordPieceTokenizer, data: Iterable[str], vocab_size: int):
    tokenizer.train_from_iterator(data, vocab_size)


def save_tokenizer(tokenizer: WordPieceTokenizer, path: str):
    tokenizer.save(path)


def load_tokenizer(path: str) -> WordPieceTokenizer:
    return WordPieceTokenizer.from_file(path)
