"""Guarded dataset downloaders (IMDB tarball, MNIST idx files).

The reference downloads IMDB through torchtext (reference
``data/imdb.py:115-117``) and MNIST through torchvision with patched
md5-verified resources (reference ``data/mnist.py:9-14``). This module is the
first-party equivalent: stdlib-urllib fetch with mirror fallback, md5
verification, atomic writes (tmp + rename, so an interrupted download never
poisons the cache), and tar/gzip extraction.

Everything is *guarded*: the data modules call ``ensure_*`` only when local
data is absent, and a network failure surfaces one clear error naming the
offline alternatives (pre-placing the tree, or ``--synthetic``). On a
zero-egress box the guarded path is exercised by tests against a localhost
HTTP server.

Transient HTTP failures (5xx responses, reset/aborted connections, read
timeouts) are retried per-URL with capped exponential backoff
(``perceiver_io_tpu.resilience.retry`` — no jax import) before falling
through to the next mirror; deterministic failures (404, refused connection
on an offline box, checksum mismatch) fail immediately so ``--no_download``
and the offline fast-fail stay instant.
"""

from __future__ import annotations

import gzip
import hashlib
import http.client
import os
import shutil
import tarfile
import tempfile
import urllib.error
import urllib.request
from typing import Optional, Sequence

from perceiver_io_tpu.resilience.retry import (
    FATAL,
    TRANSIENT,
    RetryPolicy,
    call_with_retry,
)

# Stanford AI original; the only canonical source (what torchtext fetches),
# with torchtext's pinned md5 for the tarball.
IMDB_URLS = [
    "https://ai.stanford.edu/~amaas/data/sentiment/aclImdb_v1.tar.gz",
]
IMDB_MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

# (filename, md5) pairs exactly as the reference pins them
# (reference data/mnist.py:9-14); mirrors tried in order.
MNIST_FILES = [
    ("train-images-idx3-ubyte.gz", "f68b3c2dcbeaaa9fbdd348bbdeb94873"),
    ("train-labels-idx1-ubyte.gz", "d53e105ee54ea40749a09fcbcd1e9432"),
    ("t10k-images-idx3-ubyte.gz", "9fb629c4189551a2d022fa330f9573f3"),
    ("t10k-labels-idx1-ubyte.gz", "ec29112dd5afa0611ce80d1b7f02629c"),
]
MNIST_MIRRORS = [
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
]


class DownloadError(RuntimeError):
    """A dataset could not be fetched (offline box, dead mirror, bad hash)."""


# capped exponential backoff for per-URL transient retries; small base so the
# offline/tier-1 paths stay fast even when a retry does fire
HTTP_RETRY_POLICY = RetryPolicy(max_retries=2, base_s=0.2, multiplier=2.0,
                                max_s=2.0, jitter=0.25)


def _classify_http_error(exc: BaseException) -> str:
    """Transient = worth re-asking the SAME url: server-side 5xx, dropped or
    half-read connections, read timeouts. Everything else (404, DNS failure,
    connection refused on an offline box, checksum mismatch) is fatal for
    this url — fall through to the next mirror immediately."""
    if isinstance(exc, urllib.error.HTTPError):
        return TRANSIENT if exc.code >= 500 else FATAL
    if isinstance(exc, urllib.error.URLError):
        reason = exc.reason
        return (_classify_http_error(reason)
                if isinstance(reason, BaseException) else FATAL)
    if isinstance(exc, (ConnectionResetError, ConnectionAbortedError,
                        BrokenPipeError, http.client.IncompleteRead,
                        http.client.RemoteDisconnected, TimeoutError)):
        return TRANSIENT
    return FATAL


def _md5(path: str) -> str:
    digest = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def download_file(
    url: str, dest: str, md5: Optional[str] = None, timeout: float = 60.0,
    retry_policy: RetryPolicy = HTTP_RETRY_POLICY,
) -> str:
    """Fetch ``url`` to ``dest`` atomically; verify ``md5`` when given.

    Transient failures (5xx, reset connections, read timeouts) re-fetch the
    url up to ``retry_policy.max_retries`` times with capped exponential
    backoff; each attempt writes a fresh temp file, so a half-downloaded
    attempt never leaks into the next one (or into ``dest``).
    """
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)

    def fetch() -> str:
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(dest) or ".", suffix=".part"
        )
        try:
            with os.fdopen(fd, "wb") as out, urllib.request.urlopen(
                url, timeout=timeout
            ) as resp:
                shutil.copyfileobj(resp, out)
            if md5 is not None:
                got = _md5(tmp)
                if got != md5:
                    raise DownloadError(
                        f"checksum mismatch for {url}: expected {md5}, got {got}"
                    )
            os.replace(tmp, dest)
            return dest
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    return call_with_retry(
        fetch, policy=retry_policy, classify=_classify_http_error,
    )


def download_any(
    urls: Sequence[str], dest: str, md5: Optional[str] = None,
    timeout: float = 60.0, retry_policy: RetryPolicy = HTTP_RETRY_POLICY,
) -> str:
    """Try each mirror in order (each with its own transient-retry budget);
    raise :class:`DownloadError` naming every failure if none succeeds."""
    failures = []
    for url in urls:
        try:
            return download_file(url, dest, md5=md5, timeout=timeout,
                                 retry_policy=retry_policy)
        except (urllib.error.URLError, OSError, DownloadError,
                http.client.HTTPException) as e:
            failures.append(f"{url}: {e}")
    raise DownloadError(
        "all mirrors failed:\n  " + "\n  ".join(failures)
    )


def ensure_imdb(
    root: str, urls: Optional[Sequence[str]] = None,
    md5: Optional[str] = "default", timeout: float = 60.0,
) -> str:
    """Make ``<root>/IMDB/aclImdb`` exist, downloading + extracting the
    tarball if absent (the torchtext step at reference ``imdb.py:115-117``).
    Extraction is atomic (temp dir + rename), so an interrupted run never
    leaves a partial tree that later runs mistake for complete. Returns the
    aclImdb directory path."""
    if md5 == "default":
        md5 = IMDB_MD5 if urls is None else None
    target = os.path.join(root, "IMDB", "aclImdb")
    if os.path.isdir(os.path.join(target, "train")):
        return target
    dest_dir = os.path.join(root, "IMDB")
    os.makedirs(dest_dir, exist_ok=True)
    tarball = os.path.join(dest_dir, "aclImdb_v1.tar.gz")
    if os.path.exists(tarball) and md5 is not None and _md5(tarball) != md5:
        os.unlink(tarball)  # corrupt/truncated leftover: re-fetch
    if not os.path.exists(tarball):
        try:
            download_any(urls or IMDB_URLS, tarball, md5=md5, timeout=timeout)
        except DownloadError as e:
            raise DownloadError(
                f"IMDB is not present under {target} and could not be "
                f"downloaded. Offline alternatives: extract aclImdb_v1.tar.gz "
                f"to {dest_dir}, or pass synthetic=True / --synthetic.\n{e}"
            ) from e
    staging = tempfile.mkdtemp(dir=dest_dir, prefix=".aclImdb-extract-")
    try:
        with tarfile.open(tarball, "r:gz") as tar:
            # reject traversal and link members in an untrusted archive
            for member in tar.getmembers():
                path = os.path.normpath(member.name)
                if path.startswith(("/", "..")) or member.issym() or member.islnk():
                    raise DownloadError(f"unsafe tar member {member.name!r}")
            try:
                tar.extractall(staging, filter="data")
            except TypeError:  # Python < 3.12: no filter=; the check above holds
                tar.extractall(staging)
        extracted = os.path.join(staging, "aclImdb")
        if not os.path.isdir(extracted):
            raise DownloadError(f"{tarball} does not contain an aclImdb/ tree")
        if os.path.isdir(target):
            # a partial tree from an interrupted earlier extraction (we only
            # early-return when train/ exists) — replace it wholesale
            shutil.rmtree(target)
        os.replace(extracted, target)
    finally:
        shutil.rmtree(staging, ignore_errors=True)
    return target


def ensure_mnist(
    root: str, mirrors: Optional[Sequence[str]] = None, timeout: float = 60.0
) -> str:
    """Make ``<root>/MNIST/raw`` hold the four idx files, downloading any that
    are missing from the md5-pinned mirror list (reference
    ``mnist.py:9-14``). Files are stored unpacked (``.gz`` kept too, matching
    torchvision's layout). Returns the raw directory path."""
    raw = os.path.join(root, "MNIST", "raw")
    os.makedirs(raw, exist_ok=True)
    for gz_name, md5 in MNIST_FILES:
        plain = os.path.join(raw, gz_name[:-3])
        gz = os.path.join(raw, gz_name)
        if os.path.exists(plain):
            continue
        if os.path.exists(gz):
            if md5 is None or _md5(gz) == md5:
                continue
            os.unlink(gz)  # corrupt/truncated leftover: re-fetch
        try:
            download_any(
                [m + gz_name for m in (mirrors or MNIST_MIRRORS)], gz,
                md5=md5, timeout=timeout,
            )
        except DownloadError as e:
            raise DownloadError(
                f"MNIST file {gz_name} is not present under {raw} and could "
                f"not be downloaded. Offline alternatives: place the idx "
                f"files at {raw}, or pass synthetic=True / --synthetic.\n{e}"
            ) from e
        fd, tmp = tempfile.mkstemp(dir=raw, suffix=".part")
        try:
            with gzip.open(gz, "rb") as src, os.fdopen(fd, "wb") as dst:
                shutil.copyfileobj(src, dst)
            os.replace(tmp, plain)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return raw
