"""MNIST image data module.

Mirrors the reference's MNIST module behavior (reference ``data/mnist.py``):
channels-last (28, 28, 1) images, ``Normalize(0.5, 0.5)`` after scaling to
[0, 1] (torchvision ``ToTensor`` + ``Normalize`` ⇒ pixel ∈ [-1, 1]), optional
random crop augmentation, 10k validation split carved from the train set.

Reads the standard idx files from ``<root>/MNIST/raw`` (torchvision's layout,
``.gz`` or unpacked) so an existing cache drops in; ``synthetic=True``
generates a deterministic digit-like dataset (class-dependent blob patterns —
learnable, so smoke training shows a falling loss) for this zero-egress box.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from perceiver_io_tpu.data.pipeline import DataLoader, image_label_collate

_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(root: str, base: str) -> str:
    for candidate in (
        os.path.join(root, "MNIST", "raw", base),
        os.path.join(root, "MNIST", "raw", base + ".gz"),
        os.path.join(root, base),
        os.path.join(root, base + ".gz"),
    ):
        if os.path.exists(candidate):
            return candidate
    raise FileNotFoundError(
        f"MNIST file {base} not found under {root} — place the idx files at "
        f"{root}/MNIST/raw, or use synthetic=True"
    )


def load_mnist(root: str, split: str) -> Tuple[np.ndarray, np.ndarray]:
    """(images uint8 (N, 28, 28), labels uint8 (N,)) for 'train' or 'test'."""
    prefix = "train" if split == "train" else "test"
    images = _read_idx(_find(root, _FILES[f"{prefix}_images"]))
    labels = _read_idx(_find(root, _FILES[f"{prefix}_labels"]))
    return images, labels


def synthetic_digits(n: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable stand-in: each class is a fixed smooth random
    28×28 template plus pixel noise."""
    rng = np.random.default_rng(seed)
    base = np.random.default_rng(1234)  # templates shared across splits/seeds
    templates = base.uniform(0, 1, size=(10, 28, 28))
    # smooth the templates a little so they look image-like...
    for _ in range(2):
        templates = (
            templates
            + np.roll(templates, 1, 1)
            + np.roll(templates, -1, 1)
            + np.roll(templates, 1, 2)
            + np.roll(templates, -1, 2)
        ) / 5.0
    # ...then restore full contrast so class signal dominates the pixel noise
    tmin = templates.min(axis=(1, 2), keepdims=True)
    tmax = templates.max(axis=(1, 2), keepdims=True)
    templates = (templates - tmin) / (tmax - tmin)
    labels = rng.integers(0, 10, size=n).astype(np.uint8)
    images = templates[labels] + rng.normal(0, 0.15, size=(n, 28, 28))
    images = (np.clip(images, 0, 1) * 255).astype(np.uint8)
    return images, labels


class MNISTDataset:
    """Normalized channels-last examples with optional random-crop augmentation."""

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        crop: Optional[int] = None,
        random_crop: bool = True,
        augment_seed: int = 0,
    ):
        self.images = images
        self.labels = labels
        self.crop = crop
        self.random_crop = random_crop
        self._rng = np.random.default_rng(augment_seed)

    def __len__(self) -> int:
        return len(self.images)

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        s = self.crop
        h, w = self.images.shape[1:3]
        return (s, s, 1) if s else (h, w, 1)

    def __getitem__(self, i: int) -> Tuple[np.ndarray, int]:
        img = self.images[i]
        if self.crop:
            s = self.crop
            h, w = img.shape
            if self.random_crop:
                top = int(self._rng.integers(0, h - s + 1))
                left = int(self._rng.integers(0, w - s + 1))
            else:  # deterministic center crop: eval shapes match train/dims
                top, left = (h - s) // 2, (w - s) // 2
            img = img[top : top + s, left : left + s]
        # ToTensor (→[0,1]) + Normalize(0.5, 0.5) + channels-last
        img = (img.astype(np.float32) / 255.0 - 0.5) / 0.5
        return img[..., None], int(self.labels[i])


class MNISTDataModule:
    """create/setup/loader surface mirroring the reference module
    (``data/mnist.py:17-82``): val_split=10000, Normalize(0.5, 0.5),
    channels-last, optional random crop."""

    num_classes = 10

    def __init__(
        self,
        root: str = ".cache",
        batch_size: int = 64,
        random_crop: Optional[int] = None,
        val_split: int = 10000,
        synthetic: bool = False,
        synthetic_size: int = 4096,
        seed: int = 0,
        shard_id: int = 0,
        num_shards: int = 1,
        download: bool = True,
    ):
        self.root = root
        self.download = download
        self.batch_size = batch_size
        self.random_crop = random_crop
        self.val_split = val_split
        self.synthetic = synthetic
        self.synthetic_size = synthetic_size
        self.seed = seed
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.ds_train: Optional[MNISTDataset] = None
        self.ds_valid: Optional[MNISTDataset] = None

    @classmethod
    def create(cls, args) -> "MNISTDataModule":
        return cls(
            root=args.root,
            batch_size=args.batch_size,
            random_crop=args.random_crop,
            synthetic=getattr(args, "synthetic", False),
        )

    @property
    def dims(self) -> Tuple[int, int, int]:
        s = self.random_crop
        return (s, s, 1) if s else (28, 28, 1)

    def prepare_data(self):
        """Download-if-absent (md5-pinned mirrors, reference ``mnist.py:9-14``),
        then validate local data exists (or synthetic mode)."""
        if self.synthetic:
            return

        def all_present() -> bool:
            try:
                for base in _FILES.values():
                    _find(self.root, base)
                return True
            except FileNotFoundError:
                return False

        if self.download:
            import jax

            # _find also accepts the flat <root>/*.gz layout, which
            # ensure_mnist doesn't manage — only download when something is
            # actually missing. The barrier is UNCONDITIONAL for every rank:
            # presence is re-evaluated per process and could disagree across
            # ranks mid-download, so a barrier inside the branch could be
            # entered by some ranks only (deadlock).
            if jax.process_index() == 0 and not all_present():
                from perceiver_io_tpu.data.download import ensure_mnist

                ensure_mnist(self.root)
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("mnist_prepare_data")
        for base in _FILES.values():
            _find(self.root, base)

    def setup(self):
        if self.synthetic:
            images, labels = synthetic_digits(self.synthetic_size, seed=self.seed)
            val = max(self.synthetic_size // 8, 32)
        else:
            images, labels = load_mnist(self.root, "train")
            val = self.val_split
        split = len(images) - val  # explicit split point: val_split=0 keeps all
        self.ds_train = MNISTDataset(
            images[:split], labels[:split], crop=self.random_crop,
            augment_seed=self.seed,
        )
        # same target size as train (center crop) so val batches match `dims`
        self.ds_valid = MNISTDataset(
            images[split:], labels[split:], crop=self.random_crop,
            random_crop=False,
        )

    def train_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_train,
            batch_size=self.batch_size,
            collate=image_label_collate,
            shuffle=True,
            seed=self.seed,
            shard_id=self.shard_id,
            num_shards=self.num_shards,
        )

    def val_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_valid,
            batch_size=self.batch_size,
            collate=image_label_collate,
            shuffle=False,
            # evaluate the full set when single-host (multi-host must drop for
            # lockstep collectives)
            drop_last=self.num_shards > 1,
            shard_id=self.shard_id,
            num_shards=self.num_shards,
        )
