"""ImageFolder-style data module for ImageNet-scale image classification.

Extends the reference repo's data layer (which stops at MNIST, reference
``data/mnist.py``) to the Perceiver paper's ImageNet-1k configuration tracked
in BASELINE.md (224×224, 512 latents). Reads the standard class-per-directory
layout torchvision calls ImageFolder::

    <root>/<name>/train/<wnid-or-class>/*.JPEG
    <root>/<name>/val/<wnid-or-class>/*.JPEG

Images are decoded lazily per index (1.2M JPEGs never fit in RAM) with the
standard recipe: train = random-resized-crop + horizontal flip, val = resize
shorter side to 1.15× then center crop; both normalized by the ImageNet
channel statistics, channels-last float32. Pair with ``DataLoader(...,
num_workers=N)`` so JPEG decode overlaps the device step.

``synthetic=True`` generates a deterministic class-template dataset (lazy,
per-index) for this zero-egress box — learnable, so smoke training shows a
falling loss, mirroring the MNIST module's synthetic mode.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from perceiver_io_tpu.data.pipeline import DataLoader, image_label_collate

IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)

_EXTENSIONS = (".jpeg", ".jpg", ".png", ".bmp", ".webp")


def list_image_folder(split_dir: str) -> Tuple[List[Tuple[str, int]], List[str]]:
    """[(path, class_index)] plus the sorted class-name list for a split dir."""
    classes = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d))
    )
    if not classes:
        raise FileNotFoundError(f"no class directories under {split_dir}")
    samples: List[Tuple[str, int]] = []
    for idx, cls in enumerate(classes):
        cdir = os.path.join(split_dir, cls)
        for name in sorted(os.listdir(cdir)):
            if name.lower().endswith(_EXTENSIONS):
                samples.append((os.path.join(cdir, name), idx))
    if not samples:
        raise FileNotFoundError(f"no images under {split_dir} (extensions {_EXTENSIONS})")
    return samples, classes


def _random_resized_crop(img, size: int, rng: np.random.Generator):
    """torchvision RandomResizedCrop semantics: area scale U(0.08, 1), aspect
    log-U(3/4, 4/3), 10 attempts then center-crop fallback."""
    from PIL import Image

    w, h = img.size
    area = w * h
    for _ in range(10):
        target = area * rng.uniform(0.08, 1.0)
        aspect = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        cw = int(round(np.sqrt(target * aspect)))
        ch = int(round(np.sqrt(target / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            left = int(rng.integers(0, w - cw + 1))
            top = int(rng.integers(0, h - ch + 1))
            return img.resize((size, size), Image.BILINEAR,
                              box=(left, top, left + cw, top + ch))
    return _center_crop(img, size)


def _center_crop(img, size: int):
    from PIL import Image

    w, h = img.size
    scale = size * 1.15 / min(w, h)
    if scale != 1.0:
        img = img.resize((max(size, int(round(w * scale))),
                          max(size, int(round(h * scale)))), Image.BILINEAR)
        w, h = img.size
    left, top = (w - size) // 2, (h - size) // 2
    return img.crop((left, top, left + size, top + size))


class ImageFolderDataset:
    """Lazy-decoding dataset over (path, label) samples, channels-last f32."""

    def __init__(
        self,
        samples: Sequence[Tuple[str, int]],
        image_size: int = 224,
        train: bool = True,
        augment_seed: int = 0,
    ):
        self.samples = list(samples)
        self.image_size = image_size
        self.train = train
        # numpy Generators are not thread-safe and __getitem__ runs on the
        # DataLoader decode pool: draw only a per-item seed under the lock,
        # then do the actual augmentation draws on a local Generator.
        self._seed_rng = np.random.default_rng(augment_seed)
        self._seed_lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.samples)

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return (self.image_size, self.image_size, 3)

    def __getitem__(self, i: int) -> Tuple[np.ndarray, int]:
        from PIL import Image

        path, label = self.samples[i]
        if self.train:
            with self._seed_lock:
                rng = np.random.default_rng(self._seed_rng.integers(2**63))
        with Image.open(path) as img:
            img = img.convert("RGB")
            if self.train:
                img = _random_resized_crop(img, self.image_size, rng)
                if rng.random() < 0.5:
                    img = img.transpose(Image.FLIP_LEFT_RIGHT)
            else:
                img = _center_crop(img, self.image_size)
            arr = np.asarray(img, np.float32) / 255.0
        return (arr - IMAGENET_MEAN) / IMAGENET_STD, label


class SyntheticImageDataset:
    """Deterministic learnable stand-in: per-class smooth low-res templates
    upsampled to the target size, plus pixel noise. Lazy per index."""

    def __init__(
        self,
        n: int,
        num_classes: int = 10,
        image_size: int = 224,
        seed: int = 0,
    ):
        self.n = n
        self.image_size = image_size
        base = np.random.default_rng(1234)  # templates shared across splits
        low = base.uniform(0, 1, size=(num_classes, 8, 8, 3)).astype(np.float32)
        self.templates = low
        rng = np.random.default_rng(seed)
        self.labels = rng.integers(0, num_classes, size=n).astype(np.int32)
        self.noise_seed = seed

    def __len__(self) -> int:
        return self.n

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return (self.image_size, self.image_size, 3)

    def __getitem__(self, i: int) -> Tuple[np.ndarray, int]:
        label = int(self.labels[i])
        s = self.image_size
        t = self.templates[label]
        # bilinear-ish upsample by nearest repeat (class signal, not beauty)
        reps = -(-s // t.shape[0])
        img = np.repeat(np.repeat(t, reps, 0), reps, 1)[:s, :s]
        rng = np.random.default_rng(np.uint64(self.noise_seed) * 1000003 + np.uint64(i))
        img = np.clip(img + rng.normal(0, 0.15, img.shape).astype(np.float32), 0, 1)
        return (img - IMAGENET_MEAN) / IMAGENET_STD, label


class ImageFolderDataModule:
    """prepare/setup/loader surface matching the other data modules."""

    def __init__(
        self,
        root: str = ".cache",
        name: str = "imagenet",
        image_size: int = 224,
        batch_size: int = 64,
        synthetic: bool = False,
        synthetic_size: int = 4096,
        synthetic_classes: int = 10,
        num_workers: int = 8,
        seed: int = 0,
        shard_id: int = 0,
        num_shards: int = 1,
    ):
        self.root = root
        self.name = name
        self.image_size = image_size
        self.batch_size = batch_size
        self.synthetic = synthetic
        self.synthetic_size = synthetic_size
        self.synthetic_classes = synthetic_classes
        self.num_workers = num_workers
        self.seed = seed
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.num_classes: Optional[int] = None
        self.ds_train = None
        self.ds_valid = None

    @property
    def dims(self) -> Tuple[int, int, int]:
        return (self.image_size, self.image_size, 3)

    def prepare_data(self):
        if not self.synthetic:
            train_dir = os.path.join(self.root, self.name, "train")
            if not os.path.isdir(train_dir):
                raise FileNotFoundError(
                    f"no image tree at {train_dir} — lay out "
                    f"{self.root}/{self.name}/{{train,val}}/<class>/*.JPEG, "
                    "or use synthetic=True"
                )

    def setup(self):
        if self.synthetic:
            self.num_classes = self.synthetic_classes
            self.ds_train = SyntheticImageDataset(
                self.synthetic_size, self.synthetic_classes, self.image_size,
                seed=self.seed,
            )
            val = max(self.synthetic_size // 8, 32)
            self.ds_valid = SyntheticImageDataset(
                val, self.synthetic_classes, self.image_size, seed=self.seed + 1,
            )
            return
        base = os.path.join(self.root, self.name)
        train_samples, classes = list_image_folder(os.path.join(base, "train"))
        val_dir = os.path.join(base, "val")
        if os.path.isdir(val_dir):
            val_samples, val_classes = list_image_folder(val_dir)
            if val_classes != classes:
                raise ValueError(
                    f"train/val class directories disagree under {base} "
                    f"({len(classes)} vs {len(val_classes)} classes)"
                )
        else:  # no val split on disk: carve a deterministic tail off train
            rng = np.random.default_rng(self.seed)
            order = rng.permutation(len(train_samples))
            n_val = max(len(train_samples) // 50, 1)
            val_samples = [train_samples[i] for i in order[:n_val]]
            train_samples = [train_samples[i] for i in order[n_val:]]
        self.num_classes = len(classes)
        self.ds_train = ImageFolderDataset(
            train_samples, self.image_size, train=True, augment_seed=self.seed
        )
        self.ds_valid = ImageFolderDataset(val_samples, self.image_size, train=False)

    def train_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_train, self.batch_size, image_label_collate, shuffle=True,
            seed=self.seed, shard_id=self.shard_id, num_shards=self.num_shards,
            num_workers=self.num_workers,
        )

    def val_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_valid, self.batch_size, image_label_collate, shuffle=False,
            drop_last=self.num_shards > 1,
            shard_id=self.shard_id, num_shards=self.num_shards,
            num_workers=self.num_workers,
        )
